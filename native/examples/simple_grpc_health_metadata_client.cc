// Health + metadata surface over gRPC: liveness, readiness, server
// and model metadata, config, statistics, repository index (parity
// example: reference src/c++/examples/simple_grpc_health_metadata.cc).
#include <cstring>
#include <iostream>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  bool live = false, ready = false, model_ready = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server live");
  FAIL_IF_ERR(client->IsServerReady(&ready), "server ready");
  FAIL_IF_ERR(client->IsModelReady(&model_ready, "simple"), "model ready");
  if (!live || !ready || !model_ready) {
    std::cerr << "server/model not ready\n";
    return 1;
  }

  inference::ServerMetadataResponse server_metadata;
  FAIL_IF_ERR(client->ServerMetadata(&server_metadata), "server metadata");
  std::cout << "server: " << server_metadata.name() << " "
            << server_metadata.version() << std::endl;

  inference::ModelMetadataResponse model_metadata;
  FAIL_IF_ERR(client->ModelMetadata(&model_metadata, "simple"),
              "model metadata");
  if (model_metadata.inputs_size() != 2) {
    std::cerr << "expected 2 inputs\n";
    return 1;
  }

  inference::ModelConfigResponse config;
  FAIL_IF_ERR(client->ModelConfig(&config, "simple"), "model config");

  inference::RepositoryIndexResponse index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");
  bool found = false;
  for (const auto& model : index.models()) {
    if (model.name() == "simple") found = true;
  }
  if (!found) {
    std::cerr << "'simple' missing from repository index\n";
    return 1;
  }

  inference::ModelStatisticsResponse stats;
  FAIL_IF_ERR(client->ModelInferenceStatistics(&stats, "simple"),
              "statistics");

  std::cout << "PASS: health + metadata" << std::endl;
  return 0;
}
