// Sequence (stateful) inference over gRPC: two interleaved sequences
// send values through the server's per-sequence-id accumulator; the
// correlation id + start/end flags ride the request options (parity
// example: reference src/c++/examples/simple_grpc_sequence_sync_infer_client.cc).
#include <cstdint>
#include <cstring>
#include <iostream>
#include <memory>
#include <vector>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)

int32_t SendSequenceValue(
    tpuclient::InferenceServerGrpcClient* client, uint64_t sequence_id,
    int32_t value, bool start, bool end) {
  tpuclient::InferInput* raw_input;
  FAIL_IF_ERR(tpuclient::InferInput::Create(&raw_input, "INPUT", {1},
                                            "INT32"),
              "create input");
  std::unique_ptr<tpuclient::InferInput> input(raw_input);
  FAIL_IF_ERR(input->AppendRaw(reinterpret_cast<const uint8_t*>(&value),
                               sizeof(value)),
              "set input");

  tpuclient::InferOptions options("simple_sequence");
  options.sequence_id = sequence_id;
  options.sequence_start = start;
  options.sequence_end = end;

  tpuclient::InferResult* raw_result = nullptr;
  FAIL_IF_ERR(client->Infer(&raw_result, options, {input.get()}),
              "sequence infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  const uint8_t* buf;
  size_t byte_size;
  FAIL_IF_ERR(result->RawData("OUTPUT", &buf, &byte_size), "read output");
  int32_t total;
  memcpy(&total, buf, sizeof(total));
  return total;
}
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  // Two sequences interleaved: the server keeps independent running
  // sums keyed by correlation id.
  const std::vector<int32_t> values = {11, 7, 5, 3, 2, 0, 1};
  int32_t total_a = 0, total_b = 0;
  for (size_t i = 0; i < values.size(); ++i) {
    bool start = i == 0;
    bool end = i + 1 == values.size();
    int32_t got_a = SendSequenceValue(client.get(), 1007, values[i],
                                      start, end);
    int32_t got_b = SendSequenceValue(client.get(), 1008, -values[i],
                                      start, end);
    total_a += values[i];
    total_b -= values[i];
    std::cout << "seq 1007 += " << values[i] << " -> " << got_a
              << " | seq 1008 += " << -values[i] << " -> " << got_b
              << std::endl;
    if (got_a != total_a || got_b != total_b) {
      std::cerr << "accumulator mismatch (expected " << total_a << ", "
                << total_b << ")" << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: sequence infer" << std::endl;
  return 0;
}
