// Decoupled bidi streaming against repeat_int32: one response per
// input element (parity example: the reference decoupled stream
// examples over ModelStreamInfer).
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <mutex>
#include <vector>

#include "grpc_client.h"


namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  int32_t values[5] = {3, 1, 4, 1, 5};
  std::mutex mutex;
  std::condition_variable cv;
  std::vector<int32_t> got;
  bool final_seen = false;

  FAIL_IF_ERR(client->StartStream([&](tpuclient::InferResult* result) {
                std::unique_ptr<tpuclient::InferResult> owned(result);
                auto* grpc_result =
                    static_cast<tpuclient::InferResultGrpc*>(owned.get());
                std::lock_guard<std::mutex> lock(mutex);
                const uint8_t* buf;
                size_t size;
                if (owned->RequestStatus().IsOk() &&
                    owned->RawData("OUT", &buf, &size).IsOk() &&
                    size == 4) {
                  got.push_back(
                      *reinterpret_cast<const int32_t*>(buf));
                }
                if (grpc_result->IsFinalResponse()) final_seen = true;
                cv.notify_all();
              }),
              "start stream");

  tpuclient::InferInput* raw_in;
  tpuclient::InferInput::Create(&raw_in, "IN", {5}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input(raw_in);
  input->AppendRaw(reinterpret_cast<uint8_t*>(values), sizeof(values));

  tpuclient::InferOptions options("repeat_int32");
  FAIL_IF_ERR(client->AsyncStreamInfer(options, {input.get()}),
              "stream infer");
  {
    std::unique_lock<std::mutex> lock(mutex);
    if (!cv.wait_for(lock, std::chrono::seconds(30),
                     [&] { return got.size() == 5 && final_seen; })) {
      std::cerr << "timeout (" << got.size() << " responses)\n";
      return 1;
    }
  }
  FAIL_IF_ERR(client->StopStream(), "stop stream");
  for (int i = 0; i < 5; ++i) {
    if (got[i] != values[i]) { std::cerr << "mismatch\n"; return 1; }
  }
  std::cout << "PASS: decoupled stream (5 responses)" << std::endl;
  return 0;
}
