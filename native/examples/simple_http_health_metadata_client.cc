// Health + metadata surface over HTTP/REST: liveness, readiness,
// server/model metadata (JSON), config, statistics, repository index
// (parity example: reference
// src/c++/examples/simple_http_health_metadata.cc).
#include <cstring>
#include <iostream>

#include "http_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerHttpClient::Create(
                  &client, Url(argc, argv, "localhost:8000")),
              "create client");

  bool live = false, ready = false, model_ready = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server live");
  FAIL_IF_ERR(client->IsServerReady(&ready), "server ready");
  FAIL_IF_ERR(client->IsModelReady(&model_ready, "simple"), "model ready");
  if (!live || !ready || !model_ready) {
    std::cerr << "server/model not ready\n";
    return 1;
  }

  std::string server_metadata;
  FAIL_IF_ERR(client->ServerMetadata(&server_metadata), "server metadata");
  if (server_metadata.find("client_tpu_server") == std::string::npos) {
    std::cerr << "unexpected server metadata: " << server_metadata << "\n";
    return 1;
  }

  std::string model_metadata;
  FAIL_IF_ERR(client->ModelMetadata(&model_metadata, "simple"),
              "model metadata");
  if (model_metadata.find("INPUT0") == std::string::npos) {
    std::cerr << "INPUT0 missing from metadata\n";
    return 1;
  }

  std::string config;
  FAIL_IF_ERR(client->ModelConfig(&config, "simple"), "model config");

  std::string index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");
  if (index.find("simple") == std::string::npos) {
    std::cerr << "'simple' missing from repository index\n";
    return 1;
  }

  std::string stats;
  FAIL_IF_ERR(client->ModelInferenceStatistics(&stats, "simple"),
              "statistics");

  std::cout << "PASS: http health + metadata" << std::endl;
  return 0;
}
