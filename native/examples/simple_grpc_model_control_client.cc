// Explicit model lifecycle over gRPC: load, infer, unload, verify
// infer-after-unload fails (parity example: reference
// src/c++/examples/simple_grpc_model_control.cc).
#include <cstdint>
#include <cstring>
#include <iostream>
#include <memory>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  FAIL_IF_ERR(client->LoadModel("add_sub"), "load model");
  bool ready = false;
  FAIL_IF_ERR(client->IsModelReady(&ready, "add_sub"), "model ready");
  if (!ready) {
    std::cerr << "add_sub not ready after load\n";
    return 1;
  }

  int32_t in0[16], in1[16];
  for (int i = 0; i < 16; ++i) { in0[i] = i; in1[i] = 2; }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  input0->AppendRaw(reinterpret_cast<uint8_t*>(in0), sizeof(in0));
  input1->AppendRaw(reinterpret_cast<uint8_t*>(in1), sizeof(in1));

  tpuclient::InferOptions options("add_sub");
  tpuclient::InferResult* raw_result = nullptr;
  FAIL_IF_ERR(client->Infer(&raw_result, options,
                            {input0.get(), input1.get()}),
              "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  const uint8_t* buf;
  size_t len;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &buf, &len), "read output");
  if (len != 16 * sizeof(int32_t)) {
    std::cerr << "unexpected output size " << len << std::endl;
    return 1;
  }
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (sums[i] != in0[i] + in1[i]) {
      std::cerr << "bad sum at " << i << std::endl;
      return 1;
    }
  }

  FAIL_IF_ERR(client->UnloadModel("add_sub"), "unload model");
  ready = true;
  client->IsModelReady(&ready, "add_sub");
  if (ready) {
    std::cerr << "add_sub still ready after unload\n";
    return 1;
  }
  tpuclient::InferResult* dead_result = nullptr;
  tpuclient::Error err = client->Infer(&dead_result, options,
                                       {input0.get(), input1.get()});
  if (err.IsOk()) {
    delete dead_result;
    std::cerr << "infer after unload should fail\n";
    return 1;
  }

  std::cout << "PASS: model control" << std::endl;
  return 0;
}
