// Callback-async HTTP inference: several requests in flight on the
// worker pool, completions on callback threads (parity example:
// reference src/c++/examples/simple_http_async_infer_client.cc).
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <iostream>
#include <memory>
#include <mutex>

#include "http_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerHttpClient::Create(
                  &client, Url(argc, argv, "localhost:8000")),
              "create client");

  int32_t in0[16], in1[16];
  for (int i = 0; i < 16; ++i) { in0[i] = i; in1[i] = 1; }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  input0->AppendRaw(reinterpret_cast<uint8_t*>(in0), sizeof(in0));
  input1->AppendRaw(reinterpret_cast<uint8_t*>(in1), sizeof(in1));

  constexpr int kRequests = 8;
  std::mutex mutex;
  std::condition_variable cv;
  int outstanding = kRequests;
  int failures = 0;

  tpuclient::InferOptions options("simple");
  for (int r = 0; r < kRequests; ++r) {
    FAIL_IF_ERR(client->AsyncInfer(
                    [&](tpuclient::InferResult* raw) {
                      std::unique_ptr<tpuclient::InferResult> result(raw);
                      bool ok = result->RequestStatus().IsOk();
                      const uint8_t* buf = nullptr;
                      size_t len = 0;
                      if (ok) {
                        ok = result->RawData("OUTPUT0", &buf, &len).IsOk() &&
                             len == 16 * sizeof(int32_t);
                      }
                      if (ok) {
                        const int32_t* sums =
                            reinterpret_cast<const int32_t*>(buf);
                        for (int i = 0; i < 16; ++i) {
                          if (sums[i] != i + 1) ok = false;
                        }
                      }
                      std::lock_guard<std::mutex> lock(mutex);
                      if (!ok) ++failures;
                      --outstanding;
                      cv.notify_one();
                    },
                    options, {input0.get(), input1.get()}),
                "async infer");
  }
  {
    std::unique_lock<std::mutex> lock(mutex);
    if (!cv.wait_for(lock, std::chrono::seconds(60),
                     [&] { return outstanding == 0; })) {
      std::cerr << "timed out waiting for callbacks\n";
      return 1;
    }
  }
  if (failures != 0) {
    std::cerr << failures << " request(s) failed\n";
    return 1;
  }
  std::cout << "PASS: http async infer (" << kRequests << " requests)"
            << std::endl;
  return 0;
}
