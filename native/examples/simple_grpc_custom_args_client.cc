// Custom connection arguments: keepalive tuning, per-call headers,
// and a client-side deadline on one client (parity example: reference
// src/c++/examples/simple_grpc_custom_args_client.cc, which sets
// grpc::ChannelArguments — keepalive intervals, message-size caps —
// before creating the client).
#include <cstring>
#include <iostream>
#include <string>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  // Connection-level custom args: keepalive probing cadence (the
  // equivalent of GRPC_ARG_KEEPALIVE_TIME_MS/TIMEOUT_MS channel args).
  tpuclient::InferenceServerGrpcClient::KeepAliveOptions keepalive;
  keepalive.keepalive_time_ms = 10 * 1000;
  keepalive.keepalive_timeout_ms = 20 * 1000;

  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001"), keepalive),
              "create client");

  int32_t in0[16], in1[16];
  for (int i = 0; i < 16; ++i) {
    in0[i] = i;
    in1[i] = 2;
  }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  input0->AppendRaw(reinterpret_cast<uint8_t*>(in0), sizeof(in0));
  input1->AppendRaw(reinterpret_cast<uint8_t*>(in1), sizeof(in1));

  // Per-call custom args: request headers ride every RPC; the
  // client-side deadline bounds the call.
  tpuclient::Headers headers;
  headers["x-example-tag"] = "custom-args";
  tpuclient::InferOptions options("simple");
  options.request_id = "custom-args-1";
  options.client_timeout_us = 5 * 1000 * 1000;  // 5s deadline

  tpuclient::InferResult* raw_result;
  FAIL_IF_ERR(
      client->Infer(&raw_result, options, {input0.get(), input1.get()}, {},
                    headers),
      "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  const uint8_t* buf;
  size_t size;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &buf, &size), "OUTPUT0");
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (sums[i] != in0[i] + in1[i]) {
      std::cerr << "error: sum mismatch at " << i << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: custom args client" << std::endl;
  return 0;
}
