// Stateful sequences over HTTP/REST: two interleaved sequences of
// correlated requests accumulate independently on the server (parity
// example: reference
// src/c++/examples/simple_http_sequence_sync_infer_client.cc).
#include <cstring>
#include <iostream>

#include "http_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)

int32_t SendSequenceValue(
    tpuclient::InferenceServerHttpClient* client, uint64_t sequence_id,
    int32_t value, bool start, bool end) {
  tpuclient::InferInput* raw_input;
  FAIL_IF_ERR(tpuclient::InferInput::Create(&raw_input, "INPUT", {1},
                                            "INT32"),
              "create input");
  std::unique_ptr<tpuclient::InferInput> input(raw_input);
  FAIL_IF_ERR(input->AppendRaw(reinterpret_cast<const uint8_t*>(&value),
                               sizeof(value)),
              "set input");

  tpuclient::InferOptions options("simple_sequence");
  options.sequence_id = sequence_id;
  options.sequence_start = start;
  options.sequence_end = end;

  tpuclient::InferResult* raw_result = nullptr;
  FAIL_IF_ERR(client->Infer(&raw_result, options, {input.get()}),
              "sequence infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  const uint8_t* buf;
  size_t size;
  FAIL_IF_ERR(result->RawData("OUTPUT", &buf, &size), "OUTPUT");
  return *reinterpret_cast<const int32_t*>(buf);
}
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerHttpClient::Create(
                  &client, Url(argc, argv, "localhost:8000")),
              "create client");

  // Two sequences, interleaved: each accumulates its own sum.
  const uint64_t seq_a = 11001, seq_b = 11002;
  SendSequenceValue(client.get(), seq_a, 1, true, false);
  SendSequenceValue(client.get(), seq_b, 100, true, false);
  SendSequenceValue(client.get(), seq_a, 2, false, false);
  SendSequenceValue(client.get(), seq_b, 200, false, false);
  int32_t total_a = SendSequenceValue(client.get(), seq_a, 3, false, true);
  int32_t total_b = SendSequenceValue(client.get(), seq_b, 300, false, true);

  if (total_a != 6 || total_b != 600) {
    std::cerr << "sequence totals wrong: " << total_a << " " << total_b
              << "\n";
    return 1;
  }
  std::cout << "PASS: http sequence sync (totals " << total_a << ", "
            << total_b << ")" << std::endl;
  return 0;
}
