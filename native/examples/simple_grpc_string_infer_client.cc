// BYTES-tensor inference via AppendFromString / StringData
// (parity example: reference src/c++/examples/simple_grpc_string_infer_client.cc).
#include <cstring>
#include <iostream>

#include "grpc_client.h"


namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  std::vector<std::string> in0, in1;
  for (int i = 0; i < 16; ++i) {
    in0.push_back(std::to_string(i));
    in1.push_back("1");
  }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "BYTES");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "BYTES");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  FAIL_IF_ERR(input0->AppendFromString(in0), "INPUT0 strings");
  FAIL_IF_ERR(input1->AppendFromString(in1), "INPUT1 strings");

  tpuclient::InferOptions options("simple_string");
  tpuclient::InferResult* raw_result;
  FAIL_IF_ERR(client->Infer(&raw_result, options,
                            {input0.get(), input1.get()}),
              "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);

  std::vector<std::string> out0;
  FAIL_IF_ERR(result->StringData("OUTPUT0", &out0), "OUTPUT0 strings");
  if (out0.size() != 16) { std::cerr << "bad count\n"; return 1; }
  for (int i = 0; i < 16; ++i) {
    if (atoi(out0[i].c_str()) != i + 1) {
      std::cerr << "mismatch at " << i << "\n";
      return 1;
    }
  }
  std::cout << "PASS: string infer" << std::endl;
  return 0;
}
