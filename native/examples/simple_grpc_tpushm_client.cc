// TPU HBM shared-memory I/O over gRPC: inputs are uploaded ONCE into
// arena regions on the accelerator, every inference references them
// by region name, and outputs land in an arena region without ever
// leaving HBM — the zero-copy co-location flow the framework is built
// around (parity example: reference
// src/c++/examples/simple_grpc_cudashm_client.cc, with the HBM arena
// standing in for cudaIpcMemHandle_t regions).
#include <cstdint>
#include <cstring>
#include <iostream>
#include <memory>
#include <vector>

#include "../perf/client_backend.h"
#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  const std::string url = Url(argc, argv, "localhost:8001");
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(&client, url),
              "create client");
  client->UnregisterTpuSharedMemory();

  // The arena service is co-hosted with the inference endpoint; it is
  // the stand-in for client-side cudaMalloc + cudaIpcGetMemHandle.
  std::unique_ptr<tpuclient::perf::TpuArenaClient> arena;
  FAIL_IF_ERR(tpuclient::perf::TpuArenaClient::Create(&arena, url),
              "create arena client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);

  // One region per input, typed at upload time so the server stores a
  // ready-to-consume device array.
  std::vector<int32_t> in0(16), in1(16);
  for (int i = 0; i < 16; ++i) { in0[i] = i; in1[i] = 1; }
  const char* names[2] = {"tpushm_in0", "tpushm_in1"};
  std::vector<int32_t>* host[2] = {&in0, &in1};
  for (int idx = 0; idx < 2; ++idx) {
    std::string raw_handle, region_id;
    FAIL_IF_ERR(arena->CreateRegion(kTensorBytes, 0, &raw_handle,
                                    &region_id),
                "allocate input region");
    FAIL_IF_ERR(arena->WriteRegion(
                    region_id, 0,
                    std::string(
                        reinterpret_cast<const char*>(host[idx]->data()),
                        kTensorBytes),
                    "INT32", {16}),
                "upload input");
    FAIL_IF_ERR(client->RegisterTpuSharedMemory(names[idx], raw_handle, 0,
                                                kTensorBytes),
                "register input region");
  }

  std::string out_handle, out_region_id;
  FAIL_IF_ERR(arena->CreateRegion(kTensorBytes * 2, 0, &out_handle,
                                  &out_region_id),
              "allocate output region");
  FAIL_IF_ERR(client->RegisterTpuSharedMemory("tpushm_out", out_handle, 0,
                                              kTensorBytes * 2),
              "register output region");

  // Inference: every tensor rides by region reference; no payload
  // bytes cross the wire and outputs stay on the accelerator.
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  FAIL_IF_ERR(tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32"),
              "create INPUT0");
  FAIL_IF_ERR(tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32"),
              "create INPUT1");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  FAIL_IF_ERR(input0->SetSharedMemory("tpushm_in0", kTensorBytes),
              "INPUT0 shm");
  FAIL_IF_ERR(input1->SetSharedMemory("tpushm_in1", kTensorBytes),
              "INPUT1 shm");

  tpuclient::InferRequestedOutput* raw_out0;
  tpuclient::InferRequestedOutput* raw_out1;
  FAIL_IF_ERR(tpuclient::InferRequestedOutput::Create(&raw_out0, "OUTPUT0"),
              "create OUTPUT0");
  FAIL_IF_ERR(tpuclient::InferRequestedOutput::Create(&raw_out1, "OUTPUT1"),
              "create OUTPUT1");
  std::unique_ptr<tpuclient::InferRequestedOutput> out0(raw_out0);
  std::unique_ptr<tpuclient::InferRequestedOutput> out1(raw_out1);
  FAIL_IF_ERR(out0->SetSharedMemory("tpushm_out", kTensorBytes, 0),
              "OUTPUT0 shm");
  FAIL_IF_ERR(out1->SetSharedMemory("tpushm_out", kTensorBytes,
                                    kTensorBytes),
              "OUTPUT1 shm");

  tpuclient::InferOptions options("simple");
  tpuclient::InferResult* result = nullptr;
  FAIL_IF_ERR(client->Infer(&result, options,
                            {input0.get(), input1.get()},
                            {out0.get(), out1.get()}),
              "infer");
  std::unique_ptr<tpuclient::InferResult> owned(result);

  // Outputs live in the arena; read them back through the allocation
  // side-channel only for verification (a co-located consumer would
  // keep them on device).
  std::string payload;
  FAIL_IF_ERR(arena->ReadRegion(out_region_id, 0, kTensorBytes * 2,
                                &payload),
              "read outputs");
  const int32_t* sum = reinterpret_cast<const int32_t*>(payload.data());
  const int32_t* diff = sum + 16;
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != in0[i] + in1[i] || diff[i] != in0[i] - in1[i]) {
      std::cerr << "mismatch at " << i << ": " << sum[i] << ", " << diff[i]
                << std::endl;
      return 1;
    }
  }

  FAIL_IF_ERR(client->UnregisterTpuSharedMemory(), "unregister");
  std::cout << "PASS: tpu shm infer" << std::endl;
  return 0;
}
