// System shared memory over the HTTP/REST front-end: inputs AND
// outputs ride POSIX shm regions, only tensor references cross the
// wire (parity example: reference
// src/c++/examples/simple_http_shm_client.cc).
#include <cstring>
#include <iostream>

#include "http_client.h"
#include "shm_utils.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerHttpClient::Create(
                  &client, Url(argc, argv, "localhost:8000")),
              "create client");
  client->UnregisterSystemSharedMemory();

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);

  int in_fd;
  void* in_addr;
  FAIL_IF_ERR(tpuclient::CreateSharedMemoryRegion(
                  "/http_example_input", kTensorBytes * 2, &in_fd),
              "create input region");
  FAIL_IF_ERR(tpuclient::MapSharedMemory(
                  in_fd, 0, kTensorBytes * 2, &in_addr),
              "map input region");
  int32_t* in0 = static_cast<int32_t*>(in_addr);
  int32_t* in1 = in0 + 16;
  for (int i = 0; i < 16; ++i) { in0[i] = i; in1[i] = 3; }

  int out_fd;
  void* out_addr;
  FAIL_IF_ERR(tpuclient::CreateSharedMemoryRegion(
                  "/http_example_output", kTensorBytes * 2, &out_fd),
              "create output region");
  FAIL_IF_ERR(tpuclient::MapSharedMemory(
                  out_fd, 0, kTensorBytes * 2, &out_addr),
              "map output region");

  FAIL_IF_ERR(client->RegisterSystemSharedMemory(
                  "http_input_data", "/http_example_input",
                  kTensorBytes * 2),
              "register input region");
  FAIL_IF_ERR(client->RegisterSystemSharedMemory(
                  "http_output_data", "/http_example_output",
                  kTensorBytes * 2),
              "register output region");

  std::string status;
  FAIL_IF_ERR(client->SystemSharedMemoryStatus(&status), "shm status");
  if (status.find("http_input_data") == std::string::npos) {
    std::cerr << "registered region missing from status\n";
    return 1;
  }

  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  input0->SetSharedMemory("http_input_data", kTensorBytes, 0);
  input1->SetSharedMemory("http_input_data", kTensorBytes, kTensorBytes);

  tpuclient::InferRequestedOutput* rout0;
  tpuclient::InferRequestedOutput* rout1;
  tpuclient::InferRequestedOutput::Create(&rout0, "OUTPUT0");
  tpuclient::InferRequestedOutput::Create(&rout1, "OUTPUT1");
  std::unique_ptr<tpuclient::InferRequestedOutput> output0(rout0),
      output1(rout1);
  output0->SetSharedMemory("http_output_data", kTensorBytes, 0);
  output1->SetSharedMemory("http_output_data", kTensorBytes, kTensorBytes);

  tpuclient::InferOptions options("simple");
  tpuclient::InferResult* raw_result;
  FAIL_IF_ERR(client->Infer(&raw_result, options,
                            {input0.get(), input1.get()},
                            {output0.get(), output1.get()}),
              "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);

  const int32_t* sum = static_cast<const int32_t*>(out_addr);
  const int32_t* diff = sum + 16;
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != in0[i] + in1[i] || diff[i] != in0[i] - in1[i]) {
      std::cerr << "mismatch at " << i << "\n";
      return 1;
    }
  }

  client->UnregisterSystemSharedMemory();
  tpuclient::UnmapSharedMemory(in_addr, kTensorBytes * 2);
  tpuclient::UnmapSharedMemory(out_addr, kTensorBytes * 2);
  tpuclient::CloseSharedMemory(in_fd);
  tpuclient::CloseSharedMemory(out_fd);
  tpuclient::UnlinkSharedMemoryRegion("/http_example_input");
  tpuclient::UnlinkSharedMemoryRegion("/http_example_output");
  std::cout << "PASS: http system shm infer" << std::endl;
  return 0;
}
