// Callback-async gRPC inference: several in-flight requests
// (parity example: reference src/c++/examples/simple_grpc_async_infer_client.cc).
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <mutex>

#include "grpc_client.h"


namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  int32_t in0[16], in1[16];
  for (int i = 0; i < 16; ++i) { in0[i] = i; in1[i] = 2; }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  input0->AppendRaw(reinterpret_cast<uint8_t*>(in0), sizeof(in0));
  input1->AppendRaw(reinterpret_cast<uint8_t*>(in1), sizeof(in1));

  constexpr int kRequests = 8;
  std::mutex mutex;
  std::condition_variable cv;
  int done = 0, ok = 0;

  tpuclient::InferOptions options("simple");
  for (int r = 0; r < kRequests; ++r) {
    FAIL_IF_ERR(client->AsyncInfer(
                    [&](tpuclient::InferResult* result) {
                      std::unique_ptr<tpuclient::InferResult> owned(result);
                      bool good = owned->RequestStatus().IsOk();
                      std::lock_guard<std::mutex> lock(mutex);
                      ++done;
                      if (good) ++ok;
                      cv.notify_all();
                    },
                    options, {input0.get(), input1.get()}),
                "async infer");
  }
  std::unique_lock<std::mutex> lock(mutex);
  cv.wait(lock, [&] { return done == kRequests; });
  if (ok != kRequests) { std::cerr << "failures\n"; return 1; }
  std::cout << "PASS: async infer x" << kRequests << std::endl;
  return 0;
}
