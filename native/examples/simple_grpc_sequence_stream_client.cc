// Sequences over the bidi stream: two interleaved correlated
// sequences share one ModelStreamInfer stream, responses matched to
// requests by id (parity example: reference
// src/c++/examples/simple_grpc_sequence_stream_infer_client.cc).
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <map>
#include <mutex>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  std::mutex mutex;
  std::condition_variable cv;
  std::map<std::string, int32_t> results;  // request id -> OUTPUT

  FAIL_IF_ERR(
      client->StartStream([&](tpuclient::InferResult* raw) {
        std::unique_ptr<tpuclient::InferResult> result(raw);
        std::string id;
        const uint8_t* buf;
        size_t size;
        if (result->Id(&id).IsOk() &&
            result->RawData("OUTPUT", &buf, &size).IsOk() && size >= 4) {
          std::lock_guard<std::mutex> lock(mutex);
          results[id] = *reinterpret_cast<const int32_t*>(buf);
          cv.notify_all();
        }
      }),
      "start stream");

  auto send = [&](uint64_t seq, int32_t value, bool start, bool end,
                  const std::string& id) {
    tpuclient::InferInput* raw_input;
    FAIL_IF_ERR(tpuclient::InferInput::Create(&raw_input, "INPUT", {1},
                                              "INT32"),
                "create input");
    std::unique_ptr<tpuclient::InferInput> input(raw_input);
    input->AppendRaw(reinterpret_cast<const uint8_t*>(&value),
                     sizeof(value));
    tpuclient::InferOptions options("simple_sequence");
    options.sequence_id = seq;
    options.sequence_start = start;
    options.sequence_end = end;
    options.request_id = id;
    FAIL_IF_ERR(client->AsyncStreamInfer(options, {input.get()}),
                "stream infer");
  };

  // Interleave two sequences on the one stream.
  send(21001, 1, true, false, "a1");
  send(21002, 10, true, false, "b1");
  send(21001, 2, false, false, "a2");
  send(21002, 20, false, false, "b2");
  send(21001, 3, false, true, "a3");
  send(21002, 30, false, true, "b3");

  {
    std::unique_lock<std::mutex> lock(mutex);
    if (!cv.wait_for(lock, std::chrono::seconds(20),
                     [&] { return results.size() >= 6; })) {
      std::cerr << "timeout (" << results.size() << " responses)\n";
      return 1;
    }
  }
  FAIL_IF_ERR(client->StopStream(), "stop stream");

  if (results["a3"] != 6 || results["b3"] != 60) {
    std::cerr << "sequence totals wrong: " << results["a3"] << " "
              << results["b3"] << "\n";
    return 1;
  }
  std::cout << "PASS: sequences over bidi stream (totals "
            << results["a3"] << ", " << results["b3"] << ")" << std::endl;
  return 0;
}
