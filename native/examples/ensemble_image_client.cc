// Ensemble pipeline client: one request drives the server-side
// preprocess -> backbone -> postprocess chain and returns the top-1
// label as a BYTES tensor (parity example: reference
// src/c++/examples/ensemble_image_client.cc, which feeds the
// preprocess+inception ensemble and prints classifications).
//
// Start a server first:
//   python -m client_tpu.server.app --models ensemble_image
#include <cstring>
#include <iostream>
#include <random>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  // The ensemble's wire input is the RAW uint8 image — all
  // preprocessing happens server-side, which is the point of the
  // ensemble: one compact request, three composed model executions.
  constexpr int kBatch = 2;
  constexpr size_t kImageBytes = 224 * 224 * 3;
  std::vector<uint8_t> images(kBatch * kImageBytes);
  std::mt19937_64 rng(7);
  for (auto& byte : images) byte = static_cast<uint8_t>(rng() % 256);

  tpuclient::InferInput* raw_input;
  tpuclient::InferInput::Create(&raw_input, "RAW_IMAGE",
                                {kBatch, 224, 224, 3}, "UINT8");
  std::unique_ptr<tpuclient::InferInput> input(raw_input);
  FAIL_IF_ERR(input->AppendRaw(images.data(), images.size()), "append");

  tpuclient::InferOptions options("ensemble_image");
  tpuclient::InferResult* raw_result;
  FAIL_IF_ERR(client->Infer(&raw_result, options, {input.get()}), "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  // LABEL rows are "score:index" strings from the postprocess step.
  std::vector<std::string> labels;
  FAIL_IF_ERR(result->StringData("LABEL", &labels), "LABEL");
  if (labels.size() != kBatch) {
    std::cerr << "error: expected " << kBatch << " labels, got "
              << labels.size() << std::endl;
    return 1;
  }
  for (size_t i = 0; i < labels.size(); ++i) {
    if (labels[i].find(':') == std::string::npos) {
      std::cerr << "error: malformed label '" << labels[i] << "'"
                << std::endl;
      return 1;
    }
    std::cout << "image " << i << " -> " << labels[i] << std::endl;
  }

  // The composing models' executions are visible in server stats —
  // the ensemble really ran as three scheduled steps.
  inference::ModelStatisticsResponse stats;
  FAIL_IF_ERR(client->ModelInferenceStatistics(&stats, "resnet50"),
              "statistics");
  if (stats.model_stats_size() < 1 ||
      stats.model_stats(0).execution_count() < 1) {
    std::cerr << "error: backbone recorded no executions" << std::endl;
    return 1;
  }
  std::cout << "PASS: ensemble image client" << std::endl;
  return 0;
}
