// Client-side keepalive: h2 PING probing on the connection detects a
// dead peer without waiting on per-call timeouts (parity example:
// reference src/c++/examples/simple_grpc_keepalive_client.cc, which
// sets GRPC_ARG_KEEPALIVE_* channel args).
#include <cstring>
#include <iostream>
#include <thread>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  tpuclient::InferenceServerGrpcClient::KeepAliveOptions keepalive;
  keepalive.keepalive_time_ms = 200;     // probe every 200ms
  keepalive.keepalive_timeout_ms = 2000; // dead if unacked for 2s

  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001"), keepalive),
              "create client");

  int32_t in0[16], in1[16];
  for (int i = 0; i < 16; ++i) { in0[i] = i; in1[i] = 1; }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  input0->AppendRaw(reinterpret_cast<uint8_t*>(in0), sizeof(in0));
  input1->AppendRaw(reinterpret_cast<uint8_t*>(in1), sizeof(in1));

  // Several inferences with idle gaps: the keepalive PINGs keep
  // flowing between calls and each ack proves the peer alive.
  tpuclient::InferOptions options("simple");
  for (int round = 0; round < 3; ++round) {
    tpuclient::InferResult* raw_result;
    FAIL_IF_ERR(client->Infer(&raw_result, options,
                              {input0.get(), input1.get()}),
                "infer");
    std::unique_ptr<tpuclient::InferResult> result(raw_result);
    const uint8_t* buf;
    size_t size;
    FAIL_IF_ERR(result->RawData("OUTPUT0", &buf, &size), "OUTPUT0");
    if (reinterpret_cast<const int32_t*>(buf)[5] != in0[5] + in1[5]) {
      std::cerr << "mismatch\n";
      return 1;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(300));
  }

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server live after idling");
  if (!live) {
    std::cerr << "server reported dead\n";
    return 1;
  }
  std::cout << "PASS: keepalive (connection probed across idle gaps)"
            << std::endl;
  return 0;
}
