// Reusing InferInput / InferRequestedOutput objects across calls and
// across BOTH protocol clients: build the request objects once, run
// them through gRPC and HTTP repeatedly with refreshed tensor data
// (parity example: reference
// src/c++/examples/reuse_infer_objects_client.cc).
#include <cstring>
#include <iostream>

#include "grpc_client.h"
#include "http_client.h"

namespace {
const char* Url(int argc, char** argv, const char* flag,
                const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], flag) == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)

template <typename Client>
void RunOnce(Client* client, tpuclient::InferInput* input0,
             tpuclient::InferInput* input1,
             tpuclient::InferRequestedOutput* output0, int32_t base) {
  int32_t in0[16], in1[16];
  for (int i = 0; i < 16; ++i) { in0[i] = base + i; in1[i] = 7; }
  // Reset() then AppendRaw(): the same objects carry fresh tensors.
  FAIL_IF_ERR(input0->Reset(), "reset input0");
  FAIL_IF_ERR(input1->Reset(), "reset input1");
  FAIL_IF_ERR(input0->AppendRaw(reinterpret_cast<uint8_t*>(in0),
                                sizeof(in0)),
              "append input0");
  FAIL_IF_ERR(input1->AppendRaw(reinterpret_cast<uint8_t*>(in1),
                                sizeof(in1)),
              "append input1");

  tpuclient::InferOptions options("simple");
  tpuclient::InferResult* raw_result;
  FAIL_IF_ERR(client->Infer(&raw_result, options, {input0, input1},
                            {output0}),
              "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  const uint8_t* buf;
  size_t size;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &buf, &size), "OUTPUT0");
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != in0[i] + in1[i]) {
      std::cerr << "mismatch at " << i << " (base " << base << ")\n";
      exit(1);
    }
  }
}
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> grpc_client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &grpc_client, Url(argc, argv, "-u", "localhost:8001")),
              "create grpc client");
  std::unique_ptr<tpuclient::InferenceServerHttpClient> http_client;
  FAIL_IF_ERR(tpuclient::InferenceServerHttpClient::Create(
                  &http_client, Url(argc, argv, "-w", "localhost:8000")),
              "create http client");

  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  tpuclient::InferRequestedOutput* rout0;
  tpuclient::InferRequestedOutput::Create(&rout0, "OUTPUT0");
  std::unique_ptr<tpuclient::InferRequestedOutput> output0(rout0);

  // The same three objects serve six calls across two protocols.
  for (int round = 0; round < 3; ++round) {
    RunOnce(grpc_client.get(), input0.get(), input1.get(), output0.get(),
            round * 10);
    RunOnce(http_client.get(), input0.get(), input1.get(), output0.get(),
            round * 10 + 5);
  }
  std::cout << "PASS: object reuse across calls and protocols"
            << std::endl;
  return 0;
}
