// Explicit model lifecycle over HTTP/REST: load, ready-check, infer,
// unload, verify-not-ready (parity example: reference
// src/c++/examples/simple_http_model_control.cc).
#include <cstring>
#include <iostream>

#include "http_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerHttpClient::Create(
                  &client, Url(argc, argv, "localhost:8000")),
              "create client");

  FAIL_IF_ERR(client->LoadModel("add_sub"), "load model");
  bool ready = false;
  FAIL_IF_ERR(client->IsModelReady(&ready, "add_sub"), "model ready");
  if (!ready) {
    std::cerr << "add_sub not ready after load\n";
    return 1;
  }

  int32_t in0[16], in1[16];
  for (int i = 0; i < 16; ++i) { in0[i] = i; in1[i] = 2; }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  input0->AppendRaw(reinterpret_cast<uint8_t*>(in0), sizeof(in0));
  input1->AppendRaw(reinterpret_cast<uint8_t*>(in1), sizeof(in1));

  tpuclient::InferOptions options("add_sub");
  tpuclient::InferResult* raw_result;
  FAIL_IF_ERR(client->Infer(&raw_result, options,
                            {input0.get(), input1.get()}),
              "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  const uint8_t* buf;
  size_t size;
  FAIL_IF_ERR(result->RawData("OUTPUT0", &buf, &size), "OUTPUT0");
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != in0[i] + in1[i]) {
      std::cerr << "mismatch\n";
      return 1;
    }
  }

  FAIL_IF_ERR(client->UnloadModel("add_sub"), "unload model");
  ready = true;
  client->IsModelReady(&ready, "add_sub");
  if (ready) {
    std::cerr << "add_sub still ready after unload\n";
    return 1;
  }
  std::cout << "PASS: http model control" << std::endl;
  return 0;
}
