// C++ image classification client for resnet50: batched NHWC float
// input over gRPC async, top-K parse of the logits (parity example:
// reference src/c++/examples/image_client.cc — there OpenCV decodes
// JPEGs; here the image is synthesized or read as raw float32 NHWC
// so the example carries no image-library dependency).
#include <algorithm>
#include <condition_variable>
#include <cstring>
#include <fstream>
#include <iostream>
#include <mutex>
#include <vector>

#include "grpc_client.h"

namespace {
constexpr int kH = 224, kW = 224, kC = 3, kClasses = 1000;

const char* Arg(int argc, char** argv, const char* flag,
                const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], flag) == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  const char* url = Arg(argc, argv, "-u", "localhost:8001");
  int batch = std::max(1, atoi(Arg(argc, argv, "-b", "2")));
  int topk = std::min(std::max(1, atoi(Arg(argc, argv, "-c", "3"))),
                      kClasses);
  const char* raw_path = Arg(argc, argv, "-f", "");  // raw f32 NHWC file

  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(&client, url),
              "create client");

  // One image: from a raw float32 file, or a synthesized gradient
  // (channel-normalized like the Python image_client's INCEPTION
  // scaling).
  std::vector<float> image(kH * kW * kC);
  if (raw_path[0] != '\0') {
    std::ifstream file(raw_path, std::ios::binary);
    if (!file.read(reinterpret_cast<char*>(image.data()),
                   image.size() * sizeof(float))) {
      std::cerr << "failed to read " << raw_path << "\n";
      return 1;
    }
  } else {
    for (int y = 0; y < kH; ++y) {
      for (int x = 0; x < kW; ++x) {
        for (int c = 0; c < kC; ++c) {
          image[(y * kW + x) * kC + c] =
              (static_cast<float>(x + y + c * 37) / (kH + kW)) - 0.5f;
        }
      }
    }
  }
  // The batch repeats the image (reference: one file per batch slot).
  std::vector<float> batched;
  batched.reserve(image.size() * batch);
  for (int i = 0; i < batch; ++i) {
    batched.insert(batched.end(), image.begin(), image.end());
  }

  tpuclient::InferInput* raw_input;
  FAIL_IF_ERR(tpuclient::InferInput::Create(
                  &raw_input, "INPUT", {batch, kH, kW, kC}, "FP32"),
              "create input");
  std::unique_ptr<tpuclient::InferInput> input(raw_input);
  FAIL_IF_ERR(
      input->AppendRaw(reinterpret_cast<uint8_t*>(batched.data()),
                       batched.size() * sizeof(float)),
      "set image data");

  std::mutex mutex;
  std::condition_variable cv;
  tpuclient::InferResult* async_result = nullptr;

  tpuclient::InferOptions options("resnet50");
  FAIL_IF_ERR(client->AsyncInfer(
                  [&](tpuclient::InferResult* r) {
                    std::lock_guard<std::mutex> lock(mutex);
                    async_result = r;
                    cv.notify_all();
                  },
                  options, {input.get()}),
              "async infer");
  {
    std::unique_lock<std::mutex> lock(mutex);
    if (!cv.wait_for(lock, std::chrono::seconds(120),
                     [&] { return async_result != nullptr; })) {
      std::cerr << "timeout\n";
      return 1;
    }
  }
  std::unique_ptr<tpuclient::InferResult> result(async_result);
  FAIL_IF_ERR(result->RequestStatus(), "inference failed");

  const uint8_t* buf;
  size_t size;
  FAIL_IF_ERR(result->RawData("OUTPUT", &buf, &size), "OUTPUT");
  if (size < static_cast<size_t>(batch) * kClasses * sizeof(float)) {
    std::cerr << "short output: " << size << " bytes\n";
    return 1;
  }
  const float* logits = reinterpret_cast<const float*>(buf);
  for (int b = 0; b < batch; ++b) {
    std::vector<int> order(kClasses);
    for (int i = 0; i < kClasses; ++i) order[i] = i;
    const float* row = logits + b * kClasses;
    std::partial_sort(order.begin(), order.begin() + topk, order.end(),
                      [row](int a, int c) { return row[a] > row[c]; });
    std::cout << "image " << b << " top-" << topk << ":";
    for (int i = 0; i < topk; ++i) {
      std::cout << " class " << order[i] << " (" << row[order[i]] << ")";
    }
    std::cout << std::endl;
  }
  std::cout << "PASS: image classification (batch " << batch << ")"
            << std::endl;
  return 0;
}
