// BYTES/string tensors over HTTP: length-prefixed string payloads in
// the binary protocol both directions (parity example: reference
// src/c++/examples/simple_http_string_infer_client.cc).
#include <cstring>
#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "http_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerHttpClient::Create(
                  &client, Url(argc, argv, "localhost:8000")),
              "create client");

  std::vector<std::string> in0, in1;
  for (int i = 0; i < 16; ++i) {
    in0.push_back(std::to_string(i));
    in1.push_back("1");
  }
  tpuclient::InferInput* raw0;
  tpuclient::InferInput* raw1;
  FAIL_IF_ERR(tpuclient::InferInput::Create(&raw0, "INPUT0", {16}, "BYTES"),
              "create INPUT0");
  FAIL_IF_ERR(tpuclient::InferInput::Create(&raw1, "INPUT1", {16}, "BYTES"),
              "create INPUT1");
  std::unique_ptr<tpuclient::InferInput> input0(raw0), input1(raw1);
  FAIL_IF_ERR(input0->AppendFromString(in0), "fill INPUT0");
  FAIL_IF_ERR(input1->AppendFromString(in1), "fill INPUT1");

  tpuclient::InferOptions options("simple_string");
  tpuclient::InferResult* raw_result = nullptr;
  FAIL_IF_ERR(client->Infer(&raw_result, options,
                            {input0.get(), input1.get()}),
              "infer");
  std::unique_ptr<tpuclient::InferResult> result(raw_result);
  FAIL_IF_ERR(result->RequestStatus(), "request status");

  std::vector<std::string> sums, diffs;
  FAIL_IF_ERR(result->StringData("OUTPUT0", &sums), "read OUTPUT0");
  FAIL_IF_ERR(result->StringData("OUTPUT1", &diffs), "read OUTPUT1");
  if (sums.size() != 16 || diffs.size() != 16) {
    std::cerr << "unexpected element counts: " << sums.size() << ", "
              << diffs.size() << std::endl;
    return 1;
  }
  for (int i = 0; i < 16; ++i) {
    if (sums[i] != std::to_string(i + 1) ||
        diffs[i] != std::to_string(i - 1)) {
      std::cerr << "mismatch at " << i << ": " << sums[i] << ", "
                << diffs[i] << std::endl;
      return 1;
    }
  }
  std::cout << "PASS: http string infer" << std::endl;
  return 0;
}
