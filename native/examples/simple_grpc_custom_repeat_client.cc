// Decoupled model driven with custom request parameters: repeat_int32
// streams one response per input element, with per-response DELAY
// values controlling the server-side pacing (parity example:
// reference src/c++/examples/simple_grpc_custom_repeat.cc).
#include <condition_variable>
#include <cstring>
#include <iostream>
#include <mutex>
#include <vector>

#include "grpc_client.h"

namespace {
const char* Url(int argc, char** argv, const char* fallback) {
  for (int i = 1; i + 1 < argc; ++i) {
    if (strcmp(argv[i], "-u") == 0) return argv[i + 1];
  }
  return fallback;
}
#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tpuclient::Error err__ = (x);                                   \
    if (!err__.IsOk()) {                                            \
      std::cerr << "error: " << msg << ": " << err__.Message()      \
                << std::endl;                                       \
      exit(1);                                                      \
    }                                                               \
  } while (0)
}  // namespace

int main(int argc, char** argv) {
  std::unique_ptr<tpuclient::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tpuclient::InferenceServerGrpcClient::Create(
                  &client, Url(argc, argv, "localhost:8001")),
              "create client");

  std::mutex mutex;
  std::condition_variable cv;
  std::vector<int32_t> received;
  bool done = false;

  FAIL_IF_ERR(
      client->StartStream([&](tpuclient::InferResult* raw) {
        std::unique_ptr<tpuclient::InferResult> result(raw);
        auto* stream_result =
            static_cast<tpuclient::InferResultGrpc*>(result.get());
        std::lock_guard<std::mutex> lock(mutex);
        const uint8_t* buf;
        size_t size;
        if (result->RawData("OUT", &buf, &size).IsOk() && size >= 4) {
          received.push_back(*reinterpret_cast<const int32_t*>(buf));
        }
        if (stream_result->IsFinalResponse()) done = true;
        cv.notify_all();
      }),
      "start stream");

  constexpr int kCount = 8;
  int32_t values[kCount];
  uint32_t delays[kCount];
  for (int i = 0; i < kCount; ++i) {
    values[i] = i * 11;
    delays[i] = 1000;  // 1ms between responses
  }
  tpuclient::InferInput* raw_in;
  tpuclient::InferInput* raw_delay;
  tpuclient::InferInput::Create(&raw_in, "IN", {kCount}, "INT32");
  tpuclient::InferInput::Create(&raw_delay, "DELAY", {kCount}, "UINT32");
  std::unique_ptr<tpuclient::InferInput> input(raw_in), delay(raw_delay);
  input->AppendRaw(reinterpret_cast<uint8_t*>(values), sizeof(values));
  delay->AppendRaw(reinterpret_cast<uint8_t*>(delays), sizeof(delays));

  tpuclient::InferOptions options("repeat_int32");
  options.request_id = "custom-repeat-1";
  FAIL_IF_ERR(client->AsyncStreamInfer(options, {input.get(), delay.get()}),
              "stream infer");

  {
    std::unique_lock<std::mutex> lock(mutex);
    if (!cv.wait_for(lock, std::chrono::seconds(20), [&] { return done; })) {
      std::cerr << "timeout (" << received.size() << " responses)\n";
      return 1;
    }
  }
  FAIL_IF_ERR(client->StopStream(), "stop stream");

  if (received.size() != kCount) {
    std::cerr << "expected " << kCount << " responses, got "
              << received.size() << "\n";
    return 1;
  }
  for (int i = 0; i < kCount; ++i) {
    if (received[i] != values[i]) {
      std::cerr << "out-of-order or wrong value at " << i << "\n";
      return 1;
    }
  }
  std::cout << "PASS: custom repeat (" << received.size()
            << " paced responses)" << std::endl;
  return 0;
}
