// In-process backend: the perf harness drives the Python server core
// directly inside this process — no RPC, no server process.
//
// Role parity: the reference's triton_c_api backend, which dlopens
// libtritonserver.so and calls its C API so perf_analyzer measures the
// model stack without network overhead
// (/root/reference/src/c++/perf_analyzer/client_backend/triton_c_api/
// triton_c_api_backend.h:64, triton_loader.cc:526-690). Here the
// "server library" is the CPython runtime: the backend embeds the
// interpreter, imports client_tpu.server.embed, and exchanges
// serialized KServe protos (bytes in, bytes out), so request
// construction and result parsing reuse the exact gRPC-client code
// paths.
#pragma once

#include <memory>

#include "client_backend.h"

namespace tpuclient {
namespace perf {

// One embedded interpreter per process (CPython is a singleton);
// repeated Create() calls share it. Not finalized at exit — the JAX
// runtime owns background threads that do not survive Py_Finalize.
class InProcessBackend : public ClientBackend {
 public:
  // models_csv seeds embed.init (e.g. "simple"); the target model is
  // loaded on demand by the server core's repository.
  static Error Create(
      const BackendConfig& config, std::unique_ptr<ClientBackend>* backend);

  Error ServerMetadataJson(json::Value* metadata) override;
  Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string& model_version) override;
  Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string& model_version) override;
  Error ModelStatisticsJson(
      json::Value* stats, const std::string& model_name) override;

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override;
  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override;
  Error StartStream(OnCompleteFn callback) override;
  Error StopStream() override;
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override;

  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset) override;
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int64_t device_id, size_t byte_size) override;
  Error UnregisterSystemSharedMemory(const std::string& name) override;
  Error UnregisterTpuSharedMemory(const std::string& name) override;

  // Allocates an HBM arena region in-process (the no-RPC analogue of
  // TpuArenaClient::Allocate).
  static Error ArenaAllocate(
      size_t byte_size, int64_t device_id, std::string* raw_handle);

 private:
  InProcessBackend() = default;
};

}  // namespace perf
}  // namespace tpuclient
