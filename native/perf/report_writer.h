// Result reporting: stdout summary, CSV rows (parity:
// /root/reference/src/c++/perf_analyzer/report_writer.h:80) and the
// JSON profile export consumed by the genai layer (parity:
// profile_data_exporter.h:54-94 — same experiments[].requests[]
// shape, so client_tpu.genai parses either harness's output).
#pragma once

#include <string>
#include <vector>

#include "../library/common.h"
#include "inference_profiler.h"

namespace tpuclient {
namespace perf {

enum class LoadMode { CONCURRENCY, REQUEST_RATE };

void PrintReport(
    const std::vector<PerfStatus>& results, LoadMode mode,
    int percentile = 0);

Error WriteCsv(
    const std::string& path, const std::vector<PerfStatus>& results,
    LoadMode mode, bool verbose_csv = false);

Error ExportProfile(
    const std::string& path, const std::vector<PerfStatus>& results,
    const std::string& model_name, const std::string& service_kind,
    const std::string& endpoint, LoadMode mode);

}  // namespace perf
}  // namespace tpuclient
