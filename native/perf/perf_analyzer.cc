// Driver orchestration (parity:
// /root/reference/src/c++/perf_analyzer/perf_analyzer.cc:56-69 —
// create backend factory -> parse model -> build data loader/manager ->
// choose load manager -> profile -> report/export) plus main() with
// SIGINT-initiated graceful drain (parity: perf_analyzer.cc:40-53).
#include <netinet/in.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>

#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "../library/grpc_client.h"
#include "command_line_parser.h"
#include "inference_profiler.h"
#include "metrics_manager.h"
#include "mpi_utils.h"
#include "report_writer.h"

namespace tpuclient {
namespace perf {

namespace {

volatile std::sig_atomic_t g_early_exit = 0;

void SignalHandler(int) { g_early_exit = 1; }

Error ApplyShapeOverrides(
    const std::vector<std::string>& overrides, ParsedModel* model) {
  for (const std::string& override_text : overrides) {
    size_t colon = override_text.find(':');
    if (colon == std::string::npos) {
      return Error("bad --shape (want name:d1,d2): " + override_text);
    }
    std::string name = override_text.substr(0, colon);
    std::string dims = override_text.substr(colon + 1);
    // name:DTYPE:d1,d2 CREATES the tensor — service kinds with no
    // metadata surface (tfserving gRPC) declare inputs this way.
    std::string datatype;
    size_t second = dims.find(':');
    if (second != std::string::npos) {
      datatype = dims.substr(0, second);
      dims = dims.substr(second + 1);
    }
    ModelTensor* target = nullptr;
    for (auto& t : model->inputs) {
      if (t.name == name) target = &t;
    }
    if (target == nullptr) {
      if (datatype.empty()) {
        return Error("--shape names unknown input '" + name +
                     "' (declare new tensors as name:DTYPE:d1,d2)");
      }
      model->inputs.emplace_back();
      target = &model->inputs.back();
      target->name = name;
    }
    if (!datatype.empty()) target->datatype = datatype;
    target->shape.clear();
    size_t pos = 0;
    while (pos < dims.size()) {
      size_t comma = dims.find(',', pos);
      target->shape.push_back(
          atoll(dims.substr(pos, comma - pos).c_str()));
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
  }
  return Error::Success;
}

}  // namespace

int RunRank(PerfAnalyzerParameters& params) {
  BackendConfig backend_config;
  if (params.service_kind == "torchserve") {
    backend_config.kind = BackendKind::TORCHSERVE;
  } else if (params.service_kind == "tfserving") {
    backend_config.kind = BackendKind::TFSERVING;
    // gRPC PredictionService is the native protocol; -i http selects
    // the REST predict API.
    backend_config.tfserving_grpc = params.protocol != "http";
  } else if (params.service_kind == "openai") {
    backend_config.kind = BackendKind::OPENAI;
    backend_config.openai_endpoint = params.endpoint;
  } else if (params.service_kind == "in_process") {
    // Embedded server core (triton_c_api analogue): no server
    // process, no RPC — embed.init warms the target model.
    backend_config.kind = BackendKind::IN_PROCESS;
    backend_config.inprocess_models = params.model_name;
  } else {
    backend_config.kind = params.protocol == "http"
                              ? BackendKind::TRITON_HTTP
                              : BackendKind::TRITON_GRPC;
  }
  backend_config.url = params.url;
  backend_config.verbose = params.verbose;
  backend_config.http_json_input = params.input_tensor_format == "json";
  backend_config.http_json_output = params.output_tensor_format == "json";
  if ((backend_config.http_json_input || backend_config.http_json_output) &&
      backend_config.kind != BackendKind::TRITON_HTTP) {
    fprintf(stderr,
            "warning: --input/--output-tensor-format json applies only "
            "to the HTTP protocol; ignored here\n");
  }
  backend_config.model_signature_name = params.model_signature_name;
  if (params.grpc_compression_algorithm != "none") {
    backend_config.grpc_compression = params.grpc_compression_algorithm;
  }
  if (params.ssl_grpc_use_ssl) {
    // The from-scratch gRPC transport is cleartext HTTP/2; TLS rides
    // the HTTP client only (tls.h). Fail loudly, never silently.
    fprintf(stderr,
            "error: --ssl-grpc-use-ssl is not supported by this build's "
            "gRPC transport (HTTPS is available with -i http)\n");
    return 1;
  }
  if (params.ssl_https_any) {
    backend_config.https = true;
    backend_config.https_ssl.root_certificates =
        params.ssl_https_ca_certificates_file;
    backend_config.https_ssl.certificate_chain =
        params.ssl_https_client_certificate_file;
    backend_config.https_ssl.private_key = params.ssl_https_private_key_file;
    backend_config.https_ssl.insecure_skip_verify =
        !params.ssl_https_verify_peer || !params.ssl_https_verify_host;
  }
  ClientBackendFactory factory(backend_config);

  std::unique_ptr<ClientBackend> setup_backend;
  Error err = factory.Create(&setup_backend);
  if (!err.IsOk()) {
    fprintf(stderr, "error: %s\n", err.Message().c_str());
    return 1;
  }

  ParsedModel model;
  err = ModelParser::Parse(
      setup_backend.get(), params.model_name, params.model_version,
      params.batch_size, &model, params.bls_composing_models);
  if (!err.IsOk()) {
    fprintf(stderr, "error: %s\n", err.Message().c_str());
    return 1;
  }
  err = ApplyShapeOverrides(params.shape_overrides, &model);
  if (!err.IsOk()) {
    fprintf(stderr, "error: %s\n", err.Message().c_str());
    return 1;
  }

  DataLoader loader(&model);
  struct stat input_stat;
  if (params.input_data == "random" || params.input_data == "zero") {
    err = loader.GenerateData(
        params.input_data == "zero", params.string_length,
        params.string_data);
  } else if (
      stat(params.input_data.c_str(), &input_stat) == 0 &&
      S_ISDIR(input_stat.st_mode)) {
    err = loader.ReadDataFromDir(params.input_data);
  } else {
    err = loader.ReadDataFromJson(params.input_data);
  }
  if (!err.IsOk()) {
    fprintf(stderr, "error: %s\n", err.Message().c_str());
    return 1;
  }

  SharedMemoryType shm_type = SharedMemoryType::NONE;
  if (params.shared_memory == "system") shm_type = SharedMemoryType::SYSTEM;
  if (params.shared_memory == "tpu") shm_type = SharedMemoryType::TPU;
  std::string arena_url = params.tpu_arena_url;
  if (shm_type == SharedMemoryType::TPU && arena_url.empty()) {
    arena_url = params.url;  // arena co-hosted with the gRPC endpoint
  }
  InferDataManager data_manager(
      &model, &loader, shm_type, params.output_shm_size, arena_url,
      params.batch_size);

  if (model.response_cache_enabled) {
    fprintf(stderr,
            "note: model has response caching enabled; server-side "
            "queue/compute breakdowns exclude cache hits\n");
  } else if (model.composing_cache_enabled) {
    // Composing-model cache hits short-circuit the ensemble subgraph
    // device-side and are counted in tpu_ensemble_cache_hits_total.
    fprintf(stderr,
            "note: a composing model has response caching enabled; "
            "cache hits short-circuit the ensemble subgraph (see "
            "tpu_ensemble_cache_hits_total)\n");
  }

  std::unique_ptr<SequenceManager> sequence_manager;
  if (model.scheduler_type == SchedulerType::SEQUENCE ||
      model.composing_sequential ||  // a composing model needs sequences
      !params.sequence_id_range.empty()) {
    uint64_t start_id = 1, id_range = 1ull << 31;
    if (!params.sequence_id_range.empty()) {
      size_t colon = params.sequence_id_range.find(':');
      start_id = strtoull(
          params.sequence_id_range.substr(0, colon).c_str(), nullptr, 10);
      if (colon != std::string::npos) {
        uint64_t end_id = strtoull(
            params.sequence_id_range.substr(colon + 1).c_str(), nullptr, 10);
        id_range = end_id > start_id ? end_id - start_id : 1;
      }
    }
    sequence_manager = std::make_unique<SequenceManager>(
        start_id, id_range, params.sequence_length,
        params.sequence_length_variation / 100.0);
  }

  MeasurementConfig config;
  config.measurement_interval_ms = params.measurement_interval_ms;
  config.count_windows = params.measurement_mode == "count_windows";
  config.measurement_request_count = params.measurement_request_count;
  // REST/chat service kinds send one logical inference per request
  // regardless of -b (their payloads are not batched).
  config.batch_size = (params.service_kind == "triton" ||
                       params.service_kind == "in_process")
                          ? static_cast<size_t>(params.batch_size)
                          : 1;
  config.max_trials = params.max_trials;
  if (params.request_count > 0) {
    // --request-count: measure exactly N requests, one window (a
    // single-trial run is by design, not an unstable measurement).
    // Must come AFTER the generic max_trials assignment — a default
    // max_trials overwriting this 1 turns the fixed-count run into a
    // stability-ruled multi-window run that can report "did not
    // stabilize" under load.
    config.count_windows = true;
    config.measurement_request_count = params.request_count;
    config.max_trials = 1;
  }
  config.stability_threshold = params.stability_percentage / 100.0;
  config.latency_threshold_ms = params.latency_threshold_ms;
  config.percentile = params.percentile;
  config.log_frequency = params.log_frequency;

  LoadManager::Options manager_options;
  manager_options.async_mode = params.async_mode;
  manager_options.streaming = params.streaming;
  manager_options.max_threads = params.max_threads;
  manager_options.num_of_sequences = params.num_of_sequences;
  manager_options.serial_sequences = params.serial_sequences;
  manager_options.request_parameters = params.request_parameters;

  // Client-driven trace configuration: forward to the server's trace
  // settings before load starts (reference --trace-level/rate/count).
  if (!params.trace_level.empty() && params.service_kind == "triton" &&
      params.protocol != "http") {
    std::unique_ptr<InferenceServerGrpcClient> trace_client;
    Error trace_err =
        InferenceServerGrpcClient::Create(&trace_client, params.url);
    if (trace_err.IsOk()) {
      std::map<std::string, std::vector<std::string>> settings;
      settings["trace_level"] = {params.trace_level};
      if (params.trace_rate > 0) {
        settings["trace_rate"] = {std::to_string(params.trace_rate)};
      }
      if (params.trace_count >= 0) {
        settings["trace_count"] = {std::to_string(params.trace_count)};
      }
      inference::TraceSettingResponse trace_response;
      trace_err = trace_client->UpdateTraceSettings(
          &trace_response, params.model_name, settings);
    }
    if (!trace_err.IsOk()) {
      fprintf(stderr, "warning: trace settings not applied: %s\n",
              trace_err.Message().c_str());
    }
  }

  std::unique_ptr<MetricsManager> metrics;
  if (params.collect_metrics) {
    std::string metrics_url = params.metrics_url;
    if (metrics_url.empty()) {
      // Default: port 8000 on the inference URL's host.
      std::string host = params.url;
      size_t scheme = host.find("://");
      if (scheme != std::string::npos) host = host.substr(scheme + 3);
      size_t colon = host.rfind(':');
      if (colon != std::string::npos) host = host.substr(0, colon);
      metrics_url = host + ":8000/metrics";
    }
    metrics = std::make_unique<MetricsManager>(
        metrics_url, params.metrics_interval_ms);
    Error reach_err = metrics->CheckReachable();
    if (!reach_err.IsOk()) {
      fprintf(stderr,
              "warning: metrics endpoint %s unreachable (%s); continuing "
              "without server metrics\n",
              metrics_url.c_str(), reach_err.Message().c_str());
      metrics.reset();
    }
  }

  std::vector<PerfStatus> results;
  LoadMode mode = LoadMode::CONCURRENCY;
  std::unique_ptr<LoadManager> manager;

  // Multi-client scale-out (reference --enable-mpi): ranks start and
  // stop together, and the profiler merges the stability decision so
  // every rank measures the same window.
  MPIDriver mpi(params.enable_mpi);

  auto profile = [&](LoadManager* m) -> Error {
    InferenceProfiler profiler(
        m, config, setup_backend.get(), model.name, params.verbose,
        metrics.get(), model.composing_models);
    if (params.enable_mpi && mpi.IsMPIRun()) profiler.set_mpi(&mpi);
    // Rank-merged: a rank whose Init fails must not leave peers
    // blocked in the profiler's collectives.
    Error init_err = profiler.RankCheck(m->Init());
    if (!init_err.IsOk()) return init_err;
    if (params.has_request_rate_range) {
      mode = LoadMode::REQUEST_RATE;
      return profiler.ProfileRequestRateRange(
          static_cast<RequestRateManager*>(m), params.rate_start,
          params.rate_end, params.rate_step, &results);
    }
    if (!params.request_intervals_file.empty()) {
      mode = LoadMode::REQUEST_RATE;
      auto* custom = static_cast<CustomLoadManager*>(m);
      Error sched_err = profiler.RankCheck(
          custom->StartSchedule(params.request_intervals_file));
      if (!sched_err.IsOk()) return sched_err;
      PerfStatus status;
      Error prof_err = profiler.ProfileSingleLevel(&status);
      if (!prof_err.IsOk()) return prof_err;
      results.push_back(std::move(status));
      custom->Stop();
      return Error::Success;
    }
    if (params.has_periodic_range) {
      auto* periodic = static_cast<PeriodicConcurrencyManager*>(m);
      PeriodicConcurrencyManager::RampConfig ramp;
      ramp.start = params.periodic_start;
      ramp.end = params.periodic_end;
      ramp.step = params.periodic_step;
      ramp.request_period = params.request_period;
      Error ramp_err = profiler.RankCheck(periodic->RunRamp(ramp));
      if (!ramp_err.IsOk()) return ramp_err;
      PerfStatus status;
      Error prof_err = profiler.ProfileSingleLevel(&status);
      if (!prof_err.IsOk()) return prof_err;
      status.concurrency = params.periodic_end;
      results.push_back(std::move(status));
      periodic->Stop();
      return Error::Success;
    }
    if (params.binary_search) {
      return profiler.ProfileConcurrencyBinarySearch(
          static_cast<ConcurrencyManager*>(m), params.concurrency_start,
          params.concurrency_end, &results);
    }
    return profiler.ProfileConcurrencyRange(
        static_cast<ConcurrencyManager*>(m), params.concurrency_start,
        params.concurrency_end, params.concurrency_step, &results);
  };

  if (params.has_request_rate_range ||
      !params.request_intervals_file.empty()) {
    RequestRateManager::Distribution dist =
        params.request_distribution == "poisson"
            ? RequestRateManager::Distribution::POISSON
            : RequestRateManager::Distribution::CONSTANT;
    if (!params.request_intervals_file.empty()) {
      manager = std::make_unique<CustomLoadManager>(
          &factory, &model, &loader, &data_manager, manager_options, dist,
          sequence_manager.get());
    } else {
      manager = std::make_unique<RequestRateManager>(
          &factory, &model, &loader, &data_manager, manager_options, dist,
          sequence_manager.get());
    }
  } else if (params.has_periodic_range) {
    manager = std::make_unique<PeriodicConcurrencyManager>(
        &factory, &model, &loader, &data_manager, manager_options,
        sequence_manager.get());
  } else {
    manager = std::make_unique<ConcurrencyManager>(
        &factory, &model, &loader, &data_manager, manager_options,
        sequence_manager.get());
  }

  if (params.enable_mpi) {
    mpi.MPIInit();
    if (getenv("TPUCLIENT_RANKS_FORKED") != nullptr && !mpi.IsMPIRun()) {
      // This world was forked by our own --ranks: running on solo
      // would silently produce N uncoordinated profiles.
      fprintf(stderr,
              "error: this rank failed to join the --ranks world\n");
      return 1;
    }
    // Per-rank output files: ranks run the same command line, so a
    // shared -f / --profile-export-file path would be clobbered
    // concurrently. Rank 0 keeps the given name.
    const int rank = mpi.MPICommRankWorld();
    if (mpi.MPICommSizeWorld() > 1 && rank > 0) {
      const std::string suffix = ".rank" + std::to_string(rank);
      if (!params.latency_report_file.empty()) {
        params.latency_report_file += suffix;
      }
      if (!params.profile_export_file.empty()) {
        params.profile_export_file += suffix;
      }
    }
    mpi.MPIBarrierWorld();
  }

  err = profile(manager.get());
  manager->Cleanup();
  if (params.enable_mpi) {
    mpi.MPIBarrierWorld();
    mpi.MPIFinalize();
  }
  if (!err.IsOk()) {
    fprintf(stderr, "perf failed: %s\n", err.Message().c_str());
    return 1;
  }

  PrintReport(results, mode, params.percentile);
  if (!params.latency_report_file.empty()) {
    err = WriteCsv(params.latency_report_file, results, mode,
                   params.verbose_csv);
    if (!err.IsOk()) fprintf(stderr, "warning: %s\n", err.Message().c_str());
  }
  if (!params.profile_export_file.empty()) {
    err = ExportProfile(
        params.profile_export_file, results, model.name, "triton",
        params.url, mode);
    if (!err.IsOk()) fprintf(stderr, "warning: %s\n", err.Message().c_str());
  }
  return 0;
}

int Run(int argc, char** argv) {
  PerfAnalyzerParameters params;
  Error err = CLParser::Parse(argc, argv, &params);
  if (!err.IsOk()) {
    fprintf(stderr, "error: %s\n", err.Message().c_str());
    CLParser::Usage(argv[0]);
    return 1;
  }

  std::signal(SIGINT, SignalHandler);

  // --ranks N: fork N-1 more local ranks over the builtin TCP
  // coordinator (the launcher-free `mpirun -n N`). Forked BEFORE any
  // backend/socket state exists; each child runs RunRank as its own
  // rank. A complete TPUCLIENT_* contract in the environment means an
  // external launcher already placed this process — don't re-fork.
  std::vector<pid_t> rank_children;
  // Defer to an external launcher only when the FULL coordinator
  // contract is present; a stale partial contract (e.g. a leftover
  // TPUCLIENT_RANK export) is cleared so --ranks works as asked.
  const bool external_contract = getenv("TPUCLIENT_COORDINATOR") != nullptr &&
                                 getenv("TPUCLIENT_WORLD_SIZE") != nullptr &&
                                 getenv("TPUCLIENT_RANK") != nullptr;
  if (params.ranks > 1 && !external_contract) {
    if (getenv("TPUCLIENT_COORDINATOR") != nullptr ||
        getenv("TPUCLIENT_WORLD_SIZE") != nullptr ||
        getenv("TPUCLIENT_RANK") != nullptr) {
      fprintf(stderr,
              "warning: ignoring a partial TPUCLIENT_* coordinator "
              "contract; --ranks %d forks its own world\n",
              params.ranks);
      unsetenv("TPUCLIENT_COORDINATOR");
      unsetenv("TPUCLIENT_WORLD_SIZE");
      unsetenv("TPUCLIENT_RANK");
    }
    int probe = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = 0;
    socklen_t addr_len = sizeof(addr);
    if (probe < 0 ||
        bind(probe, reinterpret_cast<struct sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
        getsockname(probe, reinterpret_cast<struct sockaddr*>(&addr),
                    &addr_len) != 0) {
      fprintf(stderr, "error: --ranks could not reserve a port\n");
      if (probe >= 0) close(probe);
      return 1;
    }
    close(probe);
    char coord[64];
    snprintf(coord, sizeof(coord), "127.0.0.1:%d", ntohs(addr.sin_port));
    setenv("TPUCLIENT_COORDINATOR", coord, 1);
    char world[16];
    snprintf(world, sizeof(world), "%d", params.ranks);
    setenv("TPUCLIENT_WORLD_SIZE", world, 1);
    // Marks a world WE forked: failing to join it is then an error,
    // not a silent degrade — N uncoordinated solo profiles exiting 0
    // would look like a successful --ranks run.
    setenv("TPUCLIENT_RANKS_FORKED", "1", 1);
    bool is_child = false;
    for (int r = 1; r < params.ranks; ++r) {
      const pid_t pid = fork();
      if (pid < 0) {
        fprintf(stderr, "error: --ranks fork failed\n");
        for (pid_t child : rank_children) {
          kill(child, SIGTERM);
          waitpid(child, nullptr, 0);
        }
        return 1;
      }
      if (pid == 0) {
        char rank_str[16];
        snprintf(rank_str, sizeof(rank_str), "%d", r);
        setenv("TPUCLIENT_RANK", rank_str, 1);
        rank_children.clear();
        is_child = true;
        break;
      }
      rank_children.push_back(pid);
    }
    if (!is_child) setenv("TPUCLIENT_RANK", "0", 1);
  }

  int rc = RunRank(params);
  for (pid_t child : rank_children) {
    int status = 0;
    if (waitpid(child, &status, 0) != child || !WIFEXITED(status) ||
        WEXITSTATUS(status) != 0) {
      fprintf(stderr, "warning: a forked rank exited abnormally\n");
      rc = rc == 0 ? 1 : rc;
    }
  }
  return rc;
}

}  // namespace perf
}  // namespace tpuclient

int main(int argc, char** argv) {
  return tpuclient::perf::Run(argc, argv);
}
