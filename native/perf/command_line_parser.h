// CLI for the native perf_analyzer (parity:
// /root/reference/src/c++/perf_analyzer/command_line_parser.h:45-176 —
// getopt_long into a plain parameters struct; same principal flags
// and defaults, with the CUDA shm choice replaced by "tpu").
#pragma once

#include <string>
#include <vector>

#include "../library/common.h"
#include "load_manager.h"

namespace tpuclient {
namespace perf {

struct PerfAnalyzerParameters {
  std::string model_name;
  std::string model_version;
  std::string url = "localhost:8001";
  std::string protocol = "grpc";  // grpc | http
  std::string service_kind = "triton";  // triton | openai
  std::string endpoint = "v1/chat/completions";  // openai request path
  int64_t batch_size = 1;
  bool verbose = false;
  bool async_mode = true;
  bool streaming = false;
  size_t max_threads = 16;

  // Load modes (mutually exclusive; concurrency default).
  bool has_concurrency_range = false;
  size_t concurrency_start = 1, concurrency_end = 1, concurrency_step = 1;
  bool has_request_rate_range = false;
  double rate_start = 0, rate_end = 0, rate_step = 1.0;
  std::string request_intervals_file;
  bool has_periodic_range = false;
  size_t periodic_start = 1, periodic_end = 8, periodic_step = 1;
  size_t request_period = 10;
  std::string request_distribution = "constant";  // constant | poisson

  // Measurement.
  uint64_t measurement_interval_ms = 5000;
  std::string measurement_mode = "time_windows";
  size_t measurement_request_count = 50;
  size_t max_trials = 10;
  double stability_percentage = 10.0;
  double latency_threshold_ms = 0.0;
  int percentile = 0;

  // Shared memory.
  std::string shared_memory = "none";  // none | system | tpu
  size_t output_shm_size = 102400;
  std::string tpu_arena_url;

  // Input data.
  std::string input_data = "random";  // random | zero | file path
  size_t string_length = 16;
  std::string string_data;
  // name:d1,d2 shape overrides.
  std::vector<std::string> shape_overrides;

  // Sequences.
  size_t sequence_length = 20;
  double sequence_length_variation = 20.0;
  std::string sequence_id_range;  // start[:end]

  // Output files.
  std::string latency_report_file;
  std::string profile_export_file;

  // Server metrics scraping.
  bool collect_metrics = false;
  std::string metrics_url;  // defaults to http://<url host>:8000/metrics
  uint64_t metrics_interval_ms = 1000;
};

class CLParser {
 public:
  // Returns an error (with a usage hint) on bad flags.
  static Error Parse(int argc, char** argv, PerfAnalyzerParameters* params);
  static void Usage(const char* program);
};

}  // namespace perf
}  // namespace tpuclient
