// CLI for the native perf_analyzer (parity:
// /root/reference/src/c++/perf_analyzer/command_line_parser.h:45-176 —
// getopt_long into a plain parameters struct; same principal flags
// and defaults, with the CUDA shm choice replaced by "tpu").
#pragma once

#include <string>
#include <vector>

#include "../library/common.h"
#include "load_manager.h"

namespace tpuclient {
namespace perf {

struct PerfAnalyzerParameters {
  std::string model_name;
  std::string model_version;
  std::string url = "localhost:8001";
  std::string protocol = "grpc";  // grpc | http
  std::string service_kind = "triton";  // triton | openai
  std::string endpoint = "v1/chat/completions";  // openai request path
  int64_t batch_size = 1;
  bool verbose = false;
  bool async_mode = true;
  bool streaming = false;
  size_t max_threads = 16;

  // Load modes (mutually exclusive; concurrency default).
  bool has_concurrency_range = false;
  size_t concurrency_start = 1, concurrency_end = 1, concurrency_step = 1;
  // Binary-search mode: bisect [start, end] for the highest
  // concurrency meeting the latency threshold (reference
  // inference_profiler.h:280-325).
  bool binary_search = false;
  bool has_request_rate_range = false;
  double rate_start = 0, rate_end = 0, rate_step = 1.0;
  std::string request_intervals_file;
  bool has_periodic_range = false;
  size_t periodic_start = 1, periodic_end = 8, periodic_step = 1;
  size_t request_period = 10;
  std::string request_distribution = "constant";  // constant | poisson

  // Measurement.
  uint64_t measurement_interval_ms = 5000;
  std::string measurement_mode = "time_windows";
  size_t measurement_request_count = 50;
  size_t max_trials = 10;
  double stability_percentage = 10.0;
  double latency_threshold_ms = 0.0;
  int percentile = 0;
  // Exactly N requests then stop (0 = window-based), reference
  // --request-count.
  size_t request_count = 0;

  // Shared memory.
  std::string shared_memory = "none";  // none | system | tpu
  size_t output_shm_size = 102400;
  std::string tpu_arena_url;

  // Input data.
  std::string input_data = "random";  // random | zero | file path
  size_t string_length = 16;
  std::string string_data;
  // name:d1,d2 shape overrides.
  std::vector<std::string> shape_overrides;

  // Sequences.
  size_t sequence_length = 20;
  double sequence_length_variation = 20.0;
  std::string sequence_id_range;  // start[:end]
  // Concurrent sequence count for sequence models (reference
  // --num-of-sequences) and strict serialization per sequence id.
  size_t num_of_sequences = 4;
  bool serial_sequences = false;

  // Output files.
  std::string latency_report_file;
  std::string profile_export_file;
  bool verbose_csv = false;

  // Server metrics scraping.
  bool collect_metrics = false;
  std::string metrics_url;  // defaults to http://<url host>:8000/metrics
  uint64_t metrics_interval_ms = 1000;

  // Composing models of a BLS/pipeline top model whose per-window
  // stats should be paired (reference --bls-composing-models).
  std::vector<std::string> bls_composing_models;

  // TF-Serving signature (reference --model-signature-name).
  std::string model_signature_name = "serving_default";

  // TLS (dlopen'd OpenSSL; both protocols).
  bool ssl_grpc_use_ssl = false;
  std::string ssl_grpc_root_certifications_file;
  std::string ssl_grpc_private_key_file;
  std::string ssl_grpc_certificate_chain_file;
  std::string ssl_https_ca_certificates_file;
  std::string ssl_https_client_certificate_file;
  std::string ssl_https_private_key_file;
  bool ssl_https_verify_peer = true;
  bool ssl_https_verify_host = true;
  // True when ANY ssl-https flag appeared (enables HTTPS even with
  // only verify flags given).
  bool ssl_https_any = false;

  // Per-request custom parameter overrides, "name:value:type"
  // (reference --request-parameter).
  std::vector<std::string> request_parameters;

  // Client-side trace knobs forwarded to the server's trace settings
  // (reference --trace-level/--trace-rate/--trace-count).
  std::string trace_level;
  uint64_t trace_rate = 0;
  int64_t trace_count = -1;

  // MPI multi-client rendezvous (reference --enable-mpi).
  bool enable_mpi = false;
  // --ranks N: fork N local analyzer ranks over the builtin TCP
  // coordinator (the launcher-free equivalent of `mpirun -n N`).
  int ranks = 1;
  // HTTP tensor wire format, binary|json (reference
  // --input-tensor-format / --output-tensor-format).
  std::string input_tensor_format = "binary";
  std::string output_tensor_format = "binary";

  // gRPC message compression (reference --grpc-compression-algorithm).
  std::string grpc_compression_algorithm = "none";

  // Progress log every N completed requests in verbose mode
  // (reference --log-frequency).
  size_t log_frequency = 0;
};

class CLParser {
 public:
  // Returns an error (with a usage hint) on bad flags.
  static Error Parse(int argc, char** argv, PerfAnalyzerParameters* params);
  static void Usage(const char* program);
};

}  // namespace perf
}  // namespace tpuclient
