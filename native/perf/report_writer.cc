#include "report_writer.h"

#include <cstdio>
#include <fstream>

namespace tpuclient {
namespace perf {

namespace {

double Pct(const PerfStatus& status, int p) {
  auto it = status.latency_percentiles.find(p);
  return it != status.latency_percentiles.end() ? it->second : 0.0;
}

}  // namespace

void PrintReport(
    const std::vector<PerfStatus>& results, LoadMode mode, int percentile) {
  for (const auto& status : results) {
    if (mode == LoadMode::CONCURRENCY) {
      printf("Concurrency: %zu, throughput: %.2f infer/sec, avg latency "
             "%.0f usec\n",
             status.concurrency, status.throughput, status.avg_latency_us);
    } else {
      printf("Request rate: %.1f, throughput: %.2f infer/sec, avg latency "
             "%.0f usec\n",
             status.request_rate, status.throughput, status.avg_latency_us);
    }
    printf("    latency percentiles (usec):");
    for (const auto& kv : status.latency_percentiles) {
      printf(" p%d %.0f", kv.first, kv.second);
    }
    printf("\n");
    if (status.overhead_pct > 50.0) {
      // Reference behavior: warn when the harness itself is the
      // bottleneck (workers busy most of the window).
      printf("    WARNING: perf client overhead %.0f%% of the window — "
             "results may be client-bound (raise --max-threads)\n",
             status.overhead_pct);
    }
    if (status.delayed_count > 0) {
      printf("    delayed requests: %zu\n", status.delayed_count);
    }
    if (status.error_count > 0) {
      printf("    errors: %zu\n", status.error_count);
      if (!status.sample_error.empty()) {
        printf("    first error: %s\n", status.sample_error.c_str());
      }
    }
    // Per-window server-side deltas (top model + ensemble composing
    // models), µs per inference — reference column set.
    if (status.server_stats.IsObject() &&
        status.server_stats.Has("model_stats")) {
      for (const auto& entry : status.server_stats["model_stats"].AsArray()) {
        if (!entry.IsObject() || !entry.Has("inference_count")) continue;
        uint64_t count = entry["inference_count"].AsUint();
        if (count == 0) continue;
        const json::Value& stats = entry["inference_stats"];
        auto us = [&](const char* section) -> double {
          if (!stats.IsObject() || !stats.Has(section)) return 0.0;
          return stats[section]["ns"].AsDouble() / count / 1000.0;
        };
        printf(
            "    server %s (this window): %llu inferences, %llu "
            "executions, queue %.0f us, compute in/infer/out "
            "%.0f/%.0f/%.0f us\n",
            entry.Has("name") ? entry["name"].AsString().c_str() : "?",
            (unsigned long long)count,
            (unsigned long long)(entry.Has("execution_count")
                                     ? entry["execution_count"].AsUint()
                                     : 0),
            us("queue"), us("compute_input"), us("compute_infer"),
            us("compute_output"));
      }
    }
    auto hbm = status.tpu_metrics.find("tpu_hbm_used_bytes");
    auto util = status.tpu_metrics.find("tpu_hbm_utilization");
    if (hbm != status.tpu_metrics.end() ||
        util != status.tpu_metrics.end()) {
      printf("    server TPU:");
      if (hbm != status.tpu_metrics.end()) {
        printf(" HBM used avg %.1f MiB / max %.1f MiB",
               hbm->second.first / 1048576.0,
               hbm->second.second / 1048576.0);
      }
      if (util != status.tpu_metrics.end()) {
        printf(", HBM util avg %.1f%%", util->second.first * 100.0);
      }
      printf("\n");
    }
    if (!status.on_target) {
      printf("    WARNING: measurement did not stabilize\n");
    }
  }
}

Error WriteCsv(
    const std::string& path, const std::vector<PerfStatus>& results,
    LoadMode mode, bool verbose_csv) {
  std::ofstream out(path);
  if (!out) return Error("cannot write CSV file '" + path + "'");
  out << (mode == LoadMode::CONCURRENCY ? "Concurrency" : "Request Rate")
      << ",Inferences/Second,p50 latency,p90 latency,p95 latency,"
         "p99 latency,Avg latency,Std latency,Completed,Delayed,Errors,"
         "Avg HBM Used (MiB),Max HBM Used (MiB),Avg HBM Utilization";
  if (verbose_csv) {
    // Server-side per-window breakdown columns (reference
    // --verbose-csv adds the queue/compute column set).
    out << ",Server Queue us,Server Compute Input us,"
           "Server Compute Infer us,Server Compute Output us,"
           "Server Inferences";
  }
  out << "\n";
  char line[512];
  for (const auto& status : results) {
    if (mode == LoadMode::CONCURRENCY) {
      snprintf(line, sizeof(line), "%zu,", status.concurrency);
    } else {
      snprintf(line, sizeof(line), "%.2f,", status.request_rate);
    }
    out << line;
    snprintf(
        line, sizeof(line),
        "%.2f,%.1f,%.1f,%.1f,%.1f,%.1f,%.1f,%zu,%zu,%zu",
        status.throughput, Pct(status, 50), Pct(status, 90), Pct(status, 95),
        Pct(status, 99), status.avg_latency_us, status.std_latency_us,
        status.completed_count, status.delayed_count, status.error_count);
    out << line;
    auto hbm = status.tpu_metrics.find("tpu_hbm_used_bytes");
    auto util = status.tpu_metrics.find("tpu_hbm_utilization");
    if (hbm != status.tpu_metrics.end()) {
      snprintf(line, sizeof(line), ",%.2f,%.2f",
               hbm->second.first / 1048576.0,
               hbm->second.second / 1048576.0);
      out << line;
    } else {
      out << ",,";
    }
    if (util != status.tpu_metrics.end()) {
      snprintf(line, sizeof(line), ",%.4f", util->second.first);
      out << line;
    } else {
      out << ",";
    }
    if (verbose_csv) {
      uint64_t count = 0;
      double queue_us = 0, in_us = 0, infer_us = 0, out_us = 0;
      if (status.server_stats.IsObject() &&
          status.server_stats.Has("model_stats")) {
        const auto& entries = status.server_stats["model_stats"];
        if (entries.IsArray() && !entries.AsArray().empty()) {
          const auto& top = entries.AsArray().front();
          if (top.IsObject() && top.Has("inference_count")) {
            count = top["inference_count"].AsUint();
            const auto& stats = top["inference_stats"];
            auto us = [&](const char* key) -> double {
              if (!stats.IsObject() || !stats.Has(key) || count == 0) {
                return 0.0;
              }
              return stats[key]["ns"].AsDouble() / count / 1000.0;
            };
            queue_us = us("queue");
            in_us = us("compute_input");
            infer_us = us("compute_infer");
            out_us = us("compute_output");
          }
        }
      }
      snprintf(line, sizeof(line), ",%.1f,%.1f,%.1f,%.1f,%llu", queue_us,
               in_us, infer_us, out_us, (unsigned long long)count);
      out << line;
    }
    out << "\n";
  }
  return Error::Success;
}

Error ExportProfile(
    const std::string& path, const std::vector<PerfStatus>& results,
    const std::string& model_name, const std::string& service_kind,
    const std::string& endpoint, LoadMode mode) {
  json::Array experiments;
  for (const auto& status : results) {
    json::Object experiment;
    json::Object meta;
    meta["mode"] = json::Value(std::string(
        mode == LoadMode::CONCURRENCY ? "concurrency" : "request_rate"));
    if (mode == LoadMode::CONCURRENCY) {
      meta["value"] = json::Value(static_cast<uint64_t>(status.concurrency));
    } else {
      meta["value"] = json::Value(status.request_rate);
    }
    experiment["experiment"] = json::Value(std::move(meta));
    json::Array requests;
    for (const auto& record : status.records) {
      if (!record.valid()) continue;
      json::Object req;
      req["timestamp"] = json::Value(record.start_ns);
      json::Array responses;
      for (uint64_t ts : record.end_ns) responses.push_back(json::Value(ts));
      req["response_timestamps"] = json::Value(std::move(responses));
      requests.push_back(json::Value(std::move(req)));
    }
    experiment["requests"] = json::Value(std::move(requests));
    json::Array window;
    window.push_back(json::Value(status.window_start_ns));
    window.push_back(json::Value(status.window_end_ns));
    experiment["window_boundaries"] = json::Value(std::move(window));
    experiments.push_back(json::Value(std::move(experiment)));
  }
  json::Object doc;
  doc["version"] = json::Value(std::string("0.1"));
  doc["service_kind"] = json::Value(service_kind);
  doc["endpoint"] = json::Value(endpoint);
  doc["model"] = json::Value(model_name);
  doc["experiments"] = json::Value(std::move(experiments));

  std::ofstream out(path);
  if (!out) return Error("cannot write profile export '" + path + "'");
  out << json::Value(std::move(doc)).Serialize();
  return Error::Success;
}

}  // namespace perf
}  // namespace tpuclient
