#include "load_manager.h"

#include <algorithm>
#include <chrono>
#include <cstring>
#include <fstream>

#include "../library/shm_utils.h"

namespace tpuclient {
namespace perf {

uint64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

//==============================================================================
// FifoCtxIdTracker

void FifoCtxIdTracker::Reset(size_t count) {
  std::lock_guard<std::mutex> lock(mutex_);
  free_.clear();
  for (size_t i = 0; i < count; ++i) free_.push_back(static_cast<int>(i));
  cv_.notify_all();
}

int FifoCtxIdTracker::Get(int timeout_ms) {
  std::unique_lock<std::mutex> lock(mutex_);
  if (!cv_.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                    [this] { return !free_.empty(); })) {
    return -1;
  }
  size_t index = PickIndex(free_.size());
  int id = free_[index];
  free_.erase(free_.begin() + index);
  return id;
}

void FifoCtxIdTracker::Release(int ctx_id) {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    free_.push_back(ctx_id);
  }
  cv_.notify_one();
}

size_t FifoCtxIdTracker::FreeCount() {
  std::lock_guard<std::mutex> lock(mutex_);
  return free_.size();
}

void ConcurrencyCtxIdTracker::Reset(size_t count) {
  std::lock_guard<std::mutex> lock(mutex_);
  free_.clear();
  for (size_t i = 0; i < count; ++i) free_.push_back(0);
  cv_.notify_all();
}

std::shared_ptr<FifoCtxIdTracker> MakeCtxIdTracker(
    bool sequences_active, bool prefer_random) {
  if (!sequences_active) return std::make_shared<ConcurrencyCtxIdTracker>();
  if (prefer_random) return std::make_shared<RandCtxIdTracker>();
  return std::make_shared<FifoCtxIdTracker>();
}

//==============================================================================
// SequenceManager

void SequenceManager::NextStep(
    Slot* slot, size_t stream_count, size_t steps_in_stream,
    InferOptions* options, size_t* stream, size_t* step) {
  std::lock_guard<std::mutex> lock(mutex_);
  if (!slot->active) {
    slot->sequence_id = start_id_ + (next_offset_++ % id_range_);
    std::uniform_real_distribution<double> dist(-variation_, variation_);
    double factor = 1.0 + dist(rng_);
    slot->remaining = std::max<size_t>(
        1, static_cast<size_t>(length_ * factor));
    slot->step = 0;
    slot->stream =
        stream_count > 1 ? (rng_() % stream_count) : 0;
    slot->active = true;
  }
  options->sequence_id = slot->sequence_id;
  options->sequence_start = (slot->step == 0);
  slot->remaining--;
  options->sequence_end = (slot->remaining == 0);
  *stream = slot->stream;
  *step = steps_in_stream > 0 ? slot->step % steps_in_stream : 0;
  slot->step++;
  if (options->sequence_end) slot->active = false;
}

//==============================================================================
// InferDataManager

InferDataManager::~InferDataManager() {
  for (auto& region : system_regions_) {
    if (region.addr != nullptr) UnmapSharedMemory(region.addr, region.byte_size);
    if (region.fd >= 0) CloseSharedMemory(region.fd);
    UnlinkSharedMemoryRegion(region.key);
  }
}

const std::string* InferDataManager::BatchedBytes(
    const ModelTensor& tensor, size_t stream, size_t step,
    const TensorData& data) {
  std::string key = tensor.name + "_" + std::to_string(stream) + "_" +
                    std::to_string(step);
  std::lock_guard<std::mutex> lock(cache_mutex_);
  auto it = batched_cache_.find(key);
  if (it != batched_cache_.end()) return &it->second;
  std::string batched;
  int64_t copies = CopiesFor(tensor);
  batched.reserve(data.bytes.size() * copies);
  for (int64_t i = 0; i < copies; ++i) batched.append(data.bytes);
  auto inserted = batched_cache_.emplace(key, std::move(batched));
  return &inserted.first->second;
}

Error InferDataManager::CreateInputRegion(
    ClientBackend* backend, const std::string& region,
    const ModelTensor& tensor, const TensorData& data) {
  int64_t copies = CopiesFor(tensor);
  size_t byte_size = std::max<size_t>(data.bytes.size() * copies, 1);
  if (shm_type_ == SharedMemoryType::SYSTEM) {
    SystemRegion sys;
    sys.name = region;
    sys.key = "/perf_" + region;
    sys.byte_size = byte_size;
    Error err = CreateSharedMemoryRegion(sys.key, byte_size, &sys.fd);
    if (!err.IsOk()) return err;
    err = MapSharedMemory(sys.fd, 0, byte_size, &sys.addr);
    if (!err.IsOk()) return err;
    char* dst = static_cast<char*>(sys.addr);
    for (int64_t i = 0; i < copies; ++i) {
      memcpy(dst + i * data.bytes.size(), data.bytes.data(),
             data.bytes.size());
    }
    err = backend->RegisterSystemSharedMemory(region, sys.key, byte_size);
    system_regions_.push_back(std::move(sys));
    return err;
  }
  // TPU: allocate in the server's HBM arena and write the batched
  // payload with dtype/shape so the arena stores a typed device array.
  TpuRegion tpu;
  tpu.name = region;
  tpu.byte_size = byte_size;
  Error err =
      arena_->CreateRegion(byte_size, 0, &tpu.raw_handle, &tpu.region_id);
  if (!err.IsOk()) return err;
  std::vector<int64_t> shape = data.shape;
  std::string payload;
  // Mirror BuildInputs' declared shape exactly (including batch 1):
  // the arena's zero-copy fast path requires the stored segment shape
  // to EQUAL the request's declared shape.
  if (model_->max_batch_size > 0 && !tensor.is_shape_tensor) {
    shape.insert(shape.begin(), copies);
  }
  payload.reserve(byte_size);
  for (int64_t i = 0; i < copies; ++i) payload.append(data.bytes);
  err = arena_->WriteRegion(tpu.region_id, 0, payload, data.datatype, shape);
  if (!err.IsOk()) return err;
  err = backend->RegisterTpuSharedMemory(region, tpu.raw_handle, 0, byte_size);
  tpu_regions_.push_back(std::move(tpu));
  return err;
}

Error InferDataManager::CreateOutputRegion(
    ClientBackend* backend, const std::string& region) {
  if (shm_type_ == SharedMemoryType::SYSTEM) {
    SystemRegion sys;
    sys.name = region;
    sys.key = "/perf_" + region;
    sys.byte_size = output_shm_size_;
    Error err = CreateSharedMemoryRegion(sys.key, output_shm_size_, &sys.fd);
    if (!err.IsOk()) return err;
    err = MapSharedMemory(sys.fd, 0, output_shm_size_, &sys.addr);
    if (!err.IsOk()) return err;
    err = backend->RegisterSystemSharedMemory(region, sys.key,
                                              output_shm_size_);
    system_regions_.push_back(std::move(sys));
    return err;
  }
  TpuRegion tpu;
  tpu.name = region;
  tpu.byte_size = output_shm_size_;
  Error err = arena_->CreateRegion(
      output_shm_size_, 0, &tpu.raw_handle, &tpu.region_id);
  if (!err.IsOk()) return err;
  err = backend->RegisterTpuSharedMemory(
      region, tpu.raw_handle, 0, output_shm_size_);
  tpu_regions_.push_back(std::move(tpu));
  return err;
}

Error InferDataManager::Init(ClientBackend* backend) {
  if (shm_type_ == SharedMemoryType::NONE) return Error::Success;
  if (shm_type_ == SharedMemoryType::TPU) {
    if (arena_url_.empty()) {
      return Error("TPU shared memory requires an arena endpoint URL");
    }
    Error err = TpuArenaClient::Create(&arena_, arena_url_);
    if (!err.IsOk()) return err;
  }
  for (size_t stream = 0; stream < loader_->stream_count(); ++stream) {
    for (size_t step = 0; step < loader_->step_count(stream); ++step) {
      for (const auto& tensor : model_->inputs) {
        const TensorData* data = nullptr;
        Error err = loader_->GetInputData(tensor.name, stream, step, &data);
        if (!err.IsOk()) return err;
        std::string region = tensor.name + "_" + std::to_string(stream) +
                             "_" + std::to_string(step);
        err = CreateInputRegion(backend, region, tensor, *data);
        if (!err.IsOk()) return err;
      }
    }
  }
  // One region per output, shared by all in-flight requests
  // (reference behavior; outputs are not validated by the harness).
  for (const auto& tensor : model_->outputs) {
    std::string region = "out_" + tensor.name;
    Error err = CreateOutputRegion(backend, region);
    if (!err.IsOk()) return err;
    output_regions_[tensor.name] = region;
  }
  return Error::Success;
}

Error InferDataManager::Cleanup(ClientBackend* backend) {
  if (shm_type_ == SharedMemoryType::SYSTEM) {
    backend->UnregisterSystemSharedMemory("");
  } else if (shm_type_ == SharedMemoryType::TPU) {
    backend->UnregisterTpuSharedMemory("");
    if (arena_ != nullptr) {
      for (auto& region : tpu_regions_) {
        arena_->DestroyRegion(region.region_id);
      }
    }
    tpu_regions_.clear();
  }
  for (auto& region : system_regions_) {
    if (region.addr != nullptr) UnmapSharedMemory(region.addr, region.byte_size);
    if (region.fd >= 0) CloseSharedMemory(region.fd);
    UnlinkSharedMemoryRegion(region.key);
  }
  system_regions_.clear();
  return Error::Success;
}

Error InferDataManager::BuildInputs(
    size_t stream, size_t step,
    std::vector<std::unique_ptr<InferInput>>* inputs) {
  inputs->clear();
  for (const auto& tensor : model_->inputs) {
    const TensorData* data = nullptr;
    Error err = loader_->GetInputData(tensor.name, stream, step, &data);
    if (!err.IsOk()) return err;
    std::vector<int64_t> shape = data->shape;
    if (model_->max_batch_size > 0 && !tensor.is_shape_tensor) {
      shape.insert(shape.begin(), batch_);
    }
    InferInput* raw = nullptr;
    err = InferInput::Create(&raw, tensor.name, shape, tensor.datatype);
    if (!err.IsOk()) return err;
    std::unique_ptr<InferInput> input(raw);
    if (shm_type_ == SharedMemoryType::NONE) {
      const std::string* payload =
          BatchedBytes(tensor, stream, step, *data);
      input->AppendRaw(
          reinterpret_cast<const uint8_t*>(payload->data()), payload->size());
    } else {
      std::string region = tensor.name + "_" + std::to_string(stream) + "_" +
                           std::to_string(step);
      input->SetSharedMemory(region,
                             data->bytes.size() * CopiesFor(tensor));
    }
    inputs->push_back(std::move(input));
  }
  return Error::Success;
}

Error InferDataManager::BuildOutputs(
    std::vector<std::unique_ptr<InferRequestedOutput>>* outputs) {
  outputs->clear();
  if (shm_type_ == SharedMemoryType::NONE) return Error::Success;
  for (const auto& tensor : model_->outputs) {
    InferRequestedOutput* raw = nullptr;
    Error err = InferRequestedOutput::Create(&raw, tensor.name);
    if (!err.IsOk()) return err;
    std::unique_ptr<InferRequestedOutput> output(raw);
    output->SetSharedMemory(output_regions_[tensor.name], output_shm_size_);
    outputs->push_back(std::move(output));
  }
  return Error::Success;
}

//==============================================================================
// LoadManager

LoadManager::LoadManager(
    const ClientBackendFactory* factory, const ParsedModel* model,
    const DataLoader* loader, InferDataManager* data_manager,
    Options options, SequenceManager* sequence_manager)
    : factory_(factory), model_(model), loader_(loader),
      data_manager_(data_manager), options_(options),
      sequence_manager_(sequence_manager) {}

LoadManager::~LoadManager() { Stop(); }

Error LoadManager::Init() {
  Error err = factory_->Create(&setup_backend_);
  if (!err.IsOk()) return err;
  return data_manager_->Init(setup_backend_.get());
}

void LoadManager::Cleanup() {
  Stop();
  if (setup_backend_ != nullptr) {
    data_manager_->Cleanup(setup_backend_.get());
    setup_backend_.reset();
  }
}

std::vector<RequestRecord> LoadManager::SwapRequestRecords() {
  std::vector<RequestRecord> records;
  for (auto& stat : thread_stats_) {
    std::lock_guard<std::mutex> lock(stat->mutex);
    records.insert(
        records.end(), std::make_move_iterator(stat->records.begin()),
        std::make_move_iterator(stat->records.end()));
    stat->records.clear();
  }
  return records;
}

uint64_t LoadManager::GetAndResetIdleNs() {
  uint64_t total = 0;
  for (auto& stat : thread_stats_) {
    total += stat->idle_ns.exchange(0);
  }
  // Average over ALL launched workers: a worker with zero idle is a
  // saturated worker, exactly what the overhead warning exists to
  // surface — excluding it would suppress the signal.
  return thread_stats_.empty() ? 0 : total / thread_stats_.size();
}

size_t LoadManager::CountCollectedRequests() {
  size_t count = 0;
  for (auto& stat : thread_stats_) {
    std::lock_guard<std::mutex> lock(stat->mutex);
    count += stat->records.size();
  }
  return count;
}

Error LoadManager::CheckHealth() {
  for (auto& stat : thread_stats_) {
    std::lock_guard<std::mutex> lock(stat->mutex);
    if (!stat->status.empty()) {
      return Error("worker thread failed: " + stat->status);
    }
  }
  return Error::Success;
}

void LoadManager::Stop() {
  stop_ = true;
  for (auto& thread : threads_) {
    if (thread.joinable()) thread.join();
  }
  threads_.clear();
  stop_ = false;
}

size_t LoadManager::NextStep(size_t stream) {
  std::lock_guard<std::mutex> lock(step_mutex_);
  size_t steps = std::max<size_t>(loader_->step_count(stream), 1);
  size_t step = step_cursor_[stream];
  step_cursor_[stream] = (step + 1) % steps;
  return step;
}

Error LoadManager::PrepareRequest(
    SequenceManager::Slot* slot,
    std::vector<std::unique_ptr<InferInput>>* inputs,
    std::vector<std::unique_ptr<InferRequestedOutput>>* outputs,
    InferOptions* options) {
  size_t stream = 0, step = 0;
  if (sequence_manager_ != nullptr && slot != nullptr) {
    sequence_manager_->NextStep(
        slot, std::max<size_t>(loader_->stream_count(), 1),
        loader_->step_count(0), options, &stream, &step);
    if (stream >= loader_->stream_count()) stream = 0;
    step = loader_->step_count(stream) > 0
               ? step % loader_->step_count(stream)
               : 0;
  } else {
    step = NextStep(stream);
  }
  Error err = data_manager_->BuildInputs(stream, step, inputs);
  if (!err.IsOk()) return err;
  err = data_manager_->BuildOutputs(outputs);
  if (!err.IsOk()) return err;
  return ApplyRequestParameters(options);
}

Error LoadManager::ApplyRequestParameters(InferOptions* options) {
  // "name:value:type" custom parameters (reference
  // --request-parameter); type in {string, int, uint, bool, double}.
  for (const std::string& parameter : options_.request_parameters) {
    size_t first = parameter.find(':');
    size_t last = parameter.rfind(':');
    if (first == std::string::npos || first == last) {
      return Error("bad --request-parameter (want name:value:type): " +
                   parameter);
    }
    std::string name = parameter.substr(0, first);
    std::string value = parameter.substr(first + 1, last - first - 1);
    std::string type = parameter.substr(last + 1);
    if (type == "string") {
      options->string_params[name] = value;
    } else if (type == "int" || type == "uint") {
      options->int_params[name] = strtoll(value.c_str(), nullptr, 10);
    } else if (type == "bool") {
      options->bool_params[name] = value == "true" || value == "1";
    } else if (type == "double") {
      options->double_params[name] = strtod(value.c_str(), nullptr);
    } else {
      return Error("bad --request-parameter type '" + type + "'");
    }
  }
  return Error::Success;
}

namespace {

std::vector<const InferRequestedOutput*> RawOutputs(
    const std::vector<std::unique_ptr<InferRequestedOutput>>& outputs) {
  std::vector<const InferRequestedOutput*> raw;
  raw.reserve(outputs.size());
  for (const auto& o : outputs) raw.push_back(o.get());
  return raw;
}

std::vector<InferInput*> RawInputs(
    const std::vector<std::unique_ptr<InferInput>>& inputs) {
  std::vector<InferInput*> raw;
  raw.reserve(inputs.size());
  for (const auto& i : inputs) raw.push_back(i.get());
  return raw;
}

}  // namespace

//==============================================================================
// ConcurrencyManager

Error ConcurrencyManager::ChangeConcurrencyLevel(size_t concurrency) {
  Stop();
  concurrency_ = concurrency;
  if (concurrency == 0) return Error::Success;
  size_t n_threads = std::min(concurrency, options_.max_threads);
  size_t base = concurrency / n_threads;
  size_t extra = concurrency % n_threads;
  thread_stats_.clear();
  for (size_t i = 0; i < n_threads; ++i) {
    thread_stats_.push_back(std::make_unique<ThreadStat>());
  }
  for (size_t i = 0; i < n_threads; ++i) {
    size_t ctxs = base + (i < extra ? 1 : 0);
    threads_.emplace_back(
        &ConcurrencyManager::Worker, this, thread_stats_[i].get(), ctxs);
  }
  return Error::Success;
}

void ConcurrencyManager::Worker(ThreadStat* stat, size_t n_ctx) {
  std::unique_ptr<ClientBackend> backend;
  Error err = factory_->Create(&backend);
  if (!err.IsOk()) {
    std::lock_guard<std::mutex> lock(stat->mutex);
    stat->status = err.Message();
    return;
  }
  if (options_.streaming) {
    StreamWorker(stat, backend.get(), n_ctx);
  } else if (options_.async_mode) {
    AsyncWorker(stat, backend.get(), n_ctx);
  } else {
    SyncWorker(stat, backend.get(), n_ctx);
  }
}

void ConcurrencyManager::SyncWorker(
    ThreadStat* stat, ClientBackend* backend, size_t n_ctx) {
  SequenceManager::Slot slot;
  while (!stop_.load()) {
    std::vector<std::unique_ptr<InferInput>> inputs;
    std::vector<std::unique_ptr<InferRequestedOutput>> outputs;
    InferOptions options(model_->name);
    Error err = PrepareRequest(&slot, &inputs, &outputs, &options);
    if (!err.IsOk()) {
      std::lock_guard<std::mutex> lock(stat->mutex);
      stat->status = err.Message();
      return;
    }
    RequestRecord record;
    record.start_ns = NowNs();
    InferResult* result = nullptr;
    err = backend->Infer(
        &result, options, RawInputs(inputs), RawOutputs(outputs));
    // Blocked-in-Infer is waiting on the server, not harness work —
    // count it as idle (reference InferContext wraps the synchronous
    // request with its idle timer the same way).
    stat->AddIdle(NowNs() - record.start_ns);
    if (err.IsOk()) {
      record.end_ns.push_back(NowNs());
      delete result;
    } else {
      record.has_error = true;
      record.error = err.Message();
    }
    stat->AddRecord(std::move(record));
  }
}

void ConcurrencyManager::AsyncWorker(
    ThreadStat* stat, ClientBackend* backend, size_t n_ctx) {
  auto tracker = MakeCtxIdTracker(sequence_manager_ != nullptr,
                                  /*prefer_random=*/false);
  tracker->Reset(n_ctx);
  std::vector<SequenceManager::Slot> slots(n_ctx);
  while (!stop_.load()) {
    uint64_t wait_start = NowNs();
    int ctx_id = tracker->Get(100);
    stat->AddIdle(NowNs() - wait_start);  // no free slot = worker idle
    if (ctx_id < 0) continue;
    if (stop_.load()) {
      tracker->Release(ctx_id);
      break;
    }
    auto inputs =
        std::make_shared<std::vector<std::unique_ptr<InferInput>>>();
    auto outputs = std::make_shared<
        std::vector<std::unique_ptr<InferRequestedOutput>>>();
    InferOptions options(model_->name);
    Error err =
        PrepareRequest(&slots[ctx_id], inputs.get(), outputs.get(), &options);
    if (!err.IsOk()) {
      std::lock_guard<std::mutex> lock(stat->mutex);
      stat->status = err.Message();
      tracker->Release(ctx_id);
      return;
    }
    auto record = std::make_shared<RequestRecord>();
    record->start_ns = NowNs();
    // inputs/outputs captured so buffers outlive the async send.
    err = backend->AsyncInfer(
        [stat, tracker, ctx_id, record, inputs, outputs](InferResult* result) {
          record->end_ns.push_back(NowNs());
          Error status = result != nullptr ? result->RequestStatus()
                                           : Error("null result");
          if (!status.IsOk()) {
            record->has_error = true;
            record->error = status.Message();
          }
          delete result;
          stat->AddRecord(std::move(*record));
          tracker->Release(ctx_id);
        },
        options, RawInputs(*inputs), RawOutputs(*outputs));
    if (!err.IsOk()) {
      record->has_error = true;
      record->error = err.Message();
      stat->AddRecord(std::move(*record));
      tracker->Release(ctx_id);
    }
  }
  // Drain in-flight requests (bounded).
  uint64_t deadline = NowNs() + 5ull * 1000 * 1000 * 1000;
  size_t acquired = 0;
  while (acquired < n_ctx && NowNs() < deadline) {
    if (tracker->Get(200) >= 0) acquired++;
  }
}

void ConcurrencyManager::StreamWorker(
    ThreadStat* stat, ClientBackend* backend, size_t n_ctx) {
  auto tracker = MakeCtxIdTracker(sequence_manager_ != nullptr,
                                  /*prefer_random=*/true);
  tracker->Reset(n_ctx);
  std::vector<SequenceManager::Slot> slots(n_ctx);

  struct Inflight {
    std::shared_ptr<RequestRecord> record;
    int ctx_id;
    std::shared_ptr<std::vector<std::unique_ptr<InferInput>>> inputs;
    std::shared_ptr<std::vector<std::unique_ptr<InferRequestedOutput>>>
        outputs;
  };
  auto inflight = std::make_shared<std::map<uint64_t, Inflight>>();
  auto order = std::make_shared<std::deque<uint64_t>>();
  auto inflight_mutex = std::make_shared<std::mutex>();

  Error err = backend->StartStream(
      [stat, tracker, inflight, order, inflight_mutex](InferResult* result) {
        std::unique_ptr<InferResult> owned(result);
        std::lock_guard<std::mutex> lock(*inflight_mutex);
        // Pair by echoed request id; FIFO fallback.
        uint64_t key = 0;
        bool have_key = false;
        if (owned != nullptr) {
          std::string id;
          if (owned->Id(&id).IsOk() && !id.empty()) {
            char* end = nullptr;
            uint64_t parsed = strtoull(id.c_str(), &end, 10);
            if (end != nullptr && *end == '\0') {
              key = parsed;
              have_key = true;
            }
          }
        }
        if (!have_key) {
          if (order->empty()) return;
          key = order->front();
        }
        auto it = inflight->find(key);
        if (it == inflight->end()) return;
        Inflight& entry = it->second;
        entry.record->end_ns.push_back(NowNs());
        Error status = owned != nullptr ? owned->RequestStatus()
                                        : Error("null stream result");
        bool final = owned == nullptr || IsFinalStreamResponse(owned.get());
        if (!status.IsOk()) {
          entry.record->has_error = true;
          entry.record->error = status.Message();
          final = true;
        }
        // Decoupled models emit several responses per request; each
        // stamps an end_ns, only the final one retires the slot.
        if (!final) return;
        stat->AddRecord(std::move(*entry.record));
        tracker->Release(entry.ctx_id);
        order->erase(
            std::remove(order->begin(), order->end(), key), order->end());
        inflight->erase(it);
      });
  if (!err.IsOk()) {
    std::lock_guard<std::mutex> lock(stat->mutex);
    stat->status = err.Message();
    return;
  }

  uint64_t counter = 0;
  while (!stop_.load()) {
    uint64_t wait_start = NowNs();
    int ctx_id = tracker->Get(100);
    stat->AddIdle(NowNs() - wait_start);  // no free slot = worker idle
    if (ctx_id < 0) continue;
    if (stop_.load()) {
      tracker->Release(ctx_id);
      break;
    }
    auto inputs =
        std::make_shared<std::vector<std::unique_ptr<InferInput>>>();
    auto outputs = std::make_shared<
        std::vector<std::unique_ptr<InferRequestedOutput>>>();
    InferOptions options(model_->name);
    Error prep_err =
        PrepareRequest(&slots[ctx_id], inputs.get(), outputs.get(), &options);
    if (!prep_err.IsOk()) {
      std::lock_guard<std::mutex> lock(stat->mutex);
      stat->status = prep_err.Message();
      tracker->Release(ctx_id);
      break;
    }
    uint64_t key;
    auto record = std::make_shared<RequestRecord>();
    {
      std::lock_guard<std::mutex> lock(*inflight_mutex);
      key = counter++;
      record->start_ns = NowNs();
      (*inflight)[key] = Inflight{record, ctx_id, inputs, outputs};
      order->push_back(key);
    }
    options.request_id = std::to_string(key);
    Error send_err = backend->AsyncStreamInfer(
        options, RawInputs(*inputs), RawOutputs(*outputs));
    if (!send_err.IsOk()) {
      std::lock_guard<std::mutex> lock(*inflight_mutex);
      auto it = inflight->find(key);
      if (it != inflight->end()) {
        it->second.record->has_error = true;
        it->second.record->error = send_err.Message();
        stat->AddRecord(std::move(*it->second.record));
        tracker->Release(it->second.ctx_id);
        order->erase(
            std::remove(order->begin(), order->end(), key), order->end());
        inflight->erase(it);
      }
    }
  }
  backend->StopStream();
}

//==============================================================================
// RequestRateManager

Error RequestRateManager::ChangeRequestRate(double rate, double duration_s) {
  Stop();
  if (rate <= 0) return Error::Success;
  schedule_.clear();
  std::mt19937_64 rng(11);
  std::exponential_distribution<double> expo(rate);
  double t = 0.0;
  while (t < duration_s) {
    t += (distribution_ == Distribution::POISSON) ? expo(rng) : 1.0 / rate;
    schedule_.push_back(t);
  }
  LaunchScheduleWorkers();
  return Error::Success;
}

Error RequestRateManager::SetCustomSchedule(
    const std::vector<double>& intervals_s) {
  Stop();
  if (intervals_s.empty()) return Error("empty custom schedule");
  schedule_.clear();
  double t = 0.0;
  size_t repeats = 200000 / intervals_s.size() + 1;
  for (size_t r = 0; r < repeats && t <= 3600.0; ++r) {
    for (double interval : intervals_s) {
      t += interval;
      schedule_.push_back(t);
    }
  }
  LaunchScheduleWorkers();
  return Error::Success;
}

void RequestRateManager::LaunchScheduleWorkers() {
  size_t n_threads = std::min<size_t>(options_.max_threads, 8);
  if (sequence_manager_ != nullptr) {
    // Concurrent sequences = workers x slots-per-worker; fewer
    // sequences than workers means fewer workers, or the flag would
    // silently over-deliver (each worker needs >= 1 private slot).
    n_threads = std::max<size_t>(
        1, std::min(n_threads, options_.num_of_sequences));
  }
  thread_stats_.clear();
  for (size_t i = 0; i < n_threads; ++i) {
    thread_stats_.push_back(std::make_unique<ThreadStat>());
  }
  uint64_t start_ns = NowNs() + 10ull * 1000 * 1000;
  for (size_t i = 0; i < n_threads; ++i) {
    threads_.emplace_back(
        &RequestRateManager::ScheduleWorker, this, thread_stats_[i].get(), i,
        n_threads, start_ns);
  }
}

void RequestRateManager::ScheduleWorker(
    ThreadStat* stat, size_t worker_idx, size_t n_workers,
    uint64_t start_ns) {
  std::unique_ptr<ClientBackend> backend;
  Error err = factory_->Create(&backend);
  if (!err.IsOk()) {
    std::lock_guard<std::mutex> lock(stat->mutex);
    stat->status = err.Message();
    return;
  }
  // Sequence slots for this worker: --num-of-sequences total across
  // the worker pool, cycled per request; serial mode additionally
  // guarantees one in-flight request per sequence.
  size_t slot_count = 1;
  if (sequence_manager_ != nullptr) {
    // This worker owns the slots {i : i % n_workers == worker_idx},
    // so the pool-wide total is exactly --num-of-sequences (the
    // launcher guarantees n_workers <= num_of_sequences).
    slot_count = std::max<size_t>(
        1, options_.num_of_sequences / n_workers +
               (worker_idx < options_.num_of_sequences % n_workers ? 1
                                                                   : 0));
  }
  std::vector<SequenceManager::Slot> worker_slots(slot_count);
  std::vector<std::shared_ptr<std::atomic<bool>>> slot_busy;
  for (size_t i = 0; i < slot_count; ++i) {
    slot_busy.push_back(std::make_shared<std::atomic<bool>>(false));
  }
  size_t slot_cursor = 0;
  for (size_t idx = worker_idx; idx < schedule_.size() && !stop_.load();
       idx += n_workers) {
    SequenceManager::Slot& slot = worker_slots[slot_cursor];
    auto busy = slot_busy[slot_cursor];
    slot_cursor = (slot_cursor + 1) % slot_count;
    if (options_.serial_sequences) {
      // A sequence must never have two requests in flight; waiting
      // for the previous one is idle time.
      while (busy->load() && !stop_.load()) {
        std::this_thread::sleep_for(std::chrono::microseconds(100));
        stat->AddIdle(100 * 1000);
      }
      if (stop_.load()) break;
    }
    uint64_t due_ns =
        start_ns + static_cast<uint64_t>(schedule_[idx] * 1e9);
    uint64_t now = NowNs();
    bool delayed = false;
    if (now < due_ns) {
      uint64_t wait_us = (due_ns - now) / 1000;
      while (wait_us > 0 && !stop_.load()) {
        uint64_t chunk = std::min<uint64_t>(wait_us, 50000);
        std::this_thread::sleep_for(std::chrono::microseconds(chunk));
        // Accrue idle incrementally: a per-window reset mid-sleep
        // then only loses one 50ms chunk, not the whole wait.
        stat->AddIdle(chunk * 1000);
        now = NowNs();
        wait_us = now < due_ns ? (due_ns - now) / 1000 : 0;
      }
      if (stop_.load()) break;
    } else {
      delayed = (now - due_ns) > 10ull * 1000 * 1000;  // >10ms late
    }
    auto inputs =
        std::make_shared<std::vector<std::unique_ptr<InferInput>>>();
    auto outputs = std::make_shared<
        std::vector<std::unique_ptr<InferRequestedOutput>>>();
    InferOptions options(model_->name);
    err = PrepareRequest(&slot, inputs.get(), outputs.get(), &options);
    if (!err.IsOk()) {
      std::lock_guard<std::mutex> lock(stat->mutex);
      stat->status = err.Message();
      return;
    }
    if (options_.async_mode) {
      auto record = std::make_shared<RequestRecord>();
      record->start_ns = NowNs();
      record->delayed = delayed;
      busy->store(true);
      Error send_err = backend->AsyncInfer(
          [stat, record, inputs, outputs, busy](InferResult* result) {
            record->end_ns.push_back(NowNs());
            Error status = result != nullptr ? result->RequestStatus()
                                             : Error("null result");
            if (!status.IsOk()) {
              record->has_error = true;
              record->error = status.Message();
            }
            delete result;
            stat->AddRecord(std::move(*record));
            busy->store(false);
          },
          options, RawInputs(*inputs), RawOutputs(*outputs));
      if (!send_err.IsOk()) {
        record->has_error = true;
        record->error = send_err.Message();
        stat->AddRecord(std::move(*record));
        busy->store(false);
      }
    } else {
      RequestRecord record;
      record.start_ns = NowNs();
      record.delayed = delayed;
      InferResult* result = nullptr;
      Error send_err = backend->Infer(
          &result, options, RawInputs(*inputs), RawOutputs(*outputs));
      // Blocked-in-Infer is server wait, not harness overhead.
      stat->AddIdle(NowNs() - record.start_ns);
      if (send_err.IsOk()) {
        record.end_ns.push_back(NowNs());
        delete result;
      } else {
        record.has_error = true;
        record.error = send_err.Message();
      }
      stat->AddRecord(std::move(record));
    }
  }
}

//==============================================================================
// CustomLoadManager

Error CustomLoadManager::ReadIntervalsFile(
    const std::string& path, std::vector<double>* intervals_s) {
  std::ifstream in(path);
  if (!in) return Error("cannot open request-intervals file '" + path + "'");
  intervals_s->clear();
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty()) continue;
    intervals_s->push_back(strtoull(line.c_str(), nullptr, 10) / 1e6);
  }
  if (intervals_s->empty()) {
    return Error("request-intervals file '" + path + "' is empty");
  }
  return Error::Success;
}

Error CustomLoadManager::StartSchedule(const std::string& intervals_file) {
  std::vector<double> intervals;
  Error err = ReadIntervalsFile(intervals_file, &intervals);
  if (!err.IsOk()) return err;
  return SetCustomSchedule(intervals);
}

//==============================================================================
// PeriodicConcurrencyManager

Error PeriodicConcurrencyManager::RunRamp(const RampConfig& config) {
  size_t current = config.start;
  Error err = ChangeConcurrencyLevel(current);
  if (!err.IsOk()) return err;
  while (current < config.end && !stop_.load()) {
    if (CountCollectedRequests() >= config.request_period) {
      // ChangeConcurrencyLevel resets worker stats; carry the level's
      // records so the whole ramp is reportable.
      auto records = SwapRequestRecords();
      {
        std::lock_guard<std::mutex> lock(carry_mutex_);
        carry_records_.insert(
            carry_records_.end(), std::make_move_iterator(records.begin()),
            std::make_move_iterator(records.end()));
      }
      current = std::min(current + config.step, config.end);
      err = ChangeConcurrencyLevel(current);
      if (!err.IsOk()) return err;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }
  return Error::Success;
}

std::vector<RequestRecord> PeriodicConcurrencyManager::SwapRampRecords() {
  std::vector<RequestRecord> records;
  {
    std::lock_guard<std::mutex> lock(carry_mutex_);
    records.swap(carry_records_);
  }
  auto live = SwapRequestRecords();
  records.insert(
      records.end(), std::make_move_iterator(live.begin()),
      std::make_move_iterator(live.end()));
  return records;
}

}  // namespace perf
}  // namespace tpuclient
