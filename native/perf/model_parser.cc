#include "model_parser.h"

namespace tpuclient {
namespace perf {

const ModelTensor* ParsedModel::FindInput(const std::string& name) const {
  for (const auto& t : inputs) {
    if (t.name == name) return &t;
  }
  return nullptr;
}

size_t DatatypeByteSize(const std::string& datatype) {
  if (datatype == "BOOL" || datatype == "INT8" || datatype == "UINT8")
    return 1;
  if (datatype == "INT16" || datatype == "UINT16" || datatype == "FP16" ||
      datatype == "BF16")
    return 2;
  if (datatype == "INT32" || datatype == "UINT32" || datatype == "FP32")
    return 4;
  if (datatype == "INT64" || datatype == "UINT64" || datatype == "FP64")
    return 8;
  return 0;  // BYTES / unknown
}

namespace {

void ParseTensors(
    const json::Value& metadata, const char* key, int64_t max_batch_size,
    std::vector<ModelTensor>* out) {
  if (!metadata.Has(key)) return;
  for (const auto& entry : metadata[key].AsArray()) {
    ModelTensor tensor;
    tensor.name = entry["name"].AsString();
    if (entry.Has("datatype")) tensor.datatype = entry["datatype"].AsString();
    if (entry.Has("shape")) {
      for (const auto& d : entry["shape"].AsArray()) {
        tensor.shape.push_back(d.AsInt());
      }
    }
    // Batching models report shapes with a leading -1 batch dim;
    // strip it (the harness re-adds the concrete batch).
    if (max_batch_size > 0 && !tensor.shape.empty() &&
        tensor.shape[0] == -1) {
      tensor.shape.erase(tensor.shape.begin());
    }
    out->push_back(std::move(tensor));
  }
}

// Appends `name` (and, recursively, its own ensemble steps) to the
// composing-model list; sequence-batched children flip
// composing_sequential. Unfetchable children keep their name so the
// profiler can still pair whatever stats the server reports.
void AddComposingModel(
    ClientBackend* backend, const std::string& name, ParsedModel* model,
    std::vector<std::string>* seen) {
  for (const auto& s : *seen) {
    if (s == name) return;
  }
  seen->push_back(name);
  model->composing_models.push_back(name);
  json::Value child;
  if (!backend->ModelConfigJson(&child, name, "").IsOk()) return;
  try {
    if (child.Has("sequence_batching")) model->composing_sequential = true;
    if (child.Has("response_cache")) {
      const json::Value& cache = child["response_cache"];
      if (cache.IsObject() && cache.Has("enable") &&
          cache["enable"].AsBool()) {
        model->composing_cache_enabled = true;
      }
    }
    if (child.Has("ensemble_scheduling")) {
      const json::Value& scheduling = child["ensemble_scheduling"];
      if (scheduling.IsObject() && scheduling.Has("step") &&
          scheduling["step"].IsArray()) {
        for (const auto& step : scheduling["step"].AsArray()) {
          if (step.IsObject() && step.Has("model_name")) {
            AddComposingModel(
                backend, step["model_name"].AsString(), model, seen);
          }
        }
      }
    }
  } catch (const std::exception&) {
    // Malformed child config: the name is already recorded.
  }
}

}  // namespace

Error ModelParser::Parse(
    ClientBackend* backend, const std::string& model_name,
    const std::string& model_version, int64_t batch_size,
    ParsedModel* model,
    const std::vector<std::string>& bls_composing_models) {
  json::Value metadata, config;
  Error err = backend->ModelMetadataJson(&metadata, model_name, model_version);
  if (!err.IsOk()) return err;
  err = backend->ModelConfigJson(&config, model_name, model_version);
  if (!err.IsOk()) return err;

  try {
    model->name =
        metadata.Has("name") ? metadata["name"].AsString() : model_name;
    model->version = model_version;
    if (metadata.Has("platform")) {
      model->platform = metadata["platform"].AsString();
    }
    model->max_batch_size =
        config.Has("max_batch_size") ? config["max_batch_size"].AsInt() : 0;

    if (batch_size > 1 && model->max_batch_size == 0) {
      return Error(
          "batch size " + std::to_string(batch_size) + " requested but "
          "model '" + model_name + "' does not support batching");
    }
    if (model->max_batch_size > 0 && batch_size > model->max_batch_size) {
      return Error(
          "batch size " + std::to_string(batch_size) +
          " exceeds model max_batch_size " +
          std::to_string(model->max_batch_size));
    }

    ParseTensors(metadata, "inputs", model->max_batch_size, &model->inputs);
    ParseTensors(metadata, "outputs", model->max_batch_size, &model->outputs);

    // Shape-tensor and optional-input flags live in the CONFIG's
    // tensor entries, not the metadata.
    for (const char* key : {"input", "output"}) {
      if (!config.Has(key) || !config[key].IsArray()) continue;
      for (const auto& entry : config[key].AsArray()) {
        if (!entry.IsObject() || !entry.Has("name")) continue;
        const std::string name = entry["name"].AsString();
        auto& tensors = (key[0] == 'i') ? model->inputs : model->outputs;
        for (auto& tensor : tensors) {
          if (tensor.name != name) continue;
          if (entry.Has("is_shape_tensor")) {
            tensor.is_shape_tensor = entry["is_shape_tensor"].AsBool();
          }
          if (entry.Has("optional")) {
            tensor.optional = entry["optional"].AsBool();
          }
        }
      }
    }

    std::vector<std::string> seen;
    if (config.Has("ensemble_scheduling")) {
      model->scheduler_type = SchedulerType::ENSEMBLE;
      const json::Value& scheduling = config["ensemble_scheduling"];
      if (scheduling.IsObject() && scheduling.Has("step") &&
          scheduling["step"].IsArray()) {
        for (const auto& step : scheduling["step"].AsArray()) {
          if (step.IsObject() && step.Has("model_name")) {
            AddComposingModel(
                backend, step["model_name"].AsString(), model, &seen);
          }
        }
      }
    } else if (config.Has("sequence_batching")) {
      model->scheduler_type = SchedulerType::SEQUENCE;
    } else if (config.Has("dynamic_batching")) {
      model->scheduler_type = SchedulerType::DYNAMIC;
    }
    if (config.Has("model_transaction_policy")) {
      const auto& policy = config["model_transaction_policy"];
      if (policy.Has("decoupled")) {
        model->decoupled = policy["decoupled"].AsBool();
      }
    }
    if (config.Has("response_cache")) {
      const auto& cache = config["response_cache"];
      if (cache.Has("enable")) {
        model->response_cache_enabled = cache["enable"].AsBool();
      }
    }
    for (const auto& name : bls_composing_models) {
      AddComposingModel(backend, name, model, &seen);
    }
    if (model->scheduler_type == SchedulerType::ENSEMBLE &&
        model->composing_sequential) {
      model->scheduler_type = SchedulerType::ENSEMBLE_SEQUENCE;
    }
  } catch (const std::exception& e) {
    return Error(
        std::string("malformed model metadata/config: ") + e.what());
  }
  return Error::Success;
}

}  // namespace perf
}  // namespace tpuclient
