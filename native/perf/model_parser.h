// Model metadata/config normalization for the native perf harness
// (parity: /root/reference/src/c++/perf_analyzer/model_parser.h:41-76
// — ModelTensor, scheduler type, decoupled flag).
#pragma once

#include <map>
#include <string>
#include <vector>

#include "../library/common.h"
#include "client_backend.h"

namespace tpuclient {
namespace perf {

// Parity: model_parser.h:63 {NONE,DYNAMIC,SEQUENCE,ENSEMBLE,
// ENSEMBLE_SEQUENCE} — the kind picks measurement semantics (sequence
// kinds auto-enable the SequenceManager; ensemble kinds pull
// composing-model server stats into the report).
enum class SchedulerType {
  NONE,
  DYNAMIC,
  SEQUENCE,
  ENSEMBLE,
  ENSEMBLE_SEQUENCE,
};

struct ModelTensor {
  std::string name;
  std::string datatype;
  std::vector<int64_t> shape;
  bool optional = false;
  // Triton shape tensors (config input.is_shape_tensor): their VALUES
  // describe shapes, one value set per batch — the data manager sends
  // them unbatched and never replicates them per row (parity:
  // model_parser.h:41 is_shape_tensor).
  bool is_shape_tensor = false;
};

struct ParsedModel {
  std::string name;
  std::string version;
  std::string platform;
  int64_t max_batch_size = 0;
  // Ordered by declaration, keyed lookups via Find*.
  std::vector<ModelTensor> inputs;
  std::vector<ModelTensor> outputs;
  SchedulerType scheduler_type = SchedulerType::NONE;
  bool decoupled = false;
  // Ensemble steps' model names, resolved recursively (a step may be
  // an ensemble itself) plus explicit BLS children (reference:
  // model_parser.cc DetermineComposingModelMap) — the profiler pairs
  // their per-window server stats with the top model's.
  std::vector<std::string> composing_models;
  // Any composing model is sequence-batched: drive sequences even
  // though the top model is an ensemble (GetComposingSchedulerType).
  bool composing_sequential = false;
  bool response_cache_enabled = false;
  // Any composing model of an ensemble enables response caching: the
  // cache-latency caveat applies to the paired composing stats even
  // when the top model's config has no response_cache section.
  bool composing_cache_enabled = false;

  const ModelTensor* FindInput(const std::string& name) const;
};

class ModelParser {
 public:
  // Fetches metadata + config from the backend and normalizes. A
  // batch_size > the model's max_batch_size (or >1 on a non-batching
  // model) is an error, mirroring the reference's validation.
  static Error Parse(
      ClientBackend* backend, const std::string& model_name,
      const std::string& model_version, int64_t batch_size,
      ParsedModel* model,
      const std::vector<std::string>& bls_composing_models = {});
};

// Bytes per element for fixed-size datatypes; 0 for BYTES.
size_t DatatypeByteSize(const std::string& datatype);

}  // namespace perf
}  // namespace tpuclient
