#include "inference_profiler.h"

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <thread>

#include "mpi_utils.h"

namespace tpuclient {
namespace perf {

namespace {

double Percentile(std::vector<double>& sorted, double p) {
  if (sorted.empty()) return 0.0;
  double rank = (p / 100.0) * (sorted.size() - 1);
  size_t lo = static_cast<size_t>(rank);
  size_t hi = std::min(lo + 1, sorted.size() - 1);
  double frac = rank - lo;
  return sorted[lo] * (1.0 - frac) + sorted[hi] * frac;
}

//==============================================================================
// Per-window server-stat pairing (parity: inference_profiler.cc:648
// start/end snapshot deltas with composing-model merging).

uint64_t StatUint(const json::Value& entry, const char* key) {
  if (!entry.IsObject() || !entry.Has(key)) return 0;
  const json::Value& v = entry[key];
  if (v.IsNumber()) return v.AsUint();
  if (v.IsString()) {
    // protobuf-JSON stringifies (u)int64 counters ("123"), which is
    // what the HTTP stats endpoint serves.
    return strtoull(v.AsString().c_str(), nullptr, 10);
  }
  return 0;
}

const json::Value* FindModelEntry(
    const json::Value& stats, const std::string& name,
    const std::string& version) {
  if (!stats.IsObject() || !stats.Has("model_stats")) return nullptr;
  const json::Value& arr = stats["model_stats"];
  if (!arr.IsArray()) return nullptr;
  for (const auto& entry : arr.AsArray()) {
    if (!entry.IsObject()) continue;
    std::string entry_name =
        entry.Has("name") ? entry["name"].AsString() : "";
    std::string entry_version =
        entry.Has("version") ? entry["version"].AsString() : "";
    if (entry_name == name &&
        (version.empty() || entry_version == version)) {
      return &entry;
    }
  }
  return nullptr;
}

// sign=-1: after + (-1)*before = the window's delta;
// sign=+1: accumulate two window deltas when merging stable trials.
json::Value CombineDuration(
    const json::Value* a, const json::Value* b, int sign) {
  json::Object out;
  uint64_t a_count = a != nullptr ? StatUint(*a, "count") : 0;
  uint64_t a_ns = a != nullptr ? StatUint(*a, "ns") : 0;
  uint64_t b_count = b != nullptr ? StatUint(*b, "count") : 0;
  uint64_t b_ns = b != nullptr ? StatUint(*b, "ns") : 0;
  auto combine = [sign](uint64_t base, uint64_t other) -> uint64_t {
    if (sign < 0) return base >= other ? base - other : 0;
    return base + other;
  };
  out["count"] = json::Value(combine(b_count, a_count));
  out["ns"] = json::Value(combine(b_ns, a_ns));
  return json::Value(std::move(out));
}

json::Value CombineModelEntry(
    const json::Value* before, const json::Value& after, int sign) {
  static const char* kSections[] = {"success", "fail", "queue",
                                    "compute_input", "compute_infer",
                                    "compute_output"};
  json::Object out;
  if (after.IsObject() && after.Has("name")) {
    out["name"] = json::Value(after["name"].AsString());
  }
  if (after.IsObject() && after.Has("version")) {
    out["version"] = json::Value(after["version"].AsString());
  }
  auto combine = [sign](uint64_t base, uint64_t other) -> uint64_t {
    if (sign < 0) return base >= other ? base - other : 0;
    return base + other;
  };
  out["inference_count"] = json::Value(combine(
      StatUint(after, "inference_count"),
      before != nullptr ? StatUint(*before, "inference_count") : 0));
  out["execution_count"] = json::Value(combine(
      StatUint(after, "execution_count"),
      before != nullptr ? StatUint(*before, "execution_count") : 0));
  const json::Value* after_stats =
      after.IsObject() && after.Has("inference_stats")
          ? &after["inference_stats"]
          : nullptr;
  const json::Value* before_stats =
      before != nullptr && before->IsObject() &&
              before->Has("inference_stats")
          ? &(*before)["inference_stats"]
          : nullptr;
  json::Object sections;
  for (const char* section : kSections) {
    const json::Value* a =
        before_stats != nullptr && before_stats->IsObject() &&
                before_stats->Has(section)
            ? &(*before_stats)[section]
            : nullptr;
    const json::Value* b =
        after_stats != nullptr && after_stats->IsObject() &&
                after_stats->Has(section)
            ? &(*after_stats)[section]
            : nullptr;
    sections[section] = CombineDuration(a, b, sign);
  }
  out["inference_stats"] = json::Value(std::move(sections));
  return json::Value(std::move(out));
}

json::Value DeltaServerStats(
    const json::Value& before, const json::Value& after,
    const std::vector<std::string>& models) {
  json::Array entries;
  for (const std::string& name : models) {
    const json::Value* b = FindModelEntry(before, name, "");
    const json::Value* a = FindModelEntry(after, name, "");
    if (a == nullptr) continue;
    entries.push_back(CombineModelEntry(b, *a, -1));
  }
  json::Object root;
  root["model_stats"] = json::Value(std::move(entries));
  return json::Value(std::move(root));
}

json::Value AccumulateServerStats(
    const json::Value& total, const json::Value& part) {
  if (!part.IsObject() || !part.Has("model_stats")) return total;
  if (!total.IsObject() || !total.Has("model_stats")) {
    return part;  // first window with stats
  }
  json::Array entries;
  for (const auto& entry : part["model_stats"].AsArray()) {
    std::string name =
        entry.IsObject() && entry.Has("name") ? entry["name"].AsString() : "";
    const json::Value* prior = FindModelEntry(total, name, "");
    entries.push_back(CombineModelEntry(prior, entry, +1));
  }
  json::Object root;
  root["model_stats"] = json::Value(std::move(entries));
  return json::Value(std::move(root));
}

}  // namespace

Error InferenceProfiler::ProfileConcurrencyRange(
    ConcurrencyManager* manager, size_t start, size_t end, size_t step,
    std::vector<PerfStatus>* results) {
  size_t concurrency = start;
  while (concurrency <= end || (end == 0 && concurrency == start)) {
    Error err = RankCheck(manager->ChangeConcurrencyLevel(concurrency));
    if (!err.IsOk()) return err;
    PerfStatus status;
    err = ProfileLevel(&status);
    if (!err.IsOk()) return err;
    status.concurrency = concurrency;
    results->push_back(std::move(status));
    // Any rank over the threshold stops EVERY rank: a local break
    // would desequence the per-trial collectives of the next level.
    if (AnyRank(ExceedsLatencyThreshold(results->back()))) break;
    if (end == 0) break;
    concurrency += step;
  }
  manager->Stop();
  return Error::Success;
}

Error InferenceProfiler::ProfileConcurrencyBinarySearch(
    ConcurrencyManager* manager, size_t start, size_t end,
    std::vector<PerfStatus>* results) {
  if (config_.latency_threshold_ms <= 0) {
    return Error("--binary-search requires --latency-threshold");
  }
  if (end < start) return Error("--binary-search needs start <= end");
  size_t lo = start, hi = end;
  size_t best = 0;
  while (lo <= hi) {
    size_t mid = lo + (hi - lo) / 2;
    Error err = RankCheck(manager->ChangeConcurrencyLevel(mid));
    if (!err.IsOk()) return err;
    PerfStatus status;
    err = ProfileLevel(&status);
    if (!err.IsOk()) return err;
    status.concurrency = mid;
    // Rank-merged: every rank must take the SAME branch or their
    // subsequent collective sequences diverge.
    bool over = AnyRank(ExceedsLatencyThreshold(status));
    results->push_back(std::move(status));
    if (verbose_) {
      fprintf(stderr, "binary search: concurrency %zu %s threshold\n",
              mid, over ? "exceeds" : "meets");
    }
    if (over) {
      if (mid == 0) break;
      hi = mid - 1;
      if (hi < start) break;  // nothing meets the threshold
    } else {
      best = mid;
      lo = mid + 1;
    }
  }
  if (best == 0) {
    return Error("no concurrency in range meets the latency threshold");
  }
  // Re-order so the winning level's measurement is last (report
  // convention: final row = recommendation).
  for (size_t i = 0; i + 1 < results->size(); ++i) {
    if ((*results)[i].concurrency == best) {
      std::rotate(results->begin() + i, results->begin() + i + 1,
                  results->end());
    }
  }
  return Error::Success;
}

Error InferenceProfiler::ProfileRequestRateRange(
    RequestRateManager* manager, double start, double end, double step,
    std::vector<PerfStatus>* results) {
  double rate = start;
  while (rate <= end + 1e-9 || (end == 0 && rate == start)) {
    Error err = RankCheck(manager->ChangeRequestRate(rate));
    if (!err.IsOk()) return err;
    PerfStatus status;
    err = ProfileLevel(&status);
    if (!err.IsOk()) return err;
    status.request_rate = rate;
    results->push_back(std::move(status));
    if (AnyRank(ExceedsLatencyThreshold(results->back()))) break;
    if (end == 0) break;
    rate += step;
  }
  manager->Stop();
  return Error::Success;
}

Error InferenceProfiler::ProfileSingleLevel(PerfStatus* status) {
  return ProfileLevel(status);
}

bool InferenceProfiler::AllRanks(bool local) const {
  // AND across ranks; identity when not under MPI. EVERY rank-local
  // control-flow decision that gates a collective (another trial's
  // allreduce, the next level's measurement) must flow through this
  // or AnyRank — a rank-local break would leave peers blocked in a
  // collective this rank never enters.
  if (mpi_ == nullptr) return local;
  return mpi_->MPIAllTrue(local);
}

bool InferenceProfiler::AnyRank(bool local) const {
  return !AllRanks(!local);
}

Error InferenceProfiler::RankCheck(const Error& err) const {
  // Merge a rank-local outcome BEFORE any early return that skips a
  // collective: without this, one failing rank leaves its peers
  // blocked in an allreduce/barrier it never reaches.
  if (AllRanks(err.IsOk())) return Error::Success;
  return err.IsOk() ? Error("a peer rank failed") : err;
}

bool InferenceProfiler::ExceedsLatencyThreshold(
    const PerfStatus& status) const {
  if (config_.latency_threshold_ms <= 0) return false;
  return StabilityMetric(status) / 1000.0 > config_.latency_threshold_ms;
}

double InferenceProfiler::StabilityMetric(const PerfStatus& status) const {
  if (config_.percentile != 0) {
    auto it = status.latency_percentiles.find(config_.percentile);
    if (it != status.latency_percentiles.end()) return it->second;
  }
  return status.avg_latency_us;
}

Error InferenceProfiler::ProfileLevel(PerfStatus* merged) {
  std::vector<PerfStatus> trials;
  for (size_t trial = 0; trial < config_.max_trials; ++trial) {
    PerfStatus status;
    Error err = Measure(&status);
    if (err.IsOk()) err = manager_->CheckHealth();
    // Merge the per-trial outcome BEFORE any early return: a rank
    // returning on a local error while peers enter the stability
    // allreduce would deadlock the world.
    if (!AllRanks(err.IsOk())) {
      return err.IsOk() ? Error("a peer rank failed its measurement")
                        : err;
    }
    if (verbose_) {
      fprintf(stderr, "  trial %zu: %.1f infer/sec, avg %.0f us\n", trial,
              status.throughput, status.avg_latency_us);
    }
    if (config_.log_frequency > 0) {
      completed_total_ += status.completed_count;
      if (completed_total_ >= next_log_at_) {
        fprintf(stderr, "completed %zu requests\n", completed_total_);
        next_log_at_ =
            (completed_total_ / config_.log_frequency + 1) *
            config_.log_frequency;
      }
    }
    trials.push_back(std::move(status));
    if (config_.max_trials == 1) {
      // Single-window modes (--request-count) measure once by
      // design; the stability rule (3 agreeing trials) cannot apply.
      if (!AllRanks(trials.back().completed_count > 0)) {
        return Error(
            "no valid requests recorded in the measurement window; "
            "use a larger --measurement-interval (-p)");
      }
      *merged = Merge(std::move(trials));
      return Error::Success;
    }
    // Rank-merged decision: no rank stops measuring until EVERY
    // rank's last trials agree, so all processes report windows
    // covering the same load interval.
    bool stable = AllRanks(IsStable(trials));
    if (stable) {
      std::vector<PerfStatus> last3(
          std::make_move_iterator(trials.end() - 3),
          std::make_move_iterator(trials.end()));
      *merged = Merge(std::move(last3));
      return Error::Success;
    }
  }
  // Reference contract: a level whose every window saw no completed
  // request is an error, not a zero-stat report. Rank-merged (any
  // empty rank fails the world) so no rank walks on to the next
  // level's collectives alone.
  bool any_completed = false;
  for (const auto& t : trials) any_completed |= t.completed_count > 0;
  if (!AllRanks(any_completed)) {
    return Error(
        "no valid requests recorded in any measurement window; use a "
        "larger --measurement-interval (-p) or --measurement-mode "
        "count_windows");
  }
  // Unstable: merge what we have, flagged.
  size_t keep = std::min<size_t>(trials.size(), 3);
  std::vector<PerfStatus> tail(
      std::make_move_iterator(trials.end() - keep),
      std::make_move_iterator(trials.end()));
  *merged = Merge(std::move(tail));
  merged->on_target = false;
  return Error::Success;
}

Error InferenceProfiler::Measure(PerfStatus* status) {
  manager_->SwapRequestRecords();  // discard warm-up residue
  if (metrics_ != nullptr) metrics_->GetAndReset();  // drop stale scrapes
  const bool want_stats = stats_backend_ != nullptr && !model_name_.empty();
  json::Value stats_before;
  if (want_stats) {
    // Best effort — a failed stats scrape never fails the window.
    stats_backend_->ModelStatisticsJson(&stats_before, "");
  }
  manager_->GetAndResetIdleNs();  // window starts with clean idle books
  uint64_t start_ns = NowNs();
  if (config_.count_windows) {
    uint64_t deadline =
        start_ns + config_.measurement_interval_ms * 10ull * 1000 * 1000;
    while (manager_->CountCollectedRequests() <
               config_.measurement_request_count &&
           NowNs() < deadline) {
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
    }
  } else {
    std::this_thread::sleep_for(
        std::chrono::milliseconds(config_.measurement_interval_ms));
  }
  uint64_t end_ns = NowNs();
  {
    // Reference SummarizeOverhead: idle above the window length (the
    // start/stop isn't instantaneous) clamps to 0% overhead.
    uint64_t window_ns = end_ns - start_ns;
    uint64_t idle_ns = manager_->GetAndResetIdleNs();
    status->overhead_pct =
        idle_ns >= window_ns
            ? 0.0
            : 100.0 * static_cast<double>(window_ns - idle_ns) / window_ns;
  }
  Summarize(manager_->SwapRequestRecords(), start_ns, end_ns, status);
  if (metrics_ != nullptr) {
    status->tpu_metrics = SummarizeMetrics(metrics_->GetAndReset());
  }
  if (want_stats) {
    json::Value stats_after;
    Error stats_err = stats_backend_->ModelStatisticsJson(&stats_after, "");
    if (stats_err.IsOk()) {
      std::vector<std::string> models = {model_name_};
      models.insert(models.end(), composing_models_.begin(),
                    composing_models_.end());
      status->server_stats =
          DeltaServerStats(stats_before, stats_after, models);
    }
  }
  return Error::Success;
}

void InferenceProfiler::Summarize(
    std::vector<RequestRecord>&& records, uint64_t start_ns, uint64_t end_ns,
    PerfStatus* status) {
  status->window_start_ns = start_ns;
  status->window_end_ns = end_ns;
  std::vector<double> latencies_us;
  for (const auto& record : records) {
    if (record.valid()) {
      latencies_us.push_back(record.latency_ns() / 1000.0);
    }
    if (record.has_error) {
      status->error_count++;
      if (status->sample_error.empty()) status->sample_error = record.error;
    }
    if (record.delayed) status->delayed_count++;
  }
  status->records = std::move(records);
  status->completed_count = latencies_us.size();
  if (latencies_us.empty()) return;
  double sum = 0.0;
  for (double v : latencies_us) sum += v;
  status->avg_latency_us = sum / latencies_us.size();
  double var = 0.0;
  for (double v : latencies_us) {
    var += (v - status->avg_latency_us) * (v - status->avg_latency_us);
  }
  status->std_latency_us = std::sqrt(var / latencies_us.size());
  std::sort(latencies_us.begin(), latencies_us.end());
  for (int p : {50, 90, 95, 99}) {
    status->latency_percentiles[p] = Percentile(latencies_us, p);
  }
  if (config_.percentile != 0 &&
      status->latency_percentiles.find(config_.percentile) ==
          status->latency_percentiles.end()) {
    status->latency_percentiles[config_.percentile] =
        Percentile(latencies_us, config_.percentile);
  }
  double window_s = (end_ns - start_ns) / 1e9;
  status->throughput =
      window_s > 0
          ? status->completed_count * config_.batch_size / window_s
          : 0.0;
}

bool InferenceProfiler::IsStable(
    const std::vector<PerfStatus>& trials) const {
  if (trials.size() < 3) return false;
  const PerfStatus* last3[3] = {
      &trials[trials.size() - 3], &trials[trials.size() - 2],
      &trials[trials.size() - 1]};
  for (const PerfStatus* t : last3) {
    if (t->completed_count == 0) return false;
  }
  double latencies[3], throughputs[3];
  for (int i = 0; i < 3; ++i) {
    latencies[i] = StabilityMetric(*last3[i]);
    throughputs[i] = last3[i]->throughput;
  }
  for (double* values : {latencies, throughputs}) {
    double mean = (values[0] + values[1] + values[2]) / 3.0;
    if (mean <= 0) return false;
    for (int i = 0; i < 3; ++i) {
      if (std::abs(values[i] - mean) / mean > config_.stability_threshold) {
        return false;
      }
    }
  }
  return true;
}

PerfStatus InferenceProfiler::Merge(std::vector<PerfStatus>&& trials) const {
  PerfStatus merged;
  if (trials.empty()) return merged;
  merged.window_start_ns = trials.front().window_start_ns;
  merged.window_end_ns = trials.back().window_end_ns;
  double window_s = 0.0;
  std::vector<double> latencies_us;
  for (auto& trial : trials) {
    merged.completed_count += trial.completed_count;
    merged.error_count += trial.error_count;
    if (merged.sample_error.empty()) merged.sample_error = trial.sample_error;
    merged.delayed_count += trial.delayed_count;
    window_s += (trial.window_end_ns - trial.window_start_ns) / 1e9;
    for (auto& record : trial.records) {
      if (record.valid()) latencies_us.push_back(record.latency_ns() / 1000.0);
      merged.records.push_back(std::move(record));
    }
  }
  // Window deltas are additive across the merged stable windows.
  for (const auto& trial : trials) {
    merged.server_stats =
        AccumulateServerStats(merged.server_stats, trial.server_stats);
  }
  {
    // Average the window averages; keep the overall max.
    std::map<std::string, std::vector<std::pair<double, double>>> collected;
    for (const auto& trial : trials) {
      for (const auto& kv : trial.tpu_metrics) {
        collected[kv.first].push_back(kv.second);
      }
    }
    for (const auto& kv : collected) {
      double sum = 0, max = 0;
      for (const auto& window : kv.second) {
        sum += window.first;
        max = std::max(max, window.second);
      }
      merged.tpu_metrics[kv.first] = {sum / kv.second.size(), max};
    }
  }
  if (!latencies_us.empty()) {
    double sum = 0.0;
    for (double v : latencies_us) sum += v;
    merged.avg_latency_us = sum / latencies_us.size();
    double var = 0.0;
    for (double v : latencies_us) {
      var += (v - merged.avg_latency_us) * (v - merged.avg_latency_us);
    }
    merged.std_latency_us = std::sqrt(var / latencies_us.size());
    std::sort(latencies_us.begin(), latencies_us.end());
    for (int p : {50, 90, 95, 99}) {
      merged.latency_percentiles[p] = Percentile(latencies_us, p);
    }
    if (config_.percentile != 0 &&
        merged.latency_percentiles.find(config_.percentile) ==
            merged.latency_percentiles.end()) {
      merged.latency_percentiles[config_.percentile] =
          Percentile(latencies_us, config_.percentile);
    }
  }
  merged.throughput =
      window_s > 0 ? merged.completed_count * config_.batch_size / window_s
                   : 0.0;
  return merged;
}

}  // namespace perf
}  // namespace tpuclient
