#include "client_backend.h"

#include <chrono>
#include <random>
#include <thread>
#include <type_traits>

#include "../library/grpc_client.h"
#include "../library/http_client.h"
#include "../library/http_transport.h"
#include "client_tpu/protocol/tensorflow_serving_apis.pb.h"
#ifdef TPUCLIENT_HAVE_PYTHON
#include "inprocess_backend.h"
#endif
#include "client_tpu/protocol/arena.pb.h"

namespace tpuclient {
namespace perf {

namespace {

//==============================================================================
// GRPC backend: wraps the native gRPC client 1:1 (parity:
// triton_client_backend.h:72).
//
class GrpcBackend : public ClientBackend {
 public:
  static Error Create(
      const BackendConfig& config, std::unique_ptr<ClientBackend>* backend) {
    auto b = std::unique_ptr<GrpcBackend>(new GrpcBackend());
    b->grpc_compression_ = config.grpc_compression;
    Error err = InferenceServerGrpcClient::Create(
        &b->client_, config.url, config.verbose);
    if (!err.IsOk()) return err;
    *backend = std::move(b);
    return Error::Success;
  }

  Error ServerMetadataJson(json::Value* metadata) override {
    inference::ServerMetadataResponse resp;
    Error err = client_->ServerMetadata(&resp);
    if (!err.IsOk()) return err;
    json::Object root;
    root["name"] = json::Value(resp.name());
    root["version"] = json::Value(resp.version());
    json::Array exts;
    for (const auto& e : resp.extensions()) exts.push_back(json::Value(e));
    root["extensions"] = json::Value(std::move(exts));
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string& model_version) override {
    inference::ModelMetadataResponse resp;
    Error err =
        client_->ModelMetadata(&resp, model_name, model_version);
    if (!err.IsOk()) return err;
    json::Object root;
    root["name"] = json::Value(resp.name());
    root["platform"] = json::Value(resp.platform());
    auto tensors_to_json = [](const auto& tensors) {
      json::Array arr;
      for (const auto& t : tensors) {
        json::Object entry;
        entry["name"] = json::Value(t.name());
        entry["datatype"] = json::Value(t.datatype());
        json::Array shape;
        for (int64_t d : t.shape()) shape.push_back(json::Value(d));
        entry["shape"] = json::Value(std::move(shape));
        arr.push_back(json::Value(std::move(entry)));
      }
      return json::Value(std::move(arr));
    };
    root["inputs"] = tensors_to_json(resp.inputs());
    root["outputs"] = tensors_to_json(resp.outputs());
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string& model_version) override {
    inference::ModelConfigResponse resp;
    Error err = client_->ModelConfig(&resp, model_name, model_version);
    if (!err.IsOk()) return err;
    const auto& c = resp.config();
    json::Object root;
    root["name"] = json::Value(c.name());
    root["max_batch_size"] =
        json::Value(static_cast<int64_t>(c.max_batch_size()));
    root["platform"] = json::Value(c.platform());
    if (c.has_sequence_batching()) {
      root["sequence_batching"] = json::Value(json::Object{});
    }
    if (c.has_dynamic_batching()) {
      root["dynamic_batching"] = json::Value(json::Object{});
    }
    if (c.has_ensemble_scheduling()) {
      // The step list carries the composing-model names the profiler
      // pairs per-window stats for — an empty object would silently
      // disable that on the gRPC path.
      json::Array steps;
      for (const auto& step : c.ensemble_scheduling().step()) {
        json::Object entry;
        entry["model_name"] = json::Value(step.model_name());
        steps.push_back(json::Value(std::move(entry)));
      }
      json::Object scheduling;
      scheduling["step"] = json::Value(std::move(steps));
      root["ensemble_scheduling"] = json::Value(std::move(scheduling));
    }
    if (c.model_transaction_policy().decoupled()) {
      json::Object policy;
      policy["decoupled"] = json::Value(true);
      root["model_transaction_policy"] = json::Value(std::move(policy));
    }
    *config = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelStatisticsJson(
      json::Value* stats, const std::string& model_name) override {
    inference::ModelStatisticsResponse resp;
    Error err = client_->ModelInferenceStatistics(&resp, model_name);
    if (!err.IsOk()) return err;
    json::Array model_stats;
    for (const auto& m : resp.model_stats()) {
      json::Object entry;
      entry["name"] = json::Value(m.name());
      entry["version"] = json::Value(m.version());
      entry["inference_count"] =
          json::Value(static_cast<uint64_t>(m.inference_count()));
      entry["execution_count"] =
          json::Value(static_cast<uint64_t>(m.execution_count()));
      json::Object infer_stats;
      auto dur = [](const inference::StatisticDuration& d) {
        json::Object o;
        o["count"] = json::Value(static_cast<uint64_t>(d.count()));
        o["ns"] = json::Value(static_cast<uint64_t>(d.ns()));
        return json::Value(std::move(o));
      };
      infer_stats["success"] = dur(m.inference_stats().success());
      infer_stats["fail"] = dur(m.inference_stats().fail());
      infer_stats["queue"] = dur(m.inference_stats().queue());
      infer_stats["compute_input"] = dur(m.inference_stats().compute_input());
      infer_stats["compute_infer"] = dur(m.inference_stats().compute_infer());
      infer_stats["compute_output"] =
          dur(m.inference_stats().compute_output());
      entry["inference_stats"] = json::Value(std::move(infer_stats));
      model_stats.push_back(json::Value(std::move(entry)));
    }
    json::Object root;
    root["model_stats"] = json::Value(std::move(model_stats));
    *stats = json::Value(std::move(root));
    return Error::Success;
  }

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    return client_->Infer(result, options, inputs, outputs, {},
                          grpc_compression_);
  }

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    return client_->AsyncInfer(std::move(callback), options, inputs, outputs,
                               {}, grpc_compression_);
  }

  Error StartStream(OnCompleteFn callback) override {
    return client_->StartStream(std::move(callback));
  }
  Error StopStream() override { return client_->StopStream(); }
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    return client_->AsyncStreamInfer(options, inputs, outputs);
  }

  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset) override {
    return client_->RegisterSystemSharedMemory(name, key, byte_size, offset);
  }
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int64_t device_id, size_t byte_size) override {
    return client_->RegisterTpuSharedMemory(
        name, raw_handle, device_id, byte_size);
  }
  Error UnregisterSystemSharedMemory(const std::string& name) override {
    return client_->UnregisterSystemSharedMemory(name);
  }
  Error UnregisterTpuSharedMemory(const std::string& name) override {
    return client_->UnregisterTpuSharedMemory(name);
  }

 private:
  std::unique_ptr<InferenceServerGrpcClient> client_;
  std::string grpc_compression_;
};

//==============================================================================
// HTTP backend.
//
class HttpBackend : public ClientBackend {
 public:
  static Error Create(
      const BackendConfig& config, std::unique_ptr<ClientBackend>* backend) {
    auto b = std::unique_ptr<HttpBackend>(new HttpBackend());
    std::string url = config.url;
    if (config.https && url.find("://") == std::string::npos) {
      url = "https://" + url;  // scheme selects TLS in the client
    }
    Error err = config.https
                    ? InferenceServerHttpClient::Create(
                          &b->client_, url, config.https_ssl,
                          config.verbose)
                    : InferenceServerHttpClient::Create(
                          &b->client_, url, config.verbose);
    if (!err.IsOk()) return err;
    b->client_->SetAsyncWorkerCount(config.http_async_workers);
    b->json_input_ = config.http_json_input;
    b->json_output_ = config.http_json_output;
    *backend = std::move(b);
    return Error::Success;
  }

  Error ServerMetadataJson(json::Value* metadata) override {
    std::string text;
    Error err = client_->ServerMetadata(&text);
    if (!err.IsOk()) return err;
    return ParseInto(text, metadata);
  }

  Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string& model_version) override {
    std::string text;
    Error err = client_->ModelMetadata(&text, model_name, model_version);
    if (!err.IsOk()) return err;
    return ParseInto(text, metadata);
  }

  Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string& model_version) override {
    std::string text;
    Error err = client_->ModelConfig(&text, model_name, model_version);
    if (!err.IsOk()) return err;
    return ParseInto(text, config);
  }

  Error ModelStatisticsJson(
      json::Value* stats, const std::string& model_name) override {
    std::string text;
    Error err = client_->ModelInferenceStatistics(&text, model_name);
    if (!err.IsOk()) return err;
    return ParseInto(text, stats);
  }

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    return client_->Infer(result, Formatted(options), inputs, outputs);
  }
  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    return client_->AsyncInfer(std::move(callback), Formatted(options),
                               inputs, outputs);
  }
  Error StartStream(OnCompleteFn callback) override {
    return Error("streaming is not supported over HTTP");
  }
  Error StopStream() override { return Error::Success; }
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    return Error("streaming is not supported over HTTP");
  }

  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset) override {
    return client_->RegisterSystemSharedMemory(name, key, byte_size, offset);
  }
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int64_t device_id, size_t byte_size) override {
    return client_->RegisterTpuSharedMemory(
        name, raw_handle, device_id, byte_size);
  }
  Error UnregisterSystemSharedMemory(const std::string& name) override {
    return client_->UnregisterSystemSharedMemory(name);
  }
  Error UnregisterTpuSharedMemory(const std::string& name) override {
    return client_->UnregisterTpuSharedMemory(name);
  }

 private:
  // Apply the configured tensor wire formats to a request's options.
  InferOptions Formatted(const InferOptions& options) const {
    if (!json_input_ && !json_output_) return options;
    InferOptions adjusted = options;
    adjusted.json_input_data = json_input_;
    if (json_output_) adjusted.binary_data_output = false;
    return adjusted;
  }

  static Error ParseInto(const std::string& text, json::Value* out) {
    std::string err = json::Parse(text.data(), text.size(), out);
    if (!err.empty()) return Error("bad JSON from server: " + err);
    return Error::Success;
  }

  std::unique_ptr<InferenceServerHttpClient> client_;
  bool json_input_ = false;
  bool json_output_ = false;
};

//==============================================================================
// OpenAI backend: chat-completions over HTTP with SSE streaming
// (parity: the reference's openai client backend,
// client_backend/openai/openai_client.h:112-176 — payload passthrough
// from the input JSON, one response callback per SSE chunk). The
// "payload" input carries the full request-body JSON; streaming mode
// appends '"stream": true' responsibility to the payload author.
//
class OpenAiInferResult : public InferResult {
 public:
  OpenAiInferResult(
      Error status, std::string body, std::string id, bool is_final)
      : status_(std::move(status)), body_(std::move(body)),
        id_(std::move(id)), is_final_(is_final) {}

  Error ModelName(std::string* name) const override {
    *name = "openai";
    return Error::Success;
  }
  Error ModelVersion(std::string* version) const override {
    *version = "";
    return Error::Success;
  }
  Error Id(std::string* id) const override {
    *id = id_;
    return Error::Success;
  }
  Error Shape(
      const std::string&, std::vector<int64_t>* shape) const override {
    *shape = {1};
    return Error::Success;
  }
  Error Datatype(const std::string&, std::string* datatype) const override {
    *datatype = "BYTES";
    return Error::Success;
  }
  Error RawData(
      const std::string&, const uint8_t** buf,
      size_t* byte_size) const override {
    *buf = reinterpret_cast<const uint8_t*>(body_.data());
    *byte_size = body_.size();
    return Error::Success;
  }
  Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const override {
    string_result->assign(1, body_);
    return Error::Success;
  }
  std::string DebugString() const override { return body_; }
  Error RequestStatus() const override { return status_; }

  bool IsFinalResponse() const { return is_final_; }

 private:
  Error status_;
  std::string body_;
  std::string id_;
  bool is_final_;
};

// One-shot POST shared by the plain-HTTP backends (OpenAI
// non-streaming and the REST kinds): transport and HTTP-status errors
// both land in the returned result's RequestStatus, the uniform shape
// the workers expect from async completions.
static InferResult* PostAndWrap(
    const std::string& host, int port, const std::string& path,
    const std::string& content_type, const std::string& body,
    const std::string& request_id, uint64_t timeout_us,
    bool use_tls = false, const SslOptions& ssl = SslOptions()) {
  HttpConnection conn(host, port, use_tls, ssl);
  HttpResponse response;
  std::string transport_err = conn.Request(
      "POST", path, {{"Content-Type", content_type}}, body, &response,
      timeout_us);
  Error status = Error::Success;
  if (!transport_err.empty()) {
    status = Error(transport_err);
  } else if (response.status_code != 200) {
    status = Error(
        "HTTP " + std::to_string(response.status_code) + ": " +
        response.body);
  }
  return new OpenAiInferResult(
      status, std::move(response.body), request_id, true);
}

class OpenAiBackend : public ClientBackend {
 public:
  explicit OpenAiBackend(const BackendConfig& config)
      : endpoint_(config.openai_endpoint), use_tls_(config.https),
        ssl_(config.https_ssl) {
    std::string rest = config.url;
    size_t scheme = rest.find("://");
    if (scheme != std::string::npos) rest = rest.substr(scheme + 3);
    size_t colon = rest.rfind(':');
    if (colon != std::string::npos) {
      port_ = atoi(rest.substr(colon + 1).c_str());
      host_ = rest.substr(0, colon);
    } else {
      host_ = rest;
    }
    if (!endpoint_.empty() && endpoint_[0] != '/') {
      endpoint_ = "/" + endpoint_;
    }
  }

  ~OpenAiBackend() override {
    StopStream();
    while (inflight_.load() > 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }

  Error ServerMetadataJson(json::Value* metadata) override {
    json::Object root;
    root["name"] = json::Value(std::string("openai-endpoint"));
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  // Synthesized schema (parity: ModelParser::InitOpenAI,
  // model_parser.cc:116): a single raw JSON "payload" input.
  Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string&) override {
    json::Object root;
    root["name"] = json::Value(model_name);
    root["platform"] = json::Value(std::string("openai"));
    json::Array inputs;
    json::Object payload;
    payload["name"] = json::Value(std::string("payload"));
    payload["datatype"] = json::Value(std::string("BYTES"));
    json::Array shape;
    shape.push_back(json::Value(static_cast<int64_t>(1)));
    payload["shape"] = json::Value(std::move(shape));
    inputs.push_back(json::Value(std::move(payload)));
    root["inputs"] = json::Value(std::move(inputs));
    root["outputs"] = json::Value(json::Array{});
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string&) override {
    json::Object root;
    root["name"] = json::Value(model_name);
    root["max_batch_size"] = json::Value(static_cast<int64_t>(0));
    *config = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelStatisticsJson(json::Value* stats, const std::string&) override {
    json::Object root;
    root["model_stats"] = json::Value(json::Array{});
    *stats = json::Value(std::move(root));
    return Error::Success;
  }

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>&) override {
    std::string payload;
    Error err = GatherPayload(inputs, &payload);
    if (!err.IsOk()) return err;
    *result = PostAndWrap(
        host_, port_, endpoint_, "application/json", payload,
        options.request_id, options.client_timeout_us, use_tls_, ssl_);
    return Error::Success;
  }

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    std::string payload;
    Error err = GatherPayload(inputs, &payload);
    if (!err.IsOk()) return err;
    inflight_++;
    std::string id = options.request_id;
    uint64_t timeout_us = options.client_timeout_us;
    std::thread([this, callback = std::move(callback), id,
                 payload = std::move(payload), timeout_us] {
      callback(PostAndWrap(host_, port_, endpoint_, "application/json",
                           payload, id, timeout_us, use_tls_, ssl_));
      inflight_--;
    }).detach();
    return Error::Success;
  }

  Error StartStream(OnCompleteFn callback) override {
    std::lock_guard<std::mutex> lock(stream_mutex_);
    stream_callback_ = std::move(callback);
    return Error::Success;
  }

  Error StopStream() override {
    std::lock_guard<std::mutex> lock(stream_mutex_);
    stream_callback_ = nullptr;
    return Error::Success;
  }

  // SSE streaming: one callback per "data:" chunk, a final empty
  // response at [DONE] / stream end.
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>&) override {
    OnCompleteFn callback;
    {
      std::lock_guard<std::mutex> lock(stream_mutex_);
      callback = stream_callback_;
    }
    if (!callback) return Error("stream not started");
    std::string payload;
    Error err = GatherPayload(inputs, &payload);
    if (!err.IsOk()) return err;
    inflight_++;
    std::string id = options.request_id;
    uint64_t timeout_us = options.client_timeout_us;
    std::thread([this, callback = std::move(callback), id,
                 payload = std::move(payload), timeout_us] {
      HttpConnection conn(host_, port_, use_tls_, ssl_);
      HttpResponse response;
      std::string buffer;
      auto on_data = [&](const char* data, size_t len) {
        buffer.append(data, len);
        size_t pos;
        while ((pos = buffer.find("\n\n")) != std::string::npos) {
          std::string event = buffer.substr(0, pos);
          buffer.erase(0, pos + 2);
          if (event.rfind("data: ", 0) != 0) continue;
          std::string chunk = event.substr(6);
          if (chunk == "[DONE]") continue;  // final fires after EOF
          callback(new OpenAiInferResult(
              Error::Success, std::move(chunk), id, false));
        }
      };
      std::string transport_err = conn.RequestStreaming(
          "POST", endpoint_, {{"Content-Type", "application/json"}},
          payload, &response, on_data, timeout_us);
      Error status = Error::Success;
      if (!transport_err.empty()) {
        status = Error(transport_err);
      } else if (response.status_code != 200) {
        status = Error("HTTP " + std::to_string(response.status_code));
      }
      callback(new OpenAiInferResult(status, "", id, true));
      inflight_--;
    }).detach();
    return Error::Success;
  }

  Error RegisterSystemSharedMemory(
      const std::string&, const std::string&, size_t, size_t) override {
    return Error("shared memory is not supported by the OpenAI backend");
  }
  Error RegisterTpuSharedMemory(
      const std::string&, const std::string&, int64_t, size_t) override {
    return Error("shared memory is not supported by the OpenAI backend");
  }
  Error UnregisterSystemSharedMemory(const std::string&) override {
    return Error::Success;
  }
  Error UnregisterTpuSharedMemory(const std::string&) override {
    return Error::Success;
  }

 private:
  static Error GatherPayload(
      const std::vector<InferInput*>& inputs, std::string* payload) {
    for (InferInput* input : inputs) {
      if (input->Name() == "payload") {
        input->GatherInto(payload);
        // BYTES wire format: strip the 4-byte length prefix.
        if (payload->size() >= 4) payload->erase(0, 4);
        return Error::Success;
      }
    }
    return Error("OpenAI requests need a 'payload' BYTES input");
  }

  std::string host_;
  int port_ = 8000;
  std::string endpoint_;
  bool use_tls_ = false;
  SslOptions ssl_;
  std::atomic<int64_t> inflight_{0};
  std::mutex stream_mutex_;
  OnCompleteFn stream_callback_;
};

//==============================================================================
// REST backends for non-Triton inference APIs (parity: the
// reference's torchserve/ and tensorflow_serving/ client backends).
// TorchServe posts the first input's raw bytes to /predictions/<m>
// (torchserve_http_client.cc); TF-Serving uses the REST predict API
// (/v1/models/<m>:predict, columnar "inputs") — same request
// semantics as the reference's gRPC PredictionService
// (tfserve_grpc_client.cc Predict) without vendoring the TF proto
// tree.
//
class RestBackend : public ClientBackend {
 public:
  explicit RestBackend(const BackendConfig& config)
      : kind_(config.kind), use_tls_(config.https), ssl_(config.https_ssl) {
    std::string rest = config.url;
    size_t scheme = rest.find("://");
    if (scheme != std::string::npos) rest = rest.substr(scheme + 3);
    size_t colon = rest.rfind(':');
    if (colon != std::string::npos) {
      port_ = atoi(rest.substr(colon + 1).c_str());
      host_ = rest.substr(0, colon);
    } else {
      host_ = rest;
    }
  }

  ~RestBackend() override {
    while (inflight_.load() > 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }

  Error ServerMetadataJson(json::Value* metadata) override {
    json::Object root;
    root["name"] = json::Value(std::string(
        kind_ == BackendKind::TORCHSERVE ? "torchserve-endpoint"
                                         : "tfserving-endpoint"));
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  // TorchServe exposes no v2 metadata; synthesize the reference shape
  // (one BYTES "data" input; reference ModelParser::InitTorchServe).
  // TF-Serving serves its signature at /v1/models/<m>/metadata — use
  // it when reachable, synthesize otherwise.
  Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string&) override {
    if (kind_ == BackendKind::TFSERVING &&
        FetchTfMetadata(model_name, metadata)) {
      return Error::Success;
    }
    json::Object root;
    root["name"] = json::Value(model_name);
    root["platform"] = json::Value(std::string(
        kind_ == BackendKind::TORCHSERVE ? "torchserve"
                                         : "tensorflow_serving"));
    json::Array inputs;
    json::Object data;
    data["name"] = json::Value(std::string("data"));
    data["datatype"] = json::Value(std::string("BYTES"));
    json::Array shape;
    shape.push_back(json::Value(static_cast<int64_t>(1)));
    data["shape"] = json::Value(std::move(shape));
    inputs.push_back(json::Value(std::move(data)));
    root["inputs"] = json::Value(std::move(inputs));
    root["outputs"] = json::Value(json::Array{});
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string&) override {
    json::Object root;
    root["name"] = json::Value(model_name);
    root["max_batch_size"] = json::Value(static_cast<int64_t>(0));
    *config = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelStatisticsJson(json::Value* stats, const std::string&) override {
    json::Object root;
    root["model_stats"] = json::Value(json::Array{});
    *stats = json::Value(std::move(root));
    return Error::Success;
  }

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>&) override {
    std::string path, body, content_type;
    Error err = BuildRequest(options, inputs, &path, &body, &content_type);
    if (!err.IsOk()) return err;
    *result = PostAndWrap(
        host_, port_, path, content_type, body, options.request_id,
        options.client_timeout_us, use_tls_, ssl_);
    return Error::Success;
  }

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>&) override {
    std::string path, body, content_type;
    Error err = BuildRequest(options, inputs, &path, &body, &content_type);
    if (!err.IsOk()) return err;
    inflight_++;
    std::string id = options.request_id;
    uint64_t timeout_us = options.client_timeout_us;
    std::thread([this, callback = std::move(callback), id,
                 path = std::move(path), body = std::move(body),
                 content_type = std::move(content_type), timeout_us] {
      callback(PostAndWrap(host_, port_, path, content_type, body, id,
                           timeout_us, use_tls_, ssl_));
      inflight_--;
    }).detach();
    return Error::Success;
  }

  Error StartStream(OnCompleteFn) override {
    return Error("streaming is not supported by this backend");
  }
  Error StopStream() override { return Error::Success; }
  Error AsyncStreamInfer(
      const InferOptions&, const std::vector<InferInput*>&,
      const std::vector<const InferRequestedOutput*>&) override {
    return Error("streaming is not supported by this backend");
  }

  Error RegisterSystemSharedMemory(
      const std::string&, const std::string&, size_t, size_t) override {
    return Error("shared memory is not supported by this backend");
  }
  Error RegisterTpuSharedMemory(
      const std::string&, const std::string&, int64_t, size_t) override {
    return Error("shared memory is not supported by this backend");
  }
  Error UnregisterSystemSharedMemory(const std::string&) override {
    return Error::Success;
  }
  Error UnregisterTpuSharedMemory(const std::string&) override {
    return Error::Success;
  }

 private:
  // GET /v1/models/<m>/metadata and translate the serving_default
  // signature into v2-style metadata (parity: the Python twin's
  // TfServingBackend.model_metadata). Returns false when the endpoint
  // is unreachable or unparseable so the caller synthesizes defaults.
  bool FetchTfMetadata(const std::string& model_name, json::Value* out) {
    HttpConnection conn(host_, port_, use_tls_, ssl_);
    HttpResponse response;
    std::string transport_err = conn.Request(
        "GET", "/v1/models/" + model_name + "/metadata", {}, "", &response,
        0);
    if (!transport_err.empty() || response.status_code != 200) return false;
    json::Value doc;
    if (!json::Parse(response.body, &doc).empty()) return false;
    const json::Value& sig =
        doc["metadata"]["signature_def"]["signature_def"]["serving_default"];
    if (!sig.IsObject()) return false;
    json::Object root;
    root["name"] = json::Value(model_name);
    root["platform"] = json::Value(std::string("tensorflow_serving"));
    json::Array inputs, outputs;
    static const std::map<std::string, std::string> kDtypes = {
        {"DT_HALF", "FP16"},     {"DT_BFLOAT16", "BF16"},
        {"DT_FLOAT", "FP32"},    {"DT_DOUBLE", "FP64"},
        {"DT_INT8", "INT8"},     {"DT_INT16", "INT16"},
        {"DT_INT32", "INT32"},   {"DT_INT64", "INT64"},
        {"DT_UINT8", "UINT8"},   {"DT_UINT16", "UINT16"},
        {"DT_UINT32", "UINT32"}, {"DT_UINT64", "UINT64"},
        {"DT_STRING", "BYTES"},  {"DT_BOOL", "BOOL"},
    };
    auto translate = [&](const json::Value& specs, json::Array* dest) {
      if (!specs.IsObject()) return;
      for (const auto& entry : specs.AsObject().entries()) {
        json::Object tensor;
        tensor["name"] = json::Value(entry.first);
        std::string dtype = entry.second["dtype"].IsString()
                                ? entry.second["dtype"].AsString()
                                : "";
        auto it = kDtypes.find(dtype);
        tensor["datatype"] =
            json::Value(it != kDtypes.end() ? it->second
                                            : std::string("FP32"));
        json::Array shape;
        const json::Value& dims = entry.second["tensor_shape"]["dim"];
        if (dims.IsArray()) {
          for (const json::Value& d : dims.AsArray()) {
            int64_t size = -1;
            if (d["size"].IsString()) {
              size = atoll(d["size"].AsString().c_str());
            } else if (d["size"].IsNumber()) {
              size = d["size"].AsInt();
            }
            shape.push_back(json::Value(size));
          }
        }
        if (shape.empty()) shape.push_back(json::Value(int64_t{-1}));
        tensor["shape"] = json::Value(std::move(shape));
        dest->push_back(json::Value(std::move(tensor)));
      }
    };
    translate(sig["inputs"], &inputs);
    translate(sig["outputs"], &outputs);
    if (inputs.empty()) return false;
    root["inputs"] = json::Value(std::move(inputs));
    root["outputs"] = json::Value(std::move(outputs));
    *out = json::Value(std::move(root));
    return true;
  }

  Error BuildRequest(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      std::string* path, std::string* body, std::string* content_type) {
    if (kind_ == BackendKind::TORCHSERVE) {
      *path = "/predictions/" + options.model_name;
      *content_type = "application/octet-stream";
      if (inputs.empty()) return Error("TorchServe requests need an input");
      std::string raw;
      inputs[0]->GatherInto(&raw);
      if (inputs[0]->Datatype() == "BYTES") {
        // Concatenate every length-prefixed element's payload.
        size_t offset = 0;
        while (offset + 4 <= raw.size()) {
          uint32_t len;
          memcpy(&len, raw.data() + offset, 4);
          offset += 4;
          if (offset + len > raw.size()) break;
          body->append(raw, offset, len);
          offset += len;
        }
        if (body->empty()) *body = std::move(raw);
      } else {
        *body = std::move(raw);
      }
      return Error::Success;
    }
    *path = "/v1/models/" + options.model_name;
    if (!options.model_version.empty()) {
      *path += "/versions/" + options.model_version;
    }
    *path += ":predict";
    *content_type = "application/json";
    body->assign("{\"inputs\":{");
    bool first = true;
    for (InferInput* input : inputs) {
      if (!first) body->push_back(',');
      first = false;
      body->push_back('"');
      body->append(input->Name());
      body->append("\":");
      std::string raw;
      input->GatherInto(&raw);
      Error err = AppendJsonTensor(input->Datatype(), raw, body);
      if (!err.IsOk()) return err;
    }
    body->append("}}");
    return Error::Success;
  }

  template <typename T>
  static void AppendNumbers(const std::string& raw, std::string* out) {
    const T* values = reinterpret_cast<const T*>(raw.data());
    size_t count = raw.size() / sizeof(T);
    out->push_back('[');
    char buf[32];
    for (size_t i = 0; i < count; ++i) {
      if (i) out->push_back(',');
      if (std::is_integral<T>::value) {
        // Integers must not round-trip through double (2^53 loss).
        if (std::is_signed<T>::value) {
          snprintf(buf, sizeof(buf), "%lld",
                   static_cast<long long>(values[i]));
        } else {
          snprintf(buf, sizeof(buf), "%llu",
                   static_cast<unsigned long long>(values[i]));
        }
      } else {
        // Shortest round-trippable double representation.
        snprintf(buf, sizeof(buf), "%.17g",
                 static_cast<double>(values[i]));
      }
      out->append(buf);
    }
    out->push_back(']');
  }

  // Flat JSON array from raw tensor bytes (TF-Serving accepts flat
  // lists for the columnar "inputs" format when ranks match server
  // side; nested re-shaping happens server-side).
  static Error AppendJsonTensor(
      const std::string& datatype, const std::string& raw,
      std::string* out) {
    if (datatype == "FP32") {
      AppendNumbers<float>(raw, out);
    } else if (datatype == "FP64") {
      AppendNumbers<double>(raw, out);
    } else if (datatype == "INT64") {
      AppendNumbers<int64_t>(raw, out);
    } else if (datatype == "INT32") {
      AppendNumbers<int32_t>(raw, out);
    } else if (datatype == "INT16") {
      AppendNumbers<int16_t>(raw, out);
    } else if (datatype == "INT8") {
      AppendNumbers<int8_t>(raw, out);
    } else if (datatype == "UINT8") {
      AppendNumbers<uint8_t>(raw, out);
    } else if (datatype == "UINT16") {
      AppendNumbers<uint16_t>(raw, out);
    } else if (datatype == "UINT32") {
      AppendNumbers<uint32_t>(raw, out);
    } else if (datatype == "UINT64") {
      AppendNumbers<uint64_t>(raw, out);
    } else if (datatype == "BOOL") {
      const char* values = raw.data();
      out->push_back('[');
      for (size_t i = 0; i < raw.size(); ++i) {
        if (i) out->push_back(',');
        out->append(values[i] ? "true" : "false");
      }
      out->push_back(']');
    } else if (datatype == "BYTES") {
      // Length-prefixed elements -> JSON strings.
      out->push_back('[');
      size_t offset = 0;
      bool first = true;
      while (offset + 4 <= raw.size()) {
        uint32_t len;
        memcpy(&len, raw.data() + offset, 4);
        offset += 4;
        if (offset + len > raw.size()) break;
        if (!first) out->push_back(',');
        first = false;
        out->append(json::Value(raw.substr(offset, len)).Serialize());
        offset += len;
      }
      out->push_back(']');
    } else {
      return Error("dtype " + datatype +
                   " is not representable in TF-Serving REST JSON");
    }
    return Error::Success;
  }

  BackendKind kind_;
  std::string host_;
  int port_ = 8080;
  bool use_tls_ = false;
  SslOptions ssl_;
  std::atomic<int64_t> inflight_{0};
};

//==============================================================================
// TF-Serving gRPC backend: the PredictionService Predict RPC over the
// library's own HTTP/2 gRPC transport, speaking the compiled
// wire-compatible proto subset (parity: the reference's
// client_backend/tensorflow_serving/tfserve_grpc_client.cc, which
// vendors the full TF proto tree at build time).
//

namespace tfs {

// triton wire dtype <-> tensorflow::DataType (types.proto values).
int TritonToTfDtype(const std::string& datatype) {
  static const std::map<std::string, int> kMap = {
      {"FP16", 19}, {"BF16", 14}, {"FP32", 1},  {"FP64", 2},
      {"INT8", 6},  {"INT16", 5}, {"INT32", 3}, {"INT64", 9},
      {"UINT8", 4}, {"UINT16", 17}, {"UINT32", 22}, {"UINT64", 23},
      {"BYTES", 7}, {"BOOL", 10}};
  auto it = kMap.find(datatype);
  return it != kMap.end() ? it->second : 1;
}

std::string TfToTritonDtype(int dtype) {
  switch (dtype) {
    case 19: return "FP16";
    case 14: return "BF16";
    case 1: return "FP32";
    case 2: return "FP64";
    case 6: return "INT8";
    case 5: return "INT16";
    case 3: return "INT32";
    case 9: return "INT64";
    case 4: return "UINT8";
    case 17: return "UINT16";
    case 22: return "UINT32";
    case 23: return "UINT64";
    case 7: return "BYTES";
    case 10: return "BOOL";
  }
  return "FP32";
}

}  // namespace tfs

class TfsPredictResult : public InferResult {
 public:
  TfsPredictResult(tensorflow::serving::PredictResponse&& response,
                   Error status)
      : status_(std::move(status)) {
    for (const auto& kv : response.outputs()) {
      Output output;
      output.dtype = kv.second.dtype();
      for (const auto& dim : kv.second.tensor_shape().dim()) {
        output.shape.push_back(dim.size());
      }
      if (!kv.second.tensor_content().empty()) {
        output.raw = kv.second.tensor_content();
      } else {
        // Real TF-Serving fills TYPED repeated fields
        // (Tensor::AsProtoField), not tensor_content — pack them into
        // the raw little-endian buffer RawData hands out.
        PackTypedValues(kv.second, &output.raw);
      }
      for (const auto& s : kv.second.string_val()) {
        output.strings.push_back(s);
      }
      outputs_[kv.first] = std::move(output);
    }
  }

  Error ModelName(std::string* name) const override {
    *name = model_name_;
    return Error::Success;
  }
  Error ModelVersion(std::string* version) const override {
    version->clear();
    return Error::Success;
  }
  Error Id(std::string* id) const override {
    id->clear();
    return Error::Success;
  }
  Error Shape(const std::string& output_name,
              std::vector<int64_t>* shape) const override {
    auto it = outputs_.find(output_name);
    if (it == outputs_.end()) return Error("no output " + output_name);
    *shape = it->second.shape;
    return Error::Success;
  }
  Error Datatype(const std::string& output_name,
                 std::string* datatype) const override {
    auto it = outputs_.find(output_name);
    if (it == outputs_.end()) return Error("no output " + output_name);
    *datatype = tfs::TfToTritonDtype(it->second.dtype);
    return Error::Success;
  }
  Error RawData(const std::string& output_name, const uint8_t** buf,
                size_t* byte_size) const override {
    auto it = outputs_.find(output_name);
    if (it == outputs_.end()) return Error("no output " + output_name);
    *buf = reinterpret_cast<const uint8_t*>(it->second.raw.data());
    *byte_size = it->second.raw.size();
    return Error::Success;
  }
  Error StringData(const std::string& output_name,
                   std::vector<std::string>* string_result) const override {
    auto it = outputs_.find(output_name);
    if (it == outputs_.end()) return Error("no output " + output_name);
    *string_result = it->second.strings;
    return Error::Success;
  }
  std::string DebugString() const override { return "TfsPredictResult"; }
  Error RequestStatus() const override { return status_; }

 private:
  struct Output {
    int dtype = 0;
    std::vector<int64_t> shape;
    std::string raw;
    std::vector<std::string> strings;
  };

  template <typename Repeated, typename Wire>
  static void AppendAs(const Repeated& values, std::string* raw) {
    for (const auto& value : values) {
      Wire wire = static_cast<Wire>(value);
      raw->append(reinterpret_cast<const char*>(&wire), sizeof(wire));
    }
  }

  static void PackTypedValues(
      const tensorflow::TensorProto& tensor, std::string* raw) {
    switch (tensor.dtype()) {
      case tensorflow::DT_FLOAT:
        AppendAs<decltype(tensor.float_val()), float>(
            tensor.float_val(), raw);
        break;
      case tensorflow::DT_DOUBLE:
        AppendAs<decltype(tensor.double_val()), double>(
            tensor.double_val(), raw);
        break;
      case tensorflow::DT_INT8:
        AppendAs<decltype(tensor.int_val()), int8_t>(tensor.int_val(), raw);
        break;
      case tensorflow::DT_INT16:
        AppendAs<decltype(tensor.int_val()), int16_t>(tensor.int_val(), raw);
        break;
      case tensorflow::DT_INT32:
        AppendAs<decltype(tensor.int_val()), int32_t>(tensor.int_val(), raw);
        break;
      case tensorflow::DT_UINT8:
        AppendAs<decltype(tensor.int_val()), uint8_t>(tensor.int_val(), raw);
        break;
      case tensorflow::DT_UINT16:
        AppendAs<decltype(tensor.int_val()), uint16_t>(
            tensor.int_val(), raw);
        break;
      case tensorflow::DT_INT64:
        AppendAs<decltype(tensor.int64_val()), int64_t>(
            tensor.int64_val(), raw);
        break;
      case tensorflow::DT_BOOL:
        AppendAs<decltype(tensor.bool_val()), uint8_t>(
            tensor.bool_val(), raw);
        break;
      case tensorflow::DT_UINT32:
        AppendAs<decltype(tensor.uint32_val()), uint32_t>(
            tensor.uint32_val(), raw);
        break;
      case tensorflow::DT_UINT64:
        AppendAs<decltype(tensor.uint64_val()), uint64_t>(
            tensor.uint64_val(), raw);
        break;
      case tensorflow::DT_HALF:
      case tensorflow::DT_BFLOAT16:
        // half_val holds raw 16-bit patterns widened to int32.
        AppendAs<decltype(tensor.half_val()), uint16_t>(
            tensor.half_val(), raw);
        break;
      default:
        break;  // DT_STRING rides string_val; others unsupported
    }
  }

  Error status_;
  std::string model_name_;
  std::map<std::string, Output> outputs_;
};

class TfServingGrpcBackend : public ClientBackend {
 public:
  static Error Create(
      const BackendConfig& config, std::unique_ptr<ClientBackend>* backend) {
    auto b = std::unique_ptr<TfServingGrpcBackend>(
        new TfServingGrpcBackend());
    b->signature_name_ = config.model_signature_name;
    Error err = GrpcChannel::Create(&b->channel_, config.url);
    if (!err.IsOk()) return err;
    *backend = std::move(b);
    return Error::Success;
  }

  Error ServerMetadataJson(json::Value* metadata) override {
    json::Object root;
    root["name"] = json::Value(std::string("tfserving-endpoint"));
    root["protocol"] = json::Value(std::string("grpc"));
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  // TF-Serving's gRPC surface has no KServe metadata; shapes come
  // from --shape overrides (reference behavior for this kind).
  Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string&) override {
    json::Object root;
    root["name"] = json::Value(model_name);
    root["platform"] = json::Value(std::string("tensorflow_serving"));
    root["inputs"] = json::Value(json::Array{});
    root["outputs"] = json::Value(json::Array{});
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string&) override {
    json::Object root;
    root["name"] = json::Value(model_name);
    *config = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelStatisticsJson(json::Value* stats, const std::string&) override {
    json::Object root;
    root["model_stats"] = json::Value(json::Array{});
    *stats = json::Value(std::move(root));
    return Error::Success;
  }

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    std::string request_bytes;
    Error err = BuildRequest(options, inputs, &request_bytes);
    if (!err.IsOk()) return err;
    std::string response_bytes;
    err = channel_->UnaryCall(
        "/tensorflow.serving.PredictionService/Predict", request_bytes,
        &response_bytes, options.client_timeout_us);
    tensorflow::serving::PredictResponse response;
    if (err.IsOk() && !response.ParseFromString(response_bytes)) {
      err = Error("failed to parse PredictResponse");
    }
    // Sync-caller contract: *result only on success (error-status
    // results are the ASYNC path's convention; sync callers skip
    // delete on a non-OK return).
    if (!err.IsOk()) return err;
    *result = new TfsPredictResult(std::move(response), Error::Success);
    return Error::Success;
  }

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) override {
    std::string request_bytes;
    Error err = BuildRequest(options, inputs, &request_bytes);
    if (!err.IsOk()) return err;
    return channel_->AsyncUnaryCall(
        "/tensorflow.serving.PredictionService/Predict", request_bytes,
        [callback](const Error& status, std::string&& response_bytes,
                   const RequestTimers&) {
          tensorflow::serving::PredictResponse response;
          Error final_status = status;
          if (final_status.IsOk() &&
              !response.ParseFromString(response_bytes)) {
            final_status = Error("failed to parse PredictResponse");
          }
          callback(new TfsPredictResult(std::move(response), final_status));
        },
        options.client_timeout_us);
  }

  Error StartStream(OnCompleteFn) override {
    return Error("tfserving backend does not support streaming");
  }
  Error StopStream() override {
    return Error("tfserving backend does not support streaming");
  }
  Error AsyncStreamInfer(
      const InferOptions&, const std::vector<InferInput*>&,
      const std::vector<const InferRequestedOutput*>&) override {
    return Error("tfserving backend does not support streaming");
  }
  Error RegisterSystemSharedMemory(
      const std::string&, const std::string&, size_t, size_t) override {
    return Error("tfserving backend does not support shared memory");
  }
  Error RegisterTpuSharedMemory(
      const std::string&, const std::string&, int64_t, size_t) override {
    return Error("tfserving backend does not support shared memory");
  }
  Error UnregisterSystemSharedMemory(const std::string&) override {
    return Error::Success;
  }
  Error UnregisterTpuSharedMemory(const std::string&) override {
    return Error::Success;
  }

 private:
  TfServingGrpcBackend() = default;

  Error BuildRequest(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      std::string* request_bytes) {
    tensorflow::serving::PredictRequest request;
    request.mutable_model_spec()->set_name(options.model_name);
    if (!signature_name_.empty() &&
        signature_name_ != "serving_default") {
      request.mutable_model_spec()->set_signature_name(signature_name_);
    }
    if (!options.model_version.empty()) {
      request.mutable_model_spec()->mutable_version()->set_value(
          strtoll(options.model_version.c_str(), nullptr, 10));
    }
    for (InferInput* input : inputs) {
      if (input->IsSharedMemory()) {
        return Error("tfserving backend does not support shared memory");
      }
      auto& tensor = (*request.mutable_inputs())[input->Name()];
      tensor.set_dtype(
          static_cast<tensorflow::DataType>(
              tfs::TritonToTfDtype(input->Datatype())));
      for (int64_t dim : input->Shape()) {
        tensor.mutable_tensor_shape()->add_dim()->set_size(dim);
      }
      // Collect this input's raw bytes.
      std::string payload;
      payload.reserve(input->TotalSendByteSize());
      input->PrepareForRequest();
      const uint8_t* buf;
      size_t chunk;
      while (input->GetNext(&buf, &chunk)) {
        payload.append(reinterpret_cast<const char*>(buf), chunk);
      }
      if (input->Datatype() == "BYTES") {
        // Our wire BYTES (u32-length-prefixed) -> string_val entries.
        size_t offset = 0;
        while (offset + 4 <= payload.size()) {
          uint32_t len;
          memcpy(&len, payload.data() + offset, 4);
          offset += 4;
          if (offset + len > payload.size()) {
            return Error("malformed BYTES payload for input '" +
                         input->Name() + "'");
          }
          tensor.add_string_val(payload.substr(offset, len));
          offset += len;
        }
      } else {
        tensor.set_tensor_content(std::move(payload));
      }
    }
    if (!request.SerializeToString(request_bytes)) {
      return Error("failed to serialize PredictRequest");
    }
    return Error::Success;
  }

  std::shared_ptr<GrpcChannel> channel_;
  std::string signature_name_;
};

//==============================================================================
// Mock backend: a fake server with programmable delay, used by the
// harness unit tests (parity: NaggyMockClientBackend firing async
// callbacks from detached threads, mock_client_backend.h:617-625).
//
std::shared_ptr<MockBackendStats> g_mock_stats =
    std::make_shared<MockBackendStats>();

class MockInferResult : public InferResult {
 public:
  explicit MockInferResult(const Error& status, std::string id = "",
                           bool final_response = true)
      : status_(status), id_(std::move(id)), data_(64, '\0'),
        final_(final_response) {}

  bool IsFinalResponse() const { return final_; }

  Error ModelName(std::string* name) const override {
    *name = "mock";
    return Error::Success;
  }
  Error ModelVersion(std::string* version) const override {
    *version = "1";
    return Error::Success;
  }
  Error Id(std::string* id) const override {
    *id = id_;
    return Error::Success;
  }
  Error Shape(
      const std::string&, std::vector<int64_t>* shape) const override {
    *shape = {16};
    return Error::Success;
  }
  Error Datatype(const std::string&, std::string* datatype) const override {
    *datatype = "INT32";
    return Error::Success;
  }
  Error RawData(
      const std::string&, const uint8_t** buf,
      size_t* byte_size) const override {
    *buf = reinterpret_cast<const uint8_t*>(data_.data());
    *byte_size = data_.size();
    return Error::Success;
  }
  Error StringData(
      const std::string&, std::vector<std::string>*) const override {
    return Error("mock outputs are not BYTES");
  }
  std::string DebugString() const override { return "MockInferResult"; }
  Error RequestStatus() const override { return status_; }

 private:
  Error status_;
  std::string id_;
  std::string data_;
  bool final_;
};

class MockBackend : public ClientBackend {
 public:
  explicit MockBackend(const BackendConfig& config)
      : delay_us_(config.mock_delay_us), error_rate_(config.mock_error_rate),
        responses_per_request_(
            config.mock_responses_per_request > 0
                ? config.mock_responses_per_request
                : 1) {}

  ~MockBackend() override {
    StopStream();
    // Wait for detached completion threads.
    while (inflight_.load() > 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }

  Error ServerMetadataJson(json::Value* metadata) override {
    json::Object root;
    root["name"] = json::Value(std::string("mock-server"));
    root["version"] = json::Value(std::string("1.0"));
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string&) override {
    json::Object root;
    root["name"] = json::Value(model_name);
    root["platform"] = json::Value(std::string("mock"));
    auto tensor = [](const char* name) {
      json::Object t;
      t["name"] = json::Value(std::string(name));
      t["datatype"] = json::Value(std::string("INT32"));
      json::Array shape;
      shape.push_back(json::Value(static_cast<int64_t>(16)));
      t["shape"] = json::Value(std::move(shape));
      return json::Value(std::move(t));
    };
    json::Array inputs;
    inputs.push_back(tensor("INPUT0"));
    inputs.push_back(tensor("INPUT1"));
    root["inputs"] = json::Value(std::move(inputs));
    json::Array outputs;
    outputs.push_back(tensor("OUTPUT0"));
    outputs.push_back(tensor("OUTPUT1"));
    root["outputs"] = json::Value(std::move(outputs));
    *metadata = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string&) override {
    json::Object root;
    root["name"] = json::Value(model_name);
    // Composing-model fixtures: "ensemble_top" -> "ensemble_mid" ->
    // "seq_leaf" exercises the parser's recursive resolution.
    if (model_name == "ensemble_top" || model_name == "ensemble_mid") {
      std::string child =
          model_name == "ensemble_top" ? "ensemble_mid" : "seq_leaf";
      json::Object step;
      step["model_name"] = json::Value(child);
      json::Array steps;
      steps.push_back(json::Value(std::move(step)));
      json::Object scheduling;
      scheduling["step"] = json::Value(std::move(steps));
      root["ensemble_scheduling"] = json::Value(std::move(scheduling));
    } else if (model_name == "seq_leaf") {
      root["sequence_batching"] = json::Value(json::Object{});
    } else if (model_name == "shape_mock") {
      // Shape-tensor fixture: INPUT1's values describe shapes
      // (config input.is_shape_tensor), INPUT0 is ordinary batched
      // data — exercises the parser flag + the data manager's
      // no-replication semantics.
      root["max_batch_size"] = json::Value(static_cast<int64_t>(8));
      json::Array inputs;
      json::Object in1;
      in1["name"] = json::Value(std::string("INPUT1"));
      in1["is_shape_tensor"] = json::Value(true);
      inputs.push_back(json::Value(std::move(in1)));
      root["input"] = json::Value(std::move(inputs));
    } else {
      root["max_batch_size"] = json::Value(static_cast<int64_t>(8));
    }
    *config = json::Value(std::move(root));
    return Error::Success;
  }

  Error ModelStatisticsJson(
      json::Value* stats, const std::string&) override {
    json::Object root;
    root["model_stats"] = json::Value(json::Array{});
    *stats = json::Value(std::move(root));
    return Error::Success;
  }

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>&,
      const std::vector<const InferRequestedOutput*>&) override {
    g_mock_stats->infer_calls++;
    if (delay_us_ > 0) {
      std::this_thread::sleep_for(std::chrono::microseconds(delay_us_));
    }
    Error status = MaybeError();
    g_mock_stats->completed++;
    if (!status.IsOk()) {
      g_mock_stats->errors++;
      return status;
    }
    *result = new MockInferResult(status, options.request_id);
    return Error::Success;
  }

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>&,
      const std::vector<const InferRequestedOutput*>&) override {
    g_mock_stats->async_infer_calls++;
    inflight_++;
    std::string id = options.request_id;
    std::thread([this, callback = std::move(callback), id] {
      if (delay_us_ > 0) {
        std::this_thread::sleep_for(std::chrono::microseconds(delay_us_));
      }
      Error status = MaybeError();
      g_mock_stats->completed++;
      if (!status.IsOk()) g_mock_stats->errors++;
      callback(new MockInferResult(status, id));
      inflight_--;
    }).detach();
    return Error::Success;
  }

  Error StartStream(OnCompleteFn callback) override {
    std::lock_guard<std::mutex> lock(stream_mutex_);
    stream_callback_ = std::move(callback);
    return Error::Success;
  }
  Error StopStream() override {
    std::lock_guard<std::mutex> lock(stream_mutex_);
    stream_callback_ = nullptr;
    return Error::Success;
  }
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>&,
      const std::vector<const InferRequestedOutput*>&) override {
    g_mock_stats->stream_infer_calls++;
    OnCompleteFn callback;
    {
      std::lock_guard<std::mutex> lock(stream_mutex_);
      callback = stream_callback_;
    }
    if (!callback) return Error("stream not started");
    inflight_++;
    std::string id = options.request_id;
    std::thread([this, callback = std::move(callback), id] {
      // Decoupled simulation: n-1 non-final responses then the final
      // one; the per-response delay spreads the timestamps so tests
      // can assert ordering.
      for (uint64_t i = 0; i + 1 < responses_per_request_; ++i) {
        if (delay_us_ > 0) {
          std::this_thread::sleep_for(std::chrono::microseconds(delay_us_));
        }
        callback(new MockInferResult(Error::Success, id,
                                     /*final_response=*/false));
      }
      if (delay_us_ > 0) {
        std::this_thread::sleep_for(std::chrono::microseconds(delay_us_));
      }
      g_mock_stats->completed++;
      callback(new MockInferResult(Error::Success, id));
      inflight_--;
    }).detach();
    return Error::Success;
  }

  Error RegisterSystemSharedMemory(
      const std::string&, const std::string&, size_t, size_t) override {
    return Error::Success;
  }
  Error RegisterTpuSharedMemory(
      const std::string&, const std::string&, int64_t, size_t) override {
    return Error::Success;
  }
  Error UnregisterSystemSharedMemory(const std::string&) override {
    return Error::Success;
  }
  Error UnregisterTpuSharedMemory(const std::string&) override {
    return Error::Success;
  }

 private:
  Error MaybeError() {
    if (error_rate_ > 0.0) {
      thread_local std::mt19937 rng(std::random_device{}());
      std::uniform_real_distribution<double> dist(0.0, 1.0);
      if (dist(rng) < error_rate_) return Error("mock injected failure");
    }
    return Error::Success;
  }

  uint64_t delay_us_;
  double error_rate_;
  uint64_t responses_per_request_;
  std::atomic<int64_t> inflight_{0};
  std::mutex stream_mutex_;
  OnCompleteFn stream_callback_;
};

}  // namespace

bool IsFinalStreamResponse(const InferResult* result) {
  const auto* grpc_result = dynamic_cast<const InferResultGrpc*>(result);
  if (grpc_result != nullptr) return grpc_result->IsFinalResponse();
  const auto* openai_result = dynamic_cast<const OpenAiInferResult*>(result);
  if (openai_result != nullptr) return openai_result->IsFinalResponse();
  const auto* mock_result = dynamic_cast<const MockInferResult*>(result);
  if (mock_result != nullptr) return mock_result->IsFinalResponse();
  return true;
}

std::shared_ptr<MockBackendStats> GetMockBackendStats() {
  return g_mock_stats;
}

void ResetMockBackendStats() {
  g_mock_stats->infer_calls = 0;
  g_mock_stats->async_infer_calls = 0;
  g_mock_stats->stream_infer_calls = 0;
  g_mock_stats->completed = 0;
  g_mock_stats->errors = 0;
}

Error ClientBackendFactory::Create(
    std::unique_ptr<ClientBackend>* backend) const {
  switch (config_.kind) {
    case BackendKind::TRITON_GRPC:
      return GrpcBackend::Create(config_, backend);
    case BackendKind::TRITON_HTTP:
      return HttpBackend::Create(config_, backend);
    case BackendKind::OPENAI:
      backend->reset(new OpenAiBackend(config_));
      return Error::Success;
    case BackendKind::TORCHSERVE:
      backend->reset(new RestBackend(config_));
      return Error::Success;
    case BackendKind::TFSERVING:
      if (config_.tfserving_grpc) {
        return TfServingGrpcBackend::Create(config_, backend);
      }
      backend->reset(new RestBackend(config_));
      return Error::Success;
    case BackendKind::MOCK:
      backend->reset(new MockBackend(config_));
      return Error::Success;
    case BackendKind::IN_PROCESS:
#ifdef TPUCLIENT_HAVE_PYTHON
      return InProcessBackend::Create(config_, backend);
#else
      return Error(
          "this build has no embedded-CPython support "
          "(in_process backend unavailable)");
#endif
  }
  return Error("unknown backend kind");
}

//==============================================================================
// TpuArenaClient

Error TpuArenaClient::Create(
    std::unique_ptr<TpuArenaClient>* client, const std::string& url) {
  auto c = std::unique_ptr<TpuArenaClient>(new TpuArenaClient());
  Error err = GrpcChannel::Create(&c->channel_, url);
  if (!err.IsOk()) return err;
  *client = std::move(c);
  return Error::Success;
}

TpuArenaClient::~TpuArenaClient() = default;

namespace {

template <typename Req, typename Resp>
Error ArenaRpc(
    const std::shared_ptr<GrpcChannel>& channel, const char* method,
    const Req& req, Resp* resp) {
  std::string request_bytes, response_bytes;
  if (!req.SerializeToString(&request_bytes)) {
    return Error("failed to serialize arena request");
  }
  Error err = channel->UnaryCall(
      std::string("/inference.TpuArenaService/") + method, request_bytes,
      &response_bytes);
  if (!err.IsOk()) return err;
  if (!resp->ParseFromString(response_bytes)) {
    return Error("failed to parse arena response");
  }
  return Error::Success;
}

}  // namespace

Error TpuArenaClient::CreateRegion(
    size_t byte_size, int64_t device_id, std::string* raw_handle,
    std::string* region_id) {
  inference::CreateRegionRequest req;
  req.set_byte_size(byte_size);
  req.set_device_id(device_id);
  inference::CreateRegionResponse resp;
  Error err = ArenaRpc(channel_, "CreateRegion", req, &resp);
  if (!err.IsOk()) return err;
  *raw_handle = resp.raw_handle();
  *region_id = resp.region_id();
  return Error::Success;
}

Error TpuArenaClient::WriteRegion(
    const std::string& region_id, size_t offset, const std::string& data,
    const std::string& datatype, const std::vector<int64_t>& shape) {
  inference::WriteRegionRequest req;
  req.set_region_id(region_id);
  req.set_offset(offset);
  req.set_data(data);
  req.set_datatype(datatype);
  for (int64_t d : shape) req.add_shape(d);
  inference::WriteRegionResponse resp;
  return ArenaRpc(channel_, "WriteRegion", req, &resp);
}

Error TpuArenaClient::ReadRegion(
    const std::string& region_id, size_t offset, size_t byte_size,
    std::string* data) {
  inference::ReadRegionRequest req;
  req.set_region_id(region_id);
  req.set_offset(offset);
  req.set_byte_size(byte_size);
  inference::ReadRegionResponse resp;
  Error err = ArenaRpc(channel_, "ReadRegion", req, &resp);
  if (!err.IsOk()) return err;
  *data = resp.data();
  return Error::Success;
}

Error TpuArenaClient::DestroyRegion(const std::string& region_id) {
  inference::DestroyRegionRequest req;
  req.set_region_id(region_id);
  inference::DestroyRegionResponse resp;
  return ArenaRpc(channel_, "DestroyRegion", req, &resp);
}

}  // namespace perf
}  // namespace tpuclient
