// Input data source for the native perf harness (parity:
// /root/reference/src/c++/perf_analyzer/data_loader.h:63-99 —
// random/zero generation, JSON data files with b64 content and
// multi-stream steps).
#pragma once

#include <map>
#include <string>
#include <vector>

#include "../library/common.h"
#include "model_parser.h"

namespace tpuclient {
namespace perf {

// One concrete tensor value for a (stream, step). BYTES tensors are
// stored pre-serialized (4-byte-LE length-prefixed).
struct TensorData {
  std::string bytes;
  std::string datatype;
  std::vector<int64_t> shape;
};

// Streams model the reference's sequence data-streams; non-sequence
// runs use stream 0 and cycle through steps.
class DataLoader {
 public:
  explicit DataLoader(const ParsedModel* model) : model_(model) {}

  size_t stream_count() const { return data_.size(); }
  size_t step_count(size_t stream = 0) const {
    return stream < data_.size() ? data_[stream].size() : 0;
  }

  Error GetInputData(
      const std::string& input_name, size_t stream, size_t step,
      const TensorData** data) const;

  // Random (or zero) data for every input (parity: GenerateData
  // data_loader.h:89). Dynamic dims resolve to 1.
  Error GenerateData(
      bool zero_input = false, size_t string_length = 16,
      const std::string& string_data = "", uint64_t seed = 7,
      size_t steps = 1);

  // Reads the reference's JSON input format: {"data": [step, ...]} or
  // {"data": [[stream0 steps], ...]}; each step maps input name ->
  // list | {"content": ..} | {"b64": ..} with optional "shape"
  // (parity: ReadDataFromJSON data_loader.h:74).
  Error ReadDataFromJson(const std::string& path);
  Error ReadDataFromJsonText(const std::string& text);

  // Directory input: one file per input named after the input
  // (parity: ReadDataFromDir data_loader.cc:42 — single stream/step;
  // non-BYTES files are raw binary matching the tensor byte size,
  // BYTES files are text with one string element per line).
  Error ReadDataFromDir(const std::string& directory);

 private:
  Error ParseValue(
      const ModelTensor& tensor, const json::Value& value, TensorData* out);
  Error Validate() const;

  const ParsedModel* model_;
  // stream -> step -> {input name -> data}
  std::vector<std::vector<std::map<std::string, TensorData>>> data_;
};

}  // namespace perf
}  // namespace tpuclient
