#include "data_loader.h"

#include <cstring>
#include <fstream>
#include <random>
#include <sstream>

#include "../library/base64.h"

namespace tpuclient {
namespace perf {

namespace {

int64_t ElementCount(const std::vector<int64_t>& shape) {
  int64_t count = 1;
  for (int64_t d : shape) count *= (d < 0 ? 1 : d);
  return count;
}

std::vector<int64_t> ResolveShape(const std::vector<int64_t>& shape) {
  std::vector<int64_t> out;
  for (int64_t d : shape) out.push_back(d < 0 ? 1 : d);
  return out;
}

// Serializes one BYTES element with its 4-byte LE length prefix.
void AppendBytesElement(const std::string& value, std::string* out) {
  uint32_t len = static_cast<uint32_t>(value.size());
  out->append(reinterpret_cast<const char*>(&len), 4);
  out->append(value);
}

template <typename T>
void AppendScalar(T value, std::string* out) {
  out->append(reinterpret_cast<const char*>(&value), sizeof(T));
}

// Converts an IEEE float to bfloat16 (truncating round) / fp16.
uint16_t FloatToBf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, 4);
  return static_cast<uint16_t>(bits >> 16);
}

uint16_t FloatToFp16(float f) {
  // Good-enough conversion for generated benchmark data (no denormal
  // care needed for values in [0,1)).
  uint32_t bits;
  memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xff) - 127 + 15;
  uint32_t mant = (bits >> 13) & 0x3ff;
  if (exp <= 0) return static_cast<uint16_t>(sign);
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00);
  return static_cast<uint16_t>(sign | (exp << 10) | mant);
}

// Appends one random element of `datatype` to out.
void AppendRandomElement(
    const std::string& datatype, std::mt19937_64* rng, std::string* out) {
  std::uniform_real_distribution<double> real(0.0, 1.0);
  std::uniform_int_distribution<int64_t> small_int(-(1 << 20), 1 << 20);
  std::uniform_int_distribution<int64_t> unsigned_int(0, 1 << 20);
  if (datatype == "FP32") {
    AppendScalar(static_cast<float>(real(*rng)), out);
  } else if (datatype == "FP64") {
    AppendScalar(real(*rng), out);
  } else if (datatype == "FP16") {
    AppendScalar(FloatToFp16(static_cast<float>(real(*rng))), out);
  } else if (datatype == "BF16") {
    AppendScalar(FloatToBf16(static_cast<float>(real(*rng))), out);
  } else if (datatype == "BOOL") {
    AppendScalar(static_cast<uint8_t>((*rng)() & 1), out);
  } else if (datatype == "INT8") {
    AppendScalar(static_cast<int8_t>(small_int(*rng)), out);
  } else if (datatype == "INT16") {
    AppendScalar(static_cast<int16_t>(small_int(*rng)), out);
  } else if (datatype == "INT32") {
    AppendScalar(static_cast<int32_t>(small_int(*rng)), out);
  } else if (datatype == "INT64") {
    AppendScalar(small_int(*rng), out);
  } else if (datatype == "UINT8") {
    AppendScalar(static_cast<uint8_t>(unsigned_int(*rng)), out);
  } else if (datatype == "UINT16") {
    AppendScalar(static_cast<uint16_t>(unsigned_int(*rng)), out);
  } else if (datatype == "UINT32") {
    AppendScalar(static_cast<uint32_t>(unsigned_int(*rng)), out);
  } else if (datatype == "UINT64") {
    AppendScalar(static_cast<uint64_t>(unsigned_int(*rng)), out);
  }
}

}  // namespace

Error DataLoader::GetInputData(
    const std::string& input_name, size_t stream, size_t step,
    const TensorData** data) const {
  if (stream >= data_.size() || step >= data_[stream].size()) {
    return Error(
        "no data for stream " + std::to_string(stream) + " step " +
        std::to_string(step));
  }
  auto it = data_[stream][step].find(input_name);
  if (it == data_[stream][step].end()) {
    return Error("no data for input '" + input_name + "'");
  }
  *data = &it->second;
  return Error::Success;
}

Error DataLoader::GenerateData(
    bool zero_input, size_t string_length, const std::string& string_data,
    uint64_t seed, size_t steps) {
  std::mt19937_64 rng(seed);
  data_.clear();
  data_.emplace_back();
  auto& stream = data_.back();
  for (size_t s = 0; s < steps; ++s) {
    stream.emplace_back();
    auto& step_data = stream.back();
    for (const auto& tensor : model_->inputs) {
      TensorData data;
      data.datatype = tensor.datatype;
      data.shape = ResolveShape(tensor.shape);
      int64_t count = ElementCount(data.shape);
      if (tensor.datatype == "BYTES") {
        for (int64_t i = 0; i < count; ++i) {
          std::string value;
          if (!string_data.empty()) {
            value = string_data;
          } else {
            for (size_t c = 0; c < string_length; ++c) {
              value.push_back(static_cast<char>('a' + (rng() % 26)));
            }
          }
          AppendBytesElement(value, &data.bytes);
        }
      } else {
        size_t elem = DatatypeByteSize(tensor.datatype);
        if (elem == 0) {
          return Error(
              "cannot generate data for datatype " + tensor.datatype);
        }
        if (zero_input) {
          data.bytes.assign(count * elem, '\0');
        } else {
          data.bytes.reserve(count * elem);
          for (int64_t i = 0; i < count; ++i) {
            AppendRandomElement(tensor.datatype, &rng, &data.bytes);
          }
        }
      }
      step_data.emplace(tensor.name, std::move(data));
    }
  }
  return Error::Success;
}

Error DataLoader::ReadDataFromDir(const std::string& directory) {
  std::vector<std::map<std::string, TensorData>> stream(1);
  std::map<std::string, TensorData>& step = stream[0];
  for (const ModelTensor& tensor : model_->inputs) {
    const std::string path = directory + "/" + tensor.name;
    std::ifstream file(path, std::ios::binary);
    if (!file) {
      if (tensor.optional) continue;
      return Error("no file for input '" + tensor.name + "' in " +
                   directory);
    }
    TensorData data;
    data.datatype = tensor.datatype;
    data.shape = ResolveShape(tensor.shape);
    int64_t count = ElementCount(data.shape);
    if (tensor.datatype == "BYTES") {
      std::string line;
      int64_t lines = 0;
      while (std::getline(file, line)) {
        AppendBytesElement(line, &data.bytes);
        ++lines;
      }
      if (lines != count) {
        return Error(
            "input '" + tensor.name + "': " + std::to_string(lines) +
            " strings in file, shape wants " + std::to_string(count));
      }
    } else {
      std::stringstream buffer;
      buffer << file.rdbuf();
      data.bytes = buffer.str();
      size_t expected = count * DatatypeByteSize(tensor.datatype);
      if (data.bytes.size() != expected) {
        return Error(
            "input '" + tensor.name + "' file has " +
            std::to_string(data.bytes.size()) + " bytes, expected " +
            std::to_string(expected));
      }
    }
    step[tensor.name] = std::move(data);
  }
  data_.clear();
  data_.push_back(std::move(stream));
  return Validate();
}

Error DataLoader::ReadDataFromJson(const std::string& path) {
  std::ifstream in(path);
  if (!in) return Error("cannot open input data file '" + path + "'");
  std::stringstream buffer;
  buffer << in.rdbuf();
  return ReadDataFromJsonText(buffer.str());
}

Error DataLoader::ReadDataFromJsonText(const std::string& text) {
  json::Value doc;
  std::string parse_err = json::Parse(text, &doc);
  if (!parse_err.empty()) {
    return Error("bad input data JSON: " + parse_err);
  }
  try {
    if (!doc.Has("data")) return Error("input JSON missing 'data' array");
    const json::Array& data = doc["data"].AsArray();
    // One stream of steps, or an array of streams.
    std::vector<const json::Array*> streams;
    if (!data.empty() && data[0].IsArray()) {
      for (const auto& s : data) streams.push_back(&s.AsArray());
    } else {
      streams.push_back(&data);
    }
    data_.clear();
    for (const json::Array* stream : streams) {
      data_.emplace_back();
      auto& steps = data_.back();
      for (const auto& step : *stream) {
        steps.emplace_back();
        auto& step_data = steps.back();
        for (const auto& kv : step.AsObject().entries()) {
          const ModelTensor* tensor = model_->FindInput(kv.first);
          if (tensor == nullptr) {
            return Error(
                "input '" + kv.first + "' in data JSON is not a model input");
          }
          TensorData parsed;
          Error err = ParseValue(*tensor, kv.second, &parsed);
          if (!err.IsOk()) return err;
          step_data.emplace(kv.first, std::move(parsed));
        }
      }
    }
  } catch (const std::exception& e) {
    return Error(std::string("malformed input data JSON: ") + e.what());
  }
  return Validate();
}

Error DataLoader::ParseValue(
    const ModelTensor& tensor, const json::Value& value, TensorData* out) {
  out->datatype = tensor.datatype;
  const json::Value* content = &value;
  if (value.IsObject()) {
    if (value.Has("shape")) {
      out->shape.clear();
      for (const auto& d : value["shape"].AsArray()) {
        out->shape.push_back(d.AsInt());
      }
    }
    if (value.Has("b64")) {
      if (!Base64Decode(value["b64"].AsString(), &out->bytes)) {
        return Error("bad b64 content for input '" + tensor.name + "'");
      }
      if (out->shape.empty()) out->shape = ResolveShape(tensor.shape);
      return Error::Success;
    }
    if (!value.Has("content")) {
      return Error(
          "input '" + tensor.name + "' object needs 'content' or 'b64'");
    }
    content = &value["content"];
  }
  const json::Array& flat = content->AsArray();
  if (out->shape.empty()) {
    if (!tensor.shape.empty() &&
        std::find(tensor.shape.begin(), tensor.shape.end(), -1) ==
            tensor.shape.end()) {
      out->shape = tensor.shape;
    } else {
      out->shape = {static_cast<int64_t>(flat.size())};
    }
  }
  if (tensor.datatype == "BYTES") {
    for (const auto& v : flat) {
      // Structured elements (e.g. OpenAI payload objects) ride as
      // their JSON serialization.
      if (v.IsObject() || v.IsArray()) {
        AppendBytesElement(v.Serialize(), &out->bytes);
      } else {
        AppendBytesElement(v.AsString(), &out->bytes);
      }
    }
    return Error::Success;
  }
  for (const auto& v : flat) {
    if (tensor.datatype == "FP32") {
      AppendScalar(static_cast<float>(v.AsDouble()), &out->bytes);
    } else if (tensor.datatype == "FP64") {
      AppendScalar(v.AsDouble(), &out->bytes);
    } else if (tensor.datatype == "FP16") {
      AppendScalar(FloatToFp16(static_cast<float>(v.AsDouble())), &out->bytes);
    } else if (tensor.datatype == "BF16") {
      AppendScalar(FloatToBf16(static_cast<float>(v.AsDouble())), &out->bytes);
    } else if (tensor.datatype == "BOOL") {
      AppendScalar(static_cast<uint8_t>(v.AsBool() ? 1 : 0), &out->bytes);
    } else if (tensor.datatype == "INT8") {
      AppendScalar(static_cast<int8_t>(v.AsInt()), &out->bytes);
    } else if (tensor.datatype == "INT16") {
      AppendScalar(static_cast<int16_t>(v.AsInt()), &out->bytes);
    } else if (tensor.datatype == "INT32") {
      AppendScalar(static_cast<int32_t>(v.AsInt()), &out->bytes);
    } else if (tensor.datatype == "INT64") {
      AppendScalar(v.AsInt(), &out->bytes);
    } else if (tensor.datatype == "UINT8") {
      AppendScalar(static_cast<uint8_t>(v.AsUint()), &out->bytes);
    } else if (tensor.datatype == "UINT16") {
      AppendScalar(static_cast<uint16_t>(v.AsUint()), &out->bytes);
    } else if (tensor.datatype == "UINT32") {
      AppendScalar(static_cast<uint32_t>(v.AsUint()), &out->bytes);
    } else if (tensor.datatype == "UINT64") {
      AppendScalar(v.AsUint(), &out->bytes);
    } else {
      return Error("unsupported datatype " + tensor.datatype);
    }
  }
  return Error::Success;
}

Error DataLoader::Validate() const {
  for (size_t stream = 0; stream < data_.size(); ++stream) {
    for (size_t step = 0; step < data_[stream].size(); ++step) {
      for (const auto& tensor : model_->inputs) {
        auto it = data_[stream][step].find(tensor.name);
        if (it == data_[stream][step].end()) {
          if (tensor.optional) continue;
          return Error(
              "missing data for input '" + tensor.name + "' (stream " +
              std::to_string(stream) + " step " + std::to_string(step) + ")");
        }
        const auto& got = it->second.shape;
        const auto& want = tensor.shape;
        bool compatible = got.size() == want.size();
        if (compatible) {
          for (size_t i = 0; i < got.size(); ++i) {
            if (want[i] != -1 && got[i] != want[i]) compatible = false;
          }
        }
        if (!compatible) {
          return Error(
              "data shape for input '" + tensor.name +
              "' incompatible with the model spec");
        }
      }
    }
  }
  return Error::Success;
}

}  // namespace perf
}  // namespace tpuclient
