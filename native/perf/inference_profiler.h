// Measurement engine for the native perf harness (parity:
// /root/reference/src/c++/perf_analyzer/inference_profiler.h:215):
// sweeps load levels, repeats measurement windows until the last
// three trials agree within the stability threshold on latency AND
// throughput, merges the stable trials (MergePerfStatusReports,
// inference_profiler.cc:648), and pairs server-side statistics.
#pragma once

#include <map>
#include <string>
#include <vector>

#include "../library/common.h"
#include "load_manager.h"
#include "metrics_manager.h"

namespace tpuclient {
namespace perf {

// One stable measurement at a load level (parity: PerfStatus,
// inference_profiler.h:178).
struct PerfStatus {
  size_t concurrency = 0;
  double request_rate = 0.0;
  double throughput = 0.0;        // infer/sec
  double avg_latency_us = 0.0;
  double std_latency_us = 0.0;
  std::map<int, double> latency_percentiles;  // us
  size_t completed_count = 0;
  size_t delayed_count = 0;
  size_t error_count = 0;
  // Share of the window the harness workers were busy (100 - idle%):
  // high values mean the measurement is client-bound (reference
  // SummarizeOverhead semantics).
  double overhead_pct = 0.0;
  // First failing request's message — without it a fully-erroring run
  // prints only a count, hiding the actual cause.
  std::string sample_error;
  bool on_target = true;  // false when the level never stabilized
  uint64_t window_start_ns = 0;
  uint64_t window_end_ns = 0;
  // Raw records for the profile export.
  std::vector<RequestRecord> records;
  // Server-side statistics for THIS window: deltas between the
  // window-start and window-end snapshots (model_stats JSON shape),
  // one entry per model (top model + ensemble composing models).
  json::Value server_stats;
  // Client-transport breakdown averaged over the window (from the
  // setup backend's cumulative stats when available).
  double avg_send_time_us = 0.0;
  double avg_receive_time_us = 0.0;
  // Server accelerator gauges for the window: {family -> {avg, max}}.
  TpuMetricsSummary tpu_metrics;
};

struct MeasurementConfig {
  uint64_t measurement_interval_ms = 5000;
  // Inferences per request: throughput is reported in inferences/sec
  // (completed requests x batch size / window), matching the
  // reference's inference_profiler.cc valid_request_count semantics.
  size_t batch_size = 1;
  bool count_windows = false;  // measure by request count, not time
  size_t measurement_request_count = 50;
  size_t max_trials = 10;
  double stability_threshold = 0.1;
  double latency_threshold_ms = 0.0;  // 0 = no limit
  int percentile = 0;                 // 0 = stabilize on average
  // Progress line every N completed requests (0 = off), reference
  // --log-frequency.
  size_t log_frequency = 0;
};

class MPIDriver;

class InferenceProfiler {
 public:
  InferenceProfiler(
      LoadManager* manager, MeasurementConfig config,
      ClientBackend* stats_backend = nullptr, std::string model_name = "",
      bool verbose = false, MetricsManager* metrics = nullptr,
      std::vector<std::string> composing_models = {})
      : manager_(manager), config_(config), stats_backend_(stats_backend),
        model_name_(std::move(model_name)),
        composing_models_(std::move(composing_models)), verbose_(verbose),
        metrics_(metrics), next_log_at_(config.log_frequency) {
    if (metrics_ != nullptr) metrics_->Start();
  }

  // Multi-rank runs: the stability decision is merged across ranks
  // (logical AND), so every analyzer process keeps measuring until
  // ALL of them are stable (parity: mpi_utils.h:32-80 — the
  // reference AllGathers per-rank stability and loops until
  // unanimous).
  void set_mpi(MPIDriver* mpi) { mpi_ = mpi; }

  // Rank-merged decisions (identity without MPI): every control-flow
  // branch that gates a collective must agree across ranks.
  bool AllRanks(bool local) const;
  bool AnyRank(bool local) const;
  // Success only when EVERY rank's err is ok; otherwise the local
  // error (or a peer-failure marker) — so error returns can never
  // desequence the ranks' collectives.
  Error RankCheck(const Error& err) const;

  // Concurrency sweep: [start, end] by step; end==0 profiles only
  // `start`. Stops early when the latency threshold is exceeded.
  Error ProfileConcurrencyRange(
      ConcurrencyManager* manager, size_t start, size_t end, size_t step,
      std::vector<PerfStatus>* results);

  // Binary-search mode (reference inference_profiler.h:280-325):
  // bisects [start, end] for the highest concurrency whose latency
  // stays under the threshold; every probed level's measurement is
  // appended, best level last.
  Error ProfileConcurrencyBinarySearch(
      ConcurrencyManager* manager, size_t start, size_t end,
      std::vector<PerfStatus>* results);

  Error ProfileRequestRateRange(
      RequestRateManager* manager, double start, double end, double step,
      std::vector<PerfStatus>* results);

  // Measures at whatever load the manager is already generating.
  Error ProfileSingleLevel(PerfStatus* status);

 private:
  Error ProfileLevel(PerfStatus* merged);
  Error Measure(PerfStatus* status);
  void Summarize(
      std::vector<RequestRecord>&& records, uint64_t start_ns,
      uint64_t end_ns, PerfStatus* status);
  bool IsStable(const std::vector<PerfStatus>& trials) const;
  double StabilityMetric(const PerfStatus& status) const;
  PerfStatus Merge(std::vector<PerfStatus>&& trials) const;
  bool ExceedsLatencyThreshold(const PerfStatus& status) const;

  LoadManager* manager_;
  MeasurementConfig config_;
  ClientBackend* stats_backend_;
  std::string model_name_;
  // Ensemble composing models: their per-window stat deltas are
  // paired alongside the top model's (reference
  // inference_profiler.cc:648).
  std::vector<std::string> composing_models_;
  bool verbose_;
  MetricsManager* metrics_;
  MPIDriver* mpi_ = nullptr;
  // --log-frequency progress accounting.
  size_t completed_total_ = 0;
  size_t next_log_at_ = 0;
};

}  // namespace perf
}  // namespace tpuclient
