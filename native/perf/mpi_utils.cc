#include "mpi_utils.h"

#include <dlfcn.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <thread>

namespace tpuclient {
namespace perf {

namespace {

// MPICH-ABI handle constants. MPICH (and its ABI family: Intel MPI,
// MVAPICH2, Cray MPT) encodes MPI handles as fixed 32-bit integers
// baked into mpi.h — stable across releases as part of the common
// MPICH ABI — and passes them BY VALUE. Passing the constant through
// a pointer-typed parameter is well-defined on the SysV ABI (both
// travel in the same register); the library reads it back as an int.
constexpr uintptr_t kMpichCommWorld = 0x44000000u;
constexpr uintptr_t kMpichTypeInt = 0x4c000405u;
constexpr uintptr_t kMpichOpLand = 0x58000005u;

// ---- built-in coordinator wire format ------------------------------
// One fixed 8-byte frame per collective message. TCP ordering plus
// the lockstep collective call sequence (every rank issues the same
// collectives in the same order — the same contract MPI itself
// assumes) means no framing beyond a sanity-checked sequence number
// is needed.
struct CoordFrame {
  uint16_t magic;  // kCoordMagic
  uint8_t op;      // CoordOp
  uint8_t flag;    // hello: low byte of rank; all_and: local flag
  uint32_t seq;    // collective counter (hello: full rank)
};
static_assert(sizeof(CoordFrame) == 8, "frame must be 8 bytes");

constexpr uint16_t kCoordMagic = 0x5043;  // "CP"
enum CoordOp : uint8_t { kHello = 1, kAllAnd = 2, kResult = 3 };

bool SendAll(int fd, const CoordFrame& frame) {
  // Network byte order on the wire: ranks may sit on different hosts.
  CoordFrame f = frame;
  f.magic = htons(f.magic);
  f.seq = htonl(f.seq);
  const char* p = reinterpret_cast<const char*>(&f);
  size_t left = sizeof(f);
  while (left > 0) {
    ssize_t n = send(fd, p, left, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    left -= static_cast<size_t>(n);
  }
  return true;
}

bool RecvAll(int fd, CoordFrame* f) {
  char* p = reinterpret_cast<char*>(f);
  size_t left = sizeof(*f);
  while (left > 0) {
    ssize_t n = recv(fd, p, left, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    p += n;
    left -= static_cast<size_t>(n);
  }
  f->magic = ntohs(f->magic);
  f->seq = ntohl(f->seq);
  return f->magic == kCoordMagic;
}

void SetSocketOptions(int fd, double timeout_s) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  struct timeval tv;
  tv.tv_sec = static_cast<long>(timeout_s);
  tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

// Resolve host:port to a connect/bind-ready IPv4/IPv6 address.
bool ResolveAddr(const std::string& host, int port, bool for_bind,
                 struct addrinfo** out) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (for_bind) hints.ai_flags = AI_PASSIVE;
  const std::string port_str = std::to_string(port);
  return getaddrinfo(host.empty() ? nullptr : host.c_str(),
                     port_str.c_str(), &hints, out) == 0;
}

}  // namespace

MPIDriver::MPIDriver(bool is_enabled) {
  if (!is_enabled) return;
  // Built-in coordinator contract (jax.distributed.initialize-style:
  // coordinator_address / num_processes / process_id). Preferred over
  // the MPI probe when set — it works with no launcher at all.
  const char* coord = getenv("TPUCLIENT_COORDINATOR");
  const char* world = getenv("TPUCLIENT_WORLD_SIZE");
  const char* rank = getenv("TPUCLIENT_RANK");
  if (coord != nullptr && world != nullptr && rank != nullptr) {
    const std::string addr(coord);
    const size_t colon = addr.rfind(':');
    const int size = atoi(world);
    const int r = atoi(rank);
    const int port =
        colon != std::string::npos ? atoi(addr.c_str() + colon + 1) : 0;
    if (colon != std::string::npos && port >= 1 && port <= 65535 &&
        size >= 2 && r >= 0 && r < size) {
      coord_host_ = addr.substr(0, colon);
      // Bracketed IPv6 literal ([fd00::1]:7000) — strip the brackets
      // for getaddrinfo (same accepted shape as
      // jax.distributed.initialize's coordinator_address).
      if (coord_host_.size() >= 2 && coord_host_.front() == '[' &&
          coord_host_.back() == ']') {
        coord_host_ = coord_host_.substr(1, coord_host_.size() - 2);
      }
      coord_port_ = port;
      world_size_ = size;
      rank_ = r;
      if (const char* t = getenv("TPUCLIENT_COORD_TIMEOUT_S")) {
        timeout_s_ = atof(t);
        if (timeout_s_ <= 0) timeout_s_ = 60.0;
      }
      // Per-collective skew budget — deliberately separate from the
      // join timeout: a fail-fast join window must not turn a long
      // measurement trial's stability collective into a degrade.
      if (const char* t = getenv("TPUCLIENT_COLLECTIVE_TIMEOUT_S")) {
        collective_timeout_s_ = atof(t);
      }
      if (collective_timeout_s_ <= 0) collective_timeout_s_ = 600.0;
      builtin_ = true;
      active_ = true;
      return;
    }
    fprintf(stderr,
            "warning: TPUCLIENT_COORDINATOR set but the rank contract "
            "is invalid (addr=%s world=%s rank=%s); running "
            "single-rank\n",
            coord, world, rank);
  }
  // OpenMPI exposes its communicator/type/op constants as dynamic
  // symbols (ompi_*); the MPICH family bakes them in as integer
  // constants (fallback below).
  for (const char* name :
       {"libmpi.so", "libmpi.so.40", "libmpi.so.12", "libmpich.so",
        "libmpich.so.12"}) {
    handle_ = dlopen(name, RTLD_NOW | RTLD_GLOBAL);
    if (handle_ != nullptr) break;
  }
  if (handle_ == nullptr) return;
  init_ = reinterpret_cast<int (*)(int*, char***)>(
      dlsym(handle_, "MPI_Init"));
  finalize_ = reinterpret_cast<int (*)()>(dlsym(handle_, "MPI_Finalize"));
  barrier_ = reinterpret_cast<int (*)(void*)>(dlsym(handle_, "MPI_Barrier"));
  comm_size_ = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(handle_, "MPI_Comm_size"));
  comm_rank_ = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(handle_, "MPI_Comm_rank"));
  allreduce_ =
      reinterpret_cast<int (*)(const void*, void*, int, void*, void*, void*)>(
          dlsym(handle_, "MPI_Allreduce"));
  comm_world_ = dlsym(handle_, "ompi_mpi_comm_world");
  type_int_ = dlsym(handle_, "ompi_mpi_int");
  op_land_ = dlsym(handle_, "ompi_mpi_op_land");
  if (comm_world_ == nullptr && init_ != nullptr) {
    // No OpenMPI handle symbols: the integer-constant fallback is
    // only valid for the MPICH ABI family (MPICH, Intel MPI,
    // MVAPICH2, Cray MPT). Identify the family before trusting it —
    // a non-MPICH-ABI libmpi under a PMI-setting launcher would
    // otherwise be handed garbage handles in MPI_Allreduce.
    // MPI_Get_library_version is MPI-3 and callable before MPI_Init;
    // every MPICH descendant names its lineage in the string. The
    // MPIR_* internal exports fingerprint MPICH lineage for builds
    // too old to have it.
    bool mpich_family = false;
    auto version_fn = reinterpret_cast<int (*)(char*, int*)>(
        dlsym(handle_, "MPI_Get_library_version"));
    if (version_fn != nullptr) {
      static char version[8704] = {0};  // >= MPICH's 8192 string max
      int len = 0;
      if (version_fn(version, &len) == 0) {
        const std::string v(version);
        mpich_family = v.find("MPICH") != std::string::npos ||
                       v.find("Intel(R) MPI") != std::string::npos ||
                       v.find("MVAPICH") != std::string::npos ||
                       v.find("CRAY") != std::string::npos;
      }
    }
    // Rebranded derivatives (e.g. ParaStation) may name neither
    // lineage in the string; the MPIR_* internal exports still
    // fingerprint the MPICH code base.
    if (!mpich_family) {
      mpich_family = dlsym(handle_, "MPIR_Err_create_code") != nullptr;
    }
    if (mpich_family) {
      comm_world_ = reinterpret_cast<void*>(kMpichCommWorld);
      type_int_ = reinterpret_cast<void*>(kMpichTypeInt);
      op_land_ = reinterpret_cast<void*>(kMpichOpLand);
    }
  }
  // Active only when everything resolved AND launched under a real
  // launcher (mpirun/mpiexec set these; a singleton would need the
  // runtime daemons this image does not ship).
  active_ = init_ != nullptr && finalize_ != nullptr &&
            barrier_ != nullptr && comm_size_ != nullptr &&
            comm_rank_ != nullptr && allreduce_ != nullptr &&
            comm_world_ != nullptr && type_int_ != nullptr &&
            op_land_ != nullptr &&
            (getenv("OMPI_COMM_WORLD_SIZE") != nullptr ||
             getenv("PMI_SIZE") != nullptr ||
             getenv("PMI_RANK") != nullptr ||
             getenv("HYDRA_CONTROL_FD") != nullptr);
}

MPIDriver::~MPIDriver() {
  BuiltinTeardown();
  if (handle_ != nullptr) dlclose(handle_);
}

void MPIDriver::MPIInit() {
  if (!active_) return;
  if (builtin_) {
    if (!BuiltinInit()) {
      fprintf(stderr,
              "warning: rank %d could not join the coordinator at "
              "%s:%d within %.0fs; degrading to a single-rank run\n",
              rank_, coord_host_.c_str(), coord_port_, timeout_s_);
      BuiltinTeardown();
      active_ = false;
    }
    return;
  }
  init_(nullptr, nullptr);
}

void MPIDriver::MPIFinalize() {
  if (!active_) return;
  if (builtin_) {
    BuiltinTeardown();
    return;
  }
  finalize_();
}

void MPIDriver::MPIBarrierWorld() {
  if (!active_) return;
  if (builtin_) {
    bool unused;
    BuiltinCollective(true, &unused);
    return;
  }
  barrier_(comm_world_);
}

int MPIDriver::MPICommSizeWorld() const {
  if (!active_) return 1;
  if (builtin_) return world_size_;
  int size = 1;
  comm_size_(comm_world_, &size);
  return size;
}

int MPIDriver::MPICommRankWorld() const {
  if (!active_) return 0;
  if (builtin_) return rank_;
  int rank = 0;
  comm_rank_(comm_world_, &rank);
  return rank;
}

bool MPIDriver::MPIAllTrue(bool local) const {
  if (!active_) return local;
  if (builtin_) {
    bool result = local;
    if (!BuiltinCollective(local, &result)) return local;
    return result;
  }
  int in = local ? 1 : 0;
  int out = 0;
  allreduce_(&in, &out, 1, type_int_, op_land_, comm_world_);
  return out != 0;
}

bool MPIDriver::BuiltinInit() {
  const auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::duration<double>(timeout_s_);
  if (rank_ == 0) {
    struct addrinfo* ai = nullptr;
    if (!ResolveAddr(coord_host_, coord_port_, /*for_bind=*/true, &ai)) {
      return false;
    }
    // Walk every resolved address (a dual-stack hostname's first
    // entry may be an unbindable family on this host).
    for (struct addrinfo* a = ai; a != nullptr; a = a->ai_next) {
      listen_fd_ = socket(a->ai_family, SOCK_STREAM, 0);
      if (listen_fd_ < 0) continue;
      int one = 1;
      setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
      if (bind(listen_fd_, a->ai_addr, a->ai_addrlen) == 0 &&
          listen(listen_fd_, world_size_) == 0) {
        break;
      }
      close(listen_fd_);
      listen_fd_ = -1;
    }
    freeaddrinfo(ai);
    if (listen_fd_ < 0) return false;
    fds_.assign(world_size_ - 1, -1);
    int joined = 0;
    while (joined < world_size_ - 1) {
      const auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
          deadline - std::chrono::steady_clock::now());
      if (left.count() <= 0) return false;
      struct pollfd pfd = {listen_fd_, POLLIN, 0};
      const int ready = poll(&pfd, 1, static_cast<int>(left.count()));
      if (ready <= 0) {
        if (ready < 0 && errno == EINTR) continue;
        return false;
      }
      const int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) continue;
      SetSocketOptions(fd, timeout_s_);
      CoordFrame hello;
      const int peer =
          RecvAll(fd, &hello) && hello.op == kHello
              ? static_cast<int>(hello.seq)
              : -1;
      if (peer < 1 || peer >= world_size_ || fds_[peer - 1] != -1) {
        close(fd);
        continue;  // malformed or duplicate join; keep listening
      }
      fds_[peer - 1] = fd;
      ++joined;
    }
    // Joined: widen the socket deadlines from the join window to the
    // per-collective skew budget.
    for (int fd : fds_) SetSocketOptions(fd, collective_timeout_s_);
    return true;
  }
  // Non-coordinator rank: connect with retry until rank 0 is up. A
  // failed resolve also retries — under a scheduler the
  // coordinator's DNS name may not be propagated yet when this rank
  // starts.
  struct addrinfo* ai = nullptr;
  while (std::chrono::steady_clock::now() < deadline) {
    if (ai == nullptr &&
        !ResolveAddr(coord_host_, coord_port_, /*for_bind=*/false, &ai)) {
      ai = nullptr;
      std::this_thread::sleep_for(std::chrono::milliseconds(250));
      continue;
    }
    int fd = -1;
    for (struct addrinfo* a = ai; a != nullptr; a = a->ai_next) {
      fd = socket(a->ai_family, SOCK_STREAM, 0);
      if (fd < 0) continue;
      if (connect(fd, a->ai_addr, a->ai_addrlen) == 0) break;
      close(fd);
      fd = -1;
    }
    if (fd >= 0) {
      freeaddrinfo(ai);
      SetSocketOptions(fd, collective_timeout_s_);
      CoordFrame hello = {kCoordMagic, kHello,
                          static_cast<uint8_t>(rank_ & 0xff),
                          static_cast<uint32_t>(rank_)};
      if (!SendAll(fd, hello)) {
        close(fd);
        return false;
      }
      fds_.assign(1, fd);
      return true;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  if (ai != nullptr) freeaddrinfo(ai);
  return false;
}

bool MPIDriver::BuiltinCollective(bool local, bool* result) const {
  const uint32_t seq = seq_++;
  bool ok = true;
  if (rank_ == 0) {
    bool agg = local;
    for (int fd : fds_) {
      CoordFrame f;
      if (!RecvAll(fd, &f) || f.op != kAllAnd || f.seq != seq) {
        ok = false;
        break;
      }
      agg = agg && f.flag != 0;
    }
    if (ok) {
      const CoordFrame out = {kCoordMagic, kResult,
                              static_cast<uint8_t>(agg ? 1 : 0), seq};
      for (int fd : fds_) {
        if (!SendAll(fd, out)) {
          ok = false;
          break;
        }
      }
    }
    if (ok) *result = agg;
  } else {
    const CoordFrame out = {kCoordMagic, kAllAnd,
                            static_cast<uint8_t>(local ? 1 : 0), seq};
    CoordFrame in;
    ok = SendAll(fds_[0], out) && RecvAll(fds_[0], &in) &&
         in.op == kResult && in.seq == seq;
    if (ok) *result = in.flag != 0;
  }
  if (!ok) {
    // A dead peer must not hang the world: drop to rank-local
    // decisions (the same degrade contract as a missing launcher).
    fprintf(stderr,
            "warning: rank %d lost the coordinator collective (seq %u); "
            "degrading to rank-local decisions\n",
            rank_, seq);
    BuiltinTeardown();
    active_ = false;
  }
  return ok;
}

void MPIDriver::BuiltinTeardown() const {
  for (int fd : fds_) {
    if (fd >= 0) close(fd);
  }
  fds_.clear();
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
  }
}

}  // namespace perf
}  // namespace tpuclient
