#include "mpi_utils.h"

#include <dlfcn.h>

#include <cstdlib>

namespace tpuclient {
namespace perf {

MPIDriver::MPIDriver(bool is_enabled) {
  if (!is_enabled) return;
  // Only OpenMPI exposes its communicator/type/op constants as
  // symbols we can resolve dynamically (ompi_*); MPICH encodes them
  // as integer constants baked in at compile time, which a pure
  // dlopen binding cannot obtain portably.
  handle_ = dlopen("libmpi.so", RTLD_NOW | RTLD_GLOBAL);
  if (handle_ == nullptr) {
    handle_ = dlopen("libmpi.so.40", RTLD_NOW | RTLD_GLOBAL);
  }
  if (handle_ == nullptr) return;
  init_ = reinterpret_cast<int (*)(int*, char***)>(
      dlsym(handle_, "MPI_Init"));
  finalize_ = reinterpret_cast<int (*)()>(dlsym(handle_, "MPI_Finalize"));
  barrier_ = reinterpret_cast<int (*)(void*)>(dlsym(handle_, "MPI_Barrier"));
  comm_size_ = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(handle_, "MPI_Comm_size"));
  comm_rank_ = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(handle_, "MPI_Comm_rank"));
  allreduce_ =
      reinterpret_cast<int (*)(const void*, void*, int, void*, void*, void*)>(
          dlsym(handle_, "MPI_Allreduce"));
  comm_world_ = dlsym(handle_, "ompi_mpi_comm_world");
  type_int_ = dlsym(handle_, "ompi_mpi_int");
  op_land_ = dlsym(handle_, "ompi_mpi_op_land");
  // Active only when everything resolved AND launched under mpirun.
  active_ = init_ != nullptr && finalize_ != nullptr &&
            barrier_ != nullptr && comm_size_ != nullptr &&
            comm_rank_ != nullptr && allreduce_ != nullptr &&
            comm_world_ != nullptr && type_int_ != nullptr &&
            op_land_ != nullptr &&
            (getenv("OMPI_COMM_WORLD_SIZE") != nullptr ||
             getenv("PMI_SIZE") != nullptr);
}

MPIDriver::~MPIDriver() {
  if (handle_ != nullptr) dlclose(handle_);
}

void MPIDriver::MPIInit() {
  if (active_) init_(nullptr, nullptr);
}

void MPIDriver::MPIFinalize() {
  if (active_) finalize_();
}

void MPIDriver::MPIBarrierWorld() {
  if (active_) barrier_(comm_world_);
}

int MPIDriver::MPICommSizeWorld() const {
  if (!active_) return 1;
  int size = 1;
  comm_size_(comm_world_, &size);
  return size;
}

int MPIDriver::MPICommRankWorld() const {
  if (!active_) return 0;
  int rank = 0;
  comm_rank_(comm_world_, &rank);
  return rank;
}

bool MPIDriver::MPIAllTrue(bool local) const {
  if (!active_) return local;
  int in = local ? 1 : 0;
  int out = 0;
  allreduce_(&in, &out, 1, type_int_, op_land_, comm_world_);
  return out != 0;
}

}  // namespace perf
}  // namespace tpuclient
