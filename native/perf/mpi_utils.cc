#include "mpi_utils.h"

#include <dlfcn.h>

#include <cstdint>
#include <cstdlib>
#include <string>

namespace tpuclient {
namespace perf {

namespace {

// MPICH-ABI handle constants. MPICH (and its ABI family: Intel MPI,
// MVAPICH2, Cray MPT) encodes MPI handles as fixed 32-bit integers
// baked into mpi.h — stable across releases as part of the common
// MPICH ABI — and passes them BY VALUE. Passing the constant through
// a pointer-typed parameter is well-defined on the SysV ABI (both
// travel in the same register); the library reads it back as an int.
constexpr uintptr_t kMpichCommWorld = 0x44000000u;
constexpr uintptr_t kMpichTypeInt = 0x4c000405u;
constexpr uintptr_t kMpichOpLand = 0x58000005u;

}  // namespace

MPIDriver::MPIDriver(bool is_enabled) {
  if (!is_enabled) return;
  // OpenMPI exposes its communicator/type/op constants as dynamic
  // symbols (ompi_*); the MPICH family bakes them in as integer
  // constants (fallback below).
  for (const char* name :
       {"libmpi.so", "libmpi.so.40", "libmpi.so.12", "libmpich.so",
        "libmpich.so.12"}) {
    handle_ = dlopen(name, RTLD_NOW | RTLD_GLOBAL);
    if (handle_ != nullptr) break;
  }
  if (handle_ == nullptr) return;
  init_ = reinterpret_cast<int (*)(int*, char***)>(
      dlsym(handle_, "MPI_Init"));
  finalize_ = reinterpret_cast<int (*)()>(dlsym(handle_, "MPI_Finalize"));
  barrier_ = reinterpret_cast<int (*)(void*)>(dlsym(handle_, "MPI_Barrier"));
  comm_size_ = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(handle_, "MPI_Comm_size"));
  comm_rank_ = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(handle_, "MPI_Comm_rank"));
  allreduce_ =
      reinterpret_cast<int (*)(const void*, void*, int, void*, void*, void*)>(
          dlsym(handle_, "MPI_Allreduce"));
  comm_world_ = dlsym(handle_, "ompi_mpi_comm_world");
  type_int_ = dlsym(handle_, "ompi_mpi_int");
  op_land_ = dlsym(handle_, "ompi_mpi_op_land");
  if (comm_world_ == nullptr && init_ != nullptr) {
    // No OpenMPI handle symbols: the integer-constant fallback is
    // only valid for the MPICH ABI family (MPICH, Intel MPI,
    // MVAPICH2, Cray MPT). Identify the family before trusting it —
    // a non-MPICH-ABI libmpi under a PMI-setting launcher would
    // otherwise be handed garbage handles in MPI_Allreduce.
    // MPI_Get_library_version is MPI-3 and callable before MPI_Init;
    // every MPICH descendant names its lineage in the string. The
    // MPIR_* internal exports fingerprint MPICH lineage for builds
    // too old to have it.
    bool mpich_family = false;
    auto version_fn = reinterpret_cast<int (*)(char*, int*)>(
        dlsym(handle_, "MPI_Get_library_version"));
    if (version_fn != nullptr) {
      static char version[8704] = {0};  // >= MPICH's 8192 string max
      int len = 0;
      if (version_fn(version, &len) == 0) {
        const std::string v(version);
        mpich_family = v.find("MPICH") != std::string::npos ||
                       v.find("Intel(R) MPI") != std::string::npos ||
                       v.find("MVAPICH") != std::string::npos ||
                       v.find("CRAY") != std::string::npos;
      }
    }
    // Rebranded derivatives (e.g. ParaStation) may name neither
    // lineage in the string; the MPIR_* internal exports still
    // fingerprint the MPICH code base.
    if (!mpich_family) {
      mpich_family = dlsym(handle_, "MPIR_Err_create_code") != nullptr;
    }
    if (mpich_family) {
      comm_world_ = reinterpret_cast<void*>(kMpichCommWorld);
      type_int_ = reinterpret_cast<void*>(kMpichTypeInt);
      op_land_ = reinterpret_cast<void*>(kMpichOpLand);
    }
  }
  // Active only when everything resolved AND launched under a real
  // launcher (mpirun/mpiexec set these; a singleton would need the
  // runtime daemons this image does not ship).
  active_ = init_ != nullptr && finalize_ != nullptr &&
            barrier_ != nullptr && comm_size_ != nullptr &&
            comm_rank_ != nullptr && allreduce_ != nullptr &&
            comm_world_ != nullptr && type_int_ != nullptr &&
            op_land_ != nullptr &&
            (getenv("OMPI_COMM_WORLD_SIZE") != nullptr ||
             getenv("PMI_SIZE") != nullptr ||
             getenv("PMI_RANK") != nullptr ||
             getenv("HYDRA_CONTROL_FD") != nullptr);
}

MPIDriver::~MPIDriver() {
  if (handle_ != nullptr) dlclose(handle_);
}

void MPIDriver::MPIInit() {
  if (active_) init_(nullptr, nullptr);
}

void MPIDriver::MPIFinalize() {
  if (active_) finalize_();
}

void MPIDriver::MPIBarrierWorld() {
  if (active_) barrier_(comm_world_);
}

int MPIDriver::MPICommSizeWorld() const {
  if (!active_) return 1;
  int size = 1;
  comm_size_(comm_world_, &size);
  return size;
}

int MPIDriver::MPICommRankWorld() const {
  if (!active_) return 0;
  int rank = 0;
  comm_rank_(comm_world_, &rank);
  return rank;
}

bool MPIDriver::MPIAllTrue(bool local) const {
  if (!active_) return local;
  int in = local ? 1 : 0;
  int out = 0;
  allreduce_(&in, &out, 1, type_int_, op_land_, comm_world_);
  return out != 0;
}

}  // namespace perf
}  // namespace tpuclient
