#include "command_line_parser.h"

#include <getopt.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace tpuclient {
namespace perf {

namespace {

// start[:end[:step]]
template <typename T>
bool ParseRange(const char* text, T* start, T* end, T* step) {
  std::string s(text);
  size_t c1 = s.find(':');
  auto cast = [](const std::string& v) -> double { return atof(v.c_str()); };
  *start = static_cast<T>(cast(s.substr(0, c1)));
  *end = *start;
  *step = static_cast<T>(1);
  if (c1 == std::string::npos) return true;
  size_t c2 = s.find(':', c1 + 1);
  *end = static_cast<T>(cast(s.substr(c1 + 1, c2 - c1 - 1)));
  if (c2 != std::string::npos) {
    *step = static_cast<T>(cast(s.substr(c2 + 1)));
  }
  return true;
}

enum LongOpt {
  kOptConcurrencyRange = 1000,
  kOptRequestRateRange,
  kOptRequestIntervals,
  kOptPeriodicRange,
  kOptRequestPeriod,
  kOptRequestDistribution,
  kOptMeasurementMode,
  kOptMeasurementRequestCount,
  kOptSharedMemory,
  kOptOutputShmSize,
  kOptTpuArenaUrl,
  kOptInputData,
  kOptStringLength,
  kOptStringData,
  kOptShape,
  kOptSequenceLength,
  kOptSequenceLengthVariation,
  kOptSequenceIdRange,
  kOptProfileExportFile,
  kOptStreaming,
  kOptSync,
  kOptAsync,
  kOptMaxThreads,
  kOptPercentile,
  kOptServiceKind,
  kOptEndpoint,
  kOptCollectMetrics,
  kOptMetricsUrl,
  kOptMetricsInterval,
  kOptBinarySearch,
  kOptRequestCount,
  kOptDataDirectory,
  kOptBlsComposingModels,
  kOptModelSignatureName,
  kOptNumOfSequences,
  kOptSerialSequences,
  kOptVerboseCsv,
  kOptSslGrpcUseSsl,
  kOptSslGrpcRootCerts,
  kOptSslGrpcPrivateKey,
  kOptSslGrpcCertChain,
  kOptSslHttpsCaCerts,
  kOptSslHttpsClientCert,
  kOptSslHttpsPrivateKey,
  kOptSslHttpsVerifyPeer,
  kOptSslHttpsVerifyHost,
  kOptRequestParameter,
  kOptTraceLevel,
  kOptTraceRate,
  kOptTraceCount,
  kOptEnableMpi,
  kOptRanks,
  kOptInputTensorFormat,
  kOptOutputTensorFormat,
  kOptSslHttpsClientCertType,
  kOptSslHttpsPrivateKeyType,
  kOptModelRepository,
  kOptTritonServerDir,
  kOptLogFrequency,
  kOptVersion,
  kOptGrpcCompression,
};

const struct option kLongOptions[] = {
    {"model-name", required_argument, nullptr, 'm'},
    {"model-version", required_argument, nullptr, 'x'},
    {"url", required_argument, nullptr, 'u'},
    {"protocol", required_argument, nullptr, 'i'},
    {"batch-size", required_argument, nullptr, 'b'},
    {"verbose", no_argument, nullptr, 'v'},
    {"measurement-interval", required_argument, nullptr, 'p'},
    {"max-trials", required_argument, nullptr, 'r'},
    {"stability-percentage", required_argument, nullptr, 's'},
    {"latency-threshold", required_argument, nullptr, 'l'},
    {"latency-report-file", required_argument, nullptr, 'f'},
    {"concurrency-range", required_argument, nullptr, kOptConcurrencyRange},
    {"request-rate-range", required_argument, nullptr, kOptRequestRateRange},
    {"request-intervals", required_argument, nullptr, kOptRequestIntervals},
    {"periodic-concurrency-range", required_argument, nullptr,
     kOptPeriodicRange},
    {"request-period", required_argument, nullptr, kOptRequestPeriod},
    {"request-distribution", required_argument, nullptr,
     kOptRequestDistribution},
    {"measurement-mode", required_argument, nullptr, kOptMeasurementMode},
    {"measurement-request-count", required_argument, nullptr,
     kOptMeasurementRequestCount},
    {"shared-memory", required_argument, nullptr, kOptSharedMemory},
    {"output-shared-memory-size", required_argument, nullptr,
     kOptOutputShmSize},
    {"tpu-arena-url", required_argument, nullptr, kOptTpuArenaUrl},
    {"input-data", required_argument, nullptr, kOptInputData},
    {"string-length", required_argument, nullptr, kOptStringLength},
    {"string-data", required_argument, nullptr, kOptStringData},
    {"shape", required_argument, nullptr, kOptShape},
    {"sequence-length", required_argument, nullptr, kOptSequenceLength},
    {"sequence-length-variation", required_argument, nullptr,
     kOptSequenceLengthVariation},
    {"sequence-id-range", required_argument, nullptr, kOptSequenceIdRange},
    {"profile-export-file", required_argument, nullptr,
     kOptProfileExportFile},
    {"streaming", no_argument, nullptr, kOptStreaming},
    {"sync", no_argument, nullptr, kOptSync},
    {"async", no_argument, nullptr, kOptAsync},
    {"max-threads", required_argument, nullptr, kOptMaxThreads},
    {"percentile", required_argument, nullptr, kOptPercentile},
    {"service-kind", required_argument, nullptr, kOptServiceKind},
    {"endpoint", required_argument, nullptr, kOptEndpoint},
    {"collect-metrics", no_argument, nullptr, kOptCollectMetrics},
    {"metrics-url", required_argument, nullptr, kOptMetricsUrl},
    {"metrics-interval", required_argument, nullptr, kOptMetricsInterval},
    {"binary-search", no_argument, nullptr, kOptBinarySearch},
    {"request-count", required_argument, nullptr, kOptRequestCount},
    {"data-directory", required_argument, nullptr, kOptDataDirectory},
    {"bls-composing-models", required_argument, nullptr,
     kOptBlsComposingModels},
    {"model-signature-name", required_argument, nullptr,
     kOptModelSignatureName},
    {"num-of-sequences", required_argument, nullptr, kOptNumOfSequences},
    {"serial-sequences", no_argument, nullptr, kOptSerialSequences},
    {"verbose-csv", no_argument, nullptr, kOptVerboseCsv},
    {"ssl-grpc-use-ssl", no_argument, nullptr, kOptSslGrpcUseSsl},
    {"ssl-grpc-root-certifications-file", required_argument, nullptr,
     kOptSslGrpcRootCerts},
    {"ssl-grpc-private-key-file", required_argument, nullptr,
     kOptSslGrpcPrivateKey},
    {"ssl-grpc-certificate-chain-file", required_argument, nullptr,
     kOptSslGrpcCertChain},
    {"ssl-https-ca-certificates-file", required_argument, nullptr,
     kOptSslHttpsCaCerts},
    {"ssl-https-client-certificate-file", required_argument, nullptr,
     kOptSslHttpsClientCert},
    {"ssl-https-private-key-file", required_argument, nullptr,
     kOptSslHttpsPrivateKey},
    {"ssl-https-verify-peer", required_argument, nullptr,
     kOptSslHttpsVerifyPeer},
    {"ssl-https-verify-host", required_argument, nullptr,
     kOptSslHttpsVerifyHost},
    {"request-parameter", required_argument, nullptr, kOptRequestParameter},
    {"trace-level", required_argument, nullptr, kOptTraceLevel},
    {"trace-rate", required_argument, nullptr, kOptTraceRate},
    {"trace-count", required_argument, nullptr, kOptTraceCount},
    {"enable-mpi", no_argument, nullptr, kOptEnableMpi},
    {"ranks", required_argument, nullptr, kOptRanks},
    {"input-tensor-format", required_argument, nullptr,
     kOptInputTensorFormat},
    {"output-tensor-format", required_argument, nullptr,
     kOptOutputTensorFormat},
    {"ssl-https-client-certificate-type", required_argument, nullptr,
     kOptSslHttpsClientCertType},
    {"ssl-https-private-key-type", required_argument, nullptr,
     kOptSslHttpsPrivateKeyType},
    {"model-repository", required_argument, nullptr, kOptModelRepository},
    {"triton-server-directory", required_argument, nullptr,
     kOptTritonServerDir},
    {"log-frequency", required_argument, nullptr, kOptLogFrequency},
    {"version", no_argument, nullptr, kOptVersion},
    {"grpc-compression-algorithm", required_argument, nullptr,
     kOptGrpcCompression},
    {nullptr, 0, nullptr, 0},
};

}  // namespace

void CLParser::Usage(const char* program) {
  fprintf(
      stderr,
      "Usage: %s -m <model> [-u host:port] [-i grpc|http] [options]\n"
      "Service kinds: --service-kind "
      "triton|openai|torchserve|tfserving|in_process\n"
      "  [--endpoint path] [--model-signature-name sig]\n"
      "Load modes (default --concurrency-range 1):\n"
      "  --concurrency-range start:end:step [--binary-search]\n"
      "  --request-rate-range start:end:step [--request-distribution "
      "constant|poisson]\n"
      "  --request-intervals <file>   (one microsecond gap per line)\n"
      "  --periodic-concurrency-range start:end:step [--request-period N]\n"
      "Measurement: -p <window ms>, -r <max trials>, -s <stability %%>,\n"
      "  -l <latency threshold ms>, --percentile N, --measurement-mode\n"
      "  time_windows|count_windows, --measurement-request-count N,\n"
      "  --request-count N\n"
      "Data: --input-data random|zero|<json>, --data-directory <dir>,\n"
      "  --shape name[:DTYPE]:d1,d2, --string-length N, --string-data S,\n"
      "  --request-parameter name:value:type\n"
      "Shared memory: --shared-memory none|system|tpu,\n"
      "  --output-shared-memory-size N, --tpu-arena-url host:port\n"
      "Sequences: --sequence-length N, --sequence-length-variation pct,\n"
      "  --sequence-id-range start[:end], --num-of-sequences N,\n"
      "  --serial-sequences\n"
      "Pipelines: --bls-composing-models m1,m2\n"
      "TLS: --ssl-https-ca-certificates-file F,\n"
      "  --ssl-https-client-certificate-file F,\n"
      "  --ssl-https-private-key-file F, --ssl-https-verify-peer 0|1,\n"
      "  --ssl-https-verify-host 0|1\n"
      "Tracing: --trace-level L [--trace-rate N] [--trace-count N]\n"
      "Metrics: --collect-metrics [--metrics-url host:port/metrics]\n"
      "  [--metrics-interval ms]\n"
      "HTTP tensor format: --input-tensor-format binary|json,\n"
      "  --output-tensor-format binary|json\n"
      "Scale-out: --enable-mpi, --ranks N (forks N local ranks over\n"
      "  the builtin coordinator; no launcher needed)\n"
      "Output: -f <csv> [--verbose-csv], --profile-export-file <json>,\n"
      "  --log-frequency N, -v, --version\n",
      program);
}

Error CLParser::Parse(
    int argc, char** argv, PerfAnalyzerParameters* params) {
  optind = 1;
  int opt;
  while ((opt = getopt_long(
              argc, argv, "m:x:u:i:b:vp:r:s:l:f:", kLongOptions, nullptr)) !=
         -1) {
    switch (opt) {
      case 'm': params->model_name = optarg; break;
      case 'x': params->model_version = optarg; break;
      case 'u': params->url = optarg; break;
      case 'i':
        params->protocol = optarg;
        if (params->protocol != "grpc" && params->protocol != "http") {
          return Error("unsupported protocol '" + params->protocol + "'");
        }
        break;
      case 'b': params->batch_size = atoll(optarg); break;
      case 'v': params->verbose = true; break;
      case 'p': params->measurement_interval_ms = atoll(optarg); break;
      case 'r': params->max_trials = atoll(optarg); break;
      case 's': params->stability_percentage = atof(optarg); break;
      case 'l': params->latency_threshold_ms = atof(optarg); break;
      case 'f': params->latency_report_file = optarg; break;
      case kOptConcurrencyRange:
        params->has_concurrency_range = true;
        ParseRange(optarg, &params->concurrency_start,
                   &params->concurrency_end, &params->concurrency_step);
        break;
      case kOptRequestRateRange:
        params->has_request_rate_range = true;
        ParseRange(optarg, &params->rate_start, &params->rate_end,
                   &params->rate_step);
        break;
      case kOptRequestIntervals:
        params->request_intervals_file = optarg;
        break;
      case kOptPeriodicRange:
        params->has_periodic_range = true;
        ParseRange(optarg, &params->periodic_start, &params->periodic_end,
                   &params->periodic_step);
        break;
      case kOptRequestPeriod: params->request_period = atoll(optarg); break;
      case kOptRequestDistribution:
        params->request_distribution = optarg;
        if (params->request_distribution != "constant" &&
            params->request_distribution != "poisson") {
          return Error("unsupported request distribution");
        }
        break;
      case kOptMeasurementMode:
        params->measurement_mode = optarg;
        if (params->measurement_mode != "time_windows" &&
            params->measurement_mode != "count_windows") {
          return Error("unsupported measurement mode");
        }
        break;
      case kOptMeasurementRequestCount:
        params->measurement_request_count = atoll(optarg);
        break;
      case kOptSharedMemory:
        params->shared_memory = optarg;
        if (params->shared_memory != "none" &&
            params->shared_memory != "system" &&
            params->shared_memory != "tpu") {
          return Error("unsupported shared memory type (none|system|tpu)");
        }
        break;
      case kOptOutputShmSize: params->output_shm_size = atoll(optarg); break;
      case kOptTpuArenaUrl: params->tpu_arena_url = optarg; break;
      case kOptInputData: params->input_data = optarg; break;
      case kOptStringLength: params->string_length = atoll(optarg); break;
      case kOptStringData: params->string_data = optarg; break;
      case kOptShape: params->shape_overrides.push_back(optarg); break;
      case kOptSequenceLength: params->sequence_length = atoll(optarg); break;
      case kOptSequenceLengthVariation:
        params->sequence_length_variation = atof(optarg);
        break;
      case kOptSequenceIdRange: params->sequence_id_range = optarg; break;
      case kOptProfileExportFile:
        params->profile_export_file = optarg;
        break;
      case kOptStreaming: params->streaming = true; break;
      case kOptSync: params->async_mode = false; break;
      case kOptAsync: params->async_mode = true; break;
      case kOptMaxThreads: params->max_threads = atoll(optarg); break;
      case kOptPercentile: params->percentile = atoi(optarg); break;
      case kOptCollectMetrics: params->collect_metrics = true; break;
      case kOptMetricsUrl: params->metrics_url = optarg; break;
      case kOptMetricsInterval:
        params->metrics_interval_ms = atoll(optarg);
        break;
      case kOptBinarySearch:
        params->binary_search = true;
        break;
      case kOptRequestCount:
        params->request_count = atoll(optarg);
        break;
      case kOptDataDirectory:
        // Alias: the reference splits file/dir input across two
        // flags; our --input-data already accepts a directory.
        params->input_data = optarg;
        break;
      case kOptBlsComposingModels: {
        std::string csv = optarg;
        size_t pos = 0;
        while (pos <= csv.size()) {
          size_t comma = csv.find(',', pos);
          std::string name = csv.substr(
              pos, comma == std::string::npos ? std::string::npos
                                              : comma - pos);
          if (!name.empty()) params->bls_composing_models.push_back(name);
          if (comma == std::string::npos) break;
          pos = comma + 1;
        }
        break;
      }
      case kOptModelSignatureName:
        params->model_signature_name = optarg;
        break;
      case kOptNumOfSequences:
        params->num_of_sequences = atoll(optarg);
        break;
      case kOptSerialSequences:
        params->serial_sequences = true;
        break;
      case kOptVerboseCsv:
        params->verbose_csv = true;
        break;
      case kOptSslGrpcUseSsl:
        params->ssl_grpc_use_ssl = true;
        break;
      case kOptSslGrpcRootCerts:
        params->ssl_grpc_root_certifications_file = optarg;
        break;
      case kOptSslGrpcPrivateKey:
        params->ssl_grpc_private_key_file = optarg;
        break;
      case kOptSslGrpcCertChain:
        params->ssl_grpc_certificate_chain_file = optarg;
        break;
      case kOptSslHttpsCaCerts:
        params->ssl_https_any = true;
        params->ssl_https_ca_certificates_file = optarg;
        break;
      case kOptSslHttpsClientCert:
        params->ssl_https_any = true;
        params->ssl_https_client_certificate_file = optarg;
        break;
      case kOptSslHttpsPrivateKey:
        params->ssl_https_any = true;
        params->ssl_https_private_key_file = optarg;
        break;
      case kOptSslHttpsVerifyPeer:
        params->ssl_https_any = true;
        params->ssl_https_verify_peer = atoi(optarg) != 0;
        break;
      case kOptSslHttpsVerifyHost:
        params->ssl_https_any = true;
        params->ssl_https_verify_host = atoi(optarg) != 0;
        break;
      case kOptRequestParameter:
        params->request_parameters.push_back(optarg);
        break;
      case kOptTraceLevel:
        params->trace_level = optarg;
        break;
      case kOptTraceRate:
        params->trace_rate = atoll(optarg);
        break;
      case kOptTraceCount:
        params->trace_count = atoll(optarg);
        break;
      case kOptSslHttpsClientCertType:
      case kOptSslHttpsPrivateKeyType:
        // The TLS loader reads PEM; DER is the only other reference
        // value and is unsupported here.
        if (std::string(optarg) != "PEM") {
          return Error("only PEM certificates/keys are supported");
        }
        break;
      case kOptModelRepository:
      case kOptTritonServerDir:
        return Error(
            "this build's --service-kind in_process embeds the model "
            "registry directly (no libtritonserver / repository "
            "directory); select models with -m");
      case kOptInputTensorFormat:
        params->input_tensor_format = optarg;
        if (params->input_tensor_format != "binary" &&
            params->input_tensor_format != "json") {
          return Error("--input-tensor-format must be binary|json");
        }
        break;
      case kOptOutputTensorFormat:
        params->output_tensor_format = optarg;
        if (params->output_tensor_format != "binary" &&
            params->output_tensor_format != "json") {
          return Error("--output-tensor-format must be binary|json");
        }
        break;
      case kOptRanks:
        params->ranks = atoi(optarg);
        if (params->ranks < 1) {
          return Error("--ranks must be >= 1");
        }
        // --ranks 1 is a plain single-process run, not an MPI run.
        if (params->ranks > 1) params->enable_mpi = true;
        break;
      case kOptEnableMpi:
        params->enable_mpi = true;
        break;
      case kOptLogFrequency:
        params->log_frequency = atoll(optarg);
        break;
      case kOptVersion:
        printf("perf_analyzer (client_tpu native harness)\n");
        exit(0);
      case kOptGrpcCompression:
        params->grpc_compression_algorithm = optarg;
        if (params->grpc_compression_algorithm != "none" &&
            params->grpc_compression_algorithm != "gzip" &&
            params->grpc_compression_algorithm != "deflate") {
          return Error(
              "--grpc-compression-algorithm must be none, gzip, or "
              "deflate");
        }
        break;
      case kOptServiceKind:
        params->service_kind = optarg;
        if (params->service_kind != "triton" &&
            params->service_kind != "openai" &&
            params->service_kind != "torchserve" &&
            params->service_kind != "tfserving" &&
            params->service_kind != "in_process") {
          return Error("--service-kind must be triton, openai, "
                       "torchserve, tfserving, or in_process");
        }
        break;
      case kOptEndpoint: params->endpoint = optarg; break;
      default:
        return Error("unknown option (see usage)");
    }
  }
  if (params->model_name.empty()) {
    return Error("-m <model name> is required");
  }
  int mode_count = (params->has_concurrency_range ? 1 : 0) +
                   (params->has_request_rate_range ? 1 : 0) +
                   (params->request_intervals_file.empty() ? 0 : 1) +
                   (params->has_periodic_range ? 1 : 0);
  if (mode_count > 1) {
    return Error("load modes are mutually exclusive");
  }
  return Error::Success;
}

}  // namespace perf
}  // namespace tpuclient
