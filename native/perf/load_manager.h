// Load-generation layer of the native perf harness: request records,
// context-id trackers, sequence bookkeeping, shared-memory data
// managers, and the load-manager hierarchy (concurrency /
// request-rate / custom-interval / periodic-concurrency).
//
// Parity map into /root/reference/src/c++/perf_analyzer/:
//   RequestRecord        -> request_record.h:63
//   ThreadStat           -> load_manager.h:137
//   FifoCtxIdTracker     -> fifo_ctx_id_tracker.h:35
//   SequenceManager      -> sequence_manager.h:46
//   InferDataManager     -> infer_data_manager.h:40 / _shm.h:93
//   LoadManager          -> load_manager.h:48
//   ConcurrencyManager   -> concurrency_manager.h:95 (+ worker .cc:42)
//   RequestRateManager   -> request_rate_manager.h:57
//   CustomLoadManager    -> custom_load_manager.h:46
//   PeriodicConcurrencyManager -> periodic_concurrency_manager.h:39
//
// The CUDA shared-memory data path is replaced by the TPU HBM arena:
// region creation/population goes through TpuArenaClient (gRPC
// side-channel to the server that owns the accelerator) instead of
// cudaMalloc + cudaIpcGetMemHandle.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "../library/common.h"
#include "client_backend.h"
#include "data_loader.h"
#include "model_parser.h"

namespace tpuclient {
namespace perf {

uint64_t NowNs();

//==============================================================================
// One request's timestamps + outcome.
//
struct RequestRecord {
  uint64_t start_ns = 0;
  std::vector<uint64_t> end_ns;  // one per response (streaming)
  bool delayed = false;
  bool sequence_end = true;
  bool has_error = false;
  std::string error;

  bool valid() const { return !end_ns.empty() && !has_error; }
  uint64_t latency_ns() const {
    return end_ns.empty() ? 0 : end_ns.back() - start_ns;
  }
};

//==============================================================================
// Per-worker record sink + health.
//
struct ThreadStat {
  std::mutex mutex;
  std::vector<RequestRecord> records;
  std::string status;  // non-empty = worker failed
  // Time this worker spent with nothing to do (waiting for a free
  // context slot / the pacing schedule) — the reference's IdleTimer
  // (idle_timer.h): the profiler turns it into an overhead_pct that
  // flags harness-bound measurements.
  std::atomic<uint64_t> idle_ns{0};

  void AddRecord(RequestRecord&& record) {
    std::lock_guard<std::mutex> lock(mutex);
    records.push_back(std::move(record));
  }

  void AddIdle(uint64_t ns) { idle_ns.fetch_add(ns); }
};

//==============================================================================
// Free-slot tracker deciding which context id a worker uses next.
//
class FifoCtxIdTracker {
 public:
  virtual ~FifoCtxIdTracker() = default;

  virtual void Reset(size_t count);
  // Blocks up to timeout_ms for a free slot; returns -1 on timeout.
  int Get(int timeout_ms);
  void Release(int ctx_id);
  size_t FreeCount();

 protected:
  // Picks which free slot Get() hands out (index into free_).
  virtual size_t PickIndex(size_t free_count) { return 0; }

  std::mutex mutex_;
  std::condition_variable cv_;
  std::deque<int> free_;
};

// Random slot selection (parity: RandCtxIdTracker,
// rand_ctx_id_tracker.h:36 — exercises sequence slots non-uniformly).
class RandCtxIdTracker : public FifoCtxIdTracker {
 protected:
  size_t PickIndex(size_t free_count) override {
    return rng_() % free_count;
  }

 private:
  std::mt19937_64 rng_{std::random_device{}()};
};

// Non-sequence concurrency: context identity is irrelevant, so every
// slot is id 0 and the tracker is purely an in-flight counter
// (parity: ConcurrencyCtxIdTracker,
// concurrency_ctx_id_tracker.h:35 — Reset enqueues `count` zeros).
class ConcurrencyCtxIdTracker : public FifoCtxIdTracker {
 public:
  void Reset(size_t count) override;
};

// Strategy selection (parity: ctx_id_tracker_factory.h): sequence
// slots carry per-slot state so their id matters — FIFO for ordered
// reuse on the async path, RAND on the stream path to exercise slots
// non-uniformly; non-sequence concurrency counts in-flight only.
std::shared_ptr<FifoCtxIdTracker> MakeCtxIdTracker(
    bool sequences_active, bool prefer_random);

//==============================================================================
// Sequence-id allocation and per-slot sequence progress.
//
class SequenceManager {
 public:
  SequenceManager(
      uint64_t start_id = 1, uint64_t id_range = (1ull << 31),
      size_t sequence_length = 20, double length_variation = 0.2,
      uint64_t seed = 3)
      : next_offset_(0), start_id_(start_id), id_range_(id_range),
        length_(sequence_length), variation_(length_variation), rng_(seed) {}

  struct Slot {
    uint64_t sequence_id = 0;
    size_t remaining = 0;
    size_t step = 0;
    size_t stream = 0;
    bool active = false;
  };

  // Fills request options for the slot's next sequence step, starting
  // a fresh sequence when the slot is idle. Also outputs the data
  // (stream, step) the request should use.
  void NextStep(
      Slot* slot, size_t stream_count, size_t steps_in_stream,
      InferOptions* options, size_t* stream, size_t* step);

 private:
  std::mutex mutex_;
  uint64_t next_offset_;
  uint64_t start_id_;
  uint64_t id_range_;
  size_t length_;
  double variation_;
  std::mt19937_64 rng_;
};

//==============================================================================
// Prepares per-request inputs/outputs. SHM modes create one region
// per input x stream x step named "<input>_<stream>_<step>", populate
// it (memcpy for system shm; arena WriteRegion for TPU), register it
// with the server, and route requests through SetSharedMemory.
//
enum class SharedMemoryType { NONE, SYSTEM, TPU };

class InferDataManager {
 public:
  InferDataManager(
      const ParsedModel* model, const DataLoader* loader,
      SharedMemoryType shm_type = SharedMemoryType::NONE,
      size_t output_shm_size = 102400, std::string arena_url = "",
      int64_t batch_size = 1)
      : model_(model), loader_(loader), shm_type_(shm_type),
        output_shm_size_(output_shm_size), arena_url_(std::move(arena_url)),
        batch_(batch_size < 1 ? 1 : batch_size) {}
  ~InferDataManager();

  Error Init(ClientBackend* backend);
  Error Cleanup(ClientBackend* backend);

  // Builds fresh InferInput objects (cheap views over shared
  // buffers; InferInput send-iteration is stateful so they are not
  // shared across in-flight requests).
  Error BuildInputs(
      size_t stream, size_t step,
      std::vector<std::unique_ptr<InferInput>>* inputs);
  // SHM modes route outputs into pre-registered regions; otherwise
  // returns an empty list (server returns all outputs inline).
  Error BuildOutputs(
      std::vector<std::unique_ptr<InferRequestedOutput>>* outputs);

 private:
  struct SystemRegion {
    std::string name;
    std::string key;
    int fd = -1;
    void* addr = nullptr;
    size_t byte_size = 0;
  };
  struct TpuRegion {
    std::string name;
    std::string region_id;
    std::string raw_handle;
    size_t byte_size = 0;
  };

  Error CreateInputRegion(
      ClientBackend* backend, const std::string& region,
      const ModelTensor& tensor, const TensorData& data);
  Error CreateOutputRegion(ClientBackend* backend, const std::string& region);

  // Per-row replication count for a tensor: batch_ for ordinary
  // batched inputs, 1 for non-batching models AND for shape tensors
  // (their values describe shapes — one value set per batch, never
  // replicated per row).
  int64_t CopiesFor(const ModelTensor& tensor) const {
    return (model_->max_batch_size > 0 && !tensor.is_shape_tensor)
               ? batch_
               : 1;
  }

  // The batched payload for (input, stream, step): data repeated
  // CopiesFor(tensor) times. Stable storage referenced by non-shm
  // InferInputs.
  const std::string* BatchedBytes(
      const ModelTensor& tensor, size_t stream, size_t step,
      const TensorData& data);

  const ParsedModel* model_;
  const DataLoader* loader_;
  SharedMemoryType shm_type_;
  size_t output_shm_size_;
  std::string arena_url_;
  int64_t batch_;

  std::unique_ptr<TpuArenaClient> arena_;
  std::vector<SystemRegion> system_regions_;
  std::vector<TpuRegion> tpu_regions_;
  std::map<std::string, std::string> output_regions_;  // output -> region
  std::map<std::string, std::string> batched_cache_;
  std::mutex cache_mutex_;
};

//==============================================================================
// Load-manager base: worker threads, records, step cursor.
//
class LoadManager {
 public:
  struct Options {
    bool async_mode = true;
    bool streaming = false;
    size_t max_threads = 16;
    // Sequence load shaping (reference --num-of-sequences /
    // --serial-sequences): how many sequences run concurrently in
    // request-rate mode, and whether a sequence may ever have more
    // than one request in flight.
    size_t num_of_sequences = 4;
    bool serial_sequences = false;
    // "name:value:type" custom request parameters attached to every
    // request (reference --request-parameter).
    std::vector<std::string> request_parameters;
  };

  LoadManager(
      const ClientBackendFactory* factory, const ParsedModel* model,
      const DataLoader* loader, InferDataManager* data_manager,
      Options options, SequenceManager* sequence_manager = nullptr);
  virtual ~LoadManager();

  // Creates the setup backend and initializes the data manager
  // (registering shm regions with the server).
  Error Init();
  void Cleanup();

  // Drains all worker records (parity: SwapRequestRecords).
  std::vector<RequestRecord> SwapRequestRecords();
  size_t CountCollectedRequests();
  // Average idle ns per active worker since the last call (parity:
  // LoadManager::GetIdleTime averaging thread_stat idle timers).
  uint64_t GetAndResetIdleNs();
  // Non-empty on worker failure (parity: CheckHealth).
  Error CheckHealth();
  virtual void Stop();

  ClientBackend* setup_backend() { return setup_backend_.get(); }

 protected:
  // One request's inputs/outputs/options. seq slot may be null.
  Error PrepareRequest(
      SequenceManager::Slot* slot,
      std::vector<std::unique_ptr<InferInput>>* inputs,
      std::vector<std::unique_ptr<InferRequestedOutput>>* outputs,
      InferOptions* options);
  Error ApplyRequestParameters(InferOptions* options);
  size_t NextStep(size_t stream);

  const ClientBackendFactory* factory_;
  const ParsedModel* model_;
  const DataLoader* loader_;
  InferDataManager* data_manager_;
  Options options_;
  SequenceManager* sequence_manager_;

  std::unique_ptr<ClientBackend> setup_backend_;
  std::vector<std::thread> threads_;
  std::vector<std::unique_ptr<ThreadStat>> thread_stats_;
  std::atomic<bool> stop_{false};

  std::mutex step_mutex_;
  std::map<size_t, size_t> step_cursor_;
};

//==============================================================================
// Maintains exactly N in-flight requests.
//
class ConcurrencyManager : public LoadManager {
 public:
  using LoadManager::LoadManager;

  // Stops current workers and relaunches with the new level
  // (parity: ChangeConcurrencyLevel).
  Error ChangeConcurrencyLevel(size_t concurrency);
  size_t concurrency() const { return concurrency_; }

 private:
  void Worker(ThreadStat* stat, size_t n_ctx);
  void SyncWorker(ThreadStat* stat, ClientBackend* backend, size_t n_ctx);
  void AsyncWorker(ThreadStat* stat, ClientBackend* backend, size_t n_ctx);
  void StreamWorker(ThreadStat* stat, ClientBackend* backend, size_t n_ctx);

  size_t concurrency_ = 0;
};

//==============================================================================
// Dispatches from a precomputed schedule at a fixed rate (constant or
// poisson); late sends are flagged delayed.
//
class RequestRateManager : public LoadManager {
 public:
  enum class Distribution { CONSTANT, POISSON };

  RequestRateManager(
      const ClientBackendFactory* factory, const ParsedModel* model,
      const DataLoader* loader, InferDataManager* data_manager,
      Options options, Distribution distribution = Distribution::CONSTANT,
      SequenceManager* sequence_manager = nullptr)
      : LoadManager(factory, model, loader, data_manager, options,
                    sequence_manager),
        distribution_(distribution) {}

  Error ChangeRequestRate(double rate, double duration_s = 3600.0);
  // Absolute schedule from explicit inter-request gaps (seconds),
  // cycled to cover a long window (CustomLoadManager path).
  Error SetCustomSchedule(const std::vector<double>& intervals_s);

 protected:
  void LaunchScheduleWorkers();
  void ScheduleWorker(
      ThreadStat* stat, size_t worker_idx, size_t n_workers,
      uint64_t start_ns);

  Distribution distribution_;
  std::vector<double> schedule_;  // offsets in seconds
};

//==============================================================================
// Replays user-provided request intervals (one microsecond value per
// line — the --request-intervals mode).
//
class CustomLoadManager : public RequestRateManager {
 public:
  using RequestRateManager::RequestRateManager;

  static Error ReadIntervalsFile(
      const std::string& path, std::vector<double>* intervals_s);
  Error StartSchedule(const std::string& intervals_file);
};

//==============================================================================
// Ramps concurrency start->end by step every request_period completed
// requests (LLM-oriented).
//
class PeriodicConcurrencyManager : public ConcurrencyManager {
 public:
  using ConcurrencyManager::ConcurrencyManager;

  struct RampConfig {
    size_t start = 1;
    size_t end = 8;
    size_t step = 1;
    size_t request_period = 10;
  };

  // Runs the ramp to completion (blocking); records accumulate across
  // levels and can be drained afterwards.
  Error RunRamp(const RampConfig& config);

  std::vector<RequestRecord> SwapRampRecords();

 private:
  std::vector<RequestRecord> carry_records_;
  std::mutex carry_mutex_;
};

}  // namespace perf
}  // namespace tpuclient
