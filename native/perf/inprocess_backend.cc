#include "inprocess_backend.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <condition_variable>
#include <cstdlib>
#include <deque>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

#include "../library/grpc_client.h"
#include "client_tpu/protocol/inference.pb.h"

namespace tpuclient {
namespace perf {

namespace {

//==============================================================================
// Embedded CPython runtime (process singleton).

std::string RepoRootGuess() {
  const char* env = std::getenv("TPUCLIENT_REPO_ROOT");
  if (env != nullptr && env[0] != '\0') return env;
  // Binary lives at <root>/native/build/perf_analyzer.
  char buf[4096];
  ssize_t n = readlink("/proc/self/exe", buf, sizeof(buf) - 1);
  if (n > 0) {
    std::string path(buf, n);
    size_t cut = path.rfind("/native/build/");
    if (cut != std::string::npos) return path.substr(0, cut);
  }
  return ".";
}

class PythonEmbed {
 public:
  static PythonEmbed& Get() {
    static PythonEmbed instance;
    return instance;
  }

  Error EnsureInit(const std::string& models_csv) {
    std::lock_guard<std::mutex> lk(init_mutex_);
    if (initialized_) return init_error_;
    initialized_ = true;

    std::string repo = RepoRootGuess();
    std::string pythonpath = repo;
    // The embedded interpreter boots from the base install; graft the
    // active venv's site-packages (jax & friends live there).
    const char* venv = std::getenv("VIRTUAL_ENV");
    std::string site =
        std::string(venv != nullptr ? venv : "/opt/venv") +
        "/lib/python" + std::to_string(PY_MAJOR_VERSION) + "." +
        std::to_string(PY_MINOR_VERSION) + "/site-packages";
    if (access(site.c_str(), F_OK) == 0) pythonpath += ":" + site;
    const char* existing = std::getenv("PYTHONPATH");
    if (existing != nullptr && existing[0] != '\0') {
      pythonpath += ":" + std::string(existing);
    }
    setenv("PYTHONPATH", pythonpath.c_str(), 1);

    Py_InitializeEx(0);
    module_ = PyImport_ImportModule("client_tpu.server.embed");
    if (module_ == nullptr) {
      init_error_ = FetchPyError("import client_tpu.server.embed");
      PyEval_SaveThread();
      return init_error_;
    }
    PyObject* r = PyObject_CallMethod(
        module_, "init", "s", models_csv.c_str());
    if (r == nullptr) {
      init_error_ = FetchPyError("embed.init");
    }
    Py_XDECREF(r);
    // Release the GIL so harness worker threads can take it per call.
    PyEval_SaveThread();
    return init_error_;
  }

  // fn(bytes) -> bytes
  Error CallBytes(
      const char* fn, const std::string& arg, std::string* result) {
    PyGILState_STATE gil = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        module_, fn, "y#", arg.data(), (Py_ssize_t)arg.size());
    Error err = Error::Success;
    if (r == nullptr) {
      err = FetchPyError(fn);
    } else {
      char* data = nullptr;
      Py_ssize_t size = 0;
      if (PyBytes_AsStringAndSize(r, &data, &size) != 0) {
        err = FetchPyError(fn);
      } else {
        result->assign(data, (size_t)size);
      }
      Py_DECREF(r);
    }
    PyGILState_Release(gil);
    return err;
  }

  // fn(*args) -> str  (args passed by Py_BuildValue format)
  Error CallStr(
      const char* fn, const char* format, std::string* result,
      const char* a0 = nullptr, const char* a1 = nullptr) {
    PyGILState_STATE gil = PyGILState_Ensure();
    PyObject* r = (a1 != nullptr)
                      ? PyObject_CallMethod(module_, fn, format, a0, a1)
                      : (a0 != nullptr)
                            ? PyObject_CallMethod(module_, fn, format, a0)
                            : PyObject_CallMethod(module_, fn, nullptr);
    Error err = Error::Success;
    if (r == nullptr) {
      err = FetchPyError(fn);
    } else {
      Py_ssize_t size = 0;
      const char* text = PyUnicode_AsUTF8AndSize(r, &size);
      if (text == nullptr) {
        err = FetchPyError(fn);
      } else {
        result->assign(text, (size_t)size);
      }
      Py_DECREF(r);
    }
    PyGILState_Release(gil);
    return err;
  }

  // Builds an argument tuple under the GIL via a callback.
  template <typename BuildFn>
  Error CallVoidBuilt(const char* fn, BuildFn build) {
    PyGILState_STATE gil = PyGILState_Ensure();
    Error err = Error::Success;
    PyObject* args = build();
    if (args == nullptr) {
      err = FetchPyError(fn);
    } else {
      PyObject* callable = PyObject_GetAttrString(module_, fn);
      if (callable == nullptr) {
        err = FetchPyError(fn);
      } else {
        PyObject* r = PyObject_CallObject(callable, args);
        if (r == nullptr) err = FetchPyError(fn);
        Py_XDECREF(r);
        Py_DECREF(callable);
      }
      Py_DECREF(args);
    }
    PyGILState_Release(gil);
    return err;
  }

  // fn(byte_size, device_id) -> bytes
  Error CallAllocate(
      size_t byte_size, int64_t device_id, std::string* handle) {
    PyGILState_STATE gil = PyGILState_Ensure();
    PyObject* r = PyObject_CallMethod(
        module_, "tpu_arena_allocate", "nL", (Py_ssize_t)byte_size,
        (long long)device_id);
    Error err = Error::Success;
    if (r == nullptr) {
      err = FetchPyError("tpu_arena_allocate");
    } else {
      char* data = nullptr;
      Py_ssize_t size = 0;
      if (PyBytes_AsStringAndSize(r, &data, &size) != 0) {
        err = FetchPyError("tpu_arena_allocate");
      } else {
        handle->assign(data, (size_t)size);
      }
      Py_DECREF(r);
    }
    PyGILState_Release(gil);
    return err;
  }

 private:
  PythonEmbed() = default;

  // Caller holds the GIL. Converts the pending Python exception into
  // an Error (InferenceServerException str() carries "[STATUS] msg").
  static Error FetchPyError(const char* what) {
    PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
    PyErr_Fetch(&type, &value, &trace);
    std::string message = std::string(what) + " failed";
    if (value != nullptr) {
      PyObject* s = PyObject_Str(value);
      if (s != nullptr) {
        const char* text = PyUnicode_AsUTF8(s);
        if (text != nullptr) message = text;
        Py_DECREF(s);
      }
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(trace);
    return Error(message);
  }

  std::mutex init_mutex_;
  bool initialized_ = false;
  Error init_error_ = Error::Success;
  PyObject* module_ = nullptr;
};

//==============================================================================
// Async worker pool: the dynamic batcher fuses requests only when
// several are in flight, so async mode needs real concurrent callers
// (each blocks in Python with the GIL released while waiting).

class AsyncPool {
 public:
  struct Job {
    std::function<void()> run;
  };

  static AsyncPool& Get() {
    // Deliberately leaked: a static-duration destructor would tear
    // down the mutex/cv while detached workers may still touch them.
    static AsyncPool* pool = new AsyncPool();
    return *pool;
  }

  void Submit(std::function<void()> run) {
    {
      std::lock_guard<std::mutex> lk(mutex_);
      queue_.push_back({std::move(run)});
      // Grow to match offered concurrency (capped): a fixed pool
      // would silently clamp --concurrency-range above its size and
      // misreport latency for the queued remainder.
      size_t wanted = queue_.size() + busy_;
      while (workers_.size() < wanted && workers_.size() < kMaxWorkers) {
        workers_.emplace_back([this] { Loop(); });
      }
    }
    cv_.notify_one();
  }

 private:
  static constexpr size_t kMaxWorkers = 128;

  AsyncPool() = default;

  void Loop() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mutex_);
        cv_.wait(lk, [this] { return !queue_.empty(); });
        job = std::move(queue_.front());
        queue_.pop_front();
        ++busy_;
      }
      job.run();
      {
        std::lock_guard<std::mutex> lk(mutex_);
        --busy_;
      }
    }
  }

  std::mutex mutex_;
  std::condition_variable cv_;
  std::deque<Job> queue_;
  std::vector<std::thread> workers_;
  size_t busy_ = 0;
};

Error ParseJsonText(const std::string& text, json::Value* out) {
  std::string err = json::Parse(text.data(), text.size(), out);
  if (!err.empty()) return Error("malformed embed JSON: " + err);
  return Error::Success;
}

}  // namespace

//==============================================================================
// InProcessBackend

Error InProcessBackend::Create(
    const BackendConfig& config, std::unique_ptr<ClientBackend>* backend) {
  Error err = PythonEmbed::Get().EnsureInit(config.inprocess_models);
  if (!err.IsOk()) return err;
  backend->reset(new InProcessBackend());
  return Error::Success;
}

Error InProcessBackend::ServerMetadataJson(json::Value* metadata) {
  std::string text;
  Error err =
      PythonEmbed::Get().CallStr("server_metadata_json", nullptr, &text);
  if (!err.IsOk()) return err;
  return ParseJsonText(text, metadata);
}

Error InProcessBackend::ModelMetadataJson(
    json::Value* metadata, const std::string& model_name,
    const std::string& model_version) {
  std::string text;
  Error err = PythonEmbed::Get().CallStr(
      "model_metadata_json", "ss", &text, model_name.c_str(),
      model_version.c_str());
  if (!err.IsOk()) return err;
  return ParseJsonText(text, metadata);
}

Error InProcessBackend::ModelConfigJson(
    json::Value* config, const std::string& model_name,
    const std::string& model_version) {
  std::string text;
  Error err = PythonEmbed::Get().CallStr(
      "model_config_json", "ss", &text, model_name.c_str(),
      model_version.c_str());
  if (!err.IsOk()) return err;
  return ParseJsonText(text, config);
}

Error InProcessBackend::ModelStatisticsJson(
    json::Value* stats, const std::string& model_name) {
  std::string text;
  Error err = PythonEmbed::Get().CallStr(
      "model_statistics_json", "s", &text, model_name.c_str());
  if (!err.IsOk()) return err;
  return ParseJsonText(text, stats);
}

Error InProcessBackend::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  inference::ModelInferRequest request;
  Error err = InferenceServerGrpcClient::PreRunProcessing(
      &request, options, inputs, outputs);
  if (!err.IsOk()) return err;
  std::string request_bytes;
  if (!request.SerializeToString(&request_bytes)) {
    return Error("failed to serialize request");
  }
  std::string response_bytes;
  err = PythonEmbed::Get().CallBytes("infer", request_bytes, &response_bytes);
  if (!err.IsOk()) return err;
  auto response = std::make_shared<inference::ModelInferResponse>();
  if (!response->ParseFromString(response_bytes)) {
    return Error("failed to parse embed response");
  }
  return InferResultGrpc::Create(result, std::move(response));
}

Error InProcessBackend::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInfer");
  }
  // Inputs are marshalled into the proto NOW (the caller may reuse
  // its buffers after we return), then the blocking call runs on the
  // pool so several requests sit inside the server core concurrently
  // — that is what lets the dynamic batcher fuse them.
  inference::ModelInferRequest request;
  Error err = InferenceServerGrpcClient::PreRunProcessing(
      &request, options, inputs, outputs);
  if (!err.IsOk()) return err;
  auto request_bytes = std::make_shared<std::string>();
  if (!request.SerializeToString(request_bytes.get())) {
    return Error("failed to serialize request");
  }
  AsyncPool::Get().Submit([request_bytes, callback] {
    std::string response_bytes;
    Error call_err = PythonEmbed::Get().CallBytes(
        "infer", *request_bytes, &response_bytes);
    auto response = std::make_shared<inference::ModelInferResponse>();
    if (call_err.IsOk() && !response->ParseFromString(response_bytes)) {
      call_err = Error("failed to parse embed response");
    }
    InferResult* result = nullptr;
    InferResultGrpc::Create(&result, std::move(response), call_err);
    callback(result);
  });
  return Error::Success;
}

Error InProcessBackend::StartStream(OnCompleteFn /*callback*/) {
  return Error("streaming is not supported by the in_process backend");
}

Error InProcessBackend::StopStream() {
  return Error("streaming is not supported by the in_process backend");
}

Error InProcessBackend::AsyncStreamInfer(
    const InferOptions&, const std::vector<InferInput*>&,
    const std::vector<const InferRequestedOutput*>&) {
  return Error("streaming is not supported by the in_process backend");
}

Error InProcessBackend::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset) {
  return PythonEmbed::Get().CallVoidBuilt(
      "register_system_shared_memory", [&]() {
        return Py_BuildValue(
            "ssnn", name.c_str(), key.c_str(), (Py_ssize_t)byte_size,
            (Py_ssize_t)offset);
      });
}

Error InProcessBackend::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle,
    int64_t device_id, size_t byte_size) {
  return PythonEmbed::Get().CallVoidBuilt(
      "register_tpu_shared_memory", [&]() {
        return Py_BuildValue(
            "sy#Ln", name.c_str(), raw_handle.data(),
            (Py_ssize_t)raw_handle.size(), (long long)device_id,
            (Py_ssize_t)byte_size);
      });
}

Error InProcessBackend::UnregisterSystemSharedMemory(
    const std::string& name) {
  return PythonEmbed::Get().CallVoidBuilt(
      "unregister_system_shared_memory",
      [&]() { return Py_BuildValue("(s)", name.c_str()); });
}

Error InProcessBackend::UnregisterTpuSharedMemory(const std::string& name) {
  return PythonEmbed::Get().CallVoidBuilt(
      "unregister_tpu_shared_memory",
      [&]() { return Py_BuildValue("(s)", name.c_str()); });
}

Error InProcessBackend::ArenaAllocate(
    size_t byte_size, int64_t device_id, std::string* raw_handle) {
  return PythonEmbed::Get().CallAllocate(byte_size, device_id, raw_handle);
}

}  // namespace perf
}  // namespace tpuclient
