// Rank coordination for multi-process perf runs (parity:
// /root/reference/src/c++/perf_analyzer/mpi_utils.h:32-80 — libmpi is
// dlopen'd at runtime, never a compile-time dependency; without it
// every call degrades to single-rank no-ops). Used to launch several
// analyzer ranks against one server and synchronize their
// measurement windows.
//
// Two transports, one facade:
//  - MPI: when launched under mpirun/mpiexec with a loadable libmpi,
//    collectives ride MPI_Allreduce/MPI_Barrier (the reference's
//    only mode).
//  - Built-in coordinator: when the TPUCLIENT_COORDINATOR /
//    TPUCLIENT_WORLD_SIZE / TPUCLIENT_RANK environment variables are
//    set (the same coordinator_address / num_processes / process_id
//    contract as jax.distributed.initialize), rank 0 listens on the
//    coordinator address and the collectives run over a TCP star.
//    This makes multi-rank scale-out work on hosts with no MPI
//    launcher at all — each rank is started by hand, a script, or a
//    scheduler, exactly like a JAX multi-host job.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace tpuclient {
namespace perf {

class MPIDriver {
 public:
  // is_enabled requests coordination; the driver only becomes active
  // when (a) libmpi.so is loadable AND the process runs under mpirun
  // (world size resolvable), or (b) the TPUCLIENT_COORDINATOR env
  // contract names this process's rank in a multi-rank world.
  explicit MPIDriver(bool is_enabled);
  ~MPIDriver();

  bool IsMPIRun() const { return active_; }

  void MPIInit();
  void MPIFinalize();
  void MPIBarrierWorld();
  int MPICommSizeWorld() const;
  int MPICommRankWorld() const;
  // Logical-AND reduce of a local flag across ranks (used to agree
  // on measurement stability; parity: the reference's AllGather over
  // stability decisions).
  bool MPIAllTrue(bool local) const;

 private:
  // Built-in coordinator transport.
  bool BuiltinInit();
  bool BuiltinCollective(bool local, bool* result) const;
  void BuiltinTeardown() const;

  // active_ / seq_ / fds are mutable so a socket failure inside the
  // const collective entry points can deactivate the driver and
  // degrade to rank-local decisions instead of hanging peers.
  mutable bool active_ = false;
  void* handle_ = nullptr;
  // Bound symbols (only valid while active_ on the MPI transport).
  int (*init_)(int*, char***) = nullptr;
  int (*finalize_)() = nullptr;
  int (*barrier_)(void*) = nullptr;
  int (*comm_size_)(void*, int*) = nullptr;
  int (*comm_rank_)(void*, int*) = nullptr;
  int (*allreduce_)(const void*, void*, int, void*, void*, void*) = nullptr;
  void* comm_world_ = nullptr;
  void* type_int_ = nullptr;
  void* op_land_ = nullptr;

  // Built-in coordinator state.
  bool builtin_ = false;
  int rank_ = 0;
  int world_size_ = 1;
  std::string coord_host_;
  int coord_port_ = 0;
  double timeout_s_ = 60.0;             // join/connect window
  double collective_timeout_s_ = 600.0;  // per-collective skew budget
  mutable int listen_fd_ = -1;
  // Coordinator: one socket per peer rank (index rank-1).
  // Non-coordinator: a single socket to rank 0 at index 0.
  mutable std::vector<int> fds_;
  mutable uint32_t seq_ = 0;
};

}  // namespace perf
}  // namespace tpuclient
