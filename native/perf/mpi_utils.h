// Optional MPI coordination for multi-process perf runs (parity:
// /root/reference/src/c++/perf_analyzer/mpi_utils.h:32-80 — libmpi is
// dlopen'd at runtime, never a compile-time dependency; without it
// every call degrades to single-rank no-ops). Used to launch several
// analyzer ranks against one server and synchronize their
// measurement windows.
#pragma once

#include <string>

namespace tpuclient {
namespace perf {

class MPIDriver {
 public:
  // is_enabled requests MPI; the driver only becomes active when
  // libmpi.so is loadable AND the process runs under mpirun (world
  // size resolvable).
  explicit MPIDriver(bool is_enabled);
  ~MPIDriver();

  bool IsMPIRun() const { return active_; }

  void MPIInit();
  void MPIFinalize();
  void MPIBarrierWorld();
  int MPICommSizeWorld() const;
  int MPICommRankWorld() const;
  // Logical-AND reduce of a local flag across ranks (used to agree
  // on measurement stability; parity: the reference's AllGather over
  // stability decisions).
  bool MPIAllTrue(bool local) const;

 private:
  bool active_ = false;
  void* handle_ = nullptr;
  // Bound symbols (only valid while active_).
  int (*init_)(int*, char***) = nullptr;
  int (*finalize_)() = nullptr;
  int (*barrier_)(void*) = nullptr;
  int (*comm_size_)(void*, int*) = nullptr;
  int (*comm_rank_)(void*, int*) = nullptr;
  int (*allreduce_)(const void*, void*, int, void*, void*, void*) = nullptr;
  void* comm_world_ = nullptr;
  void* type_int_ = nullptr;
  void* op_land_ = nullptr;
};

}  // namespace perf
}  // namespace tpuclient
