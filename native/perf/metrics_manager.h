// Server accelerator-metrics collection (parity:
// /root/reference/src/c++/perf_analyzer/metrics_manager.h:56-82 —
// a poller thread scrapes the server's Prometheus /metrics every
// interval and the profiler pairs per-window summaries with its
// measurements). The DCGM GPU gauges of the reference map to the TPU
// server's HBM gauges: tpu_hbm_used_bytes / tpu_hbm_total_bytes /
// tpu_hbm_utilization, labelled by tpu_uuid.
#pragma once

#include <atomic>
#include <condition_variable>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "../library/common.h"

namespace tpuclient {
namespace perf {

// One scrape: {family -> {tpu_uuid -> value}}.
struct TpuMetrics {
  std::map<std::string, std::map<std::string, double>> families;
};

// {family -> {avg, max}} across a window's scrapes, averaged over
// devices first.
using TpuMetricsSummary = std::map<std::string, std::pair<double, double>>;

TpuMetrics ParsePrometheus(const std::string& text);
TpuMetricsSummary SummarizeMetrics(const std::vector<TpuMetrics>& snapshots);

class MetricsManager {
 public:
  // url is "host:port" or "host:port/metrics".
  MetricsManager(const std::string& url, uint64_t interval_ms = 1000);
  ~MetricsManager();

  // Scrapes once synchronously; fails fast when the endpoint is
  // unreachable (parity: CheckForMissingMetrics).
  Error CheckReachable();

  void Start();
  void Stop();

  // Drains the snapshots collected since the last call.
  std::vector<TpuMetrics> GetAndReset();

  size_t scrape_failures() const { return scrape_failures_.load(); }

 private:
  Error ScrapeOnce(TpuMetrics* metrics);
  void PollLoop();

  std::string host_;
  int port_ = 8000;
  std::string path_ = "/metrics";
  uint64_t interval_ms_;

  std::thread poller_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool stopping_ = false;
  std::vector<TpuMetrics> snapshots_;
  std::atomic<size_t> scrape_failures_{0};
};

}  // namespace perf
}  // namespace tpuclient
