// Backend abstraction for the native perf harness.
//
// Mirrors the reference perf_analyzer's cb::ClientBackend
// (/root/reference/src/c++/perf_analyzer/client_backend/
// client_backend.h:366) and its factory (:268): a backend-neutral
// veneer over the protocol clients so the load-generation layer is
// transport-agnostic. Concrete backends: TRITON_GRPC / TRITON_HTTP
// (the native clients in ../library), and MOCK — a fake server with
// programmable per-request delay used by the unit tests (parity:
// mock_client_backend.h:471,617-625).
//
// The CUDA shared-memory verbs are replaced by TPU HBM arena verbs;
// TpuArenaClient is the client side of the arena allocation
// side-channel (client_tpu/protocol/arena.proto), standing in for
// cudaMalloc/cudaIpcGetMemHandle.
#pragma once

#include <atomic>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "../library/common.h"
#include "../library/json.h"
#include "../library/tls.h"

namespace tpuclient {

class GrpcChannel;
class InferenceServerGrpcClient;
class InferenceServerHttpClient;

namespace perf {

enum class BackendKind {
  TRITON_GRPC,
  TRITON_HTTP,
  OPENAI,
  TORCHSERVE,
  TFSERVING,
  MOCK,
  // Embedded server core, no RPC (parity: triton_c_api).
  IN_PROCESS,
};

struct BackendConfig {
  BackendKind kind = BackendKind::TRITON_GRPC;
  std::string url;  // host:port
  bool verbose = false;
  size_t http_async_workers = 8;
  // OPENAI: request path on the server (reference --endpoint).
  std::string openai_endpoint = "/v1/chat/completions";
  // MOCK: simulated per-request latency and failure rate.
  uint64_t mock_delay_us = 500;
  double mock_error_rate = 0.0;
  // MOCK: stream responses per request (>1 simulates a decoupled
  // model — only the last response carries the final flag).
  uint64_t mock_responses_per_request = 1;
  // IN_PROCESS: comma-separated models for embed.init to warm.
  std::string inprocess_models;
  // TFSERVING: gRPC PredictionService (native protocol) vs REST.
  bool tfserving_grpc = true;
  // gRPC message compression for Infer calls ("gzip"/"deflate"/"").
  std::string grpc_compression;
  // TFSERVING: signature to invoke (reference --model-signature-name).
  std::string model_signature_name = "serving_default";
  // HTTPS for the HTTP client (TLS via dlopen'd OpenSSL).
  bool https = false;
  SslOptions https_ssl;
  // HTTP tensor wire format (reference --input-tensor-format /
  // --output-tensor-format): JSON mode interoperates with KServe
  // servers lacking the binary extension.
  bool http_json_input = false;
  bool http_json_output = false;
};

//==============================================================================
// Backend-neutral client (parity: cb::ClientBackend).
//
class ClientBackend {
 public:
  virtual ~ClientBackend() = default;

  virtual Error ServerMetadataJson(json::Value* metadata) = 0;
  virtual Error ModelMetadataJson(
      json::Value* metadata, const std::string& model_name,
      const std::string& model_version = "") = 0;
  virtual Error ModelConfigJson(
      json::Value* config, const std::string& model_name,
      const std::string& model_version = "") = 0;
  // {model_name -> {inference_count, execution_count, ...ns totals}}.
  virtual Error ModelStatisticsJson(
      json::Value* stats, const std::string& model_name = "") = 0;

  virtual Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) = 0;
  virtual Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) = 0;
  virtual Error StartStream(OnCompleteFn callback) = 0;
  virtual Error StopStream() = 0;
  virtual Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs) = 0;

  virtual Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0) = 0;
  virtual Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int64_t device_id, size_t byte_size) = 0;
  virtual Error UnregisterSystemSharedMemory(const std::string& name = "") = 0;
  virtual Error UnregisterTpuSharedMemory(const std::string& name = "") = 0;
};

//==============================================================================
// Factory (parity: ClientBackendFactory::Create,
// client_backend.h:268).
//
class ClientBackendFactory {
 public:
  explicit ClientBackendFactory(BackendConfig config)
      : config_(std::move(config)) {}

  Error Create(std::unique_ptr<ClientBackend>* backend) const;

  const BackendConfig& config() const { return config_; }

 private:
  BackendConfig config_;
};

//==============================================================================
// Client for the TPU HBM arena allocation service — the stand-in for
// client-side cudaMalloc + cudaIpcGetMemHandle (reference
// infer_data_manager_shm.h:56 CreateCUDAIPCHandle).
//
class TpuArenaClient {
 public:
  // url is the gRPC endpoint hosting TpuArenaService (same server
  // process that owns the HBM arena).
  static Error Create(
      std::unique_ptr<TpuArenaClient>* client, const std::string& url);
  ~TpuArenaClient();

  // Allocates an HBM region; returns the opaque raw handle (what gets
  // registered with the inference service) and the region id.
  Error CreateRegion(
      size_t byte_size, int64_t device_id, std::string* raw_handle,
      std::string* region_id);
  // Writes bytes into the region, optionally typed so the server
  // stores a ready-to-consume device array.
  Error WriteRegion(
      const std::string& region_id, size_t offset, const std::string& data,
      const std::string& datatype = "",
      const std::vector<int64_t>& shape = {});
  Error ReadRegion(
      const std::string& region_id, size_t offset, size_t byte_size,
      std::string* data);
  Error DestroyRegion(const std::string& region_id);

 private:
  TpuArenaClient() = default;
  std::shared_ptr<GrpcChannel> channel_;
};

//==============================================================================
// Mock backend call statistics (parity: MockClientStats,
// mock_client_backend.h:145).
//
struct MockBackendStats {
  std::atomic<uint64_t> infer_calls{0};
  std::atomic<uint64_t> async_infer_calls{0};
  std::atomic<uint64_t> stream_infer_calls{0};
  std::atomic<uint64_t> completed{0};
  std::atomic<uint64_t> errors{0};
};

std::shared_ptr<MockBackendStats> GetMockBackendStats();
void ResetMockBackendStats();

// Whether a stream response is the last for its request (decoupled
// models emit several). True for non-stream result types.
bool IsFinalStreamResponse(const InferResult* result);

}  // namespace perf
}  // namespace tpuclient
