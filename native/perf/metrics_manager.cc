#include "metrics_manager.h"

#include <algorithm>
#include <cstring>

#include "../library/http_transport.h"

namespace tpuclient {
namespace perf {

namespace {

const char* kFamilies[] = {
    "tpu_hbm_used_bytes", "tpu_hbm_total_bytes", "tpu_hbm_utilization"};

bool IsTrackedFamily(const std::string& name) {
  for (const char* f : kFamilies) {
    if (name == f) return true;
  }
  return false;
}

}  // namespace

TpuMetrics ParsePrometheus(const std::string& text) {
  TpuMetrics metrics;
  size_t pos = 0;
  while (pos < text.size()) {
    size_t eol = text.find('\n', pos);
    if (eol == std::string::npos) eol = text.size();
    std::string line = text.substr(pos, eol - pos);
    pos = eol + 1;
    if (line.empty() || line[0] == '#') continue;
    // name{labels} value   |   name value
    size_t name_end = line.find_first_of("{ ");
    if (name_end == std::string::npos) continue;
    std::string name = line.substr(0, name_end);
    if (!IsTrackedFamily(name)) continue;
    std::string uuid = "0";
    size_t value_start = name_end;
    if (line[name_end] == '{') {
      size_t close = line.find('}', name_end);
      if (close == std::string::npos) continue;
      std::string labels = line.substr(name_end + 1, close - name_end - 1);
      for (const char* key : {"tpu_uuid=\"", "gpu_uuid=\""}) {
        size_t at = labels.find(key);
        if (at != std::string::npos) {
          at += strlen(key);
          size_t end = labels.find('"', at);
          if (end != std::string::npos) uuid = labels.substr(at, end - at);
          break;
        }
      }
      value_start = close + 1;
    }
    while (value_start < line.size() && line[value_start] == ' ') {
      value_start++;
    }
    char* end = nullptr;
    double value = strtod(line.c_str() + value_start, &end);
    if (end == line.c_str() + value_start) continue;
    metrics.families[name][uuid] = value;
  }
  return metrics;
}

TpuMetricsSummary SummarizeMetrics(const std::vector<TpuMetrics>& snapshots) {
  TpuMetricsSummary summary;
  std::map<std::string, std::vector<double>> per_family;
  for (const auto& snapshot : snapshots) {
    for (const auto& family : snapshot.families) {
      if (family.second.empty()) continue;
      double sum = 0;
      for (const auto& kv : family.second) sum += kv.second;
      per_family[family.first].push_back(sum / family.second.size());
    }
  }
  for (const auto& kv : per_family) {
    double sum = 0, max = 0;
    for (double v : kv.second) {
      sum += v;
      max = std::max(max, v);
    }
    summary[kv.first] = {sum / kv.second.size(), max};
  }
  return summary;
}

MetricsManager::MetricsManager(const std::string& url, uint64_t interval_ms)
    : interval_ms_(interval_ms) {
  std::string rest = url;
  size_t scheme = rest.find("://");
  if (scheme != std::string::npos) rest = rest.substr(scheme + 3);
  size_t slash = rest.find('/');
  if (slash != std::string::npos) {
    path_ = rest.substr(slash);
    rest = rest.substr(0, slash);
  }
  size_t colon = rest.rfind(':');
  if (colon != std::string::npos) {
    port_ = atoi(rest.substr(colon + 1).c_str());
    host_ = rest.substr(0, colon);
  } else {
    host_ = rest;
  }
  if (path_ == "/") path_ = "/metrics";
}

MetricsManager::~MetricsManager() { Stop(); }

Error MetricsManager::ScrapeOnce(TpuMetrics* metrics) {
  HttpConnection conn(host_, port_);
  HttpResponse response;
  std::string err = conn.Request(
      "GET", path_, {}, "", &response, 2 * 1000 * 1000);
  if (!err.empty()) return Error(err);
  if (response.status_code != 200) {
    return Error("metrics endpoint returned HTTP " +
                 std::to_string(response.status_code));
  }
  *metrics = ParsePrometheus(response.body);
  return Error::Success;
}

Error MetricsManager::CheckReachable() {
  TpuMetrics metrics;
  return ScrapeOnce(&metrics);
}

void MetricsManager::Start() {
  Stop();
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stopping_ = false;
  }
  poller_ = std::thread(&MetricsManager::PollLoop, this);
}

void MetricsManager::Stop() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stopping_ = true;
  }
  cv_.notify_all();
  if (poller_.joinable()) poller_.join();
}

void MetricsManager::PollLoop() {
  std::unique_lock<std::mutex> lock(mutex_);
  while (!cv_.wait_for(lock, std::chrono::milliseconds(interval_ms_),
                       [this] { return stopping_; })) {
    lock.unlock();
    TpuMetrics metrics;
    Error err = ScrapeOnce(&metrics);
    lock.lock();
    if (err.IsOk()) {
      snapshots_.push_back(std::move(metrics));
    } else {
      scrape_failures_++;
    }
  }
}

std::vector<TpuMetrics> MetricsManager::GetAndReset() {
  std::lock_guard<std::mutex> lock(mutex_);
  std::vector<TpuMetrics> out;
  out.swap(snapshots_);
  return out;
}

}  // namespace perf
}  // namespace tpuclient
