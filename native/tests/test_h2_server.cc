// Transport-level tests for the native gRPC server (h2_server.cc)
// with a pure-C++ handler — no embedded Python, so this binary also
// runs in the ThreadSanitizer build where CPython is out of scope.
// The client side is the framework's own GrpcChannel: every test is a
// real cross-stack pair (native client transport <-> native server
// transport) over localhost.
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <mutex>
#include <thread>
#include <vector>

#include "../server/h2_server.h"
#include "../server/http1_server.h"
#include "grpc_transport.h"
#include "h2/h2_connection.h"
#include "minitest.h"

using namespace tpuclient;
using namespace tpuclient::server;

namespace {

// Echo-style handler: unary reverses the message, "slow" sleeps
// first; stream returns the message twice; "/fail" aborts with
// status 5.
class StubHandler : public GrpcHandler {
 public:
  int MethodKind(const std::string& path) override {
    if (path == "/test.Svc/Echo" || path == "/test.Svc/Slow" ||
        path == "/test.Svc/Fail") {
      return 1;
    }
    if (path == "/test.Svc/Duplicate" || path == "/test.Svc/Drip") return 2;
    return 0;
  }

  GrpcReply Call(const std::string& path,
                 const std::string& message) override {
    calls++;
    GrpcReply reply;
    if (path == "/test.Svc/Fail") {
      reply.status = 5;
      reply.message = "not found, on purpose";
      return reply;
    }
    if (path == "/test.Svc/Slow") {
      std::this_thread::sleep_for(std::chrono::milliseconds(30));
    }
    reply.responses.push_back(
        std::string(message.rbegin(), message.rend()));
    return reply;
  }

  GrpcReply StreamCall(const std::string& path, const std::string& message,
                       const StreamEmit& emit) override {
    GrpcReply reply;
    if (path == "/test.Svc/Drip") {
      // Slow producer: three messages 60 ms apart, all incremental.
      for (int i = 0; i < 3; ++i) {
        if (i > 0) {
          std::this_thread::sleep_for(std::chrono::milliseconds(60));
        }
        if (!emit(message + "-" + std::to_string(i))) return reply;
      }
      return reply;
    }
    // First copy through the incremental path, second via the
    // returned list — covers both delivery routes.
    if (!emit(message)) return reply;
    reply.responses.push_back(message);
    return reply;
  }

  std::atomic<int> calls{0};
};

struct ServerFixture {
  StubHandler handler;
  H2Server server;

  ServerFixture() : server(&handler, /*workers=*/4) {
    std::string err = server.Listen("127.0.0.1", 0);
    REQUIRE(err.empty());
  }

  std::string url() const {
    return "127.0.0.1:" + std::to_string(server.bound_port());
  }
};

}  // namespace

TEST_CASE("h2 server: unary echo round-trip") {
  ServerFixture fx;
  std::shared_ptr<GrpcChannel> channel;
  REQUIRE_OK(GrpcChannel::Create(&channel, fx.url()));
  std::string response;
  REQUIRE_OK(channel->UnaryCall("/test.Svc/Echo", "hello", &response,
                                5 * 1000 * 1000));
  CHECK_EQ(response, "olleh");
  // Large message: exercises gRPC framing across DATA frames and the
  // server's flow-controlled sends.
  std::string big(300000, 'x');
  big[0] = 'a';
  REQUIRE_OK(channel->UnaryCall("/test.Svc/Echo", big, &response,
                                10 * 1000 * 1000));
  CHECK_EQ(response.size(), big.size());
  CHECK_EQ(response[response.size() - 1], 'a');
  channel->Shutdown();
}

TEST_CASE("h2 server: error trailers and unknown methods") {
  ServerFixture fx;
  std::shared_ptr<GrpcChannel> channel;
  REQUIRE_OK(GrpcChannel::Create(&channel, fx.url()));
  std::string response;
  Error err = channel->UnaryCall("/test.Svc/Fail", "x", &response,
                                 5 * 1000 * 1000);
  CHECK(!err.IsOk());
  CHECK(err.Message().find("not found, on purpose") != std::string::npos);
  err = channel->UnaryCall("/test.Svc/Nope", "x", &response,
                           5 * 1000 * 1000);
  CHECK(!err.IsOk());
  channel->Shutdown();
}

TEST_CASE("h2 server: bidi stream fan-out") {
  ServerFixture fx;
  std::shared_ptr<GrpcChannel> channel;
  REQUIRE_OK(GrpcChannel::Create(&channel, fx.url()));

  std::mutex mutex;
  std::condition_variable cv;
  std::vector<std::string> messages;
  bool done = false;
  Error final_status = Error::Success;

  std::unique_ptr<GrpcBidiStream> stream;
  REQUIRE_OK(channel->StartBidiStream(
      &stream, "/test.Svc/Duplicate",
      [&](std::string&& m) {
        std::lock_guard<std::mutex> lk(mutex);
        messages.push_back(std::move(m));
        cv.notify_all();
      },
      [&](const Error& e) {
        std::lock_guard<std::mutex> lk(mutex);
        done = true;
        final_status = e;
        cv.notify_all();
      }));
  REQUIRE_OK(stream->Write("one"));
  REQUIRE_OK(stream->Write("two"));
  {
    // Each request yields two copies; wait for all four.
    std::unique_lock<std::mutex> lk(mutex);
    CHECK(cv.wait_for(lk, std::chrono::seconds(5),
                      [&] { return messages.size() >= 4; }));
  }
  REQUIRE_OK(stream->WritesDone());
  {
    std::unique_lock<std::mutex> lk(mutex);
    CHECK(cv.wait_for(lk, std::chrono::seconds(5), [&] { return done; }));
  }
  CHECK(final_status.IsOk());
  CHECK_EQ(messages[0], "one");
  CHECK_EQ(messages[1], "one");
  CHECK_EQ(messages[2], "two");
  CHECK_EQ(messages[3], "two");
  channel->Shutdown();
}

TEST_CASE("h2 server: stream responses are delivered incrementally") {
  ServerFixture fx;
  std::shared_ptr<GrpcChannel> channel;
  REQUIRE_OK(GrpcChannel::Create(&channel, fx.url()));

  std::mutex mutex;
  std::condition_variable cv;
  std::vector<std::chrono::steady_clock::time_point> arrivals;
  bool done = false;

  std::unique_ptr<GrpcBidiStream> stream;
  REQUIRE_OK(channel->StartBidiStream(
      &stream, "/test.Svc/Drip",
      [&](std::string&&) {
        std::lock_guard<std::mutex> lk(mutex);
        arrivals.push_back(std::chrono::steady_clock::now());
        cv.notify_all();
      },
      [&](const Error&) {
        std::lock_guard<std::mutex> lk(mutex);
        done = true;
        cv.notify_all();
      }));
  REQUIRE_OK(stream->Write("tick"));
  {
    std::unique_lock<std::mutex> lk(mutex);
    CHECK(cv.wait_for(lk, std::chrono::seconds(5),
                      [&] { return arrivals.size() >= 3; }));
  }
  REQUIRE_OK(stream->WritesDone());
  {
    std::unique_lock<std::mutex> lk(mutex);
    CHECK(cv.wait_for(lk, std::chrono::seconds(5), [&] { return done; }));
  }
  // The producer sleeps 60 ms between messages; a buffering transport
  // would deliver all three in one end-of-call burst (total spread
  // ~0). Only the first-to-last spread is asserted — adjacent gaps
  // can coalesce when the read thread is descheduled under TSAN/load.
  if (arrivals.size() < 3) {
    CHECK(false);  // stream never produced three messages
    channel->Shutdown();
    return;
  }
  auto spread_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                       arrivals[2] - arrivals[0])
                       .count();
  CHECK(spread_ms >= 60);
  channel->Shutdown();
}

TEST_CASE("h2 server: concurrent clients hammer the worker pool") {
  ServerFixture fx;
  constexpr int kThreads = 6;
  constexpr int kCallsPerThread = 25;
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&fx, &failures] {
      std::shared_ptr<GrpcChannel> channel;
      if (!GrpcChannel::Create(&channel, fx.url()).IsOk()) {
        failures++;
        return;
      }
      for (int i = 0; i < kCallsPerThread; ++i) {
        std::string response;
        const char* method =
            (i % 5 == 0) ? "/test.Svc/Slow" : "/test.Svc/Echo";
        Error err = channel->UnaryCall(method, "payload-" + std::to_string(i),
                                       &response, 10 * 1000 * 1000);
        if (!err.IsOk() || response.empty()) failures++;
      }
      channel->Shutdown();
    });
  }
  for (auto& thread : threads) thread.join();
  CHECK_EQ(failures.load(), 0);
  CHECK(fx.handler.calls.load() >= kThreads * kCallsPerThread);
}

TEST_CASE("h2 server: shutdown with in-flight calls") {
  auto fx = std::make_unique<ServerFixture>();
  std::shared_ptr<GrpcChannel> channel;
  REQUIRE_OK(GrpcChannel::Create(&channel, fx->url()));
  std::thread caller([&channel] {
    std::string response;
    // May fail (server goes away) — must not hang or crash.
    channel->UnaryCall("/test.Svc/Slow", "x", &response, 5 * 1000 * 1000);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(5));
  fx->server.Shutdown();
  caller.join();
  channel->Shutdown();
}

namespace {

// Minimal HTTP/1.1 client for exercising Http1Server: one request per
// call over a fresh connection (or a provided keep-alive fd).
std::string HttpRequest(int port, const std::string& method,
                        const std::string& path, const std::string& body,
                        int* reuse_fd = nullptr) {
  int fd = (reuse_fd != nullptr && *reuse_fd >= 0) ? *reuse_fd : -1;
  if (fd < 0) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr),
                sizeof(addr)) != 0) {
      ::close(fd);
      return "";
    }
  }
  std::string request = method + " " + path + " HTTP/1.1\r\n" +
                        "Host: test\r\nContent-Length: " +
                        std::to_string(body.size()) + "\r\n\r\n" + body;
  ::send(fd, request.data(), request.size(), MSG_NOSIGNAL);
  std::string response;
  char buf[4096];
  // Read until the body announced by Content-Length is complete.
  size_t body_needed = std::string::npos;
  size_t header_end = std::string::npos;
  for (;;) {
    ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
    if (n <= 0) break;
    response.append(buf, static_cast<size_t>(n));
    if (header_end == std::string::npos) {
      header_end = response.find("\r\n\r\n");
      if (header_end != std::string::npos) {
        size_t cl = response.find("Content-Length: ");
        if (cl != std::string::npos && cl < header_end) {
          body_needed = strtoull(response.c_str() + cl + 16, nullptr, 10);
        }
      }
    }
    if (header_end != std::string::npos && body_needed != std::string::npos &&
        response.size() >= header_end + 4 + body_needed) {
      break;
    }
  }
  if (reuse_fd != nullptr) {
    *reuse_fd = fd;
  } else {
    ::close(fd);
  }
  return response;
}

class StubHttpHandler : public HttpHandler {
 public:
  HttpReply HttpCall(const std::string& method, const std::string& path,
                     const std::string& headers_json,
                     const std::string& body) override {
    calls++;
    HttpReply reply;
    if (path == "/missing") {
      reply.status = 404;
      reply.body = "{\"error\": \"nope\"}";
    } else {
      reply.body = method + " " + path + " " +
                   std::string(body.rbegin(), body.rend());
    }
    reply.headers_json = "{\"Content-Type\": \"text/plain\"}";
    return reply;
  }

  std::atomic<int> calls{0};
};

}  // namespace

TEST_CASE("http1 server: request round-trips + keep-alive + errors") {
  StubHttpHandler handler;
  Http1Server server(&handler);
  REQUIRE(server.Listen("127.0.0.1", 0).empty());
  int port = server.bound_port();

  std::string response = HttpRequest(port, "POST", "/echo", "hello");
  CHECK(response.find("HTTP/1.1 200 OK") == 0);
  CHECK(response.find("POST /echo olleh") != std::string::npos);

  // Two requests over one keep-alive connection.
  int fd = -1;
  std::string first = HttpRequest(port, "GET", "/a", "", &fd);
  std::string second = HttpRequest(port, "GET", "/b", "", &fd);
  ::close(fd);
  CHECK(first.find("GET /a") != std::string::npos);
  CHECK(second.find("GET /b") != std::string::npos);

  CHECK(HttpRequest(port, "GET", "/missing", "")
            .find("HTTP/1.1 404") == 0);

  // Conflicting duplicate Content-Length headers: 400, not
  // last-one-wins (RFC 7230 §3.3.3 — request-smuggling vector).
  {
    int raw = ::socket(AF_INET, SOCK_STREAM, 0);
    REQUIRE(raw >= 0);
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(port);
    REQUIRE(::connect(raw, reinterpret_cast<struct sockaddr*>(&addr),
                      sizeof(addr)) == 0);
    const char* smuggle =
        "POST /echo HTTP/1.1\r\nHost: x\r\nContent-Length: 5\r\n"
        "Content-Length: 3\r\n\r\nhello";
    ::send(raw, smuggle, strlen(smuggle), MSG_NOSIGNAL);
    std::string reply;
    char buf[1024];
    ssize_t n;
    while ((n = ::recv(raw, buf, sizeof(buf), 0)) > 0) {
      reply.append(buf, (size_t)n);
      if (reply.find("\r\n\r\n") != std::string::npos) break;
    }
    ::close(raw);
    CHECK(reply.find("HTTP/1.1 400") == 0);
    // Matching duplicates are tolerated (same value, no conflict).
    int raw2 = ::socket(AF_INET, SOCK_STREAM, 0);
    REQUIRE(raw2 >= 0);
    REQUIRE(::connect(raw2, reinterpret_cast<struct sockaddr*>(&addr),
                      sizeof(addr)) == 0);
    const char* benign_req =
        "POST /echo HTTP/1.1\r\nHost: x\r\nContent-Length: 5\r\n"
        "Content-Length: 5\r\nConnection: close\r\n\r\nhello";
    ::send(raw2, benign_req, strlen(benign_req), MSG_NOSIGNAL);
    std::string benign;
    while ((n = ::recv(raw2, buf, sizeof(buf), 0)) > 0) {
      benign.append(buf, (size_t)n);
    }
    ::close(raw2);
    CHECK(benign.find("HTTP/1.1 200 OK") == 0);
    CHECK(benign.find("olleh") != std::string::npos);
  }

  // Concurrent clients across connections (worker-thread reaping +
  // shutdown with connections open run under TSAN here).
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < 4; ++t) {
    threads.emplace_back([port, &failures] {
      for (int i = 0; i < 10; ++i) {
        if (HttpRequest(port, "POST", "/w", "x").find("200") ==
            std::string::npos) {
          failures++;
        }
      }
    });
  }
  for (auto& thread : threads) thread.join();
  CHECK_EQ(failures.load(), 0);
  CHECK(handler.calls.load() >= 44);
  server.Shutdown();
}

TEST_CASE("h2 client: keepalive detects a silent peer") {
  // A peer that completes the h2 handshake then never responds: the
  // client's PING watchdog must fail the connection in bounded time
  // (the failure-detection story — no per-call timeout needed).
  int listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  REQUIRE(listen_fd >= 0);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  REQUIRE(bind(listen_fd, reinterpret_cast<struct sockaddr*>(&addr),
               sizeof(addr)) == 0);
  REQUIRE(listen(listen_fd, 1) == 0);
  socklen_t alen = sizeof(addr);
  getsockname(listen_fd, reinterpret_cast<struct sockaddr*>(&addr), &alen);
  int port = ntohs(addr.sin_port);

  std::thread silent_peer([listen_fd] {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) return;
    // Server SETTINGS so the client handshake completes...
    const char settings[9] = {0, 0, 0, 0x4, 0, 0, 0, 0, 0};
    ::send(fd, settings, sizeof(settings), MSG_NOSIGNAL);
    // ...then read and discard everything, never answering PINGs.
    char buf[4096];
    while (::recv(fd, buf, sizeof(buf), 0) > 0) {
    }
    ::close(fd);
  });

  tpuclient::h2::H2Connection conn("127.0.0.1", port);
  REQUIRE(conn.Connect(2 * 1000 * 1000).empty());
  conn.EnableKeepAlive(/*interval_ms=*/100, /*timeout_ms=*/500);

  std::mutex mutex;
  std::condition_variable cv;
  std::string close_error;
  bool closed = false;
  tpuclient::h2::StreamCallbacks callbacks;
  callbacks.on_close = [&](const tpuclient::h2::HeaderList&,
                           const std::string& error) {
    std::lock_guard<std::mutex> lk(mutex);
    closed = true;
    close_error = error;
    cv.notify_all();
  };
  std::string err;
  int32_t sid = conn.StartStream(
      {{":method", "POST"}, {":scheme", "http"}, {":path", "/x"},
       {":authority", "test"}},
      callbacks, &err);
  CHECK(sid > 0);
  {
    std::unique_lock<std::mutex> lk(mutex);
    CHECK(cv.wait_for(lk, std::chrono::seconds(5), [&] { return closed; }));
  }
  CHECK(close_error.find("keepalive") != std::string::npos);
  conn.Close();
  ::shutdown(listen_fd, SHUT_RDWR);
  ::close(listen_fd);
  silent_peer.join();
}

MINITEST_MAIN
