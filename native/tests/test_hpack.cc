// HPACK codec unit tests, driven by the RFC 7541 Appendix C worked
// examples (integer coding C.1, huffman requests C.4, plain requests
// C.3 with dynamic-table evolution).
#include <string>

#include "../library/h2/hpack.h"
#include "minitest.h"

using namespace tpuclient::h2;

namespace {

std::string Unhex(const std::string& hex) {
  std::string out;
  for (size_t i = 0; i + 1 < hex.size(); i += 2) {
    auto nib = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      return c - 'a' + 10;
    };
    out.push_back(static_cast<char>((nib(hex[i]) << 4) | nib(hex[i + 1])));
  }
  return out;
}

}  // namespace

TEST_CASE("hpack: integer encoding (RFC 7541 C.1)") {
  std::string out;
  EncodeInteger(10, 5, 0, &out);
  CHECK_EQ(out.size(), 1u);
  CHECK_EQ(static_cast<uint8_t>(out[0]), 0x0au);

  out.clear();
  EncodeInteger(1337, 5, 0, &out);
  REQUIRE(out.size() == 3);
  CHECK_EQ(static_cast<uint8_t>(out[0]), 0x1fu);
  CHECK_EQ(static_cast<uint8_t>(out[1]), 0x9au);
  CHECK_EQ(static_cast<uint8_t>(out[2]), 0x0au);

  out.clear();
  EncodeInteger(42, 8, 0, &out);
  CHECK_EQ(out.size(), 1u);
  CHECK_EQ(static_cast<uint8_t>(out[0]), 0x2au);

  // Round-trip decode.
  size_t pos = 0;
  uint64_t value = 0;
  std::string enc;
  EncodeInteger(1337, 5, 0, &enc);
  REQUIRE(DecodeInteger(
      reinterpret_cast<const uint8_t*>(enc.data()), enc.size(), &pos, 5,
      &value));
  CHECK_EQ(value, 1337u);
  CHECK_EQ(pos, enc.size());
}

TEST_CASE("hpack: huffman decode (RFC 7541 C.4.1)") {
  std::string encoded = Unhex("f1e3c2e5f23a6ba0ab90f4ff");
  std::string out;
  REQUIRE(HuffmanDecode(
      reinterpret_cast<const uint8_t*>(encoded.data()), encoded.size(),
      &out));
  CHECK_EQ(out, "www.example.com");

  // "no-cache" (C.4.2).
  encoded = Unhex("a8eb10649cbf");
  out.clear();
  REQUIRE(HuffmanDecode(
      reinterpret_cast<const uint8_t*>(encoded.data()), encoded.size(),
      &out));
  CHECK_EQ(out, "no-cache");

  // Bad padding (zero bits) must fail.
  encoded = Unhex("f1e3c2e5f23a6ba0ab90f400");
  out.clear();
  CHECK(!HuffmanDecode(
      reinterpret_cast<const uint8_t*>(encoded.data()), encoded.size(),
      &out));
}

TEST_CASE("hpack: request decode without huffman (RFC 7541 C.3)") {
  HpackDecoder decoder;

  // First request.
  std::string block =
      Unhex("828684410f7777772e6578616d706c652e636f6d");
  HeaderList headers;
  REQUIRE(decoder
              .Decode(
                  reinterpret_cast<const uint8_t*>(block.data()),
                  block.size(), &headers)
              .empty());
  REQUIRE(headers.size() == 4);
  CHECK_EQ(headers[0].first, ":method");
  CHECK_EQ(headers[0].second, "GET");
  CHECK_EQ(headers[1].first, ":scheme");
  CHECK_EQ(headers[1].second, "http");
  CHECK_EQ(headers[2].first, ":path");
  CHECK_EQ(headers[2].second, "/");
  CHECK_EQ(headers[3].first, ":authority");
  CHECK_EQ(headers[3].second, "www.example.com");
  CHECK_EQ(decoder.dynamic_size(), 57u);

  // Second request reuses the dynamic-table entry (index 62).
  block = Unhex("828684be58086e6f2d6361636865");
  headers.clear();
  REQUIRE(decoder
              .Decode(
                  reinterpret_cast<const uint8_t*>(block.data()),
                  block.size(), &headers)
              .empty());
  REQUIRE(headers.size() == 5);
  CHECK_EQ(headers[3].first, ":authority");
  CHECK_EQ(headers[3].second, "www.example.com");
  CHECK_EQ(headers[4].first, "cache-control");
  CHECK_EQ(headers[4].second, "no-cache");
  CHECK_EQ(decoder.dynamic_size(), 110u);

  // Third request.
  block = Unhex(
      "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565");
  headers.clear();
  REQUIRE(decoder
              .Decode(
                  reinterpret_cast<const uint8_t*>(block.data()),
                  block.size(), &headers)
              .empty());
  REQUIRE(headers.size() == 5);
  CHECK_EQ(headers[1].second, "https");
  CHECK_EQ(headers[2].second, "/index.html");
  CHECK_EQ(headers[4].first, "custom-key");
  CHECK_EQ(headers[4].second, "custom-value");
  CHECK_EQ(decoder.dynamic_size(), 164u);
}

TEST_CASE("hpack: request decode with huffman (RFC 7541 C.4)") {
  HpackDecoder decoder;
  std::string block = Unhex("828684418cf1e3c2e5f23a6ba0ab90f4ff");
  HeaderList headers;
  REQUIRE(decoder
              .Decode(
                  reinterpret_cast<const uint8_t*>(block.data()),
                  block.size(), &headers)
              .empty());
  REQUIRE(headers.size() == 4);
  CHECK_EQ(headers[3].first, ":authority");
  CHECK_EQ(headers[3].second, "www.example.com");
  CHECK_EQ(decoder.dynamic_size(), 57u);
}

TEST_CASE("hpack: encoder round-trips through decoder") {
  HpackEncoder encoder;
  HpackDecoder decoder;
  HeaderList headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", "/inference.GRPCInferenceService/ModelInfer"},
      {":authority", "localhost:8001"},
      {"te", "trailers"},
      {"content-type", "application/grpc"},
      {"grpc-timeout", "5000000u"},
      {"x-custom-header", "hello world"},
  };
  std::string block = encoder.Encode(headers);
  HeaderList decoded;
  REQUIRE(decoder
              .Decode(
                  reinterpret_cast<const uint8_t*>(block.data()),
                  block.size(), &decoded)
              .empty());
  REQUIRE(decoded.size() == headers.size());
  for (size_t i = 0; i < headers.size(); ++i) {
    CHECK_EQ(decoded[i].first, headers[i].first);
    CHECK_EQ(decoded[i].second, headers[i].second);
  }
}

TEST_CASE("hpack: decoder rejects malformed input") {
  HpackDecoder decoder;
  HeaderList headers;
  // Index 0 is invalid.
  uint8_t bad_index[] = {0x80};
  CHECK(!decoder.Decode(bad_index, 1, &headers).empty());
  // Truncated string.
  HpackDecoder decoder2;
  uint8_t truncated[] = {0x00, 0x05, 'a', 'b'};
  CHECK(!decoder2.Decode(truncated, 4, &headers).empty());
  // Out-of-range dynamic index.
  HpackDecoder decoder3;
  uint8_t big_index[] = {0xff, 0x20};
  CHECK(!decoder3.Decode(big_index, 2, &headers).empty());
}

TEST_CASE("hpack: dynamic table eviction") {
  // Cap the table to 100 bytes via a size update, then insert two
  // entries whose combined size exceeds it — older entry evicts.
  HpackDecoder decoder;
  std::string block;
  // Size update to 100 (prefix 5, pattern 001xxxxx).
  block.push_back(0x3f);  // 31 + ...
  block.push_back(0x45);  // 31+69=100
  // Insert "aa"->"bb" (36 bytes) and "cc"->"dd" (36 bytes), then
  // "ee"->"ff" (36 bytes) — first insert must be evicted (108>100).
  auto literal_inc = [](const std::string& n, const std::string& v) {
    std::string s;
    s.push_back(0x40);
    s.push_back(static_cast<char>(n.size()));
    s += n;
    s.push_back(static_cast<char>(v.size()));
    s += v;
    return s;
  };
  block += literal_inc("aa", "bb");
  block += literal_inc("cc", "dd");
  block += literal_inc("ee", "ff");
  HeaderList headers;
  REQUIRE(decoder
              .Decode(
                  reinterpret_cast<const uint8_t*>(block.data()),
                  block.size(), &headers)
              .empty());
  CHECK_EQ(decoder.dynamic_size(), 72u);  // two entries remain
}

MINITEST_MAIN
