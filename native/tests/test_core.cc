// Unit tests for the core native library: JSON, base64, common data
// model, shm_utils (test-strategy parity: reference tier-1 unit tests,
// SURVEY.md §4).
#include <unistd.h>

#include <cstring>

#include "../library/base64.h"
#include "../library/common.h"
#include "../library/json.h"
#include "../library/shm_utils.h"
#include "minitest.h"

using namespace tpuclient;

TEST_CASE("json: roundtrip scalars") {
  json::Value v;
  REQUIRE(json::Parse("{\"a\": 1, \"b\": -2.5, \"c\": true, \"d\": null, "
                      "\"e\": \"hi\", \"f\": 18446744073709551615}",
                      &v)
              .empty());
  CHECK_EQ(v["a"].AsInt(), 1);
  CHECK_EQ(v["b"].AsDouble(), -2.5);
  CHECK(v["c"].AsBool());
  CHECK(v["d"].IsNull());
  CHECK_EQ(v["e"].AsString(), "hi");
  CHECK_EQ(v["f"].AsUint(), 18446744073709551615ull);
}

TEST_CASE("json: nested structures and order preservation") {
  json::Object obj;
  obj["z"] = json::Value(int64_t{1});
  obj["a"] = json::Value("x");
  json::Array arr;
  arr.push_back(json::Value(obj));
  arr.push_back(json::Value(3.5));
  json::Value root{json::Value(arr)};
  std::string s = root.Serialize();
  CHECK_EQ(s, "[{\"z\":1,\"a\":\"x\"},3.5]");

  json::Value back;
  REQUIRE(json::Parse(s, &back).empty());
  CHECK_EQ(back.AsArray()[0]["z"].AsInt(), 1);
  CHECK_EQ(back.AsArray()[1].AsDouble(), 3.5);
}

TEST_CASE("json: string escapes") {
  json::Value v;
  REQUIRE(json::Parse("\"a\\n\\t\\\"\\u0041\\u00e9\\ud83d\\ude00\"", &v)
              .empty());
  CHECK_EQ(v.AsString(), std::string("a\n\t\"A\xc3\xa9\xf0\x9f\x98\x80"));
  json::Value w{v.AsString()};
  json::Value back;
  REQUIRE(json::Parse(w.Serialize(), &back).empty());
  CHECK_EQ(back.AsString(), v.AsString());
}

TEST_CASE("json: errors") {
  json::Value v;
  CHECK(!json::Parse("{\"a\": }", &v).empty());
  CHECK(!json::Parse("[1,2", &v).empty());
  CHECK(!json::Parse("", &v).empty());
  CHECK(!json::Parse("{} extra", &v).empty());
}

TEST_CASE("base64: roundtrip") {
  const char* cases[] = {"", "f", "fo", "foo", "foob", "fooba", "foobar"};
  const char* expect[] = {"",     "Zg==", "Zm8=",    "Zm9v",
                          "Zm9vYg==", "Zm9vYmE=", "Zm9vYmFy"};
  for (int i = 0; i < 7; ++i) {
    CHECK_EQ(Base64Encode(std::string(cases[i])), std::string(expect[i]));
    std::string dec;
    REQUIRE(Base64Decode(expect[i], &dec));
    CHECK_EQ(dec, std::string(cases[i]));
  }
  std::string bin;
  for (int i = 0; i < 256; ++i) bin.push_back(static_cast<char>(i));
  std::string dec;
  REQUIRE(Base64Decode(Base64Encode(bin), &dec));
  CHECK(dec == bin);
}

TEST_CASE("common: InferInput raw append and chunk iteration") {
  InferInput* input = nullptr;
  REQUIRE_OK(InferInput::Create(&input, "in0", {2, 2}, "FP32"));
  std::unique_ptr<InferInput> guard(input);
  float a[2] = {1.0f, 2.0f};
  float b[2] = {3.0f, 4.0f};
  REQUIRE_OK(input->AppendRaw(reinterpret_cast<uint8_t*>(a), sizeof(a)));
  REQUIRE_OK(input->AppendRaw(reinterpret_cast<uint8_t*>(b), sizeof(b)));
  CHECK_EQ(input->ByteSize(), sizeof(a) + sizeof(b));

  input->PrepareForRequest();
  const uint8_t* buf;
  size_t len;
  size_t total = 0;
  int chunks = 0;
  while (input->GetNext(&buf, &len)) {
    total += len;
    ++chunks;
  }
  CHECK_EQ(total, sizeof(a) + sizeof(b));
  CHECK_EQ(chunks, 2);

  std::string gathered;
  input->GatherInto(&gathered);
  CHECK_EQ(gathered.size(), sizeof(a) + sizeof(b));
  CHECK(memcmp(gathered.data(), a, sizeof(a)) == 0);
}

TEST_CASE("common: InferInput BYTES serialization") {
  InferInput* input = nullptr;
  REQUIRE_OK(InferInput::Create(&input, "in0", {2}, "BYTES"));
  std::unique_ptr<InferInput> guard(input);
  REQUIRE_OK(input->AppendFromString({"ab", "xyz"}));
  std::string wire;
  input->GatherInto(&wire);
  // 4-byte LE length prefix per element.
  REQUIRE(wire.size() == 4 + 2 + 4 + 3);
  CHECK_EQ(static_cast<int>(wire[0]), 2);
  CHECK_EQ(wire.substr(4, 2), "ab");
  CHECK_EQ(static_cast<int>(wire[6]), 3);
  CHECK_EQ(wire.substr(10, 3), "xyz");

  InferInput* nonbytes = nullptr;
  REQUIRE_OK(InferInput::Create(&nonbytes, "in1", {2}, "FP32"));
  std::unique_ptr<InferInput> guard2(nonbytes);
  CHECK(!nonbytes->AppendFromString({"x"}).IsOk());

  // Repeated appends must keep earlier chunks valid (the backing
  // store must not reallocate out from under recorded pointers).
  InferInput* multi = nullptr;
  REQUIRE_OK(InferInput::Create(&multi, "in2", {8}, "BYTES"));
  std::unique_ptr<InferInput> guard3(multi);
  for (int i = 0; i < 8; ++i) {
    REQUIRE_OK(multi->AppendFromString({std::string(1, 'a' + i)}));
  }
  std::string all;
  multi->GatherInto(&all);
  REQUIRE(all.size() == 8 * 5);
  for (int i = 0; i < 8; ++i) {
    CHECK_EQ(static_cast<int>(all[i * 5]), 1);
    CHECK_EQ(all[i * 5 + 4], static_cast<char>('a' + i));
  }
}

TEST_CASE("common: shared memory routing") {
  InferInput* input = nullptr;
  REQUIRE_OK(InferInput::Create(&input, "in0", {4}, "FP32"));
  std::unique_ptr<InferInput> guard(input);
  CHECK(!input->IsSharedMemory());
  REQUIRE_OK(input->SetSharedMemory("region0", 64, 16));
  CHECK(input->IsSharedMemory());
  std::string name;
  size_t sz, off;
  REQUIRE_OK(input->SharedMemoryInfo(&name, &sz, &off));
  CHECK_EQ(name, "region0");
  CHECK_EQ(sz, 64u);
  CHECK_EQ(off, 16u);
  REQUIRE_OK(input->Reset());
  CHECK(!input->IsSharedMemory());

  InferRequestedOutput* output = nullptr;
  REQUIRE_OK(InferRequestedOutput::Create(&output, "out0"));
  std::unique_ptr<InferRequestedOutput> oguard(output);
  REQUIRE_OK(output->SetSharedMemory("region1", 128));
  CHECK(output->IsSharedMemory());
  REQUIRE_OK(output->UnsetSharedMemory());
  CHECK(!output->IsSharedMemory());
}

TEST_CASE("common: RequestTimers durations") {
  RequestTimers t;
  t.SetTimestamp(RequestTimers::Kind::REQUEST_START, 100);
  t.SetTimestamp(RequestTimers::Kind::REQUEST_END, 350);
  CHECK_EQ(
      t.Duration(
          RequestTimers::Kind::REQUEST_START, RequestTimers::Kind::REQUEST_END),
      250u);
  // Reversed order clamps to 0 rather than underflowing.
  CHECK_EQ(
      t.Duration(
          RequestTimers::Kind::REQUEST_END, RequestTimers::Kind::REQUEST_START),
      0u);
}

TEST_CASE("shm_utils: create/map/write/read/unlink") {
  std::string key = "/tpuclient_test_" + std::to_string(getpid());
  int fd = -1;
  REQUIRE_OK(CreateSharedMemoryRegion(key, 4096, &fd));
  void* addr = nullptr;
  REQUIRE_OK(MapSharedMemory(fd, 0, 4096, &addr));
  memcpy(addr, "hello", 5);

  // Second mapping sees the data (cross-mapping visibility).
  void* addr2 = nullptr;
  REQUIRE_OK(MapSharedMemory(fd, 0, 4096, &addr2));
  CHECK(memcmp(addr2, "hello", 5) == 0);

  REQUIRE_OK(UnmapSharedMemory(addr, 4096));
  REQUIRE_OK(UnmapSharedMemory(addr2, 4096));
  REQUIRE_OK(CloseSharedMemory(fd));
  REQUIRE_OK(UnlinkSharedMemoryRegion(key));
  CHECK(!UnlinkSharedMemoryRegion(key).IsOk());
}

MINITEST_MAIN
