// Perf harness unit tests over the mock backend — no server needed
// (parity tier 1: the reference's 131 doctest TEST_CASEs run against
// NaggyMockClientBackend, SURVEY.md §4).
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

#include <chrono>
#include <fstream>
#include <sstream>
#include <thread>

#include "../perf/command_line_parser.h"
#include "../perf/inference_profiler.h"
#include "../perf/metrics_manager.h"
#include "../perf/mpi_utils.h"
#include "../perf/report_writer.h"
#include "minitest.h"

using namespace tpuclient;
using namespace tpuclient::perf;

namespace {

BackendConfig MockConfig(uint64_t delay_us = 300) {
  BackendConfig config;
  config.kind = BackendKind::MOCK;
  config.mock_delay_us = delay_us;
  return config;
}

struct Harness {
  ClientBackendFactory factory;
  std::unique_ptr<ClientBackend> backend;
  ParsedModel model;
  DataLoader loader;
  InferDataManager data_manager;

  explicit Harness(uint64_t delay_us = 300)
      : Harness(MockConfig(delay_us)) {}

  explicit Harness(const BackendConfig& config)
      : factory(config), loader(&model), data_manager(&model, &loader) {
    factory.Create(&backend);
    ModelParser::Parse(backend.get(), "mock", "", 1, &model);
    loader.GenerateData();
  }
};

}  // namespace

TEST_CASE("perf: model parser over mock backend") {
  Harness h;
  CHECK_EQ(h.model.name, "mock");
  CHECK_EQ(h.model.inputs.size(), 2u);
  CHECK_EQ(h.model.outputs.size(), 2u);
  CHECK_EQ(h.model.max_batch_size, 8);
  CHECK(h.model.FindInput("INPUT0") != nullptr);
  CHECK(h.model.FindInput("NOPE") == nullptr);

  // Batch-size validation.
  ParsedModel rejected;
  Error err = ModelParser::Parse(h.backend.get(), "mock", "", 99, &rejected);
  CHECK(!err.IsOk());
}

TEST_CASE("perf: model parser recursive composing + bls") {
  Harness h;
  ParsedModel model;
  Error err =
      ModelParser::Parse(h.backend.get(), "ensemble_top", "", 1, &model);
  CHECK(err.IsOk());
  // A sequence-batched composing model refines the kind to
  // ENSEMBLE_SEQUENCE (reference model_parser.h:63).
  CHECK(model.scheduler_type == SchedulerType::ENSEMBLE_SEQUENCE);
  REQUIRE(model.composing_models.size() == 2u);
  CHECK_EQ(model.composing_models[0], "ensemble_mid");
  CHECK_EQ(model.composing_models[1], "seq_leaf");
  CHECK(model.composing_sequential);

  // BLS children named explicitly merge (and dedupe) into the map.
  ParsedModel bls;
  err = ModelParser::Parse(
      h.backend.get(), "mock", "", 1, &bls, {"callee", "callee"});
  CHECK(err.IsOk());
  REQUIRE(bls.composing_models.size() == 1u);
  CHECK_EQ(bls.composing_models[0], "callee");
}

TEST_CASE("perf: shape tensors stay unbatched") {
  // Parity: reference ModelTensor.is_shape_tensor (model_parser.h:41)
  // — a shape tensor's values describe SHAPES, one value set per
  // batch, so the data manager must neither add the batch dim nor
  // replicate its bytes per row.
  Harness h;
  ParsedModel model;
  REQUIRE_OK(ModelParser::Parse(h.backend.get(), "shape_mock", "", 4,
                                &model));
  const ModelTensor* plain = model.FindInput("INPUT0");
  const ModelTensor* shape_tensor = model.FindInput("INPUT1");
  REQUIRE(plain != nullptr);
  REQUIRE(shape_tensor != nullptr);
  CHECK(!plain->is_shape_tensor);
  CHECK(shape_tensor->is_shape_tensor);

  DataLoader loader(&model);
  REQUIRE_OK(loader.GenerateData());
  InferDataManager manager(&model, &loader, SharedMemoryType::NONE,
                           102400, "", /*batch=*/4);
  std::vector<std::unique_ptr<InferInput>> inputs;
  REQUIRE_OK(manager.BuildInputs(0, 0, &inputs));
  REQUIRE(inputs.size() == 2u);
  // INPUT0: leading batch dim 4, bytes replicated 4x.
  CHECK_EQ(inputs[0]->Shape().size(), 2u);
  CHECK_EQ(inputs[0]->Shape()[0], 4);
  // INPUT1 (shape tensor): unbatched shape, single copy of the data.
  CHECK_EQ(inputs[1]->Shape().size(), 1u);
  CHECK_EQ(inputs[1]->Shape()[0], 16);
}

TEST_CASE("perf: data loader random + json") {
  Harness h;
  const TensorData* data = nullptr;
  REQUIRE_OK(h.loader.GetInputData("INPUT0", 0, 0, &data));
  CHECK_EQ(data->bytes.size(), 64u);  // 16 x INT32
  CHECK_EQ(data->datatype, "INT32");

  DataLoader json_loader(&h.model);
  REQUIRE_OK(json_loader.ReadDataFromJsonText(
      R"({"data": [{"INPUT0": [1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16],
                    "INPUT1": [1,1,1,1,1,1,1,1,1,1,1,1,1,1,1,1]}]})"));
  REQUIRE_OK(json_loader.GetInputData("INPUT0", 0, 0, &data));
  REQUIRE(data->bytes.size() == 64);
  const int32_t* values =
      reinterpret_cast<const int32_t*>(data->bytes.data());
  CHECK_EQ(values[0], 1);
  CHECK_EQ(values[15], 16);

  // Missing input -> validation error.
  DataLoader bad_loader(&h.model);
  Error err = bad_loader.ReadDataFromJsonText(
      R"({"data": [{"INPUT0": [1]}]})");
  CHECK(!err.IsOk());

  // Multi-stream form.
  DataLoader stream_loader(&h.model);
  REQUIRE_OK(stream_loader.ReadDataFromJsonText(
      R"({"data": [[{"INPUT0": {"content": [0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0]},
                     "INPUT1": [1,1,1,1,1,1,1,1,1,1,1,1,1,1,1,1]}],
                   [{"INPUT0": [2,2,2,2,2,2,2,2,2,2,2,2,2,2,2,2],
                     "INPUT1": [3,3,3,3,3,3,3,3,3,3,3,3,3,3,3,3]}]]})"));
  CHECK_EQ(stream_loader.stream_count(), 2u);
  CHECK_EQ(stream_loader.step_count(1), 1u);
}

TEST_CASE("perf: ctx id tracker") {
  FifoCtxIdTracker tracker;
  tracker.Reset(2);
  int a = tracker.Get(100);
  int b = tracker.Get(100);
  CHECK_EQ(a, 0);
  CHECK_EQ(b, 1);
  CHECK_EQ(tracker.Get(10), -1);  // exhausted
  tracker.Release(a);
  CHECK_EQ(tracker.Get(100), 0);
}

TEST_CASE("perf: sequence manager start/end options") {
  SequenceManager seq(100, 1000, /*length=*/3, /*variation=*/0.0);
  SequenceManager::Slot slot;
  InferOptions options("m");
  size_t stream, step;

  seq.NextStep(&slot, 1, 4, &options, &stream, &step);
  CHECK_EQ(options.sequence_id, 100u);
  CHECK(options.sequence_start);
  CHECK(!options.sequence_end);
  CHECK_EQ(step, 0u);

  seq.NextStep(&slot, 1, 4, &options, &stream, &step);
  CHECK(!options.sequence_start);
  CHECK(!options.sequence_end);
  CHECK_EQ(step, 1u);

  seq.NextStep(&slot, 1, 4, &options, &stream, &step);
  CHECK(options.sequence_end);

  // Next call starts a fresh sequence with a new id.
  seq.NextStep(&slot, 1, 4, &options, &stream, &step);
  CHECK_EQ(options.sequence_id, 101u);
  CHECK(options.sequence_start);
}

TEST_CASE("perf: concurrency manager drives mock backend") {
  ResetMockBackendStats();
  Harness h(200);
  ConcurrencyManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/false,
                           /*max_threads=*/4});
  REQUIRE_OK(manager.Init());
  REQUIRE_OK(manager.ChangeConcurrencyLevel(4));
  std::this_thread::sleep_for(std::chrono::milliseconds(300));
  REQUIRE_OK(manager.CheckHealth());
  size_t collected = manager.CountCollectedRequests();
  CHECK(collected > 20);
  manager.Stop();  // quiesce before draining so the count stays 0
  auto records = manager.SwapRequestRecords();
  CHECK(records.size() >= collected);
  CHECK_EQ(manager.CountCollectedRequests(), 0u);
  for (const auto& record : records) {
    if (!record.valid()) continue;
    CHECK(record.latency_ns() >= 200 * 1000ull);
  }
  CHECK(GetMockBackendStats()->async_infer_calls.load() > 20);
}

TEST_CASE("perf: request-rate schedule adherence constant + poisson") {
  // Parity: test_request_rate_manager.cc — a CONSTANT schedule's
  // inter-send gaps are uniform, a POISSON schedule's are not, and
  // both deliver approximately rate * duration requests.
  auto run_mode = [](RequestRateManager::Distribution distribution) {
    ResetMockBackendStats();
    Harness h(200);
    RequestRateManager manager(
        &h.factory, &h.model, &h.loader, &h.data_manager,
        LoadManager::Options{/*async=*/true, /*streaming=*/false,
                             /*max_threads=*/4},
        distribution);
    REQUIRE_OK(manager.Init());
    constexpr double kRate = 200.0;  // req/s
    REQUIRE_OK(manager.ChangeRequestRate(kRate));
    std::this_thread::sleep_for(std::chrono::milliseconds(600));
    REQUIRE_OK(manager.CheckHealth());
    manager.Stop();
    auto records = manager.SwapRequestRecords();
    // ~120 expected in 600ms; generous window for CI jitter.
    CHECK(records.size() > 60);
    CHECK(records.size() < 240);
    // Inter-send gap dispersion separates the distributions.
    std::vector<uint64_t> starts;
    for (const auto& record : records) starts.push_back(record.start_ns);
    std::sort(starts.begin(), starts.end());
    std::vector<double> gaps_ms;
    for (size_t i = 1; i < starts.size(); ++i) {
      gaps_ms.push_back((starts[i] - starts[i - 1]) / 1e6);
    }
    double mean = 0;
    for (double g : gaps_ms) mean += g;
    mean /= gaps_ms.size();
    double var = 0;
    for (double g : gaps_ms) var += (g - mean) * (g - mean);
    var /= gaps_ms.size();
    // Coefficient of variation: ~0 for CONSTANT, ~1 for POISSON.
    return std::sqrt(var) / mean;
  };

  double cv_constant =
      run_mode(RequestRateManager::Distribution::CONSTANT);
  double cv_poisson = run_mode(RequestRateManager::Distribution::POISSON);
  CHECK(cv_constant < 0.5);
  CHECK(cv_poisson > 0.5);
  CHECK(cv_poisson > cv_constant);
}

TEST_CASE("perf: request-rate delayed accounting under overload") {
  // A rate the mock's latency cannot sustain with the worker pool
  // forces sends behind schedule; those records carry delayed=true
  // (reference request_rate_worker delayed-request accounting).
  ResetMockBackendStats();
  Harness h(40 * 1000);  // 40 ms per request
  RequestRateManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/false, /*streaming=*/false,
                           /*max_threads=*/2});
  REQUIRE_OK(manager.Init());
  // 2 sync workers x 40 ms = ~50 req/s sustainable; ask for 500.
  REQUIRE_OK(manager.ChangeRequestRate(500.0));
  std::this_thread::sleep_for(std::chrono::milliseconds(600));
  manager.Stop();
  auto records = manager.SwapRequestRecords();
  size_t delayed = 0;
  for (const auto& record : records) {
    if (record.delayed) ++delayed;
  }
  CHECK(records.size() > 5);
  CHECK(delayed > 0);
  CHECK(delayed >= records.size() / 2);  // overload: most sends late
}

TEST_CASE("perf: custom load manager replays interval file") {
  // Parity: test_custom_load_manager.cc — explicit inter-request
  // intervals from a file drive the schedule verbatim (cycled).
  ResetMockBackendStats();
  const char* path = "/tmp/tpuclient_test_intervals.txt";
  {
    std::ofstream f(path);
    // microseconds per line: 4ms, 4ms, 12ms -> mean gap ~6.7ms
    f << "4000\n4000\n12000\n";
  }
  Harness h(200);
  CustomLoadManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/false,
                           /*max_threads=*/2});
  REQUIRE_OK(manager.Init());
  std::vector<double> intervals;
  REQUIRE_OK(CustomLoadManager::ReadIntervalsFile(path, &intervals));
  REQUIRE(intervals.size() == 3u);
  CHECK(intervals[2] > intervals[0]);
  REQUIRE_OK(manager.StartSchedule(path));
  std::this_thread::sleep_for(std::chrono::milliseconds(500));
  manager.Stop();
  auto records = manager.SwapRequestRecords();
  // 20ms per 3-interval cycle -> ~150/s -> ~75 requests in 500ms.
  CHECK(records.size() > 35);
  CHECK(records.size() < 150);
  std::vector<uint64_t> starts;
  for (const auto& record : records) starts.push_back(record.start_ns);
  std::sort(starts.begin(), starts.end());
  // The long 12ms interval must be visible in the send pattern: at
  // least a quarter of gaps >= 9ms while the median stays small.
  size_t long_gaps = 0, all_gaps = 0;
  for (size_t i = 1; i < starts.size(); ++i) {
    double gap_ms = (starts[i] - starts[i - 1]) / 1e6;
    ++all_gaps;
    if (gap_ms >= 9.0) ++long_gaps;
  }
  CHECK(all_gaps > 0);
  CHECK(long_gaps * 5 >= all_gaps);  // >= 20% of gaps are the long one
}

TEST_CASE("perf: periodic concurrency manager ramps by request period") {
  // Parity: periodic_concurrency_manager.cc — concurrency climbs
  // start -> end, advancing one step per request_period completed
  // responses, and every level's records survive into the ramp drain.
  ResetMockBackendStats();
  Harness h(1000);  // 1 ms per request: levels turn over fast
  PeriodicConcurrencyManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/false,
                           /*max_threads=*/4});
  REQUIRE_OK(manager.Init());
  PeriodicConcurrencyManager::RampConfig config;
  config.start = 1;
  config.end = 4;
  config.step = 1;
  config.request_period = 8;
  REQUIRE_OK(manager.RunRamp(config));
  CHECK_EQ(manager.concurrency(), 4u);  // reached the top level
  manager.Stop();
  auto records = manager.SwapRampRecords();
  // Each of the 3 intermediate levels collected >= request_period
  // records before advancing, plus whatever the final level ran.
  CHECK(records.size() >= 3 * config.request_period);
  size_t valid = 0;
  for (const auto& record : records) {
    if (record.valid()) ++valid;
  }
  CHECK(valid >= 3 * config.request_period);
  CHECK(GetMockBackendStats()->async_infer_calls.load() >=
        3 * config.request_period);
}

TEST_CASE("perf: sync concurrency mode") {
  ResetMockBackendStats();
  Harness h(100);
  ConcurrencyManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/false, /*streaming=*/false,
                           /*max_threads=*/2});
  REQUIRE_OK(manager.Init());
  REQUIRE_OK(manager.ChangeConcurrencyLevel(2));
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  manager.Stop();
  CHECK(GetMockBackendStats()->infer_calls.load() > 5);
  CHECK(manager.CountCollectedRequests() > 5);
}

TEST_CASE("perf: streaming concurrency mode") {
  ResetMockBackendStats();
  Harness h(100);
  ConcurrencyManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/true,
                           /*max_threads=*/2});
  REQUIRE_OK(manager.Init());
  REQUIRE_OK(manager.ChangeConcurrencyLevel(2));
  std::this_thread::sleep_for(std::chrono::milliseconds(250));
  manager.Stop();
  CHECK(GetMockBackendStats()->stream_infer_calls.load() > 5);
  auto records = manager.SwapRequestRecords();
  size_t valid = 0;
  for (const auto& r : records) {
    if (r.valid()) valid++;
  }
  CHECK(valid > 5);
}

TEST_CASE("perf: decoupled stream responses attribute to their request") {
  // Pins the decoupled-statistics contract stated in
  // docs/perf_analyzer.md: every response pairs to the RECORD OF THE
  // REQUEST THAT ISSUED IT (echoed request id; FIFO fallback), a
  // request retires only on its final-flagged response, latency =
  // final response - send, and request throughput counts requests —
  // never responses. (The reference documents its own punt here:
  // grpc_client.cc FIXME DLIS-1263.)
  ResetMockBackendStats();
  BackendConfig config = MockConfig(100);
  config.mock_responses_per_request = 3;
  Harness h(config);
  ConcurrencyManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/true,
                           /*max_threads=*/2});
  REQUIRE_OK(manager.Init());
  REQUIRE_OK(manager.ChangeConcurrencyLevel(4));
  std::this_thread::sleep_for(std::chrono::milliseconds(400));
  manager.Stop();
  auto records = manager.SwapRequestRecords();
  size_t valid = 0;
  for (const auto& r : records) {
    if (!r.valid()) continue;
    valid++;
    // All of a request's responses land on ITS record: a split or
    // cross-request misattribution shows up as a wrong count.
    CHECK_EQ(r.end_ns.size(), 3u);
    CHECK(r.end_ns.front() >= r.start_ns);
    for (size_t i = 1; i < r.end_ns.size(); ++i) {
      CHECK(r.end_ns[i] >= r.end_ns[i - 1]);
    }
    CHECK_EQ(r.latency_ns(), r.end_ns.back() - r.start_ns);
  }
  CHECK(valid > 5);
  // Request throughput counts requests, not responses.
  CHECK(valid <= GetMockBackendStats()->stream_infer_calls.load());
}

TEST_CASE("perf: request rate manager paces dispatch") {
  ResetMockBackendStats();
  Harness h(50);
  RequestRateManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/false,
                           /*max_threads=*/4});
  REQUIRE_OK(manager.Init());
  REQUIRE_OK(manager.ChangeRequestRate(100.0));  // 100 rps
  std::this_thread::sleep_for(std::chrono::milliseconds(500));
  manager.Stop();
  size_t count = manager.CountCollectedRequests();
  // ~50 expected in 500ms at 100 rps; generous bounds for CI noise.
  CHECK(count > 20);
  CHECK(count < 100);
}

TEST_CASE("perf: custom schedule from intervals") {
  Harness h(10);
  RequestRateManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/false,
                           /*max_threads=*/2});
  REQUIRE_OK(manager.Init());
  // 5ms gaps -> ~200 rps.
  REQUIRE_OK(manager.SetCustomSchedule({0.005}));
  std::this_thread::sleep_for(std::chrono::milliseconds(300));
  manager.Stop();
  size_t count = manager.CountCollectedRequests();
  CHECK(count > 20);
}

TEST_CASE("perf: profiler errors when every window is empty") {
  // Mock delay far beyond the window: no request completes in any
  // trial — the level must fail (reference: "No valid requests
  // recorded"), not report zero stats.
  Harness h(10 * 1000 * 1000);  // 10s per request
  ConcurrencyManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/false,
                           /*max_threads=*/2});
  REQUIRE_OK(manager.Init());
  MeasurementConfig config;
  config.measurement_interval_ms = 40;
  config.max_trials = 2;
  InferenceProfiler profiler(&manager, config);
  std::vector<PerfStatus> results;
  Error err = profiler.ProfileConcurrencyRange(&manager, 1, 1, 1, &results);
  CHECK(!err.IsOk());
  CHECK(err.Message().find("no valid requests") != std::string::npos);
  manager.Stop();
}

TEST_CASE("perf: profiler stabilizes on mock load") {
  // 2ms mock delay: large enough that per-request bookkeeping (which
  // TSAN inflates 10-20x) stays small next to it, so the concurrency
  // scaling check below holds under sanitizers too.
  Harness h(2000);
  ConcurrencyManager manager(
      &h.factory, &h.model, &h.loader, &h.data_manager,
      LoadManager::Options{/*async=*/true, /*streaming=*/false,
                           /*max_threads=*/4});
  REQUIRE_OK(manager.Init());
  MeasurementConfig config;
  config.measurement_interval_ms = 120;
  config.max_trials = 8;
  config.stability_threshold = 0.5;  // generous for CI
  InferenceProfiler profiler(&manager, config);
  std::vector<PerfStatus> results;
  REQUIRE_OK(profiler.ProfileConcurrencyRange(&manager, 1, 2, 1, &results));
  REQUIRE(results.size() == 2);
  CHECK_EQ(results[0].concurrency, 1u);
  CHECK_EQ(results[1].concurrency, 2u);
  for (const auto& status : results) {
    CHECK(status.completed_count > 0);
    CHECK(status.throughput > 0.0);
    CHECK(status.avg_latency_us >= 200.0);
    CHECK(status.latency_percentiles.count(99) == 1);
  }
  // 2 concurrent requests at the same per-request delay ≈ 2x the
  // throughput of 1 (loose bound).
  CHECK(results[1].throughput > results[0].throughput * 1.3);
}

TEST_CASE("perf: report writer and profile export") {
  PerfStatus status;
  status.concurrency = 2;
  status.throughput = 123.4;
  status.avg_latency_us = 810.0;
  status.latency_percentiles = {{50, 800.0}, {90, 900.0},
                                {95, 950.0}, {99, 990.0}};
  status.completed_count = 100;
  RequestRecord record;
  record.start_ns = 1000;
  record.end_ns = {2000};
  status.records.push_back(record);
  std::vector<PerfStatus> results = {status};

  REQUIRE_OK(WriteCsv("/tmp/tpuperf_test.csv", results,
                      LoadMode::CONCURRENCY));
  std::ifstream csv("/tmp/tpuperf_test.csv");
  std::string header, row;
  std::getline(csv, header);
  std::getline(csv, row);
  CHECK(header.find("Inferences/Second") != std::string::npos);
  CHECK(row.find("123.40") != std::string::npos);

  REQUIRE_OK(ExportProfile(
      "/tmp/tpuperf_test.json", results, "mock", "triton", "localhost",
      LoadMode::CONCURRENCY));
  std::ifstream jf("/tmp/tpuperf_test.json");
  std::stringstream buf;
  buf << jf.rdbuf();
  json::Value doc;
  REQUIRE(json::Parse(buf.str(), &doc).empty());
  CHECK_EQ(doc["model"].AsString(), "mock");
  CHECK_EQ(doc["experiments"].AsArray().size(), 1u);
  CHECK_EQ(
      doc["experiments"].AsArray()[0]["requests"].AsArray().size(), 1u);
}

TEST_CASE("perf: prometheus metrics parse + summarize") {
  const char* text =
      "# HELP tpu_hbm_used_bytes Accelerator HBM bytes in use\n"
      "# TYPE tpu_hbm_used_bytes gauge\n"
      "tpu_hbm_used_bytes{tpu_uuid=\"TPU-0\"} 1048576\n"
      "tpu_hbm_used_bytes{tpu_uuid=\"TPU-1\"} 3145728\n"
      "tpu_hbm_utilization{tpu_uuid=\"TPU-0\"} 0.25\n"
      "nv_inference_count{model=\"simple\",version=\"1\"} 42\n"
      "tpu_hbm_total_bytes 8388608\n";
  TpuMetrics metrics = ParsePrometheus(text);
  REQUIRE(metrics.families.count("tpu_hbm_used_bytes") == 1);
  CHECK_EQ(metrics.families["tpu_hbm_used_bytes"].size(), 2u);
  CHECK_EQ(metrics.families["tpu_hbm_used_bytes"]["TPU-0"], 1048576.0);
  CHECK_EQ(metrics.families["tpu_hbm_total_bytes"]["0"], 8388608.0);
  // Untracked families are ignored.
  CHECK_EQ(metrics.families.count("nv_inference_count"), 0u);

  TpuMetrics second;
  second.families["tpu_hbm_used_bytes"]["TPU-0"] = 2097152;
  second.families["tpu_hbm_used_bytes"]["TPU-1"] = 2097152;
  TpuMetricsSummary summary = SummarizeMetrics({metrics, second});
  // Window 1 device-avg = 2 MiB, window 2 device-avg = 2 MiB.
  CHECK_EQ(summary["tpu_hbm_used_bytes"].first, 2097152.0);
  CHECK_EQ(summary["tpu_hbm_used_bytes"].second, 2097152.0);
  CHECK_EQ(summary["tpu_hbm_utilization"].first, 0.25);
}

TEST_CASE("perf: command line parser") {
  PerfAnalyzerParameters params;
  const char* argv1[] = {
      "perf_analyzer", "-m", "resnet50", "-u", "host:9", "-b", "4",
      "--concurrency-range", "1:8:2", "--shared-memory", "tpu",
      "--percentile", "99", "-p", "2000"};
  REQUIRE_OK(CLParser::Parse(
      15, const_cast<char**>(argv1), &params));
  CHECK_EQ(params.model_name, "resnet50");
  CHECK_EQ(params.batch_size, 4);
  CHECK_EQ(params.concurrency_start, 1u);
  CHECK_EQ(params.concurrency_end, 8u);
  CHECK_EQ(params.concurrency_step, 2u);
  CHECK_EQ(params.shared_memory, "tpu");
  CHECK_EQ(params.percentile, 99);
  CHECK_EQ(params.measurement_interval_ms, 2000u);

  // Missing -m fails.
  PerfAnalyzerParameters missing;
  const char* argv2[] = {"perf_analyzer", "-u", "host:9"};
  CHECK(!CLParser::Parse(3, const_cast<char**>(argv2), &missing).IsOk());

  // Mutually exclusive modes fail.
  PerfAnalyzerParameters exclusive;
  const char* argv3[] = {
      "perf_analyzer", "-m", "x", "--concurrency-range", "1:2",
      "--request-rate-range", "10:20"};
  CHECK(!CLParser::Parse(7, const_cast<char**>(argv3), &exclusive).IsOk());
}

namespace {

// Reserve a loopback port for a coordinator test (bind :0, read the
// kernel's pick, release it).
int PickLoopbackPort() {
  int probe = socket(AF_INET, SOCK_STREAM, 0);
  if (probe < 0) return -1;
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  socklen_t len = sizeof(addr);
  const bool ok =
      bind(probe, reinterpret_cast<struct sockaddr*>(&addr),
           sizeof(addr)) == 0 &&
      getsockname(probe, reinterpret_cast<struct sockaddr*>(&addr),
                  &len) == 0;
  const int port = ok ? ntohs(addr.sin_port) : -1;
  close(probe);
  return port;
}

// Scoped TPUCLIENT_* env contract for a 2-rank coordinator world.
struct CoordEnv {
  explicit CoordEnv(int port) {
    char coord[64];
    snprintf(coord, sizeof(coord), "127.0.0.1:%d", port);
    setenv("TPUCLIENT_COORDINATOR", coord, 1);
    setenv("TPUCLIENT_WORLD_SIZE", "2", 1);
    // Generous: under TSAN's 10-20x slowdown plus full-suite
    // contention, a tight join window flakes; a healthy join is
    // milliseconds either way.
    setenv("TPUCLIENT_COORD_TIMEOUT_S", "120", 1);
  }
  ~CoordEnv() {
    unsetenv("TPUCLIENT_COORDINATOR");
    unsetenv("TPUCLIENT_WORLD_SIZE");
    unsetenv("TPUCLIENT_RANK");
    unsetenv("TPUCLIENT_COORD_TIMEOUT_S");
  }
};

}  // namespace

TEST_CASE("perf: builtin rank coordinator 2-rank collectives") {
  // Two real processes (fork) join over the TPUCLIENT_COORDINATOR
  // TCP contract — the launcher-free replacement for the reference's
  // mpirun path (mpi_utils.h:32-80) — and must agree on every
  // AllTrue decision.
  const int port = PickLoopbackPort();
  REQUIRE(port > 0);
  CoordEnv env(port);

  const pid_t pid = fork();
  REQUIRE(pid >= 0);
  if (pid == 0) {
    // Rank 1: exit code reports each collective's outcome.
    setenv("TPUCLIENT_RANK", "1", 1);
    MPIDriver peer(true);
    if (!peer.IsMPIRun()) _exit(10);
    peer.MPIInit();
    if (!peer.IsMPIRun()) _exit(11);
    if (peer.MPICommSizeWorld() != 2 || peer.MPICommRankWorld() != 1) {
      _exit(12);
    }
    if (!peer.MPIAllTrue(true)) _exit(13);   // both true -> true
    if (peer.MPIAllTrue(false)) _exit(14);   // local false -> false
    if (peer.MPIAllTrue(true)) _exit(15);    // peer false -> false
    peer.MPIBarrierWorld();
    peer.MPIFinalize();
    _exit(0);
  }
  setenv("TPUCLIENT_RANK", "0", 1);
  MPIDriver mpi(true);
  CHECK(mpi.IsMPIRun());
  mpi.MPIInit();
  REQUIRE(mpi.IsMPIRun());
  CHECK_EQ(mpi.MPICommSizeWorld(), 2);
  CHECK_EQ(mpi.MPICommRankWorld(), 0);
  CHECK(mpi.MPIAllTrue(true));
  CHECK(!mpi.MPIAllTrue(true));   // peer votes false
  CHECK(!mpi.MPIAllTrue(false));  // local false
  mpi.MPIBarrierWorld();
  mpi.MPIFinalize();
  int status = 0;
  REQUIRE(waitpid(pid, &status, 0) == pid);
  CHECK(WIFEXITED(status));
  CHECK_EQ(WEXITSTATUS(status), 0);
}

TEST_CASE("perf: builtin rank coordinator 3-rank world, reverse joins") {
  // Three ranks; rank 2 connects before rank 1 (the coordinator must
  // key peers by their HELLO rank, not arrival order), and the AND
  // reduce must mix all three votes.
  const int port = PickLoopbackPort();
  REQUIRE(port > 0);
  CoordEnv env(port);
  setenv("TPUCLIENT_WORLD_SIZE", "3", 1);

  auto child = [&](int rank, int delay_ms) {
    const pid_t pid = fork();
    if (pid != 0) return pid;
    std::this_thread::sleep_for(std::chrono::milliseconds(delay_ms));
    char rank_str[8];
    snprintf(rank_str, sizeof(rank_str), "%d", rank);
    setenv("TPUCLIENT_RANK", rank_str, 1);
    MPIDriver peer(true);
    peer.MPIInit();
    if (!peer.IsMPIRun()) _exit(10 + rank);
    if (peer.MPICommSizeWorld() != 3) _exit(20 + rank);
    if (!peer.MPIAllTrue(true)) _exit(30 + rank);          // all true
    if (peer.MPIAllTrue(rank != 1)) _exit(40 + rank);      // rank1 false
    peer.MPIBarrierWorld();
    peer.MPIFinalize();
    _exit(0);
  };
  // Rank 2 starts immediately; rank 1 joins 300ms later.
  const pid_t pid2 = child(2, 0);
  REQUIRE(pid2 > 0);
  const pid_t pid1 = child(1, 300);
  REQUIRE(pid1 > 0);

  setenv("TPUCLIENT_RANK", "0", 1);
  MPIDriver mpi(true);
  mpi.MPIInit();
  REQUIRE(mpi.IsMPIRun());
  CHECK_EQ(mpi.MPICommSizeWorld(), 3);
  CHECK(mpi.MPIAllTrue(true));
  CHECK(!mpi.MPIAllTrue(true));  // rank 1 votes false
  mpi.MPIBarrierWorld();
  mpi.MPIFinalize();
  for (pid_t pid : {pid1, pid2}) {
    int status = 0;
    REQUIRE(waitpid(pid, &status, 0) == pid);
    CHECK(WIFEXITED(status));
    CHECK_EQ(WEXITSTATUS(status), 0);
  }
}

TEST_CASE("perf: builtin rank coordinator degrades when a peer dies") {
  const int port = PickLoopbackPort();
  REQUIRE(port > 0);
  CoordEnv env(port);

  const pid_t pid = fork();
  REQUIRE(pid >= 0);
  if (pid == 0) {
    // Rank 1 joins, answers one collective, then dies without
    // finalizing — the coordinator must degrade, not hang.
    setenv("TPUCLIENT_RANK", "1", 1);
    MPIDriver peer(true);
    peer.MPIInit();
    if (!peer.IsMPIRun()) _exit(11);
    peer.MPIAllTrue(true);
    _exit(0);
  }
  setenv("TPUCLIENT_RANK", "0", 1);
  MPIDriver mpi(true);
  mpi.MPIInit();
  REQUIRE(mpi.IsMPIRun());
  CHECK(mpi.MPIAllTrue(true));
  int status = 0;
  REQUIRE(waitpid(pid, &status, 0) == pid);
  // The peer is gone: the next collective degrades to the local
  // value (both polarities) instead of blocking forever.
  CHECK(mpi.MPIAllTrue(true));
  CHECK(!mpi.IsMPIRun());
  CHECK(!mpi.MPIAllTrue(false));
  mpi.MPIFinalize();
}

MINITEST_MAIN
