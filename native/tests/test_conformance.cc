// Protocol-conformance suite: ONE typed matrix of client-visible
// behavior run over BOTH InferenceServerGrpcClient and
// InferenceServerHttpClient against a live tpu_serverd (parity: the
// reference's typed dual-protocol suite
// /root/reference/src/c++/tests/cc_client_test.cc:42,300-1350, plus
// client_timeout_test.cc and memory_leak_test.cc's iteration loop).
//
// Every case is written once as a template over the client type; the
// CONFORMANCE_CASE macro instantiates it for each protocol, gated on
// TPUCLIENT_SERVER_GRPC / TPUCLIENT_SERVER_HTTP (tests/test_native.py
// launches tpu_serverd with both front-ends and sets both).
#include <sys/mman.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "../library/grpc_client.h"
#include "../library/http_client.h"
#include "../library/shm_utils.h"
#include "minitest.h"

using namespace tpuclient;

namespace {

// Adapter: uniform Create + protocol tag for the typed cases.
template <typename ClientT>
struct Protocol;

template <>
struct Protocol<InferenceServerGrpcClient> {
  static const char* EnvUrl() { return getenv("TPUCLIENT_SERVER_GRPC"); }
  static Error Create(std::unique_ptr<InferenceServerGrpcClient>* client) {
    return InferenceServerGrpcClient::Create(client, EnvUrl());
  }
  static constexpr bool kStreaming = true;
};

template <>
struct Protocol<InferenceServerHttpClient> {
  static const char* EnvUrl() { return getenv("TPUCLIENT_SERVER_HTTP"); }
  static Error Create(std::unique_ptr<InferenceServerHttpClient>* client) {
    return InferenceServerHttpClient::Create(client, EnvUrl());
  }
  static constexpr bool kStreaming = false;
};

std::unique_ptr<InferInput> MakeInt32Input(
    const std::string& name, const std::vector<int64_t>& shape,
    const std::vector<int32_t>& data) {
  InferInput* raw = nullptr;
  InferInput::Create(&raw, name, shape, "INT32");
  raw->AppendRaw(reinterpret_cast<const uint8_t*>(data.data()),
                 data.size() * sizeof(int32_t));
  return std::unique_ptr<InferInput>(raw);
}

std::vector<int32_t> Iota(int n, int32_t start = 0) {
  std::vector<int32_t> v(n);
  for (int i = 0; i < n; ++i) v[i] = start + i;
  return v;
}

void CheckInt32Output(InferResult* result, const std::string& name,
                      const std::vector<int32_t>& expect) {
  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  REQUIRE_OK(result->RawData(name, &buf, &byte_size));
  REQUIRE(byte_size == expect.size() * sizeof(int32_t));
  const int32_t* got = reinterpret_cast<const int32_t*>(buf);
  for (size_t i = 0; i < expect.size(); ++i) CHECK_EQ(got[i], expect[i]);
}

// The conformance matrix ------------------------------------------------

// cc_client_test.cc InferMulti variants: several requests with
// DIFFERENT options/request ids in one call; results in order.
template <typename ClientT>
void CaseInferMulti() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));

  constexpr int kRequests = 3;
  std::vector<std::vector<int32_t>> data0, data1;
  std::vector<std::unique_ptr<InferInput>> keep;
  std::vector<std::vector<InferInput*>> inputs;
  std::vector<InferOptions> options;
  for (int r = 0; r < kRequests; ++r) {
    data0.push_back(Iota(16, r));
    data1.push_back(std::vector<int32_t>(16, r + 1));
    auto in0 = MakeInt32Input("INPUT0", {16}, data0.back());
    auto in1 = MakeInt32Input("INPUT1", {16}, data1.back());
    inputs.push_back({in0.get(), in1.get()});
    keep.push_back(std::move(in0));
    keep.push_back(std::move(in1));
    InferOptions option("simple");
    option.request_id = "multi-" + std::to_string(r);
    options.push_back(option);
  }

  std::vector<InferResult*> raw_results;
  REQUIRE_OK(client->InferMulti(&raw_results, options, inputs));
  REQUIRE(raw_results.size() == kRequests);
  for (int r = 0; r < kRequests; ++r) {
    std::unique_ptr<InferResult> result(raw_results[r]);
    REQUIRE_OK(result->RequestStatus());
    std::string id;
    REQUIRE_OK(result->Id(&id));
    CHECK_EQ(id, "multi-" + std::to_string(r));
    std::vector<int32_t> sum(16), diff(16);
    for (int i = 0; i < 16; ++i) {
      sum[i] = data0[r][i] + data1[r][i];
      diff[i] = data0[r][i] - data1[r][i];
    }
    CheckInt32Output(result.get(), "OUTPUT0", sum);
    CheckInt32Output(result.get(), "OUTPUT1", diff);
  }
}

// AsyncInferMulti: one callback with every result.
template <typename ClientT>
void CaseAsyncInferMulti() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));

  constexpr int kRequests = 4;
  std::vector<std::unique_ptr<InferInput>> keep;
  std::vector<std::vector<InferInput*>> inputs;
  std::vector<InferOptions> options;
  auto base0 = Iota(16);
  auto base1 = std::vector<int32_t>(16, 7);
  for (int r = 0; r < kRequests; ++r) {
    auto in0 = MakeInt32Input("INPUT0", {16}, base0);
    auto in1 = MakeInt32Input("INPUT1", {16}, base1);
    inputs.push_back({in0.get(), in1.get()});
    keep.push_back(std::move(in0));
    keep.push_back(std::move(in1));
    options.push_back(InferOptions("simple"));
  }

  std::mutex mutex;
  std::condition_variable cv;
  bool done = false;
  int ok = 0;
  REQUIRE_OK(client->AsyncInferMulti(
      [&](std::vector<InferResult*> results) {
        int good = 0;
        for (InferResult* raw : results) {
          std::unique_ptr<InferResult> result(raw);
          if (result->RequestStatus().IsOk()) {
            const uint8_t* buf = nullptr;
            size_t n = 0;
            if (result->RawData("OUTPUT0", &buf, &n).IsOk() && n == 64) {
              ++good;
            }
          }
        }
        std::lock_guard<std::mutex> lock(mutex);
        ok = good;
        done = true;
        cv.notify_all();
      },
      options, inputs));
  std::unique_lock<std::mutex> lock(mutex);
  REQUIRE(cv.wait_for(lock, std::chrono::seconds(30), [&] { return done; }));
  CHECK_EQ(ok, kRequests);
}

// BYTES tensors in and out (cc_client_test string-tensor variants).
template <typename ClientT>
void CaseStringTensors() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));

  std::vector<std::string> values0, values1;
  for (int i = 0; i < 16; ++i) {
    values0.push_back(std::to_string(i));
    values1.push_back(std::to_string(1));
  }
  InferInput* raw0 = nullptr;
  InferInput::Create(&raw0, "INPUT0", {16}, "BYTES");
  std::unique_ptr<InferInput> in0(raw0);
  REQUIRE_OK(in0->AppendFromString(values0));
  InferInput* raw1 = nullptr;
  InferInput::Create(&raw1, "INPUT1", {16}, "BYTES");
  std::unique_ptr<InferInput> in1(raw1);
  REQUIRE_OK(in1->AppendFromString(values1));

  InferResult* raw_result = nullptr;
  REQUIRE_OK(client->Infer(&raw_result, InferOptions("simple_string"),
                           {in0.get(), in1.get()}));
  std::unique_ptr<InferResult> result(raw_result);
  REQUIRE_OK(result->RequestStatus());
  std::vector<std::string> sums;
  REQUIRE_OK(result->StringData("OUTPUT0", &sums));
  REQUIRE(sums.size() == 16);
  for (int i = 0; i < 16; ++i) CHECK_EQ(sums[i], std::to_string(i + 1));
  std::vector<std::string> diffs;
  REQUIRE_OK(result->StringData("OUTPUT1", &diffs));
  REQUIRE(diffs.size() == 16);
  for (int i = 0; i < 16; ++i) CHECK_EQ(diffs[i], std::to_string(i - 1));
}

// System shared memory for inputs AND outputs: register, infer with
// shm-backed tensors, read outputs from the region, status +
// unregister (cc_client_test shm variants over both protocols).
template <typename ClientT>
void CaseSystemSharedMemory() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));

  const std::string tag =
      Protocol<ClientT>::kStreaming ? "grpc" : "http";
  const std::string in_key = "/conf_in_" + tag;
  const std::string out_key = "/conf_out_" + tag;
  const size_t in_size = 2 * 16 * sizeof(int32_t);
  const size_t out_size = 2 * 16 * sizeof(int32_t);

  // Fresh regions (unlink leftovers from a crashed prior run).
  UnlinkSharedMemoryRegion(in_key);
  UnlinkSharedMemoryRegion(out_key);
  int in_fd = -1, out_fd = -1;
  REQUIRE_OK(CreateSharedMemoryRegion(in_key, in_size, &in_fd));
  REQUIRE_OK(CreateSharedMemoryRegion(out_key, out_size, &out_fd));
  void* in_ptr = nullptr;
  void* out_ptr = nullptr;
  REQUIRE_OK(MapSharedMemory(in_fd, 0, in_size, &in_ptr));
  REQUIRE_OK(MapSharedMemory(out_fd, 0, out_size, &out_ptr));

  auto data0 = Iota(16);
  std::vector<int32_t> data1(16, 5);
  memcpy(in_ptr, data0.data(), 16 * sizeof(int32_t));
  memcpy(static_cast<uint8_t*>(in_ptr) + 16 * sizeof(int32_t),
         data1.data(), 16 * sizeof(int32_t));

  const std::string in_region = "conf_in_region_" + tag;
  const std::string out_region = "conf_out_region_" + tag;
  client->UnregisterSystemSharedMemory(in_region);
  client->UnregisterSystemSharedMemory(out_region);
  REQUIRE_OK(client->RegisterSystemSharedMemory(in_region, in_key, in_size));
  REQUIRE_OK(
      client->RegisterSystemSharedMemory(out_region, out_key, out_size));

  InferInput* raw0 = nullptr;
  InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  std::unique_ptr<InferInput> in0(raw0);
  REQUIRE_OK(in0->SetSharedMemory(in_region, 16 * sizeof(int32_t), 0));
  InferInput* raw1 = nullptr;
  InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<InferInput> in1(raw1);
  REQUIRE_OK(
      in1->SetSharedMemory(in_region, 16 * sizeof(int32_t),
                           16 * sizeof(int32_t)));

  InferRequestedOutput* rout0 = nullptr;
  InferRequestedOutput::Create(&rout0, "OUTPUT0");
  std::unique_ptr<InferRequestedOutput> out0(rout0);
  REQUIRE_OK(out0->SetSharedMemory(out_region, 16 * sizeof(int32_t), 0));
  InferRequestedOutput* rout1 = nullptr;
  InferRequestedOutput::Create(&rout1, "OUTPUT1");
  std::unique_ptr<InferRequestedOutput> out1(rout1);
  REQUIRE_OK(out1->SetSharedMemory(out_region, 16 * sizeof(int32_t),
                                   16 * sizeof(int32_t)));

  InferResult* raw_result = nullptr;
  REQUIRE_OK(client->Infer(&raw_result, InferOptions("simple"),
                           {in0.get(), in1.get()},
                           {out0.get(), out1.get()}));
  std::unique_ptr<InferResult> result(raw_result);
  REQUIRE_OK(result->RequestStatus());

  const int32_t* sums = static_cast<const int32_t*>(out_ptr);
  const int32_t* diffs = sums + 16;
  for (int i = 0; i < 16; ++i) {
    CHECK_EQ(sums[i], data0[i] + data1[i]);
    CHECK_EQ(diffs[i], data0[i] - data1[i]);
  }

  REQUIRE_OK(client->UnregisterSystemSharedMemory(in_region));
  REQUIRE_OK(client->UnregisterSystemSharedMemory(out_region));
  UnmapSharedMemory(in_ptr, in_size);
  UnmapSharedMemory(out_ptr, out_size);
  CloseSharedMemory(in_fd);
  CloseSharedMemory(out_fd);
  UnlinkSharedMemoryRegion(in_key);
  UnlinkSharedMemoryRegion(out_key);
}

// LoadModel with a config override, infer against the overridden
// config, then unload (cc_client_test.cc:1202-1350 LoadWithConfig).
template <typename ClientT>
void CaseLoadWithOverride() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));

  client->UnloadModel("add_sub_fp32");
  // Override sticks a recognizable max_batch_size on the loaded copy.
  REQUIRE_OK(client->LoadModel(
      "add_sub_fp32", {}, "{\"max_batch_size\": 5}"));

  bool ready = false;
  REQUIRE_OK(client->IsModelReady(&ready, "add_sub_fp32"));
  CHECK(ready);

  std::vector<float> f0(16), f1(16);
  for (int i = 0; i < 16; ++i) {
    f0[i] = static_cast<float>(i);
    f1[i] = 2.0f;
  }
  InferInput* raw0 = nullptr;
  InferInput::Create(&raw0, "INPUT0", {16}, "FP32");
  std::unique_ptr<InferInput> in0(raw0);
  in0->AppendRaw(reinterpret_cast<const uint8_t*>(f0.data()),
                 f0.size() * sizeof(float));
  InferInput* raw1 = nullptr;
  InferInput::Create(&raw1, "INPUT1", {16}, "FP32");
  std::unique_ptr<InferInput> in1(raw1);
  in1->AppendRaw(reinterpret_cast<const uint8_t*>(f1.data()),
                 f1.size() * sizeof(float));

  InferResult* raw_result = nullptr;
  REQUIRE_OK(client->Infer(&raw_result, InferOptions("add_sub_fp32"),
                           {in0.get(), in1.get()}));
  std::unique_ptr<InferResult> result(raw_result);
  REQUIRE_OK(result->RequestStatus());
  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  REQUIRE_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  REQUIRE(byte_size == 16 * sizeof(float));
  const float* sums = reinterpret_cast<const float*>(buf);
  for (int i = 0; i < 16; ++i) CHECK_EQ(sums[i], f0[i] + f1[i]);

  REQUIRE_OK(client->UnloadModel("add_sub_fp32"));
  ready = true;
  REQUIRE_OK(client->IsModelReady(&ready, "add_sub_fp32"));
  CHECK(!ready);
  // Restore for other cases/suites.
  REQUIRE_OK(client->LoadModel("add_sub_fp32"));
}

// Client-side timeout: a 1 us deadline must surface as an error, and
// the client must remain usable afterwards (client_timeout_test.cc).
template <typename ClientT>
void CaseClientTimeout() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));

  auto data0 = Iota(16);
  std::vector<int32_t> data1(16, 1);
  auto in0 = MakeInt32Input("INPUT0", {16}, data0);
  auto in1 = MakeInt32Input("INPUT1", {16}, data1);

  InferOptions options("simple");
  options.client_timeout_us = 1;  // unmeetable
  InferResult* raw_result = nullptr;
  Error err =
      client->Infer(&raw_result, options, {in0.get(), in1.get()});
  if (err.IsOk()) {
    // Some transports report the deadline on the result instead.
    std::unique_ptr<InferResult> result(raw_result);
    CHECK(!result->RequestStatus().IsOk());
  } else {
    CHECK(!err.IsOk());
  }

  // The same client must still complete a normal request.
  InferOptions ok_options("simple");
  InferResult* ok_raw = nullptr;
  REQUIRE_OK(client->Infer(&ok_raw, ok_options, {in0.get(), in1.get()}));
  std::unique_ptr<InferResult> ok_result(ok_raw);
  REQUIRE_OK(ok_result->RequestStatus());
}

// Unknown-model error mapping is identical across protocols.
template <typename ClientT>
void CaseUnknownModel() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));
  auto data = Iota(16);
  auto in0 = MakeInt32Input("INPUT0", {16}, data);
  InferResult* raw_result = nullptr;
  Error err = client->Infer(&raw_result, InferOptions("no_such_model"),
                            {in0.get()});
  if (err.IsOk()) {
    // The HTTP client surfaces server-side errors on the result
    // (parity: InferResultHttp::RequestStatus); gRPC fails the call.
    REQUIRE(raw_result != nullptr);
    std::unique_ptr<InferResult> result(raw_result);
    CHECK(!result->RequestStatus().IsOk());
  } else {
    CHECK(!err.IsOk());
  }
}

// Leak-iteration loop (memory_leak_test.cc): many create/infer/destroy
// cycles; watches process RSS stays bounded rather than instrumenting
// the allocator.
template <typename ClientT>
void CaseIterationLoop() {
  auto rss_kb = [] {
    FILE* f = fopen("/proc/self/status", "r");
    long kb = 0;
    if (f != nullptr) {
      char line[256];
      while (fgets(line, sizeof(line), f) != nullptr) {
        if (strncmp(line, "VmRSS:", 6) == 0) {
          kb = atol(line + 6);
          break;
        }
      }
      fclose(f);
    }
    return kb;
  };

  auto data0 = Iota(16);
  std::vector<int32_t> data1(16, 3);
  auto one_cycle = [&]() {
    std::unique_ptr<ClientT> client;
    REQUIRE_OK(Protocol<ClientT>::Create(&client));
    for (int i = 0; i < 10; ++i) {
      auto in0 = MakeInt32Input("INPUT0", {16}, data0);
      auto in1 = MakeInt32Input("INPUT1", {16}, data1);
      InferResult* raw = nullptr;
      REQUIRE_OK(client->Infer(&raw, InferOptions("simple"),
                               {in0.get(), in1.get()}));
      std::unique_ptr<InferResult> result(raw);
      REQUIRE_OK(result->RequestStatus());
    }
  };

  for (int warm = 0; warm < 3; ++warm) one_cycle();  // settle allocator
  long before = rss_kb();
  for (int cycle = 0; cycle < 15; ++cycle) one_cycle();
  long after = rss_kb();
  // 150 inferences + 15 client setups should not grow RSS by more
  // than a few MB; a per-request leak shows up far larger.
  CHECK(after - before < 16 * 1024);
}

// 4 MiB FP32 tensors each way (8 MiB request, 8 MiB response): the
// bodies far exceed HTTP/2's 64 KiB default windows and the 1 MiB max
// frame size, so this passes only if chunked DATA + WINDOW_UPDATE
// flow control works in both directions on both transports (and the
// HTTP/1.1 binary path handles multi-megabyte bodies).
template <typename ClientT>
void CaseLargeTensorFlowControl() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));
  constexpr int64_t kN = 1048576;
  std::vector<float> a(kN), b(kN);
  for (int64_t i = 0; i < kN; ++i) {
    a[i] = static_cast<float>(i % 9973);
    b[i] = static_cast<float>(i % 7919);
  }
  auto make = [](const char* name, const std::vector<float>& data) {
    InferInput* raw = nullptr;
    InferInput::Create(&raw, name, {kN}, "FP32");
    raw->AppendRaw(reinterpret_cast<const uint8_t*>(data.data()),
                   data.size() * sizeof(float));
    return std::unique_ptr<InferInput>(raw);
  };
  auto in0 = make("INPUT0", a);
  auto in1 = make("INPUT1", b);
  InferResult* raw_result = nullptr;
  REQUIRE_OK(client->Infer(&raw_result, InferOptions("add_sub_large"),
                           {in0.get(), in1.get()}));
  std::unique_ptr<InferResult> result(raw_result);
  REQUIRE(result->RequestStatus().IsOk());
  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  REQUIRE_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  REQUIRE(byte_size == static_cast<size_t>(kN) * sizeof(float));
  const float* sum = reinterpret_cast<const float*>(buf);
  // Spot-check across the whole tensor (every frame boundary region
  // matters; a misordered chunk shows up as a wrong stripe).
  for (int64_t i = 0; i < kN; i += 65521) {
    CHECK_EQ(sum[i], a[i] + b[i]);
  }
  CHECK_EQ(sum[kN - 1], a[kN - 1] + b[kN - 1]);
  REQUIRE_OK(result->RawData("OUTPUT1", &buf, &byte_size));
  REQUIRE(byte_size == static_cast<size_t>(kN) * sizeof(float));
  const float* diff = reinterpret_cast<const float*>(buf);
  for (int64_t i = 0; i < kN; i += 65521) {
    CHECK_EQ(diff[i], a[i] - b[i]);
  }
}

// Four concurrent 4 MiB-per-tensor infers on ONE client: on gRPC the
// async worker multiplexes them over a shared HTTP/2 connection, so
// large DATA frames from different streams interleave and compete
// for the shared connection window while each stream's own window
// gates it — the distinct failure mode vs one big sequential call is
// cross-stream window accounting. HTTP exercises the connection
// pool's concurrent large bodies.
template <typename ClientT>
void CaseConcurrentLargeTensors() {
  std::unique_ptr<ClientT> client;
  REQUIRE_OK(Protocol<ClientT>::Create(&client));
  constexpr int64_t kN = 1048576;
  constexpr int kRequests = 4;
  std::vector<std::vector<float>> a(kRequests), b(kRequests);
  std::vector<std::unique_ptr<InferInput>> keep;
  std::mutex mutex;
  std::condition_variable cv;
  int done = 0, good = 0;
  for (int r = 0; r < kRequests; ++r) {
    a[r].resize(kN);
    b[r].resize(kN);
    for (int64_t i = 0; i < kN; ++i) {
      a[r][i] = static_cast<float>((i + r) % 9973);
      b[r][i] = static_cast<float>((i + 2 * r) % 7919);
    }
    auto make = [](const char* name, const std::vector<float>& data) {
      InferInput* raw = nullptr;
      InferInput::Create(&raw, name, {kN}, "FP32");
      raw->AppendRaw(reinterpret_cast<const uint8_t*>(data.data()),
                     data.size() * sizeof(float));
      return std::unique_ptr<InferInput>(raw);
    };
    auto in0 = make("INPUT0", a[r]);
    auto in1 = make("INPUT1", b[r]);
    REQUIRE_OK(client->AsyncInfer(
        [&, r](InferResult* raw) {
          std::unique_ptr<InferResult> result(raw);
          bool ok = result->RequestStatus().IsOk();
          if (ok) {
            const uint8_t* buf = nullptr;
            size_t byte_size = 0;
            ok = result->RawData("OUTPUT0", &buf, &byte_size).IsOk() &&
                 byte_size == static_cast<size_t>(kN) * sizeof(float);
            if (ok) {
              const float* sum = reinterpret_cast<const float*>(buf);
              for (int64_t i = 0; i < kN && ok; i += 65521) {
                ok = sum[i] == a[r][i] + b[r][i];
              }
              ok = ok && sum[kN - 1] == a[r][kN - 1] + b[r][kN - 1];
            }
          }
          std::lock_guard<std::mutex> lock(mutex);
          ++done;
          if (ok) ++good;
          cv.notify_all();
        },
        InferOptions("add_sub_large"), {in0.get(), in1.get()}));
    keep.push_back(std::move(in0));
    keep.push_back(std::move(in1));
  }
  std::unique_lock<std::mutex> lock(mutex);
  REQUIRE(cv.wait_for(lock, std::chrono::seconds(120),
                      [&] { return done == kRequests; }));
  CHECK_EQ(good, kRequests);
}

}  // namespace

// minitest's TEST_CASE keys its registration symbols on __LINE__, so
// one macro expanding to TWO cases would collide — register directly.
#define CONFORMANCE_CASE(case_fn, label)                            \
  static void run_grpc_##case_fn() {                                \
    if (Protocol<InferenceServerGrpcClient>::EnvUrl() == nullptr)   \
      return;                                                       \
    case_fn<InferenceServerGrpcClient>();                           \
  }                                                                 \
  static minitest::Registrar reg_grpc_##case_fn(                    \
      "conformance-grpc: " label, run_grpc_##case_fn);              \
  static void run_http_##case_fn() {                                \
    if (Protocol<InferenceServerHttpClient>::EnvUrl() == nullptr)   \
      return;                                                       \
    case_fn<InferenceServerHttpClient>();                           \
  }                                                                 \
  static minitest::Registrar reg_http_##case_fn(                    \
      "conformance-http: " label, run_http_##case_fn);

CONFORMANCE_CASE(CaseInferMulti, "InferMulti ordered results")
CONFORMANCE_CASE(CaseAsyncInferMulti, "AsyncInferMulti one callback")
CONFORMANCE_CASE(CaseStringTensors, "BYTES tensors round trip")
CONFORMANCE_CASE(CaseSystemSharedMemory, "system shm inputs + outputs")
CONFORMANCE_CASE(CaseLoadWithOverride, "load with config override")
CONFORMANCE_CASE(CaseClientTimeout, "client timeout surfaces + recovers")
CONFORMANCE_CASE(CaseUnknownModel, "unknown model error mapping")
CONFORMANCE_CASE(CaseIterationLoop, "leak iteration loop bounded RSS")
CONFORMANCE_CASE(CaseLargeTensorFlowControl,
                 "multi-MB tensors chunk through flow control")
CONFORMANCE_CASE(CaseConcurrentLargeTensors,
                 "concurrent multi-MB streams share one connection")

// Streaming is protocol-specific (the reference's streaming matrix is
// gRPC-only too): decoupled bidi stream with per-request options.
TEST_CASE("conformance-grpc: bidi streaming with request ids") {
  if (Protocol<InferenceServerGrpcClient>::EnvUrl() == nullptr) return;
  std::unique_ptr<InferenceServerGrpcClient> client;
  REQUIRE_OK(Protocol<InferenceServerGrpcClient>::Create(&client));

  std::mutex mutex;
  std::condition_variable cv;
  std::vector<std::string> ids;
  int ok = 0;
  REQUIRE_OK(client->StartStream([&](InferResult* raw) {
    std::unique_ptr<InferResult> result(raw);
    std::string id;
    bool good = result->RequestStatus().IsOk() &&
                result->Id(&id).IsOk();
    std::lock_guard<std::mutex> lock(mutex);
    ids.push_back(id);
    if (good) ++ok;
    cv.notify_all();
  }));

  auto data0 = Iota(16);
  std::vector<int32_t> data1(16, 9);
  constexpr int kRequests = 6;
  for (int r = 0; r < kRequests; ++r) {
    auto in0 = MakeInt32Input("INPUT0", {16}, data0);
    auto in1 = MakeInt32Input("INPUT1", {16}, data1);
    InferOptions options("simple");
    options.request_id = "stream-" + std::to_string(r);
    REQUIRE_OK(client->AsyncStreamInfer(options, {in0.get(), in1.get()}));
  }
  {
    std::unique_lock<std::mutex> lock(mutex);
    REQUIRE(cv.wait_for(lock, std::chrono::seconds(30), [&] {
      return ids.size() == kRequests;
    }));
  }
  CHECK_EQ(ok, kRequests);
  // Per-request ids all came back (order may interleave).
  for (int r = 0; r < kRequests; ++r) {
    bool found = false;
    for (const auto& id : ids) {
      if (id == "stream-" + std::to_string(r)) found = true;
    }
    CHECK(found);
  }
  REQUIRE_OK(client->StopStream());
}

MINITEST_MAIN
