// gRPC client tests.
//
// Offline cases cover the gRPC wire framing, status mapping, and
// request marshaling. Integration cases run when
// TPUCLIENT_SERVER_GRPC is set to a live server's host:port
// (tests/test_native.py launches the Python server and sets it) —
// parity with the reference's tier-2 live-server suite
// (cc_client_test.cc run against localhost:8001).
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>

#include "../library/grpc_client.h"
#include "minitest.h"

using namespace tpuclient;

namespace {

std::unique_ptr<InferInput> MakeInt32Input(
    const std::string& name, const std::vector<int64_t>& shape,
    const int32_t* data, size_t count) {
  InferInput* raw = nullptr;
  InferInput::Create(&raw, name, shape, "INT32");
  raw->AppendRaw(
      reinterpret_cast<const uint8_t*>(data), count * sizeof(int32_t));
  return std::unique_ptr<InferInput>(raw);
}

}  // namespace

TEST_CASE("grpc: message framing round trip") {
  std::string payload = "hello-protobuf-bytes";
  std::string framed = FrameGrpcMessage(payload);
  REQUIRE(framed.size() == payload.size() + 5);
  CHECK_EQ(framed[0], '\0');

  GrpcMessageReader reader;
  std::vector<std::string> messages;
  // Feed in awkward split points.
  const uint8_t* data = reinterpret_cast<const uint8_t*>(framed.data());
  REQUIRE(reader.Feed(data, 3, &messages));
  CHECK_EQ(messages.size(), 0u);
  REQUIRE(reader.Feed(data + 3, 4, &messages));
  REQUIRE(reader.Feed(data + 7, framed.size() - 7, &messages));
  REQUIRE(messages.size() == 1);
  CHECK_EQ(messages[0], payload);

  // Two messages in one feed.
  std::string two = FrameGrpcMessage("one") + FrameGrpcMessage("two");
  messages.clear();
  GrpcMessageReader reader2;
  REQUIRE(reader2.Feed(
      reinterpret_cast<const uint8_t*>(two.data()), two.size(), &messages));
  REQUIRE(messages.size() == 2);
  CHECK_EQ(messages[0], "one");
  CHECK_EQ(messages[1], "two");

  // Compressed flag (unsupported) must be rejected.
  GrpcMessageReader reader3;
  std::string compressed = FrameGrpcMessage("x");
  compressed[0] = 1;
  messages.clear();
  CHECK(!reader3.Feed(
      reinterpret_cast<const uint8_t*>(compressed.data()),
      compressed.size(), &messages));
}

TEST_CASE("grpc: status from trailers") {
  // OK.
  Error err = StatusFromTrailers(
      {{":status", "200"}}, {{"grpc-status", "0"}}, "");
  CHECK(err.IsOk());
  // Error with percent-encoded message.
  err = StatusFromTrailers(
      {{":status", "200"}},
      {{"grpc-status", "5"}, {"grpc-message", "model%20not%20found"}}, "");
  CHECK(!err.IsOk());
  CHECK(err.Message().find("model not found") != std::string::npos);
  // Trailers-only response: status appears in the header list.
  err = StatusFromTrailers(
      {{":status", "200"}, {"grpc-status", "12"}}, {}, "");
  CHECK(!err.IsOk());
  // Transport error dominates.
  err = StatusFromTrailers({}, {{"grpc-status", "0"}}, "connection reset");
  CHECK(!err.IsOk());
  CHECK(err.Message().find("connection reset") != std::string::npos);
}

TEST_CASE("grpc: percent decode") {
  CHECK_EQ(PercentDecode("a%20b%2Fc"), "a b/c");
  CHECK_EQ(PercentDecode("no-escapes"), "no-escapes");
  CHECK_EQ(PercentDecode("trailing%2"), "trailing%2");
}

//==============================================================================
// Integration against a live server.

namespace {

const char* ServerUrl() { return getenv("TPUCLIENT_SERVER_GRPC"); }

}  // namespace

TEST_CASE("grpc-live: health and metadata") {
  if (ServerUrl() == nullptr) return;
  std::unique_ptr<InferenceServerGrpcClient> client;
  REQUIRE_OK(InferenceServerGrpcClient::Create(&client, ServerUrl()));

  bool live = false, ready = false, model_ready = false;
  REQUIRE_OK(client->IsServerLive(&live));
  CHECK(live);
  REQUIRE_OK(client->IsServerReady(&ready));
  CHECK(ready);
  REQUIRE_OK(client->IsModelReady(&model_ready, "simple"));
  CHECK(model_ready);

  inference::ServerMetadataResponse server_metadata;
  REQUIRE_OK(client->ServerMetadata(&server_metadata));
  CHECK(!server_metadata.name().empty());

  inference::ModelMetadataResponse model_metadata;
  REQUIRE_OK(client->ModelMetadata(&model_metadata, "simple"));
  CHECK_EQ(model_metadata.name(), "simple");
  CHECK_EQ(model_metadata.inputs_size(), 2);

  inference::ModelConfigResponse model_config;
  REQUIRE_OK(client->ModelConfig(&model_config, "simple"));
  CHECK_EQ(model_config.config().name(), "simple");

  inference::RepositoryIndexResponse index;
  REQUIRE_OK(client->ModelRepositoryIndex(&index));
  CHECK(index.models_size() >= 1);
}

TEST_CASE("grpc-live: sync infer add_sub") {
  if (ServerUrl() == nullptr) return;
  std::unique_ptr<InferenceServerGrpcClient> client;
  REQUIRE_OK(InferenceServerGrpcClient::Create(&client, ServerUrl()));

  int32_t data0[16], data1[16];
  for (int i = 0; i < 16; ++i) {
    data0[i] = i;
    data1[i] = 1;
  }
  auto in0 = MakeInt32Input("INPUT0", {16}, data0, 16);
  auto in1 = MakeInt32Input("INPUT1", {16}, data1, 16);

  InferOptions options("simple");
  options.request_id = "native-grpc-1";
  InferResult* raw_result = nullptr;
  REQUIRE_OK(client->Infer(&raw_result, options, {in0.get(), in1.get()}));
  std::unique_ptr<InferResult> result(raw_result);
  REQUIRE_OK(result->RequestStatus());

  std::string id;
  REQUIRE_OK(result->Id(&id));
  CHECK_EQ(id, "native-grpc-1");

  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  REQUIRE_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  REQUIRE(byte_size == 64);
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK_EQ(sum[i], data0[i] + 1);

  REQUIRE_OK(result->RawData("OUTPUT1", &buf, &byte_size));
  REQUIRE(byte_size == 64);
  const int32_t* diff = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK_EQ(diff[i], data0[i] - 1);

  // Client-side stats were recorded.
  InferStat stat;
  REQUIRE_OK(client->ClientInferStat(&stat));
  CHECK_EQ(stat.completed_request_count, 1u);

  // Error path: unknown model maps to a gRPC error.
  InferOptions bad_options("no-such-model");
  InferResult* bad_result = nullptr;
  Error err = client->Infer(&bad_result, bad_options, {in0.get()});
  CHECK(!err.IsOk());
}

TEST_CASE("grpc-live: async infer") {
  if (ServerUrl() == nullptr) return;
  std::unique_ptr<InferenceServerGrpcClient> client;
  REQUIRE_OK(InferenceServerGrpcClient::Create(&client, ServerUrl()));

  int32_t data0[16], data1[16];
  for (int i = 0; i < 16; ++i) {
    data0[i] = i;
    data1[i] = 2;
  }
  auto in0 = MakeInt32Input("INPUT0", {16}, data0, 16);
  auto in1 = MakeInt32Input("INPUT1", {16}, data1, 16);

  constexpr int kRequests = 8;
  std::mutex mutex;
  std::condition_variable cv;
  int completed = 0;
  int ok = 0;

  InferOptions options("simple");
  for (int r = 0; r < kRequests; ++r) {
    REQUIRE_OK(client->AsyncInfer(
        [&](InferResult* result) {
          std::unique_ptr<InferResult> owned(result);
          bool good = owned->RequestStatus().IsOk();
          if (good) {
            const uint8_t* buf = nullptr;
            size_t n = 0;
            good = owned->RawData("OUTPUT0", &buf, &n).IsOk() && n == 64;
          }
          std::lock_guard<std::mutex> lock(mutex);
          ++completed;
          if (good) ++ok;
          cv.notify_all();
        },
        options, {in0.get(), in1.get()}));
  }
  std::unique_lock<std::mutex> lock(mutex);
  REQUIRE(cv.wait_for(lock, std::chrono::seconds(30), [&] {
    return completed == kRequests;
  }));
  CHECK_EQ(ok, kRequests);
}

TEST_CASE("grpc-live: bidi stream infer") {
  if (ServerUrl() == nullptr) return;
  std::unique_ptr<InferenceServerGrpcClient> client;
  REQUIRE_OK(InferenceServerGrpcClient::Create(&client, ServerUrl()));

  int32_t data0[16], data1[16];
  for (int i = 0; i < 16; ++i) {
    data0[i] = i;
    data1[i] = 3;
  }
  auto in0 = MakeInt32Input("INPUT0", {16}, data0, 16);
  auto in1 = MakeInt32Input("INPUT1", {16}, data1, 16);

  std::mutex mutex;
  std::condition_variable cv;
  int received = 0;
  int ok = 0;
  REQUIRE_OK(client->StartStream([&](InferResult* result) {
    std::unique_ptr<InferResult> owned(result);
    bool good = owned->RequestStatus().IsOk();
    if (good) {
      const uint8_t* buf = nullptr;
      size_t n = 0;
      good = owned->RawData("OUTPUT0", &buf, &n).IsOk() && n == 64;
    }
    std::lock_guard<std::mutex> lock(mutex);
    ++received;
    if (good) ++ok;
    cv.notify_all();
  }));

  constexpr int kRequests = 5;
  InferOptions options("simple");
  for (int r = 0; r < kRequests; ++r) {
    REQUIRE_OK(client->AsyncStreamInfer(options, {in0.get(), in1.get()}));
  }
  {
    std::unique_lock<std::mutex> lock(mutex);
    REQUIRE(cv.wait_for(lock, std::chrono::seconds(30), [&] {
      return received == kRequests;
    }));
  }
  CHECK_EQ(ok, kRequests);
  REQUIRE_OK(client->StopStream());
}

TEST_CASE("grpc-live: model statistics and concurrency limit") {
  if (ServerUrl() == nullptr) return;
  std::unique_ptr<InferenceServerGrpcClient> client;
  REQUIRE_OK(InferenceServerGrpcClient::Create(&client, ServerUrl()));

  inference::ModelStatisticsResponse stats;
  REQUIRE_OK(client->ModelInferenceStatistics(&stats, "simple"));
  CHECK(stats.model_stats_size() >= 1);
}

TEST_CASE("grpc-live: channel cache shares connections per URL") {
  if (ServerUrl() == nullptr) return;
  // Default max share count is 6: the first six clients ride one
  // connection, the seventh opens a new one (parity: GetStub,
  // grpc_client.cc:50-152). The cache is URL-string-keyed and other
  // cases already used the bare URL, so take a fresh alias (the
  // transport strips the scheme).
  const std::string url = std::string("sharetest://") + ServerUrl();
  std::vector<std::unique_ptr<InferenceServerGrpcClient>> clients;
  for (int i = 0; i < 7; ++i) {
    std::unique_ptr<InferenceServerGrpcClient> c;
    REQUIRE_OK(InferenceServerGrpcClient::Create(
        &c, url, /*verbose=*/false, /*use_cached_channel=*/true));
    clients.push_back(std::move(c));
  }
  for (int i = 1; i < 6; ++i) {
    CHECK_EQ(clients[0]->RawChannel(), clients[i]->RawChannel());
  }
  CHECK(clients[6]->RawChannel() != clients[0]->RawChannel());

  // Opting out always gets a private connection.
  std::unique_ptr<InferenceServerGrpcClient> solo;
  REQUIRE_OK(InferenceServerGrpcClient::Create(
      &solo, ServerUrl(), false, /*use_cached_channel=*/false));
  CHECK(solo->RawChannel() != clients[6]->RawChannel());

  // Shared-channel clients still serve traffic correctly.
  for (auto& c : clients) {
    bool live = false;
    REQUIRE_OK(c->IsServerLive(&live));
    CHECK(live);
  }
}

MINITEST_MAIN
