// Minimal single-header test framework for the native unit tests.
//
// The reference vendors doctest (a ~6 kLoC public single header,
// /root/reference/src/c++/perf_analyzer/doctest.h); this image has no
// test library and we do not copy vendored code, so we carry a small
// registration-macro framework with the same usage shape:
//
//   TEST_CASE("name") { CHECK(x == y); REQUIRE(!err); }
//
// A failing CHECK records and continues; a failing REQUIRE aborts the
// test case. The runner prints per-case results and exits non-zero on
// any failure. Filter cases with argv[1] substring.
#pragma once

#include <cstdio>
#include <cstring>
#include <functional>
#include <sstream>
#include <string>
#include <vector>

namespace minitest {

struct TestCase {
  const char* name;
  std::function<void()> fn;
};

inline std::vector<TestCase>& Registry() {
  static std::vector<TestCase> cases;
  return cases;
}

struct Registrar {
  Registrar(const char* name, std::function<void()> fn) {
    Registry().push_back({name, std::move(fn)});
  }
};

struct Failure {
  std::string message;
};

struct State {
  int checks_failed = 0;
  int checks_passed = 0;
  std::vector<std::string> messages;
};

inline State*& Current() {
  static State* s = nullptr;
  return s;
}

inline void RecordFailure(
    const char* kind, const char* expr, const char* file, int line,
    const std::string& extra = "") {
  std::ostringstream os;
  os << file << ":" << line << ": " << kind << "(" << expr << ") failed";
  if (!extra.empty()) os << " — " << extra;
  Current()->checks_failed++;
  Current()->messages.push_back(os.str());
}

inline int RunAll(int argc, char** argv) {
  const char* filter = (argc > 1) ? argv[1] : nullptr;
  int failed_cases = 0, ran = 0;
  for (auto& tc : Registry()) {
    if (filter && strstr(tc.name, filter) == nullptr) continue;
    State state;
    Current() = &state;
    bool aborted = false;
    try {
      tc.fn();
    } catch (const Failure&) {
      aborted = true;
    } catch (const std::exception& e) {
      state.checks_failed++;
      state.messages.push_back(
          std::string("unhandled exception: ") + e.what());
    }
    ++ran;
    if (state.checks_failed > 0) {
      ++failed_cases;
      printf("[FAIL] %s%s\n", tc.name, aborted ? " (aborted)" : "");
      for (const auto& m : state.messages) printf("       %s\n", m.c_str());
    } else {
      printf("[ ok ] %s (%d checks)\n", tc.name, state.checks_passed);
    }
    Current() = nullptr;
  }
  printf(
      "%d/%d test cases passed\n", ran - failed_cases, ran);
  return failed_cases == 0 ? 0 : 1;
}

}  // namespace minitest

#define MT_CONCAT_(a, b) a##b
#define MT_CONCAT(a, b) MT_CONCAT_(a, b)

#define TEST_CASE(name)                                                \
  static void MT_CONCAT(mt_case_, __LINE__)();                         \
  static ::minitest::Registrar MT_CONCAT(mt_reg_, __LINE__)(           \
      name, MT_CONCAT(mt_case_, __LINE__));                            \
  static void MT_CONCAT(mt_case_, __LINE__)()

#define CHECK(expr)                                                    \
  do {                                                                 \
    if (expr) {                                                        \
      ::minitest::Current()->checks_passed++;                          \
    } else {                                                           \
      ::minitest::RecordFailure("CHECK", #expr, __FILE__, __LINE__);   \
    }                                                                  \
  } while (0)

#define CHECK_EQ(a, b)                                                 \
  do {                                                                 \
    auto _mta = (a);                                                   \
    auto _mtb = (b);                                                   \
    if (_mta == _mtb) {                                                \
      ::minitest::Current()->checks_passed++;                          \
    } else {                                                           \
      std::ostringstream _os;                                          \
      _os << "lhs=" << _mta << " rhs=" << _mtb;                        \
      ::minitest::RecordFailure(                                       \
          "CHECK_EQ", #a " == " #b, __FILE__, __LINE__, _os.str());    \
    }                                                                  \
  } while (0)

#define REQUIRE(expr)                                                  \
  do {                                                                 \
    if (expr) {                                                        \
      ::minitest::Current()->checks_passed++;                          \
    } else {                                                           \
      ::minitest::RecordFailure("REQUIRE", #expr, __FILE__, __LINE__); \
      throw ::minitest::Failure{#expr};                                \
    }                                                                  \
  } while (0)

// Requires a tpuclient::Error to be OK; prints its message otherwise.
#define REQUIRE_OK(err_expr)                                           \
  do {                                                                 \
    auto _mterr = (err_expr);                                          \
    if (_mterr.IsOk()) {                                               \
      ::minitest::Current()->checks_passed++;                          \
    } else {                                                           \
      ::minitest::RecordFailure(                                       \
          "REQUIRE_OK", #err_expr, __FILE__, __LINE__, _mterr.Message()); \
      throw ::minitest::Failure{#err_expr};                            \
    }                                                                  \
  } while (0)

#define MINITEST_MAIN                                                  \
  int main(int argc, char** argv) {                                    \
    return ::minitest::RunAll(argc, argv);                             \
  }
