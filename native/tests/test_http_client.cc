// HTTP client tests.
//
// Offline cases cover request-body generation and response parsing
// (parity: reference HTTPJSONDataTest, cc_client_test.cc:1660).
// Integration cases run when TPUCLIENT_SERVER_HTTP is set to a live
// server's host:port (tests/test_native.py launches the Python server
// and sets it) — parity with the reference's tier-2 live-server tests.
#include <cstdlib>
#include <cstring>

#include "../library/http_client.h"
#include "minitest.h"

using namespace tpuclient;

namespace {

std::unique_ptr<InferInput> MakeFp32Input(
    const std::string& name, const std::vector<int64_t>& shape,
    const float* data, size_t count) {
  InferInput* raw = nullptr;
  InferInput::Create(&raw, name, shape, "FP32");
  raw->AppendRaw(
      reinterpret_cast<const uint8_t*>(data), count * sizeof(float));
  return std::unique_ptr<InferInput>(raw);
}

}  // namespace

TEST_CASE("http: GenerateRequestBody binary layout") {
  float data0[16], data1[16];
  for (int i = 0; i < 16; ++i) {
    data0[i] = static_cast<float>(i);
    data1[i] = static_cast<float>(i);
  }
  auto in0 = MakeFp32Input("INPUT0", {1, 16}, data0, 16);
  auto in1 = MakeFp32Input("INPUT1", {1, 16}, data1, 16);

  InferRequestedOutput* out0 = nullptr;
  InferRequestedOutput::Create(&out0, "OUTPUT0");
  std::unique_ptr<InferRequestedOutput> out_guard(out0);

  InferOptions options("simple");
  options.request_id = "req-1";

  std::vector<char> body;
  size_t header_length = 0;
  REQUIRE_OK(InferenceServerHttpClient::GenerateRequestBody(
      &body, &header_length, options, {in0.get(), in1.get()}, {out0}));

  // Header is valid JSON followed by 2x64 binary bytes.
  CHECK_EQ(body.size(), header_length + 128);
  json::Value header;
  REQUIRE(json::Parse(body.data(), header_length, &header).empty());
  CHECK_EQ(header["id"].AsString(), "req-1");
  CHECK_EQ(header["inputs"].AsArray().size(), 2u);
  CHECK_EQ(
      header["inputs"].AsArray()[0]["parameters"]["binary_data_size"].AsUint(),
      64u);
  CHECK(memcmp(body.data() + header_length, data0, 64) == 0);
  CHECK(memcmp(body.data() + header_length + 64, data1, 64) == 0);
}

TEST_CASE("http: GenerateRequestBody JSON tensor data") {
  // json_input_data: tensors ride as JSON "data" arrays, the body IS
  // the header (no binary section), and binary_data_output=false is
  // stated so the server answers in JSON too.
  float data0[4] = {0.5f, -1.25f, 2.0f, 3.75f};
  int32_t data1[4] = {1, -2, 3, -4};
  auto in0 = MakeFp32Input("INPUT0", {4}, data0, 4);
  InferInput* raw1 = nullptr;
  InferInput::Create(&raw1, "INPUT1", {4}, "INT32");
  std::unique_ptr<InferInput> in1(raw1);
  in1->AppendRaw(reinterpret_cast<const uint8_t*>(data1), sizeof(data1));

  InferOptions options("simple");
  options.json_input_data = true;
  options.binary_data_output = false;

  std::vector<char> body;
  size_t header_length = 0;
  REQUIRE_OK(InferenceServerHttpClient::GenerateRequestBody(
      &body, &header_length, options, {in0.get(), in1.get()}, {}));
  CHECK_EQ(body.size(), header_length);  // no binary section at all
  json::Value header;
  REQUIRE(json::Parse(body.data(), header_length, &header).empty());
  CHECK_EQ(header["parameters"]["binary_data_output"].AsBool(), false);
  const auto& inputs = header["inputs"].AsArray();
  REQUIRE(inputs.size() == 2u);
  CHECK(!inputs[0]["parameters"].Has("binary_data_size"));
  const auto& d0 = inputs[0]["data"].AsArray();
  REQUIRE(d0.size() == 4u);
  CHECK_EQ(d0[1].AsDouble(), -1.25);
  CHECK_EQ(d0[3].AsDouble(), 3.75);
  const auto& d1 = inputs[1]["data"].AsArray();
  REQUIRE(d1.size() == 4u);
  CHECK_EQ(d1[1].AsInt(), -2);
  CHECK_EQ(d1[3].AsInt(), -4);
}

TEST_CASE("http: GenerateRequestBody shm params") {
  InferInput* raw = nullptr;
  InferInput::Create(&raw, "INPUT0", {4}, "FP32");
  std::unique_ptr<InferInput> input(raw);
  input->SetSharedMemory("region0", 16, 4);

  InferOptions options("simple");
  options.sequence_id = 7;
  options.sequence_start = true;

  std::vector<char> body;
  size_t header_length = 0;
  REQUIRE_OK(InferenceServerHttpClient::GenerateRequestBody(
      &body, &header_length, options, {input.get()}, {}));
  CHECK_EQ(body.size(), header_length);  // no binary section
  json::Value header;
  REQUIRE(json::Parse(body.data(), header_length, &header).empty());
  const auto& p = header["inputs"].AsArray()[0]["parameters"];
  CHECK_EQ(p["shared_memory_region"].AsString(), "region0");
  CHECK_EQ(p["shared_memory_byte_size"].AsUint(), 16u);
  CHECK_EQ(p["shared_memory_offset"].AsUint(), 4u);
  CHECK_EQ(header["parameters"]["sequence_id"].AsUint(), 7u);
  CHECK(header["parameters"]["sequence_start"].AsBool());
}

TEST_CASE("http: ParseResponseBody binary and errors") {
  std::string json_part =
      "{\"model_name\":\"simple\",\"model_version\":\"1\",\"outputs\":["
      "{\"name\":\"OUTPUT0\",\"datatype\":\"FP32\",\"shape\":[2],"
      "\"parameters\":{\"binary_data_size\":8}}]}";
  float vals[2] = {1.5f, -2.0f};
  std::vector<char> body(json_part.begin(), json_part.end());
  body.insert(
      body.end(), reinterpret_cast<char*>(vals),
      reinterpret_cast<char*>(vals) + 8);

  InferResult* result = nullptr;
  REQUIRE_OK(InferenceServerHttpClient::ParseResponseBody(
      &result, std::move(body), json_part.size()));
  std::unique_ptr<InferResult> guard(result);
  REQUIRE_OK(result->RequestStatus());

  std::string name;
  REQUIRE_OK(result->ModelName(&name));
  CHECK_EQ(name, "simple");
  std::vector<int64_t> shape;
  REQUIRE_OK(result->Shape("OUTPUT0", &shape));
  REQUIRE(shape.size() == 1u);
  CHECK_EQ(shape[0], 2);
  const uint8_t* buf;
  size_t len;
  REQUIRE_OK(result->RawData("OUTPUT0", &buf, &len));
  CHECK_EQ(len, 8u);
  CHECK(memcmp(buf, vals, 8) == 0);
  CHECK(!result->RawData("NOPE", &buf, &len).IsOk());
}

TEST_CASE("http: integration against live server") {
  const char* url = getenv("TPUCLIENT_SERVER_HTTP");
  if (url == nullptr) {
    printf("       (skipped: TPUCLIENT_SERVER_HTTP not set)\n");
    return;
  }
  std::unique_ptr<InferenceServerHttpClient> client;
  REQUIRE_OK(InferenceServerHttpClient::Create(&client, url));

  bool live = false, ready = false;
  REQUIRE_OK(client->IsServerLive(&live));
  CHECK(live);
  REQUIRE_OK(client->IsServerReady(&ready));
  CHECK(ready);
  bool model_ready = false;
  REQUIRE_OK(client->IsModelReady(&model_ready, "simple"));
  CHECK(model_ready);
  bool missing_ready = true;
  client->IsModelReady(&missing_ready, "no_such_model");
  CHECK(!missing_ready);

  std::string metadata;
  REQUIRE_OK(client->ServerMetadata(&metadata));
  CHECK(metadata.find("extensions") != std::string::npos);
  REQUIRE_OK(client->ModelMetadata(&metadata, "simple"));
  CHECK(metadata.find("INPUT0") != std::string::npos);
  REQUIRE_OK(client->ModelConfig(&metadata, "simple"));
  std::string index;
  REQUIRE_OK(client->ModelRepositoryIndex(&index));
  CHECK(index.find("simple") != std::string::npos);

  // Inference: simple add/sub — INPUT0+INPUT1 -> OUTPUT0=sum,
  // OUTPUT1=diff (16-wide INT32, same contract as the reference
  // 'simple' model).
  int32_t data0[16], data1[16];
  for (int i = 0; i < 16; ++i) {
    data0[i] = i;
    data1[i] = 1;
  }
  InferInput* raw0 = nullptr;
  InferInput::Create(&raw0, "INPUT0", {16}, "INT32");
  std::unique_ptr<InferInput> in0(raw0);
  in0->AppendRaw(reinterpret_cast<const uint8_t*>(data0), sizeof(data0));
  InferInput* raw1 = nullptr;
  InferInput::Create(&raw1, "INPUT1", {16}, "INT32");
  std::unique_ptr<InferInput> in1(raw1);
  in1->AppendRaw(reinterpret_cast<const uint8_t*>(data1), sizeof(data1));
  InferOptions options("simple");
  InferResult* result = nullptr;
  REQUIRE_OK(client->Infer(&result, options, {in0.get(), in1.get()}));
  std::unique_ptr<InferResult> result_guard(result);
  REQUIRE_OK(result->RequestStatus());
  const uint8_t* buf;
  size_t len;
  REQUIRE_OK(result->RawData("OUTPUT0", &buf, &len));
  REQUIRE(len == 64u);
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    CHECK_EQ(sums[i], data0[i] + 1);
  }

  // JSON tensor mode round trip: inputs as "data" arrays, outputs
  // requested as JSON, RawData materializes the packed bytes.
  {
    InferOptions json_options("simple");
    json_options.json_input_data = true;
    json_options.binary_data_output = false;
    InferResult* json_result = nullptr;
    REQUIRE_OK(client->Infer(&json_result, json_options,
                             {in0.get(), in1.get()}));
    std::unique_ptr<InferResult> json_guard(json_result);
    REQUIRE_OK(json_result->RequestStatus());
    const uint8_t* jbuf;
    size_t jlen;
    REQUIRE_OK(json_result->RawData("OUTPUT0", &jbuf, &jlen));
    REQUIRE(jlen == 64u);
    const int32_t* jsums = reinterpret_cast<const int32_t*>(jbuf);
    for (int i = 0; i < 16; ++i) {
      CHECK_EQ(jsums[i], data0[i] + 1);
    }
  }

  // Async: issue 8 requests and wait for all callbacks.
  std::mutex mu;
  std::condition_variable cv;
  int outstanding = 8;
  int failures = 0;
  for (int r = 0; r < 8; ++r) {
    Error err = client->AsyncInfer(
        [&](InferResult* res) {
          std::unique_ptr<InferResult> g(res);
          std::lock_guard<std::mutex> lk(mu);
          if (!res->RequestStatus().IsOk()) ++failures;
          --outstanding;
          cv.notify_one();
        },
        options, {in0.get(), in1.get()});
    REQUIRE_OK(err);
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    REQUIRE(cv.wait_for(lk, std::chrono::seconds(60),
                        [&]() { return outstanding == 0; }));
  }
  CHECK_EQ(failures, 0);

  // Client-side stats accumulated.
  InferStat stat;
  REQUIRE_OK(client->ClientInferStat(&stat));
  CHECK(stat.completed_request_count >= 9);

  // Per-call compression: every request/response algorithm pairing
  // round-trips (parity: http_client.cc:2130-2247).
  for (CompressionType req_alg :
       {CompressionType::NONE, CompressionType::DEFLATE,
        CompressionType::GZIP}) {
    for (CompressionType resp_alg :
         {CompressionType::NONE, CompressionType::DEFLATE,
          CompressionType::GZIP}) {
      InferResult* zres = nullptr;
      REQUIRE_OK(client->Infer(&zres, options, {in0.get(), in1.get()}, {},
                               {}, {}, req_alg, resp_alg));
      std::unique_ptr<InferResult> zguard(zres);
      REQUIRE_OK(zres->RequestStatus());
      const uint8_t* zbuf;
      size_t zlen;
      REQUIRE_OK(zres->RawData("OUTPUT0", &zbuf, &zlen));
      REQUIRE(zlen == 64u);
      const int32_t* zsums = reinterpret_cast<const int32_t*>(zbuf);
      for (int i = 0; i < 16; ++i) CHECK_EQ(zsums[i], data0[i] + 1);
    }
  }

  // Error mapping: unknown model -> HTTP error with server message.
  InferOptions bad("no_such_model");
  InferResult* bad_result = nullptr;
  Error err = client->Infer(&bad_result, bad, {in0.get(), in1.get()});
  if (bad_result != nullptr) {
    CHECK(!bad_result->RequestStatus().IsOk());
    delete bad_result;
  } else {
    CHECK(!err.IsOk());
  }

  // Statistics endpoint.
  std::string stats;
  REQUIRE_OK(client->ModelInferenceStatistics(&stats, "simple"));
  CHECK(stats.find("inference_count") != std::string::npos);
}

MINITEST_MAIN
