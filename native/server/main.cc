// tpu_serverd — native gRPC front-end for the inference server core.
//
//   tpu_serverd --port 8001 --models simple,resnet50 [--workers 8]
//
// Terminates HTTP/2 + gRPC framing in C++ (native/server/h2_server)
// and dispatches to the embedded Python core (client_tpu.server.embed)
// — the full GRPCInferenceService + TpuArenaService surface at native
// transport speed. Prints "LISTENING <port>" on stdout once ready so
// harnesses can scrape the bound (possibly ephemeral) port.
#include <signal.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>

#include "h2_server.h"
#include "http1_server.h"
#include "py_core.h"

namespace {

std::atomic<bool> g_stop{false};

void OnSignal(int) { g_stop.store(true); }

}  // namespace

int main(int argc, char** argv) {
  std::string host = "127.0.0.1";
  int port = 8001;
  int http_port = -1;  // -1 = disabled; 0 = ephemeral
  // Dispatch threads bound server-side in-flight concurrency, which
  // feeds the dynamic batcher: fewer workers than the offered client
  // concurrency starves batch fusion (bert c64 measured 117 vs 700
  // infer/s at 8 vs 96 workers). Threads mostly block on the GIL or
  // batcher events, so a large pool is cheap — default generously
  // and size --workers >= expected client concurrency.
  int workers = 64;
  std::string models = "simple";
  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    auto next = [&]() -> const char* {
      return (i + 1 < argc) ? argv[++i] : "";
    };
    if (arg == "--port" || arg == "-p") {
      port = atoi(next());
    } else if (arg == "--http-port") {
      http_port = atoi(next());
    } else if (arg == "--host") {
      host = next();
    } else if (arg == "--models" || arg == "-m") {
      models = next();
    } else if (arg == "--workers") {
      workers = atoi(next());
    } else if (arg == "--help" || arg == "-h") {
      printf(
          "usage: tpu_serverd [--host H] [--port P] [--http-port P] "
          "[--models a,b] [--workers N]\n");
      return 0;
    } else {
      fprintf(stderr, "unknown argument: %s\n", arg.c_str());
      return 2;
    }
  }

  tpuclient::server::PyCoreHandler handler;
  fprintf(stderr, "initializing core (models=%s)...\n", models.c_str());
  std::string err = handler.Init(models);
  if (!err.empty()) {
    fprintf(stderr, "core init failed: %s\n", err.c_str());
    return 1;
  }

  tpuclient::server::H2Server server(&handler, workers);
  err = server.Bind(host, port);
  if (!err.empty()) {
    fprintf(stderr, "listen failed: %s\n", err.c_str());
    return 1;
  }
  // Post-bind, pre-serve: the first accepted connection must already
  // see the published arena route in any handle it mints (early
  // connections queue in the kernel backlog until Serve()). The embed
  // side applies the same routing rules as the Python front-end: a
  // bind-any host is not a route, CLIENT_TPU_ARENA_URL overrides.
  err = handler.SetArenaPublicUrl(
      host + ":" + std::to_string(server.bound_port()));
  if (!err.empty()) {
    fprintf(stderr, "arena route publish failed (cross-host "
            "redemption of local handles disabled): %s\n", err.c_str());
  }
  server.Serve();
  std::unique_ptr<tpuclient::server::Http1Server> http_server;
  if (http_port >= 0) {
    http_server.reset(new tpuclient::server::Http1Server(&handler));
    err = http_server->Listen(host, http_port);
    if (!err.empty()) {
      fprintf(stderr, "http listen failed: %s\n", err.c_str());
      return 1;
    }
  }
  printf("LISTENING %d\n", server.bound_port());
  if (http_server != nullptr) {
    printf("LISTENING-HTTP %d\n", http_server->bound_port());
  }
  fflush(stdout);

  signal(SIGINT, OnSignal);
  signal(SIGTERM, OnSignal);
  while (!g_stop.load()) {
    usleep(100 * 1000);
  }
  fprintf(stderr, "shutting down\n");
  server.Shutdown();
  return 0;
}
