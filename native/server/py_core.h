// GrpcHandler implementation that embeds CPython and dispatches every
// RPC to client_tpu.server.embed.grpc_call / grpc_stream_call — the
// server-side twin of the perf harness's in-process backend
// (native/perf/inprocess_backend.cc), which embeds the same module
// from the client direction.
#pragma once

#include <string>

#include "h2_server.h"
#include "http1_server.h"

namespace tpuclient {
namespace server {

class PyCoreHandler : public GrpcHandler, public HttpHandler {
 public:
  // Initializes the interpreter and builds the server core, warming
  // `models_csv` (comma-separated). Returns "" on success. Must be
  // called once before the H2Server starts dispatching.
  std::string Init(const std::string& models_csv);

  // Publishes the bound address into arena handles (embed.
  // set_arena_public_url) so they are redeemable cross-host via the
  // DCN pull path. Call after Listen(), before serving. Returns "" on
  // success.
  std::string SetArenaPublicUrl(const std::string& url);

  int MethodKind(const std::string& path) override;
  GrpcReply Call(const std::string& path,
                 const std::string& message) override;
  GrpcReply StreamCall(const std::string& path,
                       const std::string& message,
                       const StreamEmit& emit) override;
  HttpReply HttpCall(const std::string& method, const std::string& path,
                     const std::string& headers_json,
                     const std::string& body) override;

 private:
  struct Impl;
  Impl* impl_ = nullptr;  // leaked on purpose: lives for the process
};

}  // namespace server
}  // namespace tpuclient
