#include "h2_server.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>

#include "../library/grpc_transport.h"
#include "../library/h2/hpack.h"

namespace tpuclient {
namespace server {

namespace {

constexpr uint8_t kFrameData = 0x0;
constexpr uint8_t kFrameHeaders = 0x1;
constexpr uint8_t kFrameRstStream = 0x3;
constexpr uint8_t kFrameSettings = 0x4;
constexpr uint8_t kFramePing = 0x6;
constexpr uint8_t kFrameGoaway = 0x7;
constexpr uint8_t kFrameWindowUpdate = 0x8;
constexpr uint8_t kFrameContinuation = 0x9;

constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;

constexpr uint16_t kSettingsInitialWindowSize = 0x4;
constexpr uint16_t kSettingsMaxFrameSize = 0x5;

// Same receive-side policy as the client transport
// (native/library/h2/h2_connection.cc): advertise big windows and
// re-credit every DATA frame immediately, so tensor uploads from
// clients never stall on flow control.
constexpr int64_t kOurInitialWindow = 1 << 24;  // 16 MB
constexpr size_t kOurMaxFrameSize = 1 << 20;    // 1 MB

const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t kPrefaceLen = sizeof(kPreface) - 1;

void PutU32(char* p, uint32_t v) {
  p[0] = static_cast<char>(v >> 24);
  p[1] = static_cast<char>(v >> 16);
  p[2] = static_cast<char>(v >> 8);
  p[3] = static_cast<char>(v);
}

uint32_t GetU32(const char* p) {
  const uint8_t* u = reinterpret_cast<const uint8_t*>(p);
  return (static_cast<uint32_t>(u[0]) << 24) |
         (static_cast<uint32_t>(u[1]) << 16) |
         (static_cast<uint32_t>(u[2]) << 8) | u[3];
}

// Serializes the 9-byte HTTP/2 frame header (RFC 9113 §4.1).
void BuildFrameHeader(char* out, uint8_t type, uint8_t flags,
                      int32_t stream_id, size_t len) {
  out[0] = static_cast<char>(len >> 16);
  out[1] = static_cast<char>(len >> 8);
  out[2] = static_cast<char>(len);
  out[3] = static_cast<char>(type);
  out[4] = static_cast<char>(flags);
  PutU32(out + 5, static_cast<uint32_t>(stream_id));
}

// grpc-message trailer values are percent-encoded (gRPC HTTP/2 spec);
// encode anything outside the printable-ASCII safe set.
std::string PercentEncode(const std::string& in) {
  static const char* hex = "0123456789ABCDEF";
  std::string out;
  out.reserve(in.size());
  for (unsigned char c : in) {
    if (c >= 0x20 && c <= 0x7e && c != '%') {
      out.push_back(static_cast<char>(c));
    } else {
      out.push_back('%');
      out.push_back(hex[c >> 4]);
      out.push_back(hex[c & 0xf]);
    }
  }
  return out;
}

class WorkPool {
 public:
  explicit WorkPool(int workers) {
    for (int i = 0; i < workers; ++i) {
      threads_.emplace_back([this] { Loop(); });
    }
  }

  ~WorkPool() { Stop(); }

  void Submit(std::function<void()> job) {
    {
      std::lock_guard<std::mutex> lk(mutex_);
      if (stopped_) return;
      queue_.push_back(std::move(job));
    }
    cv_.notify_one();
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mutex_);
      if (stopped_) return;
      stopped_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
    threads_.clear();
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lk(mutex_);
        cv_.wait(lk, [this] { return stopped_ || !queue_.empty(); });
        if (queue_.empty()) return;  // stopped
        job = std::move(queue_.front());
        queue_.pop_front();
      }
      job();
    }
  }

  std::mutex mutex_;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_;
  std::vector<std::thread> threads_;
  bool stopped_ = false;
};

}  // namespace

//==============================================================================
// Connection

class Conn : public std::enable_shared_from_this<Conn> {
 public:
  Conn(int fd, GrpcHandler* handler, WorkPool* pool)
      : fd_(fd), handler_(handler), pool_(pool) {}

  ~Conn() {
    if (fd_ >= 0) ::close(fd_);
  }

  void Start() { reader_ = std::thread(&Conn::ReaderLoop, this); }

  void ForceClose() {
    dead_.store(true);
    ::shutdown(fd_, SHUT_RDWR);
    cv_.notify_all();
  }

  void Join() {
    if (reader_.joinable()) reader_.join();
  }

  bool finished() const { return finished_.load(); }

 private:
  struct Stream {
    std::string path;
    int kind = 0;  // 1 unary, 2 bidi stream
    GrpcMessageReader reader;
    std::deque<std::string> pending;  // complete request messages
    bool processing = false;
    bool end_stream_received = false;
    bool response_headers_sent = false;
    bool closed = false;
    bool got_any_message = false;
    int64_t send_window = 65535;
    // HEADERS/CONTINUATION accumulation.
    std::string header_block;
    bool in_header_block = false;
    bool header_block_end_stream = false;
  };

  //----------------------------------------------------------------
  // Write side (any thread; write_mutex_ serializes the socket).

  std::string SendAll(const char* data, size_t len) {
    size_t sent = 0;
    while (sent < len) {
      ssize_t n = ::send(fd_, data + sent, len - sent, MSG_NOSIGNAL);
      if (n > 0) {
        sent += static_cast<size_t>(n);
        continue;
      }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK ||
                    errno == EINTR)) {
        struct pollfd pfd = {fd_, POLLOUT, 0};
        poll(&pfd, 1, 50);
        continue;
      }
      return std::string("send failed: ") + strerror(errno);
    }
    return "";
  }

  std::string WriteFrame(uint8_t type, uint8_t flags, int32_t stream_id,
                         const char* payload, size_t len) {
    char header[9];
    BuildFrameHeader(header, type, flags, stream_id, len);
    std::string err = SendAll(header, 9);
    if (!err.empty() || len == 0) return err;
    return SendAll(payload, len);
  }

  void SendResponseHeaders(int32_t stream_id) {
    h2::HeaderList headers = {{":status", "200"},
                              {"content-type", "application/grpc"}};
    std::string block = encoder_.Encode(headers);
    std::lock_guard<std::mutex> wl(write_mutex_);
    WriteFrame(kFrameHeaders, kFlagEndHeaders, stream_id, block.data(),
               block.size());
  }

  void SendTrailers(int32_t stream_id, int status, const std::string& message,
                    bool headers_sent) {
    h2::HeaderList trailers;
    if (!headers_sent) {
      // Trailers-only response (gRPC over HTTP/2 spec).
      trailers.push_back({":status", "200"});
      trailers.push_back({"content-type", "application/grpc"});
    }
    trailers.push_back({"grpc-status", std::to_string(status)});
    if (!message.empty()) {
      trailers.push_back({"grpc-message", PercentEncode(message)});
    }
    std::string block = encoder_.Encode(trailers);
    std::lock_guard<std::mutex> wl(write_mutex_);
    WriteFrame(kFrameHeaders, kFlagEndHeaders | kFlagEndStream, stream_id,
               block.data(), block.size());
  }

  // Fast path for unary replies: response HEADERS + DATA + trailers
  // coalesce into ONE buffered write under one lock acquisition
  // (three separate frame writes cost 3x the syscalls and lock
  // traffic — measurable at the simple-model request rates the bench
  // runs). Returns false when the flow-control windows can't take
  // the whole message at once; the caller then uses the chunked path.
  bool SendUnaryResponse(int32_t stream_id, const std::string& payload) {
    std::string framed = FrameGrpcMessage(payload);
    {
      std::lock_guard<std::mutex> lock(mutex_);
      auto it = streams_.find(stream_id);
      if (it == streams_.end() || it->second->closed) return true;
      auto& stream = it->second;
      if (framed.size() > peer_max_frame_size_ ||
          static_cast<int64_t>(framed.size()) > peer_conn_window_ ||
          static_cast<int64_t>(framed.size()) > stream->send_window) {
        return false;
      }
      peer_conn_window_ -= framed.size();
      stream->send_window -= framed.size();
      stream->response_headers_sent = true;
    }
    std::string buffer;
    auto append_frame = [&buffer](uint8_t type, uint8_t flags,
                                  int32_t sid, const std::string& body) {
      char header[9];
      BuildFrameHeader(header, type, flags, sid, body.size());
      buffer.append(header, 9);
      buffer.append(body);
    };
    append_frame(kFrameHeaders, kFlagEndHeaders, stream_id,
                 encoder_.Encode({{":status", "200"},
                                  {"content-type", "application/grpc"}}));
    append_frame(kFrameData, 0, stream_id, framed);
    append_frame(kFrameHeaders, kFlagEndHeaders | kFlagEndStream,
                 stream_id, encoder_.Encode({{"grpc-status", "0"}}));
    std::lock_guard<std::mutex> wl(write_mutex_);
    SendAll(buffer.data(), buffer.size());
    return true;
  }

  // Frames `payload` as one gRPC message and sends it as DATA,
  // honouring the peer's flow-control windows.
  std::string SendMessage(int32_t stream_id, const std::string& payload) {
    std::string framed = FrameGrpcMessage(payload);
    size_t pos = 0;
    while (pos < framed.size()) {
      size_t chunk;
      {
        std::unique_lock<std::mutex> lock(mutex_);
        auto it = streams_.find(stream_id);
        if (it == streams_.end() || it->second->closed) {
          return "stream closed";
        }
        auto stream = it->second;
        cv_.wait(lock, [&] {
          return dead_.load() || stream->closed ||
                 (peer_conn_window_ > 0 && stream->send_window > 0);
        });
        if (dead_.load()) return "connection closed";
        if (stream->closed) return "stream closed";
        chunk = std::min<size_t>(
            {framed.size() - pos, peer_max_frame_size_,
             static_cast<size_t>(peer_conn_window_),
             static_cast<size_t>(stream->send_window)});
        peer_conn_window_ -= chunk;
        stream->send_window -= chunk;
      }
      std::lock_guard<std::mutex> wl(write_mutex_);
      std::string e = WriteFrame(kFrameData, 0, stream_id,
                                 framed.data() + pos, chunk);
      if (!e.empty()) return e;
      pos += chunk;
    }
    return "";
  }

  //----------------------------------------------------------------
  // Reader side (connection's own thread).

  bool ReadExact(char* buf, size_t len) {
    size_t got = 0;
    while (got < len) {
      ssize_t n = ::recv(fd_, buf + got, len - got, 0);
      if (n > 0) {
        got += static_cast<size_t>(n);
        continue;
      }
      if (n < 0 && errno == EINTR) continue;
      return false;
    }
    return true;
  }

  void ReaderLoop() {
    // Server SETTINGS + a big connection window, then the client
    // preface. RFC 9113 §3.4: the server sends its SETTINGS first.
    {
      std::string settings;
      auto add_setting = [&settings](uint16_t id, uint32_t value) {
        char buf[6];
        buf[0] = static_cast<char>(id >> 8);
        buf[1] = static_cast<char>(id);
        PutU32(buf + 2, value);
        settings.append(buf, 6);
      };
      add_setting(kSettingsInitialWindowSize, kOurInitialWindow);
      add_setting(kSettingsMaxFrameSize, kOurMaxFrameSize);
      std::lock_guard<std::mutex> wl(write_mutex_);
      std::string e =
          WriteFrame(kFrameSettings, 0, 0, settings.data(), settings.size());
      if (e.empty()) {
        char wu[4];
        PutU32(wu, (1u << 30) - 65535);
        e = WriteFrame(kFrameWindowUpdate, 0, 0, wu, 4);
      }
      if (!e.empty()) {
        Fail("handshake write failed");
        return;
      }
    }
    char preface[kPrefaceLen];
    if (!ReadExact(preface, kPrefaceLen) ||
        memcmp(preface, kPreface, kPrefaceLen) != 0) {
      Fail("bad client preface");
      return;
    }
    char header[9];
    std::string payload;
    while (!dead_.load()) {
      if (!ReadExact(header, 9)) {
        Fail("connection reset");
        return;
      }
      size_t len =
          (static_cast<size_t>(static_cast<uint8_t>(header[0])) << 16) |
          (static_cast<size_t>(static_cast<uint8_t>(header[1])) << 8) |
          static_cast<uint8_t>(header[2]);
      uint8_t type = static_cast<uint8_t>(header[3]);
      uint8_t flags = static_cast<uint8_t>(header[4]);
      int32_t stream_id =
          static_cast<int32_t>(GetU32(header + 5) & 0x7fffffffu);
      if (len > kOurMaxFrameSize + 1024) {
        Fail("oversized frame");
        return;
      }
      payload.resize(len);
      if (len > 0 && !ReadExact(&payload[0], len)) {
        Fail("connection reset mid-frame");
        return;
      }
      HandleFrame(type, flags, stream_id, payload);
    }
    finished_.store(true);
  }

  void HandleFrame(uint8_t type, uint8_t flags, int32_t stream_id,
                   const std::string& payload) {
    switch (type) {
      case kFrameData:
        HandleData(flags, stream_id, payload);
        break;
      case kFrameHeaders: {
        size_t off = 0;
        size_t len = payload.size();
        if (flags & kFlagPadded) {
          if (len < 1) break;
          uint8_t pad = static_cast<uint8_t>(payload[0]);
          off += 1;
          if (len < off + pad) break;
          len -= pad;
        }
        if (flags & kFlagPriority) {
          if (len < off + 5) break;
          off += 5;
        }
        std::shared_ptr<Stream> stream;
        {
          std::lock_guard<std::mutex> lock(mutex_);
          auto it = streams_.find(stream_id);
          if (it != streams_.end()) {
            // Second header block on an open stream = client
            // trailers; feed HPACK to keep decoder state in sync but
            // leave the in-flight stream untouched.
            stream = it->second;
          } else {
            stream = std::make_shared<Stream>();
            stream->send_window = peer_initial_window_;
            streams_[stream_id] = stream;
          }
        }
        stream->header_block.assign(payload, off, len - off);
        stream->header_block_end_stream = (flags & kFlagEndStream) != 0;
        stream->in_header_block = true;
        if (flags & kFlagEndHeaders) {
          HandleHeaderBlockDone(stream_id, stream);
        }
        break;
      }
      case kFrameContinuation: {
        std::shared_ptr<Stream> stream = FindStream(stream_id);
        if (!stream || !stream->in_header_block) break;
        stream->header_block.append(payload);
        if (flags & kFlagEndHeaders) {
          HandleHeaderBlockDone(stream_id, stream);
        }
        break;
      }
      case kFrameSettings: {
        if (flags & kFlagAck) break;
        {
          std::lock_guard<std::mutex> lock(mutex_);
          for (size_t i = 0; i + 6 <= payload.size(); i += 6) {
            uint16_t id =
                (static_cast<uint16_t>(static_cast<uint8_t>(payload[i]))
                 << 8) |
                static_cast<uint8_t>(payload[i + 1]);
            uint32_t value = GetU32(payload.data() + i + 2);
            switch (id) {
              case kSettingsInitialWindowSize: {
                int64_t delta =
                    static_cast<int64_t>(value) - peer_initial_window_;
                peer_initial_window_ = value;
                for (auto& kv : streams_) kv.second->send_window += delta;
                break;
              }
              case kSettingsMaxFrameSize:
                // RFC 9113 §6.5.2: valid range [2^14, 2^24-1]; a
                // value below the floor would otherwise zero out
                // SendMessage's chunk computation and spin.
                if (value >= 16384 && value <= (1u << 24) - 1) {
                  peer_max_frame_size_ = value;
                }
                break;
              default:
                break;
            }
          }
        }
        cv_.notify_all();
        std::lock_guard<std::mutex> wl(write_mutex_);
        WriteFrame(kFrameSettings, kFlagAck, 0, nullptr, 0);
        break;
      }
      case kFramePing: {
        if (!(flags & kFlagAck) && payload.size() == 8) {
          std::lock_guard<std::mutex> wl(write_mutex_);
          WriteFrame(kFramePing, kFlagAck, 0, payload.data(), 8);
        }
        break;
      }
      case kFrameWindowUpdate: {
        if (payload.size() != 4) break;
        uint32_t increment = GetU32(payload.data()) & 0x7fffffffu;
        {
          std::lock_guard<std::mutex> lock(mutex_);
          if (stream_id == 0) {
            peer_conn_window_ += increment;
          } else {
            auto it = streams_.find(stream_id);
            if (it != streams_.end()) {
              it->second->send_window += increment;
            }
          }
        }
        cv_.notify_all();
        break;
      }
      case kFrameRstStream: {
        std::lock_guard<std::mutex> lock(mutex_);
        auto it = streams_.find(stream_id);
        if (it != streams_.end()) {
          it->second->closed = true;
          if (!it->second->processing) streams_.erase(it);
        }
        cv_.notify_all();
        break;
      }
      case kFrameGoaway:
        Fail("client GOAWAY");
        break;
      default:
        break;  // PRIORITY, PUSH_PROMISE (never valid from client), ...
    }
  }

  void HandleHeaderBlockDone(int32_t stream_id,
                             const std::shared_ptr<Stream>& stream) {
    stream->in_header_block = false;
    h2::HeaderList headers;
    std::string err = decoder_.Decode(
        reinterpret_cast<const uint8_t*>(stream->header_block.data()),
        stream->header_block.size(), &headers);
    stream->header_block.clear();
    if (!err.empty()) {
      Fail("HPACK error: " + err);
      return;
    }
    if (!stream->path.empty()) {
      // A second header block on an open request stream would be
      // client trailers; gRPC clients don't send them — ignore.
      return;
    }
    std::string encoding;
    for (const auto& kv : headers) {
      if (kv.first == ":path") stream->path = kv.second;
      if (kv.first == "grpc-encoding") encoding = kv.second;
    }
    if (!encoding.empty()) stream->reader.SetEncoding(encoding);
    stream->kind = handler_->MethodKind(stream->path);
    if (stream->kind == 0) {
      SendTrailers(stream_id, 12, "unknown method " + stream->path,
                   /*headers_sent=*/false);
      std::lock_guard<std::mutex> lock(mutex_);
      streams_.erase(stream_id);
      return;
    }
    if (stream->header_block_end_stream) {
      {
        std::lock_guard<std::mutex> lock(mutex_);
        stream->end_stream_received = true;
      }
      Schedule(stream_id);
    }
  }

  void HandleData(uint8_t flags, int32_t stream_id,
                  const std::string& payload) {
    std::shared_ptr<Stream> stream = FindStream(stream_id);
    size_t data_len = payload.size();
    const char* data = payload.data();
    if (flags & kFlagPadded) {
      if (payload.empty()) return;
      uint8_t pad = static_cast<uint8_t>(payload[0]);
      if (static_cast<size_t>(pad) + 1 > payload.size()) return;
      data += 1;
      data_len = payload.size() - 1 - pad;
    }
    bool stream_open = false;
    if (stream) {
      std::lock_guard<std::mutex> lock(mutex_);
      stream_open = !stream->closed;
    }
    if (stream_open && data_len > 0) {
      std::vector<std::string> messages;
      if (!stream->reader.Feed(reinterpret_cast<const uint8_t*>(data),
                               data_len, &messages)) {
        // RST, not gRPC trailers: a worker may be mid-response on
        // this stream, and a reader-thread trailers write could land
        // before/after its frames in the wrong order. RST_STREAM is
        // ordering-safe and maps to an error client-side.
        char code[4];
        PutU32(code, 0x1);  // PROTOCOL_ERROR
        {
          std::lock_guard<std::mutex> wl(write_mutex_);
          WriteFrame(kFrameRstStream, 0, stream_id, code, 4);
        }
        std::lock_guard<std::mutex> lock(mutex_);
        stream->closed = true;
        if (!stream->processing) streams_.erase(stream_id);
        return;
      }
      if (!messages.empty()) {
        std::lock_guard<std::mutex> lock(mutex_);
        for (auto& m : messages) {
          stream->pending.push_back(std::move(m));
        }
        stream->got_any_message = true;
      }
    }
    // Eagerly re-credit both windows (mirror of the client policy).
    if (!payload.empty()) {
      char wu[4];
      PutU32(wu, static_cast<uint32_t>(payload.size()));
      std::lock_guard<std::mutex> wl(write_mutex_);
      WriteFrame(kFrameWindowUpdate, 0, 0, wu, 4);
      if (!(flags & kFlagEndStream)) {
        WriteFrame(kFrameWindowUpdate, 0, stream_id, wu, 4);
      }
    }
    if (stream && (flags & kFlagEndStream)) {
      std::lock_guard<std::mutex> lock(mutex_);
      stream->end_stream_received = true;
    }
    if (stream) Schedule(stream_id);
  }

  std::shared_ptr<Stream> FindStream(int32_t stream_id) {
    std::lock_guard<std::mutex> lock(mutex_);
    auto it = streams_.find(stream_id);
    return it == streams_.end() ? nullptr : it->second;
  }

  //----------------------------------------------------------------
  // Dispatch (worker threads).

  // Enqueues a worker for the stream unless one is already running.
  void Schedule(int32_t stream_id) {
    {
      std::lock_guard<std::mutex> lock(mutex_);
      auto it = streams_.find(stream_id);
      if (it == streams_.end()) return;
      auto& s = it->second;
      if (s->processing || s->closed) return;
      if (s->pending.empty() && !s->end_stream_received) return;
      s->processing = true;
    }
    auto self = shared_from_this();
    pool_->Submit([self, stream_id] { self->Work(stream_id); });
  }

  // Drains one stream's pending messages in order; a stream is only
  // ever worked by one thread at a time, so per-stream dispatch order
  // matches arrival order while different streams run in parallel.
  void Work(int32_t stream_id) {
    for (;;) {
      std::shared_ptr<Stream> stream;
      std::string message;
      bool have = false;
      bool finish = false;
      bool got_any = false;
      {
        std::lock_guard<std::mutex> lock(mutex_);
        auto it = streams_.find(stream_id);
        if (it == streams_.end()) return;
        stream = it->second;
        if (stream->closed) {
          stream->processing = false;
          streams_.erase(it);
          return;
        }
        got_any = stream->got_any_message;
        if (!stream->pending.empty()) {
          message = std::move(stream->pending.front());
          stream->pending.pop_front();
          have = true;
        } else if (stream->end_stream_received) {
          finish = true;
        } else {
          stream->processing = false;
          return;
        }
      }
      if (have && stream->kind == 1) {
        GrpcReply reply = handler_->Call(stream->path, message);
        if (reply.status == 0 && !reply.responses.empty()) {
          if (!SendUnaryResponse(stream_id, reply.responses.front())) {
            // Flow-control window too small for one coalesced write:
            // fall back to the chunked path.
            SendResponseHeaders(stream_id);
            {
              std::lock_guard<std::mutex> lock(mutex_);
              stream->response_headers_sent = true;
            }
            SendMessage(stream_id, reply.responses.front());
            SendTrailers(stream_id, 0, "", /*headers_sent=*/true);
          }
        } else if (reply.status == 0) {
          SendTrailers(stream_id, 13, "handler produced no response",
                       /*headers_sent=*/false);
        } else {
          SendTrailers(stream_id, reply.status, reply.message,
                       /*headers_sent=*/false);
        }
        CloseStream(stream_id);
        return;
      }
      if (have) {  // streaming message
        // Each response hits the wire as the handler produces it, so
        // decoupled models stream incrementally through this
        // front-end (TTFT = first token, not full generation).
        auto emit = [this, stream_id, &stream](
                        const std::string& response) -> bool {
          bool need_headers;
          {
            std::lock_guard<std::mutex> lock(mutex_);
            if (stream->closed) return false;
            need_headers = !stream->response_headers_sent;
            stream->response_headers_sent = true;
          }
          if (need_headers) SendResponseHeaders(stream_id);
          return SendMessage(stream_id, response).empty();
        };
        GrpcReply reply = handler_->StreamCall(stream->path, message, emit);
        if (reply.status != 0) {
          bool headers_sent;
          {
            std::lock_guard<std::mutex> lock(mutex_);
            headers_sent = stream->response_headers_sent;
          }
          SendTrailers(stream_id, reply.status, reply.message, headers_sent);
          CloseStream(stream_id);
          return;
        }
        for (const auto& response : reply.responses) {
          if (!emit(response)) {
            CloseStream(stream_id);
            return;
          }
        }
        continue;  // more pending messages / wait for half-close
      }
      // finish: client half-closed and everything is dispatched.
      if (finish) {
        bool headers_sent;
        {
          std::lock_guard<std::mutex> lock(mutex_);
          headers_sent = stream->response_headers_sent;
        }
        if (stream->kind == 1 && !got_any) {
          SendTrailers(stream_id, 13, "request message missing",
                       headers_sent);
        } else {
          SendTrailers(stream_id, 0, "", headers_sent);
        }
        CloseStream(stream_id);
        return;
      }
    }
  }

  void CloseStream(int32_t stream_id) {
    std::lock_guard<std::mutex> lock(mutex_);
    auto it = streams_.find(stream_id);
    if (it != streams_.end()) {
      it->second->closed = true;
      it->second->processing = false;
      streams_.erase(it);
    }
    cv_.notify_all();
  }

  void Fail(const std::string&) {
    if (dead_.exchange(true)) return;
    {
      std::lock_guard<std::mutex> lock(mutex_);
      for (auto& kv : streams_) kv.second->closed = true;
    }
    cv_.notify_all();
    ::shutdown(fd_, SHUT_RDWR);
    finished_.store(true);
  }

  int fd_;
  GrpcHandler* handler_;
  WorkPool* pool_;
  std::thread reader_;
  std::atomic<bool> dead_{false};
  std::atomic<bool> finished_{false};

  std::mutex write_mutex_;
  h2::HpackEncoder encoder_;
  h2::HpackDecoder decoder_;

  std::mutex mutex_;  // guards everything below
  std::condition_variable cv_;
  std::map<int32_t, std::shared_ptr<Stream>> streams_;
  int64_t peer_initial_window_ = 65535;
  int64_t peer_conn_window_ = 65535;
  size_t peer_max_frame_size_ = 16384;
};

//==============================================================================
// H2Server

struct H2Server::Impl {
  explicit Impl(int workers) : pool(workers) {}
  WorkPool pool;
  std::mutex mutex;
  std::vector<std::shared_ptr<Conn>> conns;
};

H2Server::H2Server(GrpcHandler* handler, int workers)
    : handler_(handler), workers_(workers),
      impl_(new Impl(workers)) {}

H2Server::~H2Server() { Shutdown(); }

std::string H2Server::Listen(const std::string& host, int port) {
  std::string err = Bind(host, port);
  if (!err.empty()) return err;
  Serve();
  return "";
}

std::string H2Server::Bind(const std::string& host, int port) {
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return strerror(errno);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(lfd);
    return "bad listen host " + host;
  }
  if (bind(lfd, reinterpret_cast<struct sockaddr*>(&addr),
           sizeof(addr)) != 0) {
    std::string err = std::string("bind failed: ") + strerror(errno);
    ::close(lfd);
    return err;
  }
  if (listen(lfd, 128) != 0) {
    std::string err = std::string("listen failed: ") + strerror(errno);
    ::close(lfd);
    return err;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<struct sockaddr*>(&addr), &alen);
  bound_port_ = ntohs(addr.sin_port);
  listen_fd_.store(lfd);
  return "";
}

void H2Server::Serve() {
  accept_thread_ = std::thread(&H2Server::AcceptLoop, this);
}

void H2Server::AcceptLoop() {
  const int lfd = listen_fd_.load();
  while (!shutting_down_.load()) {
    int fd = ::accept(lfd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      return;  // listen socket closed
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto conn = std::make_shared<Conn>(fd, handler_, &impl_->pool);
    {
      std::lock_guard<std::mutex> lk(impl_->mutex);
      // Opportunistically reap connections whose reader has exited.
      auto& conns = impl_->conns;
      for (size_t i = 0; i < conns.size();) {
        if (conns[i]->finished()) {
          conns[i]->Join();
          conns.erase(conns.begin() + i);
        } else {
          ++i;
        }
      }
      conns.push_back(conn);
    }
    conn->Start();
  }
}

void H2Server::Shutdown() {
  if (shutting_down_.exchange(true)) return;
  // shutdown() wakes the blocked accept; the fd is closed only after
  // the accept thread has exited so it can't be reused under it.
  int lfd = listen_fd_.exchange(-1);
  if (lfd >= 0) ::shutdown(lfd, SHUT_RDWR);
  if (accept_thread_.joinable()) accept_thread_.join();
  if (lfd >= 0) ::close(lfd);
  std::vector<std::shared_ptr<Conn>> conns;
  {
    std::lock_guard<std::mutex> lk(impl_->mutex);
    conns.swap(impl_->conns);
  }
  for (auto& conn : conns) conn->ForceClose();
  // Workers may still hold references to conns; stop them before the
  // connections are destroyed.
  impl_->pool.Stop();
  for (auto& conn : conns) conn->Join();
}

}  // namespace server
}  // namespace tpuclient
