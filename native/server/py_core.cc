#include "py_core.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <unistd.h>

#include <cstdlib>
#include <mutex>
#include <unordered_map>

namespace tpuclient {
namespace server {

namespace {

std::string RepoRootGuess() {
  const char* env = std::getenv("TPUCLIENT_REPO_ROOT");
  if (env != nullptr && env[0] != '\0') return env;
  // Binary lives at <root>/native/build/tpu_serverd.
  char buf[4096];
  ssize_t n = readlink("/proc/self/exe", buf, sizeof(buf) - 1);
  if (n > 0) {
    std::string path(buf, n);
    size_t cut = path.rfind("/native/build/");
    if (cut != std::string::npos) return path.substr(0, cut);
  }
  return ".";
}

// Caller holds the GIL. Formats the pending exception; embed.GrpcAbort
// stringifies as "[GRPC:<code>] <details>".
std::string FetchPyError(const char* what) {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  std::string message = std::string(what) + " failed";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* text = PyUnicode_AsUTF8(s);
      if (text != nullptr) message = text;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
  return message;
}

// Maps an exception message to (grpc-status, details): "[GRPC:n] ..."
// comes from embed.GrpcAbort; anything else is INTERNAL (13).
void ParseAbort(const std::string& text, GrpcReply* reply) {
  if (text.rfind("[GRPC:", 0) == 0) {
    size_t close = text.find(']');
    if (close != std::string::npos) {
      reply->status = atoi(text.c_str() + 6);
      size_t start = close + 1;
      while (start < text.size() && text[start] == ' ') ++start;
      reply->message = text.substr(start);
      if (reply->status == 0) reply->status = 13;
      return;
    }
  }
  reply->status = 13;
  reply->message = text;
}

}  // namespace

struct PyCoreHandler::Impl {
  PyObject* module = nullptr;
  std::mutex kind_mutex;
  std::unordered_map<std::string, int> kind_cache;
};

std::string PyCoreHandler::Init(const std::string& models_csv) {
  impl_ = new Impl();
  std::string repo = RepoRootGuess();
  std::string pythonpath = repo;
  // The embedded interpreter boots from the base install; graft the
  // active venv's site-packages (jax & friends live there).
  const char* venv = std::getenv("VIRTUAL_ENV");
  std::string site = std::string(venv != nullptr ? venv : "/opt/venv") +
                     "/lib/python" + std::to_string(PY_MAJOR_VERSION) + "." +
                     std::to_string(PY_MINOR_VERSION) + "/site-packages";
  if (access(site.c_str(), F_OK) == 0) pythonpath += ":" + site;
  const char* existing = std::getenv("PYTHONPATH");
  if (existing != nullptr && existing[0] != '\0') {
    pythonpath += ":" + std::string(existing);
  }
  setenv("PYTHONPATH", pythonpath.c_str(), 1);

  Py_InitializeEx(0);
  impl_->module = PyImport_ImportModule("client_tpu.server.embed");
  if (impl_->module == nullptr) {
    std::string err = FetchPyError("import client_tpu.server.embed");
    PyEval_SaveThread();
    return err;
  }
  PyObject* r = PyObject_CallMethod(
      impl_->module, "init", "s", models_csv.c_str());
  std::string err;
  if (r == nullptr) err = FetchPyError("embed.init");
  Py_XDECREF(r);
  // Release the GIL; transport worker threads take it per call.
  PyEval_SaveThread();
  return err;
}

std::string PyCoreHandler::SetArenaPublicUrl(const std::string& url) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = PyObject_CallMethod(
      impl_->module, "set_arena_public_url", "s", url.c_str());
  std::string err;
  if (r == nullptr) err = FetchPyError("embed.set_arena_public_url");
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return err;
}

int PyCoreHandler::MethodKind(const std::string& path) {
  {
    std::lock_guard<std::mutex> lk(impl_->kind_mutex);
    auto it = impl_->kind_cache.find(path);
    if (it != impl_->kind_cache.end()) return it->second;
  }
  int kind = 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = PyObject_CallMethod(
      impl_->module, "grpc_method_kind", "s", path.c_str());
  if (r != nullptr) {
    const char* text = PyUnicode_AsUTF8(r);
    if (text != nullptr) {
      if (strcmp(text, "unary") == 0) kind = 1;
      if (strcmp(text, "stream") == 0) kind = 2;
    }
    Py_DECREF(r);
  } else {
    PyErr_Clear();
  }
  PyGILState_Release(gil);
  std::lock_guard<std::mutex> lk(impl_->kind_mutex);
  impl_->kind_cache[path] = kind;
  return kind;
}

GrpcReply PyCoreHandler::Call(const std::string& path,
                              const std::string& message) {
  GrpcReply reply;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = PyObject_CallMethod(
      impl_->module, "grpc_call", "sy#", path.c_str(), message.data(),
      (Py_ssize_t)message.size());
  if (r == nullptr) {
    ParseAbort(FetchPyError("grpc_call"), &reply);
  } else {
    char* data = nullptr;
    Py_ssize_t size = 0;
    if (PyBytes_AsStringAndSize(r, &data, &size) != 0) {
      ParseAbort(FetchPyError("grpc_call result"), &reply);
    } else {
      reply.responses.emplace_back(data, (size_t)size);
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return reply;
}

namespace {

// Python-callable bridge handed to embed.grpc_stream_call_emit: each
// call forwards one serialized response to the transport's emit
// closure with the GIL released (the socket write may block on h2
// flow control; holding the GIL there would stall every other call).
// The StreamEmit the capsule refers to lives on StreamCall's stack, so
// a handler that retains the emit callable past the call (e.g. a
// future async path) must get a safe no-op (False = stream gone),
// never a dangling dereference. The capsule therefore owns a heap
// holder whose mutex spans pointer-fetch AND invoke: expiry (below)
// blocks until any in-flight emit drains, closing the window where a
// fetched pointer outlives the frame across a GIL release.
struct EmitHolder {
  std::mutex mu;
  const GrpcHandler::StreamEmit* emit = nullptr;  // null once expired
};

extern "C" void DestroyEmitHolder(PyObject* capsule) {
  delete static_cast<EmitHolder*>(
      PyCapsule_GetPointer(capsule, "tpuclient.stream_emit"));
}

extern "C" PyObject* EmitTrampoline(PyObject* self, PyObject* args) {
  auto* holder = static_cast<EmitHolder*>(
      PyCapsule_GetPointer(self, "tpuclient.stream_emit"));
  const char* data = nullptr;
  Py_ssize_t size = 0;
  if (holder == nullptr || !PyArg_ParseTuple(args, "y#", &data, &size)) {
    return nullptr;
  }
  std::string payload(data, (size_t)size);
  bool ok = false;
  Py_BEGIN_ALLOW_THREADS
  {
    // mu is released before the GIL is re-acquired, so expiry blocking
    // on mu while holding the GIL cannot deadlock against this thread.
    std::lock_guard<std::mutex> lock(holder->mu);
    ok = holder->emit != nullptr && (*holder->emit)(payload);
  }
  Py_END_ALLOW_THREADS
  return PyBool_FromLong(ok ? 1 : 0);
}

PyMethodDef kEmitDef = {"emit", EmitTrampoline, METH_VARARGS, nullptr};

}  // namespace

GrpcReply PyCoreHandler::StreamCall(const std::string& path,
                                    const std::string& message,
                                    const StreamEmit& emit) {
  GrpcReply reply;
  PyGILState_STATE gil = PyGILState_Ensure();
  auto* holder = new EmitHolder;
  holder->emit = &emit;
  PyObject* capsule =
      PyCapsule_New(holder, "tpuclient.stream_emit", DestroyEmitHolder);
  if (capsule == nullptr) delete holder;
  PyObject* emit_fn =
      capsule != nullptr ? PyCFunction_New(&kEmitDef, capsule) : nullptr;
  if (emit_fn == nullptr) {
    ParseAbort(FetchPyError("stream emit bridge"), &reply);
    Py_XDECREF(capsule);
    PyGILState_Release(gil);
    return reply;
  }
  PyObject* r = PyObject_CallMethod(
      impl_->module, "grpc_stream_call_emit", "sy#O", path.c_str(),
      message.data(), (Py_ssize_t)message.size(), emit_fn);
  if (r == nullptr) {
    ParseAbort(FetchPyError("grpc_stream_call_emit"), &reply);
  } else {
    Py_DECREF(r);
  }
  // Expire before the frame's StreamEmit dies: blocks on mu until any
  // in-flight emit drains (its lock is released GIL-free, so waiting
  // here with the GIL held cannot deadlock), then later calls no-op.
  {
    std::lock_guard<std::mutex> lock(holder->mu);
    holder->emit = nullptr;
  }
  Py_DECREF(emit_fn);
  Py_DECREF(capsule);
  PyGILState_Release(gil);
  return reply;
}

HttpReply PyCoreHandler::HttpCall(const std::string& method,
                                  const std::string& path,
                                  const std::string& headers_json,
                                  const std::string& body) {
  HttpReply reply;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = PyObject_CallMethod(
      impl_->module, "http_call", "sssy#", method.c_str(), path.c_str(),
      headers_json.c_str(), body.data(), (Py_ssize_t)body.size());
  if (r == nullptr) {
    reply.status = 500;
    reply.body = "{\"error\": \"" +
                 JsonEscapeLatin1(FetchPyError("http_call")) + "\"}";
    reply.headers_json = "{\"Content-Type\": \"application/json\"}";
  } else {
    // (status:int, headers_json:str, body:bytes)
    bool ok = false;
    PyObject* status = PyTuple_GetItem(r, 0);
    PyObject* headers = PyTuple_GetItem(r, 1);
    PyObject* payload = PyTuple_GetItem(r, 2);
    if (status != nullptr && headers != nullptr && payload != nullptr) {
      long code = PyLong_AsLong(status);
      const char* text = PyUnicode_AsUTF8(headers);
      char* data = nullptr;
      Py_ssize_t size = 0;
      if (code != -1 || PyErr_Occurred() == nullptr) {
        if (text != nullptr &&
            PyBytes_AsStringAndSize(payload, &data, &size) == 0) {
          reply.status = (int)code;
          reply.headers_json = text;
          reply.body.assign(data, (size_t)size);
          ok = true;
        }
      }
    }
    if (!ok) {
      // A pending conversion error must never leak past this call
      // (running the next C-API call with an exception set is UB).
      PyErr_Clear();
      reply.status = 500;
      reply.body = "{\"error\": \"malformed http_call result\"}";
      reply.headers_json = "{\"Content-Type\": \"application/json\"}";
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return reply;
}

}  // namespace server
}  // namespace tpuclient
