#include "http1_server.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cstring>
#include <map>
#include <mutex>

namespace tpuclient {
namespace server {

namespace {

constexpr size_t kMaxHeaderBytes = 64 * 1024;
constexpr size_t kMaxBodyBytes = 1ull << 31;  // 2 GB, same as gRPC side

const char* ReasonPhrase(int status) {
  switch (status) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 409: return "Conflict";
    case 500: return "Internal Server Error";
    case 501: return "Not Implemented";
    case 503: return "Service Unavailable";
    default: return "Status";
  }
}

void AppendJsonString(const std::string& in, std::string* out) {
  out->push_back('"');
  *out += JsonEscapeLatin1(in);
  out->push_back('"');
}

// Minimal parse of the handler's {"Name": "value", ...} headers_json
// (produced by json.dumps of a flat str->str dict — no nesting).
std::map<std::string, std::string> ParseFlatJson(const std::string& text) {
  std::map<std::string, std::string> out;
  size_t pos = 0;
  auto read_string = [&](std::string* value) -> bool {
    while (pos < text.size() && text[pos] != '"') {
      if (text[pos] == '}') return false;
      ++pos;
    }
    if (pos >= text.size()) return false;
    ++pos;
    value->clear();
    while (pos < text.size() && text[pos] != '"') {
      char c = text[pos];
      if (c == '\\' && pos + 1 < text.size()) {
        ++pos;
        char e = text[pos];
        if (e == 'u' && pos + 4 < text.size()) {
          int code = std::stoi(text.substr(pos + 1, 4), nullptr, 16);
          value->push_back(static_cast<char>(code));
          pos += 4;
        } else {
          value->push_back(e == 'n' ? '\n' : e == 't' ? '\t' : e);
        }
      } else {
        value->push_back(c);
      }
      ++pos;
    }
    if (pos < text.size()) ++pos;  // closing quote
    return true;
  };
  std::string key, value;
  while (read_string(&key)) {
    if (!read_string(&value)) break;
    out[key] = value;
  }
  return out;
}

}  // namespace

std::string JsonEscapeLatin1(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (unsigned char c : in) {
    if (c == '"' || c == '\\') {
      out.push_back('\\');
      out.push_back(static_cast<char>(c));
    } else if (c < 0x20 || c >= 0x80) {
      // HTTP/1.1 header values may be latin-1; raw high bytes would
      // make the JSON invalid UTF-8 (\u00XX IS the latin-1 codepoint).
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out.push_back(static_cast<char>(c));
    }
  }
  return out;
}

struct Http1Server::Impl {
  struct Worker {
    std::thread thread;
    std::atomic<bool> done{false};
  };

  std::mutex mutex;
  std::vector<std::unique_ptr<Worker>> workers;
  std::vector<int> active_fds;  // connections currently being served

  void Register(int fd) {
    std::lock_guard<std::mutex> lk(mutex);
    active_fds.push_back(fd);
  }

  void Unregister(int fd) {
    std::lock_guard<std::mutex> lk(mutex);
    active_fds.erase(
        std::remove(active_fds.begin(), active_fds.end(), fd),
        active_fds.end());
  }

  // Joins workers whose connection has ended (called from the accept
  // loop so a long-lived server doesn't accumulate zombie threads).
  void Reap() {
    std::lock_guard<std::mutex> lk(mutex);
    for (size_t i = 0; i < workers.size();) {
      if (workers[i]->done.load()) {
        workers[i]->thread.join();
        workers.erase(workers.begin() + i);
      } else {
        ++i;
      }
    }
  }
};

Http1Server::Http1Server(HttpHandler* handler) : handler_(handler) {}

Http1Server::~Http1Server() { Shutdown(); }

std::string Http1Server::Listen(const std::string& host, int port) {
  impl_.reset(new Impl());
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return strerror(errno);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(lfd);
    return "bad listen host " + host;
  }
  if (bind(lfd, reinterpret_cast<struct sockaddr*>(&addr),
           sizeof(addr)) != 0) {
    std::string err = std::string("bind failed: ") + strerror(errno);
    ::close(lfd);
    return err;
  }
  if (listen(lfd, 128) != 0) {
    std::string err = std::string("listen failed: ") + strerror(errno);
    ::close(lfd);
    return err;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<struct sockaddr*>(&addr), &alen);
  bound_port_ = ntohs(addr.sin_port);
  listen_fd_.store(lfd);
  accept_thread_ = std::thread(&Http1Server::AcceptLoop, this);
  return "";
}

void Http1Server::AcceptLoop() {
  const int lfd = listen_fd_.load();
  while (!shutting_down_.load()) {
    int fd = ::accept(lfd, nullptr, nullptr);
    if (fd < 0) {
      if (errno == EINTR) continue;
      return;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    impl_->Reap();
    auto worker = std::make_unique<Impl::Worker>();
    Impl::Worker* raw = worker.get();
    {
      std::lock_guard<std::mutex> lk(impl_->mutex);
      impl_->workers.push_back(std::move(worker));
    }
    raw->thread = std::thread([this, fd, raw] {
      ServeConnection(fd);
      raw->done.store(true);
    });
  }
}

void Http1Server::ServeConnection(int fd) {
  impl_->Register(fd);
  // Shutdown() may have snapshotted active_fds before this Register:
  // re-check so a connection accepted during shutdown can't sit in
  // recv() forever (Shutdown would then hang in join()).
  if (shutting_down_.load()) {
    ::shutdown(fd, SHUT_RDWR);
  }
  ServeRequests(fd);
  // Unregister BEFORE closing: Shutdown() only shuts down fds still
  // in the registry, so a closed-and-reused descriptor can never be
  // disturbed.
  impl_->Unregister(fd);
  ::close(fd);
}

void Http1Server::ServeRequests(int fd) {
  std::string buffer;
  char chunk[16384];
  bool keep_alive = true;
  while (keep_alive && !shutting_down_.load()) {
    // Read until the header terminator.
    size_t header_end;
    while ((header_end = buffer.find("\r\n\r\n")) == std::string::npos) {
      if (buffer.size() > kMaxHeaderBytes) {
        return;
      }
      ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
      if (n <= 0) {
        return;
      }
      buffer.append(chunk, static_cast<size_t>(n));
    }
    // Request line.
    size_t line_end = buffer.find("\r\n");
    std::string line = buffer.substr(0, line_end);
    size_t sp1 = line.find(' ');
    size_t sp2 = line.rfind(' ');
    if (sp1 == std::string::npos || sp2 <= sp1) {
      return;
    }
    std::string method = line.substr(0, sp1);
    std::string target = line.substr(sp1 + 1, sp2 - sp1 - 1);
    size_t query = target.find('?');
    std::string path =
        query == std::string::npos ? target : target.substr(0, query);
    // Headers -> lower-cased JSON for the handler. The query string
    // (stripped from the routed path so the anchored route regexes
    // keep matching) rides along as a synthetic x-request-query
    // header — /v2/debug's ?model= filter reads it there.
    std::string headers_json = "{";
    bool first = true;
    if (query != std::string::npos && query + 1 < target.size()) {
      AppendJsonString("x-request-query", &headers_json);
      headers_json += ":";
      AppendJsonString(target.substr(query + 1), &headers_json);
      first = false;
    }
    size_t content_length = 0;
    bool content_length_seen = false;
    bool close_requested = false;
    size_t pos = line_end + 2;
    while (pos < header_end) {
      size_t eol = buffer.find("\r\n", pos);
      std::string header = buffer.substr(pos, eol - pos);
      pos = eol + 2;
      size_t colon = header.find(':');
      if (colon == std::string::npos) continue;
      std::string name = header.substr(0, colon);
      std::transform(name.begin(), name.end(), name.begin(),
                     [](unsigned char c) { return std::tolower(c); });
      size_t vstart = colon + 1;
      while (vstart < header.size() && header[vstart] == ' ') ++vstart;
      std::string value = header.substr(vstart);
      if (name == "transfer-encoding") {
        // Chunked bodies are not implemented; answering without
        // draining the body would desync the connection — reject and
        // close.
        const char* resp =
            "HTTP/1.1 501 Not Implemented\r\nContent-Length: 0\r\n"
            "Connection: close\r\n\r\n";
        ::send(fd, resp, strlen(resp), MSG_NOSIGNAL);
        return;
      }
      if (name == "content-length") {
        // Trim RFC 7230 optional whitespace both sides, then require
        // every char to be a digit: strtoull would skip tabs, accept
        // signs (wrapping "-1" to 2^64-1), and clamp overflow.
        while (!value.empty() &&
               (value.back() == ' ' || value.back() == '\t')) {
          value.pop_back();
        }
        while (!value.empty() &&
               (value.front() == ' ' || value.front() == '\t')) {
          value.erase(value.begin());
        }
        bool bad = value.empty() || value.size() > 18;  // > 1e18: absurd
        for (char c : value) {
          if (c < '0' || c > '9') bad = true;
        }
        if (!bad) {
          size_t parsed = strtoull(value.c_str(), nullptr, 10);
          // RFC 7230 §3.3.3: conflicting repeated Content-Length
          // headers are a request-smuggling vector behind proxies —
          // reject rather than last-one-wins.
          if (content_length_seen && parsed != content_length) {
            bad = true;
          }
          content_length = parsed;
          content_length_seen = true;
        }
        if (bad) {
          const char* resp =
              "HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n"
              "Connection: close\r\n\r\n";
          ::send(fd, resp, strlen(resp), MSG_NOSIGNAL);
          return;
        }
      }
      if (name == "connection") {
        std::transform(value.begin(), value.end(), value.begin(),
                       [](unsigned char c) { return std::tolower(c); });
        close_requested = value.find("close") != std::string::npos;
      }
      if (name == "x-request-query") {
        // Reserved for the synthetic query-string entry above: a
        // client-supplied copy would duplicate the JSON key and
        // (last-one-wins on parse) spoof the real query.
        continue;
      }
      if (!first) headers_json += ",";
      AppendJsonString(name, &headers_json);
      headers_json += ":";
      AppendJsonString(value, &headers_json);
      first = false;
    }
    headers_json += "}";
    if (content_length > kMaxBodyBytes) {
      return;
    }
    // Body.
    size_t body_start = header_end + 4;
    while (buffer.size() < body_start + content_length) {
      ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
      if (n <= 0) {
        return;
      }
      buffer.append(chunk, static_cast<size_t>(n));
    }
    std::string body = buffer.substr(body_start, content_length);
    buffer.erase(0, body_start + content_length);

    HttpReply reply = handler_->HttpCall(method, path, headers_json, body);

    std::string response = "HTTP/1.1 " + std::to_string(reply.status) +
                           " " + ReasonPhrase(reply.status) + "\r\n";
    for (const auto& kv : ParseFlatJson(reply.headers_json)) {
      response += kv.first + ": " + kv.second + "\r\n";
    }
    response += "Content-Length: " + std::to_string(reply.body.size()) +
                "\r\n";
    keep_alive = !close_requested;
    response += keep_alive ? "Connection: keep-alive\r\n"
                           : "Connection: close\r\n";
    response += "\r\n";
    // Header and body go out as two sends: appending a large tensor
    // reply to the header string would double peak memory.
    auto send_all = [fd](const char* data, size_t len) {
      size_t sent = 0;
      while (sent < len) {
        ssize_t n = ::send(fd, data + sent, len - sent, MSG_NOSIGNAL);
        if (n <= 0) return false;
        sent += static_cast<size_t>(n);
      }
      return true;
    };
    if (!send_all(response.data(), response.size()) ||
        !send_all(reply.body.data(), reply.body.size())) {
      return;
    }
  }
}

void Http1Server::Shutdown() {
  if (shutting_down_.exchange(true)) return;
  int lfd = listen_fd_.exchange(-1);
  if (lfd >= 0) ::shutdown(lfd, SHUT_RDWR);
  if (accept_thread_.joinable()) accept_thread_.join();
  if (lfd >= 0) ::close(lfd);
  if (impl_) {
    // Wake connection threads blocked in recv() (shutdown makes it
    // return 0), then join them all before the server is destroyed.
    std::vector<std::unique_ptr<Impl::Worker>> workers;
    {
      std::lock_guard<std::mutex> lk(impl_->mutex);
      for (int fd : impl_->active_fds) ::shutdown(fd, SHUT_RDWR);
      workers.swap(impl_->workers);
    }
    for (auto& worker : workers) worker->thread.join();
  }
}

}  // namespace server
}  // namespace tpuclient
