// Server-side HTTP/2 (RFC 9113) + gRPC framing: the transport under
// tpu_serverd, the native front-end for the inference server core.
//
// The reference repo is client-only — its servers are the Triton
// binaries it talks to. This framework serves its own models, and the
// Python grpc front-ends (sync ~1.1k simple infer/s, asyncio ~1.9k)
// leave most of the embedded core's ~40k infer/s on the table. This
// C++ front-end terminates TCP/h2/HPACK/gRPC framing natively and
// forwards each call to the embedded core (native/server/py_core),
// so the only Python on the hot path is the servicer itself.
//
// Counterpart of the client-side transport in native/library/h2/
// (same HPACK codec, same frame grammar, mirrored roles).
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <thread>
#include <vector>

namespace tpuclient {
namespace server {

// Outcome of one dispatched call (unary: responses.size() <= 1).
struct GrpcReply {
  int status = 0;         // grpc-status trailer value (0 = OK)
  std::string message;    // grpc-message when status != 0
  std::vector<std::string> responses;  // serialized response protos
};

// Dispatch interface the transport calls into; the implementation
// (PyCoreHandler) bridges to the embedded Python core. Called from
// worker threads; implementations must be thread-safe.
class GrpcHandler {
 public:
  virtual ~GrpcHandler() = default;
  // 0 = unknown path, 1 = unary, 2 = bidi streaming.
  virtual int MethodKind(const std::string& path) = 0;
  // One unary request message -> reply.
  virtual GrpcReply Call(const std::string& path,
                         const std::string& message) = 0;
  // Writes one serialized response to the peer immediately; returns
  // false when the stream is gone (the handler should stop producing).
  using StreamEmit = std::function<bool(const std::string&)>;
  // One message of a bidi-streaming RPC -> zero or more responses,
  // delivered incrementally through `emit` as they are produced (a
  // decoupled model's token stream reaches the wire token by token,
  // not as one end-of-generation burst). Responses left in the
  // returned reply are flushed after the call as a convenience.
  virtual GrpcReply StreamCall(const std::string& path,
                               const std::string& message,
                               const StreamEmit& emit) = 0;
};

class H2Server {
 public:
  // `workers`: dispatch threads shared across connections. The GIL
  // serializes the Python servicer anyway; workers exist so slow
  // calls on one stream don't head-of-line-block other streams at
  // the transport level.
  explicit H2Server(GrpcHandler* handler, int workers = 8);
  ~H2Server();

  H2Server(const H2Server&) = delete;
  H2Server& operator=(const H2Server&) = delete;

  // Binds and starts the accept loop. port 0 = ephemeral; see
  // bound_port(). Returns "" on success. Equivalent to Bind()+Serve().
  std::string Listen(const std::string& host, int port);
  // Two-phase variant: Bind() resolves the port (early connections
  // queue in the kernel backlog), letting the caller finish
  // port-dependent setup (e.g. publishing the arena route into
  // handles) before Serve() starts accepting.
  std::string Bind(const std::string& host, int port);
  void Serve();
  int bound_port() const { return bound_port_; }

  // Stops accepting, closes all connections, joins all threads.
  void Shutdown();

 private:
  void AcceptLoop();

  GrpcHandler* handler_;
  int workers_;
  std::atomic<int> listen_fd_{-1};
  int bound_port_ = 0;
  std::atomic<bool> shutting_down_{false};
  std::thread accept_thread_;
  struct Impl;
  std::unique_ptr<Impl> impl_;
};

}  // namespace server
}  // namespace tpuclient
