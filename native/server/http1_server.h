// HTTP/1.1 server transport for tpu_serverd's REST front-end: accepts
// KServe-v2 REST calls (JSON + binary-tensor extension) and forwards
// them to the embedded core via the HttpHandler interface
// (PyCoreHandler::HttpCall -> client_tpu.server.embed.http_call).
// HTTP/1.1 is one request at a time per connection, so dispatch runs
// on the connection's own thread — parallelism comes from concurrent
// connections, mirroring the reference server's REST front-end model.
#pragma once

#include <atomic>
#include <memory>
#include <string>
#include <thread>
#include <vector>

namespace tpuclient {
namespace server {

// Escapes a byte string for embedding in a JSON string literal:
// quote/backslash escaped, control chars and bytes >= 0x80 \u-escaped
// (high bytes as their latin-1 codepoints, keeping the JSON valid
// UTF-8). Shared by the transport's header marshalling and the
// Python bridge's error bodies.
std::string JsonEscapeLatin1(const std::string& in);

struct HttpReply {
  int status = 200;
  std::string headers_json;  // {"Header-Name": "value", ...}
  std::string body;
};

class HttpHandler {
 public:
  virtual ~HttpHandler() = default;
  // `headers_json` carries the request headers with lower-cased names.
  virtual HttpReply HttpCall(const std::string& method,
                             const std::string& path,
                             const std::string& headers_json,
                             const std::string& body) = 0;
};

class Http1Server {
 public:
  explicit Http1Server(HttpHandler* handler);
  ~Http1Server();

  Http1Server(const Http1Server&) = delete;
  Http1Server& operator=(const Http1Server&) = delete;

  std::string Listen(const std::string& host, int port);
  int bound_port() const { return bound_port_; }
  void Shutdown();

 private:
  void AcceptLoop();
  void ServeConnection(int fd);
  void ServeRequests(int fd);

  HttpHandler* handler_;
  std::atomic<int> listen_fd_{-1};
  int bound_port_ = 0;
  std::atomic<bool> shutting_down_{false};
  std::thread accept_thread_;
  struct Impl;
  std::unique_ptr<Impl> impl_;
};

}  // namespace server
}  // namespace tpuclient
