// Per-call HTTP body compression (parity: the reference client's
// request/response_compression_algorithm args,
// /root/reference/src/c++/library/http_client.cc:2130-2247 — there
// implemented with libcurl+zlib; here plain zlib).
#pragma once

#include <string>

#include "common.h"

namespace tpuclient {

enum class CompressionType { NONE, DEFLATE, GZIP };

// Header token for Content-Encoding / Accept-Encoding ("" for NONE).
const char* CompressionName(CompressionType type);

// in -> compressed out ("deflate" = zlib format per RFC 9110).
Error CompressBody(CompressionType type, const std::string& in,
                   std::string* out);

// Undoes a Content-Encoding ("gzip"/"deflate"; ""/"identity" copies).
Error DecompressBody(const std::string& encoding, const std::string& in,
                     std::string* out);

}  // namespace tpuclient
