#include "http_transport.h"

#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstring>

namespace tpuclient {

static uint64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

HttpConnection::~HttpConnection() { Close(); }

void HttpConnection::Close() {
  if (tls_ != nullptr) {
    tls_->Close();
    tls_.reset();
  }
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
  leftover_.clear();
}

std::string HttpConnection::Connect(uint64_t timeout_us) {
  Close();
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(port_);
  int rc = getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    return "failed to resolve " + host_ + ": " + gai_strerror(rc);
  }
  uint64_t deadline_ns =
      (timeout_us != 0) ? NowNs() + timeout_us * 1000ull : 0;
  std::string err;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    int fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) {
      err = strerror(errno);
      continue;
    }
    // Non-blocking from the start: connect with EINPROGRESS + poll so
    // the timeout is honoured, and all later send/recv calls hit the
    // EAGAIN paths that enforce the request deadline.
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
    int rc2 = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc2 != 0 && errno == EINPROGRESS) {
      struct pollfd pfd = {fd, POLLOUT, 0};
      while (true) {
        int pr = poll(&pfd, 1, 50);
        if (pr > 0) break;
        if (deadline_ns != 0 && NowNs() > deadline_ns) {
          err = "connect timeout";
          break;
        }
        if (pr < 0 && errno != EINTR) {
          err = strerror(errno);
          break;
        }
      }
      if (err.empty()) {
        int so_error = 0;
        socklen_t slen = sizeof(so_error);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &so_error, &slen);
        if (so_error != 0) {
          err = strerror(so_error);
          rc2 = -1;
        } else {
          rc2 = 0;
        }
      } else {
        rc2 = -1;
      }
    } else if (rc2 != 0) {
      err = strerror(errno);
    }
    if (rc2 == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      fd_ = fd;
      err.clear();
      break;
    }
    ::close(fd);
  }
  freeaddrinfo(res);
  if (fd_ < 0) {
    return "failed to connect to " + host_ + ":" + port_str + ": " + err;
  }
  if (use_tls_) {
    tls_ = std::make_unique<TlsSession>();
    uint64_t deadline_ns =
        (timeout_us != 0) ? NowNs() + timeout_us * 1000ull : 0;
    std::string tls_err =
        tls_->Handshake(fd_, host_, ssl_options_, "", deadline_ns);
    if (!tls_err.empty()) {
      Close();
      return "TLS handshake with " + host_ + ": " + tls_err;
    }
  }
  return "";
}

std::string HttpConnection::SendAll(
    const char* data, size_t len, uint64_t deadline_ns) {
  if (tls_ != nullptr) return tls_->Write(data, len, deadline_ns);
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd_, data + sent, len - sent, MSG_NOSIGNAL);
    if (n > 0) {
      sent += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)) {
      if (deadline_ns != 0 && NowNs() > deadline_ns) {
        return "send timeout";
      }
      struct pollfd pfd = {fd_, POLLOUT, 0};
      poll(&pfd, 1, 50);
      continue;
    }
    return std::string("send failed: ") + strerror(errno);
  }
  return "";
}

ssize_t HttpConnection::RecvSome(
    char* buf, size_t len, uint64_t deadline_ns, std::string* err) {
  if (tls_ != nullptr) {
    return static_cast<ssize_t>(tls_->Read(buf, len, deadline_ns, err));
  }
  while (true) {
    ssize_t n = ::recv(fd_, buf, len, 0);
    if (n >= 0) return n;
    if (errno == EINTR) continue;
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      if (deadline_ns != 0 && NowNs() > deadline_ns) {
        *err = "receive timeout";
        return -1;
      }
      struct pollfd pfd = {fd_, POLLIN, 0};
      poll(&pfd, 1, 50);
      continue;
    }
    *err = std::string("recv failed: ") + strerror(errno);
    return -1;
  }
}

namespace {

// Incremental HTTP/1.1 response parser.
struct ResponseParser {
  enum State { kStatusLine, kHeaders, kBody, kChunkSize, kChunkData,
               kChunkTrailer, kDone } state = kStatusLine;
  HttpResponse* response;
  const std::function<void(const char*, size_t)>* on_data;
  std::string line_buf;
  size_t content_length = 0;
  bool have_content_length = false;
  bool chunked = false;
  bool close_delimited = false;
  size_t body_received = 0;
  size_t chunk_remaining = 0;

  // Feeds bytes; consumes from data, returns error or "".
  std::string Feed(const char* data, size_t len, size_t* consumed) {
    size_t i = 0;
    while (i < len && state != kDone) {
      switch (state) {
        case kStatusLine:
        case kHeaders:
        case kChunkSize:
        case kChunkTrailer: {
          // Accumulate a CRLF-terminated line.
          char c = data[i++];
          line_buf.push_back(c);
          if (c == '\n') {
            std::string line = line_buf;
            line_buf.clear();
            while (!line.empty() &&
                   (line.back() == '\n' || line.back() == '\r')) {
              line.pop_back();
            }
            std::string err = OnLine(line);
            if (!err.empty()) return err;
          }
          break;
        }
        case kBody: {
          size_t want = len - i;
          if (have_content_length) {
            want = std::min(want, content_length - body_received);
          }
          Deliver(data + i, want);
          body_received += want;
          i += want;
          if (have_content_length && body_received >= content_length) {
            state = kDone;
          }
          break;
        }
        case kChunkData: {
          size_t want = std::min(len - i, chunk_remaining);
          Deliver(data + i, want);
          i += want;
          chunk_remaining -= want;
          if (chunk_remaining == 0) {
            // Consume the CRLF after the chunk via line machinery.
            state = kChunkTrailer;
          }
          break;
        }
        case kDone:
          break;
      }
    }
    *consumed = i;
    return "";
  }

  void Deliver(const char* data, size_t len) {
    if (on_data != nullptr) {
      (*on_data)(data, len);
    } else {
      response->body.append(data, len);
    }
  }

  std::string OnLine(const std::string& line) {
    switch (state) {
      case kStatusLine: {
        // "HTTP/1.1 200 OK"
        size_t sp = line.find(' ');
        if (sp == std::string::npos || line.compare(0, 5, "HTTP/") != 0) {
          return "malformed status line: " + line;
        }
        response->status_code = atoi(line.c_str() + sp + 1);
        state = kHeaders;
        break;
      }
      case kHeaders: {
        if (line.empty()) {
          // End of headers.
          auto it = response->headers.find("transfer-encoding");
          if (it != response->headers.end() &&
              it->second.find("chunked") != std::string::npos) {
            chunked = true;
            state = kChunkSize;
          } else {
            it = response->headers.find("content-length");
            if (it != response->headers.end()) {
              have_content_length = true;
              content_length =
                  static_cast<size_t>(strtoull(it->second.c_str(), nullptr, 10));
              state = (content_length == 0) ? kDone : kBody;
            } else {
              // Read until connection close.
              close_delimited = true;
              state = kBody;
            }
          }
          break;
        }
        size_t colon = line.find(':');
        if (colon == std::string::npos) break;  // ignore malformed
        std::string name = line.substr(0, colon);
        for (auto& ch : name) ch = static_cast<char>(tolower(ch));
        size_t vstart = colon + 1;
        while (vstart < line.size() && line[vstart] == ' ') ++vstart;
        response->headers[name] = line.substr(vstart);
        break;
      }
      case kChunkSize: {
        if (line.empty()) break;  // tolerate stray CRLF between chunks
        chunk_remaining =
            static_cast<size_t>(strtoull(line.c_str(), nullptr, 16));
        if (chunk_remaining == 0) {
          // Final chunk; trailing headers until empty line.
          state = kChunkTrailer;
          final_chunk_seen = true;
        } else {
          state = kChunkData;
        }
        break;
      }
      case kChunkTrailer: {
        if (final_chunk_seen) {
          if (line.empty()) state = kDone;
        } else {
          // This was the CRLF after a chunk's data.
          state = kChunkSize;
          if (!line.empty()) {
            // Line actually held the next chunk size.
            return OnLine(line);
          }
        }
        break;
      }
      default:
        break;
    }
    return "";
  }

  bool final_chunk_seen = false;
};

}  // namespace

std::string HttpConnection::ReadResponse(
    HttpResponse* response,
    const std::function<void(const char*, size_t)>* on_data,
    uint64_t deadline_ns) {
  ResponseParser parser;
  parser.response = response;
  parser.on_data = on_data;

  // Feed any bytes buffered beyond the previous response first.
  if (!leftover_.empty()) {
    std::string pending;
    pending.swap(leftover_);
    size_t consumed = 0;
    std::string err = parser.Feed(pending.data(), pending.size(), &consumed);
    if (!err.empty()) return err;
    if (consumed < pending.size()) {
      leftover_ = pending.substr(consumed);
    }
  }

  char buf[65536];
  while (parser.state != ResponseParser::kDone) {
    std::string err;
    ssize_t n = RecvSome(buf, sizeof(buf), deadline_ns, &err);
    if (n < 0) return err;
    if (n == 0) {
      if (parser.close_delimited &&
          parser.state == ResponseParser::kBody) {
        break;  // body delimited by EOF
      }
      return "connection closed before full response";
    }
    size_t consumed = 0;
    err = parser.Feed(buf, static_cast<size_t>(n), &consumed);
    if (!err.empty()) return err;
    if (consumed < static_cast<size_t>(n)) {
      leftover_.append(buf + consumed, static_cast<size_t>(n) - consumed);
    }
  }

  auto conn_hdr = response->headers.find("connection");
  if (parser.close_delimited ||
      (conn_hdr != response->headers.end() &&
       conn_hdr->second.find("close") != std::string::npos)) {
    Close();
  }
  return "";
}

std::string HttpConnection::Request(
    const std::string& method, const std::string& path,
    const std::map<std::string, std::string>& headers,
    const std::string& body, HttpResponse* response, uint64_t timeout_us,
    uint64_t* sent_ns_out) {
  return RequestStreaming(
      method, path, headers, body, response, nullptr, timeout_us,
      sent_ns_out);
}

std::string HttpConnection::RequestStreaming(
    const std::string& method, const std::string& path,
    const std::map<std::string, std::string>& headers,
    const std::string& body, HttpResponse* response,
    const std::function<void(const char*, size_t)>& on_data,
    uint64_t timeout_us, uint64_t* sent_ns_out) {
  uint64_t deadline_ns =
      (timeout_us != 0) ? NowNs() + timeout_us * 1000ull : 0;

  std::string head;
  head.reserve(256);
  head.append(method).append(" ").append(path).append(" HTTP/1.1\r\n");
  head.append("Host: ").append(host_).append(":")
      .append(std::to_string(port_)).append("\r\n");
  bool have_cl = false;
  for (const auto& h : headers) {
    head.append(h.first).append(": ").append(h.second).append("\r\n");
    std::string lower = h.first;
    for (auto& c : lower) c = static_cast<char>(tolower(c));
    if (lower == "content-length") have_cl = true;
  }
  if (!have_cl && (!body.empty() || method == "POST" || method == "PUT")) {
    head.append("Content-Length: ")
        .append(std::to_string(body.size()))
        .append("\r\n");
  }
  head.append("\r\n");

  // Retry once on stale keep-alive connection.
  for (int attempt = 0; attempt < 2; ++attempt) {
    bool fresh = false;
    if (fd_ < 0) {
      std::string err = Connect(timeout_us);
      if (!err.empty()) return err;
      fresh = true;
    }
    *response = HttpResponse();
    std::string err = SendAll(head.data(), head.size(), deadline_ns);
    if (err.empty() && !body.empty()) {
      err = SendAll(body.data(), body.size(), deadline_ns);
    }
    if (err.empty() && sent_ns_out != nullptr) *sent_ns_out = NowNs();
    if (err.empty()) {
      err = ReadResponse(
          response, on_data ? &on_data : nullptr, deadline_ns);
    }
    if (err.empty()) return "";
    Close();
    // Never retry once response bytes were seen (a streaming on_data
    // callback may already have observed partial data).
    if (fresh || attempt == 1 || response->status_code != 0) return err;
    // else: stale keep-alive — reconnect and retry
  }
  return "unreachable";
}

}  // namespace tpuclient
