// Base64 encode/decode (RFC 4648). The reference vendors the
// public-domain libb64 (cencode.{h,c}) for shipping CUDA IPC handles
// over HTTP; we need the same for TPU region descriptors in REST
// bodies.
#pragma once

#include <cstdint>
#include <string>

namespace tpuclient {

std::string Base64Encode(const uint8_t* data, size_t len);
std::string Base64Encode(const std::string& data);

// Returns false on malformed input.
bool Base64Decode(const std::string& encoded, std::string* out);

}  // namespace tpuclient
