#include "compression.h"

#include <zlib.h>

#include <vector>

namespace tpuclient {

namespace {

// windowBits selects the format: 15 = zlib ("deflate" per RFC 9110),
// 15+16 = gzip, 15+32 on inflate = auto-detect either.
constexpr int kZlibWindow = 15;
constexpr int kGzipWindow = 15 + 16;
constexpr int kAutoWindow = 15 + 32;

Error Deflate(const std::string& in, int window_bits, std::string* out) {
  if (in.size() >= UINT32_MAX) {  // zlib avail_in is 32-bit
    return Error("body too large to compress in one pass (>4GiB)");
  }
  z_stream stream{};
  if (deflateInit2(&stream, Z_DEFAULT_COMPRESSION, Z_DEFLATED, window_bits,
                   8, Z_DEFAULT_STRATEGY) != Z_OK) {
    return Error("zlib deflateInit failed");
  }
  uLong bound = deflateBound(&stream, in.size());
  if (bound >= UINT32_MAX) {  // avail_out is 32-bit too
    deflateEnd(&stream);
    return Error("body too large to compress in one pass (>4GiB)");
  }
  out->clear();
  out->resize(bound);
  stream.next_in =
      reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  stream.avail_in = static_cast<uInt>(in.size());
  stream.next_out = reinterpret_cast<Bytef*>(&(*out)[0]);
  stream.avail_out = static_cast<uInt>(out->size());
  int rc = deflate(&stream, Z_FINISH);
  deflateEnd(&stream);
  if (rc != Z_STREAM_END) return Error("zlib deflate failed");
  out->resize(out->size() - stream.avail_out);
  return Error::Success;
}

}  // namespace

const char* CompressionName(CompressionType type) {
  switch (type) {
    case CompressionType::NONE: return "";
    case CompressionType::DEFLATE: return "deflate";
    case CompressionType::GZIP: return "gzip";
  }
  return "";
}

Error CompressBody(CompressionType type, const std::string& in,
                   std::string* out) {
  switch (type) {
    case CompressionType::NONE:
      *out = in;
      return Error::Success;
    case CompressionType::DEFLATE:
      return Deflate(in, kZlibWindow, out);
    case CompressionType::GZIP:
      return Deflate(in, kGzipWindow, out);
  }
  return Error("unknown compression type");
}

Error DecompressBody(const std::string& encoding, const std::string& in,
                     std::string* out) {
  if (encoding.empty() || encoding == "identity") {
    *out = in;
    return Error::Success;
  }
  if (encoding != "gzip" && encoding != "deflate") {
    return Error("unsupported Content-Encoding '" + encoding + "'");
  }
  if (in.size() >= UINT32_MAX) {  // zlib avail_in is 32-bit
    return Error("body too large to decompress in one pass (>4GiB)");
  }
  z_stream stream{};
  if (inflateInit2(&stream, kAutoWindow) != Z_OK) {
    return Error("zlib inflateInit failed");
  }
  out->clear();
  stream.next_in =
      reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  stream.avail_in = static_cast<uInt>(in.size());
  std::vector<char> buffer(64 * 1024);
  int rc = Z_OK;
  while (rc != Z_STREAM_END) {
    stream.next_out = reinterpret_cast<Bytef*>(buffer.data());
    stream.avail_out = static_cast<uInt>(buffer.size());
    rc = inflate(&stream, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&stream);
      return Error("zlib inflate failed (corrupt body?)");
    }
    out->append(buffer.data(), buffer.size() - stream.avail_out);
  }
  inflateEnd(&stream);
  return Error::Success;
}

}  // namespace tpuclient
