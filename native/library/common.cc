#include "common.h"

#include <ostream>

namespace tpuclient {

//============================================================ Error

const Error Error::Success("");

Error::Error(const std::string& msg) : msg_(msg) {}

std::ostream& operator<<(std::ostream& out, const Error& err) {
  if (!err.msg_.empty()) out << err.msg_;
  return out;
}

//============================================================ InferInput

Error InferInput::Create(
    InferInput** infer_input, const std::string& name,
    const std::vector<int64_t>& dims, const std::string& datatype) {
  *infer_input = new InferInput(name, dims, datatype);
  return Error::Success;
}

InferInput::InferInput(
    const std::string& name, const std::vector<int64_t>& dims,
    const std::string& datatype)
    : name_(name), shape_(dims), datatype_(datatype) {}

Error InferInput::SetShape(const std::vector<int64_t>& dims) {
  shape_ = dims;
  return Error::Success;
}

Error InferInput::AppendRaw(const std::vector<uint8_t>& input) {
  return AppendRaw(input.data(), input.size());
}

Error InferInput::AppendRaw(const uint8_t* input, size_t input_byte_size) {
  bufs_.emplace_back(input, input_byte_size);
  total_send_byte_size_ += input_byte_size;
  byte_size_ = total_send_byte_size_;
  return Error::Success;
}

Error InferInput::AppendFromString(const std::vector<std::string>& input) {
  if (datatype_ != "BYTES") {
    return Error(
        "unable to append string data to non-BYTES input '" + name_ + "'");
  }
  // 4-byte little-endian length prefix per element — the v2 BYTES
  // wire format (reference serialize_byte_tensor,
  // tritonclient/utils/__init__.py:193).
  str_bufs_.emplace_back();
  std::string& serialized = str_bufs_.back();
  for (const auto& s : input) {
    uint32_t len = static_cast<uint32_t>(s.size());
    char lenbuf[4];
    lenbuf[0] = static_cast<char>(len & 0xFF);
    lenbuf[1] = static_cast<char>((len >> 8) & 0xFF);
    lenbuf[2] = static_cast<char>((len >> 16) & 0xFF);
    lenbuf[3] = static_cast<char>((len >> 24) & 0xFF);
    serialized.append(lenbuf, 4);
    serialized.append(s);
  }
  return AppendRaw(
      reinterpret_cast<const uint8_t*>(serialized.data()), serialized.size());
}

Error InferInput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset) {
  shm_name_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success;
}

Error InferInput::SharedMemoryInfo(
    std::string* name, size_t* byte_size, size_t* offset) const {
  if (shm_name_.empty()) {
    return Error("input '" + name_ + "' has no shared-memory region set");
  }
  *name = shm_name_;
  *byte_size = shm_byte_size_;
  *offset = shm_offset_;
  return Error::Success;
}

Error InferInput::Reset() {
  bufs_.clear();
  str_bufs_.clear();
  total_send_byte_size_ = 0;
  byte_size_ = 0;
  bufs_idx_ = 0;
  buf_pos_ = 0;
  shm_name_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success;
}

void InferInput::PrepareForRequest() {
  bufs_idx_ = 0;
  buf_pos_ = 0;
}

bool InferInput::GetNext(const uint8_t** buf, size_t* input_bytes) {
  while (bufs_idx_ < bufs_.size()) {
    const auto& entry = bufs_[bufs_idx_];
    if (buf_pos_ < entry.second) {
      *buf = entry.first + buf_pos_;
      *input_bytes = entry.second - buf_pos_;
      ++bufs_idx_;
      buf_pos_ = 0;
      return true;
    }
    ++bufs_idx_;
    buf_pos_ = 0;
  }
  *buf = nullptr;
  *input_bytes = 0;
  return false;
}

void InferInput::GatherInto(std::string* out) const {
  for (const auto& entry : bufs_) {
    out->append(reinterpret_cast<const char*>(entry.first), entry.second);
  }
}

//============================================================ InferRequestedOutput

Error InferRequestedOutput::Create(
    InferRequestedOutput** infer_output, const std::string& name,
    const size_t class_count, const std::string& datatype) {
  *infer_output = new InferRequestedOutput(name, datatype, class_count);
  return Error::Success;
}

InferRequestedOutput::InferRequestedOutput(
    const std::string& name, const std::string& datatype,
    const size_t class_count)
    : name_(name), datatype_(datatype), class_count_(class_count) {}

Error InferRequestedOutput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset) {
  shm_name_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success;
}

Error InferRequestedOutput::UnsetSharedMemory() {
  shm_name_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success;
}

Error InferRequestedOutput::SharedMemoryInfo(
    std::string* name, size_t* byte_size, size_t* offset) const {
  if (shm_name_.empty()) {
    return Error("output '" + name_ + "' has no shared-memory region set");
  }
  *name = shm_name_;
  *byte_size = shm_byte_size_;
  *offset = shm_offset_;
  return Error::Success;
}

Error InferRequestedOutput::SetBinaryData(bool binary_data) {
  binary_data_ = binary_data;
  return Error::Success;
}

//============================================================ client base

Error InferenceServerClient::ClientInferStat(InferStat* infer_stat) const {
  std::lock_guard<std::mutex> lk(stat_mutex_);
  *infer_stat = infer_stat_;
  return Error::Success;
}

void InferenceServerClient::UpdateInferStat(const RequestTimers& timer) {
  std::lock_guard<std::mutex> lk(stat_mutex_);
  infer_stat_.completed_request_count++;
  infer_stat_.cumulative_total_request_time_ns += timer.Duration(
      RequestTimers::Kind::REQUEST_START, RequestTimers::Kind::REQUEST_END);
  infer_stat_.cumulative_send_time_ns += timer.Duration(
      RequestTimers::Kind::SEND_START, RequestTimers::Kind::SEND_END);
  infer_stat_.cumulative_receive_time_ns += timer.Duration(
      RequestTimers::Kind::RECV_START, RequestTimers::Kind::RECV_END);
}

}  // namespace tpuclient
