#include "json.h"

#include <cmath>
#include <cstdio>
#include <cstring>
#include <stdexcept>

namespace tpuclient {
namespace json {

static const Value kNullValue;

Value::Value(const char* s) : type_(Type::kString), str_(new std::string(s)) {}
Value::Value(const std::string& s)
    : type_(Type::kString), str_(new std::string(s)) {}
Value::Value(std::string&& s)
    : type_(Type::kString), str_(new std::string(std::move(s))) {}
Value::Value(const Array& a) : type_(Type::kArray), array_(new Array(a)) {}
Value::Value(Array&& a) : type_(Type::kArray), array_(new Array(std::move(a))) {}
Value::Value(const Object& o) : type_(Type::kObject), object_(new Object(o)) {}
Value::Value(Object&& o)
    : type_(Type::kObject), object_(new Object(std::move(o))) {}

Value::Value(const Value& other) : type_(Type::kNull) { CopyFrom(other); }
Value::Value(Value&& other) noexcept : type_(Type::kNull) {
  MoveFrom(std::move(other));
}
Value& Value::operator=(const Value& other) {
  if (this != &other) {
    Destroy();
    CopyFrom(other);
  }
  return *this;
}
Value& Value::operator=(Value&& other) noexcept {
  if (this != &other) {
    Destroy();
    MoveFrom(std::move(other));
  }
  return *this;
}
Value::~Value() { Destroy(); }

void Value::Destroy() {
  str_.reset();
  array_.reset();
  object_.reset();
  type_ = Type::kNull;
}

void Value::CopyFrom(const Value& other) {
  type_ = other.type_;
  switch (type_) {
    case Type::kBool:
      bool_ = other.bool_;
      break;
    case Type::kInt:
      int_ = other.int_;
      break;
    case Type::kUint:
      uint_ = other.uint_;
      break;
    case Type::kDouble:
      double_ = other.double_;
      break;
    case Type::kString:
      str_.reset(new std::string(*other.str_));
      break;
    case Type::kArray:
      array_.reset(new Array(*other.array_));
      break;
    case Type::kObject:
      object_.reset(new Object(*other.object_));
      break;
    default:
      break;
  }
}

void Value::MoveFrom(Value&& other) {
  type_ = other.type_;
  switch (type_) {
    case Type::kBool:
      bool_ = other.bool_;
      break;
    case Type::kInt:
      int_ = other.int_;
      break;
    case Type::kUint:
      uint_ = other.uint_;
      break;
    case Type::kDouble:
      double_ = other.double_;
      break;
    case Type::kString:
      str_ = std::move(other.str_);
      break;
    case Type::kArray:
      array_ = std::move(other.array_);
      break;
    case Type::kObject:
      object_ = std::move(other.object_);
      break;
    default:
      break;
  }
  other.type_ = Type::kNull;
}

bool Value::AsBool() const {
  if (type_ != Type::kBool) throw std::runtime_error("json: not a bool");
  return bool_;
}

int64_t Value::AsInt() const {
  switch (type_) {
    case Type::kInt:
      return int_;
    case Type::kUint:
      return static_cast<int64_t>(uint_);
    case Type::kDouble:
      return static_cast<int64_t>(double_);
    default:
      throw std::runtime_error("json: not a number");
  }
}

uint64_t Value::AsUint() const {
  switch (type_) {
    case Type::kInt:
      if (int_ < 0) throw std::runtime_error("json: negative to uint");
      return static_cast<uint64_t>(int_);
    case Type::kUint:
      return uint_;
    case Type::kDouble:
      return static_cast<uint64_t>(double_);
    default:
      throw std::runtime_error("json: not a number");
  }
}

double Value::AsDouble() const {
  switch (type_) {
    case Type::kInt:
      return static_cast<double>(int_);
    case Type::kUint:
      return static_cast<double>(uint_);
    case Type::kDouble:
      return double_;
    default:
      throw std::runtime_error("json: not a number");
  }
}

const std::string& Value::AsString() const {
  if (type_ != Type::kString) throw std::runtime_error("json: not a string");
  return *str_;
}

const Array& Value::AsArray() const {
  if (type_ != Type::kArray) throw std::runtime_error("json: not an array");
  return *array_;
}
Array& Value::AsArray() {
  if (type_ != Type::kArray) throw std::runtime_error("json: not an array");
  return *array_;
}
const Object& Value::AsObject() const {
  if (type_ != Type::kObject) throw std::runtime_error("json: not an object");
  return *object_;
}
Object& Value::AsObject() {
  if (type_ != Type::kObject) throw std::runtime_error("json: not an object");
  return *object_;
}

const Value& Value::operator[](const std::string& key) const {
  if (type_ != Type::kObject) return kNullValue;
  const Value* v = object_->Find(key);
  return v ? *v : kNullValue;
}

bool Value::Has(const std::string& key) const {
  return type_ == Type::kObject && object_->Has(key);
}

Value& Object::operator[](const std::string& key) {
  for (auto& e : entries_) {
    if (e.first == key) return e.second;
  }
  entries_.emplace_back(key, Value());
  return entries_.back().second;
}

const Value* Object::Find(const std::string& key) const {
  for (const auto& e : entries_) {
    if (e.first == key) return &e.second;
  }
  return nullptr;
}

void Object::Set(const std::string& key, Value v) {
  (*this)[key] = std::move(v);
}

// ---------------------------------------------------------------- writer

static void WriteEscaped(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"':
        out->append("\\\"");
        break;
      case '\\':
        out->append("\\\\");
        break;
      case '\b':
        out->append("\\b");
        break;
      case '\f':
        out->append("\\f");
        break;
      case '\n':
        out->append("\\n");
        break;
      case '\r':
        out->append("\\r");
        break;
      case '\t':
        out->append("\\t");
        break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out->append(buf);
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
  out->push_back('"');
}

void Value::SerializeTo(std::string* out) const {
  switch (type_) {
    case Type::kNull:
      out->append("null");
      break;
    case Type::kBool:
      out->append(bool_ ? "true" : "false");
      break;
    case Type::kInt: {
      char buf[32];
      snprintf(buf, sizeof(buf), "%lld", static_cast<long long>(int_));
      out->append(buf);
      break;
    }
    case Type::kUint: {
      char buf[32];
      snprintf(buf, sizeof(buf), "%llu", static_cast<unsigned long long>(uint_));
      out->append(buf);
      break;
    }
    case Type::kDouble: {
      char buf[64];
      if (std::isfinite(double_)) {
        snprintf(buf, sizeof(buf), "%.17g", double_);
      } else {
        // JSON has no Inf/NaN; emit null like most writers.
        snprintf(buf, sizeof(buf), "null");
      }
      out->append(buf);
      break;
    }
    case Type::kString:
      WriteEscaped(*str_, out);
      break;
    case Type::kArray: {
      out->push_back('[');
      bool first = true;
      for (const auto& v : *array_) {
        if (!first) out->push_back(',');
        first = false;
        v.SerializeTo(out);
      }
      out->push_back(']');
      break;
    }
    case Type::kObject: {
      out->push_back('{');
      bool first = true;
      for (const auto& e : object_->entries()) {
        if (!first) out->push_back(',');
        first = false;
        WriteEscaped(e.first, out);
        out->push_back(':');
        e.second.SerializeTo(out);
      }
      out->push_back('}');
      break;
    }
  }
}

std::string Value::Serialize() const {
  std::string out;
  SerializeTo(&out);
  return out;
}

// ---------------------------------------------------------------- parser

namespace {

class Parser {
 public:
  Parser(const char* data, size_t len) : p_(data), end_(data + len) {}

  std::string Run(Value* out) {
    SkipWs();
    std::string err = ParseValue(out);
    if (!err.empty()) return err;
    SkipWs();
    if (p_ != end_) return Error("trailing characters");
    return "";
  }

 private:
  std::string Error(const std::string& what) {
    return "json parse error at offset " +
           std::to_string(static_cast<size_t>(p_ - start_)) + ": " + what;
  }

  void SkipWs() {
    while (p_ != end_ &&
           (*p_ == ' ' || *p_ == '\t' || *p_ == '\n' || *p_ == '\r')) {
      ++p_;
    }
  }

  bool Consume(char c) {
    if (p_ != end_ && *p_ == c) {
      ++p_;
      return true;
    }
    return false;
  }

  std::string ParseValue(Value* out) {
    if (p_ == end_) return Error("unexpected end of input");
    switch (*p_) {
      case '{':
        return ParseObject(out);
      case '[':
        return ParseArray(out);
      case '"': {
        std::string s;
        std::string err = ParseString(&s);
        if (!err.empty()) return err;
        *out = Value(std::move(s));
        return "";
      }
      case 't':
        if (end_ - p_ >= 4 && memcmp(p_, "true", 4) == 0) {
          p_ += 4;
          *out = Value(true);
          return "";
        }
        return Error("invalid literal");
      case 'f':
        if (end_ - p_ >= 5 && memcmp(p_, "false", 5) == 0) {
          p_ += 5;
          *out = Value(false);
          return "";
        }
        return Error("invalid literal");
      case 'n':
        if (end_ - p_ >= 4 && memcmp(p_, "null", 4) == 0) {
          p_ += 4;
          *out = Value();
          return "";
        }
        return Error("invalid literal");
      default:
        return ParseNumber(out);
    }
  }

  std::string ParseObject(Value* out) {
    ++p_;  // '{'
    Object obj;
    SkipWs();
    if (Consume('}')) {
      *out = Value(std::move(obj));
      return "";
    }
    while (true) {
      SkipWs();
      if (p_ == end_ || *p_ != '"') return Error("expected object key");
      std::string key;
      std::string err = ParseString(&key);
      if (!err.empty()) return err;
      SkipWs();
      if (!Consume(':')) return Error("expected ':'");
      SkipWs();
      Value v;
      err = ParseValue(&v);
      if (!err.empty()) return err;
      obj.entries().emplace_back(std::move(key), std::move(v));
      SkipWs();
      if (Consume(',')) continue;
      if (Consume('}')) break;
      return Error("expected ',' or '}'");
    }
    *out = Value(std::move(obj));
    return "";
  }

  std::string ParseArray(Value* out) {
    ++p_;  // '['
    Array arr;
    SkipWs();
    if (Consume(']')) {
      *out = Value(std::move(arr));
      return "";
    }
    while (true) {
      SkipWs();
      Value v;
      std::string err = ParseValue(&v);
      if (!err.empty()) return err;
      arr.push_back(std::move(v));
      SkipWs();
      if (Consume(',')) continue;
      if (Consume(']')) break;
      return Error("expected ',' or ']'");
    }
    *out = Value(std::move(arr));
    return "";
  }

  static void AppendUtf8(uint32_t cp, std::string* s) {
    if (cp < 0x80) {
      s->push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      s->push_back(static_cast<char>(0xC0 | (cp >> 6)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      s->push_back(static_cast<char>(0xE0 | (cp >> 12)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      s->push_back(static_cast<char>(0xF0 | (cp >> 18)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  std::string ParseHex4(uint32_t* out) {
    if (end_ - p_ < 4) return Error("truncated \\u escape");
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = *p_++;
      v <<= 4;
      if (c >= '0' && c <= '9')
        v |= static_cast<uint32_t>(c - '0');
      else if (c >= 'a' && c <= 'f')
        v |= static_cast<uint32_t>(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F')
        v |= static_cast<uint32_t>(c - 'A' + 10);
      else
        return Error("bad \\u escape");
    }
    *out = v;
    return "";
  }

  std::string ParseString(std::string* out) {
    ++p_;  // '"'
    while (true) {
      if (p_ == end_) return Error("unterminated string");
      unsigned char c = static_cast<unsigned char>(*p_);
      if (c == '"') {
        ++p_;
        return "";
      }
      if (c == '\\') {
        ++p_;
        if (p_ == end_) return Error("truncated escape");
        char e = *p_++;
        switch (e) {
          case '"':
            out->push_back('"');
            break;
          case '\\':
            out->push_back('\\');
            break;
          case '/':
            out->push_back('/');
            break;
          case 'b':
            out->push_back('\b');
            break;
          case 'f':
            out->push_back('\f');
            break;
          case 'n':
            out->push_back('\n');
            break;
          case 'r':
            out->push_back('\r');
            break;
          case 't':
            out->push_back('\t');
            break;
          case 'u': {
            uint32_t cp;
            std::string err = ParseHex4(&cp);
            if (!err.empty()) return err;
            if (cp >= 0xD800 && cp <= 0xDBFF) {
              // Surrogate pair.
              if (end_ - p_ < 6 || p_[0] != '\\' || p_[1] != 'u') {
                return Error("unpaired surrogate");
              }
              p_ += 2;
              uint32_t lo;
              err = ParseHex4(&lo);
              if (!err.empty()) return err;
              if (lo < 0xDC00 || lo > 0xDFFF) {
                return Error("invalid low surrogate");
              }
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            AppendUtf8(cp, out);
            break;
          }
          default:
            return Error("bad escape character");
        }
      } else if (c < 0x20) {
        return Error("control character in string");
      } else {
        out->push_back(static_cast<char>(c));
        ++p_;
      }
    }
  }

  std::string ParseNumber(Value* out) {
    const char* begin = p_;
    bool negative = Consume('-');
    bool is_double = false;
    while (p_ != end_ && *p_ >= '0' && *p_ <= '9') ++p_;
    if (p_ != end_ && *p_ == '.') {
      is_double = true;
      ++p_;
      while (p_ != end_ && *p_ >= '0' && *p_ <= '9') ++p_;
    }
    if (p_ != end_ && (*p_ == 'e' || *p_ == 'E')) {
      is_double = true;
      ++p_;
      if (p_ != end_ && (*p_ == '+' || *p_ == '-')) ++p_;
      while (p_ != end_ && *p_ >= '0' && *p_ <= '9') ++p_;
    }
    if (p_ == begin || (negative && p_ == begin + 1)) {
      return Error("invalid number");
    }
    std::string text(begin, static_cast<size_t>(p_ - begin));
    if (is_double) {
      *out = Value(strtod(text.c_str(), nullptr));
      return "";
    }
    errno = 0;
    if (negative) {
      long long v = strtoll(text.c_str(), nullptr, 10);
      if (errno == ERANGE) {
        *out = Value(strtod(text.c_str(), nullptr));
      } else {
        *out = Value(static_cast<int64_t>(v));
      }
    } else {
      unsigned long long v = strtoull(text.c_str(), nullptr, 10);
      if (errno == ERANGE) {
        *out = Value(strtod(text.c_str(), nullptr));
      } else if (v <= static_cast<unsigned long long>(INT64_MAX)) {
        *out = Value(static_cast<int64_t>(v));
      } else {
        *out = Value(static_cast<uint64_t>(v));
      }
    }
    return "";
  }

  const char* p_;
  const char* end_;
  const char* start_ = p_;
};

}  // namespace

std::string Parse(const char* data, size_t len, Value* out) {
  Parser parser(data, len);
  return parser.Run(out);
}

std::string Parse(const std::string& text, Value* out) {
  return Parse(text.data(), text.size(), out);
}

}  // namespace json
}  // namespace tpuclient
