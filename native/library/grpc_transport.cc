#include "grpc_transport.h"

#include <cstring>

#include "compression.h"

namespace tpuclient {

std::string PercentDecode(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); ++i) {
    if (in[i] == '%' && i + 2 < in.size()) {
      auto hex = [](char c) -> int {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'a' && c <= 'f') return c - 'a' + 10;
        if (c >= 'A' && c <= 'F') return c - 'A' + 10;
        return -1;
      };
      int hi = hex(in[i + 1]), lo = hex(in[i + 2]);
      if (hi >= 0 && lo >= 0) {
        out.push_back(static_cast<char>((hi << 4) | lo));
        i += 2;
        continue;
      }
    }
    out.push_back(in[i]);
  }
  return out;
}

std::string FrameGrpcMessage(
    const std::string& payload, const std::string& compression) {
  const std::string* body = &payload;
  std::string compressed;
  bool flag = false;
  if (compression == "gzip" || compression == "deflate") {
    Error err = CompressBody(
        compression == "gzip" ? CompressionType::GZIP
                              : CompressionType::DEFLATE,
        payload, &compressed);
    if (err.IsOk()) {
      body = &compressed;
      flag = true;
    }  // compression failure degrades to an uncompressed frame
  }
  std::string framed;
  framed.reserve(body->size() + 5);
  framed.push_back(flag ? '\x01' : '\0');
  uint32_t len = static_cast<uint32_t>(body->size());
  framed.push_back(static_cast<char>(len >> 24));
  framed.push_back(static_cast<char>(len >> 16));
  framed.push_back(static_cast<char>(len >> 8));
  framed.push_back(static_cast<char>(len));
  framed.append(*body);
  return framed;
}

bool GrpcMessageReader::Feed(
    const uint8_t* data, size_t len, std::vector<std::string>* messages) {
  buffer_.append(reinterpret_cast<const char*>(data), len);
  while (buffer_.size() >= 5) {
    uint8_t flag = static_cast<uint8_t>(buffer_[0]);
    if (flag > 1) return false;
    uint32_t msg_len =
        (static_cast<uint32_t>(static_cast<uint8_t>(buffer_[1])) << 24) |
        (static_cast<uint32_t>(static_cast<uint8_t>(buffer_[2])) << 16) |
        (static_cast<uint32_t>(static_cast<uint8_t>(buffer_[3])) << 8) |
        static_cast<uint8_t>(buffer_[4]);
    if (buffer_.size() < 5u + msg_len) break;
    if (flag == 1) {
      if (encoding_.empty() || encoding_ == "identity") {
        return false;  // compressed frame, no encoding negotiated
      }
      std::string plain;
      Error err =
          DecompressBody(encoding_, buffer_.substr(5, msg_len), &plain);
      if (!err.IsOk()) return false;
      messages->push_back(std::move(plain));
    } else {
      messages->emplace_back(buffer_.substr(5, msg_len));
    }
    buffer_.erase(0, 5 + msg_len);
  }
  return true;
}

Error StatusFromTrailers(
    const h2::HeaderList& headers, const h2::HeaderList& trailers,
    const std::string& transport_error) {
  if (!transport_error.empty()) {
    return Error("transport error: " + transport_error);
  }
  const std::string* status = nullptr;
  const std::string* message = nullptr;
  auto scan = [&](const h2::HeaderList& list) {
    for (const auto& kv : list) {
      if (kv.first == "grpc-status") status = &kv.second;
      else if (kv.first == "grpc-message") message = &kv.second;
    }
  };
  scan(trailers);
  if (status == nullptr) scan(headers);
  if (status == nullptr) {
    for (const auto& kv : headers) {
      if (kv.first == ":status" && kv.second != "200") {
        return Error("HTTP status " + kv.second);
      }
    }
    return Error("missing grpc-status");
  }
  if (*status == "0") return Error::Success;
  std::string text = "gRPC error " + *status;
  if (message != nullptr && !message->empty()) {
    text += ": " + PercentDecode(*message);
  }
  return Error(text);
}

//==============================================================================
// GrpcChannel

Error GrpcChannel::Create(
    std::shared_ptr<GrpcChannel>* channel, const std::string& url,
    uint64_t connect_timeout_us) {
  std::string host = url;
  int port = 8001;
  // Strip optional scheme, split host:port.
  size_t scheme = host.find("://");
  if (scheme != std::string::npos) host = host.substr(scheme + 3);
  size_t colon = host.rfind(':');
  if (colon != std::string::npos) {
    port = atoi(host.substr(colon + 1).c_str());
    host = host.substr(0, colon);
  }
  auto ch = std::shared_ptr<GrpcChannel>(new GrpcChannel(host, port));
  ch->conn_ = std::make_shared<h2::H2Connection>(host, port);
  std::string err = ch->conn_->Connect(connect_timeout_us);
  if (!err.empty()) return Error(err);
  *channel = ch;
  return Error::Success;
}

h2::HeaderList GrpcChannel::BuildRequestHeaders(
    const std::string& method, uint64_t timeout_us,
    const Headers& metadata) const {
  h2::HeaderList headers;
  headers.emplace_back(":method", "POST");
  headers.emplace_back(":scheme", "http");
  headers.emplace_back(":path", method);
  headers.emplace_back(":authority", host_ + ":" + std::to_string(port_));
  headers.emplace_back("te", "trailers");
  headers.emplace_back("content-type", "application/grpc");
  headers.emplace_back("user-agent", "tpuclient-grpc/1.0");
  if (timeout_us > 0) {
    // The gRPC spec caps TimeoutValue at 8 digits; step up units as
    // needed.
    if (timeout_us < 100000000ull) {
      headers.emplace_back("grpc-timeout", std::to_string(timeout_us) + "u");
    } else if (timeout_us / 1000 < 100000000ull) {
      headers.emplace_back(
          "grpc-timeout", std::to_string(timeout_us / 1000) + "m");
    } else {
      uint64_t secs = std::min<uint64_t>(timeout_us / 1000000, 99999999ull);
      headers.emplace_back("grpc-timeout", std::to_string(secs) + "S");
    }
  }
  for (const auto& kv : metadata) {
    headers.emplace_back(kv.first, kv.second);
  }
  return headers;
}

namespace {

// Adds grpc-encoding / grpc-accept-encoding metadata for a
// per-call message compression algorithm ("" = none).
// The only supported message codings; anything else degrades to
// uncompressed rather than sending a header/flag mismatch.
bool SupportedGrpcCompression(const std::string& compression) {
  return compression == "gzip" || compression == "deflate";
}

Headers WithCompressionHeaders(
    const Headers& metadata, const std::string& compression) {
  if (compression.empty()) return metadata;
  Headers out = metadata;
  out["grpc-encoding"] = compression;
  out["grpc-accept-encoding"] = "gzip,deflate,identity";
  return out;
}

// Shared state for one unary call, owned jointly by the caller (sync)
// or nobody (async, callbacks keep it alive) and the H2 callbacks.
struct UnaryState {
  std::mutex mutex;
  std::condition_variable cv;
  bool done = false;
  GrpcMessageReader reader;
  std::vector<std::string> messages;
  h2::HeaderList headers;
  Error status = Error::Success;
  RequestTimers timers;
  GrpcChannel::AsyncUnaryCallback async_callback;  // async mode only
};

h2::StreamCallbacks MakeUnaryCallbacks(std::shared_ptr<UnaryState> state) {
  h2::StreamCallbacks callbacks;
  callbacks.on_headers = [state](const h2::HeaderList& headers) {
    std::lock_guard<std::mutex> lock(state->mutex);
    state->headers = headers;
    for (const auto& kv : headers) {
      if (kv.first == "grpc-encoding") state->reader.SetEncoding(kv.second);
    }
  };
  callbacks.on_data = [state](const uint8_t* data, size_t len) {
    std::lock_guard<std::mutex> lock(state->mutex);
    if (!state->reader.Feed(data, len, &state->messages)) {
      state->status = Error("malformed gRPC frame");
    }
  };
  callbacks.on_close = [state](
                           const h2::HeaderList& trailers,
                           const std::string& transport_error) {
    GrpcChannel::AsyncUnaryCallback callback;
    Error status;
    std::string response;
    {
      std::lock_guard<std::mutex> lock(state->mutex);
      state->timers.CaptureTimestamp(RequestTimers::Kind::RECV_END);
      if (state->status.IsOk()) {
        state->status = StatusFromTrailers(
            state->headers, trailers, transport_error);
      }
      if (state->status.IsOk() && state->messages.empty()) {
        state->status = Error("no response message");
      }
      state->done = true;
      status = state->status;
      callback = std::move(state->async_callback);
      // Sync callers read messages[0] themselves after the wait.
      if (callback && !state->messages.empty()) {
        response = std::move(state->messages[0]);
      }
    }
    state->cv.notify_all();
    if (callback) {
      state->timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
      callback(status, std::move(response), state->timers);
    }
  };
  return callbacks;
}

}  // namespace

Error GrpcChannel::UnaryCall(
    const std::string& method, const std::string& request,
    std::string* response, uint64_t timeout_us, const Headers& metadata,
    RequestTimers* timers, const std::string& compression_arg) {
  const std::string compression =
      SupportedGrpcCompression(compression_arg) ? compression_arg : "";
  auto state = std::make_shared<UnaryState>();
  state->timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
  std::string err;
  state->timers.CaptureTimestamp(RequestTimers::Kind::SEND_START);
  int32_t stream_id = conn_->StartStream(
      BuildRequestHeaders(method, timeout_us,
                          WithCompressionHeaders(metadata, compression)),
      MakeUnaryCallbacks(state), &err);
  if (stream_id < 0) return Error(err);
  std::string framed = FrameGrpcMessage(request, compression);
  err = conn_->SendData(
      stream_id, reinterpret_cast<const uint8_t*>(framed.data()),
      framed.size(), /*end_stream=*/true);
  {
    // Under the lock: on_close may already be capturing RECV_END on
    // the reader thread.
    std::lock_guard<std::mutex> lock(state->mutex);
    state->timers.CaptureTimestamp(RequestTimers::Kind::SEND_END);
    state->timers.CaptureTimestamp(RequestTimers::Kind::RECV_START);
  }
  if (!err.empty()) {
    // The stream may have finished before the send completed (server
    // rejected the call and reset the stream): prefer the gRPC status
    // captured by on_close when it arrives promptly.
    std::unique_lock<std::mutex> lock(state->mutex);
    if (state->cv.wait_for(
            lock, std::chrono::seconds(5), [&] { return state->done; }) &&
        !state->status.IsOk()) {
      return state->status;
    }
    return Error(err);
  }

  std::unique_lock<std::mutex> lock(state->mutex);
  if (timeout_us > 0) {
    if (!state->cv.wait_for(
            lock, std::chrono::microseconds(timeout_us),
            [&] { return state->done; })) {
      lock.unlock();
      conn_->CancelStream(stream_id);
      lock.lock();
      state->cv.wait(lock, [&] { return state->done; });
      return Error("Deadline Exceeded");
    }
  } else {
    state->cv.wait(lock, [&] { return state->done; });
  }
  state->timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
  if (timers != nullptr) *timers = state->timers;
  if (!state->status.IsOk()) return state->status;
  *response = std::move(state->messages[0]);
  return Error::Success;
}

Error GrpcChannel::AsyncUnaryCall(
    const std::string& method, const std::string& request,
    AsyncUnaryCallback callback, uint64_t timeout_us,
    const Headers& metadata, const std::string& compression_arg) {
  const std::string compression =
      SupportedGrpcCompression(compression_arg) ? compression_arg : "";
  auto state = std::make_shared<UnaryState>();
  state->async_callback = std::move(callback);
  state->timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
  state->timers.CaptureTimestamp(RequestTimers::Kind::SEND_START);
  std::string err;
  int32_t stream_id = conn_->StartStream(
      BuildRequestHeaders(method, timeout_us,
                          WithCompressionHeaders(metadata, compression)),
      MakeUnaryCallbacks(state), &err);
  if (stream_id < 0) return Error(err);
  std::string framed = FrameGrpcMessage(request, compression);
  // Once the stream is open, completion is owned by on_close — even
  // on a send error it fires (the stream already finished, or the
  // broken connection triggers FailAll), so never ALSO return an
  // error here: the caller would double-complete.
  conn_->SendData(
      stream_id, reinterpret_cast<const uint8_t*>(framed.data()),
      framed.size(), /*end_stream=*/true);
  {
    std::lock_guard<std::mutex> lock(state->mutex);
    state->timers.CaptureTimestamp(RequestTimers::Kind::SEND_END);
    state->timers.CaptureTimestamp(RequestTimers::Kind::RECV_START);
  }
  return Error::Success;
}

//==============================================================================
// GrpcBidiStream

struct GrpcBidiStream::State {
  std::mutex mutex;
  std::condition_variable cv;
  bool done = false;
  Error status = Error::Success;
  GrpcMessageReader reader;
  h2::HeaderList headers;
  std::function<void(std::string&&)> on_message;
  std::function<void(const Error&)> on_done;
};

GrpcBidiStream::~GrpcBidiStream() {
  if (conn_ && stream_id_ >= 0) {
    bool open;
    {
      std::lock_guard<std::mutex> lock(state_->mutex);
      open = !state_->done;
    }
    // Abandoned without Finish(): cancel to release the stream. Must
    // not hold state_->mutex here — CancelStream fires on_close which
    // locks it.
    if (open) conn_->CancelStream(stream_id_);
  }
}

Error GrpcBidiStream::Write(const std::string& message) {
  std::string framed = FrameGrpcMessage(message);
  std::string err = conn_->SendData(
      stream_id_, reinterpret_cast<const uint8_t*>(framed.data()),
      framed.size(), /*end_stream=*/false);
  if (!err.empty()) return Error(err);
  return Error::Success;
}

Error GrpcBidiStream::WritesDone() {
  std::string err = conn_->CloseSendSide(stream_id_);
  if (!err.empty()) return Error(err);
  return Error::Success;
}

void GrpcBidiStream::Cancel() { conn_->CancelStream(stream_id_); }

Error GrpcBidiStream::Finish() {
  std::unique_lock<std::mutex> lock(state_->mutex);
  state_->cv.wait(lock, [&] { return state_->done; });
  return state_->status;
}

Error GrpcChannel::StartBidiStream(
    std::unique_ptr<GrpcBidiStream>* stream, const std::string& method,
    std::function<void(std::string&&)> on_message,
    std::function<void(const Error&)> on_done, const Headers& metadata) {
  auto state = std::make_shared<GrpcBidiStream::State>();
  state->on_message = std::move(on_message);
  state->on_done = std::move(on_done);

  h2::StreamCallbacks callbacks;
  callbacks.on_headers = [state](const h2::HeaderList& headers) {
    std::lock_guard<std::mutex> lock(state->mutex);
    state->headers = headers;
    for (const auto& kv : headers) {
      if (kv.first == "grpc-encoding") state->reader.SetEncoding(kv.second);
    }
  };
  callbacks.on_data = [state](const uint8_t* data, size_t len) {
    std::vector<std::string> messages;
    {
      std::lock_guard<std::mutex> lock(state->mutex);
      if (!state->reader.Feed(data, len, &messages)) {
        state->status = Error("malformed gRPC frame");
        return;
      }
    }
    if (state->on_message) {
      for (auto& m : messages) state->on_message(std::move(m));
    }
  };
  callbacks.on_close = [state](
                           const h2::HeaderList& trailers,
                           const std::string& transport_error) {
    Error status;
    std::function<void(const Error&)> on_done;
    {
      std::lock_guard<std::mutex> lock(state->mutex);
      if (state->status.IsOk()) {
        state->status =
            StatusFromTrailers(state->headers, trailers, transport_error);
      }
      state->done = true;
      status = state->status;
      on_done = state->on_done;
    }
    state->cv.notify_all();
    if (on_done) on_done(status);
  };

  std::string err;
  int32_t stream_id = conn_->StartStream(
      BuildRequestHeaders(method, 0, metadata), std::move(callbacks), &err);
  if (stream_id < 0) return Error(err);

  auto bidi = std::unique_ptr<GrpcBidiStream>(new GrpcBidiStream());
  bidi->state_ = state;
  bidi->conn_ = conn_;
  bidi->stream_id_ = stream_id;
  *stream = std::move(bidi);
  return Error::Success;
}

}  // namespace tpuclient
