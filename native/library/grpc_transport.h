// gRPC-over-HTTP/2 transport: length-prefixed message framing, unary
// calls (sync + callback-async), and bidirectional streams, over the
// self-contained H2Connection. Fills the role grpc++'s channel,
// CompletionQueue and ClientReaderWriter play for the reference
// client (/root/reference/src/c++/library/grpc_client.cc:1583
// AsyncTransfer, :1629 AsyncStreamTransfer).
#pragma once

#include <condition_variable>
#include <functional>
#include <memory>
#include <mutex>
#include <string>

#include "common.h"
#include "h2/h2_connection.h"

namespace tpuclient {

// Decodes %xx escapes (gRPC percent-encodes grpc-message).
std::string PercentDecode(const std::string& in);

class GrpcChannel;

//==============================================================================
// A bidirectional gRPC stream (client side). Writes are sequenced by
// the caller; responses arrive on the connection reader thread via
// on_message, and on_done fires exactly once when the stream closes.
//
class GrpcBidiStream {
 public:
  ~GrpcBidiStream();

  // Sends one length-prefixed message.
  Error Write(const std::string& message);
  // Half-closes the send side (WritesDone).
  Error WritesDone();
  // RST_STREAMs the call.
  void Cancel();

  // Blocks until the stream has fully closed; returns final status.
  Error Finish();

 private:
  friend class GrpcChannel;
  GrpcBidiStream() = default;

  struct State;
  std::shared_ptr<State> state_;
  std::shared_ptr<h2::H2Connection> conn_;
  int32_t stream_id_ = -1;
};

//==============================================================================
// One gRPC channel == one HTTP/2 connection. Thread-safe; calls
// multiplex as independent HTTP/2 streams.
//
class GrpcChannel {
 public:
  // url is "host:port".
  static Error Create(
      std::shared_ptr<GrpcChannel>* channel, const std::string& url,
      uint64_t connect_timeout_us = 20 * 1000 * 1000);

  // Synchronous unary call. `method` is "/package.Service/Method".
  // Fills `response` with the raw message bytes. Timeout 0 = none.
  // `compression` ("gzip"/"deflate") compresses the request message
  // per the gRPC wire spec and advertises grpc-accept-encoding.
  Error UnaryCall(
      const std::string& method, const std::string& request,
      std::string* response, uint64_t timeout_us = 0,
      const Headers& metadata = {}, RequestTimers* timers = nullptr,
      const std::string& compression = "");

  // Callback-async unary call; `callback(status, response_bytes,
  // timers)` fires on the connection reader thread.
  using AsyncUnaryCallback = std::function<void(
      const Error&, std::string&&, const RequestTimers&)>;
  Error AsyncUnaryCall(
      const std::string& method, const std::string& request,
      AsyncUnaryCallback callback, uint64_t timeout_us = 0,
      const Headers& metadata = {}, const std::string& compression = "");

  // Opens a bidi stream. `on_message(bytes)` per response message,
  // `on_done(status)` once at stream end; both on the reader thread.
  Error StartBidiStream(
      std::unique_ptr<GrpcBidiStream>* stream, const std::string& method,
      std::function<void(std::string&&)> on_message,
      std::function<void(const Error&)> on_done,
      const Headers& metadata = {});

  // Transport-level liveness probing with h2 PINGs (gRPC keepalive
  // semantics): unacked PINGs fail the connection and every pending
  // call errors out, so dead servers are detected without waiting on
  // per-call timeouts.
  void EnableKeepAlive(uint64_t interval_ms, uint64_t timeout_ms) {
    if (conn_) conn_->EnableKeepAlive(interval_ms, timeout_ms);
  }

  // Synchronously closes the connection, failing all in-flight calls
  // (their callbacks fire before this returns). Lets owners tear down
  // callback targets safely afterwards.
  void Shutdown() {
    if (conn_) conn_->Close();
  }

  bool IsConnected() const {
    return conn_ != nullptr && conn_->IsConnected();
  }

  size_t num_active_calls() {
    return conn_ ? conn_->num_active_streams() : 0;
  }

 private:
  GrpcChannel(const std::string& host, int port)
      : host_(host), port_(port) {}

  h2::HeaderList BuildRequestHeaders(
      const std::string& method, uint64_t timeout_us,
      const Headers& metadata) const;

  std::string host_;
  int port_ = 0;
  std::shared_ptr<h2::H2Connection> conn_;
};

// Parses status from trailers (grpc-status / grpc-message), falling
// back to :status when the gRPC trailer is absent.
Error StatusFromTrailers(
    const h2::HeaderList& headers, const h2::HeaderList& trailers,
    const std::string& transport_error);

// Incremental decoder for the gRPC length-prefix wire format.
class GrpcMessageReader {
 public:
  // Feed DATA bytes; complete messages are appended to *messages.
  // Returns false on malformed framing (or a compressed message when
  // no encoding was negotiated).
  bool Feed(
      const uint8_t* data, size_t len, std::vector<std::string>* messages);

  // Message-encoding from the response's grpc-encoding header;
  // compressed-flag frames are inflated with it.
  void SetEncoding(const std::string& encoding) { encoding_ = encoding; }

 private:
  std::string buffer_;
  std::string encoding_;
};

// Frames one message: flag byte + 4-byte BE length + payload.
// `compression` ("gzip"/"deflate") compresses the payload and sets
// the compressed flag (reference grpc_compression_algorithm parity).
std::string FrameGrpcMessage(
    const std::string& payload, const std::string& compression = "");

}  // namespace tpuclient
