// Core client data model for the TPU-native inference client.
//
// Mirrors the public surface of the reference C++ client library's
// common.h (/root/reference/src/c++/library/common.h:61-677): Error,
// InferStat, InferenceServerClient base, InferOptions, InferInput,
// InferRequestedOutput, InferResult, RequestTimers — re-implemented
// for the KServe-v2 TPU server (system shm + TPU HBM arena regions
// instead of CUDA IPC).
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace tpuclient {

class InferResult;

//==============================================================================
// Error status object returned by every API (parity: common.h:61).
//
class Error {
 public:
  explicit Error(const std::string& msg = "");
  bool IsOk() const { return msg_.empty(); }
  const std::string& Message() const { return msg_; }

  static const Error Success;

  friend std::ostream& operator<<(std::ostream&, const Error&);

 private:
  std::string msg_;
};

//==============================================================================
// Cumulative client-side inference statistics (parity: common.h:93).
//
struct InferStat {
  size_t completed_request_count = 0;
  uint64_t cumulative_total_request_time_ns = 0;
  uint64_t cumulative_send_time_ns = 0;
  uint64_t cumulative_receive_time_ns = 0;
};

//==============================================================================
// Nanosecond timestamps captured around each request
// (parity: common.h:568-648).
//
class RequestTimers {
 public:
  enum class Kind {
    REQUEST_START,
    REQUEST_END,
    SEND_START,
    SEND_END,
    RECV_START,
    RECV_END,
    COUNT__
  };

  RequestTimers() { Reset(); }

  void Reset() {
    for (auto& t : timestamps_) t = 0;
  }

  void CaptureTimestamp(Kind kind) {
    timestamps_[static_cast<size_t>(kind)] =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count();
  }

  void SetTimestamp(Kind kind, uint64_t ns) {
    timestamps_[static_cast<size_t>(kind)] = ns;
  }

  uint64_t Timestamp(Kind kind) const {
    return timestamps_[static_cast<size_t>(kind)];
  }

  uint64_t Duration(Kind start, Kind end) const {
    uint64_t s = Timestamp(start), e = Timestamp(end);
    return (e >= s) ? (e - s) : 0;
  }

 private:
  uint64_t timestamps_[static_cast<size_t>(Kind::COUNT__)];
};

//==============================================================================
// Per-request options (parity: common.h:164-231).
//
struct InferOptions {
  explicit InferOptions(const std::string& model_name_in)
      : model_name(model_name_in) {}

  std::string model_name;
  std::string model_version;
  std::string request_id;
  uint64_t sequence_id = 0;
  bool sequence_start = false;
  bool sequence_end = false;
  uint64_t priority = 0;
  // Server-side timeout in microseconds (0 = none).
  uint64_t server_timeout_us = 0;
  // Client-side transport timeout in microseconds (0 = none).
  uint64_t client_timeout_us = 0;
  // Generic request parameters forwarded on the wire.
  std::map<std::string, std::string> string_params;
  std::map<std::string, int64_t> int_params;
  std::map<std::string, bool> bool_params;
  std::map<std::string, double> double_params;
  // Whether to request/parse outputs as binary over HTTP.
  bool binary_data_output = true;
  // HTTP only: send input tensors as JSON "data" arrays instead of
  // the binary extension (interop with KServe servers lacking the
  // binary protocol; reference --input-tensor-format json).
  bool json_input_data = false;
};

//==============================================================================
// An input tensor for an inference request (parity: common.h:237-394).
// Data is either appended host buffers (zero-copy chunk iteration via
// GetNext) or a named shared-memory region (system or TPU HBM).
//
class InferInput {
 public:
  static Error Create(
      InferInput** infer_input, const std::string& name,
      const std::vector<int64_t>& dims, const std::string& datatype);

  const std::string& Name() const { return name_; }
  const std::string& Datatype() const { return datatype_; }
  const std::vector<int64_t>& Shape() const { return shape_; }
  Error SetShape(const std::vector<int64_t>& dims);

  // Appends a chunk of raw tensor data (not copied; caller keeps the
  // buffer alive until the request completes; parity common.h:296).
  Error AppendRaw(const uint8_t* input, size_t input_byte_size);
  Error AppendRaw(const std::vector<uint8_t>& input);
  // Appends BYTES-tensor strings (serialized 4-byte-LE length
  // prefixed into an internal buffer; parity common.h:313).
  Error AppendFromString(const std::vector<std::string>& input);

  // Routes this input through a registered shared-memory region
  // (system or TPU; parity common.h:331).
  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  bool IsSharedMemory() const { return !shm_name_.empty(); }
  Error SharedMemoryInfo(
      std::string* name, size_t* byte_size, size_t* offset) const;

  Error Reset();

  size_t ByteSize() const { return byte_size_; }
  // Total bytes appended so far (must equal ByteSize() at send time
  // for fixed-size dtypes).
  size_t TotalSendByteSize() const { return total_send_byte_size_; }

  // Chunk iterator used by transports to serialize without copying
  // (parity: common.h:380 GetNext).
  void PrepareForRequest();
  bool GetNext(const uint8_t** buf, size_t* input_bytes);
  // Convenience: gather all chunks into out (single copy).
  void GatherInto(std::string* out) const;

 private:
  InferInput(
      const std::string& name, const std::vector<int64_t>& dims,
      const std::string& datatype);

  std::string name_;
  std::vector<int64_t> shape_;
  std::string datatype_;
  size_t byte_size_ = 0;

  std::vector<std::pair<const uint8_t*, size_t>> bufs_;
  // Backing store for AppendFromString serialization. A deque keeps
  // element addresses stable across later appends (bufs_ holds raw
  // pointers into these strings).
  std::deque<std::string> str_bufs_;
  size_t total_send_byte_size_ = 0;
  size_t bufs_idx_ = 0;
  size_t buf_pos_ = 0;

  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

//==============================================================================
// A requested output tensor (parity: common.h:400-482).
//
class InferRequestedOutput {
 public:
  static Error Create(
      InferRequestedOutput** infer_output, const std::string& name,
      const size_t class_count = 0, const std::string& datatype = "");

  const std::string& Name() const { return name_; }
  const std::string& Datatype() const { return datatype_; }
  size_t ClassCount() const { return class_count_; }

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  Error UnsetSharedMemory();
  bool IsSharedMemory() const { return !shm_name_.empty(); }
  Error SharedMemoryInfo(
      std::string* name, size_t* byte_size, size_t* offset) const;

  // HTTP-only: request this output as binary data (default true;
  // parity common.h:466 BinaryData).
  bool BinaryData() const { return binary_data_; }
  Error SetBinaryData(bool binary_data);

 private:
  InferRequestedOutput(
      const std::string& name, const std::string& datatype,
      const size_t class_count);

  std::string name_;
  std::string datatype_;
  size_t class_count_;
  bool binary_data_ = true;

  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

//==============================================================================
// Result interface returned to the user (parity: common.h:488-563).
//
class InferResult {
 public:
  virtual ~InferResult() = default;

  virtual Error ModelName(std::string* name) const = 0;
  virtual Error ModelVersion(std::string* version) const = 0;
  virtual Error Id(std::string* id) const = 0;
  virtual Error Shape(
      const std::string& output_name, std::vector<int64_t>* shape) const = 0;
  virtual Error Datatype(
      const std::string& output_name, std::string* datatype) const = 0;
  virtual Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const = 0;
  virtual Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const = 0;
  virtual std::string DebugString() const = 0;
  virtual Error RequestStatus() const = 0;
};

using OnCompleteFn = std::function<void(InferResult*)>;
using OnMultiCompleteFn = std::function<void(std::vector<InferResult*>)>;

//==============================================================================
// Client base: shared stats + async-worker scaffolding
// (parity: common.h:119-153).
//
class InferenceServerClient {
 public:
  explicit InferenceServerClient(bool verbose)
      : verbose_(verbose), exiting_(false) {}
  virtual ~InferenceServerClient() = default;

  Error ClientInferStat(InferStat* infer_stat) const;

 protected:
  void UpdateInferStat(const RequestTimers& timer);

  bool verbose_;

  std::thread worker_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool exiting_;

  mutable std::mutex stat_mutex_;
  InferStat infer_stat_;
};

//==============================================================================
// Headers / query-string types used by both protocol clients.
//
using Headers = std::map<std::string, std::string>;
using Parameters = std::map<std::string, std::string>;

}  // namespace tpuclient
