// gRPC (KServe-v2) client for the TPU inference server.
//
// Mirrors the reference InferenceServerGrpcClient surface
// (/root/reference/src/c++/library/grpc_client.h:100): the same
// endpoint methods, sync Infer, callback-async AsyncInfer with a
// completion worker thread (parity: AsyncTransfer,
// grpc_client.cc:1583), and decoupled bidi streaming via
// StartStream/AsyncStreamInfer/StopStream (parity:
// AsyncStreamTransfer, grpc_client.cc:1629). Transport is the
// self-contained HTTP/2 + HPACK stack in h2/ (this image has no
// grpc++), and the CUDA shared-memory verbs are replaced by TPU HBM
// arena verbs carrying a serialized arena-region descriptor.
//
// Thread-safety contract matches the reference (grpc_client.h:86-89):
// StartStream, StopStream and AsyncStreamInfer must not be called
// concurrently with each other; everything else is thread-safe.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>

#include "client_tpu/protocol/inference.pb.h"
#include "common.h"
#include "grpc_transport.h"

namespace tpuclient {

//==============================================================================
// Result of a gRPC inference (parity: InferResultGrpc,
// grpc_client.cc:238).
//
class InferResultGrpc : public InferResult {
 public:
  static Error Create(
      InferResult** result, std::shared_ptr<inference::ModelInferResponse>
                                response,
      const Error& request_status = Error::Success);
  static Error Create(
      InferResult** result,
      std::shared_ptr<inference::ModelStreamInferResponse> stream_response);

  Error ModelName(std::string* name) const override;
  Error ModelVersion(std::string* version) const override;
  Error Id(std::string* id) const override;
  Error Shape(
      const std::string& output_name,
      std::vector<int64_t>* shape) const override;
  Error Datatype(
      const std::string& output_name, std::string* datatype) const override;
  Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const override;
  Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const override;
  std::string DebugString() const override;
  Error RequestStatus() const override;

  const inference::ModelInferResponse& Response() const { return *response_; }
  // Decoupled models: false while more responses follow this one
  // (triton_final_response parameter; parity grpc_client.cc:1650).
  bool IsFinalResponse() const { return is_final_response_; }
  bool HasNullLastResponse() const { return null_last_response_; }

 private:
  InferResultGrpc(
      std::shared_ptr<inference::ModelInferResponse> response,
      const Error& request_status);

  Error FindOutput(
      const std::string& output_name,
      const inference::ModelInferResponse::InferOutputTensor** tensor,
      size_t* index) const;

  std::shared_ptr<inference::ModelInferResponse> response_;
  std::shared_ptr<inference::ModelStreamInferResponse> stream_response_;
  Error status_;
  bool is_final_response_ = true;
  bool null_last_response_ = false;
};

//==============================================================================
// The gRPC client (parity: grpc_client.h:100).
//
class InferenceServerGrpcClient : public InferenceServerClient {
 public:
  ~InferenceServerGrpcClient() override;

  // url is "host:port" (no scheme), like the reference.
  // use_cached_channel shares one HTTP/2 connection among up to
  // TPUCLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT (default 6) clients per
  // URL before opening the next one (parity: GetStub channel cache,
  // grpc_client.cc:50-152).
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& url, bool verbose = false,
      bool use_cached_channel = true);

  // Client-side keepalive (parity: the reference's KeepAliveOptions,
  // grpc_client.h:94 — GRPC_ARG_KEEPALIVE_* channel args; here h2
  // PING probing on the owned connection). keepalive_time_ms is the
  // probe interval, keepalive_timeout_ms the unacked-PING deadline.
  struct KeepAliveOptions {
    uint64_t keepalive_time_ms = UINT64_MAX;  // default: disabled
    uint64_t keepalive_timeout_ms = 20000;
  };

  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& url, const KeepAliveOptions& keepalive,
      bool verbose = false);

  Error IsServerLive(bool* live, const Headers& headers = {});
  Error IsServerReady(bool* ready, const Headers& headers = {});
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "", const Headers& headers = {});

  Error ServerMetadata(
      inference::ServerMetadataResponse* server_metadata,
      const Headers& headers = {});
  Error ModelMetadata(
      inference::ModelMetadataResponse* model_metadata,
      const std::string& model_name, const std::string& model_version = "",
      const Headers& headers = {});
  Error ModelConfig(
      inference::ModelConfigResponse* model_config,
      const std::string& model_name, const std::string& model_version = "",
      const Headers& headers = {});
  Error ModelRepositoryIndex(
      inference::RepositoryIndexResponse* repository_index,
      const Headers& headers = {});
  Error LoadModel(
      const std::string& model_name, const Headers& headers = {},
      const std::string& config = "");
  Error UnloadModel(const std::string& model_name, const Headers& headers = {});
  Error ModelInferenceStatistics(
      inference::ModelStatisticsResponse* infer_stat,
      const std::string& model_name = "", const std::string& model_version = "",
      const Headers& headers = {});

  Error UpdateTraceSettings(
      inference::TraceSettingResponse* response,
      const std::string& model_name = "",
      const std::map<std::string, std::vector<std::string>>& settings = {},
      const Headers& headers = {});
  Error GetTraceSettings(
      inference::TraceSettingResponse* settings,
      const std::string& model_name = "", const Headers& headers = {});

  Error SystemSharedMemoryStatus(
      inference::SystemSharedMemoryStatusResponse* status,
      const std::string& region_name = "", const Headers& headers = {});
  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0, const Headers& headers = {});
  Error UnregisterSystemSharedMemory(
      const std::string& name = "", const Headers& headers = {});

  // TPU HBM arena regions (replace Register/UnregisterCudaSharedMemory,
  // grpc_client.cc:1023,1058).
  Error TpuSharedMemoryStatus(
      inference::TpuSharedMemoryStatusResponse* status,
      const std::string& region_name = "", const Headers& headers = {});
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int64_t device_id, size_t byte_size, const Headers& headers = {});
  Error UnregisterTpuSharedMemory(
      const std::string& name = "", const Headers& headers = {});

  // grpc_compression ("gzip"/"deflate"/"" ) compresses request
  // messages per the gRPC wire spec (parity: the reference's
  // grpc_compression_algorithm argument).
  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = {},
      const std::string& grpc_compression = "");
  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = {},
      const std::string& grpc_compression = "");
  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const Headers& headers = {});
  Error AsyncInferMulti(
      OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const Headers& headers = {});

  // Decoupled bidi streaming (parity: grpc_client.cc:1323-1416).
  Error StartStream(
      OnCompleteFn callback, bool enable_stats = true,
      uint32_t stream_timeout = 0, const Headers& headers = {});
  Error StopStream();
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});

  // Marshals options/inputs/outputs into the request proto (parity:
  // PreRunProcessing, grpc_client.cc:1419). Static and public so
  // non-RPC consumers (the in-process perf backend) can build the
  // same request protos without a connection.
  static Error PreRunProcessing(
      inference::ModelInferRequest* request, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs);

 private:
  InferenceServerGrpcClient(bool verbose);

  // Serializes req, runs the unary RPC, parses into resp.
  Error Rpc(const std::string& method, const google::protobuf::Message& req,
            google::protobuf::Message* resp, const Headers& headers,
            uint64_t timeout_us = 0, RequestTimers* timers = nullptr,
            const std::string& compression = "");

  void DispatchLoop();

 public:
  // Connection identity, for tests/diagnostics of channel sharing.
  const GrpcChannel* RawChannel() const { return channel_.get(); }

 private:
  std::shared_ptr<GrpcChannel> channel_;

  // Completed async results waiting for user-callback dispatch (the
  // worker_ thread from the base class runs DispatchLoop; parity with
  // the reference's AsyncTransfer CQ-drain thread).
  struct Completed {
    OnCompleteFn callback;
    InferResult* result;
  };
  std::deque<Completed> completed_;
  std::atomic<bool> dispatch_started_{false};
  // True when channel_ came from the URL-keyed cache: the destructor
  // must then WAIT for this client's in-flight calls instead of
  // shutting the (shared) connection down under other clients.
  // The tracker is shared into every async callback so its final
  // "done" signal never touches freed client members (the callback
  // may fire on the shared connection's reader thread after this
  // client is gone).
  struct InflightTracker {
    std::mutex mu;
    std::condition_variable cv;
    size_t count = 0;

    void Add() {
      std::lock_guard<std::mutex> lock(mu);
      ++count;
    }
    void Sub() {
      {
        std::lock_guard<std::mutex> lock(mu);
        --count;
      }
      cv.notify_all();
    }
    template <typename Rep, typename Period>
    bool WaitZero(const std::chrono::duration<Rep, Period>& timeout) {
      std::unique_lock<std::mutex> lock(mu);
      return cv.wait_for(lock, timeout, [this] { return count == 0; });
    }
  };
  bool channel_shared_ = false;
  std::shared_ptr<InflightTracker> inflight_ =
      std::make_shared<InflightTracker>();

  // Streaming state.
  std::unique_ptr<GrpcBidiStream> bidi_stream_;
  OnCompleteFn stream_callback_;
  bool stream_stats_ = true;
  std::mutex stream_mutex_;
};

}  // namespace tpuclient
