// Minimal JSON value / parser / writer for the TPU client stack.
//
// The reference links rapidjson (via triton-common TritonJson,
// /root/reference/src/c++/library/http_client.cc); this image has no
// JSON library, so we carry a small self-contained implementation.
// Covers the full KServe-v2 REST surface: objects, arrays, strings
// (with \uXXXX escapes), int64/uint64/double numbers, bool, null.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace tpuclient {
namespace json {

enum class Type { kNull, kBool, kInt, kUint, kDouble, kString, kArray, kObject };

class Value;
using Array = std::vector<Value>;
// Preserves insertion order (KServe binary protocol depends on the
// order of "inputs"/"outputs" entries matching appended raw buffers).
class Object;

class Value {
 public:
  Value() : type_(Type::kNull) {}
  Value(std::nullptr_t) : type_(Type::kNull) {}
  Value(bool b) : type_(Type::kBool), bool_(b) {}
  Value(int i) : type_(Type::kInt), int_(i) {}
  Value(int64_t i) : type_(Type::kInt), int_(i) {}
  Value(uint64_t u) : type_(Type::kUint), uint_(u) {}
  Value(double d) : type_(Type::kDouble), double_(d) {}
  Value(const char* s);
  Value(const std::string& s);
  Value(std::string&& s);
  Value(const Array& a);
  Value(Array&& a);
  Value(const Object& o);
  Value(Object&& o);
  Value(const Value& other);
  Value(Value&& other) noexcept;
  Value& operator=(const Value& other);
  Value& operator=(Value&& other) noexcept;
  ~Value();

  Type type() const { return type_; }
  bool IsNull() const { return type_ == Type::kNull; }
  bool IsBool() const { return type_ == Type::kBool; }
  bool IsNumber() const {
    return type_ == Type::kInt || type_ == Type::kUint ||
           type_ == Type::kDouble;
  }
  bool IsString() const { return type_ == Type::kString; }
  bool IsArray() const { return type_ == Type::kArray; }
  bool IsObject() const { return type_ == Type::kObject; }

  bool AsBool() const;
  int64_t AsInt() const;
  uint64_t AsUint() const;
  double AsDouble() const;
  const std::string& AsString() const;
  const Array& AsArray() const;
  Array& AsArray();
  const Object& AsObject() const;
  Object& AsObject();

  // Object convenience: returns member or null-Value if absent.
  const Value& operator[](const std::string& key) const;
  bool Has(const std::string& key) const;

  std::string Serialize() const;
  void SerializeTo(std::string* out) const;

 private:
  void Destroy();
  void CopyFrom(const Value& other);
  void MoveFrom(Value&& other);

  Type type_;
  union {
    bool bool_;
    int64_t int_;
    uint64_t uint_;
    double double_;
  };
  std::unique_ptr<std::string> str_;
  std::unique_ptr<Array> array_;
  std::unique_ptr<Object> object_;
};

class Object {
 public:
  using Entry = std::pair<std::string, Value>;

  Value& operator[](const std::string& key);
  const Value* Find(const std::string& key) const;
  bool Has(const std::string& key) const { return Find(key) != nullptr; }
  void Set(const std::string& key, Value v);

  std::vector<Entry>& entries() { return entries_; }
  const std::vector<Entry>& entries() const { return entries_; }
  size_t size() const { return entries_.size(); }
  bool empty() const { return entries_.empty(); }

 private:
  std::vector<Entry> entries_;
};

// Parses `text` into `out`. Returns empty string on success, else an
// error description (with byte offset).
std::string Parse(const std::string& text, Value* out);
std::string Parse(const char* data, size_t len, Value* out);

}  // namespace json
}  // namespace tpuclient
