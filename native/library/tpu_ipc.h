// TPU HBM region handle — the TPU analogue of the reference's
// cudaIpcMemHandle_t shim (/root/reference/src/c++/library/ipc.h).
//
// PJRT exposes no cross-process device-pointer handle, so a region
// handle is a logical descriptor minted by the server's HBM arena
// service (client_tpu/server/tpu_arena.py): the server owns the
// jax.Array buffers and clients address them by region id. The raw
// wire form is the JSON descriptor produced by the arena's
// CreateRegion RPC and passed verbatim to
// RegisterTpuSharedMemory (the slot the reference fills with a
// base64 cudaIpcMemHandle_t, http_client.cc:1712).
#pragma once

#include <cstdint>
#include <string>

namespace tpuclient {

struct TpuShmHandle {
  // Opaque region id within the server's arena.
  std::string region_id;
  // Arena instance identity (guards against stale handles after a
  // server restart).
  std::string arena_id;
  uint64_t byte_size = 0;
  int64_t device_ordinal = 0;
  // The serialized descriptor exactly as minted by the server; this
  // is what travels in TpuSharedMemoryRegisterRequest.raw_handle.
  std::string raw;
};

}  // namespace tpuclient
