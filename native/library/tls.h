// TLS client sessions over dlopen'd OpenSSL (libssl.so.3).
//
// Role parity: the reference links grpc++/libcurl which carry TLS
// (SslOptions, grpc_client.h:43; HTTPS via curl). This image ships
// the OpenSSL 3 runtime but no development headers, so — like the
// MPI driver (perf/mpi_utils.h) — the needed symbols are bound at
// runtime and the feature degrades gracefully when the library is
// absent.
#pragma once

#include <cstdint>
#include <string>

namespace tpuclient {

// Mirrors the reference's SslOptions (grpc_client.h:43) for both
// protocol clients.
struct SslOptions {
  // PEM root certificates file ("" = system default verify paths).
  std::string root_certificates;
  // PEM private key + certificate chain for mutual TLS ("" = none).
  std::string private_key;
  std::string certificate_chain;
  // Skip peer verification (self-signed test endpoints).
  bool insecure_skip_verify = false;
};

class TlsSession {
 public:
  TlsSession();
  ~TlsSession();

  TlsSession(const TlsSession&) = delete;
  TlsSession& operator=(const TlsSession&) = delete;

  // True when libssl.so.3 was found and all symbols bound.
  static bool Available();

  // Handshakes over an already-connected NON-BLOCKING socket.
  // `alpn` is an optional protocol name (e.g. "h2" for gRPC).
  // Returns "" on success, else error text.
  std::string Handshake(
      int fd, const std::string& host, const SslOptions& options,
      const std::string& alpn, uint64_t deadline_ns);

  // Encrypted I/O over the handshaken socket. Semantics match
  // send/recv on a non-blocking fd: Write sends everything or
  // errors; Read returns >0 bytes, 0 on clean EOF, <0 with *err set.
  std::string Write(const char* data, size_t len, uint64_t deadline_ns);
  int64_t Read(char* buf, size_t len, uint64_t deadline_ns,
               std::string* err);

  void Close();
  bool active() const { return ssl_ != nullptr; }

 private:
  void* ctx_ = nullptr;  // SSL_CTX*
  void* ssl_ = nullptr;  // SSL*
  int fd_ = -1;
};

}  // namespace tpuclient
