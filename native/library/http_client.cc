#include "http_client.h"

#include <string.h>

#include <algorithm>
#include <chrono>
#include <cmath>
#include <limits>
#include <sstream>

#include "base64.h"
#include "http_transport.h"

namespace tpuclient {

namespace {

// URI builders matching the server routes
// (client_tpu/http/_endpoints.py — single source of truth for /v2).
std::string ModelPath(const std::string& name, const std::string& version) {
  std::string p = "/v2/models/" + name;
  if (!version.empty()) p += "/versions/" + version;
  return p;
}

std::string AppendQuery(std::string path, const Parameters& query_params) {
  bool first = true;
  for (const auto& q : query_params) {
    path += (first ? "?" : "&");
    path += q.first + "=" + q.second;
    first = false;
  }
  return path;
}

Error ErrorFromResponse(const HttpResponse& response) {
  if (response.status_code >= 200 && response.status_code < 300) {
    return Error::Success;
  }
  json::Value parsed;
  std::string detail = response.body;
  if (json::Parse(response.body, &parsed).empty() && parsed.Has("error")) {
    detail = parsed["error"].AsString();
  }
  return Error(
      "HTTP " + std::to_string(response.status_code) + ": " + detail);
}

json::Value ParamValue(const std::string& s) { return json::Value(s); }

// float -> IEEE half with round-to-nearest (for FP16 JSON outputs).
uint16_t HalfFromFloat(float f) {
  uint32_t bits;
  memcpy(&bits, &f, 4);
  const uint16_t sign = static_cast<uint16_t>((bits >> 16) & 0x8000);
  const uint32_t exp8 = (bits >> 23) & 0xff;
  uint32_t frac = bits & 0x7fffff;
  if (exp8 == 0xff) {  // inf / nan
    return sign | 0x7c00 | (frac ? 0x200 : 0);
  }
  const int32_t exp = static_cast<int32_t>(exp8) - 127 + 15;
  if (exp >= 31) return sign | 0x7c00;  // overflow -> inf
  if (exp <= 0) {
    if (exp < -10) return sign;  // underflow -> signed zero
    frac |= 0x800000;            // make the implicit bit explicit
    const uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint16_t sub = static_cast<uint16_t>(frac >> shift);
    if ((frac >> (shift - 1)) & 1) ++sub;  // round to nearest
    return sign | sub;
  }
  uint16_t h =
      sign | static_cast<uint16_t>(exp << 10) | static_cast<uint16_t>(
                                                    frac >> 13);
  if (frac & 0x1000) ++h;  // round to nearest
  return h;
}

// JSON "data" array -> packed wire bytes per datatype (so RawData()
// behaves identically whether the server answered binary or JSON).
// May throw (json accessors throw on type mismatches); the caller
// converts to an Error.
Error RawFromJsonData(const json::Value& data, const std::string& datatype,
                      std::string* out) {
  auto append = [out](const void* p, size_t n) {
    out->append(reinterpret_cast<const char*>(p), n);
  };
  for (const auto& v : data.AsArray()) {
    if (datatype == "BOOL") {
      uint8_t b = v.AsBool() ? 1 : 0;
      append(&b, 1);
    } else if (datatype == "INT8") {
      int8_t x = static_cast<int8_t>(v.AsInt());
      append(&x, 1);
    } else if (datatype == "INT16") {
      int16_t x = static_cast<int16_t>(v.AsInt());
      append(&x, 2);
    } else if (datatype == "INT32") {
      int32_t x = static_cast<int32_t>(v.AsInt());
      append(&x, 4);
    } else if (datatype == "INT64") {
      int64_t x = v.AsInt();
      append(&x, 8);
    } else if (datatype == "UINT8") {
      uint8_t x = static_cast<uint8_t>(v.AsUint());
      append(&x, 1);
    } else if (datatype == "UINT16") {
      uint16_t x = static_cast<uint16_t>(v.AsUint());
      append(&x, 2);
    } else if (datatype == "UINT32") {
      uint32_t x = static_cast<uint32_t>(v.AsUint());
      append(&x, 4);
    } else if (datatype == "UINT64") {
      uint64_t x = v.AsUint();
      append(&x, 8);
    } else if (datatype == "FP32") {
      float x = static_cast<float>(v.AsDouble());
      append(&x, 4);
    } else if (datatype == "FP64") {
      double x = v.AsDouble();
      append(&x, 8);
    } else if (datatype == "BF16") {
      float f = static_cast<float>(v.AsDouble());
      uint32_t bits;
      memcpy(&bits, &f, 4);
      uint16_t h = static_cast<uint16_t>(bits >> 16);
      append(&h, 2);
    } else if (datatype == "FP16") {
      uint16_t h = HalfFromFloat(static_cast<float>(v.AsDouble()));
      append(&h, 2);
    } else if (datatype == "BYTES") {
      const std::string& s = v.AsString();
      uint32_t len = static_cast<uint32_t>(s.size());
      uint8_t prefix[4] = {static_cast<uint8_t>(len),
                           static_cast<uint8_t>(len >> 8),
                           static_cast<uint8_t>(len >> 16),
                           static_cast<uint8_t>(len >> 24)};
      append(prefix, 4);
      out->append(s);
    } else {
      return Error("JSON output datatype '" + datatype +
                   "' has no wire packing");
    }
  }
  return Error::Success;
}

}  // namespace

//==============================================================================
// InferResultHttp

Error InferResultHttp::Create(
    InferResult** result, std::string&& body, size_t header_length,
    const Error& request_status) {
  auto* r = new InferResultHttp();
  r->status_ = request_status;
  r->body_ = std::move(body);
  size_t json_end = (header_length != 0) ? header_length : r->body_.size();
  if (!request_status.IsOk()) {
    *result = r;
    return Error::Success;
  }
  if (json_end > r->body_.size()) {
    // Never trust the server's Inference-Header-Content-Length.
    r->status_ = Error("response header length exceeds body size");
    *result = r;
    return Error::Success;
  }
  std::string err =
      json::Parse(r->body_.data(), json_end, &r->header_);
  if (!err.empty()) {
    r->status_ = Error("failed to parse inference response: " + err);
    *result = r;
    return Error::Success;
  }
  // JSON accessors throw on shape mismatches; convert any
  // unexpected-shape response into an error status instead of
  // letting the exception escape (it would terminate async workers).
  try {
    const uint8_t* base = reinterpret_cast<const uint8_t*>(r->body_.data());
    size_t binary_offset = json_end;
    if (r->header_.Has("outputs")) {
      for (const auto& entry : r->header_["outputs"].AsArray()) {
        Output out;
        const std::string& name = entry["name"].AsString();
        if (entry.Has("datatype")) out.datatype = entry["datatype"].AsString();
        if (entry.Has("shape")) {
          for (const auto& d : entry["shape"].AsArray()) {
            out.shape.push_back(d.AsInt());
          }
        }
        const json::Value& params = entry["parameters"];
        if (params.Has("shared_memory_region")) {
          out.in_shm = true;
        } else if (params.Has("binary_data_size")) {
          size_t size = params["binary_data_size"].AsUint();
          // Overflow-safe bounds check (binary_offset <= body size).
          if (size > r->body_.size() - binary_offset) {
            r->status_ = Error("binary output '" + name + "' truncated");
            break;
          }
          out.raw = base + binary_offset;
          out.raw_size = size;
          binary_offset += size;
        } else if (entry.Has("data")) {
          out.json_data = entry["data"];
        }
        r->outputs_.emplace(name, std::move(out));
      }
    }
  } catch (const std::exception& e) {
    r->status_ = Error(
        std::string("malformed inference response: ") + e.what());
  }
  *result = r;
  return Error::Success;
}

Error InferResultHttp::ModelName(std::string* name) const {
  if (!status_.IsOk()) return status_;
  *name = header_["model_name"].IsString() ? header_["model_name"].AsString()
                                           : "";
  return Error::Success;
}

Error InferResultHttp::ModelVersion(std::string* version) const {
  if (!status_.IsOk()) return status_;
  *version = header_["model_version"].IsString()
                 ? header_["model_version"].AsString()
                 : "";
  return Error::Success;
}

Error InferResultHttp::Id(std::string* id) const {
  if (!status_.IsOk()) return status_;
  *id = header_["id"].IsString() ? header_["id"].AsString() : "";
  return Error::Success;
}

Error InferResultHttp::FindOutput(
    const std::string& name, const Output** out) const {
  if (!status_.IsOk()) return status_;
  auto it = outputs_.find(name);
  if (it == outputs_.end()) {
    return Error("output '" + name + "' not found in response");
  }
  *out = &it->second;
  return Error::Success;
}

Error InferResultHttp::Shape(
    const std::string& output_name, std::vector<int64_t>* shape) const {
  const Output* out;
  Error err = FindOutput(output_name, &out);
  if (!err.IsOk()) return err;
  *shape = out->shape;
  return Error::Success;
}

Error InferResultHttp::Datatype(
    const std::string& output_name, std::string* datatype) const {
  const Output* out;
  Error err = FindOutput(output_name, &out);
  if (!err.IsOk()) return err;
  *datatype = out->datatype;
  return Error::Success;
}

Error InferResultHttp::RawData(
    const std::string& output_name, const uint8_t** buf,
    size_t* byte_size) const {
  const Output* out;
  Error err = FindOutput(output_name, &out);
  if (!err.IsOk()) return err;
  if (out->in_shm) {
    return Error(
        "output '" + output_name +
        "' is in shared memory; read it from the region");
  }
  if (out->raw != nullptr) {
    *buf = out->raw;
    *byte_size = out->raw_size;
    return Error::Success;
  }
  if (out->json_data.IsArray()) {
    // JSON-format output: pack once, then serve the cached bytes.
    // json accessors throw on malformed server data (nested arrays,
    // wrong element types) — convert to an Error so nothing escapes
    // an async worker (same invariant as the response parser above).
    if (!out->decode_attempted) {
      out->decode_attempted = true;
      Error perr = Error::Success;
      try {
        perr = RawFromJsonData(out->json_data, out->datatype,
                               &out->decoded);
      } catch (const std::exception& e) {
        perr = Error(std::string("malformed JSON output data: ") + e.what());
      }
      if (!perr.IsOk()) {
        out->decoded.clear();
        return perr;
      }
    }
    if (!out->decoded.empty() || out->json_data.AsArray().empty()) {
      *buf = reinterpret_cast<const uint8_t*>(out->decoded.data());
      *byte_size = out->decoded.size();
      return Error::Success;
    }
  }
  return Error(
      "output '" + output_name +
      "' was returned as JSON data that could not be packed");
}

Error InferResultHttp::StringData(
    const std::string& output_name,
    std::vector<std::string>* string_result) const {
  const Output* out;
  Error err = FindOutput(output_name, &out);
  if (!err.IsOk()) return err;
  string_result->clear();
  if (out->raw != nullptr) {
    // BYTES wire format: 4-byte LE length prefix per element.
    size_t pos = 0;
    while (pos + 4 <= out->raw_size) {
      uint32_t len = static_cast<uint32_t>(out->raw[pos]) |
                     (static_cast<uint32_t>(out->raw[pos + 1]) << 8) |
                     (static_cast<uint32_t>(out->raw[pos + 2]) << 16) |
                     (static_cast<uint32_t>(out->raw[pos + 3]) << 24);
      pos += 4;
      if (pos + len > out->raw_size) {
        return Error("malformed BYTES output '" + output_name + "'");
      }
      string_result->emplace_back(
          reinterpret_cast<const char*>(out->raw + pos), len);
      pos += len;
    }
    return Error::Success;
  }
  if (out->json_data.IsArray()) {
    for (const auto& v : out->json_data.AsArray()) {
      string_result->push_back(v.IsString() ? v.AsString() : v.Serialize());
    }
    return Error::Success;
  }
  return Error("output '" + output_name + "' has no string data");
}

std::string InferResultHttp::DebugString() const {
  if (!status_.IsOk()) return "error: " + status_.Message();
  return header_.Serialize();
}

Error InferResultHttp::RequestStatus() const { return status_; }

//==============================================================================
// InferenceServerHttpClient

Error InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client, const std::string& url,
    bool verbose) {
  return Create(client, url, SslOptions(), verbose);
}

Error InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client, const std::string& url,
    const SslOptions& ssl_options, bool verbose) {
  client->reset(new InferenceServerHttpClient(url, ssl_options, verbose));
  if ((*client)->port_ == 0) {
    client->reset();
    return Error("invalid url '" + url + "': expected host:port");
  }
  if ((*client)->use_tls_ && !TlsSession::Available()) {
    client->reset();
    return Error("https requested but libssl.so.3 is unavailable");
  }
  return Error::Success;
}

InferenceServerHttpClient::InferenceServerHttpClient(
    const std::string& url, const SslOptions& ssl_options, bool verbose)
    : InferenceServerClient(verbose), ssl_options_(ssl_options) {
  // Strip optional scheme ("https://" selects TLS).
  std::string rest = url;
  size_t scheme = rest.find("://");
  if (scheme != std::string::npos) {
    use_tls_ = rest.compare(0, scheme, "https") == 0;
    rest = rest.substr(scheme + 3);
  }
  size_t colon = rest.rfind(':');
  if (colon != std::string::npos) {
    host_ = rest.substr(0, colon);
    port_ = atoi(rest.c_str() + colon + 1);
  } else {
    host_ = rest;
    port_ = use_tls_ ? 443 : 8000;
  }
  sync_conn_.reset(new HttpConnection(host_, port_, use_tls_, ssl_options_));
}

InferenceServerHttpClient::~InferenceServerHttpClient() {
  {
    std::lock_guard<std::mutex> lk(async_mutex_);
    async_exiting_ = true;
  }
  async_cv_.notify_all();
  for (auto& w : async_workers_) {
    if (w.joinable()) w.join();
  }
}

Error InferenceServerHttpClient::DoRequest(
    const std::string& method, const std::string& path,
    const std::string& body, const Headers& headers,
    const std::string& content_type, size_t json_header_length,
    std::string* response_body, size_t* response_header_length,
    HttpConnection* conn, uint64_t timeout_us, uint64_t* sent_ns) {
  std::map<std::string, std::string> hdrs(headers.begin(), headers.end());
  if (!content_type.empty()) hdrs["Content-Type"] = content_type;
  if (json_header_length != 0) {
    hdrs["Inference-Header-Content-Length"] =
        std::to_string(json_header_length);
  }
  HttpResponse response;
  auto call_start = std::chrono::steady_clock::now();
  std::string terr =
      conn->Request(method, path, hdrs, body, &response, timeout_us, sent_ns);
  if (!terr.empty()) return Error(terr);
  if (timeout_us > 0) {
    // Deadline semantics match the gRPC client: finishing after the
    // deadline is a timeout even if the bounded wait won the race.
    auto elapsed_us = std::chrono::duration_cast<std::chrono::microseconds>(
                          std::chrono::steady_clock::now() - call_start)
                          .count();
    if (static_cast<uint64_t>(elapsed_us) > timeout_us) {
      return Error("timeout: request exceeded client deadline");
    }
  }
  Error err = ErrorFromResponse(response);
  if (!err.IsOk()) return err;
  if (response_header_length != nullptr) {
    auto it = response.headers.find("inference-header-content-length");
    *response_header_length =
        (it != response.headers.end())
            ? strtoull(it->second.c_str(), nullptr, 10)
            : 0;
  }
  auto enc = response.headers.find("content-encoding");
  if (enc != response.headers.end()) {
    return DecompressBody(enc->second, response.body, response_body);
  }
  *response_body = std::move(response.body);
  return Error::Success;
}

Error InferenceServerHttpClient::Get(
    const std::string& path, const Headers& headers, std::string* response,
    json::Value* parsed) {
  std::lock_guard<std::mutex> lk(sync_mutex_);
  std::string body;
  Error err = DoRequest(
      "GET", path, "", headers, "", 0, &body, nullptr, sync_conn_.get(), 0);
  if (!err.IsOk()) return err;
  if (parsed != nullptr && !body.empty()) {
    std::string jerr = json::Parse(body, parsed);
    if (!jerr.empty()) return Error(jerr);
  }
  if (response != nullptr) *response = std::move(body);
  return Error::Success;
}

Error InferenceServerHttpClient::Post(
    const std::string& path, const std::string& body, const Headers& headers,
    std::string* response, json::Value* parsed) {
  std::lock_guard<std::mutex> lk(sync_mutex_);
  std::string response_body;
  Error err = DoRequest(
      "POST", path, body, headers, "application/json", 0, &response_body,
      nullptr, sync_conn_.get(), 0);
  if (!err.IsOk()) return err;
  if (parsed != nullptr && !response_body.empty()) {
    std::string jerr = json::Parse(response_body, parsed);
    if (!jerr.empty()) return Error(jerr);
  }
  if (response != nullptr) *response = std::move(response_body);
  return Error::Success;
}

Error InferenceServerHttpClient::IsServerLive(bool* live, const Headers& headers) {
  Error err = Get("/v2/health/live", headers, nullptr, nullptr);
  *live = err.IsOk();
  if (!err.IsOk() && err.Message().rfind("HTTP", 0) != 0) return err;
  return Error::Success;
}

Error InferenceServerHttpClient::IsServerReady(bool* ready, const Headers& headers) {
  Error err = Get("/v2/health/ready", headers, nullptr, nullptr);
  *ready = err.IsOk();
  if (!err.IsOk() && err.Message().rfind("HTTP", 0) != 0) return err;
  return Error::Success;
}

Error InferenceServerHttpClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  Error err = Get(
      ModelPath(model_name, model_version) + "/ready", headers, nullptr,
      nullptr);
  *ready = err.IsOk();
  if (!err.IsOk() && err.Message().rfind("HTTP", 0) != 0) return err;
  return Error::Success;
}

Error InferenceServerHttpClient::ServerMetadata(
    std::string* server_metadata, const Headers& headers) {
  return Get("/v2", headers, server_metadata, nullptr);
}

Error InferenceServerHttpClient::ModelMetadata(
    std::string* model_metadata, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  return Get(
      ModelPath(model_name, model_version), headers, model_metadata, nullptr);
}

Error InferenceServerHttpClient::ModelConfig(
    std::string* model_config, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  return Get(
      ModelPath(model_name, model_version) + "/config", headers, model_config,
      nullptr);
}

Error InferenceServerHttpClient::ModelRepositoryIndex(
    std::string* repository_index, const Headers& headers) {
  return Post("/v2/repository/index", "{}", headers, repository_index, nullptr);
}

Error InferenceServerHttpClient::LoadModel(
    const std::string& model_name, const Headers& headers,
    const std::string& config) {
  std::string body = "{}";
  if (!config.empty()) {
    json::Object params;
    params["config"] = json::Value(config);
    json::Object root;
    root["parameters"] = json::Value(std::move(params));
    body = json::Value(std::move(root)).Serialize();
  }
  return Post(
      "/v2/repository/models/" + model_name + "/load", body, headers, nullptr,
      nullptr);
}

Error InferenceServerHttpClient::UnloadModel(
    const std::string& model_name, const Headers& headers) {
  return Post(
      "/v2/repository/models/" + model_name + "/unload", "{}", headers,
      nullptr, nullptr);
}

Error InferenceServerHttpClient::ModelInferenceStatistics(
    std::string* infer_stat, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  std::string path = model_name.empty()
                         ? "/v2/models/stats"
                         : ModelPath(model_name, model_version) + "/stats";
  return Get(path, headers, infer_stat, nullptr);
}

Error InferenceServerHttpClient::UpdateTraceSettings(
    std::string* response, const std::string& model_name,
    const std::map<std::string, std::vector<std::string>>& settings,
    const Headers& headers) {
  json::Object obj;
  for (const auto& s : settings) {
    json::Array values;
    for (const auto& v : s.second) values.push_back(ParamValue(v));
    obj[s.first] = json::Value(std::move(values));
  }
  std::string path = model_name.empty()
                         ? "/v2/trace/setting"
                         : "/v2/models/" + model_name + "/trace/setting";
  return Post(
      path, json::Value(std::move(obj)).Serialize(), headers, response,
      nullptr);
}

Error InferenceServerHttpClient::GetTraceSettings(
    std::string* settings, const std::string& model_name,
    const Headers& headers) {
  std::string path = model_name.empty()
                         ? "/v2/trace/setting"
                         : "/v2/models/" + model_name + "/trace/setting";
  return Get(path, headers, settings, nullptr);
}

Error InferenceServerHttpClient::UpdateLogSettings(
    std::string* response, const std::map<std::string, std::string>& settings,
    const Headers& headers) {
  json::Object obj;
  for (const auto& s : settings) obj[s.first] = json::Value(s.second);
  return Post(
      "/v2/logging", json::Value(std::move(obj)).Serialize(), headers,
      response, nullptr);
}

Error InferenceServerHttpClient::GetLogSettings(
    std::string* settings, const Headers& headers) {
  return Get("/v2/logging", headers, settings, nullptr);
}

Error InferenceServerHttpClient::SystemSharedMemoryStatus(
    std::string* status, const std::string& region_name,
    const Headers& headers) {
  std::string path =
      region_name.empty()
          ? "/v2/systemsharedmemory/status"
          : "/v2/systemsharedmemory/region/" + region_name + "/status";
  return Get(path, headers, status, nullptr);
}

Error InferenceServerHttpClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset, const Headers& headers) {
  json::Object obj;
  obj["key"] = json::Value(key);
  obj["offset"] = json::Value(static_cast<uint64_t>(offset));
  obj["byte_size"] = json::Value(static_cast<uint64_t>(byte_size));
  return Post(
      "/v2/systemsharedmemory/region/" + name + "/register",
      json::Value(std::move(obj)).Serialize(), headers, nullptr, nullptr);
}

Error InferenceServerHttpClient::UnregisterSystemSharedMemory(
    const std::string& name, const Headers& headers) {
  std::string path =
      name.empty() ? "/v2/systemsharedmemory/unregister"
                   : "/v2/systemsharedmemory/region/" + name + "/unregister";
  return Post(path, "{}", headers, nullptr, nullptr);
}

Error InferenceServerHttpClient::TpuSharedMemoryStatus(
    std::string* status, const std::string& region_name,
    const Headers& headers) {
  std::string path =
      region_name.empty()
          ? "/v2/tpusharedmemory/status"
          : "/v2/tpusharedmemory/region/" + region_name + "/status";
  return Get(path, headers, status, nullptr);
}

Error InferenceServerHttpClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle, int64_t device_id,
    size_t byte_size, const Headers& headers) {
  // Wire shape parity with the reference's CUDA register
  // (http_client.cc:1712): {"raw_handle": {"b64": ...}, "device_id":
  // N, "byte_size": N}, with the TPU arena descriptor in the b64 slot.
  json::Object handle;
  handle["b64"] = json::Value(Base64Encode(raw_handle));
  json::Object obj;
  obj["raw_handle"] = json::Value(std::move(handle));
  obj["device_id"] = json::Value(static_cast<int64_t>(device_id));
  obj["byte_size"] = json::Value(static_cast<uint64_t>(byte_size));
  return Post(
      "/v2/tpusharedmemory/region/" + name + "/register",
      json::Value(std::move(obj)).Serialize(), headers, nullptr, nullptr);
}

Error InferenceServerHttpClient::UnregisterTpuSharedMemory(
    const std::string& name, const Headers& headers) {
  std::string path =
      name.empty() ? "/v2/tpusharedmemory/unregister"
                   : "/v2/tpusharedmemory/region/" + name + "/unregister";
  return Post(path, "{}", headers, nullptr, nullptr);
}

//==============================================================================
// Inference request body

namespace {

double HalfToDouble(uint16_t h) {
  const uint32_t sign = (h >> 15) & 0x1;
  const uint32_t exp = (h >> 10) & 0x1f;
  const uint32_t frac = h & 0x3ff;
  double value;
  if (exp == 0) {
    value = std::ldexp(static_cast<double>(frac), -24);  // subnormal
  } else if (exp == 31) {
    value = frac == 0 ? std::numeric_limits<double>::infinity()
                      : std::numeric_limits<double>::quiet_NaN();
  } else {
    value = std::ldexp(1.0 + frac / 1024.0, static_cast<int>(exp) - 15);
  }
  return sign ? -value : value;
}

// Contiguous raw tensor bytes -> JSON "data" array per datatype
// (inverse of the server's JSON-tensor decode; used for
// --input-tensor-format json / binary_data=false interop).
Error JsonDataFromRaw(const std::string& datatype, const uint8_t* data,
                      size_t byte_size, json::Array* out) {
  auto pack_ints = [&](auto typed, size_t width) {
    using T = decltype(typed);
    for (size_t pos = 0; pos + width <= byte_size; pos += width) {
      T v;
      memcpy(&v, data + pos, width);
      out->push_back(json::Value(static_cast<int64_t>(v)));
    }
  };
  auto pack_uints = [&](auto typed, size_t width) {
    using T = decltype(typed);
    for (size_t pos = 0; pos + width <= byte_size; pos += width) {
      T v;
      memcpy(&v, data + pos, width);
      out->push_back(json::Value(static_cast<uint64_t>(v)));
    }
  };
  if (datatype == "BOOL") {
    for (size_t i = 0; i < byte_size; ++i) {
      out->push_back(json::Value(data[i] != 0));
    }
  } else if (datatype == "INT8") {
    pack_ints(int8_t{}, 1);
  } else if (datatype == "INT16") {
    pack_ints(int16_t{}, 2);
  } else if (datatype == "INT32") {
    pack_ints(int32_t{}, 4);
  } else if (datatype == "INT64") {
    pack_ints(int64_t{}, 8);
  } else if (datatype == "UINT8") {
    pack_uints(uint8_t{}, 1);
  } else if (datatype == "UINT16") {
    pack_uints(uint16_t{}, 2);
  } else if (datatype == "UINT32") {
    pack_uints(uint32_t{}, 4);
  } else if (datatype == "UINT64") {
    pack_uints(uint64_t{}, 8);
  } else if (datatype == "FP32") {
    for (size_t pos = 0; pos + 4 <= byte_size; pos += 4) {
      float v;
      memcpy(&v, data + pos, 4);
      out->push_back(json::Value(static_cast<double>(v)));
    }
  } else if (datatype == "FP64") {
    for (size_t pos = 0; pos + 8 <= byte_size; pos += 8) {
      double v;
      memcpy(&v, data + pos, 8);
      out->push_back(json::Value(v));
    }
  } else if (datatype == "FP16") {
    for (size_t pos = 0; pos + 2 <= byte_size; pos += 2) {
      uint16_t v;
      memcpy(&v, data + pos, 2);
      out->push_back(json::Value(HalfToDouble(v)));
    }
  } else if (datatype == "BF16") {
    for (size_t pos = 0; pos + 2 <= byte_size; pos += 2) {
      uint16_t v;
      memcpy(&v, data + pos, 2);
      uint32_t bits = static_cast<uint32_t>(v) << 16;
      float f;
      memcpy(&f, &bits, 4);
      out->push_back(json::Value(static_cast<double>(f)));
    }
  } else if (datatype == "BYTES") {
    size_t pos = 0;
    while (pos + 4 <= byte_size) {
      uint32_t len = static_cast<uint32_t>(data[pos]) |
                     (static_cast<uint32_t>(data[pos + 1]) << 8) |
                     (static_cast<uint32_t>(data[pos + 2]) << 16) |
                     (static_cast<uint32_t>(data[pos + 3]) << 24);
      pos += 4;
      if (pos + len > byte_size) {
        return Error("malformed BYTES input for JSON tensor data");
      }
      out->push_back(json::Value(
          std::string(reinterpret_cast<const char*>(data + pos), len)));
      pos += len;
    }
  } else {
    return Error("datatype '" + datatype +
                 "' has no JSON tensor representation");
  }
  return Error::Success;
}

}  // namespace

Error InferenceServerHttpClient::GenerateRequestBodyStr(
    std::string* request_body, size_t* header_length,
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  json::Object root;
  if (!options.request_id.empty()) {
    root["id"] = json::Value(options.request_id);
  }

  json::Object params;
  if (options.sequence_id != 0) {
    params["sequence_id"] = json::Value(options.sequence_id);
    params["sequence_start"] = json::Value(options.sequence_start);
    params["sequence_end"] = json::Value(options.sequence_end);
  }
  if (options.priority != 0) {
    params["priority"] = json::Value(options.priority);
  }
  if (options.server_timeout_us != 0) {
    params["timeout"] = json::Value(options.server_timeout_us);
  }
  for (const auto& p : options.string_params) {
    params[p.first] = json::Value(p.second);
  }
  for (const auto& p : options.int_params) {
    params[p.first] = json::Value(p.second);
  }
  for (const auto& p : options.bool_params) {
    params[p.first] = json::Value(p.second);
  }
  for (const auto& p : options.double_params) {
    params[p.first] = json::Value(p.second);
  }
  if (outputs.empty()) {
    // No explicit outputs: state the desired format for all outputs
    // (parity: reference http _get_inference_request
    // binary_data_output, http/_utils.py:115; false = JSON data).
    params["binary_data_output"] = json::Value(options.binary_data_output);
  }
  if (!params.empty()) {
    root["parameters"] = json::Value(std::move(params));
  }

  // Inputs: shm regions ride as parameters; raw tensors append to the
  // binary section in declaration order.
  std::vector<const InferInput*> binary_inputs;
  json::Array input_entries;
  for (InferInput* input : inputs) {
    json::Object entry;
    entry["name"] = json::Value(input->Name());
    json::Array shape;
    for (int64_t d : input->Shape()) shape.push_back(json::Value(d));
    entry["shape"] = json::Value(std::move(shape));
    entry["datatype"] = json::Value(input->Datatype());
    json::Object tensor_params;
    if (input->IsSharedMemory()) {
      std::string region;
      size_t byte_size, shm_offset;
      input->SharedMemoryInfo(&region, &byte_size, &shm_offset);
      tensor_params["shared_memory_region"] = json::Value(region);
      tensor_params["shared_memory_byte_size"] =
          json::Value(static_cast<uint64_t>(byte_size));
      if (shm_offset != 0) {
        tensor_params["shared_memory_offset"] =
            json::Value(static_cast<uint64_t>(shm_offset));
      }
    } else if (options.json_input_data) {
      // JSON tensor data: collect the (possibly chunked) raw bytes
      // and encode them as a flat "data" array.
      std::string raw;
      raw.reserve(input->ByteSize());
      input->PrepareForRequest();
      const uint8_t* buf;
      size_t len;
      while (input->GetNext(&buf, &len)) {
        raw.append(reinterpret_cast<const char*>(buf), len);
      }
      json::Array data;
      Error jerr = JsonDataFromRaw(
          input->Datatype(), reinterpret_cast<const uint8_t*>(raw.data()),
          raw.size(), &data);
      if (!jerr.IsOk()) return jerr;
      entry["data"] = json::Value(std::move(data));
    } else {
      tensor_params["binary_data_size"] =
          json::Value(static_cast<uint64_t>(input->ByteSize()));
      binary_inputs.push_back(input);
    }
    entry["parameters"] = json::Value(std::move(tensor_params));
    input_entries.push_back(json::Value(std::move(entry)));
  }
  root["inputs"] = json::Value(std::move(input_entries));

  if (!outputs.empty()) {
    json::Array output_entries;
    for (const InferRequestedOutput* output : outputs) {
      json::Object entry;
      entry["name"] = json::Value(output->Name());
      json::Object tensor_params;
      if (output->IsSharedMemory()) {
        std::string region;
        size_t byte_size, shm_offset;
        output->SharedMemoryInfo(&region, &byte_size, &shm_offset);
        tensor_params["shared_memory_region"] = json::Value(region);
        tensor_params["shared_memory_byte_size"] =
            json::Value(static_cast<uint64_t>(byte_size));
        if (shm_offset != 0) {
          tensor_params["shared_memory_offset"] =
              json::Value(static_cast<uint64_t>(shm_offset));
        }
      } else {
        tensor_params["binary_data"] = json::Value(output->BinaryData());
      }
      if (output->ClassCount() != 0) {
        tensor_params["classification"] =
            json::Value(static_cast<uint64_t>(output->ClassCount()));
      }
      entry["parameters"] = json::Value(std::move(tensor_params));
      output_entries.push_back(json::Value(std::move(entry)));
    }
    root["outputs"] = json::Value(std::move(output_entries));
  }

  std::string json_text = json::Value(std::move(root)).Serialize();
  *header_length = json_text.size();

  size_t total = json_text.size();
  for (const InferInput* input : binary_inputs) {
    total += input->ByteSize();
  }
  request_body->clear();
  request_body->reserve(total);
  request_body->append(json_text);
  for (const InferInput* input : binary_inputs) {
    const_cast<InferInput*>(input)->PrepareForRequest();
    const uint8_t* buf;
    size_t len;
    while (const_cast<InferInput*>(input)->GetNext(&buf, &len)) {
      request_body->append(reinterpret_cast<const char*>(buf), len);
    }
  }
  return Error::Success;
}

Error InferenceServerHttpClient::GenerateRequestBody(
    std::vector<char>* request_body, size_t* header_length,
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  std::string body;
  Error err =
      GenerateRequestBodyStr(&body, header_length, options, inputs, outputs);
  if (!err.IsOk()) return err;
  request_body->assign(body.begin(), body.end());
  return Error::Success;
}

Error InferenceServerHttpClient::ParseResponseBody(
    InferResult** result, std::vector<char>&& response_body,
    size_t header_length) {
  std::string body(response_body.data(), response_body.size());
  return InferResultHttp::Create(result, std::move(body), header_length);
}

Error InferenceServerHttpClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, const Parameters& query_params,
    CompressionType request_compression,
    CompressionType response_compression) {
  RequestTimers timers;
  timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);

  std::string body;
  size_t header_length = 0;
  Error err = GenerateRequestBodyStr(&body, &header_length, options, inputs,
                                     outputs);
  if (!err.IsOk()) return err;

  Headers call_headers = headers;
  if (request_compression != CompressionType::NONE) {
    std::string compressed;
    err = CompressBody(request_compression, body, &compressed);
    if (!err.IsOk()) return err;
    body = std::move(compressed);
    call_headers["Content-Encoding"] = CompressionName(request_compression);
  }
  if (response_compression != CompressionType::NONE) {
    call_headers["Accept-Encoding"] = CompressionName(response_compression);
  }

  std::string path = AppendQuery(
      ModelPath(options.model_name, options.model_version) + "/infer",
      query_params);

  timers.CaptureTimestamp(RequestTimers::Kind::SEND_START);
  std::string response_body;
  size_t response_header_length = 0;
  uint64_t sent_ns = 0;
  {
    std::lock_guard<std::mutex> lk(sync_mutex_);
    err = DoRequest(
        "POST", path, body, call_headers,
        "application/octet-stream", header_length, &response_body,
        &response_header_length, sync_conn_.get(), options.client_timeout_us,
        &sent_ns);
  }
  // Send ends when the request hit the socket; everything after is
  // server + receive time.
  if (sent_ns != 0) {
    timers.SetTimestamp(RequestTimers::Kind::SEND_END, sent_ns);
    timers.SetTimestamp(RequestTimers::Kind::RECV_START, sent_ns);
  } else {
    timers.CaptureTimestamp(RequestTimers::Kind::SEND_END);
    timers.CaptureTimestamp(RequestTimers::Kind::RECV_START);
  }

  Error create_err = InferResultHttp::Create(
      result, std::move(response_body), response_header_length, err);
  timers.CaptureTimestamp(RequestTimers::Kind::RECV_END);
  timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
  if (create_err.IsOk() && err.IsOk()) UpdateInferStat(timers);
  return create_err;
}

void InferenceServerHttpClient::SetAsyncWorkerCount(size_t count) {
  std::lock_guard<std::mutex> lk(async_mutex_);
  if (async_workers_.empty() && count > 0) {
    async_worker_count_ = count;
  }
}

void InferenceServerHttpClient::EnsureAsyncWorkers() {
  std::lock_guard<std::mutex> lk(async_mutex_);
  if (!async_workers_.empty()) return;
  for (size_t i = 0; i < async_worker_count_; ++i) {
    async_workers_.emplace_back(
        [this]() { AsyncWorkerLoop(); });
  }
}

void InferenceServerHttpClient::AsyncWorkerLoop() {
  // Each worker owns its own connection — concurrent in-flight
  // requests without sharing (the reference multiplexes via
  // curl_multi; a per-worker connection achieves the same pipeline
  // depth with simpler lifetime rules).
  HttpConnection conn(host_, port_);
  while (true) {
    std::unique_ptr<AsyncRequest> req;
    {
      std::unique_lock<std::mutex> lk(async_mutex_);
      async_cv_.wait(lk, [this]() {
        return async_exiting_ || !async_queue_.empty();
      });
      if (async_exiting_ && async_queue_.empty()) return;
      req = std::move(async_queue_.front());
      async_queue_.pop_front();
    }

    req->timers.CaptureTimestamp(RequestTimers::Kind::SEND_START);
    std::map<std::string, std::string> hdrs(
        req->headers.begin(), req->headers.end());
    hdrs["Content-Type"] = "application/octet-stream";
    if (req->header_length != 0) {
      hdrs["Inference-Header-Content-Length"] =
          std::to_string(req->header_length);
    }
    HttpResponse response;
    uint64_t sent_ns = 0;
    std::string terr = conn.Request(
        "POST", req->path, hdrs, req->body, &response, req->timeout_us,
        &sent_ns);
    if (sent_ns != 0) {
      req->timers.SetTimestamp(RequestTimers::Kind::SEND_END, sent_ns);
      req->timers.SetTimestamp(RequestTimers::Kind::RECV_START, sent_ns);
    } else {
      req->timers.CaptureTimestamp(RequestTimers::Kind::SEND_END);
      req->timers.CaptureTimestamp(RequestTimers::Kind::RECV_START);
    }

    Error err = terr.empty() ? ErrorFromResponse(response) : Error(terr);
    size_t response_header_length = 0;
    auto it = response.headers.find("inference-header-content-length");
    if (it != response.headers.end()) {
      response_header_length = strtoull(it->second.c_str(), nullptr, 10);
    }
    auto enc = response.headers.find("content-encoding");
    if (err.IsOk() && enc != response.headers.end()) {
      std::string plain;
      err = DecompressBody(enc->second, response.body, &plain);
      if (err.IsOk()) response.body = std::move(plain);
    }
    InferResult* result = nullptr;
    InferResultHttp::Create(
        &result, std::move(response.body), response_header_length, err);
    req->timers.CaptureTimestamp(RequestTimers::Kind::RECV_END);
    req->timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
    if (err.IsOk()) UpdateInferStat(req->timers);
    req->callback(result);
  }
}

Error InferenceServerHttpClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, const Parameters& query_params,
    CompressionType request_compression,
    CompressionType response_compression) {
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInfer");
  }
  EnsureAsyncWorkers();

  auto req = std::make_unique<AsyncRequest>();
  req->timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
  size_t header_length = 0;
  Error err = GenerateRequestBodyStr(&req->body, &header_length, options,
                                     inputs, outputs);
  if (!err.IsOk()) return err;
  req->path = AppendQuery(
      ModelPath(options.model_name, options.model_version) + "/infer",
      query_params);
  req->header_length = header_length;
  req->headers = headers;
  if (request_compression != CompressionType::NONE) {
    std::string compressed;
    err = CompressBody(request_compression, req->body, &compressed);
    if (!err.IsOk()) return err;
    req->body = std::move(compressed);
    req->headers["Content-Encoding"] = CompressionName(request_compression);
  }
  if (response_compression != CompressionType::NONE) {
    req->headers["Accept-Encoding"] = CompressionName(response_compression);
  }
  req->timeout_us = options.client_timeout_us;
  req->callback = std::move(callback);

  {
    std::lock_guard<std::mutex> lk(async_mutex_);
    async_queue_.push_back(std::move(req));
  }
  async_cv_.notify_one();
  return Error::Success;
}

Error InferenceServerHttpClient::InferMulti(
    std::vector<InferResult*>* results, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("options must be 1 or match inputs count");
  }
  results->clear();
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = (options.size() == 1) ? options[0] : options[i];
    static const std::vector<const InferRequestedOutput*> kNoOutputs;
    const auto& outs = (i < outputs.size()) ? outputs[i] : kNoOutputs;
    InferResult* result = nullptr;
    Error err = Infer(&result, opt, inputs[i], outs, headers);
    if (!err.IsOk()) {
      for (auto* r : *results) delete r;
      results->clear();
      return err;
    }
    results->push_back(result);
  }
  return Error::Success;
}

Error InferenceServerHttpClient::AsyncInferMulti(
    OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInferMulti");
  }
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("options must be 1 or match inputs count");
  }
  struct MultiState {
    std::mutex mutex;
    std::vector<InferResult*> results;
    size_t remaining;
    OnMultiCompleteFn callback;
  };
  auto state = std::make_shared<MultiState>();
  state->results.resize(inputs.size(), nullptr);
  state->remaining = inputs.size();
  state->callback = std::move(callback);

  // Build every request body up front so a failure on request i
  // cannot leave earlier requests in flight with a callback that can
  // never fire (nothing is enqueued until all succeed).
  std::vector<std::unique_ptr<AsyncRequest>> requests;
  requests.reserve(inputs.size());
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = (options.size() == 1) ? options[0] : options[i];
    static const std::vector<const InferRequestedOutput*> kNoOutputs;
    const auto& outs = (i < outputs.size()) ? outputs[i] : kNoOutputs;
    auto req = std::make_unique<AsyncRequest>();
    req->timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
    size_t header_length = 0;
    Error err =
        GenerateRequestBodyStr(&req->body, &header_length, opt, inputs[i], outs);
    if (!err.IsOk()) return err;
    req->path = ModelPath(opt.model_name, opt.model_version) + "/infer";
    req->header_length = header_length;
    req->headers = headers;
    req->timeout_us = opt.client_timeout_us;
    req->callback = [state, i](InferResult* result) {
      bool done = false;
      {
        std::lock_guard<std::mutex> lk(state->mutex);
        state->results[i] = result;
        done = (--state->remaining == 0);
      }
      if (done) state->callback(state->results);
    };
    requests.push_back(std::move(req));
  }
  EnsureAsyncWorkers();
  {
    std::lock_guard<std::mutex> lk(async_mutex_);
    for (auto& req : requests) async_queue_.push_back(std::move(req));
  }
  async_cv_.notify_all();
  return Error::Success;
}

}  // namespace tpuclient
