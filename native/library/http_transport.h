// Minimal HTTP/1.1 connection over POSIX sockets with keep-alive.
// Fills the role libcurl plays in the reference http_client
// (/root/reference/src/c++/library/http_client.cc:1364-1393); also
// reused by the perf harness's OpenAI-style backend for SSE streams.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>

#include "tls.h"

namespace tpuclient {

struct HttpResponse {
  int status_code = 0;
  // Header names lowercased.
  std::map<std::string, std::string> headers;
  std::string body;
};

class HttpConnection {
 public:
  HttpConnection(const std::string& host, int port)
      : host_(host), port_(port) {}
  // HTTPS: TLS over dlopen'd OpenSSL (tls.h); options mirror the
  // reference SslOptions.
  HttpConnection(const std::string& host, int port, bool use_tls,
                 const SslOptions& ssl_options)
      : host_(host), port_(port), use_tls_(use_tls),
        ssl_options_(ssl_options) {}
  ~HttpConnection();

  HttpConnection(const HttpConnection&) = delete;
  HttpConnection& operator=(const HttpConnection&) = delete;

  // Performs a request, transparently (re)connecting and retrying
  // once if a kept-alive connection went stale. timeout_us==0 means
  // no timeout. Returns empty string on success, else error text.
  std::string Request(
      const std::string& method, const std::string& path,
      const std::map<std::string, std::string>& headers,
      const std::string& body, HttpResponse* response,
      uint64_t timeout_us = 0, uint64_t* sent_ns_out = nullptr);

  // Like Request but delivers body bytes incrementally to `on_data`
  // as they arrive (for server-sent-event streams). Headers are
  // filled in `response`; response->body stays empty. If
  // `sent_ns_out` is non-null it receives the steady-clock time (ns)
  // when the request finished hitting the socket, so callers can
  // attribute send vs. receive latency.
  std::string RequestStreaming(
      const std::string& method, const std::string& path,
      const std::map<std::string, std::string>& headers,
      const std::string& body, HttpResponse* response,
      const std::function<void(const char*, size_t)>& on_data,
      uint64_t timeout_us = 0, uint64_t* sent_ns_out = nullptr);

  void Close();
  bool IsConnected() const { return fd_ >= 0; }

 private:
  std::string Connect(uint64_t timeout_us);
  std::string SendAll(const char* data, size_t len, uint64_t deadline_ns);
  // Returns >0 bytes read, 0 on EOF, <0 on error (sets err).
  ssize_t RecvSome(char* buf, size_t len, uint64_t deadline_ns,
                   std::string* err);
  std::string ReadResponse(
      HttpResponse* response,
      const std::function<void(const char*, size_t)>* on_data,
      uint64_t deadline_ns);

  std::string host_;
  int port_;
  int fd_ = -1;
  bool use_tls_ = false;
  SslOptions ssl_options_;
  std::unique_ptr<TlsSession> tls_;
  // Buffered bytes read past the previous response (pipelining slop).
  std::string leftover_;
};

}  // namespace tpuclient
