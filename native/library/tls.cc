#include "tls.h"

#include <dlfcn.h>
#include <poll.h>
#include <time.h>

#include <mutex>

namespace tpuclient {

namespace {

// OpenSSL constants (stable public ABI values, openssl/ssl.h).
constexpr int kSslErrorWantRead = 2;
constexpr int kSslErrorWantWrite = 3;
constexpr int kSslErrorZeroReturn = 6;
constexpr int kSslVerifyNone = 0;
constexpr int kSslVerifyPeer = 1;
constexpr int kSslFiletypePem = 1;
constexpr long kCtrlSetTlsextHostname = 55;
constexpr long kTlsextNametypeHostName = 0;

struct OpenSsl {
  void* (*TLS_client_method)();
  void* (*SSL_CTX_new)(void*);
  void (*SSL_CTX_free)(void*);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  int (*SSL_CTX_set_default_verify_paths)(void*);
  int (*SSL_CTX_use_certificate_chain_file)(void*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int);
  int (*SSL_CTX_set_alpn_protos)(void*, const unsigned char*, unsigned);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  long (*SSL_ctrl)(void*, int, long, void*);
  int (*SSL_set1_host)(void*, const char*);
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_get_error)(const void*, int);
  int (*SSL_shutdown)(void*);
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);

  bool ok = false;
};

OpenSsl* Lib() {
  static OpenSsl lib;
  static std::once_flag once;
  std::call_once(once, [] {
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) ssl = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    if (ssl == nullptr) return;
    void* crypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (crypto == nullptr) crypto = dlopen("libcrypto.so", RTLD_NOW);

    auto bind = [&](const char* name) -> void* {
      void* sym = dlsym(ssl, name);
      if (sym == nullptr && crypto != nullptr) sym = dlsym(crypto, name);
      return sym;
    };
#define TPUCLIENT_BIND(field)                                        \
  lib.field = reinterpret_cast<decltype(lib.field)>(bind(#field));   \
  if (lib.field == nullptr) return;
    TPUCLIENT_BIND(TLS_client_method)
    TPUCLIENT_BIND(SSL_CTX_new)
    TPUCLIENT_BIND(SSL_CTX_free)
    TPUCLIENT_BIND(SSL_CTX_set_verify)
    TPUCLIENT_BIND(SSL_CTX_load_verify_locations)
    TPUCLIENT_BIND(SSL_CTX_set_default_verify_paths)
    TPUCLIENT_BIND(SSL_CTX_use_certificate_chain_file)
    TPUCLIENT_BIND(SSL_CTX_use_PrivateKey_file)
    TPUCLIENT_BIND(SSL_CTX_set_alpn_protos)
    TPUCLIENT_BIND(SSL_new)
    TPUCLIENT_BIND(SSL_free)
    TPUCLIENT_BIND(SSL_set_fd)
    TPUCLIENT_BIND(SSL_ctrl)
    TPUCLIENT_BIND(SSL_set1_host)
    TPUCLIENT_BIND(SSL_connect)
    TPUCLIENT_BIND(SSL_read)
    TPUCLIENT_BIND(SSL_write)
    TPUCLIENT_BIND(SSL_get_error)
    TPUCLIENT_BIND(SSL_shutdown)
    TPUCLIENT_BIND(ERR_get_error)
    TPUCLIENT_BIND(ERR_error_string_n)
#undef TPUCLIENT_BIND
    lib.ok = true;
  });
  return &lib;
}

uint64_t NowNs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
}

std::string LastSslError(const char* fallback) {
  OpenSsl* lib = Lib();
  unsigned long code = lib->ERR_get_error();
  if (code == 0) return fallback;
  char buf[256];
  lib->ERR_error_string_n(code, buf, sizeof(buf));
  return buf;
}

// Polls until the fd is ready for what OpenSSL wants, or deadline.
std::string WaitFor(int fd, int ssl_error, uint64_t deadline_ns) {
  struct pollfd pfd;
  pfd.fd = fd;
  pfd.events = ssl_error == kSslErrorWantWrite ? POLLOUT : POLLIN;
  int timeout_ms = -1;
  if (deadline_ns != 0) {
    uint64_t now = NowNs();
    if (now >= deadline_ns) return "TLS timeout";
    timeout_ms = static_cast<int>((deadline_ns - now) / 1000000ull);
    if (timeout_ms == 0) timeout_ms = 1;
  }
  int rc = poll(&pfd, 1, timeout_ms);
  if (rc == 0) return "TLS timeout";
  if (rc < 0) return "TLS poll failed";
  return "";
}

}  // namespace

TlsSession::TlsSession() = default;

TlsSession::~TlsSession() { Close(); }

bool TlsSession::Available() { return Lib()->ok; }

std::string TlsSession::Handshake(
    int fd, const std::string& host, const SslOptions& options,
    const std::string& alpn, uint64_t deadline_ns) {
  OpenSsl* lib = Lib();
  if (!lib->ok) {
    return "TLS unavailable: libssl.so.3 not found or incomplete";
  }
  Close();
  ctx_ = lib->SSL_CTX_new(lib->TLS_client_method());
  if (ctx_ == nullptr) return LastSslError("SSL_CTX_new failed");
  if (options.insecure_skip_verify) {
    lib->SSL_CTX_set_verify(ctx_, kSslVerifyNone, nullptr);
  } else {
    lib->SSL_CTX_set_verify(ctx_, kSslVerifyPeer, nullptr);
    if (!options.root_certificates.empty()) {
      if (lib->SSL_CTX_load_verify_locations(
              ctx_, options.root_certificates.c_str(), nullptr) != 1) {
        return LastSslError("failed to load root certificates");
      }
    } else {
      lib->SSL_CTX_set_default_verify_paths(ctx_);
    }
  }
  if (!options.certificate_chain.empty()) {
    if (lib->SSL_CTX_use_certificate_chain_file(
            ctx_, options.certificate_chain.c_str()) != 1) {
      return LastSslError("failed to load certificate chain");
    }
  }
  if (!options.private_key.empty()) {
    if (lib->SSL_CTX_use_PrivateKey_file(
            ctx_, options.private_key.c_str(), kSslFiletypePem) != 1) {
      return LastSslError("failed to load private key");
    }
  }
  if (!alpn.empty()) {
    // Wire format: one length-prefixed protocol name.
    std::string wire;
    wire.push_back(static_cast<char>(alpn.size()));
    wire += alpn;
    lib->SSL_CTX_set_alpn_protos(
        ctx_, reinterpret_cast<const unsigned char*>(wire.data()),
        static_cast<unsigned>(wire.size()));
  }
  ssl_ = lib->SSL_new(ctx_);
  if (ssl_ == nullptr) return LastSslError("SSL_new failed");
  lib->SSL_set_fd(ssl_, fd);
  fd_ = fd;
  if (!host.empty()) {
    lib->SSL_ctrl(ssl_, kCtrlSetTlsextHostname, kTlsextNametypeHostName,
                  const_cast<char*>(host.c_str()));  // SNI
    if (!options.insecure_skip_verify) {
      lib->SSL_set1_host(ssl_, host.c_str());  // hostname check
    }
  }
  for (;;) {
    int rc = lib->SSL_connect(ssl_);
    if (rc == 1) return "";
    int ssl_error = lib->SSL_get_error(ssl_, rc);
    if (ssl_error == kSslErrorWantRead || ssl_error == kSslErrorWantWrite) {
      std::string err = WaitFor(fd_, ssl_error, deadline_ns);
      if (!err.empty()) return err;
      continue;
    }
    return LastSslError("TLS handshake failed");
  }
}

std::string TlsSession::Write(
    const char* data, size_t len, uint64_t deadline_ns) {
  OpenSsl* lib = Lib();
  size_t sent = 0;
  while (sent < len) {
    int rc = lib->SSL_write(ssl_, data + sent,
                            static_cast<int>(len - sent));
    if (rc > 0) {
      sent += rc;
      continue;
    }
    int ssl_error = lib->SSL_get_error(ssl_, rc);
    if (ssl_error == kSslErrorWantRead || ssl_error == kSslErrorWantWrite) {
      std::string err = WaitFor(fd_, ssl_error, deadline_ns);
      if (!err.empty()) return err;
      continue;
    }
    return LastSslError("TLS write failed");
  }
  return "";
}

int64_t TlsSession::Read(
    char* buf, size_t len, uint64_t deadline_ns, std::string* err) {
  OpenSsl* lib = Lib();
  for (;;) {
    int rc = lib->SSL_read(ssl_, buf, static_cast<int>(len));
    if (rc > 0) return rc;
    int ssl_error = lib->SSL_get_error(ssl_, rc);
    if (ssl_error == kSslErrorZeroReturn) return 0;  // clean close
    if (ssl_error == kSslErrorWantRead || ssl_error == kSslErrorWantWrite) {
      std::string wait_err = WaitFor(fd_, ssl_error, deadline_ns);
      if (!wait_err.empty()) {
        *err = wait_err;
        return -1;
      }
      continue;
    }
    *err = LastSslError("TLS read failed");
    return -1;
  }
}

void TlsSession::Close() {
  OpenSsl* lib = Lib();
  if (ssl_ != nullptr) {
    lib->SSL_shutdown(ssl_);
    lib->SSL_free(ssl_);
    ssl_ = nullptr;
  }
  if (ctx_ != nullptr) {
    lib->SSL_CTX_free(ctx_);
    ctx_ = nullptr;
  }
  fd_ = -1;
}

}  // namespace tpuclient
