#include "grpc_client.h"

#include <chrono>
#include <cstring>
#include <thread>

namespace tpuclient {

namespace {

const char kService[] = "/inference.GRPCInferenceService/";

std::string Method(const char* name) {
  return std::string(kService) + name;
}

}  // namespace

//==============================================================================
// InferResultGrpc

Error InferResultGrpc::Create(
    InferResult** result, std::shared_ptr<inference::ModelInferResponse>
                              response,
    const Error& request_status) {
  *result = new InferResultGrpc(std::move(response), request_status);
  return Error::Success;
}

Error InferResultGrpc::Create(
    InferResult** result,
    std::shared_ptr<inference::ModelStreamInferResponse> stream_response) {
  Error status = Error::Success;
  if (!stream_response->error_message().empty()) {
    status = Error(stream_response->error_message());
  }
  auto shared_response = std::shared_ptr<inference::ModelInferResponse>(
      stream_response, stream_response->mutable_infer_response());
  auto* grpc_result = new InferResultGrpc(shared_response, status);
  grpc_result->stream_response_ = stream_response;
  // Decoupled final-response marker (parity: grpc_client.cc:254-262).
  const auto& params = shared_response->parameters();
  auto it = params.find("triton_final_response");
  if (it != params.end() && it->second.has_bool_param()) {
    grpc_result->is_final_response_ = it->second.bool_param();
  }
  // An empty final response from a decoupled model.
  grpc_result->null_last_response_ =
      grpc_result->is_final_response_ &&
      shared_response->outputs_size() == 0 &&
      shared_response->model_name().empty();
  *result = grpc_result;
  return Error::Success;
}

InferResultGrpc::InferResultGrpc(
    std::shared_ptr<inference::ModelInferResponse> response,
    const Error& request_status)
    : response_(std::move(response)), status_(request_status) {}

Error InferResultGrpc::FindOutput(
    const std::string& output_name,
    const inference::ModelInferResponse::InferOutputTensor** tensor,
    size_t* index) const {
  for (int i = 0; i < response_->outputs_size(); ++i) {
    if (response_->outputs(i).name() == output_name) {
      *tensor = &response_->outputs(i);
      *index = static_cast<size_t>(i);
      return Error::Success;
    }
  }
  return Error(
      "The response does not contain output '" + output_name + "'");
}

Error InferResultGrpc::ModelName(std::string* name) const {
  *name = response_->model_name();
  return Error::Success;
}

Error InferResultGrpc::ModelVersion(std::string* version) const {
  *version = response_->model_version();
  return Error::Success;
}

Error InferResultGrpc::Id(std::string* id) const {
  *id = response_->id();
  return Error::Success;
}

Error InferResultGrpc::Shape(
    const std::string& output_name, std::vector<int64_t>* shape) const {
  const inference::ModelInferResponse::InferOutputTensor* tensor;
  size_t index;
  Error err = FindOutput(output_name, &tensor, &index);
  if (!err.IsOk()) return err;
  shape->assign(tensor->shape().begin(), tensor->shape().end());
  return Error::Success;
}

Error InferResultGrpc::Datatype(
    const std::string& output_name, std::string* datatype) const {
  const inference::ModelInferResponse::InferOutputTensor* tensor;
  size_t index;
  Error err = FindOutput(output_name, &tensor, &index);
  if (!err.IsOk()) return err;
  *datatype = tensor->datatype();
  return Error::Success;
}

Error InferResultGrpc::RawData(
    const std::string& output_name, const uint8_t** buf,
    size_t* byte_size) const {
  const inference::ModelInferResponse::InferOutputTensor* tensor;
  size_t index;
  Error err = FindOutput(output_name, &tensor, &index);
  if (!err.IsOk()) return err;
  if (static_cast<int>(index) < response_->raw_output_contents_size()) {
    const std::string& raw = response_->raw_output_contents(index);
    *buf = reinterpret_cast<const uint8_t*>(raw.data());
    *byte_size = raw.size();
    return Error::Success;
  }
  return Error(
      "output '" + output_name + "' has no raw data (in shared memory?)");
}

Error InferResultGrpc::StringData(
    const std::string& output_name,
    std::vector<std::string>* string_result) const {
  const uint8_t* buf;
  size_t byte_size;
  Error err = RawData(output_name, &buf, &byte_size);
  if (!err.IsOk()) return err;
  string_result->clear();
  size_t pos = 0;
  while (pos + 4 <= byte_size) {
    uint32_t len;
    memcpy(&len, buf + pos, 4);  // little-endian wire format
    pos += 4;
    if (pos + len > byte_size) {
      return Error("malformed BYTES tensor in output '" + output_name + "'");
    }
    string_result->emplace_back(
        reinterpret_cast<const char*>(buf + pos), len);
    pos += len;
  }
  return Error::Success;
}

std::string InferResultGrpc::DebugString() const {
  return response_->DebugString();
}

Error InferResultGrpc::RequestStatus() const { return status_; }

//==============================================================================
// InferenceServerGrpcClient

InferenceServerGrpcClient::InferenceServerGrpcClient(bool verbose)
    : InferenceServerClient(verbose) {}

namespace {

// URL-keyed channel cache (parity: GetStub's grpc_channel_stub_map_,
// grpc_client.cc:50-152): up to max_share_count clients share one
// HTTP/2 connection per URL before a fresh one is opened —
// distributing clients over channels relieves per-connection stream
// concurrency limits.
std::map<std::string, std::pair<size_t, std::shared_ptr<GrpcChannel>>>
    g_channel_cache;
std::mutex g_channel_cache_mutex;

Error GetChannel(
    const std::string& url, bool use_cached_channel, bool* shared,
    std::shared_ptr<GrpcChannel>* out) {
  *shared = false;
  if (!use_cached_channel) {
    return GrpcChannel::Create(out, url);
  }
  std::lock_guard<std::mutex> lock(g_channel_cache_mutex);
  static const size_t max_share_count = []() {
    const char* env = getenv("TPUCLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT");
    size_t count = env != nullptr ? strtoull(env, nullptr, 10) : 0;
    return count != 0 ? count : 6;
  }();
  auto it = g_channel_cache.find(url);
  if (it != g_channel_cache.end() &&
      it->second.first % max_share_count != 0 &&
      it->second.second->IsConnected()) {
    it->second.first++;
    *out = it->second.second;
    *shared = true;
    return Error::Success;
  }
  std::shared_ptr<GrpcChannel> channel;
  Error err = GrpcChannel::Create(&channel, url);
  if (!err.IsOk()) return err;
  g_channel_cache[url] = {1, channel};
  *out = channel;
  *shared = true;
  return Error::Success;
}

}  // namespace

InferenceServerGrpcClient::~InferenceServerGrpcClient() {
  StopStream();
  if (channel_shared_) {
    // The connection belongs to the cache and other clients: wait for
    // our own in-flight calls to complete instead of shutting it
    // down (their callbacks reference this object). The wait is
    // instant when nothing is in flight — the common case. A call
    // still pending after the grace (generous: past normal inference
    // latency, including long LLM generations) forces Shutdown — a
    // connection that cannot answer for that long is broken for every
    // sharer, and Shutdown synchronously fails the calls so the wait
    // terminates.
    if (!inflight_->WaitZero(std::chrono::seconds(30)) && channel_) {
      channel_->Shutdown();
      inflight_->WaitZero(std::chrono::seconds(30));
    }
  } else if (channel_) {
    // Sole owner: fail all in-flight async calls now, while
    // completed_ is still alive to receive their results; the
    // dispatch worker then drains the queue before exiting.
    channel_->Shutdown();
  }
  {
    std::lock_guard<std::mutex> lock(mutex_);
    exiting_ = true;
  }
  cv_.notify_all();
  if (worker_.joinable()) worker_.join();
}

Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& url, bool verbose, bool use_cached_channel) {
  client->reset(new InferenceServerGrpcClient(verbose));
  Error err = GetChannel(url, use_cached_channel,
                         &(*client)->channel_shared_, &(*client)->channel_);
  if (!err.IsOk()) client->reset();
  return err;
}

Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& url, const KeepAliveOptions& keepalive,
    bool verbose) {
  // Keepalive probing is per-connection state: never share a cached
  // channel (another client's probing policy must not leak in).
  Error err = Create(client, url, verbose, /*use_cached_channel=*/false);
  if (!err.IsOk()) return err;
  if (keepalive.keepalive_time_ms != UINT64_MAX) {
    (*client)->channel_->EnableKeepAlive(
        keepalive.keepalive_time_ms, keepalive.keepalive_timeout_ms);
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::Rpc(
    const std::string& method, const google::protobuf::Message& req,
    google::protobuf::Message* resp, const Headers& headers,
    uint64_t timeout_us, RequestTimers* timers,
    const std::string& compression) {
  std::string request_bytes;
  if (!req.SerializeToString(&request_bytes)) {
    return Error("failed to serialize request");
  }
  if (request_bytes.size() > static_cast<size_t>(INT32_MAX)) {
    // Parity: the reference rejects >INT_MAX messages
    // (grpc_client.cc:1459).
    return Error("request exceeds 2GB gRPC message limit");
  }
  std::string response_bytes;
  auto call_start = std::chrono::steady_clock::now();
  Error err = channel_->UnaryCall(
      method, request_bytes, &response_bytes, timeout_us, headers, timers,
      compression);
  if (!err.IsOk()) return err;
  if (timeout_us > 0) {
    // gRPC deadline semantics: completing AFTER the deadline is still
    // DEADLINE_EXCEEDED, even when the transport's bounded wait won
    // the race (the server may well have executed the request).
    auto elapsed_us = std::chrono::duration_cast<std::chrono::microseconds>(
                          std::chrono::steady_clock::now() - call_start)
                          .count();
    if (static_cast<uint64_t>(elapsed_us) > timeout_us) {
      return Error("Deadline Exceeded");
    }
  }
  if (!resp->ParseFromString(response_bytes)) {
    return Error("failed to parse response");
  }
  if (verbose_) {
    fprintf(stderr, "%s\n%s\n", method.c_str(),
            resp->DebugString().c_str());
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::IsServerLive(
    bool* live, const Headers& headers) {
  inference::ServerLiveRequest req;
  inference::ServerLiveResponse resp;
  Error err = Rpc(Method("ServerLive"), req, &resp, headers);
  *live = err.IsOk() && resp.live();
  return err;
}

Error InferenceServerGrpcClient::IsServerReady(
    bool* ready, const Headers& headers) {
  inference::ServerReadyRequest req;
  inference::ServerReadyResponse resp;
  Error err = Rpc(Method("ServerReady"), req, &resp, headers);
  *ready = err.IsOk() && resp.ready();
  return err;
}

Error InferenceServerGrpcClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  inference::ModelReadyRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  inference::ModelReadyResponse resp;
  Error err = Rpc(Method("ModelReady"), req, &resp, headers);
  *ready = err.IsOk() && resp.ready();
  return err;
}

Error InferenceServerGrpcClient::ServerMetadata(
    inference::ServerMetadataResponse* server_metadata,
    const Headers& headers) {
  inference::ServerMetadataRequest req;
  return Rpc(Method("ServerMetadata"), req, server_metadata, headers);
}

Error InferenceServerGrpcClient::ModelMetadata(
    inference::ModelMetadataResponse* model_metadata,
    const std::string& model_name, const std::string& model_version,
    const Headers& headers) {
  inference::ModelMetadataRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Rpc(Method("ModelMetadata"), req, model_metadata, headers);
}

Error InferenceServerGrpcClient::ModelConfig(
    inference::ModelConfigResponse* model_config,
    const std::string& model_name, const std::string& model_version,
    const Headers& headers) {
  inference::ModelConfigRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Rpc(Method("ModelConfig"), req, model_config, headers);
}

Error InferenceServerGrpcClient::ModelRepositoryIndex(
    inference::RepositoryIndexResponse* repository_index,
    const Headers& headers) {
  inference::RepositoryIndexRequest req;
  return Rpc(Method("RepositoryIndex"), req, repository_index, headers);
}

Error InferenceServerGrpcClient::LoadModel(
    const std::string& model_name, const Headers& headers,
    const std::string& config) {
  inference::RepositoryModelLoadRequest req;
  req.set_model_name(model_name);
  if (!config.empty()) {
    (*req.mutable_parameters())["config"].set_string_param(config);
  }
  inference::RepositoryModelLoadResponse resp;
  return Rpc(Method("RepositoryModelLoad"), req, &resp, headers);
}

Error InferenceServerGrpcClient::UnloadModel(
    const std::string& model_name, const Headers& headers) {
  inference::RepositoryModelUnloadRequest req;
  req.set_model_name(model_name);
  inference::RepositoryModelUnloadResponse resp;
  return Rpc(Method("RepositoryModelUnload"), req, &resp, headers);
}

Error InferenceServerGrpcClient::ModelInferenceStatistics(
    inference::ModelStatisticsResponse* infer_stat,
    const std::string& model_name, const std::string& model_version,
    const Headers& headers) {
  inference::ModelStatisticsRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Rpc(Method("ModelStatistics"), req, infer_stat, headers);
}

Error InferenceServerGrpcClient::UpdateTraceSettings(
    inference::TraceSettingResponse* response, const std::string& model_name,
    const std::map<std::string, std::vector<std::string>>& settings,
    const Headers& headers) {
  inference::TraceSettingRequest req;
  req.set_model_name(model_name);
  for (const auto& kv : settings) {
    auto& value = (*req.mutable_settings())[kv.first];
    for (const auto& v : kv.second) value.add_value(v);
  }
  return Rpc(Method("TraceSetting"), req, response, headers);
}

Error InferenceServerGrpcClient::GetTraceSettings(
    inference::TraceSettingResponse* settings, const std::string& model_name,
    const Headers& headers) {
  inference::TraceSettingRequest req;
  req.set_model_name(model_name);
  return Rpc(Method("TraceSetting"), req, settings, headers);
}

Error InferenceServerGrpcClient::SystemSharedMemoryStatus(
    inference::SystemSharedMemoryStatusResponse* status,
    const std::string& region_name, const Headers& headers) {
  inference::SystemSharedMemoryStatusRequest req;
  req.set_name(region_name);
  return Rpc(Method("SystemSharedMemoryStatus"), req, status, headers);
}

Error InferenceServerGrpcClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset, const Headers& headers) {
  inference::SystemSharedMemoryRegisterRequest req;
  req.set_name(name);
  req.set_key(key);
  req.set_offset(offset);
  req.set_byte_size(byte_size);
  inference::SystemSharedMemoryRegisterResponse resp;
  return Rpc(Method("SystemSharedMemoryRegister"), req, &resp, headers);
}

Error InferenceServerGrpcClient::UnregisterSystemSharedMemory(
    const std::string& name, const Headers& headers) {
  inference::SystemSharedMemoryUnregisterRequest req;
  req.set_name(name);
  inference::SystemSharedMemoryUnregisterResponse resp;
  return Rpc(Method("SystemSharedMemoryUnregister"), req, &resp, headers);
}

Error InferenceServerGrpcClient::TpuSharedMemoryStatus(
    inference::TpuSharedMemoryStatusResponse* status,
    const std::string& region_name, const Headers& headers) {
  inference::TpuSharedMemoryStatusRequest req;
  req.set_name(region_name);
  return Rpc(Method("TpuSharedMemoryStatus"), req, status, headers);
}

Error InferenceServerGrpcClient::RegisterTpuSharedMemory(
    const std::string& name, const std::string& raw_handle,
    int64_t device_id, size_t byte_size, const Headers& headers) {
  inference::TpuSharedMemoryRegisterRequest req;
  req.set_name(name);
  req.set_raw_handle(raw_handle);
  req.set_device_id(device_id);
  req.set_byte_size(byte_size);
  inference::TpuSharedMemoryRegisterResponse resp;
  return Rpc(Method("TpuSharedMemoryRegister"), req, &resp, headers);
}

Error InferenceServerGrpcClient::UnregisterTpuSharedMemory(
    const std::string& name, const Headers& headers) {
  inference::TpuSharedMemoryUnregisterRequest req;
  req.set_name(name);
  inference::TpuSharedMemoryUnregisterResponse resp;
  return Rpc(Method("TpuSharedMemoryUnregister"), req, &resp, headers);
}

Error InferenceServerGrpcClient::PreRunProcessing(
    inference::ModelInferRequest* request, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  request->set_model_name(options.model_name);
  request->set_model_version(options.model_version);
  request->set_id(options.request_id);

  auto& params = *request->mutable_parameters();
  if (options.sequence_id != 0) {
    params["sequence_id"].set_int64_param(options.sequence_id);
    params["sequence_start"].set_bool_param(options.sequence_start);
    params["sequence_end"].set_bool_param(options.sequence_end);
  }
  if (options.priority != 0) {
    params["priority"].set_int64_param(options.priority);
  }
  if (options.server_timeout_us != 0) {
    params["timeout"].set_int64_param(options.server_timeout_us);
  }
  for (const auto& kv : options.string_params)
    params[kv.first].set_string_param(kv.second);
  for (const auto& kv : options.int_params)
    params[kv.first].set_int64_param(kv.second);
  for (const auto& kv : options.bool_params)
    params[kv.first].set_bool_param(kv.second);
  for (const auto& kv : options.double_params)
    params[kv.first].set_double_param(kv.second);

  size_t total_bytes = 0;
  for (InferInput* input : inputs) {
    auto* tensor = request->add_inputs();
    tensor->set_name(input->Name());
    tensor->set_datatype(input->Datatype());
    for (int64_t dim : input->Shape()) tensor->add_shape(dim);
    if (input->IsSharedMemory()) {
      std::string region;
      size_t byte_size, offset;
      input->SharedMemoryInfo(&region, &byte_size, &offset);
      auto& tensor_params = *tensor->mutable_parameters();
      // Same parameter convention as the reference
      // (grpc_client.cc:1494-1507).
      tensor_params["shared_memory_region"].set_string_param(region);
      tensor_params["shared_memory_byte_size"].set_int64_param(byte_size);
      if (offset != 0) {
        tensor_params["shared_memory_offset"].set_int64_param(offset);
      }
    } else {
      std::string* raw = request->add_raw_input_contents();
      input->PrepareForRequest();
      raw->clear();
      raw->reserve(input->TotalSendByteSize());
      const uint8_t* buf;
      size_t chunk;
      while (input->GetNext(&buf, &chunk)) {
        raw->append(reinterpret_cast<const char*>(buf), chunk);
      }
      total_bytes += raw->size();
    }
  }
  if (total_bytes > static_cast<size_t>(INT32_MAX)) {
    return Error("request exceeds 2GB gRPC message limit");
  }

  for (const InferRequestedOutput* output : outputs) {
    auto* tensor = request->add_outputs();
    tensor->set_name(output->Name());
    auto& tensor_params = *tensor->mutable_parameters();
    if (output->ClassCount() > 0) {
      tensor_params["classification"].set_int64_param(output->ClassCount());
    }
    if (output->IsSharedMemory()) {
      std::string region;
      size_t byte_size, offset;
      output->SharedMemoryInfo(&region, &byte_size, &offset);
      tensor_params["shared_memory_region"].set_string_param(region);
      tensor_params["shared_memory_byte_size"].set_int64_param(byte_size);
      if (offset != 0) {
        tensor_params["shared_memory_offset"].set_int64_param(offset);
      }
    }
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, const std::string& grpc_compression) {
  inference::ModelInferRequest request;
  Error err = PreRunProcessing(&request, options, inputs, outputs);
  if (!err.IsOk()) return err;
  auto response = std::make_shared<inference::ModelInferResponse>();
  RequestTimers timers;
  err = Rpc(
      Method("ModelInfer"), request, response.get(), headers,
      options.client_timeout_us, &timers, grpc_compression);
  UpdateInferStat(timers);
  if (!err.IsOk()) return err;
  return InferResultGrpc::Create(result, std::move(response));
}

Error InferenceServerGrpcClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, const std::string& grpc_compression) {
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInfer");
  }
  if (!dispatch_started_.exchange(true)) {
    worker_ = std::thread(&InferenceServerGrpcClient::DispatchLoop, this);
  }
  inference::ModelInferRequest request;
  Error err = PreRunProcessing(&request, options, inputs, outputs);
  if (!err.IsOk()) return err;
  std::string request_bytes;
  if (!request.SerializeToString(&request_bytes)) {
    return Error("failed to serialize request");
  }
  inflight_->Add();
  // The tracker shared_ptr keeps the "done" signal alive even if the
  // callback fires after this client object is destroyed; every
  // access to client members happens BEFORE tracker->Sub().
  auto tracker = inflight_;
  Error call_err = channel_->AsyncUnaryCall(
      Method("ModelInfer"), request_bytes,
      [this, callback, tracker](
          const Error& status, std::string&& response_bytes,
          const RequestTimers& timers) {
        auto response = std::make_shared<inference::ModelInferResponse>();
        Error final_status = status;
        if (final_status.IsOk() &&
            !response->ParseFromString(response_bytes)) {
          final_status = Error("failed to parse response");
        }
        UpdateInferStat(timers);
        InferResult* result = nullptr;
        InferResultGrpc::Create(&result, std::move(response), final_status);
        {
          std::lock_guard<std::mutex> lock(mutex_);
          completed_.push_back({callback, result});
        }
        cv_.notify_all();
        tracker->Sub();  // last: no member access beyond this point
      },
      options.client_timeout_us, headers, grpc_compression);
  if (!call_err.IsOk()) inflight_->Sub();
  return call_err;
}

Error InferenceServerGrpcClient::InferMulti(
    std::vector<InferResult*>* results,
    const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  // Parity with reference semantics (grpc_client.cc:1213): one
  // options entry may fan out over all requests.
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("options size must be 1 or match inputs size");
  }
  results->clear();
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? std::vector<const InferRequestedOutput*>{}
                           : outputs[outputs.size() == 1 ? 0 : i];
    InferResult* result = nullptr;
    Error err = Infer(&result, opt, inputs[i], outs, headers);
    if (!err.IsOk()) {
      for (auto* r : *results) delete r;
      results->clear();
      return err;
    }
    results->push_back(result);
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::AsyncInferMulti(
    OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInferMulti");
  }
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("options size must be 1 or match inputs size");
  }
  struct MultiState {
    std::mutex mutex;
    std::vector<InferResult*> results;
    size_t remaining;
    OnMultiCompleteFn callback;
  };
  auto state = std::make_shared<MultiState>();
  state->results.resize(inputs.size(), nullptr);
  state->remaining = inputs.size();
  state->callback = std::move(callback);
  for (size_t i = 0; i < inputs.size(); ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? std::vector<const InferRequestedOutput*>{}
                           : outputs[outputs.size() == 1 ? 0 : i];
    Error err = AsyncInfer(
        [state, i](InferResult* result) {
          bool fire = false;
          {
            std::lock_guard<std::mutex> lock(state->mutex);
            state->results[i] = result;
            fire = (--state->remaining == 0);
          }
          if (fire) state->callback(state->results);
        },
        opt, inputs[i], outs, headers);
    if (!err.IsOk()) {
      InferResult* error_result = nullptr;
      auto response = std::make_shared<inference::ModelInferResponse>();
      InferResultGrpc::Create(&error_result, std::move(response), err);
      bool fire = false;
      {
        std::lock_guard<std::mutex> lock(state->mutex);
        state->results[i] = error_result;
        fire = (--state->remaining == 0);
      }
      if (fire) state->callback(state->results);
    }
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::StartStream(
    OnCompleteFn callback, bool enable_stats, uint32_t stream_timeout,
    const Headers& headers) {
  if (callback == nullptr) {
    return Error("callback must not be null for StartStream");
  }
  std::lock_guard<std::mutex> stream_lock(stream_mutex_);
  if (bidi_stream_ != nullptr) {
    return Error("cannot start another stream with one already running");
  }
  if (!dispatch_started_.exchange(true)) {
    worker_ = std::thread(&InferenceServerGrpcClient::DispatchLoop, this);
  }
  stream_callback_ = std::move(callback);
  stream_stats_ = enable_stats;
  Headers stream_headers = headers;
  if (stream_timeout > 0) {
    stream_headers["grpc-timeout"] = std::to_string(stream_timeout) + "u";
  }
  return channel_->StartBidiStream(
      &bidi_stream_, Method("ModelStreamInfer"),
      [this](std::string&& message_bytes) {
        auto stream_response =
            std::make_shared<inference::ModelStreamInferResponse>();
        Error status = Error::Success;
        if (!stream_response->ParseFromString(message_bytes)) {
          status = Error("failed to parse stream response");
        }
        InferResult* result = nullptr;
        InferResultGrpc::Create(&result, std::move(stream_response));
        {
          std::lock_guard<std::mutex> lock(mutex_);
          completed_.push_back({stream_callback_, result});
        }
        cv_.notify_all();
      },
      [this](const Error& status) {
        if (!status.IsOk()) {
          // Surface terminal stream errors as a result with error
          // status (parity: grpc_client.cc:1663-1669).
          auto response = std::make_shared<inference::ModelInferResponse>();
          InferResult* result = nullptr;
          InferResultGrpc::Create(&result, std::move(response), status);
          std::lock_guard<std::mutex> lock(mutex_);
          if (stream_callback_) completed_.push_back({stream_callback_, result});
          cv_.notify_all();
        }
      },
      stream_headers);
}

Error InferenceServerGrpcClient::StopStream() {
  std::lock_guard<std::mutex> stream_lock(stream_mutex_);
  if (bidi_stream_ == nullptr) return Error::Success;
  bidi_stream_->WritesDone();
  Error err = bidi_stream_->Finish();
  bidi_stream_.reset();
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stream_callback_ = nullptr;
  }
  return err;
}

Error InferenceServerGrpcClient::AsyncStreamInfer(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  if (bidi_stream_ == nullptr) {
    return Error("stream not established, use StartStream() first");
  }
  inference::ModelInferRequest request;
  Error err = PreRunProcessing(&request, options, inputs, outputs);
  if (!err.IsOk()) return err;
  std::string request_bytes;
  if (!request.SerializeToString(&request_bytes)) {
    return Error("failed to serialize request");
  }
  return bidi_stream_->Write(request_bytes);
}

void InferenceServerGrpcClient::DispatchLoop() {
  while (true) {
    Completed item;
    {
      std::unique_lock<std::mutex> lock(mutex_);
      cv_.wait(lock, [this] { return exiting_ || !completed_.empty(); });
      if (completed_.empty()) {
        if (exiting_) return;
        continue;
      }
      item = std::move(completed_.front());
      completed_.pop_front();
    }
    if (item.callback) {
      item.callback(item.result);
    } else {
      delete item.result;
    }
  }
}

}  // namespace tpuclient
