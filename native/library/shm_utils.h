// POSIX shared-memory helpers (parity: reference
// /root/reference/src/c++/library/shm_utils.h:38-64).
#pragma once

#include <cstddef>
#include <string>

#include "common.h"

namespace tpuclient {

// Creates a POSIX shared-memory region (shm_open + ftruncate) and
// returns its fd.
Error CreateSharedMemoryRegion(
    const std::string& shm_key, size_t byte_size, int* shm_fd);

// Maps `byte_size` bytes at `offset` of the region into this process.
Error MapSharedMemory(
    int shm_fd, size_t offset, size_t byte_size, void** shm_addr);

// Closes the region fd.
Error CloseSharedMemory(int shm_fd);

// Removes the named region from the system.
Error UnlinkSharedMemoryRegion(const std::string& shm_key);

// Unmaps a mapping obtained from MapSharedMemory.
Error UnmapSharedMemory(void* shm_addr, size_t byte_size);

}  // namespace tpuclient
