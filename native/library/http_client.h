// HTTP/REST (KServe-v2) client for the TPU inference server.
//
// Mirrors the reference InferenceServerHttpClient surface
// (/root/reference/src/c++/library/http_client.h:105): the same ~25
// endpoint methods, the binary tensor protocol with
// Inference-Header-Content-Length, sync Infer and callback-async
// AsyncInfer, and static GenerateRequestBody/ParseResponseBody.
// Transport is a self-contained POSIX-socket HTTP/1.1 implementation
// with keep-alive and a worker pool for async (the reference uses
// libcurl easy/multi, which this image does not provide).
//
// The CUDA shared-memory verbs are replaced by TPU HBM arena verbs:
// RegisterTpuSharedMemory posts the serialized arena-region
// descriptor where the reference posts a base64 cudaIpcMemHandle_t
// (http_client.cc:1712).
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "common.h"
#include "compression.h"
#include "json.h"
#include "tls.h"

namespace tpuclient {

class HttpConnection;

//==============================================================================
// Result of an HTTP inference (parity: InferResultHttp,
// http_client.cc:740).
//
class InferResultHttp : public InferResult {
 public:
  // Takes ownership of `body`; parses the v2 JSON header + trailing
  // binary segments.
  static Error Create(
      InferResult** result, std::string&& body, size_t header_length,
      const Error& request_status = Error::Success);

  Error ModelName(std::string* name) const override;
  Error ModelVersion(std::string* version) const override;
  Error Id(std::string* id) const override;
  Error Shape(
      const std::string& output_name,
      std::vector<int64_t>* shape) const override;
  Error Datatype(
      const std::string& output_name, std::string* datatype) const override;
  Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const override;
  Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const override;
  std::string DebugString() const override;
  Error RequestStatus() const override;

  // Response-header dict access (parameters etc.).
  const json::Value& Header() const { return header_; }

 private:
  struct Output {
    std::string datatype;
    std::vector<int64_t> shape;
    const uint8_t* raw = nullptr;  // into body_, or nullptr
    size_t raw_size = 0;
    json::Value json_data;         // when not binary
    // Lazily packed wire bytes for JSON-data outputs, so RawData()
    // works identically in both tensor formats.
    mutable std::string decoded;
    mutable bool decode_attempted = false;
    bool in_shm = false;
  };

  Error FindOutput(const std::string& name, const Output** out) const;

  std::string body_;
  json::Value header_;
  std::map<std::string, Output> outputs_;
  Error status_;
};

//==============================================================================
// The HTTP client (parity: http_client.h:105).
//
class InferenceServerHttpClient : public InferenceServerClient {
 public:
  ~InferenceServerHttpClient() override;

  // url is "host:port" (no scheme) like the reference; an
  // "https://" scheme prefix selects TLS.
  static Error Create(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const std::string& url, bool verbose = false);

  // TLS variant (parity: http_client.h:105 Create-with-HttpSslOptions).
  static Error Create(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const std::string& url, const SslOptions& ssl_options,
      bool verbose = false);

  Error IsServerLive(bool* live, const Headers& headers = {});
  Error IsServerReady(bool* ready, const Headers& headers = {});
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "", const Headers& headers = {});

  Error ServerMetadata(std::string* server_metadata, const Headers& headers = {});
  Error ModelMetadata(
      std::string* model_metadata, const std::string& model_name,
      const std::string& model_version = "", const Headers& headers = {});
  Error ModelConfig(
      std::string* model_config, const std::string& model_name,
      const std::string& model_version = "", const Headers& headers = {});
  Error ModelRepositoryIndex(
      std::string* repository_index, const Headers& headers = {});
  Error LoadModel(
      const std::string& model_name, const Headers& headers = {},
      const std::string& config = "");
  Error UnloadModel(const std::string& model_name, const Headers& headers = {});
  Error ModelInferenceStatistics(
      std::string* infer_stat, const std::string& model_name = "",
      const std::string& model_version = "", const Headers& headers = {});

  Error UpdateTraceSettings(
      std::string* response, const std::string& model_name = "",
      const std::map<std::string, std::vector<std::string>>& settings = {},
      const Headers& headers = {});
  Error GetTraceSettings(
      std::string* settings, const std::string& model_name = "",
      const Headers& headers = {});
  Error UpdateLogSettings(
      std::string* response,
      const std::map<std::string, std::string>& settings,
      const Headers& headers = {});
  Error GetLogSettings(std::string* settings, const Headers& headers = {});

  Error SystemSharedMemoryStatus(
      std::string* status, const std::string& region_name = "",
      const Headers& headers = {});
  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0, const Headers& headers = {});
  Error UnregisterSystemSharedMemory(
      const std::string& name = "", const Headers& headers = {});

  // TPU HBM arena regions (replaces Register/UnregisterCudaSharedMemory).
  Error TpuSharedMemoryStatus(
      std::string* status, const std::string& region_name = "",
      const Headers& headers = {});
  Error RegisterTpuSharedMemory(
      const std::string& name, const std::string& raw_handle,
      int64_t device_id, size_t byte_size, const Headers& headers = {});
  Error UnregisterTpuSharedMemory(
      const std::string& name = "", const Headers& headers = {});

  // Compression args select per-call gzip/deflate on the request
  // body and (via Accept-Encoding) the response body (parity:
  // http_client.cc:2130-2247).
  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = {}, const Parameters& query_params = {},
      CompressionType request_compression = CompressionType::NONE,
      CompressionType response_compression = CompressionType::NONE);

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = {}, const Parameters& query_params = {},
      CompressionType request_compression = CompressionType::NONE,
      CompressionType response_compression = CompressionType::NONE);

  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs = {},
      const Headers& headers = {});
  Error AsyncInferMulti(
      OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs = {},
      const Headers& headers = {});

  // Builds the POST body + json header length without sending
  // (parity: http_client.h:121 GenerateRequestBody).
  static Error GenerateRequestBody(
      std::vector<char>* request_body, size_t* header_length,
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});

  // Parses a response body obtained elsewhere
  // (parity: http_client.h:135 ParseResponseBody).
  static Error ParseResponseBody(
      InferResult** result, std::vector<char>&& response_body,
      size_t header_length);

  // Number of async worker threads (connections). Must be set before
  // the first AsyncInfer; default 4.
  void SetAsyncWorkerCount(size_t count);

 private:
  InferenceServerHttpClient(
      const std::string& url, const SslOptions& ssl_options, bool verbose);

  // Copy-free variant used on the request hot path (the public
  // vector<char> API above wraps it for reference parity).
  static Error GenerateRequestBodyStr(
      std::string* request_body, size_t* header_length,
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs);

  Error Get(
      const std::string& path, const Headers& headers, std::string* response,
      json::Value* parsed);
  Error Post(
      const std::string& path, const std::string& body,
      const Headers& headers, std::string* response, json::Value* parsed);
  Error DoRequest(
      const std::string& method, const std::string& path,
      const std::string& body, const Headers& headers,
      const std::string& content_type, size_t json_header_length,
      std::string* response_body, size_t* response_header_length,
      HttpConnection* conn, uint64_t timeout_us,
      uint64_t* sent_ns = nullptr);

  struct AsyncRequest {
    std::string path;
    std::string body;
    size_t header_length = 0;
    Headers headers;
    uint64_t timeout_us = 0;
    OnCompleteFn callback;
    RequestTimers timers;
  };
  void AsyncWorkerLoop();
  void EnsureAsyncWorkers();

  std::string host_;
  int port_ = 0;
  bool use_tls_ = false;
  SslOptions ssl_options_;

  // Sync path: one persistent connection guarded by a mutex.
  std::unique_ptr<HttpConnection> sync_conn_;
  std::mutex sync_mutex_;

  // Async path: worker pool, each worker owns a connection.
  size_t async_worker_count_ = 4;
  std::vector<std::thread> async_workers_;
  std::deque<std::unique_ptr<AsyncRequest>> async_queue_;
  std::mutex async_mutex_;
  std::condition_variable async_cv_;
  std::atomic<bool> async_exiting_{false};
};

}  // namespace tpuclient
