#include "base64.h"

namespace tpuclient {

static const char kAlphabet[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string Base64Encode(const uint8_t* data, size_t len) {
  std::string out;
  out.reserve(((len + 2) / 3) * 4);
  size_t i = 0;
  while (i + 3 <= len) {
    uint32_t v = (static_cast<uint32_t>(data[i]) << 16) |
                 (static_cast<uint32_t>(data[i + 1]) << 8) |
                 static_cast<uint32_t>(data[i + 2]);
    out.push_back(kAlphabet[(v >> 18) & 0x3F]);
    out.push_back(kAlphabet[(v >> 12) & 0x3F]);
    out.push_back(kAlphabet[(v >> 6) & 0x3F]);
    out.push_back(kAlphabet[v & 0x3F]);
    i += 3;
  }
  size_t rem = len - i;
  if (rem == 1) {
    uint32_t v = static_cast<uint32_t>(data[i]) << 16;
    out.push_back(kAlphabet[(v >> 18) & 0x3F]);
    out.push_back(kAlphabet[(v >> 12) & 0x3F]);
    out.append("==");
  } else if (rem == 2) {
    uint32_t v = (static_cast<uint32_t>(data[i]) << 16) |
                 (static_cast<uint32_t>(data[i + 1]) << 8);
    out.push_back(kAlphabet[(v >> 18) & 0x3F]);
    out.push_back(kAlphabet[(v >> 12) & 0x3F]);
    out.push_back(kAlphabet[(v >> 6) & 0x3F]);
    out.push_back('=');
  }
  return out;
}

std::string Base64Encode(const std::string& data) {
  return Base64Encode(
      reinterpret_cast<const uint8_t*>(data.data()), data.size());
}

static int DecodeChar(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}

bool Base64Decode(const std::string& encoded, std::string* out) {
  out->clear();
  uint32_t acc = 0;
  int bits = 0;
  for (char c : encoded) {
    if (c == '=' || c == '\n' || c == '\r') continue;
    int v = DecodeChar(c);
    if (v < 0) return false;
    acc = (acc << 6) | static_cast<uint32_t>(v);
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out->push_back(static_cast<char>((acc >> bits) & 0xFF));
    }
  }
  return true;
}

}  // namespace tpuclient
