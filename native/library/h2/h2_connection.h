// Minimal HTTP/2 (RFC 9113) client connection over POSIX sockets:
// stream multiplexing, HPACK header compression, flow control, and a
// reader thread that dispatches frames to per-stream callbacks.
//
// This is the transport under the native gRPC client. The reference
// links grpc++ whose channel owns the equivalent machinery
// (/root/reference/src/c++/library/grpc_client.cc:50-152 caches
// channels); this image has no grpc++, so the protocol lives here.
// Cleartext (h2c with prior knowledge) only — same trust model as the
// reference's default insecure channels.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "hpack.h"

namespace tpuclient {
namespace h2 {

// Callbacks fire on the connection's reader thread; keep them quick
// or hand off to another thread (the gRPC layer does the latter for
// user callbacks, mirroring the reference's AsyncTransfer thread).
struct StreamCallbacks {
  // First response header block (e.g. :status, content-type).
  std::function<void(const HeaderList&)> on_headers;
  // A chunk of DATA payload.
  std::function<void(const uint8_t*, size_t)> on_data;
  // Stream finished: trailers (may be empty) + transport error text
  // ("" = clean END_STREAM).
  std::function<void(const HeaderList&, const std::string&)> on_close;
};

class H2Connection {
 public:
  H2Connection(const std::string& host, int port)
      : host_(host), port_(port) {}
  ~H2Connection();

  H2Connection(const H2Connection&) = delete;
  H2Connection& operator=(const H2Connection&) = delete;

  // Establishes TCP + HTTP/2 preface/SETTINGS and starts the reader
  // thread. Returns "" on success.
  std::string Connect(uint64_t timeout_us = 0);
  bool IsConnected() const { return !dead_.load() && fd_ >= 0; }

  // Liveness probing with h2 PING frames (the transport-level
  // equivalent of gRPC keepalive): every `interval_ms` an outstanding
  // PING is sent; a PING unacked for `timeout_ms` fails the
  // connection ("keepalive watchdog"). Call after Connect().
  void EnableKeepAlive(uint64_t interval_ms, uint64_t timeout_ms);

  // Opens a stream by sending a HEADERS frame (END_STREAM unset).
  // Blocks while the peer's MAX_CONCURRENT_STREAMS limit is reached.
  // Returns the stream id (>0) or -1 with *err filled.
  int32_t StartStream(
      const HeaderList& headers, StreamCallbacks callbacks,
      std::string* err);

  // Sends DATA on the stream, honouring peer flow-control windows and
  // max frame size (blocks while windows are exhausted). Set
  // end_stream on the final chunk to half-close.
  std::string SendData(
      int32_t stream_id, const uint8_t* data, size_t len, bool end_stream);

  // Half-closes the send side with an empty DATA+END_STREAM frame.
  std::string CloseSendSide(int32_t stream_id);

  // Sends RST_STREAM (CANCEL) and releases the stream. on_close fires
  // with error "cancelled" if the stream was still open.
  void CancelStream(int32_t stream_id);

  // Closes the socket; fails all open streams.
  void Close();

  size_t num_active_streams();

 private:
  struct Stream {
    StreamCallbacks callbacks;
    int64_t send_window = 0;
    bool saw_headers = false;
    bool closed = false;
    HeaderList response_headers;
    // Accumulates a header block across HEADERS/CONTINUATION.
    std::string header_block;
    bool header_block_end_stream = false;
    bool in_header_block = false;
  };

  std::string SendAll(const char* data, size_t len);
  std::string WriteFrame(
      uint8_t type, uint8_t flags, int32_t stream_id, const char* payload,
      size_t len);
  void ReaderLoop();
  bool ReadExact(char* buf, size_t len);
  void HandleFrame(
      uint8_t type, uint8_t flags, int32_t stream_id,
      const std::string& payload);
  void HandleHeaderBlockDone(int32_t stream_id, Stream* stream);
  // Fails every open stream and marks the connection dead.
  void FailAll(const std::string& error);
  // Removes the stream and fires on_close outside the lock.
  void FinishStream(
      int32_t stream_id, const HeaderList& trailers,
      const std::string& error);

  std::string host_;
  int port_;
  int fd_ = -1;
  std::atomic<bool> dead_{false};
  std::string dead_reason_;

  std::thread reader_;
  std::thread keepalive_;
  std::atomic<bool> keepalive_stop_{false};
  std::atomic<bool> keepalive_expired_{false};
  std::atomic<uint64_t> pings_sent_{0};
  std::atomic<uint64_t> pings_acked_{0};

  std::mutex write_mutex_;
  HpackEncoder encoder_;

  std::mutex mutex_;  // guards everything below
  std::condition_variable cv_;
  std::map<int32_t, std::shared_ptr<Stream>> streams_;
  int32_t next_stream_id_ = 1;
  // Peer-advertised limits.
  int64_t peer_initial_window_ = 65535;
  int64_t peer_conn_window_ = 65535;
  size_t peer_max_frame_size_ = 16384;
  uint64_t peer_max_concurrent_ = 0x7fffffff;

  HpackDecoder decoder_;
};

}  // namespace h2
}  // namespace tpuclient
