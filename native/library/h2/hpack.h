// HPACK (RFC 7541) header compression for the HTTP/2 transport that
// carries gRPC in the TPU client. The reference's grpc_client links
// grpc++ which bundles its own HPACK
// (/root/reference/src/c++/library/grpc_client.cc uses the grpc++
// channel); this image has no grpc++, so the codec is implemented
// here from the RFC.
//
// Encoder strategy: indexed fields for exact static-table matches,
// literal-without-indexing otherwise, never-huffman, no dynamic-table
// insertions (legal per RFC 7541 §6.2.2 and keeps the encoder
// stateless). Decoder implements the full spec — dynamic table,
// size updates, huffman — since the peer (grpcio) uses all of it.
#pragma once

#include <cstdint>
#include <deque>
#include <string>
#include <utility>
#include <vector>

namespace tpuclient {
namespace h2 {

using HeaderList = std::vector<std::pair<std::string, std::string>>;

// Appends the RFC 7541 §5.1 variable-length integer encoding of
// `value` with an `prefix_bits`-bit prefix, OR-ing `first_byte_flags`
// into the first byte.
void EncodeInteger(
    uint64_t value, uint8_t prefix_bits, uint8_t first_byte_flags,
    std::string* out);

// Decodes an integer at data[*pos]; advances *pos. Returns false on
// truncation/overflow.
bool DecodeInteger(
    const uint8_t* data, size_t len, size_t* pos, uint8_t prefix_bits,
    uint64_t* value);

// Decodes an HPACK huffman-coded string (RFC 7541 §5.2 / Appendix B).
// Returns false on invalid padding or EOS in the stream.
bool HuffmanDecode(const uint8_t* data, size_t len, std::string* out);

class HpackEncoder {
 public:
  // Encodes a header block fragment for one HEADERS frame.
  std::string Encode(const HeaderList& headers) const;
};

class HpackDecoder {
 public:
  explicit HpackDecoder(size_t max_dynamic_size = 4096)
      : max_size_(max_dynamic_size), settings_cap_(max_dynamic_size) {}

  // Decodes one complete header block. Returns empty string on
  // success, else an error description (connection error per RFC).
  std::string Decode(const uint8_t* data, size_t len, HeaderList* out);

  // SETTINGS_HEADER_TABLE_SIZE from our side caps what dynamic-table
  // size updates the peer may choose.
  void SetSettingsCap(size_t cap) { settings_cap_ = cap; }

  size_t dynamic_size() const { return dynamic_bytes_; }

 private:
  struct Entry {
    std::string name;
    std::string value;
  };

  bool LookupIndex(uint64_t index, std::string* name, std::string* value);
  void InsertDynamic(const std::string& name, const std::string& value);
  void EvictTo(size_t target);

  std::deque<Entry> dynamic_;  // front = most recent (index 62)
  size_t dynamic_bytes_ = 0;
  size_t max_size_;
  size_t settings_cap_;
};

}  // namespace h2
}  // namespace tpuclient
