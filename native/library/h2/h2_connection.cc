#include "h2_connection.h"

#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstring>

namespace tpuclient {
namespace h2 {

namespace {

constexpr uint8_t kFrameData = 0x0;
constexpr uint8_t kFrameHeaders = 0x1;
constexpr uint8_t kFramePriority = 0x2;
constexpr uint8_t kFrameRstStream = 0x3;
constexpr uint8_t kFrameSettings = 0x4;
constexpr uint8_t kFramePushPromise = 0x5;
constexpr uint8_t kFramePing = 0x6;
constexpr uint8_t kFrameGoaway = 0x7;
constexpr uint8_t kFrameWindowUpdate = 0x8;
constexpr uint8_t kFrameContinuation = 0x9;

constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;

constexpr uint16_t kSettingsHeaderTableSize = 0x1;
constexpr uint16_t kSettingsEnablePush = 0x2;
constexpr uint16_t kSettingsMaxConcurrentStreams = 0x3;
constexpr uint16_t kSettingsInitialWindowSize = 0x4;
constexpr uint16_t kSettingsMaxFrameSize = 0x5;

// Our advertised per-stream receive window. Receive-side flow
// control is kept trivially open: every DATA frame is immediately
// re-credited with WINDOW_UPDATEs, so windows never shrink in
// steady state and large tensors stream without stalls.
constexpr int64_t kOurInitialWindow = 1 << 24;  // 16 MB
constexpr size_t kOurMaxFrameSize = 1 << 20;    // 1 MB

const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

uint64_t NowNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void PutU32(char* p, uint32_t v) {
  p[0] = static_cast<char>(v >> 24);
  p[1] = static_cast<char>(v >> 16);
  p[2] = static_cast<char>(v >> 8);
  p[3] = static_cast<char>(v);
}

uint32_t GetU32(const char* p) {
  const uint8_t* u = reinterpret_cast<const uint8_t*>(p);
  return (static_cast<uint32_t>(u[0]) << 24) |
         (static_cast<uint32_t>(u[1]) << 16) |
         (static_cast<uint32_t>(u[2]) << 8) | u[3];
}

}  // namespace

H2Connection::~H2Connection() { Close(); }

std::string H2Connection::Connect(uint64_t timeout_us) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(port_);
  int rc = getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    return "failed to resolve " + host_ + ": " + gai_strerror(rc);
  }
  uint64_t deadline_ns = (timeout_us != 0) ? NowNs() + timeout_us * 1000ull : 0;
  std::string err;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    int fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) {
      err = strerror(errno);
      continue;
    }
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
    int rc2 = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc2 != 0 && errno == EINPROGRESS) {
      struct pollfd pfd = {fd, POLLOUT, 0};
      while (true) {
        int pr = poll(&pfd, 1, 50);
        if (pr > 0) break;
        if (deadline_ns != 0 && NowNs() > deadline_ns) {
          err = "connect timeout";
          break;
        }
        if (pr < 0 && errno != EINTR) {
          err = strerror(errno);
          break;
        }
      }
      if (err.empty()) {
        int so_error = 0;
        socklen_t slen = sizeof(so_error);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &so_error, &slen);
        rc2 = (so_error == 0) ? 0 : (err = strerror(so_error), -1);
      } else {
        rc2 = -1;
      }
    } else if (rc2 != 0) {
      err = strerror(errno);
    }
    if (rc2 == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // Blocking mode from here: the reader thread blocks in recv and
      // writers use a poll loop for partial sends.
      fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) & ~O_NONBLOCK);
      fd_ = fd;
      err.clear();
      break;
    }
    ::close(fd);
  }
  freeaddrinfo(res);
  if (fd_ < 0) {
    return "failed to connect to " + host_ + ":" + port_str + ": " + err;
  }

  // Client preface + SETTINGS + a big connection-level window.
  std::string settings;
  auto add_setting = [&settings](uint16_t id, uint32_t value) {
    char buf[6];
    buf[0] = static_cast<char>(id >> 8);
    buf[1] = static_cast<char>(id);
    PutU32(buf + 2, value);
    settings.append(buf, 6);
  };
  add_setting(kSettingsEnablePush, 0);
  add_setting(kSettingsInitialWindowSize, kOurInitialWindow);
  add_setting(kSettingsMaxFrameSize, kOurMaxFrameSize);
  {
    std::lock_guard<std::mutex> wl(write_mutex_);
    std::string e = SendAll(kPreface, sizeof(kPreface) - 1);
    if (e.empty()) {
      e = WriteFrame(kFrameSettings, 0, 0, settings.data(), settings.size());
    }
    if (e.empty()) {
      // Grow the connection receive window to 1 GB; with the eager
      // re-credit below it never drains.
      char wu[4];
      PutU32(wu, (1u << 30) - 65535);
      e = WriteFrame(kFrameWindowUpdate, 0, 0, wu, 4);
    }
    if (!e.empty()) {
      ::close(fd_);
      fd_ = -1;
      return e;
    }
  }
  reader_ = std::thread(&H2Connection::ReaderLoop, this);
  return "";
}

std::string H2Connection::SendAll(const char* data, size_t len) {
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd_, data + sent, len - sent, MSG_NOSIGNAL);
    if (n > 0) {
      sent += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)) {
      struct pollfd pfd = {fd_, POLLOUT, 0};
      poll(&pfd, 1, 50);
      continue;
    }
    return std::string("send failed: ") + strerror(errno);
  }
  return "";
}

std::string H2Connection::WriteFrame(
    uint8_t type, uint8_t flags, int32_t stream_id, const char* payload,
    size_t len) {
  char header[9];
  header[0] = static_cast<char>(len >> 16);
  header[1] = static_cast<char>(len >> 8);
  header[2] = static_cast<char>(len);
  header[3] = static_cast<char>(type);
  header[4] = static_cast<char>(flags);
  PutU32(header + 5, static_cast<uint32_t>(stream_id));
  std::string err = SendAll(header, 9);
  if (!err.empty() || len == 0) return err;
  return SendAll(payload, len);
}

int32_t H2Connection::StartStream(
    const HeaderList& headers, StreamCallbacks callbacks, std::string* err) {
  int32_t stream_id = -1;
  size_t max_frame = 16384;
  auto stream = std::make_shared<Stream>();
  stream->callbacks = std::move(callbacks);
  // Stream IDs must hit the wire in increasing order (RFC 9113 §5.1.1),
  // so the ID is allocated while already holding write_mutex_ and the
  // HEADERS frame goes out before releasing it. Lock order is always
  // write_mutex_ → mutex_ (never nested the other way), and the
  // concurrency-limit wait happens with neither held so the reader
  // thread can keep re-crediting windows and retiring streams.
  std::unique_lock<std::mutex> wl(write_mutex_, std::defer_lock);
  while (stream_id < 0) {
    {
      std::unique_lock<std::mutex> lock(mutex_);
      cv_.wait(lock, [this] {
        return dead_.load() || streams_.size() < peer_max_concurrent_;
      });
    }
    wl.lock();
    {
      std::lock_guard<std::mutex> lock(mutex_);
      if (dead_.load()) {
        *err = "connection closed: " + dead_reason_;
        return -1;
      }
      if (streams_.size() < peer_max_concurrent_) {
        stream_id = next_stream_id_;
        next_stream_id_ += 2;
        stream->send_window = peer_initial_window_;
        max_frame = peer_max_frame_size_;
        streams_[stream_id] = stream;
      }
    }
    if (stream_id < 0) wl.unlock();  // limit hit again; re-wait
  }

  std::string block = encoder_.Encode(headers);
  // Header block fits one frame (our blocks are tiny; peer
  // MAX_FRAME_SIZE is ≥16384 which far exceeds gRPC request
  // headers). Chunk defensively anyway.
  size_t pos = 0;
  bool first = true;
  do {
    size_t chunk = std::min(block.size() - pos, max_frame);
    bool last = (pos + chunk == block.size());
    uint8_t type = first ? kFrameHeaders : kFrameContinuation;
    uint8_t flags = last ? kFlagEndHeaders : 0;
    std::string e =
        WriteFrame(type, flags, stream_id, block.data() + pos, chunk);
    if (!e.empty()) {
      std::lock_guard<std::mutex> lock(mutex_);
      streams_.erase(stream_id);
      *err = e;
      return -1;
    }
    pos += chunk;
    first = false;
  } while (pos < block.size());
  return stream_id;
}

std::string H2Connection::SendData(
    int32_t stream_id, const uint8_t* data, size_t len, bool end_stream) {
  size_t pos = 0;
  while (pos < len || (end_stream && len == 0)) {
    size_t chunk;
    {
      std::unique_lock<std::mutex> lock(mutex_);
      auto it = streams_.find(stream_id);
      if (it == streams_.end()) return "stream closed";
      if (len == 0) {
        chunk = 0;
      } else {
        auto stream = it->second;
        cv_.wait(lock, [&] {
          return dead_.load() || stream->closed ||
                 (peer_conn_window_ > 0 && stream->send_window > 0);
        });
        if (dead_.load()) return "connection closed: " + dead_reason_;
        if (stream->closed || streams_.find(stream_id) == streams_.end())
          return "stream closed";
        chunk = std::min<size_t>(
            {len - pos, peer_max_frame_size_,
             static_cast<size_t>(peer_conn_window_),
             static_cast<size_t>(stream->send_window)});
        peer_conn_window_ -= chunk;
        stream->send_window -= chunk;
      }
    }
    bool last = (pos + chunk == len);
    uint8_t flags = (last && end_stream) ? kFlagEndStream : 0;
    std::lock_guard<std::mutex> wl(write_mutex_);
    std::string e = WriteFrame(
        kFrameData, flags, stream_id,
        reinterpret_cast<const char*>(data) + pos, chunk);
    if (!e.empty()) return e;
    pos += chunk;
    if (last) break;
  }
  return "";
}

std::string H2Connection::CloseSendSide(int32_t stream_id) {
  return SendData(stream_id, nullptr, 0, true);
}

void H2Connection::CancelStream(int32_t stream_id) {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    if (streams_.find(stream_id) == streams_.end()) return;
  }
  char payload[4];
  PutU32(payload, 0x8);  // CANCEL
  {
    std::lock_guard<std::mutex> wl(write_mutex_);
    WriteFrame(kFrameRstStream, 0, stream_id, payload, 4);
  }
  FinishStream(stream_id, {}, "cancelled");
}

size_t H2Connection::num_active_streams() {
  std::lock_guard<std::mutex> lock(mutex_);
  return streams_.size();
}

void H2Connection::EnableKeepAlive(uint64_t interval_ms,
                                   uint64_t timeout_ms) {
  if (keepalive_.joinable()) return;
  keepalive_ = std::thread([this, interval_ms, timeout_ms] {
    const char payload[8] = {'k', 'e', 'e', 'p', 'a', 'l', 'v', 0};
    while (!keepalive_stop_.load() && !dead_.load()) {
      {
        std::lock_guard<std::mutex> wl(write_mutex_);
        if (WriteFrame(kFramePing, 0, 0, payload, 8).empty()) {
          pings_sent_.fetch_add(1);
        }
      }
      // Wait for the ack within timeout_ms, polling in small steps so
      // stop/death are noticed promptly.
      uint64_t waited = 0;
      while (waited < timeout_ms && !keepalive_stop_.load() &&
             !dead_.load() && pings_acked_.load() < pings_sent_.load()) {
        std::this_thread::sleep_for(std::chrono::milliseconds(20));
        waited += 20;
      }
      if (pings_acked_.load() < pings_sent_.load() &&
          !keepalive_stop_.load() && !dead_.load()) {
        // Only flag + kill the socket here: the reader thread then
        // fails the streams and fires user callbacks on ITS thread.
        // Running FailAll from this thread could destroy the
        // connection inside a user callback while this thread still
        // touches members (use-after-free, then std::terminate on
        // the joinable thread member).
        keepalive_expired_.store(true);
        ::shutdown(fd_, SHUT_RDWR);
        return;
      }
      uint64_t slept = 0;
      while (slept < interval_ms && !keepalive_stop_.load() &&
             !dead_.load()) {
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
        slept += 50;
      }
    }
  });
}

void H2Connection::Close() {
  keepalive_stop_.store(true);
  if (fd_ >= 0) {
    // Socket shutdown FIRST: it unsticks a keepalive PING send wedged
    // in SendAll's retry loop, so the join below can't hang.
    ::shutdown(fd_, SHUT_RDWR);
  }
  if (keepalive_.joinable() &&
      keepalive_.get_id() != std::this_thread::get_id()) {
    keepalive_.join();
  }
  if (reader_.joinable() &&
      reader_.get_id() != std::this_thread::get_id()) {
    reader_.join();
  }
  FailAll("connection closed");
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool H2Connection::ReadExact(char* buf, size_t len) {
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd_, buf + got, len - got, 0);
    if (n > 0) {
      got += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && errno == EINTR) continue;
    return false;
  }
  return true;
}

void H2Connection::ReaderLoop() {
  char header[9];
  std::string payload;
  while (true) {
    if (!ReadExact(header, 9)) {
      FailAll(keepalive_expired_.load()
                  ? "keepalive timeout: PING unacked"
                  : "connection reset");
      return;
    }
    size_t len = (static_cast<size_t>(static_cast<uint8_t>(header[0])) << 16) |
                 (static_cast<size_t>(static_cast<uint8_t>(header[1])) << 8) |
                 static_cast<uint8_t>(header[2]);
    uint8_t type = static_cast<uint8_t>(header[3]);
    uint8_t flags = static_cast<uint8_t>(header[4]);
    int32_t stream_id =
        static_cast<int32_t>(GetU32(header + 5) & 0x7fffffffu);
    if (len > kOurMaxFrameSize + 1024) {
      FailAll("oversized frame");
      return;
    }
    payload.resize(len);
    if (len > 0 && !ReadExact(&payload[0], len)) {
      FailAll("connection reset mid-frame");
      return;
    }
    HandleFrame(type, flags, stream_id, payload);
    if (dead_.load()) return;
  }
}

void H2Connection::HandleFrame(
    uint8_t type, uint8_t flags, int32_t stream_id,
    const std::string& payload) {
  switch (type) {
    case kFrameData: {
      std::shared_ptr<Stream> stream;
      {
        std::lock_guard<std::mutex> lock(mutex_);
        auto it = streams_.find(stream_id);
        if (it != streams_.end()) stream = it->second;
      }
      size_t data_len = payload.size();
      const char* data = payload.data();
      if (flags & kFlagPadded) {
        if (payload.empty()) break;
        uint8_t pad = static_cast<uint8_t>(payload[0]);
        if (static_cast<size_t>(pad) + 1 > payload.size()) break;
        data += 1;
        data_len = payload.size() - 1 - pad;
      }
      if (stream && data_len > 0 && stream->callbacks.on_data) {
        stream->callbacks.on_data(
            reinterpret_cast<const uint8_t*>(data), data_len);
      }
      // Eagerly re-credit both windows so they never drain.
      if (!payload.empty()) {
        char wu[4];
        PutU32(wu, static_cast<uint32_t>(payload.size()));
        std::lock_guard<std::mutex> wl(write_mutex_);
        WriteFrame(kFrameWindowUpdate, 0, 0, wu, 4);
        if (!(flags & kFlagEndStream)) {
          WriteFrame(kFrameWindowUpdate, 0, stream_id, wu, 4);
        }
      }
      if (flags & kFlagEndStream) {
        FinishStream(stream_id, {}, "");
      }
      break;
    }
    case kFrameHeaders: {
      std::shared_ptr<Stream> stream;
      {
        std::lock_guard<std::mutex> lock(mutex_);
        auto it = streams_.find(stream_id);
        if (it != streams_.end()) stream = it->second;
      }
      size_t off = 0;
      size_t len = payload.size();
      if (flags & kFlagPadded) {
        if (len < 1) break;
        uint8_t pad = static_cast<uint8_t>(payload[0]);
        off += 1;
        if (len < off + pad) break;
        len -= pad;
      }
      if (flags & kFlagPriority) {
        if (len < off + 5) break;
        off += 5;
      }
      if (!stream) {
        // Unknown stream: still must feed HPACK decoder to keep
        // dynamic-table state in sync.
        HeaderList ignored;
        decoder_.Decode(
            reinterpret_cast<const uint8_t*>(payload.data()) + off,
            len - off, &ignored);
        break;
      }
      stream->header_block.assign(payload, off, len - off);
      stream->header_block_end_stream = (flags & kFlagEndStream) != 0;
      stream->in_header_block = true;
      if (flags & kFlagEndHeaders) {
        HandleHeaderBlockDone(stream_id, stream.get());
      }
      break;
    }
    case kFrameContinuation: {
      std::shared_ptr<Stream> stream;
      {
        std::lock_guard<std::mutex> lock(mutex_);
        auto it = streams_.find(stream_id);
        if (it != streams_.end()) stream = it->second;
      }
      if (!stream || !stream->in_header_block) break;
      stream->header_block.append(payload);
      if (flags & kFlagEndHeaders) {
        HandleHeaderBlockDone(stream_id, stream.get());
      }
      break;
    }
    case kFrameSettings: {
      if (flags & kFlagAck) break;
      int64_t window_delta = 0;
      {
        std::lock_guard<std::mutex> lock(mutex_);
        for (size_t i = 0; i + 6 <= payload.size(); i += 6) {
          uint16_t id =
              (static_cast<uint16_t>(static_cast<uint8_t>(payload[i])) << 8) |
              static_cast<uint8_t>(payload[i + 1]);
          uint32_t value = GetU32(payload.data() + i + 2);
          switch (id) {
            case kSettingsInitialWindowSize:
              window_delta =
                  static_cast<int64_t>(value) - peer_initial_window_;
              peer_initial_window_ = value;
              for (auto& kv : streams_) kv.second->send_window += window_delta;
              break;
            case kSettingsMaxFrameSize:
              peer_max_frame_size_ = value;
              break;
            case kSettingsMaxConcurrentStreams:
              peer_max_concurrent_ = value;
              break;
            case kSettingsHeaderTableSize:
              // The peer's SETTINGS_HEADER_TABLE_SIZE constrains OUR
              // encoder's dynamic table (which is stateless: every
              // header is sent as a non-indexed literal, so any value
              // is honored). The decoder's cap stays at the locally
              // advertised size (4096 default) — lowering it from the
              // peer's value would reject the peer's own legitimate
              // table-size updates.
              break;
            default:
              break;
          }
        }
      }
      cv_.notify_all();
      {
        std::lock_guard<std::mutex> wl(write_mutex_);
        WriteFrame(kFrameSettings, kFlagAck, 0, nullptr, 0);
      }
      break;
    }
    case kFramePing: {
      if (flags & kFlagAck) {
        pings_acked_.fetch_add(1);
      } else if (payload.size() == 8) {
        std::lock_guard<std::mutex> wl(write_mutex_);
        WriteFrame(kFramePing, kFlagAck, 0, payload.data(), 8);
      }
      break;
    }
    case kFrameWindowUpdate: {
      if (payload.size() != 4) break;
      uint32_t increment = GetU32(payload.data()) & 0x7fffffffu;
      {
        std::lock_guard<std::mutex> lock(mutex_);
        if (stream_id == 0) {
          peer_conn_window_ += increment;
        } else {
          auto it = streams_.find(stream_id);
          if (it != streams_.end()) it->second->send_window += increment;
        }
      }
      cv_.notify_all();
      break;
    }
    case kFrameRstStream: {
      uint32_t code =
          payload.size() >= 4 ? GetU32(payload.data()) : 0xffffffffu;
      FinishStream(
          stream_id, {}, "stream reset by server (code " +
                             std::to_string(code) + ")");
      break;
    }
    case kFrameGoaway: {
      std::string reason = "GOAWAY";
      if (payload.size() > 8) {
        reason += ": " + payload.substr(8);
      }
      FailAll(reason);
      break;
    }
    case kFramePriority:
    case kFramePushPromise:
    default:
      break;  // ignore (push is disabled via SETTINGS)
  }
}

void H2Connection::HandleHeaderBlockDone(int32_t stream_id, Stream* stream) {
  stream->in_header_block = false;
  HeaderList headers;
  std::string err = decoder_.Decode(
      reinterpret_cast<const uint8_t*>(stream->header_block.data()),
      stream->header_block.size(), &headers);
  stream->header_block.clear();
  if (!err.empty()) {
    FailAll(err);
    return;
  }
  if (!stream->saw_headers) {
    stream->saw_headers = true;
    stream->response_headers = headers;
    if (stream->callbacks.on_headers) stream->callbacks.on_headers(headers);
    if (stream->header_block_end_stream) {
      // Trailers-only response: headers double as trailers.
      FinishStream(stream_id, headers, "");
    }
  } else {
    // Trailers.
    FinishStream(stream_id, headers, "");
  }
}

void H2Connection::FinishStream(
    int32_t stream_id, const HeaderList& trailers, const std::string& error) {
  std::shared_ptr<Stream> stream;
  {
    std::lock_guard<std::mutex> lock(mutex_);
    auto it = streams_.find(stream_id);
    if (it == streams_.end()) return;
    stream = it->second;
    stream->closed = true;
    streams_.erase(it);
  }
  cv_.notify_all();
  if (stream->callbacks.on_close) {
    stream->callbacks.on_close(trailers, error);
  }
}

void H2Connection::FailAll(const std::string& error) {
  std::vector<std::shared_ptr<Stream>> doomed;
  {
    std::lock_guard<std::mutex> lock(mutex_);
    if (dead_.exchange(true)) return;
    dead_reason_ = error;
    for (auto& kv : streams_) {
      kv.second->closed = true;
      doomed.push_back(kv.second);
    }
    streams_.clear();
  }
  cv_.notify_all();
  for (auto& stream : doomed) {
    if (stream->callbacks.on_close) {
      stream->callbacks.on_close({}, error);
    }
  }
}

}  // namespace h2
}  // namespace tpuclient
