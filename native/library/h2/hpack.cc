#include "hpack.h"

#include <cstring>

#include "hpack_tables.h"

namespace tpuclient {
namespace h2 {

namespace {

// RFC 7541 §4.1: dynamic-table entry overhead.
constexpr size_t kEntryOverhead = 32;

//------------------------------------------------------------------
// Huffman decode tree, built once from the Appendix B tables.
//
struct HuffNode {
  int16_t next[2] = {-1, -1};  // child node index, or -1
  int16_t symbol = -1;         // 0..255 leaf, 256 EOS, -1 interior
};

class HuffTree {
 public:
  HuffTree() {
    nodes_.emplace_back();  // root
    for (int sym = 0; sym <= 256; ++sym) {
      uint32_t code = kHuffmanCodes[sym];
      uint8_t len = kHuffmanCodeLengths[sym];
      int node = 0;
      for (int bit = len - 1; bit >= 0; --bit) {
        int b = (code >> bit) & 1;
        if (nodes_[node].next[b] < 0) {
          nodes_[node].next[b] = static_cast<int16_t>(nodes_.size());
          nodes_.emplace_back();
        }
        node = nodes_[node].next[b];
      }
      nodes_[node].symbol = static_cast<int16_t>(sym);
    }
  }

  const HuffNode& at(int i) const { return nodes_[i]; }

 private:
  std::vector<HuffNode> nodes_;
};

const HuffTree& huff_tree() {
  static const HuffTree tree;
  return tree;
}

}  // namespace

void EncodeInteger(
    uint64_t value, uint8_t prefix_bits, uint8_t first_byte_flags,
    std::string* out) {
  const uint64_t max_prefix = (1u << prefix_bits) - 1;
  if (value < max_prefix) {
    out->push_back(static_cast<char>(first_byte_flags | value));
    return;
  }
  out->push_back(static_cast<char>(first_byte_flags | max_prefix));
  value -= max_prefix;
  while (value >= 128) {
    out->push_back(static_cast<char>(0x80 | (value & 0x7f)));
    value >>= 7;
  }
  out->push_back(static_cast<char>(value));
}

bool DecodeInteger(
    const uint8_t* data, size_t len, size_t* pos, uint8_t prefix_bits,
    uint64_t* value) {
  if (*pos >= len) return false;
  const uint64_t max_prefix = (1u << prefix_bits) - 1;
  uint64_t v = data[*pos] & max_prefix;
  ++*pos;
  if (v < max_prefix) {
    *value = v;
    return true;
  }
  uint32_t shift = 0;
  while (true) {
    if (*pos >= len) return false;
    uint8_t byte = data[*pos];
    ++*pos;
    if (shift > 56) return false;  // overflow guard
    v += static_cast<uint64_t>(byte & 0x7f) << shift;
    shift += 7;
    if ((byte & 0x80) == 0) break;
  }
  *value = v;
  return true;
}

bool HuffmanDecode(const uint8_t* data, size_t len, std::string* out) {
  const HuffTree& tree = huff_tree();
  int node = 0;
  int depth = 0;  // bits consumed since last emitted symbol
  for (size_t i = 0; i < len; ++i) {
    for (int bit = 7; bit >= 0; --bit) {
      int b = (data[i] >> bit) & 1;
      int next = tree.at(node).next[b];
      if (next < 0) return false;
      node = next;
      ++depth;
      int16_t sym = tree.at(node).symbol;
      if (sym >= 0) {
        if (sym == 256) return false;  // EOS in stream is an error
        out->push_back(static_cast<char>(sym));
        node = 0;
        depth = 0;
      }
    }
  }
  // Remaining bits must be a prefix of EOS (all ones), < 8 bits.
  if (depth >= 8) return false;
  // Walking 1-bits from the current node must not have emitted a
  // symbol; since EOS is all ones, any strict prefix of it decodes to
  // nothing. Check that every consumed padding bit was 1 by verifying
  // the path taken matches ones: re-verify cheaply — the node we're at
  // must lie on the all-ones path from the root.
  int check = 0;
  for (int i = 0; i < depth; ++i) {
    check = tree.at(check).next[1];
    if (check < 0) return false;
  }
  return check == node;
}

namespace {

bool DecodeString(
    const uint8_t* data, size_t len, size_t* pos, std::string* out) {
  if (*pos >= len) return false;
  bool huffman = (data[*pos] & 0x80) != 0;
  uint64_t str_len = 0;
  if (!DecodeInteger(data, len, pos, 7, &str_len)) return false;
  if (str_len > len - *pos) return false;
  if (huffman) {
    if (!HuffmanDecode(data + *pos, str_len, out)) return false;
  } else {
    out->assign(reinterpret_cast<const char*>(data + *pos), str_len);
  }
  *pos += str_len;
  return true;
}

void EncodeString(const std::string& s, std::string* out) {
  EncodeInteger(s.size(), 7, 0x00, out);  // no huffman
  out->append(s);
}

}  // namespace

std::string HpackEncoder::Encode(const HeaderList& headers) const {
  std::string out;
  for (const auto& kv : headers) {
    // Exact static-table match → indexed field (§6.1).
    int name_idx = 0;
    int exact_idx = 0;
    for (int i = 0; i < 61; ++i) {
      if (kv.first == kStaticTable[i].name) {
        if (name_idx == 0) name_idx = i + 1;
        if (kv.second == kStaticTable[i].value) {
          exact_idx = i + 1;
          break;
        }
      }
    }
    if (exact_idx > 0) {
      EncodeInteger(exact_idx, 7, 0x80, &out);
      continue;
    }
    // Literal without indexing (§6.2.2), indexed or new name.
    if (name_idx > 0) {
      EncodeInteger(name_idx, 4, 0x00, &out);
    } else {
      out.push_back(0x00);
      EncodeString(kv.first, &out);
    }
    EncodeString(kv.second, &out);
  }
  return out;
}

bool HpackDecoder::LookupIndex(
    uint64_t index, std::string* name, std::string* value) {
  if (index == 0) return false;
  if (index <= 61) {
    *name = kStaticTable[index - 1].name;
    *value = kStaticTable[index - 1].value;
    return true;
  }
  size_t dyn = index - 62;
  if (dyn >= dynamic_.size()) return false;
  *name = dynamic_[dyn].name;
  *value = dynamic_[dyn].value;
  return true;
}

void HpackDecoder::EvictTo(size_t target) {
  while (dynamic_bytes_ > target && !dynamic_.empty()) {
    const Entry& e = dynamic_.back();
    dynamic_bytes_ -= e.name.size() + e.value.size() + kEntryOverhead;
    dynamic_.pop_back();
  }
}

void HpackDecoder::InsertDynamic(
    const std::string& name, const std::string& value) {
  size_t entry_size = name.size() + value.size() + kEntryOverhead;
  if (entry_size > max_size_) {
    // Larger than the whole table: empties it (§4.4).
    EvictTo(0);
    return;
  }
  EvictTo(max_size_ - entry_size);
  dynamic_.push_front({name, value});
  dynamic_bytes_ += entry_size;
}

std::string HpackDecoder::Decode(
    const uint8_t* data, size_t len, HeaderList* out) {
  size_t pos = 0;
  while (pos < len) {
    uint8_t b = data[pos];
    if (b & 0x80) {
      // Indexed header field (§6.1).
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 7, &index))
        return "hpack: bad indexed field";
      std::string name, value;
      if (!LookupIndex(index, &name, &value))
        return "hpack: index out of range";
      out->emplace_back(std::move(name), std::move(value));
    } else if (b & 0x40) {
      // Literal with incremental indexing (§6.2.1).
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 6, &index))
        return "hpack: bad literal";
      std::string name, value;
      if (index > 0) {
        std::string unused;
        if (!LookupIndex(index, &name, &unused))
          return "hpack: name index out of range";
      } else if (!DecodeString(data, len, &pos, &name)) {
        return "hpack: bad name string";
      }
      if (!DecodeString(data, len, &pos, &value))
        return "hpack: bad value string";
      InsertDynamic(name, value);
      out->emplace_back(std::move(name), std::move(value));
    } else if (b & 0x20) {
      // Dynamic table size update (§6.3).
      uint64_t size = 0;
      if (!DecodeInteger(data, len, &pos, 5, &size))
        return "hpack: bad table size update";
      if (size > settings_cap_) return "hpack: table size above cap";
      max_size_ = size;
      EvictTo(max_size_);
    } else {
      // Literal without indexing (0x00) / never indexed (0x10).
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 4, &index))
        return "hpack: bad literal";
      std::string name, value;
      if (index > 0) {
        std::string unused;
        if (!LookupIndex(index, &name, &unused))
          return "hpack: name index out of range";
      } else if (!DecodeString(data, len, &pos, &name)) {
        return "hpack: bad name string";
      }
      if (!DecodeString(data, len, &pos, &value))
        return "hpack: bad value string";
      out->emplace_back(std::move(name), std::move(value));
    }
  }
  return "";
}

}  // namespace h2
}  // namespace tpuclient
