// Node gRPC sample for the TPU inference server (parity: reference
// src/grpc_generated/javascript/client.js — @grpc/proto-loader over
// the v2 proto, ModelInfer on `simple`).
//
//   npm install @grpc/grpc-js @grpc/proto-loader
//   node client.js localhost:8001
"use strict";

const path = require("path");
const grpc = require("@grpc/grpc-js");
const protoLoader = require("@grpc/proto-loader");

const PROTO = path.join(
  __dirname, "..", "..", "client_tpu", "protocol", "inference.proto");

function int32Bytes(values) {
  const buf = Buffer.alloc(values.length * 4);
  values.forEach((v, i) => buf.writeInt32LE(v, i * 4));
  return buf;
}

function main() {
  const url = process.argv[2] || "localhost:8001";
  const definition = protoLoader.loadSync(PROTO, {
    keepCase: true,
    includeDirs: [path.join(__dirname, "..", "..")],
  });
  const proto = grpc.loadPackageDefinition(definition).inference;
  const client = new proto.GRPCInferenceService(
    url, grpc.credentials.createInsecure());

  client.ServerLive({}, (err, reply) => {
    if (err || !reply.live) {
      console.error("server not live:", err);
      process.exit(1);
    }
    const in0 = Array.from({ length: 16 }, (_, i) => i);
    const in1 = Array.from({ length: 16 }, () => 1);
    const request = {
      model_name: "simple",
      inputs: [
        { name: "INPUT0", datatype: "INT32", shape: [16] },
        { name: "INPUT1", datatype: "INT32", shape: [16] },
      ],
      raw_input_contents: [int32Bytes(in0), int32Bytes(in1)],
    };
    client.ModelInfer(request, (inferErr, response) => {
      if (inferErr) {
        console.error("infer failed:", inferErr);
        process.exit(1);
      }
      const sum = response.raw_output_contents[0];
      for (let i = 0; i < 16; i++) {
        if (sum.readInt32LE(i * 4) !== in0[i] + in1[i]) {
          console.error("mismatch at", i);
          process.exit(1);
        }
      }
      console.log("PASS: infer");
    });
  });
}

main();
