// Java gRPC sample for the TPU inference server (parity: reference
// src/grpc_generated/java — ModelInfer on the `simple` model through
// protoc-generated grpc-java stubs, as opposed to java/src which is a
// full hand-written client speaking the wire protocol itself).
//
// Generate stubs (needs protoc + the protoc-gen-grpc-java plugin):
//
//   protoc -I ../.. \
//     --java_out=src/main/java --grpc-java_out=src/main/java \
//     client_tpu/protocol/inference.proto \
//     client_tpu/protocol/model_config.proto
//
// Build with the grpc-java BOM on the classpath (io.grpc:grpc-netty,
// grpc-protobuf, grpc-stub), then:
//
//   java SimpleGrpcClient localhost:8001
//
// The generated service class is inference.GRPCInferenceServiceGrpc;
// message types live in the inference.* package.

import java.nio.ByteBuffer;
import java.nio.ByteOrder;

import com.google.protobuf.ByteString;

import io.grpc.ManagedChannel;
import io.grpc.ManagedChannelBuilder;

import inference.GRPCInferenceServiceGrpc;
import inference.Inference.ModelInferRequest;
import inference.Inference.ModelInferResponse;
import inference.Inference.ServerLiveRequest;

public final class SimpleGrpcClient {
  public static void main(String[] args) throws Exception {
    String target = args.length > 0 ? args[0] : "localhost:8001";
    ManagedChannel channel =
        ManagedChannelBuilder.forTarget(target).usePlaintext().build();
    try {
      GRPCInferenceServiceGrpc.GRPCInferenceServiceBlockingStub stub =
          GRPCInferenceServiceGrpc.newBlockingStub(channel);

      boolean live = stub.serverLive(
          ServerLiveRequest.newBuilder().build()).getLive();
      if (!live) {
        throw new IllegalStateException("server not live");
      }

      // INPUT0 = 0..15, INPUT1 = 1s, as raw little-endian int32.
      ByteBuffer in0 = ByteBuffer.allocate(16 * 4)
          .order(ByteOrder.LITTLE_ENDIAN);
      ByteBuffer in1 = ByteBuffer.allocate(16 * 4)
          .order(ByteOrder.LITTLE_ENDIAN);
      for (int i = 0; i < 16; ++i) {
        in0.putInt(i);
        in1.putInt(1);
      }
      in0.flip();
      in1.flip();

      ModelInferRequest request = ModelInferRequest.newBuilder()
          .setModelName("simple")
          .addInputs(ModelInferRequest.InferInputTensor.newBuilder()
              .setName("INPUT0").setDatatype("INT32").addShape(16))
          .addInputs(ModelInferRequest.InferInputTensor.newBuilder()
              .setName("INPUT1").setDatatype("INT32").addShape(16))
          .addRawInputContents(ByteString.copyFrom(in0))
          .addRawInputContents(ByteString.copyFrom(in1))
          .build();

      ModelInferResponse response = stub.modelInfer(request);

      ByteBuffer sum = response.getRawOutputContents(0).asReadOnlyByteBuffer()
          .order(ByteOrder.LITTLE_ENDIAN);
      ByteBuffer diff = response.getRawOutputContents(1).asReadOnlyByteBuffer()
          .order(ByteOrder.LITTLE_ENDIAN);
      for (int i = 0; i < 16; ++i) {
        int s = sum.getInt();
        int d = diff.getInt();
        System.out.printf("%d + 1 = %d, %d - 1 = %d%n", i, s, i, d);
        if (s != i + 1 || d != i - 1) {
          throw new IllegalStateException("mismatch at " + i);
        }
      }
      System.out.println("PASS: java grpc sample");
    } finally {
      channel.shutdownNow();
    }
  }
}
