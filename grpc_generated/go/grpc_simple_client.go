// Go gRPC sample for the TPU inference server (parity:
// reference src/grpc_generated/go/grpc_simple_client.go — ModelInfer
// on the `simple` model using protoc-generated stubs).
//
// Generate stubs (needs protoc + protoc-gen-go + protoc-gen-go-grpc):
//
//	protoc -I ../.. \
//	  --go_out=. --go-grpc_out=. \
//	  client_tpu/protocol/inference.proto client_tpu/protocol/model_config.proto
//
// then: go run grpc_simple_client.go -u localhost:8001
package main

import (
	"bytes"
	"context"
	"encoding/binary"
	"flag"
	"log"
	"time"

	"google.golang.org/grpc"
	"google.golang.org/grpc/credentials/insecure"

	pb "tpuclient_go/inference" // adjust to the generated module path
)

func main() {
	url := flag.String("u", "localhost:8001", "server host:port")
	flag.Parse()

	conn, err := grpc.Dial(*url,
		grpc.WithTransportCredentials(insecure.NewCredentials()))
	if err != nil {
		log.Fatalf("connect: %v", err)
	}
	defer conn.Close()
	client := pb.NewGRPCInferenceServiceClient(conn)
	ctx, cancel := context.WithTimeout(context.Background(), 30*time.Second)
	defer cancel()

	live, err := client.ServerLive(ctx, &pb.ServerLiveRequest{})
	if err != nil || !live.Live {
		log.Fatalf("server not live: %v", err)
	}

	// INPUT0 = 0..15, INPUT1 = ones; raw little-endian int32 payloads.
	var in0, in1 bytes.Buffer
	for i := int32(0); i < 16; i++ {
		binary.Write(&in0, binary.LittleEndian, i)
		binary.Write(&in1, binary.LittleEndian, int32(1))
	}
	request := &pb.ModelInferRequest{
		ModelName: "simple",
		Inputs: []*pb.ModelInferRequest_InferInputTensor{
			{Name: "INPUT0", Datatype: "INT32", Shape: []int64{16}},
			{Name: "INPUT1", Datatype: "INT32", Shape: []int64{16}},
		},
		RawInputContents: [][]byte{in0.Bytes(), in1.Bytes()},
	}
	response, err := client.ModelInfer(ctx, request)
	if err != nil {
		log.Fatalf("infer: %v", err)
	}

	sum := make([]int32, 16)
	diff := make([]int32, 16)
	binary.Read(bytes.NewReader(response.RawOutputContents[0]),
		binary.LittleEndian, &sum)
	binary.Read(bytes.NewReader(response.RawOutputContents[1]),
		binary.LittleEndian, &diff)
	for i := 0; i < 16; i++ {
		if sum[i] != int32(i)+1 || diff[i] != int32(i)-1 {
			log.Fatalf("mismatch at %d: %d / %d", i, sum[i], diff[i])
		}
	}
	log.Println("PASS: infer")
}
