"""Mesh + sharding utilities for multi-chip serving and training.

The framework's distributed design follows the JAX SPMD recipe: pick a
``Mesh`` over the device grid, annotate arrays with ``PartitionSpec``s
via logical axis rules, jit, and let XLA insert the collectives (ICI
for intra-slice axes, DCN for the data axis across hosts). Axis
conventions:

- ``dp``   data parallel (batch dim; DCN-friendly)
- ``fsdp`` fully-sharded data parallel (params sharded over dp axis)
- ``tp``   tensor parallel (heads / hidden dims; ICI)
- ``sp``   sequence/context parallel (long-context; ICI)
- ``ep``   expert parallel (MoE)
- ``pp``   pipeline parallel (layer stages)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AxisSpec = Sequence[Tuple[str, int]]


def create_mesh(axes: AxisSpec, devices: Optional[list] = None) -> Mesh:
    """Build a Mesh from ((name, size), ...); size -1 absorbs the
    remaining devices."""
    if devices is None:
        devices = jax.devices()
    names = [name for name, _ in axes]
    sizes = [size for _, size in axes]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            "mesh %s needs %d devices, have %d" % (axes, total, len(devices))
        )
    grid = np.array(devices[:total]).reshape(sizes)
    return Mesh(grid, tuple(names))


def single_device_mesh(device=None) -> Mesh:
    """1x1 mesh — lets the same pjit-ed code run on one chip."""
    if device is None:
        device = jax.devices()[0]
    return Mesh(np.array([device]).reshape(1, 1), ("dp", "tp"))


class ShardingRules:
    """Logical-axis -> mesh-axis mapping (the scaling-book recipe:
    name your array dims logically, map them to mesh axes once)."""

    def __init__(self, rules: Dict[str, Optional[str]]):
        self.rules = dict(rules)

    def spec(self, *logical_axes: Optional[str]) -> PartitionSpec:
        return PartitionSpec(
            *[self.rules.get(axis) if axis else None
              for axis in logical_axes]
        )

    def sharding(self, mesh: Mesh, *logical_axes: Optional[str]
                 ) -> NamedSharding:
        return NamedSharding(mesh, self.spec(*logical_axes))


# Megatron-style rules for transformer serving/training.
LLM_RULES = ShardingRules({
    "batch": "dp",
    "sequence": None,      # "sp" for context parallelism (long seqs)
    "model": None,         # residual stream stays replicated
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "ffn": "tp",
    "vocab": "tp",
    "experts": "ep",
})

LONG_CONTEXT_RULES = ShardingRules({
    **LLM_RULES.rules,
    "sequence": "sp",
})


def shard_params(params, mesh: Mesh, spec_tree):
    """device_put a parameter pytree according to a matching tree of
    PartitionSpecs."""
    return jax.tree.map(
        lambda p, spec: jax.device_put(p, NamedSharding(mesh, spec)),
        params, spec_tree,
    )


def replicate(value, mesh: Mesh):
    return jax.device_put(value, NamedSharding(mesh, PartitionSpec()))
