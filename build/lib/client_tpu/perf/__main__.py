from client_tpu.perf.cli import main

main()
