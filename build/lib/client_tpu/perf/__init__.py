"""Load-generation + profiling harness (perf_analyzer equivalent).

CLI: python -m client_tpu.perf -m <model> [--concurrency-range a:b] ...
"""

from client_tpu.perf.client_backend import (  # noqa: F401
    BackendKind,
    ClientBackend,
    ClientBackendFactory,
    MockBackend,
)
from client_tpu.perf.data_loader import DataLoader  # noqa: F401
from client_tpu.perf.load_manager import (  # noqa: F401
    ConcurrencyManager,
    InferDataManager,
    LoadManager,
    PeriodicConcurrencyManager,
    RequestRateManager,
    RequestRecord,
    SequenceManager,
)
from client_tpu.perf.model_parser import ModelParser, ParsedModel  # noqa: F401
from client_tpu.perf.profiler import (  # noqa: F401
    InferenceProfiler,
    MeasurementConfig,
    PerfStatus,
)
