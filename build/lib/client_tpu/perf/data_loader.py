"""Input data source for the perf harness (parity: data_loader.h:63-99
— random/zero generation, JSON data files with b64 content and
multi-stream steps)."""

from __future__ import annotations

import base64
import json
from typing import Dict, List, Optional

import numpy as np

from client_tpu.perf.model_parser import ModelTensor, ParsedModel
from client_tpu.utils import (
    InferenceServerException,
    num_elements,
    serialize_byte_tensor,
    tensor_byte_size,
    triton_to_np_dtype,
)


def _resolve_shape(tensor: ModelTensor, default_dim: int = 1) -> List[int]:
    return [default_dim if d < 0 else int(d) for d in tensor.shape]


class TensorData:
    """One concrete tensor value for a (stream, step)."""

    def __init__(self, array: np.ndarray, datatype: str):
        self.array = array
        self.datatype = datatype

    @property
    def shape(self) -> List[int]:
        return list(self.array.shape)

    def raw_bytes(self) -> bytes:
        if self.datatype == "BYTES":
            return serialize_byte_tensor(self.array).tobytes()
        return np.ascontiguousarray(self.array).tobytes()


class DataLoader:
    """Holds per-(stream, step) input tensors. Streams model the
    sequence data-streams of the reference; non-sequence runs use
    stream 0 and cycle through steps."""

    def __init__(self, model: ParsedModel):
        self._model = model
        # stream -> step -> {input name -> TensorData}
        self._data: List[List[Dict[str, TensorData]]] = []

    @property
    def stream_count(self) -> int:
        return len(self._data)

    def step_count(self, stream: int = 0) -> int:
        return len(self._data[stream]) if stream < len(self._data) else 0

    def get_input_data(self, input_name: str, stream: int = 0,
                       step: int = 0) -> TensorData:
        try:
            return self._data[stream][step][input_name]
        except (IndexError, KeyError):
            raise InferenceServerException(
                "no data for input '%s' stream %d step %d"
                % (input_name, stream, step)
            )

    # -- generation ------------------------------------------------------

    def generate_data(self, zero_input: bool = False,
                      string_length: int = 16, string_data: Optional[str] = None,
                      seed: int = 7, steps: int = 1) -> None:
        """Random (or zero) data for every input (parity:
        GenerateData data_loader.h:89)."""
        rng = np.random.default_rng(seed)
        stream = []
        for _ in range(steps):
            step_data = {}
            for name, tensor in self._model.inputs.items():
                shape = _resolve_shape(tensor)
                step_data[name] = TensorData(
                    self._generate_tensor(tensor, shape, zero_input,
                                          string_length, string_data, rng),
                    tensor.datatype,
                )
            stream.append(step_data)
        self._data = [stream]

    def _generate_tensor(self, tensor: ModelTensor, shape, zero_input,
                         string_length, string_data, rng) -> np.ndarray:
        np_dtype = triton_to_np_dtype(tensor.datatype)
        if tensor.datatype == "BYTES":
            if string_data is not None:
                value = string_data.encode()
                flat = np.array([value] * int(np.prod(shape)),
                                dtype=np.object_)
            else:
                flat = np.array(
                    [
                        bytes(rng.integers(97, 123, string_length,
                                           dtype=np.uint8))
                        for _ in range(int(np.prod(shape)))
                    ],
                    dtype=np.object_,
                )
            return flat.reshape(shape)
        if zero_input:
            return np.zeros(shape, dtype=np_dtype)
        if np_dtype is None:
            raise InferenceServerException(
                "cannot generate data for datatype %s" % tensor.datatype
            )
        kind = np.dtype(np_dtype).kind
        if kind == "f" or tensor.datatype == "BF16":
            return rng.random(shape).astype(np_dtype)
        if kind == "b":
            return rng.integers(0, 2, shape).astype(np_dtype)
        info = np.iinfo(np_dtype)
        high = min(int(info.max), 2**20)
        low = max(int(info.min), -(2**20))
        return rng.integers(low, high, shape).astype(np_dtype)

    # -- JSON file -------------------------------------------------------

    def read_data_from_dir(self, directory: str) -> None:
        """Directory input: one file per input named after the input
        (parity: reference DataLoader::ReadDataFromDir,
        data_loader.cc:42 — single stream/step; non-BYTES files are
        raw binary matching the tensor byte size, BYTES files are
        text with one string element per line)."""
        import os

        step: Dict[str, TensorData] = {}
        for name, tensor in self._model.inputs.items():
            path = os.path.join(directory, name)
            if not os.path.exists(path):
                if tensor.optional:
                    continue
                raise InferenceServerException(
                    "no file for input '%s' in %s" % (name, directory))
            shape = _resolve_shape(tensor)
            if tensor.datatype == "BYTES":
                # Binary line split (parity with the native reader):
                # BYTES elements need not be valid UTF-8.
                with open(path, "rb") as f:
                    lines = f.read().split(b"\n")
                if lines and lines[-1] == b"":
                    lines.pop()  # trailing newline
                count = num_elements(shape)
                if len(lines) != count:
                    raise InferenceServerException(
                        "input '%s': %d strings in file, shape %s wants "
                        "%d" % (name, len(lines), shape, count))
                arr = np.array(lines, dtype=np.object_).reshape(shape)
            else:
                with open(path, "rb") as f:
                    raw = f.read()
                np_dtype = triton_to_np_dtype(tensor.datatype)
                expected = tensor_byte_size(tensor.datatype, shape)
                if len(raw) != expected:
                    raise InferenceServerException(
                        "input '%s' file has %d bytes, expected %d for "
                        "shape %s" % (name, len(raw), expected, shape))
                arr = np.frombuffer(raw, dtype=np_dtype).reshape(shape)
            step[name] = TensorData(arr, tensor.datatype)
        self._data = [[step]]
        self._validate()

    def read_data_from_json(self, path_or_dict) -> None:
        """Load the reference's JSON input format: {"data": [step,
        ...]} or {"data": [[stream0 steps], [stream1 steps], ...]};
        each step maps input name -> list | {"content": .., "shape":
        ..} | {"b64": ..} (parity: ReadDataFromJSON data_loader.h:74)."""
        if isinstance(path_or_dict, dict):
            doc = path_or_dict
        else:
            with open(path_or_dict) as f:
                doc = json.load(f)
        data = doc.get("data")
        if data is None:
            raise InferenceServerException("input JSON missing 'data' array")
        if data and isinstance(data[0], list):
            streams = data
        else:
            streams = [data]
        self._data = []
        for stream in streams:
            steps = []
            for step in stream:
                step_data = {}
                for name, value in step.items():
                    tensor = self._model.inputs.get(name)
                    if tensor is None:
                        raise InferenceServerException(
                            "input '%s' in data JSON is not a model input"
                            % name
                        )
                    step_data[name] = self._parse_value(tensor, value)
                steps.append(step_data)
            self._data.append(steps)
        self._validate()

    def _parse_value(self, tensor: ModelTensor, value) -> TensorData:
        shape = None
        if isinstance(value, dict):
            if "shape" in value:
                shape = [int(d) for d in value["shape"]]
            if "b64" in value:
                raw = base64.b64decode(value["b64"])
                np_dtype = triton_to_np_dtype(tensor.datatype)
                arr = np.frombuffer(raw, dtype=np_dtype)
                if shape:
                    arr = arr.reshape(shape)
                return TensorData(arr, tensor.datatype)
            value = value.get("content")
        if tensor.datatype == "BYTES":
            # Nested lists (multi-dimensional BYTES tensors) flatten
            # element-wise; only structured dict elements (e.g. OpenAI
            # payload objects) ride as their JSON serialization.
            def encode(v):
                if isinstance(v, dict):
                    return json.dumps(v).encode()
                return v.encode() if isinstance(v, str) else bytes(v)

            def flatten(v):
                if isinstance(v, list):
                    for item in v:
                        yield from flatten(item)
                else:
                    yield v

            listed = list(flatten(value)) if isinstance(value, list) \
                else [value]
            arr = np.array([encode(v) for v in listed], dtype=np.object_)
        else:
            arr = np.array(value).astype(triton_to_np_dtype(tensor.datatype))
        if shape:
            arr = arr.reshape(shape)
        elif len(tensor.shape) and -1 not in tensor.shape:
            arr = arr.reshape(tensor.shape)
        return TensorData(arr, tensor.datatype)

    def _validate(self):
        """Every step must cover all non-optional inputs with
        spec-compatible shapes (parity: data_loader validation
        :173-198)."""
        for stream_idx, stream in enumerate(self._data):
            for step_idx, step in enumerate(stream):
                for name, tensor in self._model.inputs.items():
                    if name not in step:
                        if tensor.optional:
                            continue
                        raise InferenceServerException(
                            "missing data for input '%s' (stream %d step %d)"
                            % (name, stream_idx, step_idx)
                        )
                    got = step[name].shape
                    want = tensor.shape
                    if len(got) != len(want) or any(
                        w != -1 and g != w for g, w in zip(got, want)
                    ):
                        raise InferenceServerException(
                            "shape %s for input '%s' incompatible with %s"
                            % (got, name, want)
                        )
