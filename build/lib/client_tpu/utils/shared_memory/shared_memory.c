/* Native POSIX shared-memory backend for client_tpu.utils.shared_memory.
 *
 * API-parity surface with the reference's small C extension
 * (tritonclient/utils/shared_memory/shared_memory.cc: 151 LoC of
 * shm_open/mmap/memcpy behind SharedMemoryRegionCreate / Set /
 * GetSharedMemoryHandleInfo / Destroy), re-implemented for the TPU
 * client stack. Built as libcshm.so and loaded with ctypes; all
 * returns are 0 on success or -errno on failure.
 */

#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

typedef struct SharedMemoryHandle {
  void* base_addr_;
  char* shm_key_;
  int shm_fd_;
  size_t offset_;
  size_t byte_size_;
  int owns_region_; /* created (unlink on destroy) vs attached */
} SharedMemoryHandle;

static int
MapRegion(int shm_fd, size_t offset, size_t byte_size, void** base_addr)
{
  *base_addr =
      mmap(NULL, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, shm_fd, offset);
  if (*base_addr == MAP_FAILED) {
    return -errno;
  }
  return 0;
}

static int
OpenCommon(
    const char* shm_key, size_t byte_size, int oflags, int owns,
    void** shm_handle)
{
  int fd = shm_open(shm_key, oflags, S_IRUSR | S_IWUSR);
  if (fd == -1) {
    return -errno;
  }
  if (owns) {
    struct stat st;
    if (fstat(fd, &st) == -1 || (size_t)st.st_size < byte_size) {
      if (ftruncate(fd, (off_t)byte_size) == -1) {
        int err = -errno;
        close(fd);
        return err;
      }
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) == -1) {
      int err = -errno;
      close(fd);
      return err;
    }
    if ((size_t)st.st_size < byte_size) {
      close(fd);
      return -EINVAL;
    }
  }

  void* base = NULL;
  int rc = MapRegion(fd, 0, byte_size, &base);
  if (rc != 0) {
    close(fd);
    return rc;
  }

  SharedMemoryHandle* handle =
      (SharedMemoryHandle*)malloc(sizeof(SharedMemoryHandle));
  if (handle == NULL) {
    munmap(base, byte_size);
    close(fd);
    return -ENOMEM;
  }
  handle->base_addr_ = base;
  handle->shm_key_ = strdup(shm_key);
  handle->shm_fd_ = fd;
  handle->offset_ = 0;
  handle->byte_size_ = byte_size;
  handle->owns_region_ = owns;
  *shm_handle = handle;
  return 0;
}

int
SharedMemoryRegionCreate(
    const char* shm_key, size_t byte_size, int create_only, void** shm_handle)
{
  int oflags = O_RDWR | O_CREAT | (create_only ? O_EXCL : 0);
  return OpenCommon(shm_key, byte_size, oflags, 1, shm_handle);
}

int
SharedMemoryRegionOpen(const char* shm_key, size_t byte_size, void** shm_handle)
{
  return OpenCommon(shm_key, byte_size, O_RDWR, 0, shm_handle);
}

int
SharedMemoryRegionSet(
    void* shm_handle, size_t offset, size_t byte_size, const void* data)
{
  SharedMemoryHandle* handle = (SharedMemoryHandle*)shm_handle;
  if (offset + byte_size > handle->byte_size_) {
    return -EINVAL;
  }
  memcpy((char*)handle->base_addr_ + offset, data, byte_size);
  return 0;
}

int
GetSharedMemoryHandleInfo(
    void* shm_handle, char** base_addr, const char** shm_key, int* shm_fd,
    size_t* offset, size_t* byte_size)
{
  SharedMemoryHandle* handle = (SharedMemoryHandle*)shm_handle;
  *base_addr = (char*)handle->base_addr_;
  *shm_key = handle->shm_key_;
  *shm_fd = handle->shm_fd_;
  *offset = handle->offset_;
  *byte_size = handle->byte_size_;
  return 0;
}

static int
ReleaseCommon(SharedMemoryHandle* handle, int unlink_region)
{
  int rc = 0;
  if (handle->base_addr_ != NULL) {
    if (munmap(handle->base_addr_, handle->byte_size_) == -1) {
      rc = -errno;
    }
    handle->base_addr_ = NULL;
  }
  if (handle->shm_fd_ >= 0) {
    close(handle->shm_fd_);
    handle->shm_fd_ = -1;
  }
  if (unlink_region && handle->shm_key_ != NULL) {
    if (shm_unlink(handle->shm_key_) == -1 && rc == 0) {
      rc = -errno;
    }
  }
  free(handle->shm_key_);
  handle->shm_key_ = NULL;
  free(handle);
  return rc;
}

int
SharedMemoryRegionDestroy(void* shm_handle)
{
  return ReleaseCommon((SharedMemoryHandle*)shm_handle, 1);
}

int
SharedMemoryRegionDetach(void* shm_handle)
{
  return ReleaseCommon((SharedMemoryHandle*)shm_handle, 0);
}
