"""Loader for the native shared-memory backend (libcshm.so).

The reference ships a prebuilt C extension loaded with ctypes
(utils/shared_memory/__init__.py:48-72); here the library is compiled
on first use from ``shared_memory.c`` with the system compiler and
cached next to this file. Set ``CLIENT_TPU_NO_CSHM=1`` to force the
pure-Python fallback.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import tempfile
from typing import Optional

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_PKG_DIR, "shared_memory.c")
_LIB_PATH = os.path.join(_PKG_DIR, "libcshm.so")


def _compile() -> Optional[str]:
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
    if cc is None or not os.path.exists(_SRC):
        return None
    # build into a temp file then atomically rename so concurrent
    # importers never load a half-written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_PKG_DIR)
    os.close(fd)
    try:
        subprocess.run(
            [cc, "-O2", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True,
            capture_output=True,
            timeout=60,
        )
        os.replace(tmp, _LIB_PATH)
        return _LIB_PATH
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def load() -> Optional[ctypes.CDLL]:
    """Load (building if necessary) libcshm.so; None on any failure."""
    if os.environ.get("CLIENT_TPU_NO_CSHM"):
        return None
    # rebuild whenever the source is newer than the cached library so
    # edits to shared_memory.c actually take effect
    fresh = (
        os.path.exists(_LIB_PATH)
        and (not os.path.exists(_SRC)
             or os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC))
    )
    path = _LIB_PATH if fresh else _compile()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None

    lib.SharedMemoryRegionCreate.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.SharedMemoryRegionCreate.restype = ctypes.c_int
    lib.SharedMemoryRegionOpen.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.SharedMemoryRegionOpen.restype = ctypes.c_int
    lib.SharedMemoryRegionSet.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.c_void_p,
    ]
    lib.SharedMemoryRegionSet.restype = ctypes.c_int
    lib.GetSharedMemoryHandleInfo.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.GetSharedMemoryHandleInfo.restype = ctypes.c_int
    lib.SharedMemoryRegionDestroy.argtypes = [ctypes.c_void_p]
    lib.SharedMemoryRegionDestroy.restype = ctypes.c_int
    lib.SharedMemoryRegionDetach.argtypes = [ctypes.c_void_p]
    lib.SharedMemoryRegionDetach.restype = ctypes.c_int
    return lib
