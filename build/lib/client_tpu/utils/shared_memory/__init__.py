"""System (POSIX) shared-memory utilities.

API-parity surface with the reference
``tritonclient.utils.shared_memory`` (utils/shared_memory/__init__.py:
93-260). Like the reference, the fast path is a small native C
extension (``shared_memory.c`` → libcshm.so, mirroring the reference's
shared_memory.cc) loaded with ctypes; if the library cannot be built
or loaded, a pure-Python ctypes ``shm_open`` + stdlib ``mmap`` path
provides identical zero-copy behavior.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import mmap
import os
import sys
import weakref
from typing import List, Optional

import numpy as np

from client_tpu.utils import (
    deserialize_bytes_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)
from client_tpu.utils.shared_memory import _cshm

# libcshm.so is built/loaded lazily on first region operation so that
# importing the package never blocks on a compiler invocation
_CSHM_LIB = None
_CSHM_ATTEMPTED = False


def _cshm_lib():
    global _CSHM_LIB, _CSHM_ATTEMPTED
    if not _CSHM_ATTEMPTED:
        _CSHM_ATTEMPTED = True
        _CSHM_LIB = _cshm.load()
    return _CSHM_LIB


def using_native_backend() -> bool:
    """True when the libcshm.so C extension backs this module."""
    return _cshm_lib() is not None


class SharedMemoryException(Exception):
    """Raised on any shared-memory operation failure."""


def _load_shm_lib():
    # shm_open lives in librt on older glibc, libc on newer.
    for name in ("rt", "c"):
        path = ctypes.util.find_library(name)
        if path is None:
            continue
        lib = ctypes.CDLL(path, use_errno=True)
        if hasattr(lib, "shm_open"):
            return lib
    raise SharedMemoryException("unable to locate shm_open in libc/librt")


_LIB = _load_shm_lib()
_LIB.shm_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint]
_LIB.shm_open.restype = ctypes.c_int
_LIB.shm_unlink.argtypes = [ctypes.c_char_p]
_LIB.shm_unlink.restype = ctypes.c_int

_O_RDWR = os.O_RDWR
_O_CREAT = os.O_CREAT


class SharedMemoryRegion:
    """Handle to a mapped POSIX shared-memory region."""

    def __init__(self, triton_shm_name: str, shm_key: str):
        self._triton_shm_name = triton_shm_name
        self._shm_key = shm_key
        self._byte_size = 0
        self._fd = -1
        self._mpg = None  # mmap.mmap (fallback) or memoryview (C ext)
        self._chandle: Optional[ctypes.c_void_p] = None
        self._np_base: Optional[np.ndarray] = None
        self._created = False

    @property
    def name(self) -> str:
        return self._triton_shm_name

    @property
    def key(self) -> str:
        return self._shm_key

    @property
    def byte_size(self) -> int:
        return self._byte_size

    def buf(self) -> mmap.mmap:
        if self._mpg is None:
            raise SharedMemoryException("region is not mapped")
        return self._mpg


_mapped_regions: dict = {}


def _adopt_chandle(region: SharedMemoryRegion, chandle: ctypes.c_void_p,
                   created: bool) -> None:
    """Fill a region from a native SharedMemoryHandle: zero-copy
    memoryview over the mapped address + bookkeeping fields."""
    base = ctypes.c_void_p()
    key = ctypes.c_char_p()
    fd = ctypes.c_int()
    offset = ctypes.c_size_t()
    size = ctypes.c_size_t()
    _cshm_lib().GetSharedMemoryHandleInfo(
        chandle, ctypes.byref(base), ctypes.byref(key), ctypes.byref(fd),
        ctypes.byref(offset), ctypes.byref(size))
    region._chandle = chandle
    region._fd = fd.value
    region._byte_size = size.value
    region._created = created
    # numpy's uint8 buffer exports format 'B' (plain ctypes arrays
    # export '<B', which memoryview.cast and some consumers reject)
    arr = np.ctypeslib.as_array(
        ctypes.cast(base, ctypes.POINTER(ctypes.c_ubyte)),
        shape=(size.value,))
    region._np_base = arr
    region._mpg = memoryview(arr)


def create_shared_memory_region(
    triton_shm_name: str, shm_key: str, byte_size: int, create_only: bool = False
) -> SharedMemoryRegion:
    """Create (or attach, unless ``create_only``) and map the POSIX
    region ``shm_key`` of ``byte_size`` bytes."""
    region = SharedMemoryRegion(triton_shm_name, shm_key)
    if using_native_backend():
        chandle = ctypes.c_void_p()
        rc = _cshm_lib().SharedMemoryRegionCreate(
            shm_key.encode(), byte_size, int(create_only),
            ctypes.byref(chandle))
        if rc != 0:
            raise SharedMemoryException(
                "unable to create shared memory region '%s': %s"
                % (shm_key, os.strerror(-rc)))
        _adopt_chandle(region, chandle, created=True)
        _mapped_regions[triton_shm_name] = region
        return region
    flags = _O_RDWR | _O_CREAT
    if create_only:
        flags |= os.O_EXCL
    fd = _LIB.shm_open(shm_key.encode(), flags, 0o600)
    if fd < 0:
        err = ctypes.get_errno()
        raise SharedMemoryException(
            "unable to create shared memory region '%s': %s"
            % (shm_key, os.strerror(err))
        )
    try:
        stat = os.fstat(fd)
        region._created = stat.st_size == 0
        if stat.st_size < byte_size:
            os.ftruncate(fd, byte_size)
        region._fd = fd
        region._byte_size = byte_size
        region._mpg = mmap.mmap(fd, byte_size)
    except OSError as e:
        os.close(fd)
        raise SharedMemoryException(
            "unable to map shared memory region '%s': %s" % (shm_key, e)
        )
    _mapped_regions[triton_shm_name] = region
    return region


def attach_shared_memory_region(
    triton_shm_name: str, shm_key: str, byte_size: int
) -> SharedMemoryRegion:
    """Attach to an existing region without creating it (used
    server-side when a client registers a region)."""
    region = SharedMemoryRegion(triton_shm_name, shm_key)
    if using_native_backend():
        chandle = ctypes.c_void_p()
        rc = _cshm_lib().SharedMemoryRegionOpen(
            shm_key.encode(), byte_size, ctypes.byref(chandle))
        if rc != 0:
            raise SharedMemoryException(
                "unable to open shared memory region '%s': %s"
                % (shm_key, os.strerror(-rc)))
        _adopt_chandle(region, chandle, created=False)
        return region
    fd = _LIB.shm_open(shm_key.encode(), _O_RDWR, 0o600)
    if fd < 0:
        raise SharedMemoryException(
            "unable to open shared memory region '%s': %s"
            % (shm_key, os.strerror(ctypes.get_errno()))
        )
    try:
        size = os.fstat(fd).st_size
        if size < byte_size:
            raise SharedMemoryException(
                "region '%s' is %d bytes, %d requested"
                % (shm_key, size, byte_size)
            )
        region._fd = fd
        region._byte_size = byte_size
        region._mpg = mmap.mmap(fd, byte_size)
    except SharedMemoryException:
        os.close(fd)
        raise
    except OSError as e:
        os.close(fd)
        raise SharedMemoryException(str(e))
    return region


def set_shared_memory_region(
    shm_handle: SharedMemoryRegion, input_values, offset: int = 0
) -> None:
    """Copy a list of numpy arrays into the region back to back
    starting at ``offset`` (BYTES arrays are wire-serialized)."""
    if not isinstance(input_values, (list, tuple)):
        raise SharedMemoryException("input_values must be a list of numpy arrays")
    buf = shm_handle.buf()
    pos = offset
    for arr in input_values:
        if arr.dtype.kind in ("O", "S", "U"):
            data = serialize_byte_tensor(arr).tobytes()
        else:
            data = np.ascontiguousarray(arr).tobytes()
        if pos + len(data) > shm_handle.byte_size:
            raise SharedMemoryException("input exceeds shared memory region size")
        if shm_handle._chandle is not None:
            rc = _cshm_lib().SharedMemoryRegionSet(
                shm_handle._chandle, pos, len(data), data)
            if rc != 0:
                raise SharedMemoryException(
                    "unable to set shared memory region: %s"
                    % os.strerror(-rc))
        else:
            buf[pos : pos + len(data)] = data
        pos += len(data)


def get_contents_as_numpy(
    shm_handle: SharedMemoryRegion, datatype, shape, offset: int = 0
) -> np.ndarray:
    """View/copy the region contents as a numpy array of
    datatype/shape. Fixed-size dtypes return a zero-copy view."""
    buf = shm_handle.buf()
    if isinstance(datatype, str):
        np_dtype = triton_to_np_dtype(datatype)
        wire = datatype
    else:
        np_dtype = np.dtype(datatype)
        wire = None
    count = int(np.prod(shape)) if len(shape) else 1
    if np_dtype == np.object_ or wire == "BYTES":
        end = shm_handle.byte_size
        arr = deserialize_bytes_tensor(bytes(buf[offset:end]))
        # the region may be larger than the tensor; trailing zero bytes
        # decode as empty elements — keep only the requested count
        return arr[:count].reshape(shape)
    return np.frombuffer(
        memoryview(buf), dtype=np_dtype, count=count, offset=offset
    ).reshape(shape)


def get_shared_memory_handle_info(shm_handle: SharedMemoryRegion):
    """(shm_key, byte_size, fd) of the underlying region."""
    return (shm_handle.key, shm_handle.byte_size, shm_handle._fd)


def mapped_shared_memory_regions() -> List[str]:
    return list(_mapped_regions.keys())


def _release_mapping(shm_handle: SharedMemoryRegion, unlink: bool) -> None:
    if shm_handle._chandle is not None:
        lib = _cshm_lib()
        chandle = shm_handle._chandle
        base = shm_handle._np_base
        shm_handle._mpg = None
        shm_handle._np_base = None
        shm_handle._chandle = None
        shm_handle._fd = -1
        if unlink:
            # the name can go immediately; the mapping itself survives
            # until munmap (POSIX keeps unlinked regions mapped)
            _LIB.shm_unlink(shm_handle.key.encode())
        # zero-copy numpy views may still reference the mapping
        # (refcount: `base` local + getrefcount arg = 2 baseline);
        # defer munmap until they die instead of leaving them dangling
        if base is not None and sys.getrefcount(base) > 2:
            weakref.finalize(base, lib.SharedMemoryRegionDetach, chandle)
        else:
            lib.SharedMemoryRegionDetach(chandle)
        return
    # Zero-copy numpy views may still reference the mapping; in that
    # case dropping our reference lets GC unmap once the views die.
    if shm_handle._mpg is not None:
        try:
            shm_handle._mpg.close()
        except BufferError:
            pass
        shm_handle._mpg = None
    if shm_handle._fd >= 0:
        os.close(shm_handle._fd)
        shm_handle._fd = -1
    if unlink:
        _LIB.shm_unlink(shm_handle.key.encode())


def destroy_shared_memory_region(shm_handle: SharedMemoryRegion) -> None:
    """Unmap and unlink the region."""
    try:
        _release_mapping(shm_handle, unlink=True)
    finally:
        _mapped_regions.pop(shm_handle.name, None)


def detach_shared_memory_region(shm_handle: SharedMemoryRegion) -> None:
    """Unmap without unlinking (server detaching a client's region)."""
    _release_mapping(shm_handle, unlink=False)
