"""Standalone pure-ctypes DLPack implementation.

Role parity with the reference's framework-independent
``tritonclient/utils/_dlpack.py`` (:57-120 struct layer, :219
contiguity check, :245 capsule access): ingest ANY tensor exposing
``__dlpack__`` without importing its framework, and without
``np.from_dlpack``'s CPU-only/device restrictions. CPU tensors become
zero-copy numpy views; the caller decides what to do with non-CPU
devices (in-process jax arrays are stored by reference upstream).

The struct layout follows the public DLPack ABI (dmlc/dlpack
``dlpack.h``, stable since v0.6).
"""

from __future__ import annotations

import ctypes
from typing import Tuple

import numpy as np


class DLDeviceType:
    kDLCPU = 1
    kDLCUDA = 2
    kDLCUDAHost = 3
    kDLOpenCL = 4
    kDLVulkan = 7
    kDLMetal = 8
    kDLVPI = 9
    kDLROCM = 10
    kDLROCMHost = 11
    kDLExtDev = 12
    kDLCUDAManaged = 13
    kDLOneAPI = 14


class DLDataTypeCode:
    kDLInt = 0
    kDLUInt = 1
    kDLFloat = 2
    kDLOpaqueHandle = 3
    kDLBfloat = 4
    kDLComplex = 5
    kDLBool = 6


class DLDevice(ctypes.Structure):
    _fields_ = [
        ("device_type", ctypes.c_int),
        ("device_id", ctypes.c_int),
    ]


class DLDataType(ctypes.Structure):
    _fields_ = [
        ("type_code", ctypes.c_uint8),
        ("bits", ctypes.c_uint8),
        ("lanes", ctypes.c_uint16),
    ]


class DLTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("device", DLDevice),
        ("ndim", ctypes.c_int),
        ("dtype", DLDataType),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("strides", ctypes.POINTER(ctypes.c_int64)),
        ("byte_offset", ctypes.c_uint64),
    ]


class DLManagedTensor(ctypes.Structure):
    _fields_ = [
        ("dl_tensor", DLTensor),
        ("manager_ctx", ctypes.c_void_p),
        ("deleter", ctypes.CFUNCTYPE(None, ctypes.c_void_p)),
    ]


_CAPSULE_NAME = b"dltensor"
_USED_CAPSULE_NAME = b"used_dltensor"

ctypes.pythonapi.PyCapsule_GetPointer.restype = ctypes.c_void_p
ctypes.pythonapi.PyCapsule_GetPointer.argtypes = [
    ctypes.py_object, ctypes.c_char_p]
ctypes.pythonapi.PyCapsule_IsValid.restype = ctypes.c_int
ctypes.pythonapi.PyCapsule_IsValid.argtypes = [
    ctypes.py_object, ctypes.c_char_p]
ctypes.pythonapi.PyCapsule_SetName.restype = ctypes.c_int
ctypes.pythonapi.PyCapsule_SetName.argtypes = [
    ctypes.py_object, ctypes.c_char_p]


def get_managed_tensor(capsule) -> DLManagedTensor:
    """The DLManagedTensor struct behind a 'dltensor' capsule."""
    if not ctypes.pythonapi.PyCapsule_IsValid(capsule, _CAPSULE_NAME):
        raise ValueError(
            "capsule is not a valid (unconsumed) dltensor capsule")
    ptr = ctypes.pythonapi.PyCapsule_GetPointer(capsule, _CAPSULE_NAME)
    return ctypes.cast(ptr, ctypes.POINTER(DLManagedTensor)).contents


def get_dlpack_capsule(tensor, stream=None):
    """Produce the capsule from any __dlpack__-capable object."""
    try:
        return tensor.__dlpack__(stream=stream)
    except TypeError:
        return tensor.__dlpack__()


def get_dlpack_device(tensor) -> Tuple[int, int]:
    """(device_type, device_id); falls back to parsing the capsule
    when the producer lacks __dlpack_device__."""
    if hasattr(tensor, "__dlpack_device__"):
        return tuple(tensor.__dlpack_device__())
    # Keep the capsule referenced while reading the struct — dropping
    # it runs the producer's deleter and frees the DLManagedTensor.
    capsule = get_dlpack_capsule(tensor)
    managed = get_managed_tensor(capsule)
    device = managed.dl_tensor.device
    result = (device.device_type, device.device_id)
    del managed, capsule
    return result


def triton_to_dlpack_dtype(wire_dtype: str) -> DLDataType:
    """Wire dtype string -> DLDataType (parity: reference
    triton_to_dlpack_dtype :170)."""
    table = {
        "BOOL": (DLDataTypeCode.kDLBool, 8),
        "INT8": (DLDataTypeCode.kDLInt, 8),
        "INT16": (DLDataTypeCode.kDLInt, 16),
        "INT32": (DLDataTypeCode.kDLInt, 32),
        "INT64": (DLDataTypeCode.kDLInt, 64),
        "UINT8": (DLDataTypeCode.kDLUInt, 8),
        "UINT16": (DLDataTypeCode.kDLUInt, 16),
        "UINT32": (DLDataTypeCode.kDLUInt, 32),
        "UINT64": (DLDataTypeCode.kDLUInt, 64),
        "FP16": (DLDataTypeCode.kDLFloat, 16),
        "BF16": (DLDataTypeCode.kDLBfloat, 16),
        "FP32": (DLDataTypeCode.kDLFloat, 32),
        "FP64": (DLDataTypeCode.kDLFloat, 64),
    }
    if wire_dtype not in table:
        raise ValueError("dtype %s has no DLPack equivalent" % wire_dtype)
    code, bits = table[wire_dtype]
    return DLDataType(code, bits, 1)


def dlpack_to_np_dtype(dtype: DLDataType) -> np.dtype:
    if dtype.lanes != 1:
        raise ValueError("vector dtypes are not supported")
    code, bits = dtype.type_code, dtype.bits
    if code == DLDataTypeCode.kDLInt:
        return np.dtype("int%d" % bits)
    if code == DLDataTypeCode.kDLUInt:
        return np.dtype("uint%d" % bits)
    if code == DLDataTypeCode.kDLFloat:
        return np.dtype("float%d" % bits)
    if code == DLDataTypeCode.kDLBool:
        return np.dtype(np.bool_)
    if code == DLDataTypeCode.kDLBfloat:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    raise ValueError(
        "DLPack type code %d is not representable in numpy" % code)


def is_contiguous_data(ndim: int, shape, strides) -> bool:
    """Row-major contiguity from DLPack shape/strides (strides may be
    NULL = contiguous by convention)."""
    if not strides:
        return True
    expected = 1
    for i in reversed(range(ndim)):
        if shape[i] != 1 and strides[i] != expected:
            return False
        expected *= shape[i]
    return True


def capsule_to_numpy(capsule, writable: bool = False) -> np.ndarray:
    """Zero-copy numpy view over a CPU dltensor capsule. The returned
    array keeps the capsule alive (the producer's deleter fires when
    the view is garbage-collected)."""
    managed = get_managed_tensor(capsule)
    tensor = managed.dl_tensor
    if tensor.device.device_type not in (
        DLDeviceType.kDLCPU, DLDeviceType.kDLCUDAHost,
        DLDeviceType.kDLROCMHost,
    ):
        raise ValueError(
            "capsule holds device memory (device_type=%d), not host"
            % tensor.device.device_type)
    shape = [tensor.shape[i] for i in range(tensor.ndim)]
    np_dtype = dlpack_to_np_dtype(tensor.dtype)
    count = int(np.prod(shape)) if shape else 1
    if count == 0:  # empty tensors need no layout validation
        return np.empty(shape, dtype=np_dtype)
    if not is_contiguous_data(tensor.ndim, tensor.shape, tensor.strides):
        raise ValueError("only contiguous DLPack tensors are supported")
    nbytes = count * np_dtype.itemsize
    address = (tensor.data or 0) + tensor.byte_offset
    buffer = (ctypes.c_char * nbytes).from_address(address)
    array = np.frombuffer(buffer, dtype=np_dtype).reshape(shape)
    if not writable:
        array.flags.writeable = False
    # Tie the capsule's lifetime to the view: numpy only keeps
    # `buffer` alive, which does not own the producer's memory.
    array = array.view(_CapsuleBackedArray)
    array._dlpack_capsule = capsule
    return array


class _CapsuleBackedArray(np.ndarray):
    """ndarray subclass carrying the owning dltensor capsule."""

    _dlpack_capsule = None


def to_numpy(tensor) -> np.ndarray:
    """Any host-resident __dlpack__-capable tensor -> zero-copy numpy
    view (the ingestion entry point)."""
    if isinstance(tensor, np.ndarray):
        return tensor
    return capsule_to_numpy(get_dlpack_capsule(tensor))
