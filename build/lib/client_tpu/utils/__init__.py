"""Core utilities: exceptions, dtype mapping, wire serialization.

API-parity surface with the reference ``tritonclient.utils``
(/root/reference/src/python/library/tritonclient/utils/__init__.py:71-348),
re-designed TPU-first: BF16 is a first-class numpy dtype here (via
``ml_dtypes.bfloat16``, the dtype JAX itself uses) instead of the
reference's uint16-view workaround.
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

try:  # ml_dtypes ships with jax; gives us a real bfloat16 numpy dtype
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes is a jax dependency
    ml_dtypes = None
    _BF16 = None


class InferenceServerException(Exception):
    """Exception carrying a message, an optional protocol status and
    optional debug details, raised by every client-facing API."""

    def __init__(self, msg: str, status: Optional[str] = None, debug_details=None):
        self._msg = msg
        self._status = status
        self._debug_details = debug_details
        super().__init__(msg)

    def __str__(self) -> str:
        msg = super().__str__() if self._msg is None else self._msg
        if self._status is not None:
            msg = "[" + self._status + "] " + msg
        return msg

    def message(self) -> str:
        return self._msg

    def status(self) -> Optional[str]:
        return self._status

    def debug_details(self):
        return self._debug_details


# KServe-v2 wire dtype <-> numpy dtype tables. BF16 maps to the real
# ml_dtypes.bfloat16 dtype (TPU native); np.object_ carries BYTES.
_NP_TO_WIRE = {
    np.dtype(np.bool_): "BOOL",
    np.dtype(np.int8): "INT8",
    np.dtype(np.int16): "INT16",
    np.dtype(np.int32): "INT32",
    np.dtype(np.int64): "INT64",
    np.dtype(np.uint8): "UINT8",
    np.dtype(np.uint16): "UINT16",
    np.dtype(np.uint32): "UINT32",
    np.dtype(np.uint64): "UINT64",
    np.dtype(np.float16): "FP16",
    np.dtype(np.float32): "FP32",
    np.dtype(np.float64): "FP64",
}
if _BF16 is not None:
    _NP_TO_WIRE[_BF16] = "BF16"

_WIRE_TO_NP = {v: k for k, v in _NP_TO_WIRE.items()}
_WIRE_TO_NP["BYTES"] = np.dtype(np.object_)

# Fixed per-element byte sizes for non-BYTES dtypes.
_WIRE_ELEM_SIZE = {
    "BOOL": 1, "INT8": 1, "UINT8": 1,
    "INT16": 2, "UINT16": 2, "FP16": 2, "BF16": 2,
    "INT32": 4, "UINT32": 4, "FP32": 4,
    "INT64": 8, "UINT64": 8, "FP64": 8,
}


def np_to_triton_dtype(np_dtype) -> Optional[str]:
    """numpy dtype (or type) -> wire dtype string, None if unmapped."""
    dt = np.dtype(np_dtype)
    if dt.kind in ("O", "S", "U"):
        return "BYTES"
    return _NP_TO_WIRE.get(dt)


def triton_to_np_dtype(dtype: str):
    """Wire dtype string -> numpy dtype (BF16 -> ml_dtypes.bfloat16)."""
    return _WIRE_TO_NP.get(dtype)


# The framework's preferred names; the triton_* spellings above are kept
# for drop-in compatibility with tritonclient user code.
np_to_wire_dtype = np_to_triton_dtype
wire_to_np_dtype = triton_to_np_dtype


def wire_dtype_element_size(dtype: str) -> int:
    """Bytes per element for a fixed-size wire dtype; -1 for BYTES."""
    return _WIRE_ELEM_SIZE.get(dtype, -1)


def num_elements(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def tensor_byte_size(dtype: str, shape) -> int:
    """Wire byte size of a fixed-size-dtype tensor; -1 for BYTES (data
    dependent)."""
    es = wire_dtype_element_size(dtype)
    if es < 0:
        return -1
    return es * num_elements(shape)


def serialize_byte_tensor(input_tensor: np.ndarray) -> np.ndarray:
    """Serialize a BYTES tensor for the wire.

    Each element is encoded as a 4-byte little-endian length followed by
    the element's bytes (str elements are UTF-8 encoded), in C order.
    Returns a flat uint8 array wrapping the serialized buffer.
    """
    if input_tensor.size == 0:
        return np.empty([0], dtype=np.uint8)
    if input_tensor.dtype.kind not in ("O", "S", "U"):
        raise InferenceServerException(
            "cannot serialize tensor of dtype %s as BYTES" % input_tensor.dtype
        )
    parts = []
    for obj in np.nditer(input_tensor, flags=["refs_ok"], order="C"):
        item = obj.item()
        if isinstance(item, (bytes, bytearray, memoryview)):
            b = bytes(item)
        else:
            b = str(item).encode("utf-8")
        parts.append(struct.pack("<I", len(b)))
        parts.append(b)
    flat = b"".join(parts)
    return np.frombuffer(flat, dtype=np.uint8)


def deserialize_bytes_tensor(encoded_tensor: bytes) -> np.ndarray:
    """Inverse of :func:`serialize_byte_tensor`: flat buffer -> 1-D
    np.object_ array of bytes elements (caller reshapes)."""
    strs = []
    offset = 0
    view = memoryview(encoded_tensor)
    n = len(view)
    while offset < n:
        if offset + 4 > n:
            raise InferenceServerException(
                "malformed BYTES tensor: truncated length prefix"
            )
        (length,) = struct.unpack_from("<I", view, offset)
        offset += 4
        if offset + length > n:
            raise InferenceServerException(
                "malformed BYTES tensor: element overruns buffer"
            )
        strs.append(bytes(view[offset : offset + length]))
        offset += length
    return np.array(strs, dtype=np.object_)


def serialize_bf16_tensor(input_tensor: np.ndarray) -> np.ndarray:
    """Serialize a bfloat16 tensor to its raw 2-byte-per-element wire
    form. Accepts ml_dtypes.bfloat16 arrays directly (zero-copy) or
    float16/float32/float64 arrays (cast)."""
    if _BF16 is not None and input_tensor.dtype == _BF16:
        arr = np.ascontiguousarray(input_tensor)
    elif input_tensor.dtype in (np.float16, np.float32, np.float64):
        if _BF16 is None:  # pragma: no cover
            raise InferenceServerException("ml_dtypes required for BF16")
        arr = np.ascontiguousarray(input_tensor.astype(_BF16))
    else:
        raise InferenceServerException(
            "cannot serialize tensor of dtype %s as BF16" % input_tensor.dtype
        )
    return arr.view(np.uint8).reshape(-1)


def deserialize_bf16_tensor(encoded_tensor: bytes) -> np.ndarray:
    """Flat wire buffer -> 1-D ml_dtypes.bfloat16 array (caller
    reshapes)."""
    if _BF16 is None:  # pragma: no cover
        raise InferenceServerException("ml_dtypes required for BF16")
    return np.frombuffer(encoded_tensor, dtype=_BF16)


def serialized_byte_size(tensor_value: np.ndarray) -> int:
    """Wire byte size of a tensor once serialized (BYTES aware)."""
    if tensor_value.dtype.kind in ("O", "S", "U"):
        return int(serialize_byte_tensor(tensor_value).size)
    return int(tensor_value.nbytes)
