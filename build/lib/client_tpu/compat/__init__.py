"""Migration shims for code written against the reference client
(parity-plus: the reference ships deprecation shims for ITS old
package names — `tritonclientutils`, `tritongrpcclient`,
`tritonhttpclient`, `tritonshmutils`, each re-exporting the new layout
with a DeprecationWarning; this build's equivalent concern is code
written against `tritonclient.*` itself).

``install()`` registers module aliases so existing scripts run
unchanged against this framework::

    import client_tpu.compat
    client_tpu.compat.install()

    import tritonclient.grpc as grpcclient          # -> client_tpu.grpc
    import tritonclient.utils.shared_memory as shm  # -> client_tpu...

Aliased surface: ``tritonclient`` (package), ``.grpc``, ``.grpc.aio``,
``.http``, ``.http.aio``, ``.utils``, ``.utils.shared_memory``, and
``.utils.cuda_shared_memory`` — the last mapping onto
``client_tpu.utils.tpu_shared_memory``, whose seven-function surface
mirrors the CUDA module one-for-one (create/get_raw_handle/set/
get_contents_as_numpy/set_from_dlpack/as_shared_memory_tensor/
destroy), so CUDA-shm call sites retarget the HBM arena without
edits. A MigrationWarning-style DeprecationWarning fires once per
aliased import path.
"""

from __future__ import annotations

import importlib
import sys
import warnings

# alias -> real module path
_ALIASES = {
    "tritonclient": "client_tpu",
    "tritonclient.grpc": "client_tpu.grpc",
    "tritonclient.grpc.aio": "client_tpu.grpc.aio",
    "tritonclient.http": "client_tpu.http",
    "tritonclient.http.aio": "client_tpu.http.aio",
    "tritonclient.utils": "client_tpu.utils",
    "tritonclient.utils.shared_memory": "client_tpu.utils.shared_memory",
    # CUDA shm call sites retarget the TPU HBM arena: identical
    # seven-function surface (SURVEY.md §2.2 north-star module).
    "tritonclient.utils.cuda_shared_memory":
        "client_tpu.utils.tpu_shared_memory",
}

_installed = False
_attr_backups: list = []  # (parent module, attr, had_prev, prev)


def install(quiet: bool = False) -> None:
    """Registers the ``tritonclient.*`` aliases in ``sys.modules``.

    Idempotent; refuses to shadow a REAL tritonclient installation
    (if one is importable, the aliases are not installed and a
    RuntimeError is raised — silently hijacking an installed package
    would be hostile)."""
    global _installed
    if _installed:
        return
    existing = sys.modules.get("tritonclient")
    if existing is not None and \
            not existing.__name__.startswith("client_tpu"):
        raise RuntimeError(
            "a real tritonclient package is already imported; refusing "
            "to alias it to client_tpu (mixed-client state would be "
            "worse than either)")
    if existing is None:
        try:
            import importlib.util

            if importlib.util.find_spec("tritonclient") is not None:
                raise RuntimeError(
                    "a real tritonclient package is installed; refusing "
                    "to alias it to client_tpu (uninstall it or import "
                    "client_tpu directly)")
        except (ImportError, ValueError):
            pass  # no spec machinery surprises block the shim
    for alias, target in _ALIASES.items():
        module = importlib.import_module(target)
        sys.modules[alias] = module
        # Attribute access (tritonclient.grpc) must also resolve:
        # wire each aliased child onto its aliased parent — recording
        # what we touch so uninstall() can restore it (the "parent"
        # IS the real client_tpu module; leaking attributes onto it
        # would outlive the shim).
        if "." in alias:
            parent_alias, child = alias.rsplit(".", 1)
            parent = sys.modules.get(parent_alias)
            if parent is not None:
                _attr_backups.append(
                    (parent, child, hasattr(parent, child),
                     getattr(parent, child, None)))
                setattr(parent, child, module)
    if not quiet:
        warnings.warn(
            "tritonclient.* imports are aliased to client_tpu.* "
            "(client_tpu.compat); port imports to client_tpu when "
            "convenient",
            DeprecationWarning,
            stacklevel=2,
        )
    _installed = True


def uninstall() -> None:
    """Removes the aliases and restores any attributes install() set
    on the real client_tpu modules (test hygiene)."""
    global _installed
    for alias in _ALIASES:
        existing = sys.modules.get(alias)
        if existing is not None and existing.__name__.startswith(
                "client_tpu"):
            del sys.modules[alias]
    while _attr_backups:
        parent, child, had_prev, prev = _attr_backups.pop()
        if had_prev:
            setattr(parent, child, prev)
        else:
            try:
                delattr(parent, child)
            except AttributeError:
                pass
    _installed = False
