"""JAX/XLA-backed KServe-v2 inference server: the integration-test
fixture, co-located zero-copy serving peer, and benchmark target."""

from client_tpu.server.core import InferenceServerCore  # noqa: F401
from client_tpu.server.model import ServedModel, TensorSpec  # noqa: F401
from client_tpu.server.repository import ModelRepository  # noqa: F401
