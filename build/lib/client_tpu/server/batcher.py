"""Server-side dynamic batching.

The TPU-first equivalent of Triton's dynamic batcher (the scheduler
the reference's perf docs benchmark against and which BASELINE.md's
"BERT dynamic batch" config presumes): concurrent single requests are
fused along the batch dimension into one XLA call — larger MXU
matmuls, one compile-shape per preferred size, far less per-request
dispatch overhead — then the stacked outputs are split back per
request.

Requests are only fused when their per-sample shapes match; shape
changes flush the current bucket. Sequence requests bypass batching
entirely (state is per-request)."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from client_tpu.utils import InferenceServerException

NANOS_PER_US = 1_000


class _Pending:
    __slots__ = ("inputs", "params", "batch", "shape_key", "event",
                 "outputs", "error", "enqueue_ns", "queue_ns", "leader")

    def __init__(self, inputs, params, batch, shape_key):
        self.inputs = inputs
        self.params = params
        self.batch = batch
        self.shape_key = shape_key
        self.event = threading.Event()
        self.outputs = None
        self.error: Optional[Exception] = None
        self.enqueue_ns = time.monotonic_ns()
        self.queue_ns = 0
        # True for the request that represents the fused execution in
        # the server's execution_count statistic.
        self.leader = False


class DynamicBatcher:
    """One batcher (and gather thread) per served model."""

    def __init__(self, model, max_queue_delay_us: int = 500,
                 preferred_batch_sizes: Optional[List[int]] = None):
        self._model = model
        self._max_batch = max(int(model.max_batch_size), 1)
        self._delay_ns = max_queue_delay_us * NANOS_PER_US
        self._preferred = sorted(
            s for s in (preferred_batch_sizes or []) if s <= self._max_batch
        )
        self._queue: List[_Pending] = []
        self._cv = threading.Condition()
        self._stopping = False
        # Host fetches of fused outputs run here so the gather thread
        # keeps dispatching; concurrent device->host transfers pipeline.
        from concurrent.futures import ThreadPoolExecutor

        self._fetch_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="batch-fetch")
        # Bucket executions run here, NOT on the gather thread: a
        # model whose infer() blocks (an ensemble fetching its final
        # outputs, any host-side model) would otherwise serialize the
        # whole batcher at one bucket per blocking round trip; in the
        # pool, consecutive buckets' device work and transfers
        # pipeline. Buckets are mutually independent, so cross-bucket
        # completion order is free.
        self._exec_pool = ThreadPoolExecutor(
            max_workers=6, thread_name_prefix="batch-exec")
        self._thread = threading.Thread(target=self._gather_loop,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
        self._exec_pool.shutdown(wait=True)
        self._fetch_pool.shutdown(wait=True)

    # -- request side ----------------------------------------------------

    def infer(self, inputs: Dict[str, np.ndarray], params: dict,
              batch: int) -> Dict[str, np.ndarray]:
        """Blocks until this request's slice of a fused execution is
        ready. `batch` is the request's own batch-dim size."""
        shape_key = (
            tuple(
                (name, array.shape[1:], array.dtype.str)
                for name, array in sorted(inputs.items())
            ),
            _params_fingerprint(params),
        )
        pending = _Pending(inputs, params, batch, shape_key)
        with self._cv:
            self._queue.append(pending)
            self._cv.notify_all()
        pending.event.wait()
        if pending.error is not None:
            raise pending.error
        return pending.outputs, pending.queue_ns, pending.leader

    # -- gather thread ---------------------------------------------------

    def _gather_loop(self):
        while True:
            bucket: List[_Pending] = []
            with self._cv:
                while not self._queue and not self._stopping:
                    self._cv.wait()
                if self._stopping and not self._queue:
                    return
                first = self._queue.pop(0)
                bucket = [first]
                total = first.batch
                deadline = first.enqueue_ns + self._delay_ns
                # Gather shape-compatible requests until the batch is
                # full or the first request's delay budget expires.
                while total < self._max_batch:
                    if self._take_compatible(bucket, first.shape_key,
                                             total):
                        total = sum(p.batch for p in bucket)
                        if self._at_preferred(total):
                            break
                        continue
                    now = time.monotonic_ns()
                    if now >= deadline or self._stopping:
                        break
                    self._cv.wait(
                        timeout=(deadline - now) / 1e9)
            try:
                self._exec_pool.submit(self._execute, bucket)
            except RuntimeError:  # pool shut down mid-stop
                self._execute(bucket)

    def _take_compatible(self, bucket, shape_key, total) -> bool:
        """Moves the next compatible queued request into the bucket
        (caller holds the lock). Returns False when none fits."""
        for i, pending in enumerate(self._queue):
            if pending.shape_key != shape_key:
                continue
            if total + pending.batch > self._max_batch:
                continue
            bucket.append(self._queue.pop(i))
            return True
        return False

    def _at_preferred(self, total) -> bool:
        # Stop gathering only once the LARGEST preferred size is
        # reached — smaller preferred sizes are padding targets, not
        # gather limits.
        return bool(self._preferred) and total >= self._preferred[-1]

    def _padded_size(self, total: int) -> int:
        """Rounds the fused batch up to a stable compile shape: the
        smallest preferred size that fits, else the next power of two
        (capped at max_batch). XLA traces once per shape — unpadded
        fusing would recompile for every distinct request mix."""
        for size in self._preferred:
            if total <= size:
                return size
        if total >= self._max_batch:
            return self._max_batch
        size = 1
        while size < total:
            size <<= 1
        return min(size, self._max_batch)

    def _execute(self, bucket: List[_Pending]):
        start_ns = time.monotonic_ns()
        bucket[0].leader = True
        for pending in bucket:
            pending.queue_ns = start_ns - pending.enqueue_ns
        done_inline = True
        try:
            total = sum(p.batch for p in bucket)
            target = self._padded_size(total)
            if len(bucket) == 1 and bucket[0].batch == target:
                bucket[0].outputs = self._model.infer(
                    bucket[0].inputs, bucket[0].params)
            else:
                fused = {
                    name: _fuse_chunks(
                        [p.inputs[name] for p in bucket], target, total)
                    for name in bucket[0].inputs
                }
                outputs = self._model.infer(fused, bucket[0].params)
                if all(
                    isinstance(p.inputs[name], np.ndarray)
                    for p in bucket for name in p.inputs
                ):
                    # Every request arrived over the wire and will be
                    # serialized to host bytes anyway: fetch the fused
                    # output ONCE (one relay round-trip for the whole
                    # bucket, not n slice transfers) — and do it on the
                    # fetch pool so the gather thread can dispatch the
                    # NEXT bucket while this transfer is in flight.
                    for array in outputs.values():
                        if hasattr(array, "copy_to_host_async"):
                            array.copy_to_host_async()
                    try:
                        self._fetch_pool.submit(
                            self._finish_host_bucket, bucket, outputs)
                        done_inline = False
                    except RuntimeError:  # pool shut down mid-stop:
                        self._finish_host_bucket(bucket, outputs)
                        return
                else:
                    # Device-resident bucket (TPU-shm path): slices are
                    # lazy device views; outputs stay in HBM end-to-end.
                    self._scatter(bucket, outputs)
        except Exception as e:
            self._assign_error(bucket, e)
        finally:
            if done_inline:
                for pending in bucket:
                    pending.event.set()

    @staticmethod
    def _scatter(bucket: List[_Pending], outputs) -> None:
        offset = 0
        for pending in bucket:
            pending.outputs = {
                name: array[offset:offset + pending.batch]
                for name, array in outputs.items()
            }
            offset += pending.batch

    def _finish_host_bucket(self, bucket: List[_Pending], outputs) -> None:
        try:
            host = {name: np.asarray(a) for name, a in outputs.items()}
            self._scatter(bucket, host)
        except Exception as e:  # noqa: BLE001 — waiters must wake
            self._assign_error(bucket, e)
        finally:
            for pending in bucket:
                pending.event.set()

    @staticmethod
    def _assign_error(bucket: List[_Pending], e: Exception) -> None:
        error = e if isinstance(e, InferenceServerException) else \
            InferenceServerException(
                "batched inference failed: %s" % e, status="INTERNAL")
        for pending in bucket:
            pending.error = error


def _fuse_chunks(chunks, target: int, total: int):
    """Assembles per-request input chunks into one batch of `target`
    rows (unfilled pad rows stay zero; they are computed and
    discarded).

    When any chunk is a device array (the TPU-shm path resolves
    inputs to ``jax.Array``s), fusion runs as device ops — a numpy
    concat here would silently drag every chunk back to host, defeating
    the arena's zero-copy design (the round-2 12-infer/s regression).
    The device path writes chunks into a zero buffer with
    ``dynamic_update_slice`` — start offsets are runtime values, so XLA
    compiles ONE kernel per (buffer, chunk) shape pair instead of one
    ``concatenate`` per distinct chunk-count/pad mix (the round-3
    steady-state recompile source)."""
    all_host = all(isinstance(c, np.ndarray) for c in chunks)
    if all_host:
        if target > total:
            pad_shape = (target - total,) + tuple(chunks[-1].shape[1:])
            if chunks[-1].dtype.kind == "O":  # BYTES: pad rows need
                pad = np.broadcast_to(  # valid payloads, not int 0
                    chunks[-1][-1:], pad_shape)
            else:
                pad = np.zeros(pad_shape, dtype=chunks[-1].dtype)
            chunks = chunks + [pad]
        return np.concatenate(chunks, axis=0)
    import jax
    import jax.numpy as jnp

    first = chunks[0]
    buf = jnp.zeros((target,) + tuple(first.shape[1:]), dtype=first.dtype)
    # np.int32 offsets are runtime arguments to the cached executable,
    # never baked-in constants — one compile per shape pair, period.
    zeros = (np.int32(0),) * (buf.ndim - 1)
    offset = 0
    for chunk in chunks:
        buf = jax.lax.dynamic_update_slice(
            buf, chunk, (np.int32(offset),) + zeros)
        offset += int(chunk.shape[0])
    return buf


def _params_fingerprint(params: dict):
    """Normalized, hashable view of request parameters. Requests are
    only fused when their parameters match — fusing would otherwise
    execute the whole bucket with the leader's params, silently
    dropping the rest (priority, timeout, custom params)."""
    if not params:
        return ()
    return tuple(
        (key, repr(params[key])) for key in sorted(params)
    )


def wants_dynamic_batching(model) -> bool:
    return (
        getattr(model, "dynamic_batching", False)
        and int(getattr(model, "max_batch_size", 0)) > 1
        and not getattr(model, "decoupled", False)
    )
