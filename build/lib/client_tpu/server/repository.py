"""Model repository: named models with explicit load/unload and an
index — the server-side counterpart of the client's model-control APIs
(RepositoryIndex / RepositoryModelLoad / RepositoryModelUnload)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from client_tpu.protocol import inference_pb2 as pb
from client_tpu.server.model import ServedModel
from client_tpu.utils import InferenceServerException


class ModelRepository:
    def __init__(self):
        self._lock = threading.RLock()
        self._models: Dict[str, ServedModel] = {}
        self._factories: Dict[str, Callable[[], ServedModel]] = {}
        self._state: Dict[str, str] = {}
        self._reason: Dict[str, str] = {}

    def add_factory(self, name: str, factory: Callable[[], ServedModel]) -> None:
        """Make ``name`` loadable on demand without instantiating it."""
        with self._lock:
            self._factories[name] = factory
            self._state.setdefault(name, "UNAVAILABLE")

    def add_model(self, model: ServedModel, warmup: bool = False) -> None:
        with self._lock:
            self._models[model.name] = model
            # reload-after-unload resurrects this exact instance (a
            # bare type() factory would lose constructor arguments)
            self._factories.setdefault(model.name, lambda model=model: model)
            self._state[model.name] = "READY"
            self._reason.pop(model.name, None)
        if warmup:
            model.warmup()

    def load(self, name: str) -> ServedModel:
        with self._lock:
            if name in self._models:
                self._state[name] = "READY"
                return self._models[name]
            factory = self._factories.get(name)
            if factory is None:
                raise InferenceServerException(
                    "unknown model '%s'" % name, status="NOT_FOUND"
                )
        try:
            model = factory()
        except Exception as e:
            with self._lock:
                self._state[name] = "UNAVAILABLE"
                self._reason[name] = str(e)
            raise InferenceServerException(
                "failed to load model '%s': %s" % (name, e), status="INTERNAL"
            )
        with self._lock:
            self._models[name] = model
            self._state[name] = "READY"
            self._reason.pop(name, None)
        return model

    def unload(self, name: str) -> None:
        with self._lock:
            model = self._models.pop(name, None)
            if model is None and name not in self._factories:
                raise InferenceServerException(
                    "unknown model '%s'" % name, status="NOT_FOUND"
                )
            self._state[name] = "UNAVAILABLE"
            self._reason[name] = "unloaded"
        if model is not None:
            model.unload()

    def get(self, name: str, version: str = "") -> ServedModel:
        with self._lock:
            model = self._models.get(name)
        if model is None:
            raise InferenceServerException(
                "request for unknown model: '%s' is not found" % name,
                status="NOT_FOUND",
            )
        if version and model.version != version:
            raise InferenceServerException(
                "request for unknown model version: '%s' version %s"
                % (name, version),
                status="NOT_FOUND",
            )
        return model

    def is_ready(self, name: str, version: str = "") -> bool:
        with self._lock:
            model = self._models.get(name)
            if model is None or self._state.get(name) != "READY":
                return False
            return not version or model.version == version

    def ready_models(self) -> List[ServedModel]:
        with self._lock:
            return [
                m for n, m in self._models.items()
                if self._state.get(n) == "READY"
            ]

    def index(self, ready_only: bool = False) -> pb.RepositoryIndexResponse:
        response = pb.RepositoryIndexResponse()
        with self._lock:
            for name in sorted(set(self._factories) | set(self._models)):
                state = self._state.get(name, "UNAVAILABLE")
                if ready_only and state != "READY":
                    continue
                model = self._models.get(name)
                response.models.add(
                    name=name,
                    version=model.version if model else "",
                    state=state,
                    reason=self._reason.get(name, ""),
                )
        return response
