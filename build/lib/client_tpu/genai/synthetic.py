"""Synthetic prompt generation (parity: genai-perf
synthetic_prompt_generator.py — prompts of approximately N tokens
drawn from a corpus, with a configurable standard deviation)."""

from __future__ import annotations

import random
from typing import List

# A small built-in corpus; prompts are built by sampling words until
# the tokenizer says the target token count is reached.
_CORPUS = (
    "the quick brown fox jumps over a lazy dog while seventy silent "
    "engineers measure throughput latency and bandwidth across oceans "
    "of accelerated matrix multiplication hardware scheduling tokens "
    "streams batches caches prompts answers questions models layers "
    "attention heads embedding tables gradients optimizers learning "
    "rates compilers graphs kernels memory tiles vectors scalars"
).split()


class SyntheticPromptGenerator:
    def __init__(self, tokenizer, seed: int = 0):
        self._tokenizer = tokenizer
        self._rng = random.Random(seed)

    def generate_prompt(self, mean_tokens: int, stddev_tokens: float = 0.0
                        ) -> str:
        target = max(1, int(self._rng.gauss(mean_tokens, stddev_tokens))
                     if stddev_tokens > 0 else mean_tokens)
        # Track the token count incrementally (word + separator) so
        # generation stays linear in the target length; re-encoding
        # the joined prompt every step is quadratic for long contexts.
        words: List[str] = []
        total = 0
        while total < target:
            for word in self._rng.choices(_CORPUS, k=8):
                piece = word if not words else " " + word
                words.append(word)
                total += self._count(piece)
                if total >= target:
                    break
        # trim down to the target token count
        while len(words) > 1 and total > target:
            tail = words.pop()
            total -= self._count(" " + tail)
        return " ".join(words) if words else _CORPUS[0]

    def generate_prompts(self, count: int, mean_tokens: int,
                         stddev_tokens: float = 0.0) -> List[str]:
        return [self.generate_prompt(mean_tokens, stddev_tokens)
                for _ in range(count)]

    def _count(self, text: str) -> int:
        return len(self._tokenizer.encode(text))
