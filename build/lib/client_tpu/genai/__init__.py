"""LLM benchmarking front-end over the perf harness.

Parity target: the reference's genai-perf package
(perf_analyzer/genai-perf: CLI -> input generation -> perf_analyzer
run -> profile-export parsing -> TTFT / inter-token-latency /
token-throughput statistics -> console/JSON/CSV export). Here the
"perf_analyzer subprocess" is the in-repo client_tpu.perf harness,
invoked in-process."""

from client_tpu.genai.metrics import (
    LLMMetrics,
    LLMProfileDataParser,
    Statistics,
)
from client_tpu.genai.inputs import LlmInputs, OutputFormat
from client_tpu.genai.synthetic import SyntheticPromptGenerator
from client_tpu.genai.tokenizer import get_tokenizer

__all__ = [
    "LLMMetrics",
    "LLMProfileDataParser",
    "Statistics",
    "LlmInputs",
    "OutputFormat",
    "SyntheticPromptGenerator",
    "get_tokenizer",
]
