from client_tpu.genai.main import main

main()
