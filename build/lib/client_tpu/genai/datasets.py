"""Public-dataset prompt sources (parity: genai-perf
llm_inputs/llm_inputs.py OpenOrca / CNN-dailymail input types).

The reference pulls rows from the HF datasets-server REST API at input
generation time. This module does the same when the network allows,
and otherwise degrades to the synthetic prompt generator with a clear
warning — offline images (like the TPU build/CI hosts) still get a
working benchmark run.
"""

from __future__ import annotations

import json
import sys
import urllib.error
import urllib.request
from typing import List, Optional

DATASETS = {
    # name -> (datasets-server query, field holding the prompt text)
    "openorca": (
        "dataset=Open-Orca%2FOpenOrca&config=default&split=train",
        "question",
    ),
    "cnn_dailymail": (
        "dataset=cnn_dailymail&config=3.0.0&split=train",
        "article",
    ),
}

_ROWS_URL = ("https://datasets-server.huggingface.co/rows"
             "?%s&offset=%d&length=%d")
_PAGE = 100  # datasets-server caps length at 100 per request


def dataset_prompts(
    name: str,
    num_prompts: int,
    fallback_generator=None,
    fallback_tokens_mean: int = 64,
    fallback_tokens_stddev: float = 0.0,
    timeout_s: float = 10.0,
    _opener=None,
) -> List[str]:
    """Fetch ``num_prompts`` prompts from a named public dataset
    (paginating past the server's 100-row page cap); falls back to
    ``fallback_generator.generate_prompts`` offline."""
    if name not in DATASETS:
        raise ValueError(
            "unknown dataset %r (have: %s)" % (name, ", ".join(DATASETS)))
    query, field = DATASETS[name]
    opener = _opener or urllib.request.urlopen
    try:
        prompts: List[str] = []
        offset = 0
        while len(prompts) < num_prompts:
            url = _ROWS_URL % (
                query, offset, min(num_prompts - len(prompts), _PAGE))
            with opener(url, timeout=timeout_s) as response:
                doc = json.load(response)
            page = [
                str(row["row"][field])
                for row in doc.get("rows", [])
                if field in row.get("row", {})
            ]
            if not page:
                break  # dataset exhausted
            prompts.extend(page)
            offset += len(doc.get("rows", []))
        if not prompts:
            raise ValueError("dataset response had no '%s' rows" % field)
        if len(prompts) < num_prompts:
            print(
                "genai: dataset '%s' yielded only %d of %d requested "
                "prompts" % (name, len(prompts), num_prompts),
                file=sys.stderr,
            )
        return prompts[:num_prompts]
    except Exception as exc:  # noqa: BLE001 — any failure degrades
        if fallback_generator is None:
            raise
        print(
            "genai: dataset '%s' unavailable (%s); using synthetic "
            "prompts" % (name, exc),
            file=sys.stderr,
        )
        return fallback_generator.generate_prompts(
            num_prompts, fallback_tokens_mean, fallback_tokens_stddev)
