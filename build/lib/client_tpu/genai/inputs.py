"""LLM input-dataset construction (parity: genai-perf
llm_inputs/llm_inputs.py — synthetic or file prompts rendered into the
payload format of the target endpoint)."""

from __future__ import annotations

import enum
import json
from typing import List, Optional

from client_tpu.genai.synthetic import SyntheticPromptGenerator


class OutputFormat(enum.Enum):
    # perf-harness data JSON driving the decoupled generate model
    TRITON_GENERATE = "triton_generate"
    # OpenAI-style chat-completions payloads (one JSON body per step)
    OPENAI_CHAT = "openai_chat"


class LlmInputs:
    """Builds the input file consumed by the perf harness (the
    reference writes llm_inputs.json for perf_analyzer)."""

    def __init__(self, tokenizer, seed: int = 0):
        self._generator = SyntheticPromptGenerator(tokenizer, seed)

    def create_prompts(
        self,
        num_prompts: int = 10,
        input_tokens_mean: int = 64,
        input_tokens_stddev: float = 0.0,
        input_file: Optional[str] = None,
    ) -> List[str]:
        if input_file:
            prompts = []
            with open(input_file) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    # JSONL with {"text_input": ...} or raw text lines
                    try:
                        doc = json.loads(line)
                    except json.JSONDecodeError:
                        prompts.append(line)
                        continue
                    if isinstance(doc, dict):
                        prompts.append(doc.get("text_input") or
                                       doc.get("prompt") or line)
                    elif isinstance(doc, str):
                        prompts.append(doc)
                    else:
                        raise ValueError(
                            "input file '%s': line is neither an object "
                            "with text_input/prompt nor a string: %r"
                            % (input_file, line[:80]))
            if not prompts:
                raise ValueError("input file '%s' has no prompts"
                                 % input_file)
            return prompts[:num_prompts] if num_prompts else prompts
        return self._generator.generate_prompts(
            num_prompts, input_tokens_mean, input_tokens_stddev)

    def convert_to_dataset(
        self,
        prompts: List[str],
        output_format: OutputFormat = OutputFormat.TRITON_GENERATE,
        output_tokens_mean: int = 32,
        ignore_eos: bool = True,
        model_name: str = "llm",
    ) -> dict:
        if output_format == OutputFormat.OPENAI_CHAT:
            return {
                "data": [
                    {"payload": [{
                        "model": model_name,
                        "messages": [
                            {"role": "user", "content": prompt}],
                        "max_tokens": output_tokens_mean,
                        "stream": True,
                    }]}
                    for prompt in prompts
                ]
            }
        steps = []
        for prompt in prompts:
            step = {
                "text_input": [prompt],
                "max_tokens": [int(output_tokens_mean)],
            }
            if ignore_eos:
                step["ignore_eos"] = [True]
            steps.append(step)
        return {"data": steps}

    def write_dataset(self, dataset: dict, path: str) -> str:
        with open(path, "w") as f:
            json.dump(dataset, f)
        return path
