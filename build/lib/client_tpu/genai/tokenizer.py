"""Tokenizer wrapper (parity: genai-perf tokenizer.py — a thin HF
AutoTokenizer facade). ``byte`` gives a dependency-free tokenizer that
matches the in-repo LLM's byte-level vocabulary; any other name is
resolved through transformers when available."""

from __future__ import annotations

from typing import List

DEFAULT_TOKENIZER = "byte"


class ByteLevelTokenizer:
    """One token per UTF-8 byte — matches models.llm.ByteTokenizer."""

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        return bytes(int(i) & 0xFF for i in ids).decode("utf-8", "replace")


class HfTokenizer:
    def __init__(self, name: str, trust_remote_code: bool = False):
        from transformers import AutoTokenizer  # gated import

        self._tok = AutoTokenizer.from_pretrained(
            name, trust_remote_code=trust_remote_code)

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids) -> str:
        return self._tok.decode(ids)


def get_tokenizer(name: str = DEFAULT_TOKENIZER,
                  trust_remote_code: bool = False):
    if name in (None, "", "byte", DEFAULT_TOKENIZER):
        return ByteLevelTokenizer()
    try:
        return HfTokenizer(name, trust_remote_code)
    except Exception as e:
        raise ValueError(
            "unable to load tokenizer '%s' (%s); use 'byte' for the "
            "dependency-free byte-level tokenizer" % (name, e))
