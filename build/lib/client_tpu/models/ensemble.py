"""Ensemble scheduling: a pipeline of composing models executed
server-side (BASELINE config #4: preprocess -> backbone ->
postprocess over decoupled streaming). The perf harness's ModelParser
reads the composing models out of the config like it does for triton
ensembles."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from client_tpu.protocol import model_config_pb2 as mc
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


class PreprocessModel(ServedModel):
    """uint8 image [224,224,3] -> normalized FP32 NHWC.

    Runs ON DEVICE: the wire payload stays the compact uint8 image
    (4x smaller than fp32) and the normalized tensor is born in HBM,
    so the downstream backbone fuses DEVICE chunks across concurrent
    ensemble requests and nothing round-trips to the host between
    steps."""

    platform = "jax"
    max_batch_size = 32

    def __init__(self, name: str = "preprocess"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("RAW_IMAGE", "UINT8", [224, 224, 3])]
        self.outputs = [TensorSpec("IMAGE", "FP32", [224, 224, 3])]
        mean = np.array([0.485, 0.456, 0.406], dtype=np.float32) * 255
        std = np.array([0.229, 0.224, 0.225], dtype=np.float32) * 255
        import jax
        import jax.numpy as jnp

        mean_d, std_d = jnp.asarray(mean), jnp.asarray(std)
        self._fn = jax.jit(
            lambda raw: (raw.astype(jnp.float32) - mean_d) / std_d)

    def infer(self, inputs, parameters=None):
        return {"IMAGE": self._fn(inputs["RAW_IMAGE"])}

    def warmup(self) -> None:
        import jax
        import jax.numpy as jnp

        for batch in (1, 8, 16, 32):
            jax.block_until_ready(
                self._fn(jnp.zeros((batch, 224, 224, 3), dtype=jnp.uint8)))


class PostprocessModel(ServedModel):
    """logits -> top-1 "score:index" BYTES label."""

    platform = "jax"
    max_batch_size = 32

    def __init__(self, name: str = "postprocess", num_classes: int = 1000):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("LOGITS", "FP32", [num_classes])]
        self.outputs = [TensorSpec("LABEL", "BYTES", [1])]

    def infer(self, inputs, parameters=None):
        logits = np.asarray(inputs["LOGITS"])
        batched = logits.ndim == 2
        if not batched:
            logits = logits[None]
        idx = logits.argmax(axis=-1)
        exp = np.exp(logits - logits.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        labels = np.array(
            [("%f:%d" % (probs[i, idx[i]], idx[i])).encode()
             for i in range(len(idx))],
            dtype=np.object_,
        )[:, None]
        return {"LABEL": labels if batched else labels[0]}


class EnsembleModel(ServedModel):
    """Executes composing models in order, wiring tensors via
    input/output maps (ensemble tensor name -> step tensor name)."""

    platform = "ensemble"

    def __init__(
        self,
        name: str,
        repository,
        steps: List[Tuple[str, Dict[str, str], Dict[str, str]]],
        inputs: List[TensorSpec],
        outputs: List[TensorSpec],
        max_batch_size: int = 0,
    ):
        super().__init__()
        self.name = name
        self._repository = repository
        self._steps = steps
        self.inputs = inputs
        self.outputs = outputs
        self.max_batch_size = max_batch_size
        # Set by the server core so composing-step executions show up
        # in per-model statistics (Triton records composing models'
        # queue/compute like top-level requests): callable
        # (model_name, count, compute_ns).
        self.stats_recorder = None
        # Set by the server core: resolves a composing model to its
        # dynamic batcher (or None). Steps entering a batching model's
        # scheduler fuse ACROSS concurrent ensemble requests — without
        # this, every concurrent stream request runs its own batch-1
        # backbone execution and pays its own device round trip.
        self.batcher_resolver = None

    def _extend_config(self, config: mc.ModelConfig) -> None:
        for model_name, input_map, output_map in self._steps:
            step = config.ensemble_scheduling.step.add()
            step.model_name = model_name
            for ens_name, step_name in input_map.items():
                step.input_map[ens_name] = step_name
            for ens_name, step_name in output_map.items():
                step.output_map[ens_name] = step_name

    def infer(self, inputs, parameters=None):
        tensors: Dict[str, np.ndarray] = dict(inputs)
        for model_name, input_map, output_map in self._steps:
            # load (not get): resolve composing models on demand even
            # if they were never explicitly loaded or got unloaded
            model = self._repository.load(model_name)
            step_inputs = {}
            for ens_name, step_name in input_map.items():
                if ens_name not in tensors:
                    raise InferenceServerException(
                        "ensemble '%s': tensor '%s' unavailable for step "
                        "'%s'" % (self.name, ens_name, model_name),
                        status="INVALID_ARGUMENT",
                    )
                step_inputs[step_name] = tensors[ens_name]
            first = next(iter(step_inputs.values()), None)
            count = (
                int(first.shape[0])
                if getattr(first, "ndim", 0) and model.max_batch_size > 0
                else 1
            )
            batcher = self.batcher_resolver(model) \
                if self.batcher_resolver is not None else None
            if self.stats_recorder is not None:
                import time

                start_ns = time.monotonic_ns()
                if batcher is not None:
                    step_outputs, queue_ns, leader = batcher.infer(
                        step_inputs, parameters or {}, count)
                    # Triton books fused compute once, per execution:
                    # only the leader records the (queue-corrected)
                    # wall time; riders contribute their row count.
                    executions = 1 if leader else 0
                    compute_ns = max(
                        time.monotonic_ns() - start_ns - queue_ns, 0
                    ) if leader else 0
                else:
                    step_outputs = model.infer(step_inputs, parameters)
                    executions = 1
                    compute_ns = time.monotonic_ns() - start_ns
                self.stats_recorder(
                    model_name, count, compute_ns, executions)
            elif batcher is not None:
                step_outputs, _, _ = batcher.infer(
                    step_inputs, parameters or {}, count)
            else:
                step_outputs = model.infer(step_inputs, parameters)
            for ens_name, step_name in output_map.items():
                tensors[ens_name] = step_outputs[step_name]
        return {spec.name: tensors[spec.name] for spec in self.outputs}

    def warmup(self) -> None:
        for model_name, _, _ in self._steps:
            self._repository.load(model_name).warmup()


def make_image_ensemble(repository, name: str = "ensemble_image",
                        backbone: str = "resnet50") -> EnsembleModel:
    """preprocess -> resnet -> postprocess with triton-style maps."""
    ensemble = EnsembleModel(
        name=name,
        repository=repository,
        steps=[
            ("preprocess", {"RAW_IMAGE": "RAW_IMAGE"}, {"image": "IMAGE"}),
            (backbone, {"image": "INPUT"}, {"logits": "OUTPUT"}),
            ("postprocess", {"logits": "LOGITS"}, {"LABEL": "LABEL"}),
        ],
        inputs=[TensorSpec("RAW_IMAGE", "UINT8", [224, 224, 3])],
        outputs=[TensorSpec("LABEL", "BYTES", [1])],
        max_batch_size=32,
    )
    # Fuse concurrent ensemble requests BEFORE the first device hop:
    # per-request image upload + logits fetch through the relay cap a
    # request-at-a-time pipeline at ~80/s regardless of server design
    # (each small transfer serializes ~12 ms in the relay), while a
    # fused bucket pays ONE upload and ONE fetch for the whole batch.
    # The 20 ms gather window (measured: 5 ms only reached ~4-wide
    # buckets under continuous streaming load; 20 ms reaches ~15 and
    # is small next to the bucket's ~150 ms pipeline) lets a response
    # burst's re-sends re-converge into the next bucket.
    ensemble.dynamic_batching = True
    ensemble.preferred_batch_sizes = [8, 16, 32]
    ensemble.max_queue_delay_us = 20000
    return ensemble
