"""The `simple` add/sub model: OUTPUT0 = INPUT0 + INPUT1,
OUTPUT1 = INPUT0 - INPUT1 — the protocol-conformance and latency-floor
workhorse (reference examples' `simple` model; BASELINE config #1).

Placement: defaults to the host CPU backend — for a 64-byte tensor the
accelerator round trip is pure loss (on this image the TPU relay's
device-to-host hop alone is ~20 ms). Pass ``device="tpu"`` to pin it
on the accelerator, which is the right choice when I/O rides TPU
shared-memory regions and never leaves HBM.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import triton_to_np_dtype


class AddSub(ServedModel):
    """Element-wise add/sub over two same-shape inputs, one fused XLA
    kernel. Device-resident inputs (TPU shm regions) are consumed in
    place with no host round-trip."""

    platform = "jax"

    def __init__(self, name: str = "add_sub", datatype: str = "INT32",
                 shape=(16,), device: str = "cpu"):
        super().__init__()
        self.name = name
        self._datatype = datatype
        self._shape = list(shape)
        self._device_kind = device
        self.inputs = [
            TensorSpec("INPUT0", datatype, self._shape),
            TensorSpec("INPUT1", datatype, self._shape),
        ]
        self.outputs = [
            TensorSpec("OUTPUT0", datatype, self._shape),
            TensorSpec("OUTPUT1", datatype, self._shape),
        ]
        self._fn = jax.jit(lambda a, b: (a + b, a - b))
        self._device = None
        if device == "cpu":
            self._device = jax.devices("cpu")[0]

    def infer(self, inputs: Dict[str, np.ndarray],
              parameters: Optional[dict] = None) -> Dict[str, np.ndarray]:
        a, b = inputs["INPUT0"], inputs["INPUT1"]
        if (
            self._device is not None
            and isinstance(a, np.ndarray)
            and isinstance(b, np.ndarray)
        ):
            # Host tensors on a host-placed model: plain numpy is the
            # fastest "kernel" there is for 16 elements.
            return {"OUTPUT0": a + b, "OUTPUT1": a - b}
        out0, out1 = self._fn(a, b)
        return {"OUTPUT0": out0, "OUTPUT1": out1}

    def warmup(self) -> None:
        np_dtype = triton_to_np_dtype(self._datatype)
        if self._device is not None:
            with jax.default_device(self._device):
                zero = jnp.zeros(self._shape, dtype=np_dtype)
                jax.block_until_ready(self._fn(zero, zero))
        else:
            zero = jnp.zeros(self._shape, dtype=np_dtype)
            jax.block_until_ready(self._fn(zero, zero))
