"""Hand-written gRPC stub/servicer glue for GRPCInferenceService.

The build image has grpcio but not grpc_tools, so instead of generated
``*_pb2_grpc.py`` we declare the service surface once in _METHODS and
derive both the client stub and the server registration from it.
"""

from __future__ import annotations

import grpc

from client_tpu.protocol import inference_pb2 as pb

SERVICE_NAME = "inference.GRPCInferenceService"

# (method, request type, response type, client-streaming, server-streaming)
_METHODS = [
    ("ServerLive", pb.ServerLiveRequest, pb.ServerLiveResponse, False, False),
    ("ServerReady", pb.ServerReadyRequest, pb.ServerReadyResponse, False, False),
    ("ModelReady", pb.ModelReadyRequest, pb.ModelReadyResponse, False, False),
    ("ServerMetadata", pb.ServerMetadataRequest, pb.ServerMetadataResponse, False, False),
    ("ModelMetadata", pb.ModelMetadataRequest, pb.ModelMetadataResponse, False, False),
    ("ModelInfer", pb.ModelInferRequest, pb.ModelInferResponse, False, False),
    ("ModelStreamInfer", pb.ModelInferRequest, pb.ModelStreamInferResponse, True, True),
    ("ModelConfig", pb.ModelConfigRequest, pb.ModelConfigResponse, False, False),
    ("ModelStatistics", pb.ModelStatisticsRequest, pb.ModelStatisticsResponse, False, False),
    ("RepositoryIndex", pb.RepositoryIndexRequest, pb.RepositoryIndexResponse, False, False),
    ("RepositoryModelLoad", pb.RepositoryModelLoadRequest, pb.RepositoryModelLoadResponse, False, False),
    ("RepositoryModelUnload", pb.RepositoryModelUnloadRequest, pb.RepositoryModelUnloadResponse, False, False),
    ("SystemSharedMemoryStatus", pb.SystemSharedMemoryStatusRequest, pb.SystemSharedMemoryStatusResponse, False, False),
    ("SystemSharedMemoryRegister", pb.SystemSharedMemoryRegisterRequest, pb.SystemSharedMemoryRegisterResponse, False, False),
    ("SystemSharedMemoryUnregister", pb.SystemSharedMemoryUnregisterRequest, pb.SystemSharedMemoryUnregisterResponse, False, False),
    ("TpuSharedMemoryStatus", pb.TpuSharedMemoryStatusRequest, pb.TpuSharedMemoryStatusResponse, False, False),
    ("TpuSharedMemoryRegister", pb.TpuSharedMemoryRegisterRequest, pb.TpuSharedMemoryRegisterResponse, False, False),
    ("TpuSharedMemoryUnregister", pb.TpuSharedMemoryUnregisterRequest, pb.TpuSharedMemoryUnregisterResponse, False, False),
    ("TraceSetting", pb.TraceSettingRequest, pb.TraceSettingResponse, False, False),
    ("LogSettings", pb.LogSettingsRequest, pb.LogSettingsResponse, False, False),
]


class GRPCInferenceServiceStub:
    """Client stub: one multicallable attribute per RPC, built against a
    ``grpc.Channel`` or ``grpc.aio.Channel``."""

    def __init__(self, channel):
        for name, req_t, resp_t, cstream, sstream in _METHODS:
            path = "/%s/%s" % (SERVICE_NAME, name)
            if cstream and sstream:
                factory = channel.stream_stream
            elif sstream:
                factory = channel.unary_stream
            elif cstream:
                factory = channel.stream_unary
            else:
                factory = channel.unary_unary
            setattr(
                self,
                name,
                factory(
                    path,
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )


class GRPCInferenceServiceServicer:
    """Base servicer; subclasses override the RPCs they implement."""

    def _unimplemented(self, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        context.set_details("method not implemented")
        raise NotImplementedError("method not implemented")


def _make_default(name):
    def handler(self, request, context):
        self._unimplemented(context)

    handler.__name__ = name
    return handler


for _name, _req, _resp, _cs, _ss in _METHODS:
    setattr(GRPCInferenceServiceServicer, _name, _make_default(_name))


def add_GRPCInferenceServiceServicer_to_server(servicer, server):
    handlers = {}
    for name, req_t, resp_t, cstream, sstream in _METHODS:
        if cstream and sstream:
            factory = grpc.stream_stream_rpc_method_handler
        elif sstream:
            factory = grpc.unary_stream_rpc_method_handler
        elif cstream:
            factory = grpc.stream_unary_rpc_method_handler
        else:
            factory = grpc.unary_unary_rpc_method_handler
        handlers[name] = factory(
            getattr(servicer, name),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
