"""KServe-v2 protocol definitions: protobuf messages + gRPC service
glue. Regenerate the ``*_pb2`` modules with ``regen.sh``."""

from client_tpu.protocol import inference_pb2, model_config_pb2  # noqa: F401
