"""Client plugin hook: a callable invoked with every outgoing request
so users can inject headers (auth, tracing) uniformly across
transports. Parity: reference tritonclient/_plugin.py:31-48."""

from __future__ import annotations

import abc


class InferenceServerClientPlugin(abc.ABC):
    """A plugin is called with the :class:`Request` right before every
    network operation and may mutate its headers in place."""

    @abc.abstractmethod
    def __call__(self, request: "Request") -> None:
        ...


class Request:
    """An outgoing request as seen by plugins: just mutable headers."""

    def __init__(self, headers: dict):
        self.headers = headers


class BasicAuth(InferenceServerClientPlugin):
    """Adds an HTTP Basic ``Authorization`` header."""

    def __init__(self, username: str, password: str):
        import base64

        cred = ("%s:%s" % (username, password)).encode()
        self._auth_header = "Basic " + base64.b64encode(cred).decode()

    def __call__(self, request: Request) -> None:
        request.headers["Authorization"] = self._auth_header


class InferenceServerClientBase:
    """Shared plugin registration/dispatch for every client flavor."""

    def __init__(self):
        self._plugin = None

    def register_plugin(self, plugin: InferenceServerClientPlugin) -> None:
        if plugin is None:
            raise ValueError("plugin must not be None")
        if self._plugin is not None:
            raise RuntimeError("a plugin is already registered")
        self._plugin = plugin

    def plugin(self):
        return self._plugin

    def unregister_plugin(self) -> None:
        if self._plugin is None:
            raise RuntimeError("no plugin is registered")
        self._plugin = None

    def _call_plugin(self, headers: dict) -> dict:
        """Run the plugin (if any) over a headers dict; returns the
        (possibly new) headers mapping."""
        if self._plugin is not None:
            if headers is None:
                headers = {}
            self._plugin(Request(headers))
        return headers
