"""client_tpu — a TPU-native inference client framework.

A ground-up re-design of the Triton Inference Server client ecosystem
(reference: ``gyulaz-htec/client``) for TPU hosts:

- ``client_tpu.grpc`` / ``client_tpu.http`` — KServe-v2 protocol clients
  (sync, callback-async, asyncio, decoupled bidi streaming).
- ``client_tpu.utils`` — dtype maps (BF16 first-class), BYTES wire
  serialization, exceptions.
- ``client_tpu.utils.shared_memory`` — POSIX system shared memory.
- ``client_tpu.utils.tpu_shared_memory`` — zero-copy TPU HBM tensor I/O
  (the re-target of the reference's ``cuda_shared_memory`` module).
- ``client_tpu.server`` — a JAX/XLA-backed KServe-v2 server used for
  integration tests, co-located zero-copy serving, and benchmarking.
- ``client_tpu.perf`` — load-generation + profiling harness
  (perf_analyzer equivalent); ``client_tpu.genai`` — LLM benchmark
  metrics (genai-perf equivalent).
- ``client_tpu.models`` / ``client_tpu.parallel`` / ``client_tpu.ops`` —
  the server-side JAX model zoo, mesh/sharding helpers, and Pallas
  kernels backing the benchmark model repository.
"""

__version__ = "0.1.0"

from client_tpu.utils import InferenceServerException  # noqa: F401
