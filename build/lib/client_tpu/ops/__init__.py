"""Hand-written TPU kernels (Pallas) for the hot ops.

XLA fuses the bulk of the models well; kernels live here only where
manual control of VMEM residency and the MXU schedule beats the
compiler — currently flash attention (streaming-softmax attention that
never materializes the [S, S] score matrix).
"""

from client_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_fn,
)
