"""Flash attention as a Pallas TPU kernel.

Dense attention materializes the [S, S] score matrix in HBM — O(S^2)
memory traffic, the classic long-context killer. This kernel streams
K/V blocks through VMEM and keeps the softmax running statistics
(row max + row sum) in registers, so scores never leave the core and
HBM traffic stays O(S * D). One grid cell per (batch*head, q-block);
the inner lax.fori_loop walks K/V blocks, skipping fully-masked
blocks under causal masking.

Head_dim is zero-padded to the 128-lane tile (guide: last dim must be
128); zero columns contribute nothing to either the scores or the
output, so padding is exact. K/V for one (batch, head) must fit VMEM
(~16 MB/core): fine through S ~ 8k at f32, far beyond the serving
shapes here — shard longer sequences over the mesh with
client_tpu.parallel.ring_attention instead (the two compose: ring
rotates shards, flash computes each block pair).

Algorithm: Dao et al., "FlashAttention: Fast and Memory-Efficient
Exact Attention with IO-Awareness" (arXiv:2205.14135), re-derived for
Pallas; no reference implementation was consulted.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, *, block_q: int,
                  block_k: int, seq_k: int, n_heads: int, causal: bool,
                  scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    d = q.shape[-1]
    # This sequence's real key length (lengths live in SMEM, whole
    # array per grid cell; batch index = bh // heads).
    valid_k = len_ref[pl.program_id(0) // n_heads]

    acc = jnp.zeros((block_q, d), jnp.float32)
    row_max = jnp.full((block_q,), _NEG_INF, jnp.float32)
    row_sum = jnp.zeros((block_q,), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)

    def body(ki, carry):
        acc, row_max, row_sum = carry
        k_block = k_ref[0, pl.dslice(ki * block_k, block_k)].astype(
            jnp.float32)
        v_block = v_ref[0, pl.dslice(ki * block_k, block_k)].astype(
            jnp.float32)
        scores = jax.lax.dot_general(
            q, k_block, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        visible = k_pos < valid_k  # padded key rows never win
        if causal:
            visible = jnp.logical_and(visible, q_pos >= k_pos)
        scores = jnp.where(visible, scores, _NEG_INF)
        block_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        alpha = jnp.exp(row_max - new_max)
        # Gate the exp with the mask: fully-masked rows would
        # otherwise contribute exp(_NEG_INF - _NEG_INF) = 1 each.
        weights = jnp.where(
            visible, jnp.exp(scores - new_max[:, None]), 0.0)
        new_sum = row_sum * alpha + jnp.sum(weights, axis=-1)
        new_acc = acc * alpha[:, None] + jax.lax.dot_general(
            weights, v_block, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_acc, new_max, new_sum

    # Skip blocks that are entirely masked: past this sequence's real
    # length, and (causal) strictly above the diagonal.
    num_k_blocks = jnp.minimum(seq_k // block_k,
                               pl.cdiv(valid_k, block_k))
    if causal:
        num_k_blocks = jnp.minimum(
            num_k_blocks,
            pl.cdiv((qi + 1) * block_q, block_k))
    acc, row_max, row_sum = jax.lax.fori_loop(
        0, num_k_blocks, body, (acc, row_max, row_sum))
    o_ref[0] = (acc / jnp.maximum(row_sum, 1e-30)[:, None]).astype(
        o_ref.dtype)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, valid_lengths=None,
                    interpret: bool = False):
    """q: [B, S_q, H, D]; k/v: [B, S_k, H, D]. Returns [B, S_q, H, D].
    Sequence lengths are padded to the block size internally (padded
    key rows are masked out; padded query rows are dropped).
    ``valid_lengths`` ([B] int32, optional) masks keys per sequence —
    the variable-length-batch shape encoder models (BERT) run, where
    each batch row has its own real length inside the padded bucket."""
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    pad_q = (-s_q) % block_q
    pad_k = (-s_k) % block_k
    pad_d = (-d) % 128
    if causal and s_q != s_k:
        raise ValueError("causal flash attention needs S_q == S_k")

    def prep(x, pad_s):
        x = jnp.pad(x, ((0, 0), (0, pad_s), (0, 0), (0, pad_d)))
        # [B, S, H, D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(
            b * h, x.shape[1], d + pad_d)

    qt = prep(q, pad_q)
    kt = prep(k, pad_k)
    vt = prep(v, pad_k)
    seq_q, seq_k = s_q + pad_q, s_k + pad_k
    if valid_lengths is None:
        lengths = jnp.full((b,), s_k, dtype=jnp.int32)
    else:
        lengths = jnp.asarray(valid_lengths, jnp.int32).reshape(b)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_k=seq_k,
        n_heads=h, causal=causal, scale=scale)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d + pad_d),
                         lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, seq_k, d + pad_d),
                         lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, seq_k, d + pad_d),
                         lambda bh, qi: (bh, 0, 0)),
            # Whole [B] lengths vector in SMEM per grid cell.
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d + pad_d),
                               lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (b * h, seq_q, d + pad_d), q.dtype),
        interpret=interpret,
    )(qt, kt, vt, lengths)

    out = out.reshape(b, h, seq_q, d + pad_d).transpose(0, 2, 1, 3)
    return out[:, :s_q, :, :d]


def flash_attention_fn(interpret: bool = False):
    """Drop-in for the LLM forward's attention_fn hook (same contract
    as parallel.ring_attention_fn): expands GQA heads, ignores the
    mask argument because causal masking happens in-kernel."""

    def attn(q, k, v, mask):  # noqa: ARG001 — causal in-kernel
        h, hkv = q.shape[2], k.shape[2]
        if h != hkv:
            k = jnp.repeat(k, h // hkv, axis=2)
            v = jnp.repeat(v, h // hkv, axis=2)
        return flash_attention(q, k, v, causal=True, interpret=interpret)

    return attn
