#!/usr/bin/env python
"""Zero-copy system shared memory: inputs and outputs ride POSIX shm regions.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_grpc_shm_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient

import client_tpu.utils.shared_memory as shm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.unregister_system_shared_memory()

        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        byte_size = in0.nbytes

        in_handle = shm.create_shared_memory_region(
            "input_data", "/example_input", byte_size * 2)
        shm.set_shared_memory_region(in_handle, [in0])
        shm.set_shared_memory_region(in_handle, [in1], offset=byte_size)
        out_handle = shm.create_shared_memory_region(
            "output_data", "/example_output", byte_size * 2)

        client.register_system_shared_memory(
            "input_data", "/example_input", byte_size * 2)
        client.register_system_shared_memory(
            "output_data", "/example_output", byte_size * 2)

        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_shared_memory("input_data", byte_size)
        inputs[1].set_shared_memory("input_data", byte_size,
                                    offset=byte_size)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]
        outputs[0].set_shared_memory("output_data", byte_size)
        outputs[1].set_shared_memory("output_data", byte_size,
                                     offset=byte_size)

        client.infer("simple", inputs, outputs=outputs)

        out0 = shm.get_contents_as_numpy(
            out_handle, np.int32, [16])
        out1 = shm.get_contents_as_numpy(
            out_handle, np.int32, [16], offset=byte_size)
        np.testing.assert_array_equal(out0, in0 + in1)
        np.testing.assert_array_equal(out1, in0 - in1)

        status = client.get_system_shared_memory_status()
        assert len(status.regions) == 2

        client.unregister_system_shared_memory()
        shm.destroy_shared_memory_region(in_handle)
        shm.destroy_shared_memory_region(out_handle)
        print("PASS: system shm infer")


if __name__ == "__main__":
    main()
