#!/usr/bin/env python
"""Explicit BYTES contents: `bytes_contents` entries instead of the
length-prefixed raw form (KServe-v2 allows both; the server must
accept either).

Start a server first:
  python -m client_tpu.server.app --models simple_string
(parity example: reference
src/python/examples/grpc_explicit_byte_content_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import grpc
import numpy as np

from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import GRPCInferenceServiceStub
from client_tpu.utils import deserialize_bytes_tensor


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)
    stub = GRPCInferenceServiceStub(channel)

    request = pb.ModelInferRequest(model_name="simple_string")
    values0 = [str(i).encode() for i in range(16)]
    values1 = [b"1"] * 16
    for name, values in (("INPUT0", values0), ("INPUT1", values1)):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "BYTES"
        tensor.shape.extend([16])
        tensor.contents.bytes_contents.extend(values)  # typed, not raw
    response = stub.ModelInfer(request)

    sums = deserialize_bytes_tensor(response.raw_output_contents[0])
    np.testing.assert_array_equal(
        sums.astype(np.int32), np.arange(16) + 1)
    channel.close()
    print("PASS: explicit byte contents")


if __name__ == "__main__":
    main()
