#!/usr/bin/env python
"""HTTP health and metadata surface: liveness, readiness, server and
model metadata, model config, statistics, repository index.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_http_health_metadata.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url,
                                          verbose=args.verbose) as client:
        assert client.is_server_live(), "server not live"
        assert client.is_server_ready(), "server not ready"
        assert client.is_model_ready("simple"), "model not ready"

        server_metadata = client.get_server_metadata()
        print("server:", server_metadata["name"],
              server_metadata.get("version", ""))
        assert "extensions" in server_metadata

        model_metadata = client.get_model_metadata("simple")
        print("model:", model_metadata["name"],
              "inputs:", [t["name"] for t in model_metadata["inputs"]])
        assert {t["name"] for t in model_metadata["inputs"]} == {
            "INPUT0", "INPUT1"}

        config = client.get_model_config("simple")
        config = config.get("config", config)
        assert config["name"] == "simple"

        index = client.get_model_repository_index()
        names = [m["name"] for m in index]
        assert "simple" in names, names

        stats = client.get_inference_statistics("simple")
        assert stats["model_stats"][0]["name"] == "simple"
        print("PASS: http health + metadata")


if __name__ == "__main__":
    main()
