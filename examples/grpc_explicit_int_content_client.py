#!/usr/bin/env python
"""Explicit typed contents: builds the ModelInferRequest proto by hand
with `int_contents` fields instead of raw_input_contents — the wire
form clients in other ecosystems emit, which the server must also
accept (KServe-v2 allows both).

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference
src/python/examples/grpc_explicit_int_content_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import grpc
import numpy as np

from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import GRPCInferenceServiceStub


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)
    stub = GRPCInferenceServiceStub(channel)

    request = pb.ModelInferRequest(model_name="simple")
    for name, values in (("INPUT0", range(16)), ("INPUT1", [1] * 16)):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT32"
        tensor.shape.extend([16])
        tensor.contents.int_contents.extend(values)  # typed, not raw
    response = stub.ModelInfer(request)

    out0 = np.frombuffer(response.raw_output_contents[0], np.int32)
    out1 = np.frombuffer(response.raw_output_contents[1], np.int32)
    np.testing.assert_array_equal(out0, np.arange(16) + 1)
    np.testing.assert_array_equal(out1, np.arange(16) - 1)
    channel.close()
    print("PASS: explicit int contents")


if __name__ == "__main__":
    main()
