#!/usr/bin/env python
"""Sync HTTP/REST inference with the binary tensor protocol.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_http_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        assert client.is_server_live()
        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        inputs = [
            httpclient.InferInput("INPUT0", [16], "INT32"),
            httpclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)

        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)
        print("PASS: http infer")


if __name__ == "__main__":
    main()
