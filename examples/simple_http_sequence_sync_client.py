#!/usr/bin/env python
"""Stateful sequences over HTTP: two interleaved sequences accumulate
server-side, addressed by correlation id + start/end flags.

Start a server first:
  python -m client_tpu.server.app --models simple_sequence
(parity example: reference
src/python/examples/simple_http_sequence_sync_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient


def send(client, inputs, seq_id, value, start=False, end=False):
    inputs[0].set_data_from_numpy(np.array([value], dtype=np.int32))
    result = client.infer(
        "simple_sequence", inputs, sequence_id=seq_id,
        sequence_start=start, sequence_end=end,
    )
    return int(result.as_numpy("OUTPUT")[0])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    values = [11, 7, 5, 3, 2, 0, 1]
    with httpclient.InferenceServerClient(args.url) as client:
        inputs = [httpclient.InferInput("INPUT", [1], "INT32")]
        total_a = total_b = 0
        for i, v in enumerate(values):
            start, end = i == 0, i + 1 == len(values)
            got_a = send(client, inputs, 1007, v, start, end)
            got_b = send(client, inputs, 1008, -v, start, end)
            total_a += v
            total_b -= v
            print("seq 1007 += %d -> %d | seq 1008 += %d -> %d"
                  % (v, got_a, -v, got_b))
            assert got_a == total_a and got_b == total_b
        print("PASS: http sequence infer")


if __name__ == "__main__":
    main()
