#!/usr/bin/env python
"""Minimal sync gRPC inference against the `simple` add/sub model.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_grpc_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url, verbose=args.verbose) as client:
        if not client.is_server_live():
            print("server is not live", file=sys.stderr)
            sys.exit(1)

        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)
        outputs = [
            grpcclient.InferRequestedOutput("OUTPUT0"),
            grpcclient.InferRequestedOutput("OUTPUT1"),
        ]

        result = client.infer("simple", inputs, outputs=outputs)
        out0 = result.as_numpy("OUTPUT0")
        out1 = result.as_numpy("OUTPUT1")
        for i in range(16):
            print(f"{in0[i]} + {in1[i]} = {out0[i]}")
            assert out0[i] == in0[i] + in1[i], "add result mismatch"
            assert out1[i] == in0[i] - in1[i], "sub result mismatch"
        print("PASS: infer")


if __name__ == "__main__":
    main()
