#!/usr/bin/env python
"""Greenlet-free async HTTP inference: futures resolved via get_result().

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_http_async_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url, concurrency=4) as client:
        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        inputs = [
            httpclient.InferInput("INPUT0", [16], "INT32"),
            httpclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)

        pending = [client.async_infer("simple", inputs) for _ in range(6)]
        for request in pending:
            result = request.get_result(timeout=30)
            np.testing.assert_array_equal(
                result.as_numpy("OUTPUT0"), in0 + in1)
        print("PASS: http async infer x%d" % len(pending))


if __name__ == "__main__":
    main()
