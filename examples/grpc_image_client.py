#!/usr/bin/env python
"""gRPC-only image classification client: metadata-driven
preprocessing, batching, sync or callback-async submission.

Start a server first:  python -m client_tpu.server.app --models resnet50
Then:  python examples/grpc_image_client.py -m resnet50 -b 4 [image...]
With no image argument a synthetic batch is generated (the served
ResNet's weights are random anyway).

(parity example: reference src/python/examples/grpc_image_client.py —
the gRPC-specific image pipeline; the protocol-generic variant lives
in image_client.py.)
"""

import argparse
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient
from client_tpu.utils import triton_to_np_dtype

from image_client import load_images, parse_model  # shared helpers


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("image", nargs="*",
                        help="image file(s) or folder(s); empty = synthetic")
    parser.add_argument("-m", "--model-name", default="resnet50")
    parser.add_argument("-x", "--model-version", default="")
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("-c", "--classes", type=int, default=0)
    parser.add_argument("-s", "--scaling", default="NONE",
                        choices=["NONE", "INCEPTION", "VGG"])
    parser.add_argument("-a", "--async-mode", action="store_true",
                        help="submit via callback async_infer")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        metadata = client.get_model_metadata(
            args.model_name, args.model_version)
        config = client.get_model_config(args.model_name, args.model_version)
        (input_name, output_name, h, w, c, datatype, max_batch) = parse_model(
            {
                "inputs": [{"name": t.name, "datatype": t.datatype,
                            "shape": list(t.shape)} for t in metadata.inputs],
                "outputs": [{"name": t.name, "datatype": t.datatype,
                             "shape": list(t.shape)} for t in metadata.outputs],
            },
            {"max_batch_size": config.config.max_batch_size},
        )
        batch = max(args.batch_size, 1)
        if max_batch == 0 and batch > 1:
            raise SystemExit("model does not support batching")
        arrays, names = load_images(
            args.image, h, w, c, datatype, args.scaling, batch)
        arrays = arrays[:batch]
        names = names[:batch]

        data = np.stack(arrays).astype(triton_to_np_dtype(datatype))
        shape = list(data.shape) if max_batch > 0 else list(data.shape[1:])
        if max_batch == 0:
            data = data[0]
        inputs = [grpcclient.InferInput(input_name, shape, datatype)]
        inputs[0].set_data_from_numpy(data)
        outputs = [grpcclient.InferRequestedOutput(
            output_name, class_count=args.classes)]

        def report(result):
            output = np.asarray(result.as_numpy(output_name))
            if max_batch == 0:
                output = output[None]
            for row, name in zip(output, names):
                if args.classes:
                    entries = [
                        e.decode() if isinstance(e, bytes) else str(e)
                        for e in np.asarray(row).reshape(-1)
                    ]
                    print("Image '%s': %s" % (name, ", ".join(entries)))
                else:
                    print("Image '%s': argmax %d" % (name, int(row.argmax())))

        if args.async_mode:
            import queue

            done: queue.Queue = queue.Queue()

            def callback(done_queue, result, error):
                done_queue.put((result, error))

            client.async_infer(args.model_name, inputs,
                               partial(callback, done),
                               model_version=args.model_version,
                               outputs=outputs)
            result, error = done.get(timeout=60)
            if error is not None:
                raise error
            report(result)
        else:
            report(client.infer(args.model_name, inputs,
                                model_version=args.model_version,
                                outputs=outputs))
    print("PASS: grpc image client (%s mode)"
          % ("async" if args.async_mode else "sync"))


if __name__ == "__main__":
    main()
