#!/usr/bin/env python
"""Stateful sequences over the bidi stream: per-sequence running sums arrive in order.

Start a server first:  python -m client_tpu.server.app --models simple_sequence
(parity example: reference src/python/examples/simple_grpc_sequence_stream_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient

import threading


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        values = [4, 7, 9]
        expected = [4, 11, 20]
        got = []
        done = threading.Event()

        def callback(result, error):
            assert error is None, "stream error: %s" % error
            got.append(int(result.as_numpy("OUTPUT")[0]))
            if len(got) == len(values):
                done.set()

        client.start_stream(callback)
        inputs = [grpcclient.InferInput("INPUT", [1], "INT32")]
        for step, value in enumerate(values):
            inputs[0].set_data_from_numpy(np.array([value], dtype=np.int32))
            client.async_stream_infer(
                "simple_sequence", inputs, sequence_id=42,
                sequence_start=(step == 0),
                sequence_end=(step == len(values) - 1),
            )
        assert done.wait(timeout=30), "stream timed out"
        client.stop_stream()
        assert got == expected, "got %s want %s" % (got, expected)
        print("PASS: sequence stream infer")


if __name__ == "__main__":
    main()
