#!/usr/bin/env python
"""BYTES tensors over HTTP (JSON-safe string payloads).

Start a server first:  python -m client_tpu.server.app --models simple_string
(parity example: reference src/python/examples/simple_http_string_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        in0 = np.array([str(i).encode() for i in range(16)],
                       dtype=np.object_)
        in1 = np.array([b"2"] * 16, dtype=np.object_)
        inputs = [
            httpclient.InferInput("INPUT0", [16], "BYTES"),
            httpclient.InferInput("INPUT1", [16], "BYTES"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)

        result = client.infer("simple_string", inputs)
        out0 = result.as_numpy("OUTPUT0")
        for i in range(16):
            assert int(out0[i]) == i + 2
        print("PASS: http string infer")


if __name__ == "__main__":
    main()
