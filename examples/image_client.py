#!/usr/bin/env python
"""Image classification client: preprocessing, batching, optional
async/streaming modes, top-K classification parsing, and optional
shared-memory I/O (the BASELINE config #2 shape: ResNet-50, batch 8,
TPU shm).

Start a server first:  python -m client_tpu.server.app --models resnet50
Then:  python examples/image_client.py -m resnet50 -b 8 -c 3 image_or_dir
With no image argument a synthetic batch is generated — handy because
the served ResNet's weights are random anyway.

(parity example: reference src/python/examples/image_client.py —
preprocessing with --scaling INCEPTION|VGG|NONE, metadata-driven
shape/dtype handling, classification via class_count.)
"""

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from client_tpu.utils import InferenceServerException, triton_to_np_dtype


def parse_model(metadata, config):
    """Validates that the model looks like an image classifier (one
    image input, one vector output) and extracts what preprocessing
    needs: (input_name, output_name, h, w, c, dtype, max_batch)."""
    if len(metadata["inputs"]) != 1:
        raise RuntimeError(
            "expecting 1 input, got %d" % len(metadata["inputs"]))
    if len(metadata["outputs"]) != 1:
        raise RuntimeError(
            "expecting 1 output, got %d" % len(metadata["outputs"]))
    input_meta = metadata["inputs"][0]
    output_meta = metadata["outputs"][0]
    max_batch = int(config.get("max_batch_size", 0))

    # Output must be a vector (all-but-one dims of size 1).
    out_shape = [int(d) for d in output_meta["shape"]]
    if max_batch > 0 and out_shape and out_shape[0] == -1:
        out_shape = out_shape[1:]
    non_one = [d for d in out_shape if d != 1]
    if len(non_one) != 1:
        raise RuntimeError(
            "expecting output to be a vector, got shape %s" % out_shape)

    shape = [int(d) for d in input_meta["shape"]]
    if max_batch > 0 and shape and shape[0] == -1:
        shape = shape[1:]
    if len(shape) != 3:
        raise RuntimeError(
            "expecting input with 3 dims (HWC), got %s" % shape)
    h, w, c = shape
    return (input_meta["name"], output_meta["name"], h, w, c,
            input_meta["datatype"], max_batch)


def preprocess(image, h, w, c, datatype, scaling):
    """PIL image -> HWC array matching the model input, with the
    reference's scaling conventions."""
    if c == 1:
        image = image.convert("L")
    else:
        image = image.convert("RGB")
    image = image.resize((w, h))
    np_dtype = triton_to_np_dtype(datatype)
    array = np.array(image).astype(np.float32)
    if array.ndim == 2:
        array = array[:, :, None]
    if scaling == "INCEPTION":
        array = array / 127.5 - 1.0
    elif scaling == "VGG":
        mean = (np.array([123.0, 117.0, 104.0], dtype=np.float32)
                if c == 3 else np.float32(128.0))
        array = array - mean
    return array.astype(np_dtype)


def load_images(paths, h, w, c, datatype, scaling, batch):
    """Image files/dirs -> list of preprocessed arrays (repeated to
    fill the batch); no paths -> synthetic data."""
    files = []
    for path in paths:
        p = Path(path)
        if p.is_dir():
            files.extend(sorted(x for x in p.iterdir() if x.is_file()))
        else:
            files.append(p)
    if not files:
        rng = np.random.default_rng(0)
        np_dtype = triton_to_np_dtype(datatype)
        synth = (rng.random((h, w, c), dtype=np.float32) * 255).astype(
            np_dtype)
        return [synth] * max(batch, 1), ["<synthetic>"] * max(batch, 1)
    from PIL import Image

    arrays, names = [], []
    for f in files:
        arrays.append(preprocess(Image.open(str(f)), h, w, c, datatype,
                                 scaling))
        names.append(str(f))
    while len(arrays) < batch:  # repeat to fill the requested batch
        arrays.append(arrays[len(arrays) % len(files)])
        names.append(names[len(names) % len(files)])
    return arrays, names


def postprocess(result, output_name, names, classes, batched):
    output = np.asarray(result.as_numpy(output_name))
    if not batched:  # non-batching model: one row, make it iterable
        output = output[None]
    if classes:
        # server-side classification: BYTES rows "score:index[:label]"
        for row, name in zip(output, names):
            print("Image '%s':" % name)
            for entry in np.asarray(row).reshape(-1):
                value = entry.decode() if isinstance(entry, bytes) else entry
                print("    %s" % value)
    else:
        for row, name in zip(output, names):
            print("Image '%s': argmax %d (%.4f)"
                  % (name, int(row.argmax()), float(row.max())))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("image", nargs="*",
                        help="image file(s) or folder(s); empty = synthetic")
    parser.add_argument("-m", "--model-name", default="resnet50")
    parser.add_argument("-x", "--model-version", default="")
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("-c", "--classes", type=int, default=0,
                        help="request top-K server-side classification")
    parser.add_argument("-s", "--scaling", default="NONE",
                        choices=["NONE", "INCEPTION", "VGG"])
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-i", "--protocol", default="grpc",
                        choices=["grpc", "http"])
    parser.add_argument("-a", "--async", dest="async_set",
                        action="store_true", help="async inference")
    parser.add_argument("--streaming", action="store_true",
                        help="bidirectional stream (gRPC only)")
    parser.add_argument("--shared-memory", default="none",
                        choices=["none", "system", "tpu"],
                        help="I/O placement (tpu = HBM arena regions)")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    if args.streaming and args.protocol != "grpc":
        sys.exit("--streaming requires -i grpc")
    if args.shared_memory != "none" and args.classes:
        sys.exit("server-side classification (-c) puts BYTES results in "
                 "the response body; combine it without --shared-memory")
    if args.shared_memory == "tpu" and args.protocol != "grpc":
        sys.exit("--shared-memory tpu requires -i grpc (the HBM arena "
                 "service is co-hosted with the gRPC endpoint)")

    if args.protocol == "grpc":
        import client_tpu.grpc as tritonclient
    else:
        import client_tpu.http as tritonclient

    with tritonclient.InferenceServerClient(
            args.url, verbose=args.verbose) as client:
        if args.protocol == "grpc":
            metadata = client.get_model_metadata(
                args.model_name, args.model_version, as_json=True)
            config = client.get_model_config(
                args.model_name, args.model_version, as_json=True)
        else:  # HTTP speaks JSON natively
            metadata = client.get_model_metadata(
                args.model_name, args.model_version)
            config = client.get_model_config(
                args.model_name, args.model_version)
        config = config.get("config", config)
        (input_name, output_name, h, w, c, datatype,
         max_batch) = parse_model(metadata, config)

        batch = args.batch_size
        if max_batch == 0 and batch != 1:
            sys.exit("model does not support batching; use -b 1")
        if max_batch > 0 and batch > max_batch:
            sys.exit("max supported batch is %d" % max_batch)

        arrays, names = load_images(
            args.image, h, w, c, datatype, args.scaling, batch)
        # Every image gets classified: surplus images become extra
        # batched requests (the shm layout holds one batch, so shm
        # mode processes exactly one).
        step = batch if max_batch > 0 else 1
        chunks = [(arrays[i:i + step], names[i:i + step])
                  for i in range(0, len(arrays), step)]
        if args.shared_memory != "none" and len(chunks) > 1:
            print("warning: --shared-memory holds one batch; classifying "
                  "the first %d image(s) only" % step, file=sys.stderr)
            chunks = chunks[:1]

        streaming_started = False
        shm_handles = []
        import queue

        stream_results = queue.Queue()  # shared by every streamed request
        try:
            for chunk_arrays, chunk_names in chunks:
                while len(chunk_arrays) < step:  # pad the tail batch
                    chunk_arrays = chunk_arrays + [chunk_arrays[-1]]
                    chunk_names = chunk_names + [chunk_names[-1]]
                batched = (np.stack(chunk_arrays, axis=0)
                           if max_batch > 0 else chunk_arrays[0])
                shape = list(batched.shape)
                inputs = [tritonclient.InferInput(
                    input_name, shape, datatype)]
                outputs = [tritonclient.InferRequestedOutput(
                    output_name, class_count=args.classes)]
                if args.shared_memory != "none":
                    inputs[0], outputs[0], shm_handles = \
                        _setup_shared_memory(
                            args, client, tritonclient, input_name,
                            output_name, batched, datatype, shape)
                else:
                    inputs[0].set_data_from_numpy(batched)

                if args.streaming:
                    if not streaming_started:
                        client.start_stream(
                            callback=lambda result, error:
                            stream_results.put((result, error)))
                        streaming_started = True
                    client.async_stream_infer(
                        args.model_name, inputs, outputs=outputs)
                    result, error = stream_results.get(timeout=60)
                    if error is not None:
                        raise error
                elif args.async_set and args.protocol == "http":
                    # HTTP async returns a handle (reference semantics).
                    result = client.async_infer(
                        args.model_name, inputs,
                        outputs=outputs).get_result()
                elif args.async_set:
                    future = {}
                    import threading

                    done = threading.Event()

                    def callback(result, error=None):
                        future["result"], future["error"] = result, error
                        done.set()

                    client.async_infer(args.model_name, inputs, callback,
                                       outputs=outputs)
                    if not done.wait(timeout=60):
                        sys.exit("async request timed out")
                    if future.get("error") is not None:
                        raise future["error"]
                    result = future["result"]
                else:
                    result = client.infer(args.model_name, inputs,
                                          outputs=outputs)
                if args.shared_memory != "none":
                    _print_shm_output(result, output_name, shm_handles,
                                      chunk_names)
                else:
                    postprocess(result, output_name, chunk_names,
                                args.classes, batched=max_batch > 0)
            print("PASS: image_client")
        finally:
            if streaming_started:
                client.stop_stream()
            _cleanup_shared_memory(args, client, shm_handles)


def _setup_shared_memory(args, client, tritonclient, input_name,
                         output_name, batched, datatype, shape):
    """Places the input (and output destination) in shared memory:
    'system' = POSIX shm, 'tpu' = HBM arena regions via the arena
    service (input uploaded once, outputs stay on device)."""
    out_size = 4 * 1024 * 1024
    if args.shared_memory == "system":
        import client_tpu.utils.shared_memory as shm

        in_handle = shm.create_shared_memory_region(
            "img_in", "/img_in", batched.nbytes)
        shm.set_shared_memory_region(in_handle, [batched])
        client.register_system_shared_memory(
            "img_in", "/img_in", batched.nbytes)
        out_handle = shm.create_shared_memory_region(
            "img_out", "/img_out", out_size)
        client.register_system_shared_memory("img_out", "/img_out", out_size)
    else:
        import client_tpu.utils.tpu_shared_memory as tpushm

        tpushm.set_arena_endpoint(args.url)
        in_handle = tpushm.create_shared_memory_region(
            "img_in", batched.nbytes, 0)
        tpushm.set_shared_memory_region(in_handle, [batched])
        client.register_tpu_shared_memory(
            "img_in", tpushm.get_raw_handle(in_handle), 0, batched.nbytes)
        out_handle = tpushm.create_shared_memory_region(
            "img_out", out_size, 0)
        client.register_tpu_shared_memory(
            "img_out", tpushm.get_raw_handle(out_handle), 0, out_size)
    infer_input = tritonclient.InferInput(input_name, shape, datatype)
    infer_input.set_shared_memory("img_in", batched.nbytes)
    requested = tritonclient.InferRequestedOutput(
        output_name, class_count=args.classes)
    requested.set_shared_memory("img_out", out_size)
    return infer_input, requested, [in_handle, out_handle]


def _print_shm_output(result, output_name, shm_handles, names):
    output = result.get_output(output_name)
    if output is None:
        raise InferenceServerException("no output in response")
    if hasattr(output, "parameters"):  # grpc proto
        region = output.parameters["shared_memory_region"].string_param
        byte_size = output.parameters["shared_memory_byte_size"].int64_param
        shape = list(output.shape)
        datatype = output.datatype
    else:  # http json
        params = output.get("parameters", {})
        region = params.get("shared_memory_region")
        byte_size = params.get("shared_memory_byte_size")
        shape = output.get("shape")
        datatype = output.get("datatype")
    handle = shm_handles[1]
    import client_tpu.utils.shared_memory as sysshm
    import client_tpu.utils.tpu_shared_memory as tpushm

    module = tpushm if type(handle).__module__.endswith(
        "tpu_shared_memory") else sysshm
    array = module.get_contents_as_numpy(
        handle, triton_to_np_dtype(datatype), shape)
    print("(output read from region '%s', %d bytes)" % (region, byte_size))
    for row, name in zip(np.asarray(array), names):
        print("Image '%s': argmax %d" % (name, int(row.argmax())))


def _cleanup_shared_memory(args, client, shm_handles):
    if not shm_handles:
        return
    if args.shared_memory == "system":
        import client_tpu.utils.shared_memory as shm

        client.unregister_system_shared_memory()
        for handle in shm_handles:
            shm.destroy_shared_memory_region(handle)
    elif args.shared_memory == "tpu":
        import client_tpu.utils.tpu_shared_memory as tpushm

        client.unregister_tpu_shared_memory()
        for handle in shm_handles:
            tpushm.destroy_shared_memory_region(handle)


if __name__ == "__main__":
    main()
