#!/usr/bin/env python
"""Callback-async inference: several in-flight requests completed on the client worker thread.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_grpc_async_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient

import threading


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)

        n_requests = 8
        done = threading.Event()
        results = []

        def callback(result, error):
            results.append((result, error))
            if len(results) == n_requests:
                done.set()

        for _ in range(n_requests):
            client.async_infer("simple", inputs, callback)
        assert done.wait(timeout=30), "async requests timed out"
        for result, error in results:
            assert error is None, "async infer failed: %s" % error
            np.testing.assert_array_equal(
                result.as_numpy("OUTPUT0"), in0 + in1)
        print("PASS: async infer x%d" % n_requests)


if __name__ == "__main__":
    main()
