#!/usr/bin/env python
"""Custom request parameters: attaching priority, timeout, and
arbitrary key/value parameters to an inference request (they ride the
request's parameters map and are visible to the server's scheduler).

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_grpc_custom_args_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url,
                                          verbose=args.verbose) as client:
        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)

        result = client.infer(
            "simple",
            inputs,
            request_id="custom-args-1",
            priority=1,
            timeout=10_000_000,  # us, server-side budget
            parameters={"triton_trace_id": "example-trace",
                        "custom_flag": True,
                        "custom_level": 3},
        )
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
        response = result.get_response()
        assert response.id == "custom-args-1"
        print("PASS: custom args (priority/timeout/parameters accepted)")


if __name__ == "__main__":
    main()
