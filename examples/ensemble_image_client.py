#!/usr/bin/env python
"""Ensemble pipeline client: sends a raw uint8 image to the
`ensemble_image` model (preprocess -> resnet50 -> postprocess executed
server-side) and prints the top-1 label each composing step produced.

Start a server first:
  python -m client_tpu.server.app --models ensemble_image
(parity example: reference src/python/examples/ensemble_image_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def load_image(path, h=224, w=224):
    if path:
        from PIL import Image

        image = Image.open(path).convert("RGB").resize((w, h))
        return np.array(image).astype(np.uint8)
    rng = np.random.default_rng(0)
    return rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("image", nargs="?", default="",
                        help="image file (empty = synthetic)")
    parser.add_argument("-m", "--model-name", default="ensemble_image")
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("--streaming", action="store_true",
                        help="send over a bidirectional stream")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    image = load_image(args.image)
    batched = np.stack([image] * args.batch_size, axis=0)

    with grpcclient.InferenceServerClient(args.url,
                                          verbose=args.verbose) as client:
        inputs = [grpcclient.InferInput(
            "RAW_IMAGE", list(batched.shape), "UINT8")]
        inputs[0].set_data_from_numpy(batched)
        outputs = [grpcclient.InferRequestedOutput("LABEL")]

        if args.streaming:
            import queue

            responses = queue.Queue()
            client.start_stream(
                callback=lambda result, error: responses.put((result, error)))
            client.async_stream_infer(args.model_name, inputs,
                                      outputs=outputs)
            result, error = responses.get(timeout=60)
            client.stop_stream()
            if error is not None:
                raise error
        else:
            result = client.infer(args.model_name, inputs, outputs=outputs)

        labels = result.as_numpy("LABEL")
        for row in np.asarray(labels).reshape(-1):
            text = row.decode() if isinstance(row, bytes) else row
            print("top-1 (score:index): %s" % text)
        print("PASS: ensemble_image")


if __name__ == "__main__":
    main()
