#!/usr/bin/env python
"""Cross-host TPU shared-memory redemption (docs/cross_host_arena.md).

Demonstrates the DCN pull path with two servers playing two hosts:
data is populated ONCE into host B's HBM arena; a client then runs
inference against host A using B's region handle. Host A transparently
pulls a typed replica of the region over the arena service's streaming
PullRegion RPC and serves from local HBM — the client never re-uploads
the tensors, and the handle is the only thing that crosses between the
client's view of the two hosts.

The reference's CUDA-IPC sharing (simple_grpc_cudashm_client.py)
cannot cross hosts at all; this is the TPU-native extension of the
same register/redeem model to a DCN-connected fleet.

Run with no arguments to self-host both servers in-process, or point
--owner-url / --serve-url at two already-running servers:

    python -m client_tpu.server.app --grpc-port 8001  # host B (owner)
    python -m client_tpu.server.app --grpc-port 8002  # host A (server)
    python examples/tpu_shm_cross_host_client.py \
        --owner-url localhost:8001 --serve-url localhost:8002
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient
import client_tpu.utils.tpu_shared_memory as tpushm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--owner-url", default="",
                        help="host B: where the data lives")
    parser.add_argument("--serve-url", default="",
                        help="host A: where inference runs")
    args = parser.parse_args()

    if bool(args.owner_url) != bool(args.serve_url):
        parser.error("--owner-url and --serve-url go together (one "
                     "alone would silently self-host both hosts)")
    started = []
    if not args.owner_url:
        # Self-hosted demo: two independent server cores in one
        # process stand in for the two hosts. An ambient deployment
        # route (CLIENT_TPU_ARENA_URL) would stamp BOTH self-hosted
        # arenas with the same external URL and misdirect the pull —
        # the self-hosted topology routes by bound address.
        os.environ.pop("CLIENT_TPU_ARENA_URL", None)
        from client_tpu.server.app import build_core, start_grpc_server

        owner = start_grpc_server(core=build_core([], warmup=False))
        server = start_grpc_server(core=build_core(["simple"]))
        started = [owner, server]
        args.owner_url, args.serve_url = owner.address, server.address
        print("self-hosted: owner(B)=%s serve(A)=%s"
              % (args.owner_url, args.serve_url))

    try:
        # 1. Populate host B's arena: one region, both input tensors
        #    as typed segments at fixed offsets.
        tpushm.set_arena_endpoint(args.owner_url)
        x = np.arange(16, dtype=np.int32)
        y = np.full(16, 3, dtype=np.int32)
        region = tpushm.create_shared_memory_region(
            "xhost_data", 2 * x.nbytes, 0)
        tpushm.set_shared_memory_region(region, [x, y])
        raw_handle = tpushm.get_raw_handle(region)
        import json

        route = json.loads(raw_handle).get("owner_url")
        if not route:
            sys.exit("owner published no route (a 0.0.0.0 bind is not "
                     "reachable) — start host B with --host <address> "
                     "or set CLIENT_TPU_ARENA_URL")
        print("host B holds the data; handle routes to %s" % route)

        # 2. Register B's handle with host A — A pulls the typed
        #    replica over DCN behind this one verb.
        client = grpcclient.InferenceServerClient(args.serve_url)
        client.register_tpu_shared_memory("xhost_data", raw_handle, 0,
                                          2 * x.nbytes)

        # 3. Infer on A from the replicated region (no tensor bytes on
        #    this wire — just region references).
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_shared_memory("xhost_data", x.nbytes, offset=0)
        inputs[1].set_shared_memory("xhost_data", y.nbytes,
                                    offset=x.nbytes)
        result = client.infer("simple", inputs)
        out0 = result.as_numpy("OUTPUT0")
        out1 = result.as_numpy("OUTPUT1")
        np.testing.assert_array_equal(out0, x + y)
        np.testing.assert_array_equal(out1, x - y)
        print("host A served from host B's tensors: OUTPUT0[:4]=%s "
              "OUTPUT1[:4]=%s" % (out0[:4], out1[:4]))

        # 4. Cleanup: A frees its replica on unregister; B's region is
        #    destroyed through the owner transport.
        client.unregister_tpu_shared_memory("xhost_data")
        client.close()
        tpushm.destroy_shared_memory_region(region)
        tpushm.reset_arena_endpoint()
        print("PASS: cross-host redemption")
    finally:
        for handle in started:
            handle.stop()


if __name__ == "__main__":
    main()
