#!/usr/bin/env python
"""asyncio sequences over the bidi stream: two correlated sequences
interleave on one ModelStreamInfer stream driven by an async
generator, with per-sequence running totals checked from the streamed
responses.

Start a server first:
  python -m client_tpu.server.app --models simple_sequence
(parity example: reference
src/python/examples/simple_grpc_aio_sequence_stream_infer_client.py)
"""

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc.aio as grpcclient_aio
from client_tpu.grpc import InferInput


def _sequence_step(sequence_id, value, start, end):
    inputs = [InferInput("INPUT", [1], "INT32")]
    inputs[0].set_data_from_numpy(np.array([value], dtype=np.int32))
    return dict(
        model_name="simple_sequence",
        inputs=inputs,
        request_id="%d-%d" % (sequence_id, value),
        sequence_id=sequence_id,
        sequence_start=start,
        sequence_end=end,
    )


async def run(url):
    seq_a, seq_b = 31001, 31002
    steps = [
        _sequence_step(seq_a, 1, True, False),
        _sequence_step(seq_b, 10, True, False),
        _sequence_step(seq_a, 2, False, False),
        _sequence_step(seq_b, 20, False, False),
        _sequence_step(seq_a, 3, False, True),
        _sequence_step(seq_b, 30, False, True),
    ]

    async def request_iterator():
        for step in steps:
            yield step

    totals = {}
    async with grpcclient_aio.InferenceServerClient(url) as client:
        async for result, error in client.stream_infer(request_iterator()):
            assert error is None, error
            request_id = result.get_response().id
            sequence = int(request_id.split("-")[0])
            totals[sequence] = int(result.as_numpy("OUTPUT")[0])
            if len(totals) == 2 and totals.get(seq_a) == 6 \
                    and totals.get(seq_b) == 60:
                break

    assert totals[seq_a] == 6, totals
    assert totals[seq_b] == 60, totals
    print("PASS: aio sequence stream (totals %d, %d)"
          % (totals[seq_a], totals[seq_b]))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()
    asyncio.run(run(args.url))


if __name__ == "__main__":
    main()
