#!/usr/bin/env python
"""Explicit INT8 typed contents: int8 values ride the proto's
int_contents field (KServe-v2 packs every integer width narrower than
64 bits there), exercising the server's typed-content decode for a
narrow dtype.

Start a server first:
  python -m client_tpu.server.app --models add_sub_int8
(parity example: reference
src/python/examples/grpc_explicit_int8_content_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import grpc
import numpy as np

from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import GRPCInferenceServiceStub


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    channel = grpc.insecure_channel(args.url)
    stub = GRPCInferenceServiceStub(channel)

    in0 = np.arange(16, dtype=np.int8)
    in1 = np.ones(16, dtype=np.int8)
    request = pb.ModelInferRequest(model_name="add_sub_int8")
    for name, values in (("INPUT0", in0), ("INPUT1", in1)):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT8"
        tensor.shape.extend([16])
        tensor.contents.int_contents.extend(int(v) for v in values)
    response = stub.ModelInfer(request)

    out0 = np.frombuffer(response.raw_output_contents[0], dtype=np.int8)
    out1 = np.frombuffer(response.raw_output_contents[1], dtype=np.int8)
    expected_sum = in0 + in1
    expected_diff = in0 - in1
    for i in range(16):
        print("%d + %d = %d" % (in0[i], in1[i], out0[i]))
        assert out0[i] == expected_sum[i]
        assert out1[i] == expected_diff[i]
    channel.close()
    print("PASS: explicit int8 contents")


if __name__ == "__main__":
    main()
