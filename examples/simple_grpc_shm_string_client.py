#!/usr/bin/env python
"""BYTES tensors through system shared memory over gRPC: the
wire-serialized string tensor (4-byte length prefixes) lives in a
POSIX shm region; only region references cross the RPC.

Start a server first:
  python -m client_tpu.server.app --models simple_string
(parity example: reference
src/python/examples/simple_grpc_shm_string_client.py — there CUDA shm
carries the serialized strings; semantics identical.)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient
import client_tpu.utils.shared_memory as shm
from client_tpu.utils import deserialize_bytes_tensor, serialize_byte_tensor


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.unregister_system_shared_memory()

        in0 = np.array([str(i).encode() for i in range(16)],
                       dtype=np.object_)
        in1 = np.array([b"1"] * 16, dtype=np.object_)
        in0_bytes = serialize_byte_tensor(in0).tobytes()
        in1_bytes = serialize_byte_tensor(in1).tobytes()

        in_handle = shm.create_shared_memory_region(
            "str_input_data", "/example_str_input",
            len(in0_bytes) + len(in1_bytes))
        shm.set_shared_memory_region(in_handle, [in0])
        shm.set_shared_memory_region(in_handle, [in1],
                                     offset=len(in0_bytes))
        # Serialized string outputs vary in length; give them slack.
        out_capacity = 2 * (len(in0_bytes) + len(in1_bytes)) + 256
        out_handle = shm.create_shared_memory_region(
            "str_output_data", "/example_str_output", out_capacity)

        client.register_system_shared_memory(
            "str_input_data", "/example_str_input",
            len(in0_bytes) + len(in1_bytes))
        client.register_system_shared_memory(
            "str_output_data", "/example_str_output", out_capacity)

        try:
            inputs = [
                grpcclient.InferInput("INPUT0", [16], "BYTES"),
                grpcclient.InferInput("INPUT1", [16], "BYTES"),
            ]
            inputs[0].set_shared_memory("str_input_data", len(in0_bytes))
            inputs[1].set_shared_memory("str_input_data", len(in1_bytes),
                                        offset=len(in0_bytes))
            half = out_capacity // 2
            outputs = [
                grpcclient.InferRequestedOutput("OUTPUT0"),
                grpcclient.InferRequestedOutput("OUTPUT1"),
            ]
            outputs[0].set_shared_memory("str_output_data", half)
            outputs[1].set_shared_memory("str_output_data", half,
                                         offset=half)

            result = client.infer("simple_string", inputs, outputs=outputs)

            sum_size = result.get_output("OUTPUT0").parameters[
                "shared_memory_byte_size"].int64_param
            raw = bytes(out_handle.buf()[:sum_size])
            decoded = deserialize_bytes_tensor(raw)
            for i, value in enumerate(decoded):
                total = int(value)
                print("%d + 1 = %d" % (i, total))
                assert total == i + 1
        finally:
            client.unregister_system_shared_memory()
            shm.destroy_shared_memory_region(in_handle)
            shm.destroy_shared_memory_region(out_handle)
    print("PASS: string tensors through system shm")


if __name__ == "__main__":
    main()
