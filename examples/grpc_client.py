#!/usr/bin/env python
"""Generic raw-stub gRPC client: drives the v2 inference protocol with
the protobuf stub directly — no client-library wrapper — touching
health, metadata, config, statistics, and one inference.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/grpc_client.py — the
same walk over raw service_pb2_grpc stubs.)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import grpc
import numpy as np

from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import GRPCInferenceServiceStub


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    model_name = "simple"
    channel = grpc.insecure_channel(args.url)
    stub = GRPCInferenceServiceStub(channel)

    # Health.
    live = stub.ServerLive(pb.ServerLiveRequest())
    assert live.live, "server not live"
    ready = stub.ServerReady(pb.ServerReadyRequest())
    assert ready.ready, "server not ready"
    model_ready = stub.ModelReady(pb.ModelReadyRequest(name=model_name))
    assert model_ready.ready, "model not ready"

    # Metadata + config + statistics.
    server_meta = stub.ServerMetadata(pb.ServerMetadataRequest())
    print("server: %s %s" % (server_meta.name, server_meta.version))
    model_meta = stub.ModelMetadata(pb.ModelMetadataRequest(name=model_name))
    print("model inputs: %s" % [t.name for t in model_meta.inputs])
    config = stub.ModelConfig(pb.ModelConfigRequest(name=model_name))
    assert config.config.name == model_name
    stats = stub.ModelStatistics(pb.ModelStatisticsRequest(name=model_name))
    if args.verbose:
        print(stats)

    # One inference, raw proto assembly (no InferInput helpers).
    request = pb.ModelInferRequest(model_name=model_name)
    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    for name, data in (("INPUT0", in0), ("INPUT1", in1)):
        tensor = request.inputs.add()
        tensor.name = name
        tensor.datatype = "INT32"
        tensor.shape.extend([16])
        request.raw_input_contents.append(data.tobytes())
    response = stub.ModelInfer(request)
    out0 = np.frombuffer(response.raw_output_contents[0], dtype=np.int32)
    out1 = np.frombuffer(response.raw_output_contents[1], dtype=np.int32)
    np.testing.assert_array_equal(out0, in0 + in1)
    np.testing.assert_array_equal(out1, in0 - in1)
    if args.verbose:
        print("OUTPUT0:", out0)
        print("OUTPUT1:", out1)
    print("PASS: raw-stub grpc client")


if __name__ == "__main__":
    main()
