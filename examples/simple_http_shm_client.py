#!/usr/bin/env python
"""System shared memory over the HTTP protocol.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_http_shm_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient

import client_tpu.utils.shared_memory as shm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        client.unregister_system_shared_memory()
        in0 = np.arange(16, dtype=np.int32)
        in1 = np.ones(16, dtype=np.int32)
        byte_size = in0.nbytes

        in_handle = shm.create_shared_memory_region(
            "http_input", "/http_example_input", byte_size * 2)
        shm.set_shared_memory_region(in_handle, [in0])
        shm.set_shared_memory_region(in_handle, [in1], offset=byte_size)
        client.register_system_shared_memory(
            "http_input", "/http_example_input", byte_size * 2)

        inputs = [
            httpclient.InferInput("INPUT0", [16], "INT32"),
            httpclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_shared_memory("http_input", byte_size)
        inputs[1].set_shared_memory("http_input", byte_size,
                                    offset=byte_size)

        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)

        client.unregister_system_shared_memory()
        shm.destroy_shared_memory_region(in_handle)
        print("PASS: http system shm infer")


if __name__ == "__main__":
    main()
