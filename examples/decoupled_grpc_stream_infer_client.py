#!/usr/bin/env python
"""Decoupled streaming: repeat_int32 emits one response per input element.

Start a server first:  python -m client_tpu.server.app --models repeat_int32
(parity example: reference src/python/examples/decoupled stream examples (repeat_int32))
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient

import threading


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        values = np.array([3, 1, 4, 1, 5], dtype=np.int32)
        got = []
        done = threading.Event()

        def callback(result, error):
            assert error is None, "stream error: %s" % error
            params = result.get_parameters()
            if result.as_numpy("OUT") is not None:
                got.append(int(result.as_numpy("OUT")[0]))
            if params.get("triton_final_response"):
                done.set()

        client.start_stream(callback)
        inputs = [grpcclient.InferInput("IN", [len(values)], "INT32")]
        inputs[0].set_data_from_numpy(values)
        client.async_stream_infer("repeat_int32", inputs)
        assert done.wait(timeout=30), "stream timed out"
        client.stop_stream()
        assert got == list(values), "got %s" % got
        print("PASS: decoupled stream (%d responses)" % len(got))


if __name__ == "__main__":
    main()
