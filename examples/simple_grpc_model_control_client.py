#!/usr/bin/env python
"""Explicit model lifecycle: load, infer, unload, repository index.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_grpc_model_control.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.load_model("add_sub")
        assert client.is_model_ready("add_sub")

        in0 = np.random.randint(0, 100, 16).astype(np.int32)
        in1 = np.random.randint(0, 100, 16).astype(np.int32)
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)
        result = client.infer("add_sub", inputs)
        np.testing.assert_allclose(
            result.as_numpy("OUTPUT0"), in0 + in1, rtol=1e-5)

        client.unload_model("add_sub")
        assert not client.is_model_ready("add_sub")

        index = client.get_model_repository_index()
        names = [m.name for m in index.models]
        assert "add_sub" in names
        print("PASS: model control")


if __name__ == "__main__":
    main()
