#!/usr/bin/env python
"""Explicit model lifecycle over HTTP: load, infer, unload, index.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_http_model_control.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient
from client_tpu.utils import InferenceServerException


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        client.load_model("add_sub")
        assert client.is_model_ready("add_sub")

        in0 = np.random.randint(0, 100, 16).astype(np.int32)
        in1 = np.random.randint(0, 100, 16).astype(np.int32)
        inputs = [
            httpclient.InferInput("INPUT0", [16], "INT32"),
            httpclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)
        result = client.infer("add_sub", inputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)

        client.unload_model("add_sub")
        assert not client.is_model_ready("add_sub")
        try:
            client.infer("add_sub", inputs)
            raise AssertionError("infer after unload should fail")
        except InferenceServerException:
            pass
        print("PASS: http model control")


if __name__ == "__main__":
    main()
