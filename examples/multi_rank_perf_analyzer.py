#!/usr/bin/env python
"""Launcher-free multi-rank perf run over the builtin coordinator.

Spawns N native perf_analyzer ranks against one server with the
TPUCLIENT_COORDINATOR env contract (the jax.distributed-style
coordinator_address / num_processes / process_id shape), the
launcher-free equivalent of `mpirun -n N perf_analyzer --enable-mpi`
(reference: src/c++/perf_analyzer/mpi_utils.h:32-80). The ranks
barrier together and rank-merge every stability decision, so all N
reports cover the same load interval. For the single-command local
form, `perf_analyzer --ranks N` does all of this itself.

    python examples/multi_rank_perf_analyzer.py -u 127.0.0.1:8001 -n 2
"""

import argparse
import os
import pathlib
import socket
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="127.0.0.1:8001")
    parser.add_argument("-m", "--model", default="simple")
    parser.add_argument("-n", "--ranks", type=int, default=2)
    parser.add_argument("--binary",
                        default=str(REPO / "native" / "build" /
                                    "perf_analyzer"))
    args = parser.parse_args()

    if not pathlib.Path(args.binary).exists():
        print("perf_analyzer not built (cmake -S native -B native/build "
              "-G Ninja && ninja -C native/build)", file=sys.stderr)
        return 1
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]

    cmd = [args.binary, "-m", args.model, "-u", args.url,
           "--enable-mpi", "--concurrency-range", "2", "--async",
           "-p", "500", "-r", "3", "-s", "50"]
    base_env = dict(
        os.environ,
        TPUCLIENT_COORDINATOR="127.0.0.1:%d" % port,
        TPUCLIENT_WORLD_SIZE=str(args.ranks),
    )
    procs = [
        subprocess.Popen(cmd, env=dict(base_env, TPUCLIENT_RANK=str(r)),
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for r in range(args.ranks)
    ]
    try:
        ok = True
        for rank, proc in enumerate(procs):
            out, _ = proc.communicate(timeout=300)
            merged = "throughput" in out and proc.returncode == 0
            ok = ok and merged
            print("--- rank %d (rc=%d) ---" % (rank, proc.returncode))
            print("\n".join(out.splitlines()[-3:]))
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
