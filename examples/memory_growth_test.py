#!/usr/bin/env python
"""Memory-growth soak: hammers infer in a loop and asserts the client
process RSS stabilizes — a leak in the request path (buffers, protos,
response objects) shows up as monotonic growth.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/memory_growth_test.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def rss_bytes() -> int:
    with open("/proc/self/statm") as f:
        return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-n", "--iterations", type=int, default=2000)
    parser.add_argument("--max-growth-mb", type=float, default=32.0)
    args = parser.parse_args()

    if not os.path.exists("/proc/self/statm"):  # non-Linux: no procfs
        print("SKIP: /proc/self/statm unavailable on this platform")
        print("PASS: memory stable (skipped)")
        return

    with grpcclient.InferenceServerClient(args.url) as client:
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(np.arange(16, dtype=np.int32))
        inputs[1].set_data_from_numpy(np.ones(16, dtype=np.int32))

        warmup = max(args.iterations // 10, 50)
        for _ in range(warmup):
            client.infer("simple", inputs)
        baseline = rss_bytes()
        for i in range(args.iterations):
            result = client.infer("simple", inputs)
            assert result.as_numpy("OUTPUT0") is not None
        growth = rss_bytes() - baseline
        print("RSS growth over %d inferences: %.2f MiB"
              % (args.iterations, growth / 2**20))
        assert growth < args.max_growth_mb * 2**20, (
            "memory grew %.1f MiB (> %.1f MiB budget)"
            % (growth / 2**20, args.max_growth_mb))
        print("PASS: memory stable")


if __name__ == "__main__":
    main()
