#!/usr/bin/env python
"""BYTES tensors through system shared memory over HTTP/REST — same
serialized-string-in-region convention as the gRPC variant, through
the REST front-end's shm extension.

Start a server first:
  python -m client_tpu.server.app --models simple_string
(parity example: reference
src/python/examples/simple_http_shm_string_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient
import client_tpu.utils.shared_memory as shm
from client_tpu.utils import deserialize_bytes_tensor, serialize_byte_tensor


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    args = parser.parse_args()

    with httpclient.InferenceServerClient(args.url) as client:
        client.unregister_system_shared_memory()

        in0 = np.array([str(i).encode() for i in range(16)],
                       dtype=np.object_)
        in1 = np.array([b"2"] * 16, dtype=np.object_)
        in0_bytes = serialize_byte_tensor(in0).tobytes()
        in1_bytes = serialize_byte_tensor(in1).tobytes()

        in_handle = shm.create_shared_memory_region(
            "str_http_input", "/http_str_input",
            len(in0_bytes) + len(in1_bytes))
        shm.set_shared_memory_region(in_handle, [in0])
        shm.set_shared_memory_region(in_handle, [in1],
                                     offset=len(in0_bytes))
        out_capacity = 2 * (len(in0_bytes) + len(in1_bytes)) + 256
        out_handle = shm.create_shared_memory_region(
            "str_http_output", "/http_str_output", out_capacity)

        client.register_system_shared_memory(
            "str_http_input", "/http_str_input",
            len(in0_bytes) + len(in1_bytes))
        client.register_system_shared_memory(
            "str_http_output", "/http_str_output", out_capacity)

        try:
            inputs = [
                httpclient.InferInput("INPUT0", [16], "BYTES"),
                httpclient.InferInput("INPUT1", [16], "BYTES"),
            ]
            inputs[0].set_shared_memory("str_http_input", len(in0_bytes))
            inputs[1].set_shared_memory("str_http_input", len(in1_bytes),
                                        offset=len(in0_bytes))
            half = out_capacity // 2
            outputs = [
                httpclient.InferRequestedOutput("OUTPUT0"),
                httpclient.InferRequestedOutput("OUTPUT1"),
            ]
            outputs[0].set_shared_memory("str_http_output", half)
            outputs[1].set_shared_memory("str_http_output", half,
                                         offset=half)

            result = client.infer("simple_string", inputs, outputs=outputs)

            params = result.get_output("OUTPUT0")["parameters"]
            sum_size = int(params["shared_memory_byte_size"])
            raw = bytes(out_handle.buf()[:sum_size])
            decoded = deserialize_bytes_tensor(raw)
            for i, value in enumerate(decoded):
                total = int(value)
                print("%d + 2 = %d" % (i, total))
                assert total == i + 2
        finally:
            client.unregister_system_shared_memory()
            shm.destroy_shared_memory_region(in_handle)
            shm.destroy_shared_memory_region(out_handle)
    print("PASS: string tensors through system shm (http)")


if __name__ == "__main__":
    main()
