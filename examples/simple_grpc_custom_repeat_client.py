#!/usr/bin/env python
"""Decoupled repeat with custom pacing: repeat_int32 emits one
response per input element, delayed per-element by the DELAY input —
demonstrates multi-input decoupled streaming and per-response timing
(parity example: reference simple_grpc_custom_repeat.py).

Start a server first:  python -m client_tpu.server.app --models repeat_int32
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-r", "--repeat-count", type=int, default=6)
    parser.add_argument("-d", "--delay-us", type=int, default=2000)
    args = parser.parse_args()

    values = np.arange(args.repeat_count, dtype=np.int32) * 7
    delays = np.full(args.repeat_count, args.delay_us, dtype=np.uint32)

    received = []
    arrivals = []
    done = threading.Event()
    start = time.perf_counter()

    def callback(result, error):
        assert error is None, "stream error: %s" % error
        out = result.as_numpy("OUT")
        if out is not None:
            received.append(int(out.reshape(-1)[0]))
            arrivals.append(time.perf_counter() - start)
        if result.get_parameters().get("triton_final_response"):
            done.set()

    with grpcclient.InferenceServerClient(args.url) as client:
        client.start_stream(callback)
        inputs = [
            grpcclient.InferInput("IN", [args.repeat_count], "INT32"),
            grpcclient.InferInput("DELAY", [args.repeat_count], "UINT32"),
        ]
        inputs[0].set_data_from_numpy(values)
        inputs[1].set_data_from_numpy(delays)
        client.async_stream_infer("repeat_int32", inputs)
        assert done.wait(timeout=60), "stream timed out"
        client.stop_stream()

    assert received == list(values), received
    # The per-element delay paces the stream: first-to-last response
    # spread (connection setup excluded) must reflect the per-element
    # delays.
    spread = (arrivals[-1] - arrivals[0]) if len(arrivals) > 1 else 0.0
    needed = (args.repeat_count - 1) * args.delay_us / 1e6 * 0.5
    assert spread >= needed, (spread, needed)
    print("PASS: custom repeat (%d responses paced over %.1f ms)"
          % (len(received), spread * 1e3))


if __name__ == "__main__":
    main()
