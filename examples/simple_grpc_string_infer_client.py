#!/usr/bin/env python
"""BYTES-tensor inference: string integers through the simple_string model.

Start a server first:  python -m client_tpu.server.app --models simple_string
(parity example: reference src/python/examples/simple_grpc_string_infer_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        in0 = np.array([str(i).encode() for i in range(16)], dtype=np.object_)
        in1 = np.array([b"1"] * 16, dtype=np.object_)
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "BYTES"),
            grpcclient.InferInput("INPUT1", [16], "BYTES"),
        ]
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)

        result = client.infer("simple_string", inputs)
        out0 = result.as_numpy("OUTPUT0")
        out1 = result.as_numpy("OUTPUT1")
        for i in range(16):
            assert int(out0[i]) == i + 1, "string add mismatch"
            assert int(out1[i]) == i - 1, "string sub mismatch"
        print("PASS: string infer")


if __name__ == "__main__":
    main()
