#!/usr/bin/env python
"""Stateful sequences: two interleaved sequences accumulate server-side.

Start a server first:  python -m client_tpu.server.app --models simple_sequence
(parity example: reference src/python/examples/simple_grpc_sequence_sync_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def send(client, inputs, seq_id, value, start=False, end=False):
    inputs[0].set_data_from_numpy(np.array([value], dtype=np.int32))
    result = client.infer(
        "simple_sequence", inputs, sequence_id=seq_id,
        sequence_start=start, sequence_end=end,
    )
    return int(result.as_numpy("OUTPUT")[0])


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        inputs = [grpcclient.InferInput("INPUT", [1], "INT32")]
        # Interleave two sequences; each keeps its own running sum.
        assert send(client, inputs, 1001, 5, start=True) == 5
        assert send(client, inputs, 1002, 100, start=True) == 100
        assert send(client, inputs, 1001, 3) == 8
        assert send(client, inputs, 1002, 11) == 111
        assert send(client, inputs, 1001, 2, end=True) == 10
        assert send(client, inputs, 1002, 9, end=True) == 120
        print("PASS: sequence sync (2 interleaved sequences)")


if __name__ == "__main__":
    main()
