#!/usr/bin/env python
"""gRPC keepalive configuration: aggressive pings keep long-idle
channels alive through NATs/load balancers (the knobs map to gRPC
channel args exactly like the reference's KeepAliveOptions).

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_grpc_keepalive_client.py)
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    keepalive = grpcclient.KeepAliveOptions(
        keepalive_time_ms=2000,            # ping every 2s when idle
        keepalive_timeout_ms=1000,         # declare dead after 1s no-ack
        keepalive_permit_without_calls=True,
        http2_max_pings_without_data=0,    # unlimited pings
    )
    # The options map 1:1 onto gRPC channel args (reference
    # KeepAliveOptions semantics) — that mapping is the example's point.
    channel_args = dict(keepalive.channel_args())
    assert channel_args["grpc.keepalive_time_ms"] == 2000
    assert channel_args["grpc.keepalive_timeout_ms"] == 1000
    assert channel_args["grpc.keepalive_permit_without_calls"] == 1
    assert channel_args["grpc.http2.max_pings_without_data"] == 0
    with grpcclient.InferenceServerClient(
            args.url, keepalive_options=keepalive) as client:
        inputs = [
            grpcclient.InferInput("INPUT0", [16], "INT32"),
            grpcclient.InferInput("INPUT1", [16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(np.arange(16, dtype=np.int32))
        inputs[1].set_data_from_numpy(np.ones(16, dtype=np.int32))

        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(
            result.as_numpy("OUTPUT0"), np.arange(16) + 1)
        # Idle past several keepalive periods; the channel must
        # survive and serve again without reconnect errors.
        time.sleep(5)
        result = client.infer("simple", inputs)
        np.testing.assert_array_equal(
            result.as_numpy("OUTPUT1"), np.arange(16) - 1)
        print("PASS: keepalive channel survived idle period")


if __name__ == "__main__":
    main()
