#!/usr/bin/env python
"""Zero-copy TPU shared-memory inference over the HTTP client —
the north-star flow on the REST protocol (parity example: reference
simple_http_cudashm_client.py, re-targeted at the HBM arena).

Start a server first:
  python -m client_tpu.server.app --models add_sub_fp32
(the arena gRPC service rides the --grpc-port; pass it as --arena-url
when the HTTP port differs).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.http as httpclient
import client_tpu.utils.tpu_shared_memory as tpushm


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000",
                        help="HTTP endpoint")
    parser.add_argument("--arena-url", default="localhost:8001",
                        help="gRPC endpoint hosting the arena service")
    parser.add_argument("-m", "--model", default="add_sub_fp32")
    args = parser.parse_args()

    tpushm.set_arena_endpoint(args.arena_url)
    client = httpclient.InferenceServerClient(args.url)

    x = np.random.rand(16).astype(np.float32)
    y = np.random.rand(16).astype(np.float32)
    byte_size = x.nbytes

    handles = {
        name: tpushm.create_shared_memory_region(name, byte_size, 0)
        for name in ("input0_data", "input1_data", "output0_data",
                     "output1_data")
    }
    tpushm.set_shared_memory_region(handles["input0_data"], [x])
    tpushm.set_shared_memory_region(handles["input1_data"], [y])

    # Registration over REST: the raw handle is a logical descriptor,
    # never a pointer (reference posts the base64 cudaIpcMemHandle_t;
    # here it is the arena's serialized region descriptor).
    for name, handle in handles.items():
        client.register_tpu_shared_memory(
            name, tpushm.get_raw_handle(handle), 0, byte_size
        )
    status = client.get_tpu_shared_memory_status()
    registered = {entry["name"] for entry in status}
    assert registered.issuperset(handles), status

    inputs = [
        httpclient.InferInput("INPUT0", [16], "FP32"),
        httpclient.InferInput("INPUT1", [16], "FP32"),
    ]
    inputs[0].set_shared_memory("input0_data", byte_size)
    inputs[1].set_shared_memory("input1_data", byte_size)
    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0"),
        httpclient.InferRequestedOutput("OUTPUT1"),
    ]
    outputs[0].set_shared_memory("output0_data", byte_size)
    outputs[1].set_shared_memory("output1_data", byte_size)

    client.infer(args.model, inputs, outputs=outputs)

    out0 = tpushm.get_contents_as_numpy(handles["output0_data"], "FP32", [16])
    out1 = tpushm.get_contents_as_numpy(handles["output1_data"], "FP32", [16])
    assert np.allclose(out0, x + y, rtol=1e-6), "add mismatch"
    assert np.allclose(out1, x - y, rtol=1e-6), "sub mismatch"
    print("PASS: tpu shared memory over http")

    client.unregister_tpu_shared_memory()
    for handle in handles.values():
        tpushm.destroy_shared_memory_region(handle)
    client.close()


if __name__ == "__main__":
    main()
