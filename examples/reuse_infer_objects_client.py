#!/usr/bin/env python
"""Reusing InferInput / InferRequestedOutput objects across requests
(and clients): the objects are plain request descriptors, so the same
instances can be re-filled with set_data_from_numpy between calls
instead of reallocating per request — the pattern the reference
documents for request-object reuse.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/reuse_infer_objects_client.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient
import client_tpu.http as httpclient


def run_requests(client, inputs, outputs, rounds=4):
    for round_idx in range(rounds):
        # Re-fill the SAME input objects with fresh data.
        in0 = np.full(16, round_idx, dtype=np.int32)
        in1 = np.arange(16, dtype=np.int32)
        inputs[0].set_data_from_numpy(in0)
        inputs[1].set_data_from_numpy(in1)
        result = client.infer("simple", inputs, outputs=outputs)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT0"), in0 + in1)
        np.testing.assert_array_equal(result.as_numpy("OUTPUT1"), in0 - in1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001",
                        help="gRPC endpoint")
    parser.add_argument("--http-url", default="",
                        help="optional HTTP endpoint to reuse the same "
                             "objects against a second protocol")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    inputs = [
        grpcclient.InferInput("INPUT0", [16], "INT32"),
        grpcclient.InferInput("INPUT1", [16], "INT32"),
    ]
    outputs = [
        grpcclient.InferRequestedOutput("OUTPUT0"),
        grpcclient.InferRequestedOutput("OUTPUT1"),
    ]

    with grpcclient.InferenceServerClient(args.url,
                                          verbose=args.verbose) as client:
        run_requests(client, inputs, outputs)
    print("PASS: reused objects across 4 gRPC requests")

    if args.http_url:
        # The same descriptor objects work across protocols too.
        with httpclient.InferenceServerClient(
                args.http_url, verbose=args.verbose) as client:
            run_requests(client, inputs, outputs)
        print("PASS: reused objects across protocols")


if __name__ == "__main__":
    main()
