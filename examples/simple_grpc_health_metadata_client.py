#!/usr/bin/env python
"""Health probes, server/model metadata, config, statistics.

Start a server first:  python -m client_tpu.server.app --models simple
(parity example: reference src/python/examples/simple_grpc_health_metadata.py)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import client_tpu.grpc as grpcclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    args = parser.parse_args()

    with grpcclient.InferenceServerClient(args.url) as client:
        assert client.is_server_live()
        assert client.is_server_ready()
        assert client.is_model_ready("simple")

        meta = client.get_server_metadata()
        assert meta.name
        model_meta = client.get_model_metadata("simple")
        assert model_meta.name == "simple"
        assert len(model_meta.inputs) == 2

        config = client.get_model_config("simple")
        assert config.config.name == "simple"

        stats = client.get_inference_statistics("simple")
        assert len(stats.model_stats) >= 1
        print("PASS: health + metadata")


if __name__ == "__main__":
    main()
