#!/usr/bin/env python
"""Continuous-batching / paged-KV-cache smoke (ISSUE 13 acceptance).

Runs the shared A/B driver (client_tpu.perf.bench_child.
run_llm_continuous_measure): a dense-arm c4 baseline (``paged_kv=
False``, 4 decode lanes — the pre-paged ceiling) against the paged
arm at c16 on an attention-dominated long-context config, with every
request carrying a shared system prompt.

Gates:
  1. paged decode is token-exact vs the dense arm (batched prefill,
     chunked prefill, and prefix-hit prompts);
  2. paged c16 tokens/s >= 5x the dense c4 baseline;
  3. paged c16 ITL p99 <= 1.5x the dense c4 ITL p99 (joins and
     chunked prefill must not spike active streams);
  4. prefix hit ratio > 0 on the shared-system-prompt workload;
  5. pool leak-free at exit after cancels and a forced
     crash-recovery (pages_used == pages_reserved == 0).
"""

from __future__ import annotations

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SPEEDUP_FLOOR = 5.0
ITL_P99_CEIL = 1.5


def main() -> int:
    from client_tpu.perf.bench_child import run_llm_continuous_measure

    result = run_llm_continuous_measure(concurrencies=(16,),
                                        paged_lanes=16, chaos=True)
    dense = result["dense_c4"]
    paged = result["paged_c16"]
    speedup = paged.get("speedup_vs_dense_c4", 0.0)
    itl_ratio = paged.get("itl_p99_vs_dense_c4", 0.0)
    print("dense c4: %.1f tok/s, ITL p99 %.2f ms"
          % (dense["tokens_per_sec"], dense["itl_p99_ms"]))
    print("paged c16: %.1f tok/s (%.2fx), ITL p99 %.2f ms (%.2fx), "
          "pages peak %d of %d (dense-equivalent %d)"
          % (paged["tokens_per_sec"], speedup, paged["itl_p99_ms"],
             itl_ratio, paged["pages_used_peak"], result["kv_pages"],
             result["dense_equivalent_pages"]))
    print("prefix hits: %d pages; prefill chunks: %d"
          % (paged["prefix_hits_total"],
             result["prefill_chunks_total"]))

    failures = []
    if not result["token_parity"]:
        failures.append("paged decode is NOT token-exact vs dense")
    if speedup < SPEEDUP_FLOOR:
        failures.append("c16 speedup %.2fx below the %.1fx floor"
                        % (speedup, SPEEDUP_FLOOR))
    if not itl_ratio or itl_ratio > ITL_P99_CEIL:
        failures.append("c16 ITL p99 ratio %.2fx above the %.1fx "
                        "ceiling" % (itl_ratio, ITL_P99_CEIL))
    if paged["prefix_hits_total"] <= 0:
        failures.append("no prefix-cache hits on a shared-system-"
                        "prompt workload")
    if not result.get("chaos_recovered"):
        failures.append("post-crash recovery request failed")
    if result["pages_used_final"] or result["pages_reserved_final"]:
        failures.append(
            "page pool leaked: used=%d reserved=%d after cancels + "
            "crash" % (result["pages_used_final"],
                       result["pages_reserved_final"]))
    for failure in failures:
        print("FAIL: %s" % failure)
    if failures:
        return 1
    print("llm smoke passed: %.2fx tokens/s at c16 (floor %.1fx), "
          "ITL p99 %.2fx (ceil %.1fx), %d prefix-hit pages, pool "
          "leak-free through cancel + crash"
          % (speedup, SPEEDUP_FLOOR, itl_ratio, ITL_P99_CEIL,
             paged["prefix_hits_total"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
