#!/usr/bin/env python
"""CI smoke for the request-cancellation lifecycle
(client_tpu/server/cancel.py, docs/cancellation.md).

Drives an abandoned-request storm A/B against an in-process core: 16
closed-loop clients, half of which walk away a few milliseconds after
submitting each request (the token flips mid-queue, exactly what a
dropped connection does). Three arms on identical workloads:

* **baseline** — survivors only, no abandoners: the p99 yardstick.
* **ignore**   — storm with the cancel kill switch off: every
  abandoned request computes to completion; its distinct payload
  values reaching the model are the wasted-work denominator.
* **cancel**   — storm with cancellation on (the default).

Gates:

1. **Waste ≤ 0.4x** — abandoned work reaching the model in the
   cancel arm is at most 0.4x the ignore arm (queued members must be
   dropped before dispatch; only the already-in-flight sliver may
   execute).
2. **Survivors unharmed** — survivor p99 in the cancel arm within
   1.2x the no-abandon baseline (floor 50 ms for CI noise): reclaimed
   capacity goes back to live callers.
3. **Nothing leaks** — after the storm drains: tenant in-flight
   slots 0, cancel registry empty, and (post-unload) HBM allocator
   leases + device-ledger residual zero. A separate paged-LLM burst
   cancels 4 live decode streams and requires pages_used ==
   pages_reserved == 0 afterwards, with the lane immediately
   reusable.
4. **Hot path free** — the shared paired-A/B overhead driver
   (`bench_child._overhead_ab_measure(core, core.cancel, "cancel")`)
   holds the always-on token mint + stage checks under 2% throughput
   cost on `add_sub_large`.

Exits non-zero with one line per violation.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SURVIVORS = 8
ABANDONERS = 8
REQUESTS_EACH = 8
ABANDON_AFTER_S = 0.005
EXEC_SLEEP_S = 0.04

FAILURES: list = []


def gate(ok: bool, label: str, detail: str = "") -> None:
    line = "%s%s" % (label, (": " + detail) if detail else "")
    if ok:
        print("  ok   %s" % line)
    else:
        print("  FAIL %s" % line)
        FAILURES.append(line)


def _p99(samples):
    if not samples:
        return 0.0
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]


def _storm_arm(abandon: bool, cancel_enabled: bool) -> dict:
    """One arm on a fresh core; returns survivor latencies, the set of
    abandoned payload values that reached the model, and the drain
    state of every storm-held resource."""
    import numpy as np

    from client_tpu.protocol import inference_pb2 as pb
    from client_tpu.server import cancel as cancel_mod
    from client_tpu.server.app import build_core
    from client_tpu.server.model import ServedModel, TensorSpec
    from client_tpu.server.qos import TenantQuotaManager
    from client_tpu.utils import InferenceServerException

    class StormModel(ServedModel):
        """Fused execution burns EXEC_SLEEP_S and records each row's
        payload value — the ground truth of what actually computed."""

        max_batch_size = 8
        dynamic_batching = True

        def __init__(self):
            super().__init__()
            self.name = "cancel_storm"
            self.inputs = [TensorSpec("IN", "FP32", [4])]
            self.outputs = [TensorSpec("OUT", "FP32", [4])]
            self.seen: set = set()
            self._lock = threading.Lock()

        def infer(self, inputs, parameters=None):
            array = np.asarray(inputs["IN"])
            time.sleep(EXEC_SLEEP_S)
            with self._lock:
                self.seen.update(int(v) for v in array[:, 0])
            return {"OUT": array * 2.0}

    core = build_core([], warmup=False)
    model = StormModel()
    core.repository.add_model(model)
    core.tenant_quotas = TenantQuotaManager.from_spec(
        "default=rate:100000,burst:1000,concurrency:64")
    core.cancel.enabled = cancel_enabled

    def request(value: int, request_id: str):
        req = pb.ModelInferRequest(model_name="cancel_storm",
                                   id=request_id)
        tensor = req.inputs.add()
        tensor.name = "IN"
        tensor.datatype = "FP32"
        tensor.shape.extend([1, 4])
        req.raw_input_contents.append(
            np.full((1, 4), float(value), np.float32).tobytes())
        req.parameters["tenant"].string_param = "storm"
        return req

    survivor_latencies: list = []
    abandoned_values: set = set()
    merge = threading.Lock()

    def survivor(index: int):
        local = []
        for i in range(REQUESTS_EACH):
            value = 1000 + index * REQUESTS_EACH + i
            t0 = time.monotonic()
            core.infer(request(value, "sv-%d" % value))
            local.append(time.monotonic() - t0)
        with merge:
            survivor_latencies.extend(local)

    def abandoner(index: int):
        for i in range(REQUESTS_EACH):
            value = 50000 + index * REQUESTS_EACH + i
            request_id = "ab-%d" % value
            with merge:
                abandoned_values.add(value)
            # The ignore arm mimics a lifecycle-less server: no token
            # is wired in, so the walk-away has nothing to flip and
            # the request computes to completion.
            token = (core.cancel.mint(request_id)
                     if cancel_enabled else None)
            if token is not None:
                # the caller walks away shortly after submitting —
                # same flip a dropped transport produces
                threading.Timer(
                    ABANDON_AFTER_S, token.cancel,
                    args=(cancel_mod.REASON_CLIENT_DISCONNECT,)).start()
            try:
                core.infer(request(value, request_id), cancel=token)
            except InferenceServerException:
                pass  # CANCELLED is this client's expected ending

    threads = [threading.Thread(target=survivor, args=(i,))
               for i in range(SURVIVORS)]
    if abandon:
        threads += [threading.Thread(target=abandoner, args=(i,))
                    for i in range(ABANDONERS)]
    t0 = time.monotonic()
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    wall_s = time.monotonic() - t0

    time.sleep(0.3)  # let in-flight fused tails and timers drain
    tenant_inflight = core.tenant_quotas.snapshot().get(
        "storm", {}).get("inflight", 0)
    registry_inflight = core.cancel.inflight()
    core.unload_model("cancel_storm")
    hbm = core.hbm.debug_snapshot()
    leased = sum(dev["leased_bytes"] for dev in hbm["devices"].values())
    ledger_residual = sum(
        sum(components.values())
        for _model, components
        in core.devstats.ledger.paged_snapshot().items())
    core.shutdown()
    return {
        "wall_s": round(wall_s, 3),
        "survivor_p99_s": round(_p99(survivor_latencies), 4),
        "wasted_executed": len(abandoned_values & model.seen),
        "abandoned_total": len(abandoned_values),
        "tenant_inflight": tenant_inflight,
        "registry_inflight": registry_inflight,
        "leased_bytes": leased,
        "ledger_residual": ledger_residual,
    }


def _llm_burst() -> dict:
    """Cancel 4 live paged-KV decode streams mid-flight; the pool must
    drain to zero and a survivor must get a lane immediately."""
    import numpy as np

    from client_tpu.models.llm import LlmConfig, LlmModel
    from client_tpu.server import cancel as cancel_mod
    from client_tpu.server.cancel import CancelToken

    model = LlmModel(
        name="cancel_smoke_llm",
        cfg=LlmConfig(d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
                      d_ff=128, max_seq=128),
        paged_kv=True, decode_lanes=4, page_size=4)
    try:
        tokens, generators = [], []
        for i in range(4):
            token = CancelToken()
            gen = model._generate(
                {"text_input": np.array([b"abandoned stream %d" % i],
                                        dtype=np.object_),
                 "max_tokens": np.array([200], dtype=np.int32),
                 "ignore_eos": np.array([True])},
                {"cancel_token": token})
            next(gen)  # stream live: pages held
            tokens.append(token)
            generators.append(gen)
        peak = model.kv_stats()
        for token in tokens:
            token.cancel(cancel_mod.REASON_CLIENT_DISCONNECT)
        for gen in generators:
            list(gen)  # reap posts the end sentinel, not 200 tokens
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = model.kv_stats()
            if not (snap["pages_used"] or snap["pages_reserved"]):
                break
            time.sleep(0.05)
        snap = model.kv_stats()
        survivor = list(model._generate(
            {"text_input": np.array([b"survivor"], dtype=np.object_),
             "max_tokens": np.array([4], dtype=np.int32),
             "ignore_eos": np.array([True])}, {}))
        return {
            "peak_pages_used": peak["pages_used"],
            "pages_used": snap["pages_used"],
            "pages_reserved": snap["pages_reserved"],
            "survivor_tokens": len(survivor),
        }
    finally:
        model.unload()


def main() -> int:
    from client_tpu.perf.bench_child import _overhead_ab_measure
    from client_tpu.server.app import build_core

    print("cancel smoke: abandoned storm A/B "
          "(%d survivors + %d abandoners x %d requests)"
          % (SURVIVORS, ABANDONERS, REQUESTS_EACH))
    baseline = _storm_arm(abandon=False, cancel_enabled=True)
    ignore = _storm_arm(abandon=True, cancel_enabled=False)
    storm = _storm_arm(abandon=True, cancel_enabled=True)
    print(json.dumps({"baseline": baseline, "ignore": ignore,
                      "cancel": storm}, indent=1))

    # Gate 1: wasted work vs the ignore-cancels arm.
    wasted_ratio = (storm["wasted_executed"] /
                    max(1, ignore["wasted_executed"]))
    gate(ignore["wasted_executed"] >= ignore["abandoned_total"] // 2,
         "ignore arm actually executed the abandoned work",
         "%d of %d" % (ignore["wasted_executed"],
                       ignore["abandoned_total"]))
    gate(wasted_ratio <= 0.4,
         "cancel arm wasted work <= 0.4x ignore arm",
         "%d vs %d executed (%.2fx)"
         % (storm["wasted_executed"], ignore["wasted_executed"],
            wasted_ratio))

    # Gate 2: survivors unharmed by the storm.
    p99_bound = max(1.2 * baseline["survivor_p99_s"],
                    baseline["survivor_p99_s"] + 0.050)
    gate(storm["survivor_p99_s"] <= p99_bound,
         "survivor p99 within 1.2x no-abandon baseline",
         "%.1f ms vs baseline %.1f ms (bound %.1f ms)"
         % (storm["survivor_p99_s"] * 1e3,
            baseline["survivor_p99_s"] * 1e3, p99_bound * 1e3))

    # Gate 3: the storm drained every held resource.
    gate(storm["tenant_inflight"] == 0 and
         storm["registry_inflight"] == 0,
         "tenant slots + cancel registry drained",
         "inflight tenant=%d registry=%d"
         % (storm["tenant_inflight"], storm["registry_inflight"]))
    gate(storm["leased_bytes"] == 0 and storm["ledger_residual"] == 0,
         "allocator + ledger residual zero after unload",
         "leased=%d paged=%d"
         % (storm["leased_bytes"], storm["ledger_residual"]))

    llm = _llm_burst()
    print(json.dumps({"llm_burst": llm}, indent=1))
    gate(llm["peak_pages_used"] > 0,
         "llm burst held pages while live",
         "peak=%d" % llm["peak_pages_used"])
    gate(llm["pages_used"] == 0 and llm["pages_reserved"] == 0,
         "kv pages + reservations freed after cancel burst",
         "used=%d reserved=%d"
         % (llm["pages_used"], llm["pages_reserved"]))
    gate(llm["survivor_tokens"] == 4,
         "lane immediately reusable by a survivor",
         "tokens=%d" % llm["survivor_tokens"])

    # Gate 4: the always-on mint + stage checks cost < 2%.
    core = build_core(["add_sub_large"], warmup=False)
    try:
        overhead = _overhead_ab_measure(core, core.cancel, "cancel")
    finally:
        core.shutdown()
    print(json.dumps(overhead, indent=1))
    gate(overhead["overhead_ok"],
         "cancel lifecycle overhead < 2%%",
         "%.2f%%" % overhead["overhead_pct"])

    for failure in FAILURES:
        print("FAIL: %s" % failure, file=sys.stderr)
    if FAILURES:
        return 1
    print("cancel smoke passed: wasted %.2fx ignore arm, survivor p99 "
          "%.1f ms vs %.1f ms baseline, kv/tenant/ledger residual 0, "
          "overhead %.2f%%"
          % (wasted_ratio, storm["survivor_p99_s"] * 1e3,
             baseline["survivor_p99_s"] * 1e3,
             overhead["overhead_pct"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
